"""Pallas tier of the fused classify+pick contract (real devices).

`ops/fused.py`'s jitted program is CPU-valid and is what this sandbox
serves with; on a real accelerator the same contract — packed tables
in, (verdict, pick) out, one launch — wants a hand-scheduled kernel:
the probe/resolve/pick chain is gather-bound, and a Pallas kernel can
keep the per-query working set (one packed slot row, one packed meta
row, one packed byte row) streaming through VMEM instead of paying
XLA's general-gather lowering.

Capability-gated, never assumed: `pallas_supported()` compiles AND
bit-verifies a tiny fused case against the jit path before anyone
serves from this tier — on a platform where Mosaic rejects the kernel
(or on this CPU sandbox, where there is no Mosaic at all) the probe
fails closed and the engine keeps the fused jit. That is the
"flip it on without rework" contract for the real-hardware campaign:
`VPROXY_TPU_FUSED_KERNEL=auto` starts serving Pallas the moment the
probe passes, and `VPROXY_TPU_PALLAS_INTERPRET=1` lets this sandbox
bit-verify the kernel logic in interpret mode (tests/test_fused.py).

Kernel shape: grid over the batch, one query row per step. The query
row blocks (hostb/urib windows, probe slots) ride VMEM; the packed
tables are left in `pl.ANY` — at million-rule scale they are
HBM-resident and the row gathers become DMAs, which is exactly the
access pattern the packed layout was chosen for (one slot row + one
meta row + one byte row per touch; see ops/fused.py). Memory-space
tuning beyond that is real-hardware work by design (ROADMAP
real-hardware campaign) — the probe keeps it safe to defer.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .hashmatch import DOT, HOST_SHIFT


def interpret_forced() -> bool:
    """VPROXY_TPU_PALLAS_INTERPRET=1: run the kernel in the Pallas
    interpreter (CPU-valid, slow) — the bit-verification lane for
    environments without a real accelerator."""
    return os.environ.get("VPROXY_TPU_PALLAS_INTERPRET", "0") == "1"


def _iota(n: int):
    # TPU wants >=2D iota; broadcasted_iota keeps the kernel Mosaic-
    # compatible while interpret mode doesn't care
    return jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)[0]


def _fused_kernel(hostb, hlen, has_host, urib, ulen, has_uri, port,
                  hp_len, hp_s1, hp_s2, up_len, up_s1, up_s2, slots,
                  pk_meta, pk_bytes, pk_hslot, pk_hkey, pk_uslot,
                  pk_ukey, hb_items, ub_items, wh_idx, wu_idx, mtab,
                  out, *, hw: int, r_cap: int, bh: int, bu: int,
                  uri_rules: bool):
    """One query row per grid step: fold every candidate's packed
    score into the (max level, min index) reduction, then gather the
    Maglev pick — all inside one launch."""
    qhost = hostb[0, :]          # (hw,) VMEM-resident query windows
    quri = urib[0, :]
    qhlen = hlen[0, 0]
    qulen = ulen[0, 0]
    qport = port[0, 0]
    qhas_host = has_host[0, 0] > 0
    qhas_uri = has_uri[0, 0] > 0
    uw = quri.shape[0]
    hspan = _iota(hw)
    uspan = _iota(uw)

    def score(c):
        """Packed-record resolve: ONE meta row + ONE byte row per
        candidate (the layout's whole point); formulas bit-identical
        to fused._hint_verdict_packed. -> (level, index) for the
        running (max level, min index) fold — a pair carry instead of
        the i32 packing so the kernel is exact at ANY r_cap (the
        million-rule single table is the fused path's scale tier)."""
        ci = jnp.maximum(c, 0)
        meta = pk_meta[ci, :]    # (8,)
        byr = pk_bytes[ci, :]    # (hw+uw,)
        rp, hk, hl = meta[1], meta[2], meta[3]
        uk, ul, uscore = meta[4], meta[5], meta[6]
        pg = (qport == 0) | (rp == 0) | (qport == rp)
        heq = jnp.all((byr[:hw] == qhost) | (hspan >= hl))
        exact = heq & (hl == qhlen)
        boundary = qhost[jnp.clip(hl, 0, hw - 1)]
        suffix = heq & (hl < qhlen) & (boundary == DOT)
        host_level = jnp.maximum(
            jnp.maximum(jnp.where(exact, 3, 0), jnp.where(suffix, 2, 0)),
            jnp.where(hk == 2, 1, 0))
        host_level = jnp.where((hk > 0) & qhas_host, host_level, 0)
        if uri_rules:
            ueq = jnp.all((byr[hw:] == quri) | (uspan >= ul))
            prefix = ueq & (ul <= qulen)
            uri_level = jnp.maximum(jnp.where(prefix, uscore, 0),
                                    jnp.where(uk == 2, 1, 0))
            uri_level = jnp.where((uk > 0) & qhas_uri, uri_level, 0)
        else:  # uri-free table: nothing can score by uri (fused.py)
            uri_level = 0
        level = (host_level << HOST_SHIFT) + uri_level
        level = jnp.where((c >= 0) & (meta[0] > 0) & pg, level, 0)
        return level, ci

    def fold(best, c):
        """best = (best_level, best_idx): strictly-greater level wins;
        equal level keeps the SMALLEST index (Upstream.java:187's
        earliest-index tie rule, same winner as _reduce_best)."""
        lvl, ci = score(c)
        bl, bi = best
        better = (lvl > bl) | ((lvl == bl) & (lvl > 0) & (ci < bi))
        return (jnp.where(better, lvl, bl), jnp.where(better, ci, bi))

    def probe_fold(best, maxp, bcap, slot_row, len_row, pslot, pkey,
                   items, qb):
        """Fold all candidates of one probe family (maxp probes x bcap
        bucket slots); same candidate set as fused._packed_probe."""
        k = pkey.shape[1]
        kspan = _iota(k)

        def per_probe(p, best):
            slot = slot_row[0, p]
            plen = len_row[0, p]
            s = jnp.maximum(slot, 0)
            srec = pslot[s, :]
            kb = pkey[s, :]
            ok = (slot >= 0) & (srec[0] == plen) & \
                jnp.all((kb == qb[:k]) | (kspan >= plen))
            start, cnt = srec[1], srec[2]

            def per_bucket(j, best):
                take = ok & (j < cnt)
                c = jnp.where(take, items[jnp.where(take, start + j, 0)],
                              -1)
                return fold(best, c)

            return jax.lax.fori_loop(0, bcap, per_bucket, best)

        return jax.lax.fori_loop(0, maxp, per_probe, best)

    best = (jnp.int32(0), jnp.int32(r_cap))
    maxp = hp_s1.shape[1]
    lw = up_s1.shape[1]
    best = probe_fold(best, maxp, bh, hp_s1, hp_len, pk_hslot,
                      pk_hkey, hb_items, qhost)
    best = probe_fold(best, maxp, bh, hp_s2, hp_len, pk_hslot,
                      pk_hkey, hb_items, qhost)
    if uri_rules:
        best = probe_fold(best, lw, bu, up_s1, up_len, pk_uslot,
                          pk_ukey, ub_items, quri)
        best = probe_fold(best, lw, bu, up_s2, up_len, pk_uslot,
                          pk_ukey, ub_items, quri)

    def wild(j, best, items):
        return fold(best, items[j])

    best = jax.lax.fori_loop(
        0, wh_idx.shape[0], functools.partial(wild, items=wh_idx), best)
    if uri_rules:
        best = jax.lax.fori_loop(
            0, wu_idx.shape[0], functools.partial(wild, items=wu_idx),
            best)

    verdict = jnp.where(best[0] > 0, best[1], -1)
    pick = mtab[slots[0, 0]]
    out[0, 0] = verdict.astype(jnp.int32)
    out[0, 1] = pick.astype(jnp.int32)


def fused_classify_pick_pallas(ht: dict, q: dict, mtab, slots,
                               interpret: Optional[bool] = None):
    """The Pallas entry with the SAME contract as fused.fused_jit's
    (verdict, pick) form: packed hint table + encoded query batch +
    Maglev column/slots -> int32 [B, 2] in one pallas_call launch."""
    from jax.experimental import pallas as pl
    if interpret is None:
        interpret = interpret_forced()
    b, hw = q["hostb"].shape
    uw = q["urib"].shape[1]
    maxp = q["hp_slot1"].shape[1]
    lw = q["up_slot1"].shape[1]
    r_cap = int(ht["pk_meta"].shape[0])
    uri_rules = "pk_uslot" in ht  # uri-free layout (fused.py pack doc)
    if uri_rules:
        uslot, ukey = ht["pk_uslot"], ht["pk_ukey"]
        ub_items, wu_idx = ht["ub_items"], ht["wu_idx"]
        bu = int(ht["bu_iota"].shape[0])
    else:  # never-read dummies keep the ref count static
        uslot = np.zeros((1, 4), np.int32)
        ukey = np.zeros((1, 1), np.uint8)
        ub_items = np.full(1, -1, np.int32)
        wu_idx = np.full(1, -1, np.int32)
        bu = 1

    def col(a):  # (B,) scalars as (B, 1) i32 rows (2D-friendly blocks)
        return np.asarray(a).astype(np.int32).reshape(b, 1)

    row = lambda w: pl.BlockSpec((1, w), lambda i: (i, 0))
    one = pl.BlockSpec((1, 1), lambda i: (i, 0))
    # packed tables: whole-array refs, compiler-placed — HBM-resident
    # at million-rule scale, row gathers become DMAs (module doc)
    full = pl.BlockSpec(memory_space=pl.ANY)

    kernel = functools.partial(_fused_kernel, hw=hw, r_cap=r_cap,
                               bh=int(ht["bh_iota"].shape[0]),
                               bu=bu, uri_rules=uri_rules)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            row(hw), one, one, row(uw), one, one, one,
            row(maxp), row(maxp), row(maxp),
            row(lw), row(lw), row(lw), one,
            full, full, full, full, full, full, full, full, full,
            full, full,
        ],
        out_specs=pl.BlockSpec((1, 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 2), jnp.int32),
        interpret=interpret,
    )(q["hostb"], col(q["hlen"]), col(q["has_host"]), q["urib"],
      col(q["ulen"]), col(q["has_uri"]), col(q["port"]),
      q["hp_len"], q["hp_slot1"], q["hp_slot2"],
      q["up_len"], q["up_slot1"], q["up_slot2"],
      col(np.asarray(slots)),
      ht["pk_meta"], ht["pk_bytes"], ht["pk_hslot"], ht["pk_hkey"],
      uslot, ukey, ht["hb_items"], ub_items,
      ht["wh_idx"], wu_idx, mtab)


# ----------------------------------------------------- capability probe

_PROBE: dict = {}  # interpret flag -> (ok, why)


def pallas_supported() -> tuple:
    """(ok, why): can THIS process serve the Pallas tier? ok only when
    the kernel compiles AND bit-matches the fused jit on a tiny fused
    case — a probe failure (no accelerator, Mosaic rejection, numeric
    mismatch) keeps the engine on the jit tier with the reason
    surfaced in the HTTP engine object. Cached PER KNOB STATE, not per
    process: a VPROXY_TPU_PALLAS_INTERPRET flip mid-process re-probes
    under the new mode instead of serving a verdict measured under the
    old one (the same stale-program family engine._fused_fn re-keys
    for). Interpret mode counts as capable so CPU environments can
    bit-verify the kernel logic."""
    interp = interpret_forced()
    hit = _PROBE.get(interp)
    if hit is not None:
        return hit
    try:
        platform = jax.devices()[0].platform
    except Exception as e:  # noqa: BLE001 — no backend at all
        return _PROBE.setdefault(interp, (False, f"no jax backend: {e!r}"))
    if platform == "cpu" and not interp:
        return _PROBE.setdefault(
            interp, (False, "cpu platform (no Mosaic); "
                            "VPROXY_TPU_PALLAS_INTERPRET=1 bit-verifies "
                            "the kernel in interpret mode"))
    try:
        res = _probe_verify(interp)
    except MemoryError:
        raise
    except Exception as e:  # noqa: BLE001 — probe must fail closed
        res = (False, f"pallas probe failed: {e!r}"[:300])
    return _PROBE.setdefault(interp, res)


def _probe_verify(interpret: bool) -> tuple:
    """Compile + run the tiny fused case on both tiers; bit-compare."""
    from ..rules.ir import Hint, HintRule
    from . import fused as F
    from . import hashmatch as H
    rules = [HintRule(host=f"p{i}.probe.example.com") for i in range(8)]
    rules.append(HintRule(host="*", uri="/probe"))
    tab = H.compile_hint_hash(rules)
    hints = [Hint.of_host("p3.probe.example.com"),
             Hint(host="x.example.org", uri="/probe/deep"), Hint()]
    q = H.encode_hint_queries(hints, tab)
    ht = F.pack_hint_table(tab.arrays)
    mtab = np.arange(11, dtype=np.int32) % 3
    slots = np.array([1, 4, 7], np.int64)
    ref = np.asarray(F.fused_jit(ht, q, mtab, slots))
    got = np.asarray(fused_classify_pick_pallas(ht, q, mtab, slots,
                                                interpret=interpret))
    if not np.array_equal(ref, got):
        return (False, f"pallas/jit mismatch: {got.tolist()} != "
                       f"{ref.tolist()}")
    return (True, "interpret" if interpret else "compiled")


def probe_cached() -> Optional[tuple]:
    """The cached probe verdict for the CURRENT knob state, or None if
    that probe hasn't run — NEVER triggers one (the control-thread-safe
    read the stat surfaces use; a probe's first pass compiles and
    dispatches a kernel)."""
    return _PROBE.get(interpret_forced())


def reset_probe() -> None:
    """Test hook: force a full re-probe (e.g. after a monkeypatched
    backend); plain env flips re-key automatically."""
    _PROBE.clear()
