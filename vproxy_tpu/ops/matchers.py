"""Batched JAX matchers over compiled tables.

These are the device kernels behind classify(): jit once, then feed
micro-batches. Selection semantics reproduce the reference exactly:

* hint match: strictly-greater max level, earliest rule wins ties
  (Upstream.searchForGroup, Upstream.java:187-198); level encoding is
  (host_level << 10) + uri_level (Hint.java:92-160).
* cidr first-match: smallest rule index among matching patterns
  (RouteTable.lookup RouteTable.java:44; SecurityGroup.allow
  SecurityGroup.java:38-43).

All matchers return plain arrays so they compose under jit/pjit and can
be sharded over a device mesh along the rule axis (see parallel/mesh.py).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .bitmatch import mismatch_counts, unpack_bits
from .tables import MATCH_CHUNK

HOST_SHIFT = 10
# Plain int, NOT jnp.int32(-1): a module-level jnp constant would touch
# the device backend at import time and fail/hang when the TPU tunnel is
# down (it weak-types to i32 inside the jitted matchers either way).
NO_MATCH = -1


def hint_match(table: dict, q_host: jnp.ndarray, q_has_host: jnp.ndarray,
               q_uri_bits: jnp.ndarray,
               q_has_uri: jnp.ndarray, q_port: jnp.ndarray):
    # NOTE: uri scoring only needs the RULE-side length (uri_score): an exact
    # uri match scores len(hint.uri)+1 and a prefix match len(rule.uri)+1,
    # which coincide whenever both fire (Hint.java:144-152).
    """-> (best_idx [B] i32 (-1 none), best_level [B] i32).

    q_host: [B, HOST_SLOT] uint8 (reversed bytes + length byte)
    q_uri_bits: [B, MAX_URI*8] f32 bit-planes
    """
    cap = table["active"].shape[0]
    hb = unpack_bits(q_host)  # [B, HOST_SLOT*8]
    level = _hint_levels(table, hb, q_has_host, q_uri_bits, q_has_uri, q_port)
    # strictly-greater max, earliest index wins ties
    order = jnp.arange(cap, dtype=jnp.int32)
    key = level * cap + (cap - 1 - order)[None]
    idx = jnp.argmax(key, axis=1).astype(jnp.int32)
    best_level = jnp.take_along_axis(level, idx[:, None], axis=1)[:, 0]
    return jnp.where(best_level > 0, idx, NO_MATCH), best_level


def cidr_first_match(table: dict, q_addr: jnp.ndarray, q_family: jnp.ndarray,
                     q_port: jnp.ndarray | None = None):
    """-> first-matching rule index [B] i32, or -1.

    q_addr: [B, 16] uint8 canonical; q_family: [B] i32 (0=v4, 1=v6).
    q_port: [B] i32 for ACL tables (port-range gate), None for routes.
    """
    cap3 = table["valid"].shape[0]
    cap = cap3 // 3
    ab = unpack_bits(q_addr)  # [B, 128]
    mm = mismatch_counts(ab, table["w"], table["c"])  # [B, cap*3]
    match = (mm == 0) & table["valid"][None] & (
        q_family[:, None] == table["family"][None])
    rule_idx = (jnp.arange(cap3, dtype=jnp.int32) // 3)[None]  # pattern -> rule
    if q_port is not None:
        port_ok = (table["min_port"][None, rule_idx[0]] <= q_port[:, None]) & (
            q_port[:, None] <= table["max_port"][None, rule_idx[0]])
        match = match & port_ok
    masked = jnp.where(match, rule_idx, jnp.int32(cap))
    first = jnp.min(masked, axis=1).astype(jnp.int32)
    return jnp.where(first < cap, first, NO_MATCH)


def _lex_better(lvl, idx, best_lvl, best_idx):
    """(level, earliest-index) lexicographic winner — avoids the level*cap
    int32 key overflow for very large tables."""
    take = (lvl > best_lvl) | ((lvl == best_lvl) & (idx < best_idx))
    return jnp.where(take, lvl, best_lvl), jnp.where(take, idx, best_idx)


def hint_match_chunked(table: dict, q_host: jnp.ndarray, q_has_host: jnp.ndarray,
                       q_uri_bits: jnp.ndarray, q_has_uri: jnp.ndarray,
                       q_port: jnp.ndarray, chunk: int = MATCH_CHUNK):
    """hint_match for big tables: lax.scan over rule chunks so the [B, cap]
    mismatch matrix never materializes beyond [B, chunk]."""
    cap = table["active"].shape[0]
    if cap <= chunk:
        return hint_match(table, q_host, q_has_host, q_uri_bits, q_has_uri, q_port)
    assert cap % chunk == 0, (cap, chunk)
    n_chunks = cap // chunk
    b = q_host.shape[0]
    hb = unpack_bits(q_host)

    def slice_chunk(i):
        s2 = i * chunk * 2
        s1 = i * chunk
        return {
            "host_w": jax.lax.dynamic_slice_in_dim(table["host_w"], s2, chunk * 2, 1),
            "host_c": jax.lax.dynamic_slice_in_dim(table["host_c"], s2, chunk * 2, 0),
            "host_valid": jax.lax.dynamic_slice_in_dim(table["host_valid"], s1, chunk, 0),
            "host_wild": jax.lax.dynamic_slice_in_dim(table["host_wild"], s1, chunk, 0),
            "uri_w": jax.lax.dynamic_slice_in_dim(table["uri_w"], s1, chunk, 1),
            "uri_c": jax.lax.dynamic_slice_in_dim(table["uri_c"], s1, chunk, 0),
            "uri_valid": jax.lax.dynamic_slice_in_dim(table["uri_valid"], s1, chunk, 0),
            "uri_wild": jax.lax.dynamic_slice_in_dim(table["uri_wild"], s1, chunk, 0),
            "uri_score": jax.lax.dynamic_slice_in_dim(table["uri_score"], s1, chunk, 0),
            "port": jax.lax.dynamic_slice_in_dim(table["port"], s1, chunk, 0),
            "active": jax.lax.dynamic_slice_in_dim(table["active"], s1, chunk, 0),
        }

    def step(carry, i):
        best_lvl, best_idx = carry
        sub = slice_chunk(i)
        level = _hint_levels(sub, hb, q_has_host, q_uri_bits, q_has_uri, q_port)
        order = jnp.arange(chunk, dtype=jnp.int32)
        key = level * chunk + (chunk - 1 - order)[None]
        loc = jnp.argmax(key, axis=1).astype(jnp.int32)
        lvl = jnp.take_along_axis(level, loc[:, None], axis=1)[:, 0]
        idx = loc + i * chunk
        return _lex_better(lvl, idx, best_lvl, best_idx), None

    init = (jnp.zeros(b, jnp.int32), jnp.full(b, 2**31 - 1, jnp.int32))
    (best_lvl, best_idx), _ = jax.lax.scan(
        step, init, jnp.arange(n_chunks, dtype=jnp.int32))
    return jnp.where(best_lvl > 0, best_idx, NO_MATCH), best_lvl


def cidr_first_match_chunked(table: dict, q_addr: jnp.ndarray,
                             q_family: jnp.ndarray,
                             q_port: jnp.ndarray | None = None,
                             chunk: int = MATCH_CHUNK):
    """cidr_first_match scanned over rule chunks (chunk counts rules, each
    rule has 3 pattern slots)."""
    cap3 = table["valid"].shape[0]
    cap = cap3 // 3
    if cap <= chunk:
        return cidr_first_match(table, q_addr, q_family, q_port)
    assert cap % chunk == 0, (cap, chunk)
    n_chunks = cap // chunk
    b = q_addr.shape[0]
    ab = unpack_bits(q_addr)

    def step(carry, i):
        s3 = i * chunk * 3
        s1 = i * chunk
        sub = {
            "w": jax.lax.dynamic_slice_in_dim(table["w"], s3, chunk * 3, 1),
            "c": jax.lax.dynamic_slice_in_dim(table["c"], s3, chunk * 3, 0),
            "family": jax.lax.dynamic_slice_in_dim(table["family"], s3, chunk * 3, 0),
            "valid": jax.lax.dynamic_slice_in_dim(table["valid"], s3, chunk * 3, 0),
        }
        mm = mismatch_counts(ab, sub["w"], sub["c"])
        match = (mm == 0) & sub["valid"][None] & (
            q_family[:, None] == sub["family"][None])
        rule_idx = (jnp.arange(chunk * 3, dtype=jnp.int32) // 3)[None]
        if q_port is not None:
            minp = jax.lax.dynamic_slice_in_dim(table["min_port"], s1, chunk, 0)
            maxp = jax.lax.dynamic_slice_in_dim(table["max_port"], s1, chunk, 0)
            port_ok = (minp[rule_idx[0]][None] <= q_port[:, None]) & (
                q_port[:, None] <= maxp[rule_idx[0]][None])
            match = match & port_ok
        masked = jnp.where(match, rule_idx + i * chunk, jnp.int32(cap))
        first = jnp.min(masked, axis=1).astype(jnp.int32)
        return jnp.minimum(carry, first), None

    init = jnp.full(b, cap, jnp.int32)
    first, _ = jax.lax.scan(step, init, jnp.arange(n_chunks, dtype=jnp.int32))
    return jnp.where(first < cap, first, NO_MATCH)


def _hint_levels(table, hb, q_has_host, q_uri_bits, q_has_uri, q_port):
    """[B, cap] match levels for one (sub-)table. Shared by direct/chunked."""
    cap = table["active"].shape[0]
    hmm = mismatch_counts(hb, table["host_w"], table["host_c"])
    hmatch = (hmm == 0).reshape(-1, cap, 2) & table["host_valid"][None]
    exact, suffix = hmatch[..., 0], hmatch[..., 1]
    host_level = jnp.maximum(
        jnp.maximum(exact * 3, suffix * 2),
        table["host_wild"][None].astype(jnp.int32) * 1,
    )
    host_level = jnp.where(q_has_host[:, None], host_level, 0)
    umm = mismatch_counts(q_uri_bits, table["uri_w"], table["uri_c"])
    prefix = (umm == 0) & table["uri_valid"][None]
    uri_level = jnp.maximum(
        prefix * table["uri_score"][None],
        table["uri_wild"][None].astype(jnp.int32) * 1,
    )
    uri_level = jnp.where(q_has_uri[:, None], uri_level, 0)
    level = (host_level << HOST_SHIFT) + uri_level
    port_ok = (q_port[:, None] == 0) | (table["port"][None] == 0) | (
        q_port[:, None] == table["port"][None])
    return jnp.where(port_ok & table["active"][None], level, 0)


@partial(jax.jit, static_argnames=())
def classify_all(hint_table: dict, route_table: dict, acl_table: dict,
                 hint_q: dict, route_q: dict, acl_q: dict):
    """The fused flagship step: one dispatch classifies a micro-batch of
    LB hints + DNS qnames (hint_q), route lookups and ACL checks."""
    h_idx, h_level = hint_match_chunked(
        hint_table, hint_q["host"], hint_q["has_host"],
        unpack_bits(hint_q["uri"]), hint_q["has_uri"], hint_q["port"])
    r_idx = cidr_first_match_chunked(route_table, route_q["addr"],
                                     route_q["family"])
    a_idx = cidr_first_match_chunked(acl_table, acl_q["addr"],
                                     acl_q["family"], acl_q["port"])
    a_allow = jnp.where(
        a_idx >= 0, acl_table["allow"][jnp.maximum(a_idx, 0)], False)
    return h_idx, h_level, r_idx, a_idx, a_allow


# jitted entry points for the engine: cache key = table shapes/dtypes, so
# same-capacity rule updates reuse the compiled program (no retrace)
hint_match_jit = jax.jit(hint_match_chunked, static_argnames=("chunk",))
cidr_match_jit = jax.jit(cidr_first_match_chunked, static_argnames=("chunk",))


def table_arrays(t) -> dict:
    """HintTable/CidrTable dataclass -> dict of arrays (jit-friendly pytree)."""
    import numpy as np
    out = {}
    for k, v in vars(t).items():
        if isinstance(v, np.ndarray):
            out[k] = v
    return out
