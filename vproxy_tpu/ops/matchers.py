"""Batched JAX matchers over compiled tables.

These are the device kernels behind classify(): jit once, then feed
micro-batches. Selection semantics reproduce the reference exactly:

* hint match: strictly-greater max level, earliest rule wins ties
  (Upstream.searchForGroup, Upstream.java:187-198); level encoding is
  (host_level << 10) + uri_level (Hint.java:92-160).
* cidr first-match: smallest rule index among matching patterns
  (RouteTable.lookup RouteTable.java:44; SecurityGroup.allow
  SecurityGroup.java:38-43).

All matchers return plain arrays so they compose under jit/pjit and can
be sharded over a device mesh along the rule axis (see parallel/mesh.py).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .bitmatch import mismatch_counts, unpack_bits

HOST_SHIFT = 10
NO_MATCH = jnp.int32(-1)


def hint_match(table: dict, q_host: jnp.ndarray, q_has_host: jnp.ndarray,
               q_uri_bits: jnp.ndarray,
               q_has_uri: jnp.ndarray, q_port: jnp.ndarray):
    # NOTE: uri scoring only needs the RULE-side length (uri_score): an exact
    # uri match scores len(hint.uri)+1 and a prefix match len(rule.uri)+1,
    # which coincide whenever both fire (Hint.java:144-152).
    """-> (best_idx [B] i32 (-1 none), best_level [B] i32).

    q_host: [B, HOST_SLOT] uint8 (reversed bytes + length byte)
    q_uri_bits: [B, MAX_URI*8] f32 bit-planes
    """
    cap = table["active"].shape[0]

    hb = unpack_bits(q_host)  # [B, HOST_SLOT*8]
    hmm = mismatch_counts(hb, table["host_w"], table["host_c"])  # [B, cap*2]
    hmatch = (hmm == 0).reshape(-1, cap, 2) & table["host_valid"][None]  # [B,cap,2]
    exact, suffix = hmatch[..., 0], hmatch[..., 1]
    host_level = jnp.maximum(
        jnp.maximum(exact * 3, suffix * 2),
        table["host_wild"][None].astype(jnp.int32) * 1,
    )
    host_level = jnp.where(q_has_host[:, None], host_level, 0)

    umm = mismatch_counts(q_uri_bits, table["uri_w"], table["uri_c"])  # [B, cap]
    prefix = (umm == 0) & table["uri_valid"][None]
    uri_level = jnp.maximum(
        prefix * table["uri_score"][None],
        table["uri_wild"][None].astype(jnp.int32) * 1,
    )
    uri_level = jnp.where(q_has_uri[:, None], uri_level, 0)

    level = (host_level << HOST_SHIFT) + uri_level
    port_ok = (q_port[:, None] == 0) | (table["port"][None] == 0) | (
        q_port[:, None] == table["port"][None])
    level = jnp.where(port_ok & table["active"][None], level, 0)

    # strictly-greater max, earliest index wins ties
    order = jnp.arange(cap, dtype=jnp.int32)
    key = level * cap + (cap - 1 - order)[None]
    idx = jnp.argmax(key, axis=1).astype(jnp.int32)
    best_level = jnp.take_along_axis(level, idx[:, None], axis=1)[:, 0]
    return jnp.where(best_level > 0, idx, NO_MATCH), best_level


def cidr_first_match(table: dict, q_addr: jnp.ndarray, q_family: jnp.ndarray,
                     q_port: jnp.ndarray | None = None):
    """-> first-matching rule index [B] i32, or -1.

    q_addr: [B, 16] uint8 canonical; q_family: [B] i32 (0=v4, 1=v6).
    q_port: [B] i32 for ACL tables (port-range gate), None for routes.
    """
    cap3 = table["valid"].shape[0]
    cap = cap3 // 3
    ab = unpack_bits(q_addr)  # [B, 128]
    mm = mismatch_counts(ab, table["w"], table["c"])  # [B, cap*3]
    match = (mm == 0) & table["valid"][None] & (
        q_family[:, None] == table["family"][None])
    rule_idx = (jnp.arange(cap3, dtype=jnp.int32) // 3)[None]  # pattern -> rule
    if q_port is not None:
        port_ok = (table["min_port"][None, rule_idx[0]] <= q_port[:, None]) & (
            q_port[:, None] <= table["max_port"][None, rule_idx[0]])
        match = match & port_ok
    masked = jnp.where(match, rule_idx, jnp.int32(cap))
    first = jnp.min(masked, axis=1).astype(jnp.int32)
    return jnp.where(first < cap, first, NO_MATCH)


@partial(jax.jit, static_argnames=())
def classify_all(hint_table: dict, route_table: dict, acl_table: dict,
                 hint_q: dict, route_q: dict, acl_q: dict):
    """The fused flagship step: one dispatch classifies a micro-batch of
    LB hints + DNS qnames (hint_q), route lookups and ACL checks."""
    h_idx, h_level = hint_match(
        hint_table, hint_q["host"], hint_q["has_host"],
        unpack_bits(hint_q["uri"]), hint_q["has_uri"], hint_q["port"])
    r_idx = cidr_first_match(route_table, route_q["addr"], route_q["family"])
    a_idx = cidr_first_match(acl_table, acl_q["addr"], acl_q["family"],
                             acl_q["port"])
    a_allow = jnp.where(
        a_idx >= 0, acl_table["allow"][jnp.maximum(a_idx, 0)], False)
    return h_idx, h_level, r_idx, a_idx, a_allow


def table_arrays(t) -> dict:
    """HintTable/CidrTable dataclass -> dict of arrays (jit-friendly pytree)."""
    import numpy as np
    out = {}
    for k, v in vars(t).items():
        if isinstance(v, np.ndarray):
            out[k] = v
    return out
