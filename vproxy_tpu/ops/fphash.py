"""Fingerprint-verified single-probe hash kernels — the gather-lean path.

Round-3 measurement (PERF_NOTES.md) showed this device's cost model is
dominated by GATHERED-ROW COUNT: ~7ns per gathered row regardless of
dtype/table size, with wide rows nearly free, while elementwise math and
matmuls are orders of magnitude cheaper. The cuckoo kernels in
ops/hashmatch.py verify probes by gathering key bytes and expand
candidate buckets into item-index gathers — ~3,400 gathered rows per
query. These kernels re-express the SAME matching semantics (reference
Upstream.searchForGroup Upstream.java:187-198, Hint.matchLevel
Hint.java:92-160, RouteTable.lookup RouteTable.java:44, SecurityGroup
.allow SecurityGroup.java:30-45) at ~1 gathered row per probe:

* single-probe tables: slot = fnv32(key, salt_slot) & (cap-1); slot
  collisions live INLINE in the slot record (E entries per row), so
  there is no second salt probe and no cuckoo displacement;
* each slot row packs everything the probe needs — per-entry 64-bit
  fingerprint (two independent salted FNV-32s) plus per-member metadata
  (rule index, port, uri/host fingerprints) — into ONE wide i32 row;
* verification is by fingerprint, not byte compare. Build REJECTS any
  table where two distinct co-slotted keys share a fingerprint pair
  (re-salts), so lookups are exact for every key IN the table; a query
  key not in the table can false-positive with probability 2^-64 per
  probe (and build also forbids the (0,0) pair used to mark empty
  slots). At 10M queries/s * ~30 probes that is one wrong verdict per
  ~50k years; callers needing certainty use the byte-verified
  ops/hashmatch.py path (engine backend "jax").
* LPM/ACL groups collapse bucket-item expansion into the row itself:
  route entries carry the precomputed min-rule-index of their bucket
  (identical masked patterns -> ordered-scan winner is the min index);
  ACL entries carry (idx, port-range) members inline.

Costs per query (P host probes, L rule-uri lengths, E entries, M
members, G cidr groups): hint = P + L + (P*E*M + L*E*M + wildcard)
rows; route = G rows; ACL = G rows. For the benchmark's 100k-rule
tables that is ~100 rows/query vs ~3,400 — a ~25x cut in the measured
cost driver.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..rules.ir import AclRule, HintRule
from . import cuckoo as CK
from .hashmatch import MAXP_TIERS, CapsExceeded, _pow2, _prune_list
from .tables import MAX_HOST, MAX_URI, V4, V6, _pad_cap

HOST_SHIFT = 10
URI_MAX_SCORE = 1023
DOT = ord(".")
LSET_MAX = 128  # lset index packs into 7 meta bits


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    """murmur3 finalizer: FNV-1a's final multiply leaves the low bits a
    pure function of the tail byte's low bits (no avalanche), which
    collapses `hash & (cap-1)` slot spreading for structured keys —
    measured E=30 slot pileups on the bench ACL table. Must stay
    bit-identical to the device version below."""
    h = np.asarray(h, np.uint32)
    with np.errstate(over="ignore"):
        h = h ^ (h >> 16)
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * np.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
    return h


def rolling_fnv32(qbytes: np.ndarray, salt: int) -> np.ndarray:
    """uint8 [B, L] -> uint32 [B, L+1]; column p = fmix32(fnv32 of the
    row prefix [:p])."""
    b, l = qbytes.shape
    out = np.empty((b, l + 1), dtype=np.uint32)
    h = np.full(b, CK.FNV32_OFFSET ^ np.uint32(salt), dtype=np.uint32)
    out[:, 0] = h
    with np.errstate(over="ignore"):
        for p in range(l):
            h = (h ^ qbytes[:, p].astype(np.uint32)) * CK.FNV32_PRIME
            out[:, p + 1] = h
    return _fmix32_np(out)


_M32 = 0xFFFFFFFF
_FNV32_OFFSET_I = int(CK.FNV32_OFFSET)
_FNV32_PRIME_I = int(CK.FNV32_PRIME)


def _fmix32_i(h: int) -> int:
    """_fmix32_np on a python int — bit-identical mod 2^32."""
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    return h ^ (h >> 16)


def fnv32_bytes(key: bytes, salt: int) -> int:
    """Python-int FNV-32+fmix (bit-identical to the numpy form, ~10x
    less GIL hold — this is the standby-install build hot loop)."""
    h = (_FNV32_OFFSET_I ^ int(salt)) & _M32
    for by in key:
        h = ((h ^ by) * _FNV32_PRIME_I) & _M32
    return _fmix32_i(h)


def fnv32_words_np(words: np.ndarray, salt) -> np.ndarray:
    """uint32 [..., 4] -> uint32 [...]; fmix32(FNV-32) over LE-packed
    u32 words (4 rounds instead of 16 byte rounds — cheaper on device)."""
    h = np.full(words.shape[:-1], 0, np.uint32)
    h[...] = CK.FNV32_OFFSET ^ np.uint32(salt)
    with np.errstate(over="ignore"):
        for p in range(4):
            h = (h ^ words[..., p]) * CK.FNV32_PRIME
    return _fmix32_np(h)


def _fnv32_words_dev(words: jnp.ndarray, salt: jnp.ndarray) -> jnp.ndarray:
    """words [B, G, 4] u32, salt [G] u32 -> [B, G] u32; bit-identical
    to fnv32_words_np (incl. the fmix32 finalizer)."""
    h = jnp.broadcast_to((jnp.uint32(CK.FNV32_OFFSET) ^ salt)[None, :],
                         words.shape[:-1])
    prime = jnp.uint32(CK.FNV32_PRIME)
    for p in range(4):
        h = (h ^ words[..., p]) * prime
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def _pack_words16(b16: np.ndarray) -> np.ndarray:
    """uint8 [..., 16] -> uint32 [..., 4] little-endian."""
    w = b16.astype(np.uint32).reshape(b16.shape[:-1] + (4, 4))
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def _pack_words16_dev(b16: jnp.ndarray) -> jnp.ndarray:
    w = b16.astype(jnp.uint32).reshape(b16.shape[:-1] + (4, 4))
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def _i32(u) -> np.ndarray:
    """uint32 bits viewed as int32 (device tables are all-i32)."""
    return np.asarray(u, np.uint32).view(np.int32)


class FpBuildError(Exception):
    pass


def _place_fp(keys: Sequence[bytes], hasher, cap: int, salt_base: int,
              max_attempts: int = 16):
    """Place keys into cap slots (single probe); returns (salts, slot[],
    fp1[], fp2[], per-slot entry lists). Re-salts until no two co-slotted
    distinct keys share a fingerprint pair and no pair is (0, 0)."""
    for attempt in range(max_attempts):
        s_slot = 0x9E3779B1 ^ (salt_base * 2654435761 + attempt * 40503) & 0x7FFFFFFF
        s_fp1 = (s_slot * 3 + 0x85EBCA6B) & 0x7FFFFFFF
        s_fp2 = (s_slot * 7 + 0xC2B2AE35) & 0x7FFFFFFF
        slots = {}
        ok = True
        for ki, k in enumerate(keys):
            if not (ki & 7):
                CK.coop_yield()  # cooperative: see cuckoo._try_build
            sl = hasher(k, s_slot) & (cap - 1)
            f1, f2 = hasher(k, s_fp1), hasher(k, s_fp2)
            if f1 == 0 and f2 == 0:
                ok = False
                break
            ent = slots.setdefault(sl, [])
            if any(ef1 == f1 and ef2 == f2 for _, ef1, ef2 in ent):
                ok = False
                break
            ent.append((k, f1, f2))
        if ok:
            return (s_slot, s_fp1, s_fp2), slots
    raise FpBuildError(f"fingerprint salting failed after {max_attempts}")


# --------------------------------------------------------------- hint side


@dataclass
class FpHintTable:
    """Compiled packed hint table. `caps` carries every static dimension
    for shape-stable rebuilds (sharding / runtime updates)."""

    n: int
    r_cap: int
    arrays: dict
    host_cap: int
    host_salts: tuple  # (slot, fp1, fp2) — fp salts shared with q_hmeta
    uri_cap: int
    uri_salts: tuple   # (slot, fp1, fp2) — fp salts shared with up_fp
    lset: list
    hw: int
    uw: int
    caps: dict = field(default_factory=dict)



def _host_member(r: HintRule, idx: int, lset_pos: dict,
                 usalts: tuple) -> list:
    """Member record for host-bucket / wh entries: the rule's URI side.
    meta = port | uri_kind<<16 | lset_idx<<18. A "*" uri keeps its
    content fingerprint too: a literal query uri "*" (or "*x...")
    content-matches at score len+1, above the wildcard level 1."""
    if r.uri is None:
        kind, lidx, f1, f2 = 0, 0, 0, 0
    else:
        ub = r.uri.encode()
        kind = 2 if r.uri == "*" else 1
        lidx = lset_pos[len(ub)]
        f1, f2 = fnv32_bytes(ub, usalts[1]), fnv32_bytes(ub, usalts[2])
    meta = (r.port & 0xFFFF) | (kind << 16) | (lidx << 18)
    return [meta, idx, int(_i32(f1)), int(_i32(f2))]


def _uri_member(r: HintRule, idx: int, hsalts: tuple) -> list:
    """Member record for uri-bucket / wu entries: the rule's HOST side.
    meta = port | host_kind<<16 | host_len<<18. Host fingerprints are
    over the REVERSED host bytes so they equal the query's rolling
    fingerprint at position host_len; a "*" host keeps its content
    fingerprint (literal "*" / ".*"-suffix queries score 3/2)."""
    if r.host is None:
        kind, hlen, f1, f2 = 0, 0, 0, 0
    else:
        hb = r.host.encode()[::-1]
        kind = 2 if r.host == "*" else 1
        hlen = len(hb)
        f1, f2 = fnv32_bytes(hb, hsalts[1]), fnv32_bytes(hb, hsalts[2])
    meta = (r.port & 0xFFFF) | (kind << 16) | (hlen << 18)
    return [meta, idx, int(_i32(f1)), int(_i32(f2))]


def _fill_rec(cap: int, e: int, m: int, slots: dict, buckets: dict,
              member_of) -> np.ndarray:
    """rec [cap, e*(2+4m)] i32: per entry [fp1, fp2, m*(meta,idx,f1,f2)];
    empty entries keep fp (0,0); unused member slots keep idx -1."""
    ew = 2 + 4 * m
    rec = np.zeros((cap, e * ew), np.int32)
    for j in range(e):
        rec[:, j * ew + 3::4][:, :m] = -1  # idx lanes
    for sl, ents in slots.items():
        for j, (key, f1, f2) in enumerate(ents):
            base = j * ew
            rec[sl, base] = _i32(f1)
            rec[sl, base + 1] = _i32(f2)
            for mi, ridx in enumerate(buckets[key]):
                rec[sl, base + 2 + 4 * mi: base + 6 + 4 * mi] = \
                    member_of(ridx)
    return rec


def compile_hint_fp(rules: Sequence[HintRule],
                    caps: Optional[dict] = None,
                    strict: bool = True) -> FpHintTable:
    """strict=True (engine runtime updates): outgrowing supplied caps
    raises CapsExceeded. strict=False (sharded cap unification): caps
    grow silently toward the fixed point."""
    caps = dict(caps or {})
    n = len(rules)
    r_cap = caps.get("r_cap") or _pad_cap(n, 256)
    if n > r_cap:
        r_cap = _pad_cap(n, 256)
    assert 4095 * (r_cap + 1) + r_cap < 2**31, "table too large for i32 packing"

    host_buckets: dict[bytes, list[int]] = {}
    uri_buckets: dict[bytes, list[int]] = {}
    wh: list[int] = []
    wu: list[int] = []
    max_hl = max_ul = 0
    for i, r in enumerate(rules):
        if r.is_empty():
            continue
        if r.host is not None:
            hb = r.host.encode()
            if len(hb) > MAX_HOST:
                raise ValueError(f"host rule longer than {MAX_HOST}: {r.host!r}")
            max_hl = max(max_hl, len(hb))
            host_buckets.setdefault(hb[::-1], []).append(i)
            if r.host == "*":
                wh.append(i)
        if r.uri is not None:
            ub = r.uri.encode()
            if len(ub) > MAX_URI:
                raise ValueError(f"uri rule longer than {MAX_URI}: {r.uri!r}")
            max_ul = max(max_ul, len(ub))
            uri_buckets.setdefault(ub, []).append(i)
            if r.uri == "*":
                wu.append(i)

    hw = min(MAX_HOST + 1, max(caps.get("hw", 0), _pow2(max_hl + 1, 8)))
    uw = min(MAX_URI, max(caps.get("uw", 0), _pow2(max(max_ul, 1), 8)))

    # pruning: identical exactness arguments as ops/hashmatch.py:166-181
    for k in host_buckets:
        host_buckets[k] = _prune_list(rules, host_buckets[k],
                                      lambda r: (r.uri, r.port))
    for k in uri_buckets:
        uri_buckets[k] = _prune_list(rules, uri_buckets[k], lambda r: r.port)
    wh = _prune_list(rules, wh, lambda r: (r.uri, r.port))
    wu = _prune_list(rules, wu, lambda r: r.port)

    # lset covers "*" too: wildcard-uri CONTENT matches ride the probes
    lset = sorted({len(r.uri.encode()) for r in rules
                   if r.uri is not None and not r.is_empty()})
    if len(lset) > LSET_MAX:
        raise FpBuildError(f"more than {LSET_MAX} distinct uri lengths")
    lset_cap = max(caps.get("lset", 0), _pow2(max(len(lset), 1), 4))
    if len(lset) > lset_cap:
        lset_cap = _pow2(len(lset), 4)
    lset_pos = {l: j for j, l in enumerate(lset)}

    def table_for(buckets, salt_base, cap_key, e_key, m_key):
        cap = max(caps.get(cap_key, 0), _pow2(2 * max(len(buckets), 1), 16))
        if len(buckets) > cap:  # keep load factor <= 0.5 when reused
            cap = _pow2(2 * len(buckets), 16)
        salts, slots = _place_fp(list(buckets.keys()), fnv32_bytes, cap,
                                 salt_base)
        e_need = max((len(v) for v in slots.values()), default=1)
        m_need = max((len(v) for v in buckets.values()), default=1)
        e = max(caps.get(e_key, 0), e_need)
        m = max(caps.get(m_key, 0), m_need)
        return cap, salts, slots, e, m

    host_cap, hsalts, hslots, hE, hM = table_for(
        host_buckets, 11, "host_cap", "hE", "hM")
    uri_cap, usalts, uslots, uE, uM = table_for(
        uri_buckets, 23, "uri_cap", "uE", "uM")

    host_rec = _fill_rec(host_cap, hE, hM, hslots, host_buckets,
                         lambda i: _host_member(rules[i], i, lset_pos, usalts))
    uri_rec = _fill_rec(uri_cap, uE, uM, uslots, uri_buckets,
                        lambda i: _uri_member(rules[i], i, hsalts))
    # ONE combined slot table: uri slots live at host_cap + slot (the
    # encoder applies the offset), so the kernel fetches all host+uri
    # probe rows in a single gather instead of two
    rw = max(host_rec.shape[1], uri_rec.shape[1])
    rec = np.zeros((host_cap + uri_cap, rw), np.int32)
    rec[:host_cap, : host_rec.shape[1]] = host_rec
    rec[host_cap:, : uri_rec.shape[1]] = uri_rec

    whc = max(caps.get("whc", 0), _pow2(max(len(wh), 1), 2))
    wuc = max(caps.get("wuc", 0), _pow2(max(len(wu), 1), 2))
    wh_rec = np.zeros((whc, 4), np.int32)
    wh_rec[:, 1] = -1
    for j, i in enumerate(wh):
        wh_rec[j] = _host_member(rules[i], i, lset_pos, usalts)
    wu_rec = np.zeros((wuc, 4), np.int32)
    wu_rec[:, 1] = -1
    for j, i in enumerate(wu):
        wu_rec[j] = _uri_member(rules[i], i, hsalts)

    lset_arr = np.full(lset_cap, -1, np.int32)
    lset_arr[: len(lset)] = lset

    arrays = {
        "rec": rec,
        "wh_rec": wh_rec, "wu_rec": wu_rec,
        "lset": lset_arr,
        "rcap_iota": np.zeros(r_cap, np.int32),
        "h_em": np.zeros((hE, hM), np.int32),   # shape carriers
        "u_em": np.zeros((uE, uM), np.int32),
    }
    new_caps = {"r_cap": r_cap, "host_cap": host_cap, "uri_cap": uri_cap,
                "hE": hE, "hM": hM, "uE": uE, "uM": uM,
                "whc": whc, "wuc": wuc, "lset": lset_cap,
                "hw": hw, "uw": uw}
    if strict and caps and any(caps.get(k, 0) and new_caps[k] > caps[k]
                               for k in new_caps):
        raise CapsExceeded(f"update outgrew reused caps: {caps} -> {new_caps}")
    return FpHintTable(
        n=n, r_cap=r_cap, arrays=arrays,
        host_cap=host_cap, host_salts=hsalts,
        uri_cap=uri_cap, uri_salts=usalts,
        lset=lset, hw=hw, uw=uw, caps=new_caps)


def encode_hint_queries_fp(hints: Sequence, tab: FpHintTable) -> dict:
    """Hints -> device-ready probe arrays. All hashing is host-side
    numpy rolling FNV-32 (three salts per table: slot + fingerprint
    pair); the kernel never touches query BYTES, only fingerprints."""
    b = len(hints)
    W = tab.hw
    q_hostb = np.zeros((b, W), np.uint8)
    q_hlen = np.zeros(b, np.int32)
    q_has_host = np.zeros(b, bool)
    q_urib = np.zeros((b, tab.uw), np.uint8)
    q_ulen = np.zeros(b, np.int32)
    q_has_uri = np.zeros(b, bool)
    q_port = np.zeros(b, np.int32)
    for i, h in enumerate(hints):
        if h.host is not None:
            hb = h.host.encode()[::-1]
            q_hlen[i] = min(len(hb), 1 << 20)
            q_hostb[i, : min(len(hb), W)] = np.frombuffer(hb[:W], np.uint8)
            q_has_host[i] = True
        if h.uri is not None:
            ub = h.uri.encode()
            q_ulen[i] = min(len(ub), 1 << 20)
            q_urib[i, : min(len(ub), tab.uw)] = np.frombuffer(
                ub[: tab.uw], np.uint8)
            q_has_uri[i] = True
        q_port[i] = h.port

    hs = [rolling_fnv32(q_hostb[:, : W - 1], s) for s in tab.host_salts]
    pos = np.arange(W)[None, :]
    # probes: every dot position (suffix rules) + the exact-length slot
    probe_ok = np.concatenate([
        (q_hostb == DOT) & (pos < q_hlen[:, None]) & (pos >= 1),
        (q_has_host & (q_hlen <= W - 1))[:, None],
    ], axis=1) & q_has_host[:, None]  # [B, W+1]
    probe_len = np.concatenate([
        np.broadcast_to(pos, (b, W)), q_hlen[:, None]], axis=1)
    probe_lvl = np.concatenate([
        np.full((b, W), 2, np.int32), np.full((b, 1), 3, np.int32)], axis=1)
    need = int(probe_ok.sum(axis=1).max(initial=0))
    maxp = next((t for t in MAXP_TIERS if t >= need), MAXP_TIERS[-1])
    order = np.argsort(~probe_ok, axis=1, kind="stable")[:, :maxp]
    pv = np.take_along_axis(probe_ok, order, 1)
    pl = np.where(pv, np.take_along_axis(probe_len, order, 1), 0)
    mask = np.uint32(tab.host_cap - 1)
    hp_slot = np.where(pv, np.take_along_axis(hs[0], pl, 1) & mask, 0)
    hp_fp1 = np.where(pv, np.take_along_axis(hs[1], pl, 1), 0)
    hp_fp2 = np.where(pv, np.take_along_axis(hs[2], pl, 1), 0)
    hp_level = np.where(pv, np.take_along_axis(probe_lvl, order, 1), 0)

    # q_hmeta[p] = (fp1, fp2, isdot) of the reversed-host prefix [:p] —
    # what a uri-bucket member's host fingerprint is compared against.
    # Positions beyond the query host length are zeroed so a longer rule
    # host can never fp-match the rolling hash of padding.
    valid_p = np.arange(W)[None, :] <= np.minimum(q_hlen, W - 1)[:, None]
    isdot = np.concatenate([
        (q_hostb == DOT) & (pos >= 1) & (pos < q_hlen[:, None]),
    ], axis=1)
    q_hmeta = np.zeros((b, W, 3), np.int32)
    q_hmeta[:, :, 0] = np.where(valid_p, hs[1][:, :W], 0).view(np.int32)
    q_hmeta[:, :, 1] = np.where(valid_p, hs[2][:, :W], 0).view(np.int32)
    q_hmeta[:, :, 2] = isdot

    us = [rolling_fnv32(q_urib, s) for s in tab.uri_salts]
    lset_cap = tab.caps["lset"]
    lset = np.full(lset_cap, -1, np.int32)
    lset[: len(tab.lset)] = tab.lset
    lv = (lset[None, :] >= 0) & (lset[None, :] <= q_ulen[:, None]) & \
        q_has_uri[:, None]
    ll = np.where(lv, np.maximum(lset[None, :], 0), 0)
    umask = np.uint32(tab.uri_cap - 1)
    # uri slots are offset into the combined host+uri slot table
    up_slot = np.where(
        lv, (np.take_along_axis(us[0], ll, 1) & umask) + tab.host_cap, 0)
    up_fp1 = np.where(lv, np.take_along_axis(us[1], ll, 1), 0)
    up_fp2 = np.where(lv, np.take_along_axis(us[2], ll, 1), 0)
    up_score = np.where(lv, np.minimum(ll + 1, URI_MAX_SCORE), 0)

    # The probe arrays are TRIMMED to the batch's live probe count —
    # each padded probe is a wasted ~23ns row gather per query. When
    # trimming happens, full lset-indexed um_* copies are kept for
    # host-side member evaluation (members reference lset positions);
    # untrimmed batches reuse the up_* arrays directly (kernel fallback)
    um = {}
    uneed = int(lv.sum(axis=1).max(initial=0))
    utier = next((t for t in (1, 2, 4, 8, 16, 32, 64, 128)
                  if t >= max(uneed, 1)), lset_cap)
    utier = min(utier, lset_cap)
    if utier < lset_cap:
        um = {"um_fp1": up_fp1.astype(np.uint32).view(np.int32),
              "um_fp2": up_fp2.astype(np.uint32).view(np.int32),
              "um_score": up_score.astype(np.int32)}
        uorder = np.argsort(~lv, axis=1, kind="stable")[:, :utier]
        up_slot = np.take_along_axis(up_slot, uorder, 1)
        up_fp1 = np.take_along_axis(up_fp1, uorder, 1)
        up_fp2 = np.take_along_axis(up_fp2, uorder, 1)
        up_score = np.take_along_axis(up_score, uorder, 1)

    return {
        **um,
        "hp_slot": hp_slot.astype(np.int32),
        "hp_fp1": hp_fp1.astype(np.uint32).view(np.int32),
        "hp_fp2": hp_fp2.astype(np.uint32).view(np.int32),
        "hp_level": hp_level.astype(np.int32),
        "up_slot": up_slot.astype(np.int32),
        "up_fp1": up_fp1.astype(np.uint32).view(np.int32),
        "up_fp2": up_fp2.astype(np.uint32).view(np.int32),
        "up_score": up_score.astype(np.int32),
        "q_hmeta": q_hmeta,
        "hlen": q_hlen, "port": q_port,
        "has_host": q_has_host, "has_uri": q_has_uri,
    }


def _member_fields(members: jnp.ndarray):
    """members [..., 4] -> (port, kind, aux, idx, f1, f2)."""
    meta = members[..., 0]
    return (meta & 0xFFFF, (meta >> 16) & 3, (meta >> 18) & 0x7F,
            members[..., 1], members[..., 2], members[..., 3])


MEMBER_MODES = ("gather", "selgather", "reduce")


def default_member_mode() -> str:
    """Member-evaluation lowering for hint_fp_match:

    * "gather"    — the round-4 shipped form: members of EVERY slot
      entry evaluated, q_umeta/q_hmeta fetched per member with
      take_along_axis. Verified on the axon backend; the slowest.
    * "selgather" — the matched entry's members are first SELECTED with
      a masked integer SUM over the E axis (exact: the build guarantees
      at most one fp-matched entry per slot row, _place_fp), then the
      same take_along member evaluation runs on E-fold fewer rows.
    * "reduce"    — entry selection as above, then member evaluation as
      a masked MAX reduction over the lset/hmeta table axis (equality
      mask × score) — NO take_along_axis anywhere on the member path.

    The round-4 fast variants (argmax+take_along entry select, 9.56M;
    equality-mask einsum member eval, 77M in-loop) both MISCOMPILED on
    the axon backend in plain-jit context (PERF_NOTES.md §7, three
    sightings: one-hot select, einsum/dot one-hot, argmax+take_along).
    These two re-lowerings express the same math with only where+reduce
    primitives — none of the three sighted bad patterns. Every mode
    must still pass verify_checksum + oracle on the chip in PLAIN-jit
    context before it ships as the default — so the LIBRARY default
    stays "gather" (the round-4 verified form) and bench.py opts into
    "reduce" with a verification-gated fallback. Flip the default only
    with a committed on-chip verification artifact.
    """
    import os
    mode = os.environ.get("VPROXY_TPU_FP_MEMBER", "gather")
    if mode not in MEMBER_MODES:
        raise ValueError(
            f"VPROXY_TPU_FP_MEMBER={mode!r} not in {MEMBER_MODES}")
    return mode


def _sel_entry(ok: jnp.ndarray, mem: jnp.ndarray):
    """Select the unique ok entry's member records via masked SUM over
    the E axis. ok [b, P, E]; mem [b, P, E, M, 4] -> ([b, P, M, 4],
    any-entry-matched [b, P]). Exact because at most one entry per slot
    row can fp-match (_place_fp rejects duplicate fingerprint pairs);
    when none matches the sum is all-zero and the caller gates on the
    returned `any` mask (a zero record would read as rule index 0)."""
    sel = jnp.sum(jnp.where(ok[..., None, None], mem, 0), axis=2)
    return sel, jnp.any(ok, axis=2)


def hint_fp_match(t: dict, q: dict, mode: Optional[str] = None):
    """-> (best rule idx [B] i32 or -1, best level [B] i32). One wide
    row gather per probe; member evaluation lowering per `mode`
    (default_member_mode)."""
    mode = mode or default_member_mode()
    if mode not in MEMBER_MODES:
        raise ValueError(f"unknown member mode {mode!r}")
    r_cap = t["rcap_iota"].shape[0]
    b = q["hp_slot"].shape[0]
    hE, hM = t["h_em"].shape
    uE, uM = t["u_em"].shape
    port = q["port"][:, None]
    has_uri = q["has_uri"][:, None]
    has_host = q["has_host"][:, None]

    # per-candidate URI evaluation data (FULL lset width — host-side
    # members index it by lset position; um_* exist iff the up_* probe
    # arrays were trimmed): [B, lset_cap, 3]
    q_umeta = jnp.stack([q.get("um_fp1", q["up_fp1"]),
                         q.get("um_fp2", q["up_fp2"]),
                         q.get("um_score", q["up_score"])], axis=-1)

    def uri_side_level(lidx, uf1, uf2, ukind, shape):
        """uri_level for host-side members (kind: 0 none / 1 normal /
        2 wildcard); lidx indexes this table's lset probes."""
        if mode == "reduce":
            # equality-mask max-reduction over the lset axis: the score
            # is the ONLY value extracted, and only the l == lidx lane
            # with matching fingerprints contributes. where+max lowers
            # to select+reduce — not a gather, einsum, or one-hot select.
            L = q_umeta.shape[1]
            um_b = q_umeta.reshape((b,) + (1,) * (len(shape) - 1) + (L, 3))
            hit = (lidx[..., None] ==
                   jnp.arange(L, dtype=jnp.int32)) & \
                (um_b[..., 0] == uf1[..., None]) & \
                (um_b[..., 1] == uf2[..., None]) & (um_b[..., 2] > 0)
            content = jnp.max(jnp.where(hit, um_b[..., 2], 0), axis=-1)
        else:
            um = jnp.take_along_axis(q_umeta, lidx.reshape(b, -1, 1), axis=1)
            um = um.reshape(shape + (3,))
            fp_ok = (um[..., 0] == uf1) & (um[..., 1] == uf2) & (um[..., 2] > 0)
            content = jnp.where(fp_ok, um[..., 2], 0)
        wild = has_uri.reshape(
            (b,) + (1,) * (len(shape) - 1)).astype(jnp.int32)
        return jnp.where(ukind == 1, content,
                         jnp.where(ukind == 2,
                                   jnp.maximum(content, wild), 0))

    def host_side_level(hlen, hf1, hf2, hkind, shape):
        """host_level for uri-side members: exact 3 / dot-suffix 2 /
        wildcard 1, via the rolling q_hmeta fingerprints."""
        if mode == "reduce":
            # only two BOOLEANS are extracted (exact / dot-suffix):
            # masked any-reduction over the rolling-fingerprint axis
            W = q["q_hmeta"].shape[1]
            hm_b = q["q_hmeta"].reshape(
                (b,) + (1,) * (len(shape) - 1) + (W, 3))
            hit = (hlen[..., None] ==
                   jnp.arange(W, dtype=jnp.int32)) & \
                (hm_b[..., 0] == hf1[..., None]) & \
                (hm_b[..., 1] == hf2[..., None])
            fp_ok = jnp.any(hit, axis=-1)
            suffix = jnp.any(hit & (hm_b[..., 2] != 0), axis=-1)
            qhlen = q["hlen"].reshape((b,) + (1,) * (len(shape) - 1))
            exact = fp_ok & (hlen == qhlen)
        else:
            hm = jnp.take_along_axis(q["q_hmeta"],
                                     jnp.clip(hlen, 0,
                                              q["q_hmeta"].shape[1] - 1)
                                     .reshape(b, -1, 1), axis=1)
            hm = hm.reshape(shape + (3,))
            fp_ok = (hm[..., 0] == hf1) & (hm[..., 1] == hf2)
            qhlen = q["hlen"].reshape((b,) + (1,) * (len(shape) - 1))
            exact = fp_ok & (hlen == qhlen)
            suffix = fp_ok & (hm[..., 2] != 0)
        hh = has_host.reshape((b,) + (1,) * (len(shape) - 1))
        lvl = jnp.maximum(jnp.where(exact, 3, 0), jnp.where(suffix, 2, 0))
        return jnp.where(hkind == 1, lvl,
                         jnp.where(hkind == 2,
                                   jnp.maximum(lvl, hh.astype(jnp.int32)), 0))

    cands = []

    def add(level, idx, mport):
        pg = (port.reshape((b,) + (1,) * (level.ndim - 1)) == 0) | \
            (mport == 0) | (mport == port.reshape(
                (b,) + (1,) * (level.ndim - 1)))
        lv = jnp.where((idx >= 0) & pg, level, 0)
        cands.append((lv.reshape(b, -1), idx.reshape(b, -1)))

    # ---- ALL probe rows (host + offset uri slots) in ONE gather.
    p_cnt = q["hp_slot"].shape[1]
    rows = t["rec"][jnp.concatenate([q["hp_slot"], q["up_slot"]], axis=1)]
    hew, uew = 2 + 4 * hM, 2 + 4 * uM
    hrows = rows[:, :p_cnt, : hE * hew].reshape(b, -1, hE, hew)
    h_ok = (hrows[..., 0] == q["hp_fp1"][:, :, None]) & \
        (hrows[..., 1] == q["hp_fp2"][:, :, None]) & \
        (q["hp_level"][:, :, None] > 0)
    hmem = hrows[..., 2:].reshape(b, -1, hE, hM, 4)
    if mode == "gather":
        # round-4 shipped form: members of EVERY entry evaluated
        mport, ukind, lidx, midx, uf1, uf2 = _member_fields(hmem)
        ul = uri_side_level(lidx, uf1, uf2, ukind, hmem.shape[:-1])
        hl = q["hp_level"][:, :, None, None]
        add(jnp.where(h_ok[..., None], (hl << HOST_SHIFT) + ul, 0),
            jnp.where(h_ok[..., None], midx, -1), mport)
    else:
        hsel, h_any = _sel_entry(h_ok, hmem)  # [b, P, hM, 4]
        mport, ukind, lidx, midx, uf1, uf2 = _member_fields(hsel)
        ul = uri_side_level(lidx, uf1, uf2, ukind, hsel.shape[:-1])
        hl = q["hp_level"][:, :, None]
        add(jnp.where(h_any[..., None], (hl << HOST_SHIFT) + ul, 0),
            jnp.where(h_any[..., None], midx, -1), mport)

    # ---- uri-probe rows (same gather, offset slots)
    urows = rows[:, p_cnt:, : uE * uew].reshape(b, -1, uE, uew)
    u_ok = (urows[..., 0] == q["up_fp1"][:, :, None]) & \
        (urows[..., 1] == q["up_fp2"][:, :, None]) & \
        (q["up_score"][:, :, None] > 0)
    umem = urows[..., 2:].reshape(b, -1, uE, uM, 4)
    if mode == "gather":
        mport, hkind, hlen, midx, hf1, hf2 = _member_fields(umem)
        hl = host_side_level(hlen, hf1, hf2, hkind, umem.shape[:-1])
        ul = q["up_score"][:, :, None, None]
        add(jnp.where(u_ok[..., None], (hl << HOST_SHIFT) + ul, 0),
            jnp.where(u_ok[..., None], midx, -1), mport)
    else:
        usel, u_any = _sel_entry(u_ok, umem)  # [b, U, uM, 4]
        mport, hkind, hlen, midx, hf1, hf2 = _member_fields(usel)
        hl = host_side_level(hlen, hf1, hf2, hkind, usel.shape[:-1])
        ul = q["up_score"][:, :, None]
        add(jnp.where(u_any[..., None], (hl << HOST_SHIFT) + ul, 0),
            jnp.where(u_any[..., None], midx, -1), mport)

    # ---- wildcard lists (broadcast, no gather)
    whm = jnp.broadcast_to(t["wh_rec"][None], (b,) + t["wh_rec"].shape)
    mport, ukind, lidx, midx, uf1, uf2 = _member_fields(whm)
    ul = uri_side_level(lidx, uf1, uf2, ukind, whm.shape[:-1])  # [B, whc]
    hl = has_host.astype(jnp.int32)  # [B, 1]: host="*" level is 1
    add((hl << HOST_SHIFT) + ul, midx, mport)

    wum = jnp.broadcast_to(t["wu_rec"][None], (b,) + t["wu_rec"].shape)
    mport, hkind, hlen, midx, hf1, hf2 = _member_fields(wum)
    hl = host_side_level(hlen, hf1, hf2, hkind, wum.shape[:-1])
    ul = has_uri.astype(jnp.int32)
    add((hl << HOST_SHIFT) + ul, midx, mport)

    level = jnp.concatenate([c[0] for c in cands], axis=1)
    idx = jnp.concatenate([c[1] for c in cands], axis=1)
    c = jnp.maximum(idx, 0)
    pack = jnp.where(level > 0, level * (r_cap + 1) + (r_cap - c), 0)
    best = jnp.max(pack, axis=1)
    best_level = best // (r_cap + 1)
    best_idx = r_cap - best % (r_cap + 1)
    return jnp.where(best > 0, best_idx, -1).astype(jnp.int32), \
        best_level.astype(jnp.int32)


# --------------------------------------------------------------- cidr side


def _expand_patterns(net) -> list:
    """Network -> [(key16, mask16, family)] — same expansion as
    ops/hashmatch._expand_patterns (Network.maskMatch, Network.java:183)."""
    from .hashmatch import _expand_patterns as _ep
    return _ep(net)


@dataclass
class FpCidrTable:
    """Packed-single-probe CIDR table. Groups (one per (family, mask)
    pattern) are laid out family-V4-first so an all-V4 batch can run on
    the `arrays_v4` slice (about 1/3 of the groups — the v4-in-v6
    duplicate patterns only serve V6-typed queries)."""

    n: int
    r_cap: int
    arrays: dict
    n4: int  # padded count of leading V4-family groups
    caps: dict = field(default_factory=dict)

    @property
    def arrays_v4(self) -> dict:
        g_keys = ("g_mask4", "g_fam", "g_salt_s", "g_salt_f1", "g_salt_f2",
                  "g_off", "g_capmask")
        return {k: (v[: self.n4] if k in g_keys else v)
                for k, v in self.arrays.items()}


def _fnv32_key16(key: bytes, salt: int) -> int:
    """fnv32_words_np(_pack_words16(key)) on python ints — bit-identical
    (LE word packing, 4 FNV rounds, fmix32), ~10x less GIL hold in the
    cidr fp build loop."""
    h = (_FNV32_OFFSET_I ^ int(salt)) & _M32
    for j in range(0, 16, 4):
        w = (key[j] | (key[j + 1] << 8) | (key[j + 2] << 16)
             | (key[j + 3] << 24))
        h = ((h ^ w) * _FNV32_PRIME_I) & _M32
    return _fmix32_i(h)


def _prune_acl_members(items: list, acl) -> list:
    """Members share one network; drop j when an earlier member's port
    range contains j's (the earlier one is always the first match)."""
    keep = []
    for j in sorted(items):
        if not any(acl[i].min_port <= acl[j].min_port and
                   acl[i].max_port >= acl[j].max_port for i in keep):
            keep.append(j)
    return keep


# ------------------------------------------------- v4 direct-index trie
#
# Every V4-family pattern is a contiguous-prefix mask over the low 32
# bits (_expand_patterns), so the whole V4 side compresses into a 16/8/8
# direct-index trie: 3 scalar gathers per query instead of one wide row
# gather per (query, mask-group). Under the measured ~7ns/gathered-row
# cost model (PERF_NOTES.md) that turns the 0.10-0.26us per-query group
# scan into ~0.02us. Semantics are exact: each cell resolves to the
# FIRST-matching rule in list order (min index among covering patterns)
# — route mode paints cells in descending rule order so the lowest index
# lands last; ACL cells keep the full pruned covering-rule list in
# `mrows` so the port filter still picks the first match.
#
# Cell encoding (i32): <0 -> next-level table id (-(id+1)); route mode:
# 0 = miss, v>0 = rule idx + 1; ACL mode: v>=0 = member-row id (row 0 is
# the all-empty row = miss).

_TRIE_TOUCH_LIMIT = 3_000_000  # build-cost guard: fall back to groups


def _trie4_tables(pats4: list, caps: dict):
    """Phase A — allocate subtables. pats4: [(key4, masklen, idx)].
    -> (l0_ptr [65536], l1_ptr [S1cap,256], sub-counts S1, S2)."""
    l0_ptr = np.full(65536, -1, np.int64)
    n_s1 = 0
    for key, m, _ in pats4:
        if m > 16:
            h = (key[0] << 8) | key[1]
            if l0_ptr[h] < 0:
                l0_ptr[h] = n_s1
                n_s1 += 1
    s1_cap = max(caps.get("S1", 0), _pow2(max(n_s1, 1), 4))
    if n_s1 > s1_cap:
        s1_cap = _pow2(n_s1, 4)
    l1_ptr = np.full((s1_cap, 256), -1, np.int64)
    n_s2 = 0
    for key, m, _ in pats4:
        if m > 24:
            s = l0_ptr[(key[0] << 8) | key[1]]
            if l1_ptr[s, key[2]] < 0:
                l1_ptr[s, key[2]] = n_s2
                n_s2 += 1
    s2_cap = max(caps.get("S2", 0), _pow2(max(n_s2, 1), 4))
    if n_s2 > s2_cap:
        s2_cap = _pow2(n_s2, 4)
    return l0_ptr, l1_ptr, s1_cap, s2_cap


def _trie4_paint_route(pats4: list, caps: dict) -> dict:
    """Route cells: min rule idx among covering patterns (descending
    paint order; numpy range writes)."""
    l0_ptr, l1_ptr, s1_cap, s2_cap = _trie4_tables(pats4, caps)
    l0_val = np.zeros(65536, np.int64)
    l1_val = np.zeros((s1_cap, 256), np.int64)
    l2_val = np.zeros((s2_cap, 256), np.int64)
    for key, m, idx in sorted(pats4, key=lambda p: -p[2]):
        v = idx + 1
        if m <= 16:
            lo = (key[0] << 8) | key[1]
            hi = lo + (1 << (16 - m))
            l0_val[lo:hi] = v
            subs = l0_ptr[lo:hi]
            subs = np.unique(subs[subs >= 0])
            if subs.size:
                l1_val[subs] = v
                l2s = l1_ptr[subs]
                l2s = np.unique(l2s[l2s >= 0])
                if l2s.size:
                    l2_val[l2s] = v
        elif m <= 24:
            s = l0_ptr[(key[0] << 8) | key[1]]
            lo = key[2]
            hi = lo + (1 << (24 - m))
            l1_val[s, lo:hi] = v
            l2s = l1_ptr[s, lo:hi]
            l2s = np.unique(l2s[l2s >= 0])
            if l2s.size:
                l2_val[l2s] = v
        else:
            t2 = l1_ptr[l0_ptr[(key[0] << 8) | key[1]], key[2]]
            lo = key[3]
            l2_val[t2, lo: lo + (1 << (32 - m))] = v
    return _trie4_pack(
        np.where(l0_ptr >= 0, -(l0_ptr + 1), l0_val),
        np.where(l1_ptr >= 0, -(l1_ptr + 1), l1_val),
        l2_val, s1_cap, s2_cap)


def _trie4_pack(l0, l1, l2, s1_cap, s2_cap) -> dict:
    """Flat levels walked with scalar gathers. A [N/16, 16] row-packed
    variant with one-hot selects probed 3x faster in isolation, but
    MISCOMPILED under the axon backend (step_fn diverged from the
    oracle while the identical math passed on CPU) and bought nothing
    inside the fused step — keep the verified layout."""
    return {"t_l0": l0.astype(np.int32),
            "t_l1": l1.astype(np.int32).reshape(-1),
            "t_l2": l2.astype(np.int32).reshape(-1),
            "S1": s1_cap, "S2": s2_cap}


def _trie4_cells_acl(pats4: list, caps: dict):
    """ACL cells: the ordered covering-rule LIST per cell (first-match
    with port ranges can't reduce to one winner at build time). Returns
    the raw (l0_ptr, l1_ptr, s1_cap, s2_cap, cell -> rule list) tuple;
    compile_cidr_fp prunes the lists, assigns member rows and encodes
    the level tables. Raises FpBuildError when the build-cost guard
    trips (caller falls back to mask groups)."""
    l0_ptr, l1_ptr, s1_cap, s2_cap = _trie4_tables(pats4, caps)
    touches = 0
    for key, m, _ in pats4:
        if m <= 16:
            lo = (key[0] << 8) | key[1]
            span = 1 << (16 - m)
            touches += span
            subs = l0_ptr[lo: lo + span]
            subs = subs[subs >= 0]
            touches += subs.size * 256
            # descending into every l2 under the covered l1 cells too
            touches += int((l1_ptr[subs] >= 0).sum()) * 256
        elif m <= 24:
            s = l0_ptr[(key[0] << 8) | key[1]]
            lo = key[2]
            span = 1 << (24 - m)
            touches += span
            touches += int((l1_ptr[s, lo: lo + span] >= 0).sum()) * 256
        else:
            touches += 1 << (32 - m)
    if touches > _TRIE_TOUCH_LIMIT:
        raise FpBuildError(f"acl trie too wide to build ({touches} cell"
                           " touches)")
    lists: dict = {}  # cell key -> [rule idx ...] ascending by paint order

    def add(cell, idx):
        lists.setdefault(cell, []).append(idx)

    for key, m, idx in sorted(pats4, key=lambda p: p[2]):
        if m <= 16:
            lo = (key[0] << 8) | key[1]
            for c in range(lo, lo + (1 << (16 - m))):
                s = l0_ptr[c]
                if s < 0:
                    add(("0", c), idx)
                else:
                    for c1 in range(256):
                        t2 = l1_ptr[s, c1]
                        if t2 < 0:
                            add(("1", s, c1), idx)
                        else:
                            for c2 in range(256):
                                add(("2", t2, c2), idx)
        elif m <= 24:
            s = l0_ptr[(key[0] << 8) | key[1]]
            lo = key[2]
            for c1 in range(lo, lo + (1 << (24 - m))):
                t2 = l1_ptr[s, c1]
                if t2 < 0:
                    add(("1", s, c1), idx)
                else:
                    for c2 in range(256):
                        add(("2", t2, c2), idx)
        else:
            t2 = l1_ptr[l0_ptr[(key[0] << 8) | key[1]], key[2]]
            lo = key[3]
            for c2 in range(lo, lo + (1 << (32 - m))):
                add(("2", t2, c2), idx)
    return l0_ptr, l1_ptr, s1_cap, s2_cap, lists


def compile_cidr_fp(networks: Sequence, acl: Optional[Sequence[AclRule]] = None,
                    caps: Optional[dict] = None,
                    strict: bool = True) -> FpCidrTable:
    caps = dict(caps or {})
    n = len(networks)
    r_cap = caps.get("r_cap") or _pad_cap(n, 256)
    if n > r_cap:
        r_cap = _pad_cap(n, 256)

    all_pats = []  # (key16, mask16, fam, rule idx)
    for i, net in enumerate(networks):
        for key, mask, fam in _expand_patterns(net):
            all_pats.append((key, mask, fam, i))

    import os as _os
    if _os.environ.get("VPROXY_TPU_NO_TRIE"):
        caps["no_trie"] = 1  # A/B escape hatch: force the group-only build
    use_trie = not caps.get("no_trie")
    groups: dict[tuple, dict[bytes, list[int]]] = {}
    pats4 = []  # (key4, masklen, rule idx) — contiguous-prefix by construction
    for key, mask, fam, i in all_pats:
        if fam == V4 and use_trie:
            m = bin(int.from_bytes(mask[12:], "big")).count("1")
            pats4.append((key[12:], m, i))
        else:
            groups.setdefault((fam, mask), {}).setdefault(key, []).append(i)

    trie = None
    trie_acl = None
    if use_trie and not pats4 and not caps.get("S1"):
        # v6-only table (and no reused-caps shape to honor): skip the
        # all-miss trie entirely — no build, upload, or per-query walk
        use_trie = False
    if use_trie:
        try:
            if acl is None:
                trie = _trie4_paint_route(pats4, caps)
            else:
                trie_acl = _trie4_cells_acl(pats4, caps)
        except FpBuildError:
            caps["no_trie"] = 1
            use_trie = False
            for key, mask, fam, i in all_pats:
                if fam == V4:
                    groups.setdefault((fam, mask), {}).setdefault(key, []).append(i)

    g4 = sorted(k for k in groups if k[0] == V4)
    g6 = sorted(k for k in groups if k[0] != V4)
    if use_trie:
        n4 = 0  # the trie serves every V4-family pattern
    else:
        n4 = max(caps.get("n4", 0), _pow2(max(len(g4), 1), 4))
    if len(g4) > n4:
        n4 = _pow2(len(g4), 4)
    n6 = max(caps.get("n6", 0), _pow2(max(len(g6), 1), 4))
    if len(g6) > n6:
        n6 = _pow2(len(g6), 4)
    g_cap = n4 + n6

    mk = 1
    trie_lists: list = []      # unique pruned covering lists (trie ACL)
    trie_list_ids: dict = {}   # tuple(list) -> position in trie_lists
    if acl is not None:
        for buckets in groups.values():
            for k in buckets:
                buckets[k] = _prune_acl_members(buckets[k], acl)
                mk = max(mk, len(buckets[k]))
        if trie_acl is not None:
            cells = trie_acl[4]
            for cell, items in cells.items():
                pruned = _prune_acl_members(items, acl)
                tup = tuple(pruned)
                if tup not in trie_list_ids:
                    trie_list_ids[tup] = len(trie_lists)
                    trie_lists.append(pruned)
                cells[cell] = tup
                mk = max(mk, len(pruned))
            if mk > 128:
                # degenerate stacking: rebuild without the trie
                caps["no_trie"] = 1
                return compile_cidr_fp(networks, acl=acl, caps=caps,
                                       strict=strict)
    # both modes use 3-lane slot entries: route = (fp, fp, min idx);
    # ACL = (fp, fp, member-row id) with the (idx, port-range) members
    # in a SECOND narrow table — a query reads the slot row for every
    # group but member rows only for its (single) fp-matched key,
    # instead of every co-slotted key's members
    Mk = max(caps.get("Mk", 0), mk)
    ew = 3

    g_mask4 = np.zeros((g_cap, 4), np.uint32)
    g_fam = np.full(g_cap, -1, np.int32)
    g_salt = np.zeros((3, g_cap), np.uint32)
    g_off = np.zeros(g_cap, np.int32)
    g_capmask = np.zeros(g_cap, np.int32)

    placed = []  # (gi, cap, salts, slots, buckets)
    off = 0
    e_need = 1
    # v4 groups occupy [0, len(g4)), v6 groups [n4, n4+len(g6))
    order = [(i, k) for i, k in enumerate(g4)] + \
            [(n4 + i, k) for i, k in enumerate(g6)]
    for gi, (fam, mask) in order:
        buckets = groups[(fam, mask)]
        cap = _pow2(2 * max(len(buckets), 1), 4)
        # E (entries per slot row) sets the gathered row WIDTH for the
        # whole table — the dominant per-query HBM cost. Grow a group's
        # slot cap until co-slotted keys stop stacking.
        while True:
            salts, slots = _place_fp(list(buckets.keys()), _fnv32_key16,
                                     cap, salt_base=101 + gi)
            e_here = max((len(v) for v in slots.values()), default=1)
            if e_here <= 4 or cap >= 64 * len(buckets):
                break
            cap *= 2
        e_need = max(e_need, e_here)
        g_mask4[gi] = _pack_words16(np.frombuffer(mask, np.uint8))
        g_fam[gi] = fam
        g_salt[0][gi], g_salt[1][gi], g_salt[2][gi] = salts
        g_off[gi] = off
        g_capmask[gi] = cap - 1
        placed.append((gi, cap, salts, slots, buckets))
        off += cap

    E = max(caps.get("E", 0), e_need)
    if E > 128:
        raise FpBuildError(f"degenerate slot pileup: E={E}")
    n_keys = sum(len(groups[k]) for k in groups)
    nm = max(caps.get("nm", 0), _pow2(n_keys + len(trie_lists) + 1, 256))
    ct = max(caps.get("ct", 0), _pow2(max(off, 1), 256))
    rec = np.zeros((ct, E * ew), np.int32)
    mrows = np.full((nm if acl is not None else 1, 2 * Mk), -1, np.int32)
    next_mrow = 1  # row 0 = empty (all idx -1)
    for gi, cap, salts, slots, buckets in placed:
        base_off = g_off[gi]
        for sl, ents in slots.items():
            row = base_off + sl
            for j, (key, f1, f2) in enumerate(ents):
                if acl is None:
                    rec[row, j * ew: j * ew + 3] = [
                        _i32(f1), _i32(f2), min(buckets[key])]
                    continue
                mrow = next_mrow
                next_mrow += 1
                for mi, ridx in enumerate(buckets[key]):
                    r = acl[ridx]
                    mrows[mrow, 2 * mi] = ridx
                    mrows[mrow, 2 * mi + 1] = _i32(
                        (r.min_port & 0xFFFF) | ((r.max_port & 0xFFFF) << 16))
                rec[row, j * ew: j * ew + 3] = [_i32(f1), _i32(f2), mrow]

    if trie_acl is not None:
        # member rows for the trie's per-cell covering lists, then the
        # encoded cell tables (cell value = member-row id, 0 = miss)
        l0_ptr, l1_ptr, s1_cap, s2_cap, cells = trie_acl
        row_of = {}
        for tup, _pos in trie_list_ids.items():
            row = next_mrow
            next_mrow += 1
            for mi, ridx in enumerate(tup):
                r = acl[ridx]
                mrows[row, 2 * mi] = ridx
                mrows[row, 2 * mi + 1] = _i32(
                    (r.min_port & 0xFFFF) | ((r.max_port & 0xFFFF) << 16))
            row_of[tup] = row
        l0_val = np.zeros(65536, np.int64)
        l1_val = np.zeros((s1_cap, 256), np.int64)
        l2_val = np.zeros((s2_cap, 256), np.int64)
        for cell, tup in cells.items():
            v = row_of[tup]
            if cell[0] == "0":
                l0_val[cell[1]] = v
            elif cell[0] == "1":
                l1_val[cell[1], cell[2]] = v
            else:
                l2_val[cell[1], cell[2]] = v
        trie = _trie4_pack(
            np.where(l0_ptr >= 0, -(l0_ptr + 1), l0_val),
            np.where(l1_ptr >= 0, -(l1_ptr + 1), l1_val),
            l2_val, s1_cap, s2_cap)

    allow = np.zeros(r_cap, bool)
    if acl is not None:
        for i, r in enumerate(acl):
            allow[i] = r.allow

    arrays = {
        "g_mask4": g_mask4, "g_fam": g_fam,
        "g_salt_s": g_salt[0], "g_salt_f1": g_salt[1], "g_salt_f2": g_salt[2],
        "g_off": g_off, "g_capmask": g_capmask,
        "rec": rec, "allow": allow,
        "rcap_iota": np.zeros(r_cap, np.int32),
        "e_m": np.zeros((E, 1), np.int32),
    }
    if acl is not None:
        arrays["mrows"] = mrows
    new_caps = {"r_cap": r_cap, "n4": n4, "n6": n6, "E": E, "ct": ct,
                "Mk": Mk, "nm": nm}
    if trie is not None:
        arrays["t_l0"] = trie["t_l0"]
        arrays["t_l1"] = trie["t_l1"]
        arrays["t_l2"] = trie["t_l2"]
        new_caps["S1"] = trie["S1"]
        new_caps["S2"] = trie["S2"]
    if caps.get("no_trie"):
        new_caps["no_trie"] = 1
    if strict and caps and any(caps.get(k, 0) and new_caps[k] > caps[k]
                               for k in new_caps):
        raise CapsExceeded(f"update outgrew reused caps: {caps} -> {new_caps}")
    return FpCidrTable(n=n, r_cap=r_cap, arrays=arrays, n4=n4,
                       caps=new_caps)


def _trie4_lookup(t: dict, addr16: jnp.ndarray) -> jnp.ndarray:
    """3 scalar gathers: 16/8/8 direct-index walk on the low 32 bits.
    -> raw cell value [B] (route: idx+1, 0 miss; ACL: member-row id)."""
    a = addr16.astype(jnp.int32)
    v0 = t["t_l0"][a[:, 12] * 256 + a[:, 13]]
    s1 = jnp.where(v0 < 0, -v0 - 1, 0)
    v1 = t["t_l1"][s1 * 256 + a[:, 14]]
    r1 = jnp.where(v0 < 0, v1, v0)
    s2 = jnp.where(r1 < 0, -r1 - 1, 0)
    v2 = t["t_l2"][s2 * 256 + a[:, 15]]
    return jnp.where(r1 < 0, v2, r1)


def _acl_first(mem: jnp.ndarray, port: Optional[jnp.ndarray],
               r_cap: int) -> jnp.ndarray:
    """mem [B, X, 2] (idx, lo|hi<<16) -> first matching idx or r_cap."""
    midx = mem[..., 0]
    valid = midx >= 0
    if port is not None:
        ports = mem[..., 1]
        lo = ports & 0xFFFF
        hi = (ports >> 16) & 0xFFFF
        p = port[:, None]
        valid = valid & (lo <= p) & (p <= hi)
    b = mem.shape[0]
    return jnp.min(jnp.where(valid, midx, r_cap).reshape(b, -1), axis=1)


def cidr_fp_match(t: dict, addr16: jnp.ndarray, fam: jnp.ndarray,
                  port: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """-> first-matching rule index [B] i32 (ordered-scan semantics), -1
    if none. V4-family queries walk the direct-index trie (3 scalar
    gathers); V6-family queries pay one wide row gather per group."""
    import jax.lax as lax

    r_cap = t["rcap_iota"].shape[0]
    b = addr16.shape[0]
    E = t["e_m"].shape[0]
    ew = t["rec"].shape[1] // E
    G = t["g_fam"].shape[0]
    acl_mode = "mrows" in t
    have_trie = "t_l0" in t

    eok = ents = None
    if G:
        aw = _pack_words16_dev(addr16)  # [B, 4] u32
        masked = aw[:, None, :] & t["g_mask4"][None]  # [B, G, 4]
        hs = _fnv32_words_dev(masked, t["g_salt_s"])
        f1 = lax.bitcast_convert_type(
            _fnv32_words_dev(masked, t["g_salt_f1"]), jnp.int32)
        f2 = lax.bitcast_convert_type(
            _fnv32_words_dev(masked, t["g_salt_f2"]), jnp.int32)
        slot = t["g_off"][None] + (hs & t["g_capmask"].astype(jnp.uint32)[None]
                                   ).astype(jnp.int32)
        rows = t["rec"][slot]  # [B, G, E*ew] — THE gather
        gok = (t["g_fam"][None] >= 0) & (fam[:, None] == t["g_fam"][None])
        ents = rows.reshape(b, -1, E, ew)
        eok = (ents[..., 0] == f1[:, :, None]) & (ents[..., 1] == f2[:, :, None]) \
            & gok[:, :, None]

    if not acl_mode:  # route: entry carries its bucket's min index
        first = jnp.full(b, r_cap, jnp.int32)
        if G:
            idx = jnp.where(eok, ents[..., 2], r_cap)
            first = jnp.min(idx.reshape(b, -1), axis=1).astype(jnp.int32)
        if have_trie:
            tri = (_trie4_lookup(t, addr16) - 1).astype(jnp.int32)
            tri = jnp.where(tri >= 0, tri, r_cap)
            first = jnp.where(fam == V4, tri, first)
        return jnp.where(first < r_cap, first, -1)

    # ACL: entry carries a member-row id; at most ONE entry per group
    # matches (distinct keys under one mask), so the per-group winner
    # reduces to a single member-row gather of (idx, lo|hi<<16) pairs
    first = jnp.full(b, r_cap, jnp.int32)
    if G:
        mrow = jnp.max(jnp.where(eok, ents[..., 2], 0), axis=2)  # [B, G]
        mem = t["mrows"][mrow]  # [B, G, 2*Mk] — narrow second-level gather
        first = _acl_first(mem.reshape(b, -1, 2), port, r_cap).astype(jnp.int32)
    if have_trie:
        mrow_t = _trie4_lookup(t, addr16)  # [B] member-row id (0 = miss)
        mem_t = t["mrows"][mrow_t]  # [B, 2*Mk]
        first_t = _acl_first(mem_t.reshape(b, -1, 2), port,
                             r_cap).astype(jnp.int32)
        first = jnp.where(fam == V4, first_t, first)
    return jnp.where(first < r_cap, first, -1)


hint_fp_jit = jax.jit(hint_fp_match, static_argnames=("mode",))
cidr_fp_jit = jax.jit(cidr_fp_match)


def classify_fp_all(hint_t: dict, route_t: dict, acl_t: dict,
                    hint_q: dict, addr16: jnp.ndarray, fam: jnp.ndarray,
                    port: jnp.ndarray) -> jnp.ndarray:
    """The fused flagship step on the packed fingerprint kernels: one
    dispatch classifies a micro-batch of LB/DNS hints + route LPM + ACL
    checks; one packed [B, 3] i32 result (classify_hash_all's contract
    at ~25x fewer gathered rows)."""
    h_idx, _ = hint_fp_match(hint_t, hint_q)
    r_idx = cidr_fp_match(route_t, addr16, fam, None)
    a_idx = cidr_fp_match(acl_t, addr16, fam, port)
    return jnp.stack([h_idx, r_idx, a_idx], axis=1)


# ----------------------------------------------------- mesh-sharded path
#
# Rule-axis sharding mirrors ops/hashmatch's ShardedHashTable: the rule
# list is sliced, each slice compiled into its OWN fp table under ONE
# unified caps dict (identical shapes), and the per-shard arrays stack
# on a leading axis carrying the mesh's "rules" PartitionSpec. Each
# device runs the UNCHANGED single-shard fp kernel on its slice inside
# shard_map; winners reduce with the same pmax/pmin collectives.

from .hashmatch import _compile_sharded, ShardedHashTable  # noqa: E402


def compile_hint_fp_sharded(rules: Sequence[HintRule], n_shards: int,
                            caps: Optional[dict] = None) -> ShardedHashTable:
    return _compile_sharded(
        rules, n_shards,
        lambda s, off, caps: compile_hint_fp(s, caps=caps, strict=False),
        caps)


def compile_cidr_fp_sharded(networks: Sequence, n_shards: int,
                            acl: Optional[Sequence[AclRule]] = None,
                            caps: Optional[dict] = None) -> ShardedHashTable:
    return _compile_sharded(
        networks, n_shards,
        lambda s, off, caps: compile_cidr_fp(
            s, acl=None if acl is None else acl[off: off + len(s)],
            caps=caps, strict=False), caps)


def encode_hint_queries_fp_sharded(hints: Sequence,
                                   stab: ShardedHashTable) -> dict:
    """Per-shard probe encodings stacked on the leading shard axis
    (salts and slot offsets are shard-local). Probe widths are
    content-dependent (trimmed to each shard's live probes), so they
    are re-padded to the widest shard before stacking."""
    per = [encode_hint_queries_fp(hints, t) for t in stab.shards]
    # um_* exist iff that shard's uri probes were trimmed; shards must
    # agree on keys (fallback = the shard's untrimmed up_* arrays)
    if any("um_fp1" in p for p in per):
        for p in per:
            for mk_, pk_ in (("um_fp1", "up_fp1"), ("um_fp2", "up_fp2"),
                             ("um_score", "up_score")):
                p.setdefault(mk_, p[pk_])
    for k in ("hp_slot", "hp_fp1", "hp_fp2", "hp_level",
              "up_slot", "up_fp1", "up_fp2", "up_score"):
        w = max(p[k].shape[1] for p in per)
        for p in per:
            if p[k].shape[1] < w:
                p[k] = np.pad(p[k], ((0, 0), (0, w - p[k].shape[1])))
    return {k: np.stack([p[k] for p in per]) for k in per[0]}
