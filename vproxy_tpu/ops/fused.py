"""Fused classify+pick dispatch — one launch, one memory sweep per batch.

The round-8 cost model (PERF_NOTES) showed the dispatch chain — FNV
hash, cuckoo probe, hint gather, verdict resolve, Maglev pick — riding
~5 separate XLA dispatches per batch, so every batch paid multiple
launch overheads and multiple passes over the tables. Pope et al.
(MLSys'23) is the template: fixed-shape batches amortize launch
overhead only when the per-batch work is ONE fused program, and Maglev
(Eisenbud, NSDI'16) makes the pick table just another gather that
belongs inside the same sweep.

Two layers live here:

* **Packing** (`pack_hint_table` / `pack_cidr_table`): the compiled
  hash tables (ops/hashmatch) re-packed into int8/int32 layouts chosen
  for a single linear sweep. The per-rule record — active flag, port,
  host/uri kind+len, uri score — becomes ONE int32 row (`pk_meta`,
  [r_cap, 8]) and the host+uri compare bytes ONE uint8 row
  (`pk_bytes`, [r_cap, hw+uw]), so resolving a candidate is two row
  gathers instead of the nine separate-array gathers the unfused
  kernel pays. The cuckoo slot side packs the same way: (used/klen,
  bucket_start, bucket_count) co-locate in one int32 row per slot
  (`pk_hslot`/`pk_uslot`/`pk_cslot`), halving the probe gathers.
  Packing is pure vectorized numpy and runs INSIDE the matcher's
  standby compile (rules/engine.py), so packed generations publish
  through the same double-buffered TableInstaller swap as everything
  else.

* **The fused kernel** (`fused_classify_pick` / `fused_jit`): one
  jitted program taking the encoded query batch plus the published
  snapshot's packed tables (hint, optional cidr/LPM, Maglev column)
  and returning (verdict, pick[, route]) stacked [B, 2|3] — one XLA
  launch, one d2h transfer per batch. Verdicts are bit-identical to
  `hashmatch.hint_hash_match` (same formulas, same i32 packing
  reduction; only the gather layout changed) and picks bit-identical
  to `maglev._device_take` (same host-side FNV slots, same clipped
  take). tests/test_fused.py proves both on randomized 100k-rule
  tables.

A Pallas implementation of the same contract lives in
ops/fused_pallas.py behind a capability probe; `layout_key()` is the
cache key every compiled-fused-fn cache must carry so a
`VPROXY_TPU_*` knob change mid-process can never serve a stale
compiled program (the PR-6 stale-mesh family of bug).
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import cuckoo as CK
from .hashmatch import DOT, HOST_SHIFT, _fnv32_device

# Packed-table layout version: bump on ANY change to the pk_* array
# shapes/column meanings. Folded into layout_key() so compiled-fn
# caches (engine._fused_fn) and cross-process consumers can detect a
# mismatch instead of gathering garbage.
PACK_LAYOUT_V = 1


def kernel_mode() -> str:
    """VPROXY_TPU_FUSED_KERNEL: "auto" (pallas on capable real devices,
    jit elsewhere), "jit" (force the CPU-valid fused jit), "pallas"
    (force the Pallas tier — interpret-mode on CPU when
    VPROXY_TPU_PALLAS_INTERPRET=1, else refused by the probe).
    Re-read per call: jit statics must honor mid-process changes."""
    return os.environ.get("VPROXY_TPU_FUSED_KERNEL", "auto")


def layout_key() -> tuple:
    """The key every fused-fn cache must use: packed layout version +
    the env knobs that select a different compiled program. A knob
    change mid-process produces a NEW key, never a stale hit."""
    return (PACK_LAYOUT_V, kernel_mode(),
            os.environ.get("VPROXY_TPU_PALLAS_INTERPRET", "0"))


# ------------------------------------------------------------- packing

def pack_hint_table(a: dict) -> dict:
    """HashHintTable.arrays -> packed numpy arrays (see module doc).

    Column map (pk_meta, int32 [r_cap, 8]):
      0 active  1 port  2 host_kind  3 host_len
      4 uri_kind  5 uri_len  6 uri_score  7 reserved
    pk_bytes (uint8 [r_cap, hw+uw]): [0:hw] reversed host bytes,
    [hw:] uri bytes — hw is carried statically by pk_hsplit's shape.
    pk_hslot/pk_uslot (int32 [C, 4]): 0 klen-or--1-when-unused,
    1 bucket_start, 2 bucket_count, 3 reserved.

    Static specialization: a generation with ZERO uri rules (no
    normal, no wildcard) can never match by uri, so the uri half of
    the sweep — probe tables, uri byte columns, wildcard list — is
    OMITTED from the packed dict entirely. The dict's key set is part
    of the jit trace structure, so the compiled program for such a
    table simply has no uri work in it (the 1M bench shape is pure
    host rules; this is where its sweep bytes go)."""
    r_cap = a["r_active"].shape[0]
    hw = a["r_host"].shape[1]
    has_uri = bool((a["r_uri_kind"] > 0).any())
    meta = np.zeros((r_cap, 8), np.int32)
    meta[:, 0] = a["r_active"]
    meta[:, 1] = a["r_port"]
    meta[:, 2] = a["r_host_kind"]
    meta[:, 3] = a["r_host_len"]
    meta[:, 4] = a["r_uri_kind"]
    meta[:, 5] = a["r_uri_len"]
    meta[:, 6] = a["r_uri_score"]
    CK.coop_yield()  # standby-compile pacing: multi-MB memcpys below
    by = np.concatenate([a["r_host"], a["r_uri"]], axis=1) if has_uri \
        else np.ascontiguousarray(a["r_host"])
    CK.coop_yield()

    def slot_pack(used, klen, bs, bc):
        s = np.zeros((used.shape[0], 4), np.int32)
        s[:, 0] = np.where(used, klen, -1)
        s[:, 1] = bs
        s[:, 2] = bc
        return s

    out = {
        "pk_meta": meta, "pk_bytes": by,
        "pk_hsplit": np.zeros(hw, np.int8),  # hw as a static shape
        "pk_hslot": slot_pack(a["hk_used"], a["hk_len"], a["hk_bs"],
                              a["hk_bc"]),
        "pk_hkey": a["hk_bytes"],
        "hb_items": a["hb_items"], "wh_idx": a["wh_idx"],
        "bh_iota": a["bh_iota"],
    }
    if has_uri:
        out.update({
            "pk_uslot": slot_pack(a["uk_used"], a["uk_len"], a["uk_bs"],
                                  a["uk_bc"]),
            "pk_ukey": a["uk_bytes"], "ub_items": a["ub_items"],
            "wu_idx": a["wu_idx"], "bu_iota": a["bu_iota"],
        })
    CK.coop_yield()
    return out


def pack_cidr_table(a: dict) -> dict:
    """HashCidrTable.arrays -> packed arrays. pk_cslot (int32 [CT, 4]):
    0 used, 1 bucket_start, 2 bucket_count, 3 reserved; pk_cmeta
    (int32 [r_cap, 4]): 0 valid, 1 min_port, 2 max_port, 3 reserved.
    The small per-group arrays (g_*) stay as-is — they are read once
    per batch, not per candidate."""
    cs = np.zeros((a["s_used"].shape[0], 4), np.int32)
    cs[:, 0] = a["s_used"]
    cs[:, 1] = a["s_bs"]
    cs[:, 2] = a["s_bc"]
    CK.coop_yield()
    cm = np.zeros((a["r_valid"].shape[0], 4), np.int32)
    cm[:, 0] = a["r_valid"]
    cm[:, 1] = a["min_port"]
    cm[:, 2] = a["max_port"]
    CK.coop_yield()
    return {
        "pk_cslot": cs, "pk_cmeta": cm, "s_key": a["s_key"],
        "cb_items": a["cb_items"], "g_fam": a["g_fam"],
        "g_mask": a["g_mask"], "g_off": a["g_off"],
        "g_capmask": a["g_capmask"], "g_salt1": a["g_salt1"],
        "g_salt2": a["g_salt2"], "bk_iota": a["bk_iota"],
    }


# ------------------------------------------------------- fused kernel

def _packed_probe(slots, plen, pslot, kbytes, qbytes, iota):
    """Byte-verified cuckoo probe against the PACKED slot rows: one
    [B, P, 4] gather answers used+klen+bucket in a single sweep (the
    unfused kernel pays four). Same candidate set as
    hashmatch._probe_buckets: unused slots carry klen -1, and a valid
    probe's plen is >= 0, so (klen == plen) subsumes the used test."""
    k = kbytes.shape[1]
    s = jnp.maximum(slots, 0)
    srec = pslot[s]  # [B, P, 4] — the ONE slot gather
    ok = (slots >= 0) & (srec[..., 0] == plen)
    kb = kbytes[s]  # [B, P, K]
    span = jnp.arange(k, dtype=jnp.int32)
    eq = (kb == qbytes[:, None, :k]) | (span[None, None, :] >= plen[:, :, None])
    ok = ok & jnp.all(eq, axis=-1)
    start, cnt = srec[..., 1], srec[..., 2]
    j = iota[None, None, :]
    return jnp.where(ok[:, :, None] & (j < cnt[:, :, None]),
                     start[:, :, None] + j, -1)


def _hint_verdict_packed(t: dict, q: dict):
    """hint_hash_match over the packed layout: candidate resolve is
    TWO row gathers (pk_meta + pk_bytes) instead of nine array
    gathers. Formula-for-formula the unfused kernel — bit-identical
    winners (tests/test_fused.py parity)."""
    r_cap = t["pk_meta"].shape[0]
    b = q["hostb"].shape[0]
    hw = t["pk_hsplit"].shape[0]
    has_uri = "pk_uslot" in t  # static: uri-free tables compile a
    #                            program with NO uri work (pack doc)

    ch1 = _packed_probe(q["hp_slot1"], q["hp_len"], t["pk_hslot"],
                        t["pk_hkey"], q["hostb"], t["bh_iota"])
    ch2 = _packed_probe(q["hp_slot2"], q["hp_len"], t["pk_hslot"],
                        t["pk_hkey"], q["hostb"], t["bh_iota"])
    host_cand = jnp.where(ch1 >= 0, t["hb_items"][jnp.maximum(ch1, 0)], -1)
    host_cand2 = jnp.where(ch2 >= 0, t["hb_items"][jnp.maximum(ch2, 0)], -1)
    parts = [host_cand.reshape(b, -1), host_cand2.reshape(b, -1)]
    if has_uri:
        cu1 = _packed_probe(q["up_slot1"], q["up_len"], t["pk_uslot"],
                            t["pk_ukey"], q["urib"], t["bu_iota"])
        cu2 = _packed_probe(q["up_slot2"], q["up_len"], t["pk_uslot"],
                            t["pk_ukey"], q["urib"], t["bu_iota"])
        parts.append(jnp.where(
            cu1 >= 0, t["ub_items"][jnp.maximum(cu1, 0)], -1)
            .reshape(b, -1))
        parts.append(jnp.where(
            cu2 >= 0, t["ub_items"][jnp.maximum(cu2, 0)], -1)
            .reshape(b, -1))
    parts.append(jnp.broadcast_to(t["wh_idx"][None],
                                  (b, t["wh_idx"].shape[0])))
    if has_uri:
        parts.append(jnp.broadcast_to(t["wu_idx"][None],
                                      (b, t["wu_idx"].shape[0])))
    cand = jnp.concatenate(parts, axis=1)  # [B, NC]

    c = jnp.maximum(cand, 0)
    meta = t["pk_meta"][c]   # [B, NC, 8] — one sweep over the records
    by = t["pk_bytes"][c]    # [B, NC, hw+uw] — one sweep over the bytes
    valid = (cand >= 0) & (meta[..., 0] > 0)

    rp = meta[..., 1]
    pg = (q["port"][:, None] == 0) | (rp == 0) | (q["port"][:, None] == rp)

    hk, hl_ = meta[..., 2], meta[..., 3]
    rb = by[..., :hw]
    span = jnp.arange(hw, dtype=jnp.int32)
    heq = jnp.all((rb == q["hostb"][:, None, :hw]) |
                  (span[None, None, :] >= hl_[:, :, None]), axis=-1)
    exact = heq & (hl_ == q["hlen"][:, None])
    boundary = jnp.take_along_axis(
        q["hostb"], jnp.clip(hl_, 0, hw - 1), axis=1)
    suffix = heq & (hl_ < q["hlen"][:, None]) & (boundary == DOT)
    host_level = jnp.maximum(
        jnp.maximum(jnp.where(exact, 3, 0), jnp.where(suffix, 2, 0)),
        jnp.where(hk == 2, 1, 0))
    host_level = jnp.where((hk > 0) & q["has_host"][:, None], host_level, 0)

    if has_uri:
        uw = by.shape[-1] - hw
        uk, ul = meta[..., 4], meta[..., 5]
        ub = by[..., hw:]
        uspan = jnp.arange(uw, dtype=jnp.int32)
        ueq = jnp.all((ub == q["urib"][:, None, :uw]) |
                      (uspan[None, None, :] >= ul[:, :, None]), axis=-1)
        prefix = ueq & (ul <= q["ulen"][:, None])
        uri_level = jnp.maximum(jnp.where(prefix, meta[..., 6], 0),
                                jnp.where(uk == 2, 1, 0))
        uri_level = jnp.where((uk > 0) & q["has_uri"][:, None],
                              uri_level, 0)
    else:
        uri_level = 0  # no uri rules exist: nothing can score by uri

    level = (host_level << HOST_SHIFT) + uri_level
    level = jnp.where(valid & pg, level, 0)
    from .hashmatch import _reduce_best
    return _reduce_best(level, c, r_cap)


def _cidr_first_packed(t: dict, addr16, fam, port):
    """cidr_hash_match over the packed layout: slot resolve one
    [B, G, 4] gather + the key row; rule gate one pk_cmeta row."""
    r_cap = t["pk_cmeta"].shape[0]
    b = addr16.shape[0]
    masked = addr16[:, None, :] & t["g_mask"][None]  # [B, G, 16]
    gok = (t["g_fam"][None] >= 0) & (fam[:, None] == t["g_fam"][None])

    cands = []
    for salt in (t["g_salt1"], t["g_salt2"]):
        h = _fnv32_device(masked, salt)
        slot = t["g_off"][None] + (
            h.astype(jnp.int32) & t["g_capmask"][None])
        srec = t["pk_cslot"][slot]  # [B, G, 4]
        key = t["s_key"][slot]      # [B, G, 16]
        ok = gok & (srec[..., 0] > 0) & jnp.all(key == masked, axis=-1)
        start, cnt = srec[..., 1], srec[..., 2]
        j = t["bk_iota"][None, None, :]
        cands.append(jnp.where(ok[:, :, None] & (j < cnt[:, :, None]),
                               start[:, :, None] + j, -1))
    slot_cand = jnp.concatenate(cands, axis=1).reshape(b, -1)
    cand = jnp.where(slot_cand >= 0,
                     t["cb_items"][jnp.maximum(slot_cand, 0)], -1)
    c = jnp.maximum(cand, 0)
    meta = t["pk_cmeta"][c]  # [B, NC, 4]
    valid = (cand >= 0) & (meta[..., 0] > 0)
    if port is not None:
        valid = valid & (meta[..., 1] <= port[:, None]) & \
            (port[:, None] <= meta[..., 2])
    first = jnp.min(jnp.where(valid, c, r_cap), axis=1).astype(jnp.int32)
    return jnp.where(first < r_cap, first, -1)


def fused_classify_pick(ht: dict, q: dict, mtab, slots,
                        ct: Optional[dict] = None, a16=None, fam=None,
                        port=None):
    """THE fused program: hint verdict + Maglev pick (+ optional
    cidr/LPM route when a packed cidr table and addr batch ride along)
    in one compiled launch. -> int32 [B, 2] (verdict, pick) or
    [B, 3] (verdict, pick, route). `slots` are host-side FNV Maglev
    slots (the shared hash contract of rules/maglev.py) so the pick
    column is bit-identical with every other pick plane."""
    v, _level = _hint_verdict_packed(ht, q)
    p = jnp.take(mtab, slots, mode="clip").astype(jnp.int32)
    cols = [v, p]
    if ct is not None:
        cols.append(_cidr_first_packed(ct, a16, fam, port))
    return jnp.stack(cols, axis=1)


fused_jit = jax.jit(fused_classify_pick)
