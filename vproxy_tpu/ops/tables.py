"""Rule-table compilers: rule IR -> fixed-shape padded device tables.

Three table kinds (SURVEY.md §7 L2):

* HintTable   — Upstream Host/SNI/URI annotation rules + DNS rrsets
                (Hint.java:92-160 scoring, Upstream.java:187 scan)
* CidrTable   — shared machinery for RouteTable LPM (RouteTable.java:44)
                and SecurityGroup ACL (SecurityGroup.java:30); each rule
                expands to <=3 (value16, mask16, family) patterns that
                reproduce Network.maskMatch's mixed v4/v6 cases
                (Network.java:183-278) exactly.

Tables are host-compiled with numpy into fixed-capacity arrays so rule
updates never retrace the jitted matchers: capacity is padded to a bucket
size, and an update re-fills + re-uploads arrays of the same shape
(double-buffer swap at the engine layer).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..rules.ir import AclRule, HintRule, Proto, RouteRule
from ..utils.ip import to16
from .bitmatch import compile_patterns

MAX_HOST = 64  # max host/domain byte length in device tables
MAX_URI = 128  # max uri prefix byte length
HOST_SLOT = MAX_HOST + 2  # +1 dot-boundary spill slot, +1 length byte
URI_MAX_SCORE = 1023

V4, V6 = 0, 1


MATCH_CHUNK = 8192  # rules per scan step in the chunked matchers


def _pad_cap(n: int, bucket: int = 256) -> int:
    # big tables pad to a multiple of MATCH_CHUNK so the scanned matchers
    # can slice even chunks
    if n > MATCH_CHUNK:
        bucket = MATCH_CHUNK
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


def encode_host(host: Optional[str]) -> np.ndarray:
    """Query-side host encoding: reversed bytes + length byte at the end."""
    out = np.zeros(HOST_SLOT, dtype=np.uint8)
    if host is not None:
        b = host.encode()[::-1]
        # length byte carries the TRUE length so a truncated over-long query
        # can never exact-match a max-length rule; suffix matching only uses
        # the first MAX_HOST reversed bytes (the domain tail), which survive.
        out[-1] = min(len(b), 255)
        # keep MAX_HOST+1 reversed bytes so the dot-boundary spill slot is
        # populated for suffix matches against max-length rule hosts
        b = b[: MAX_HOST + 1]
        out[: len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


def encode_uri(uri: Optional[str]) -> tuple[np.ndarray, int]:
    out = np.zeros(MAX_URI, dtype=np.uint8)
    if uri is None:
        return out, 0
    b = uri.encode()[:MAX_URI]
    out[: len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out, len(b)


@dataclass
class HintTable:
    """Compiled hint-rule table (numpy; upload with jax.device_put)."""

    n: int  # live rule count
    cap: int  # padded capacity
    # host patterns: slot 0 = exact, slot 1 = dot-suffix
    host_w: np.ndarray  # [HOST_SLOT*8, cap*2] f32
    host_c: np.ndarray  # [cap*2] f32
    host_valid: np.ndarray  # [cap, 2] bool
    host_wild: np.ndarray  # [cap] bool
    # uri prefix patterns
    uri_w: np.ndarray  # [MAX_URI*8, cap] f32
    uri_c: np.ndarray  # [cap] f32
    uri_valid: np.ndarray  # [cap] bool
    uri_wild: np.ndarray  # [cap] bool
    uri_score: np.ndarray  # [cap] i32  (min(len+1, 1023))
    port: np.ndarray  # [cap] i32
    active: np.ndarray  # [cap] bool


def compile_hint_rules(rules: Sequence[HintRule], cap: Optional[int] = None) -> HintTable:
    n = len(rules)
    cap = cap or _pad_cap(n)
    assert n <= cap
    hv = np.zeros((cap * 2, HOST_SLOT), dtype=np.uint8)
    hm = np.zeros((cap * 2, HOST_SLOT), dtype=np.uint8)
    host_valid = np.zeros((cap, 2), dtype=bool)
    host_wild = np.zeros(cap, dtype=bool)
    uv = np.zeros((cap, MAX_URI), dtype=np.uint8)
    um = np.zeros((cap, MAX_URI), dtype=np.uint8)
    uri_valid = np.zeros(cap, dtype=bool)
    uri_wild = np.zeros(cap, dtype=bool)
    uri_score = np.zeros(cap, dtype=np.int32)
    port = np.zeros(cap, dtype=np.int32)
    active = np.zeros(cap, dtype=bool)

    for i, r in enumerate(rules):
        if r.is_empty():
            continue
        active[i] = True
        port[i] = r.port
        if r.host is not None:
            hb = r.host.encode()[::-1]
            if len(hb) > MAX_HOST:
                raise ValueError(
                    f"host rule longer than MAX_HOST={MAX_HOST}: {r.host!r}")
            # exact: bytes + length byte must both match
            hv[2 * i, : len(hb)] = np.frombuffer(hb, dtype=np.uint8)
            hm[2 * i, : len(hb)] = 0xFF
            hv[2 * i, -1] = len(hb) & 0xFF
            hm[2 * i, -1] = 0xFF
            host_valid[i, 0] = True
            # suffix: query endswith("." + host) — bytes + '.' boundary,
            # length byte unconstrained (query strictly longer)
            hv[2 * i + 1, : len(hb)] = np.frombuffer(hb, dtype=np.uint8)
            hm[2 * i + 1, : len(hb)] = 0xFF
            hv[2 * i + 1, len(hb)] = ord(".")
            hm[2 * i + 1, len(hb)] = 0xFF
            host_valid[i, 1] = True
            if r.host == "*":
                host_wild[i] = True
        if r.uri is not None:
            ub = r.uri.encode()
            if len(ub) > MAX_URI:
                raise ValueError(
                    f"uri rule longer than MAX_URI={MAX_URI}: {r.uri!r}")
            uv[i, : len(ub)] = np.frombuffer(ub, dtype=np.uint8)
            um[i, : len(ub)] = 0xFF
            uri_valid[i] = True
            uri_score[i] = min(len(ub) + 1, URI_MAX_SCORE)
            if r.uri == "*":
                uri_wild[i] = True

    host_w, host_c = compile_patterns(hv, hm)
    uri_w, uri_c = compile_patterns(uv, um)
    return HintTable(
        n=n, cap=cap,
        host_w=host_w, host_c=host_c, host_valid=host_valid, host_wild=host_wild,
        uri_w=uri_w, uri_c=uri_c, uri_valid=uri_valid, uri_wild=uri_wild,
        uri_score=uri_score, port=port, active=active,
    )


@dataclass
class CidrTable:
    """Compiled CIDR pattern table (3 pattern slots per rule)."""

    n: int
    cap: int
    w: np.ndarray  # [128, cap*3] f32
    c: np.ndarray  # [cap*3] f32
    family: np.ndarray  # [cap*3] i32 (V4/V6)
    valid: np.ndarray  # [cap*3] bool
    # ACL extras (unused for routes):
    min_port: np.ndarray  # [cap] i32
    max_port: np.ndarray  # [cap] i32
    allow: np.ndarray  # [cap] bool


def _expand_cidr(network, vals, masks, fams, valids, base: int) -> None:
    """Fill up to 3 pattern slots (starting at `base`) for one Network,
    reproducing Network.maskMatch. vals/masks are uint8 [slots, 16]."""
    ip, mask = network.ip, network.mask
    if len(ip) == 4:
        # v4 rule: v4 inputs (case 5) + v6 ::x / ::ffff:x inputs (case 4)
        vals[base, 12:] = np.frombuffer(ip, dtype=np.uint8)
        masks[base, 12:] = np.frombuffer(mask, dtype=np.uint8)
        fams[base], valids[base] = V4, True
        vals[base + 1, 12:] = np.frombuffer(ip, dtype=np.uint8)
        masks[base + 1, :12] = 0xFF
        masks[base + 1, 12:] = np.frombuffer(mask, dtype=np.uint8)
        fams[base + 1], valids[base + 1] = V6, True
        vals[base + 2, 10:12] = 0xFF
        vals[base + 2, 12:] = np.frombuffer(ip, dtype=np.uint8)
        masks[base + 2, :12] = 0xFF
        masks[base + 2, 12:] = np.frombuffer(mask, dtype=np.uint8)
        fams[base + 2], valids[base + 2] = V6, True
    elif len(mask) == 4:
        # v6 rule, mask <= 32: v6 inputs only, compare first 4 bytes (case 1)
        vals[base, :4] = np.frombuffer(ip[:4], dtype=np.uint8)
        masks[base, :4] = np.frombuffer(mask, dtype=np.uint8)
        fams[base], valids[base] = V6, True
    else:
        # v6 rule, mask > 32: v6 inputs (case 5) ...
        vals[base, :] = np.frombuffer(ip, dtype=np.uint8)
        masks[base, :] = np.frombuffer(mask, dtype=np.uint8)
        fams[base], valids[base] = V6, True
        # ... and v4 inputs iff rule high bytes are [0]*10 + (0000|ffff)
        hi_ok = all(b == 0 for b in ip[:10]) and (ip[10:12] in (b"\x00\x00", b"\xff\xff"))
        if hi_ok:
            vals[base + 1, 12:] = np.frombuffer(ip[12:], dtype=np.uint8)
            masks[base + 1, 12:] = np.frombuffer(mask[12:], dtype=np.uint8)
            fams[base + 1], valids[base + 1] = V4, True


def compile_cidr_rules(networks: Sequence, cap: Optional[int] = None,
                       acl: Optional[Sequence[AclRule]] = None) -> CidrTable:
    """networks: list of Network in match-priority order (first wins)."""
    n = len(networks)
    cap = cap or _pad_cap(n)
    assert n <= cap
    vals = np.zeros((cap * 3, 16), dtype=np.uint8)
    masks = np.zeros((cap * 3, 16), dtype=np.uint8)
    fams = np.zeros(cap * 3, dtype=np.int32)
    valids = np.zeros(cap * 3, dtype=bool)
    min_port = np.zeros(cap, dtype=np.int32)
    max_port = np.zeros(cap, dtype=np.int32)
    allow = np.zeros(cap, dtype=bool)
    for i, net in enumerate(networks):
        _expand_cidr(net, vals, masks, fams, valids, 3 * i)
    if acl is not None:
        for i, r in enumerate(acl):
            min_port[i], max_port[i], allow[i] = r.min_port, r.max_port, r.allow
    w, c = compile_patterns(vals, masks)
    return CidrTable(n=n, cap=cap, w=w, c=c, family=fams, valid=valids,
                     min_port=min_port, max_port=max_port, allow=allow)


def compile_route_table(rules: Sequence[RouteRule], cap: Optional[int] = None) -> CidrTable:
    return compile_cidr_rules([r.rule for r in rules], cap)


def compile_acl(rules: Sequence[AclRule], proto: Proto, cap: Optional[int] = None) -> CidrTable:
    sub = [r for r in rules if r.protocol == proto]
    return compile_cidr_rules([r.network for r in sub], cap, acl=sub)


def encode_hints(hints: Sequence) -> dict:
    """Batch of Hint queries -> device-ready arrays."""
    b = len(hints)
    host = np.zeros((b, HOST_SLOT), dtype=np.uint8)
    has_host = np.zeros(b, dtype=bool)
    uri = np.zeros((b, MAX_URI), dtype=np.uint8)
    has_uri = np.zeros(b, dtype=bool)
    port = np.zeros(b, dtype=np.int32)
    for i, h in enumerate(hints):
        if h.host is not None:
            host[i] = encode_host(h.host)
            has_host[i] = True
        if h.uri is not None:
            uri[i], _ = encode_uri(h.uri)
            has_uri[i] = True
        port[i] = h.port
    return {"host": host, "has_host": has_host, "uri": uri,
            "has_uri": has_uri, "port": port}


def encode_ips(addrs: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """-> (addr16 [B,16] uint8, family [B] i32)."""
    b = len(addrs)
    # all-v4 fast path (the switch burst, LB accept batches): one buffer
    # reshape instead of a python loop — per-batch encode showed up in
    # the data-plane profile
    if b and all(len(a) == 4 for a in addrs):
        out = np.zeros((b, 16), dtype=np.uint8)
        out[:, 12:] = np.frombuffer(b"".join(addrs),
                                    dtype=np.uint8).reshape(b, 4)
        return out, np.full(b, V4, dtype=np.int32)
    out = np.zeros((b, 16), dtype=np.uint8)
    fam = np.zeros(b, dtype=np.int32)
    for i, a in enumerate(addrs):
        out[i] = np.frombuffer(to16(a), dtype=np.uint8)
        fam[i] = V4 if len(a) == 4 else V6
    return out, fam
