"""Length-framed protocol processors: generic int32-framed and Dubbo.

Parity: processor/common/HeadPayloadProcessor.java:6 (generic protocols
with a fixed-size head carrying the payload length at a fixed offset)
and processor/dubbo/DubboProcessor.java (head 16 bytes, 4-byte payload
length at offset 12). Sessions pick one backend on the first frame
(hint=None -> plain upstream WRR) and relay whole frames; frame
boundaries are tracked both ways so a backend lost between frames can be
replaced silently (the reference's silent DisconnectTODO), mid-frame
loss kills the session.
"""
from __future__ import annotations

from typing import Optional

from .base import Processor, ProcessorEngine, ProtoSession, register


class _FrameScanner:
    """Tracks frame boundaries: head of `head_len` bytes, payload length =
    int at [off, off+len_bytes) big-endian (+head itself not counted)."""

    def __init__(self, head_len: int, off: int, len_bytes: int, max_frame: int):
        self.head_len = head_len
        self.off = off
        self.len_bytes = len_bytes
        self.max_frame = max_frame
        self.head = bytearray()
        self.payload_left = 0
        self.error: Optional[str] = None

    def at_boundary(self) -> bool:
        return not self.head and self.payload_left == 0

    def feed(self, data: bytes) -> int:
        """Consume data (it is relayed verbatim elsewhere); returns number
        of complete frames that ENDED inside this chunk."""
        ended = 0
        pos = 0
        n = len(data)
        while pos < n:
            if self.payload_left:
                take = min(self.payload_left, n - pos)
                self.payload_left -= take
                pos += take
                if self.payload_left == 0:
                    ended += 1
                continue
            need = self.head_len - len(self.head)
            take = min(need, n - pos)
            self.head += data[pos:pos + take]
            pos += take
            if len(self.head) < self.head_len:
                break
            ln = int.from_bytes(
                self.head[self.off:self.off + self.len_bytes], "big")
            self.head = bytearray()
            if ln < 0 or ln > self.max_frame:
                self.error = f"frame length {ln} out of range"
                break
            if ln == 0:
                ended += 1
            else:
                self.payload_left = ln
        return ended


class FramedSession(ProtoSession):
    def __init__(self, engine: ProcessorEngine, proc: "HeadPayloadProcessor"):
        self.engine = engine
        self.proc = proc
        self.back: Optional[int] = None
        self.fscan = proc.scanner()
        self.bscan = proc.scanner()
        self.in_flight = 0  # frames sent minus responses completed

    def _ensure_back(self) -> Optional[int]:
        if self.back is not None:
            return self.back
        try:
            sel = self.engine.select(None)
            self.back = self.engine.open(sel)
        except OSError:
            self.engine.close()
            return None
        return self.back

    def on_front_data(self, data: bytes) -> None:
        back = self._ensure_back()
        if back is None:
            return
        self.in_flight += self.fscan.feed(data)
        if self.fscan.error:
            self.engine.close()
            return
        self.engine.send_back(back, data)

    def on_back_data(self, conn_id: int, data: bytes) -> None:
        done = self.bscan.feed(data)
        if self.bscan.error:
            self.engine.close()
            return
        self.in_flight = max(0, self.in_flight - done)
        self.engine.send_front(data)

    def on_back_closed(self, conn_id: int, err: int) -> bool:
        self.back = None
        # lost between frames with nothing outstanding: next frame reconnects
        if self.fscan.at_boundary() and self.bscan.at_boundary() and \
                self.in_flight == 0:
            return True
        return False

    def on_back_eof(self, conn_id: int) -> None:
        self.engine.close_back(conn_id)


class HeadPayloadProcessor(Processor):
    def __init__(self, name: str, head_len: int, off: int, len_bytes: int,
                 max_frame: int = 16 * 1024 * 1024):
        self.name = name
        self.head_len = head_len
        self.off = off
        self.len_bytes = len_bytes
        self.max_frame = max_frame

    def scanner(self) -> _FrameScanner:
        return _FrameScanner(self.head_len, self.off, self.len_bytes,
                             self.max_frame)

    def session(self, engine: ProcessorEngine, client_addr) -> FramedSession:
        return FramedSession(engine, self)


# dubbo wire: 2B magic, 1B flags, 1B status, 8B request id, 4B body length
register(HeadPayloadProcessor("dubbo", head_len=16, off=12, len_bytes=4))
# generic 4-byte big-endian length-prefixed framing
register(HeadPayloadProcessor("framed-int32", head_len=4, off=0, len_bytes=4))
