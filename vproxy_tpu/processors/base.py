"""Processor SPI — pluggable L7 protocol engines for the proxy.

Functional analog of the reference's processor SPI
(processor/Processor.java:11-276 + ProcessorProvider.java:6): a TcpLB
with `protocol=<name>` drives every accepted connection through a
per-connection protocol session that may route each request/stream to a
different backend (Hint-based selection through the classify engine).

The reference SPI is pull-based (process() returns TODO{len, mode
handle|proxy, feed} instructions the library executes). This framework's
Connection layer is callback-driven, so the SPI here is push-based and
event-driven — same capabilities (per-frame backend selection, proxy
mode for bulk bytes, multiple backends per frontend), mapped 1:1 onto
handler callbacks instead of TODO objects:

    reference                         here
    ---------                         ----
    process().mode=handle + feed()    on_front_data / on_back_data
    HandleTODO.send + connTODO        engine.send_back(conn_id, data)
    HandleTODO.produce                engine.send_front(data)
    ConnectionTODO{-1, hint, chosen}  engine.connect(hint) -> conn_id
    proxy mode (bulk)                 the same callbacks (python relays
                                      in large chunks; the native splice
                                      pump covers protocol="tcp")
    disconnected() silent|kill        on_back_closed returning bool
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..rules.ir import Hint


class ProcessorEngine:
    """What a ProtoSession may call. Implemented by components/l7.py."""

    def send_front(self, data: bytes) -> None:
        raise NotImplementedError

    def send_back(self, conn_id: int, data: bytes) -> None:
        raise NotImplementedError

    def connect(self, hint: Optional[Hint]) -> int:
        """Open a backend connection selected via the upstream (hint goes
        through the classify engine). Returns a conn_id > 0. Raises
        OSError if no backend matches. The connection is established
        asynchronously; on_back_connected(conn_id) fires when writable.
        Data may be queued with send_back before that."""
        raise NotImplementedError

    def close_back(self, conn_id: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        """Tear down the whole session (frontend + all backends)."""
        raise NotImplementedError

    def pause_front(self) -> None: ...

    def resume_front(self) -> None: ...

    def pause_back(self, conn_id: int) -> None: ...

    def resume_back(self, conn_id: int) -> None: ...


class ProtoSession:
    """Per-frontend-connection protocol state machine."""

    def on_front_data(self, data: bytes) -> None:
        raise NotImplementedError

    def on_front_eof(self) -> None:
        """Frontend half-closed. Default: tear down."""
        self.engine.close()  # type: ignore[attr-defined]

    def on_back_connected(self, conn_id: int) -> None: ...

    def on_back_data(self, conn_id: int, data: bytes) -> None:
        raise NotImplementedError

    def on_back_eof(self, conn_id: int) -> None:
        self.engine.close_back(conn_id)  # type: ignore[attr-defined]

    def on_back_closed(self, conn_id: int, err: int) -> bool:
        """Backend gone. Return True if handled silently (session keeps
        going — Processor.DisconnectTODO.silent), False to kill the whole
        session."""
        return False

    def on_front_drained(self) -> None:
        """Frontend out-buffer flushed (resume proxying paused sources)."""

    def on_back_drained(self, conn_id: int) -> None: ...


class Processor:
    """Protocol factory registered under a name (ProcessorProvider)."""

    name: str = ""
    alpn: Optional[Sequence[str]] = None

    def session(self, engine: ProcessorEngine, client_addr) -> ProtoSession:
        raise NotImplementedError


class TcpRelaySession(ProtoSession):
    """Raw bidirectional relay through one backend — the handleDirect
    analog for fronts that cannot use the native splice pump (e.g. a
    TLS-terminated frontend, Proxy.java:65-149 with SSL buffers). The
    backend is selected on first data via hint_fn (SNI flows in here)."""

    def __init__(self, engine: ProcessorEngine, client_addr, hint_fn=None):
        self.engine = engine
        self.client_addr = client_addr
        self.hint_fn = hint_fn
        self.back: Optional[int] = None

    def _ensure(self) -> Optional[int]:
        if self.back is None:
            hint = self.hint_fn() if self.hint_fn is not None else None
            try:
                self.back = self.engine.open(self.engine.select(hint))
            except OSError:
                self.engine.close()
                return None
        return self.back

    def _mirror(self, data: bytes, outbound: bool) -> None:
        from ..utils.ip import parse_ip
        from ..utils.mirror import Mirror
        addr = self.client_addr
        try:
            cip = parse_ip(addr[0]) if addr else b"\x00\x00\x00\x00"
        except ValueError:
            cip = b"\x00\x00\x00\x00"
        cport = addr[1] if addr else 0
        if outbound:
            Mirror.get().mirror("proxy", data, dst_ip=cip, dst_port=cport)
        else:
            Mirror.get().mirror("proxy", data, src_ip=cip, src_port=cport)

    def on_front_data(self, data: bytes) -> None:
        from ..utils.mirror import Mirror
        if Mirror.get().hot:
            self._mirror(data, outbound=False)
        back = self._ensure()
        if back is not None:
            self.engine.send_back(back, data)

    def on_back_data(self, conn_id: int, data: bytes) -> None:
        from ..utils.mirror import Mirror
        if Mirror.get().hot:
            self._mirror(data, outbound=True)
        self.engine.send_front(data)

    def on_back_eof(self, conn_id: int) -> None:
        self.engine.close()

    def on_back_closed(self, conn_id: int, err: int) -> bool:
        return False


_REGISTRY: dict[str, Processor] = {}


def register(p: Processor) -> None:
    _REGISTRY[p.name] = p


def get(name: str) -> Optional[Processor]:
    _ensure_defaults()
    return _REGISTRY.get(name)


def names() -> list[str]:
    _ensure_defaults()
    return sorted(_REGISTRY)


_defaults_loaded = False


def _ensure_defaults() -> None:
    """Register built-ins lazily (DefaultProcessorRegistry.java:19-23:
    h2, int32-framed, dubbo, http1, general http)."""
    global _defaults_loaded
    if _defaults_loaded:
        return
    _defaults_loaded = True
    from . import framed, h2, http1  # noqa: F401  (self-registering)
