"""HPACK (RFC 7541) header compression for the h2 processor.

Functional analog of the reference's vendored twitter hpack
(com/twitter/hpack/Decoder.java, Encoder.java). The constant tables
below are the RFC 7541 appendices verbatim: Appendix A static table,
Appendix B Huffman codes.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

# ---------------------------------------------------------------- constants

# RFC 7541 Appendix A — indices 1..61
STATIC_TABLE: list[tuple[bytes, bytes]] = [(n.encode(), v.encode()) for n, v in [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""),
    ("access-control-allow-origin", ""), ("age", ""), ("allow", ""),
    ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""),
    ("content-location", ""), ("content-range", ""), ("content-type", ""),
    ("cookie", ""), ("date", ""), ("etag", ""), ("expect", ""),
    ("expires", ""), ("from", ""), ("host", ""), ("if-match", ""),
    ("if-modified-since", ""), ("if-none-match", ""), ("if-range", ""),
    ("if-unmodified-since", ""), ("last-modified", ""), ("link", ""),
    ("location", ""), ("max-forwards", ""), ("proxy-authenticate", ""),
    ("proxy-authorization", ""), ("range", ""), ("referer", ""),
    ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""),
    ("via", ""), ("www-authenticate", ""),
]]

# RFC 7541 Appendix B — Huffman code for each of 256 byte values + EOS
HUFFMAN_CODES = [
    8184, 8388568, 268435426, 268435427, 268435428, 268435429, 268435430,
    268435431, 268435432, 16777194, 1073741820, 268435433, 268435434,
    1073741821, 268435435, 268435436, 268435437, 268435438, 268435439,
    268435440, 268435441, 268435442, 1073741822, 268435443, 268435444,
    268435445, 268435446, 268435447, 268435448, 268435449, 268435450,
    268435451, 20, 1016, 1017, 4090, 8185, 21, 248, 2042, 1018, 1019, 249,
    2043, 250, 22, 23, 24, 0, 1, 2, 25, 26, 27, 28, 29, 30, 31, 92, 251,
    32764, 32, 4091, 1020, 8186, 33, 93, 94, 95, 96, 97, 98, 99, 100, 101,
    102, 103, 104, 105, 106, 107, 108, 109, 110, 111, 112, 113, 114, 252,
    115, 253, 8187, 524272, 8188, 16380, 34, 32765, 3, 35, 4, 36, 5, 37, 38,
    39, 6, 116, 117, 40, 41, 42, 7, 43, 118, 44, 8, 9, 45, 119, 120, 121,
    122, 123, 32766, 2044, 16381, 8189, 268435452, 1048550, 4194258, 1048551,
    1048552, 4194259, 4194260, 4194261, 8388569, 4194262, 8388570, 8388571,
    8388572, 8388573, 8388574, 16777195, 8388575, 16777196, 16777197,
    4194263, 8388576, 16777198, 8388577, 8388578, 8388579, 8388580, 2097116,
    4194264, 8388581, 4194265, 8388582, 8388583, 16777199, 4194266, 2097117,
    1048553, 4194267, 4194268, 8388584, 8388585, 2097118, 8388586, 4194269,
    4194270, 16777200, 2097119, 4194271, 8388587, 8388588, 2097120, 2097121,
    4194272, 2097122, 8388589, 4194273, 8388590, 8388591, 1048554, 4194274,
    4194275, 4194276, 8388592, 4194277, 4194278, 8388593, 67108832,
    67108833, 1048555, 524273, 4194279, 8388594, 4194280, 33554412,
    67108834, 67108835, 67108836, 134217694, 134217695, 67108837, 16777201,
    33554413, 524274, 2097123, 67108838, 134217696, 134217697, 67108839,
    134217698, 16777202, 2097124, 2097125, 67108840, 67108841, 268435453,
    134217699, 134217700, 134217701, 1048556, 16777203, 1048557, 2097126,
    4194281, 2097127, 2097128, 8388595, 4194282, 4194283, 33554414,
    33554415, 16777204, 16777205, 67108842, 8388596, 67108843, 134217702,
    67108844, 67108845, 134217703, 134217704, 134217705, 134217706,
    134217707, 268435454, 134217708, 134217709, 134217710, 134217711,
    134217712, 67108846, 1073741823,
]
HUFFMAN_LENGTHS = [
    13, 23, 28, 28, 28, 28, 28, 28, 28, 24, 30, 28, 28, 30, 28, 28, 28, 28,
    28, 28, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 28, 6, 10, 10, 12,
    13, 6, 8, 11, 10, 10, 8, 11, 8, 6, 6, 6, 5, 5, 5, 6, 6, 6, 6, 6, 6, 6,
    7, 8, 15, 6, 12, 10, 13, 6, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7,
    7, 7, 7, 7, 7, 7, 7, 7, 8, 7, 8, 13, 19, 13, 14, 6, 15, 5, 6, 5, 6, 5,
    6, 6, 6, 5, 7, 7, 6, 6, 6, 5, 6, 7, 6, 5, 5, 6, 7, 7, 7, 7, 7, 15, 11,
    14, 13, 28, 20, 22, 20, 20, 22, 22, 22, 23, 22, 23, 23, 23, 23, 23, 24,
    23, 24, 24, 22, 23, 24, 23, 23, 23, 23, 21, 22, 23, 22, 23, 23, 24, 22,
    21, 20, 22, 22, 23, 23, 21, 23, 22, 22, 24, 21, 22, 23, 23, 21, 21, 22,
    21, 23, 22, 23, 23, 20, 22, 22, 22, 23, 22, 22, 23, 26, 26, 20, 19, 22,
    23, 22, 25, 26, 26, 26, 27, 27, 26, 24, 25, 19, 21, 26, 27, 27, 26, 27,
    24, 21, 21, 26, 26, 28, 27, 27, 27, 20, 24, 20, 21, 22, 21, 21, 23, 22,
    22, 25, 25, 24, 24, 26, 23, 26, 27, 26, 26, 27, 27, 27, 27, 27, 28, 27,
    27, 27, 27, 27, 26, 30,
]

ENTRY_OVERHEAD = 32  # RFC 7541 §4.1
DEFAULT_TABLE_SIZE = 4096


class HpackError(Exception):
    pass


# ---------------------------------------------------------------- huffman

def _build_decode_tree():
    root: list = [None, None]
    for sym, (code, ln) in enumerate(zip(HUFFMAN_CODES, HUFFMAN_LENGTHS)):
        node = root
        for i in range(ln - 1, -1, -1):
            bit = (code >> i) & 1
            if i == 0:
                node[bit] = sym
            else:
                if node[bit] is None:
                    node[bit] = [None, None]
                node = node[bit]
    return root


_DECODE_TREE = _build_decode_tree()
EOS = 256


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = _DECODE_TREE
    # track bits consumed since last symbol for the padding validity check
    pad_bits = 0
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            nxt = node[bit]
            if nxt is None:
                raise HpackError("bad huffman code")
            if isinstance(nxt, int):
                if nxt == EOS:
                    raise HpackError("EOS in huffman data")
                out.append(nxt)
                node = _DECODE_TREE
                pad_bits = 0
            else:
                node = nxt
                pad_bits += 1
    if pad_bits > 7:
        raise HpackError("huffman padding too long")
    # remaining bits must be the EOS prefix (all ones)
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    cur = 0
    nbits = 0
    out = bytearray()
    for b in data:
        cur = (cur << HUFFMAN_LENGTHS[b]) | HUFFMAN_CODES[b]
        nbits += HUFFMAN_LENGTHS[b]
        while nbits >= 8:
            nbits -= 8
            out.append((cur >> nbits) & 0xFF)
    if nbits:
        out.append(((cur << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def huffman_len(data: bytes) -> int:
    return (sum(HUFFMAN_LENGTHS[b] for b in data) + 7) // 8


# ---------------------------------------------------------------- integers

def encode_int(value: int, prefix_bits: int, first_byte: int = 0) -> bytes:
    """RFC 7541 §5.1 prefix-coded integer; first_byte carries the pattern
    bits above the prefix."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte | value])
    out = bytearray([first_byte | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data: bytes, pos: int, prefix_bits: int) -> tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise HpackError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer too large")
        if not b & 0x80:
            return value, pos


# ---------------------------------------------------------------- tables

class _DynamicTable:
    def __init__(self, max_size: int):
        self.max_size = max_size
        self.size = 0
        self.entries: deque[tuple[bytes, bytes]] = deque()

    def add(self, name: bytes, value: bytes) -> None:
        sz = len(name) + len(value) + ENTRY_OVERHEAD
        if sz > self.max_size:
            self.entries.clear()
            self.size = 0
            return
        while self.size + sz > self.max_size:
            en, ev = self.entries.pop()
            self.size -= len(en) + len(ev) + ENTRY_OVERHEAD
        self.entries.appendleft((name, value))
        self.size += sz

    def resize(self, max_size: int) -> None:
        self.max_size = max_size
        while self.size > max_size:
            en, ev = self.entries.pop()
            self.size -= len(en) + len(ev) + ENTRY_OVERHEAD

    def get(self, i: int) -> tuple[bytes, bytes]:  # 0-based
        if i >= len(self.entries):
            raise HpackError(f"dynamic index {i} out of range")
        return self.entries[i]


def _lookup(table: _DynamicTable, index: int) -> tuple[bytes, bytes]:
    if index <= 0:
        raise HpackError("index 0")
    if index <= len(STATIC_TABLE):
        return STATIC_TABLE[index - 1]
    return table.get(index - len(STATIC_TABLE) - 1)


# ---------------------------------------------------------------- decoder

class Decoder:
    def __init__(self, max_table_size: int = DEFAULT_TABLE_SIZE):
        self.table = _DynamicTable(max_table_size)
        self.protocol_max = max_table_size

    def set_protocol_max(self, n: int) -> None:
        """SETTINGS_HEADER_TABLE_SIZE we advertised (upper bound for
        dynamic-table-size updates from the peer)."""
        self.protocol_max = n
        if self.table.max_size > n:
            self.table.resize(n)

    def _read_string(self, data: bytes, pos: int) -> tuple[bytes, int]:
        if pos >= len(data):
            raise HpackError("truncated string")
        huff = bool(data[pos] & 0x80)
        ln, pos = decode_int(data, pos, 7)
        if pos + ln > len(data):
            raise HpackError("truncated string data")
        raw = data[pos:pos + ln]
        pos += ln
        return (huffman_decode(raw) if huff else raw), pos

    def decode(self, data: bytes) -> list[tuple[bytes, bytes]]:
        out: list[tuple[bytes, bytes]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed
                idx, pos = decode_int(data, pos, 7)
                out.append(_lookup(self.table, idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = decode_int(data, pos, 6)
                name = _lookup(self.table, idx)[0] if idx else None
                if name is None:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                self.table.add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                sz, pos = decode_int(data, pos, 5)
                if sz > self.protocol_max:
                    raise HpackError("table size update beyond settings")
                self.table.resize(sz)
            else:  # literal without indexing / never indexed
                idx, pos = decode_int(data, pos, 4)
                name = _lookup(self.table, idx)[0] if idx else None
                if name is None:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                out.append((name, value))
        return out


# ---------------------------------------------------------------- encoder

_STATIC_FULL = {e: i + 1 for i, e in reversed(list(enumerate(STATIC_TABLE)))}
_STATIC_NAME = {}
for _i, (_n, _v) in reversed(list(enumerate(STATIC_TABLE))):
    _STATIC_NAME[_n] = _i + 1


class Encoder:
    def __init__(self, max_table_size: int = DEFAULT_TABLE_SIZE):
        self.table = _DynamicTable(max_table_size)

    def _write_string(self, out: bytearray, s: bytes) -> None:
        hl = huffman_len(s)
        if hl < len(s):
            out += encode_int(hl, 7, 0x80)
            out += huffman_encode(s)
        else:
            out += encode_int(len(s), 7, 0)
            out += s

    def encode(self, headers: list[tuple[bytes, bytes]],
               sensitive: Optional[set[bytes]] = None) -> bytes:
        out = bytearray()
        for name, value in headers:
            if sensitive and name in sensitive:
                # never-indexed literal
                idx = _STATIC_NAME.get(name, 0)
                out += encode_int(idx, 4, 0x10)
                if not idx:
                    self._write_string(out, name)
                self._write_string(out, value)
                continue
            full = _STATIC_FULL.get((name, value))
            if full is None:
                for j, e in enumerate(self.table.entries):
                    if e == (name, value):
                        full = len(STATIC_TABLE) + j + 1
                        break
            if full is not None:
                out += encode_int(full, 7, 0x80)
                continue
            idx = _STATIC_NAME.get(name, 0)
            if not idx:
                for j, e in enumerate(self.table.entries):
                    if e[0] == name:
                        idx = len(STATIC_TABLE) + j + 1
                        break
            # literal with incremental indexing
            out += encode_int(idx, 6, 0x40)
            if not idx:
                self._write_string(out, name)
            self._write_string(out, value)
            self.table.add(name, value)
        return bytes(out)
