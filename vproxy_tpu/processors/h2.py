"""HTTP/2 processor — per-stream backend routing (the `h2` protocol).

Behavioral parity with the reference's httpbin processor
(processor/httpbin/BinaryHttpProcessor.java:10,
BinaryHttpSubContext.java: state machine over preface/SETTINGS/frames,
per-stream Hint routing httpbin/Stream.java:50, HPACK re-encoding): this
framework terminates h2 framing on both sides and relays per stream —
client streams map to streams on per-backend h2 connections selected by
Hint(:authority, :path) through the classify engine, header blocks are
HPACK-decoded and re-encoded per hop (each hop has its own dynamic-table
state), DATA is relayed under both hops' flow-control windows, and
PING/SETTINGS/WINDOW_UPDATE stay hop-local.

grpc and h2c (connection-preface cleartext, as used by h2load/grpc) work
through this processor; our encoder is static-table-only (never adds
dynamic entries), which keeps hop HPACK state trivially consistent.
"""
from __future__ import annotations

import struct
from typing import Optional

from ..rules.ir import Hint
from . import hpack
from .base import Processor, ProcessorEngine, ProtoSession, register

FRAME_HEAD = 9
PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

DATA, HEADERS, PRIORITY, RST_STREAM, SETTINGS, PUSH_PROMISE, PING, GOAWAY, \
    WINDOW_UPDATE, CONTINUATION = range(10)

F_END_STREAM = 0x1
F_ACK = 0x1
F_END_HEADERS = 0x4
F_PADDED = 0x8
F_PRIORITY = 0x20

S_HEADER_TABLE_SIZE = 1
S_ENABLE_PUSH = 2
S_MAX_CONCURRENT = 3
S_INITIAL_WINDOW = 4
S_MAX_FRAME_SIZE = 5

ERR_NO, ERR_PROTOCOL, ERR_INTERNAL, ERR_FLOW, ERR_REFUSED = 0, 1, 2, 3, 7

DEFAULT_WINDOW = 65535
MAX_PEND = 4 * 1024 * 1024  # per-stream relay buffer cap


class H2Error(Exception):
    def __init__(self, msg: str, code: int = ERR_PROTOCOL):
        super().__init__(msg)
        self.code = code


def frame(ftype: int, flags: int, sid: int, payload: bytes = b"") -> bytes:
    return struct.pack(">I", len(payload))[1:] + bytes((ftype, flags)) + \
        struct.pack(">I", sid & 0x7FFFFFFF) + payload


def settings_payload(pairs: list[tuple[int, int]]) -> bytes:
    return b"".join(struct.pack(">HI", k, v) for k, v in pairs)


class _Side:
    """One h2 hop (frontend conn or one backend conn): framing state,
    HPACK codecs, and SEND-direction flow-control accounting."""

    def __init__(self, server: bool, send, sid_start: int = 0):
        self.server = server
        self.send = send  # callable(bytes)
        self.buf = bytearray()
        self.preface_left = len(PREFACE) if server else 0
        self.dec = hpack.Decoder()
        self.enc = _StaticEncoder()
        self.conn_window = DEFAULT_WINDOW  # our budget for sending to them
        self.stream_window: dict[int, int] = {}
        self.initial_window = DEFAULT_WINDOW  # their INITIAL_WINDOW_SIZE
        self.peer_max_frame = 16384
        self.got_settings = False
        self.next_sid = sid_start  # client role: odd ids we allocate
        self.goaway = False
        # header-block accumulation (HEADERS/CONTINUATION until END_HEADERS)
        self.hdr_sid: Optional[int] = None
        self.hdr_flags = 0
        self.hdr_buf = bytearray()

    def alloc_sid(self) -> int:
        self.next_sid += 2
        return self.next_sid - 2

    # ---------------------------------------------------------- rx framing

    def feed(self, data: bytes):
        """-> list of (ftype, flags, sid, payload). Raises H2Error."""
        self.buf += data
        out = []
        if self.preface_left:
            take = min(self.preface_left, len(self.buf))
            expect = PREFACE[len(PREFACE) - self.preface_left:][:take]
            if bytes(self.buf[:take]) != expect:
                raise H2Error("bad client preface")
            del self.buf[:take]
            self.preface_left -= take
            if self.preface_left:
                return out
        while len(self.buf) >= FRAME_HEAD:
            ln = int.from_bytes(self.buf[:3], "big")
            if ln > 16384 + 256:  # our MAX_FRAME_SIZE stays default
                raise H2Error("frame too large", ERR_FLOW)
            if len(self.buf) < FRAME_HEAD + ln:
                break
            ftype, flags = self.buf[3], self.buf[4]
            sid = int.from_bytes(self.buf[5:9], "big") & 0x7FFFFFFF
            payload = bytes(self.buf[FRAME_HEAD:FRAME_HEAD + ln])
            del self.buf[:FRAME_HEAD + ln]
            out.append((ftype, flags, sid, payload))
        return out

    # ---------------------------------------------------------- tx helpers

    def send_headers(self, sid: int, headers: list[tuple[bytes, bytes]],
                     end_stream: bool) -> None:
        block = self.enc.encode(headers)
        flags = F_END_STREAM if end_stream else 0
        first = block[: self.peer_max_frame]
        rest = block[self.peer_max_frame:]
        if not rest:
            self.send(frame(HEADERS, flags | F_END_HEADERS, sid, first))
            return
        self.send(frame(HEADERS, flags, sid, first))
        while rest:
            chunk, rest = rest[: self.peer_max_frame], rest[self.peer_max_frame:]
            f = F_END_HEADERS if not rest else 0
            self.send(frame(CONTINUATION, f, sid, chunk))

    def window_for(self, sid: int) -> int:
        return min(self.conn_window, self.stream_window.get(sid, 0))

    def send_data(self, sid: int, chunk: bytes, end_stream: bool) -> None:
        self.conn_window -= len(chunk)
        if sid in self.stream_window:
            self.stream_window[sid] -= len(chunk)
        self.send(frame(DATA, F_END_STREAM if end_stream else 0, sid, chunk))

    def grant(self, sid: int, n: int) -> None:
        """Give the peer back receive window for relayed DATA."""
        if n <= 0:
            return
        inc = struct.pack(">I", n)
        self.send(frame(WINDOW_UPDATE, 0, 0, inc))
        self.send(frame(WINDOW_UPDATE, 0, sid, inc))

    def apply_settings(self, payload: bytes) -> None:
        if len(payload) % 6:
            raise H2Error("bad SETTINGS length")
        for off in range(0, len(payload), 6):
            k, v = struct.unpack_from(">HI", payload, off)
            if k == S_INITIAL_WINDOW:
                if v > 0x7FFFFFFF:
                    raise H2Error("bad INITIAL_WINDOW_SIZE", ERR_FLOW)
                delta = v - self.initial_window
                self.initial_window = v
                for s in self.stream_window:
                    self.stream_window[s] += delta
            elif k == S_MAX_FRAME_SIZE:
                if 16384 <= v <= 16777215:
                    self.peer_max_frame = v
            elif k == S_HEADER_TABLE_SIZE:
                # our encoder is static-only; nothing to resize
                pass
        self.got_settings = True
        self.send(frame(SETTINGS, F_ACK, 0))


class _StaticEncoder(hpack.Encoder):
    """HPACK encoder that never grows the dynamic table (always-legal
    stateless hop encoding; peers still compress toward us and our
    Decoder tracks their dynamic table)."""

    def __init__(self):
        super().__init__(max_table_size=0)


def strip_padding(flags: int, payload: bytes, has_priority: bool) -> bytes:
    pos = 0
    pad = 0
    if flags & F_PADDED:
        if not payload:
            raise H2Error("bad padding")
        pad = payload[0]
        pos = 1
    if has_priority and flags & F_PRIORITY:
        pos += 5
    if pad > len(payload) - pos:
        raise H2Error("padding exceeds payload")
    return payload[pos: len(payload) - pad]


class _Stream:
    __slots__ = ("fsid", "conn_id", "bsid", "to_back", "to_front",
                 "end_to_back", "end_to_front", "front_closed", "back_closed",
                 "got_response", "trailers", "front_trailers")

    def __init__(self, fsid: int, conn_id: int, bsid: int):
        self.fsid = fsid
        self.conn_id = conn_id
        self.bsid = bsid
        self.to_back = bytearray()   # DATA bytes waiting for backend window
        self.to_front = bytearray()  # DATA bytes waiting for client window
        self.end_to_back = False     # END_STREAM pending/seen from client
        self.end_to_front = False
        self.front_closed = False    # fully relayed toward front
        self.back_closed = False
        self.got_response = False
        self.trailers = None         # client trailers waiting behind to_back
        self.front_trailers = None   # backend trailers waiting behind to_front


class H2Session(ProtoSession):
    def __init__(self, engine: ProcessorEngine, client_addr,
                 first_data: bytes = b""):
        self.engine = engine
        self.front = _Side(server=True, send=engine.send_front)
        self.backs: dict[int, _Side] = {}
        self.by_key: dict = {}  # connector key -> conn_id
        self.streams: dict[int, _Stream] = {}  # by front sid
        self.bstreams: dict[tuple[int, int], _Stream] = {}
        self.dead = False
        # our server settings toward the client
        engine.send_front(frame(SETTINGS, 0, 0, settings_payload([
            (S_MAX_CONCURRENT, 1024), (S_INITIAL_WINDOW, DEFAULT_WINDOW),
        ])))
        if first_data:
            self.on_front_data(first_data)

    # ------------------------------------------------------------ frontend

    def on_front_data(self, data: bytes) -> None:
        if self.dead:
            return
        try:
            for ftype, flags, sid, payload in self.front.feed(data):
                self._front_frame(ftype, flags, sid, payload)
        except H2Error as e:
            self._conn_error(e)

    def _conn_error(self, e: H2Error) -> None:
        if self.dead:
            return
        self.dead = True
        last = max(self.streams, default=0)
        try:
            self.engine.send_front(
                frame(GOAWAY, 0, 0, struct.pack(">II", last, e.code)))
        except Exception:
            pass
        self.engine.close()

    def _front_frame(self, ftype: int, flags: int, sid: int,
                     payload: bytes) -> None:
        fr = self.front
        if fr.hdr_sid is not None and ftype != CONTINUATION:
            raise H2Error("expected CONTINUATION")
        if ftype == SETTINGS:
            if sid:
                raise H2Error("SETTINGS on stream")
            if not flags & F_ACK:
                fr.apply_settings(payload)
            return
        if ftype == PING:
            if not flags & F_ACK:
                fr.send(frame(PING, F_ACK, 0, payload))
            return
        if ftype == WINDOW_UPDATE:
            inc = int.from_bytes(payload, "big") & 0x7FFFFFFF
            if inc == 0:
                raise H2Error("zero WINDOW_UPDATE")
            if sid == 0:
                fr.conn_window += inc
                for st in list(self.streams.values()):
                    self._pump_front(st)
            elif sid in self.streams:
                fr.stream_window[sid] = fr.stream_window.get(sid, 0) + inc
                self._pump_front(self.streams[sid])
            return
        if ftype == PRIORITY:
            return
        if ftype == GOAWAY:
            # client is going away; finish nothing new, drop the session
            self.engine.close()
            return
        if ftype == HEADERS:
            block = strip_padding(flags, payload, has_priority=True)
            if flags & F_END_HEADERS:
                self._front_headers(sid, flags, bytes(block))
            else:
                fr.hdr_sid, fr.hdr_flags = sid, flags
                fr.hdr_buf = bytearray(block)
            return
        if ftype == CONTINUATION:
            if fr.hdr_sid != sid:
                raise H2Error("CONTINUATION on wrong stream")
            fr.hdr_buf += payload
            if flags & F_END_HEADERS:
                hsid, hflags = fr.hdr_sid, fr.hdr_flags
                fr.hdr_sid = None
                self._front_headers(hsid, hflags, bytes(fr.hdr_buf))
            return
        if ftype == DATA:
            st = self.streams.get(sid)
            body = strip_padding(flags, payload, has_priority=False)
            if st is None or st.back_closed:
                # stream already reset/unknown: still return conn window
                fr.send(frame(WINDOW_UPDATE, 0, 0,
                              struct.pack(">I", max(len(payload), 1))))
                return
            fr.grant(sid, len(payload))
            st.to_back += body
            if len(st.to_back) > MAX_PEND:
                self._reset_both(st, ERR_FLOW)
                return
            if flags & F_END_STREAM:
                st.end_to_back = True
            self._pump_back(st)
            return
        if ftype == RST_STREAM:
            st = self.streams.pop(sid, None)
            if st is not None:
                self.bstreams.pop((st.conn_id, st.bsid), None)
                back = self.backs.get(st.conn_id)
                if back is not None and not st.back_closed:
                    back.send(frame(RST_STREAM, 0, st.bsid, payload[:4]))
            return
        if ftype == PUSH_PROMISE:
            raise H2Error("PUSH_PROMISE from client")
        # unknown frame types are ignored per RFC 7540 §4.1

    def _front_headers(self, sid: int, flags: int, block: bytes) -> None:
        headers = self._decode(self.front, block)
        end = bool(flags & F_END_STREAM)
        st = self.streams.get(sid)
        if st is not None:
            # trailers toward the backend
            back = self.backs.get(st.conn_id)
            if back is not None and not st.back_closed:
                st.end_to_back = True
                if st.to_back:
                    # flush pending data first; trailers follow when drained
                    st.trailers = headers  # type: ignore[attr-defined]
                    self._pump_back(st)
                else:
                    back.send_headers(st.bsid, headers, end_stream=True)
            return
        # new request stream
        authority = path = None
        for k, v in headers:
            if k == b":authority":
                authority = v.decode("latin-1")
            elif k == b":path":
                path = v.decode("latin-1")
            elif k == b"host" and authority is None:
                authority = v.decode("latin-1")
        hint = None
        if authority is not None and path is not None:
            hint = Hint.of_host_uri(authority, path)
        elif authority is not None:
            hint = Hint.of_host(authority)
        try:
            sel = self.engine.select(hint)
        except OSError:
            self.front.send(
                frame(RST_STREAM, 0, sid, struct.pack(">I", ERR_REFUSED)))
            return
        conn_id = self.by_key.get(sel.key, -1)
        back = self.backs.get(conn_id)
        if back is None or back.goaway:
            conn_id = self.engine.open(sel)
            back = _Side(server=False, send=lambda b, c=conn_id:
                         self.engine.send_back(c, b), sid_start=1)
            back.send(PREFACE + frame(SETTINGS, 0, 0, settings_payload([
                (S_ENABLE_PUSH, 0), (S_MAX_CONCURRENT, 1024),
            ])))
            self.backs[conn_id] = back
            self.by_key[sel.key] = conn_id
        bsid = back.alloc_sid()
        st = _Stream(sid, conn_id, bsid)
        self.streams[sid] = st
        self.bstreams[(conn_id, bsid)] = st
        self.front.stream_window.setdefault(sid, self.front.initial_window)
        back.stream_window[bsid] = back.initial_window
        back.send_headers(bsid, headers, end_stream=end)
        if end:
            st.end_to_back = True

    # ------------------------------------------------------------ backend

    def on_back_connected(self, conn_id: int) -> None: ...

    def on_back_data(self, conn_id: int, data: bytes) -> None:
        if self.dead:
            return
        back = self.backs.get(conn_id)
        if back is None:
            return
        try:
            for ftype, flags, sid, payload in back.feed(data):
                self._back_frame(back, conn_id, ftype, flags, sid, payload)
        except H2Error as e:
            self._back_dead(conn_id, e.code)

    def _back_frame(self, back: _Side, conn_id: int, ftype: int, flags: int,
                    sid: int, payload: bytes) -> None:
        if back.hdr_sid is not None and ftype != CONTINUATION:
            raise H2Error("expected CONTINUATION")
        if ftype == SETTINGS:
            if not flags & F_ACK:
                back.apply_settings(payload)
                for st in list(self.bstreams.values()):
                    if st.conn_id == conn_id:
                        self._pump_back(st)
            return
        if ftype == PING:
            if not flags & F_ACK:
                back.send(frame(PING, F_ACK, 0, payload))
            return
        if ftype == WINDOW_UPDATE:
            inc = int.from_bytes(payload, "big") & 0x7FFFFFFF
            if sid == 0:
                back.conn_window += inc
                for st in list(self.bstreams.values()):
                    if st.conn_id == conn_id:
                        self._pump_back(st)
            else:
                st = self.bstreams.get((conn_id, sid))
                if st is not None:
                    back.stream_window[sid] = back.stream_window.get(sid, 0) + inc
                    self._pump_back(st)
            return
        if ftype in (PRIORITY,):
            return
        if ftype == GOAWAY:
            self._back_dead(conn_id, ERR_NO)
            return
        if ftype == PUSH_PROMISE:
            # we sent ENABLE_PUSH=0
            raise H2Error("unexpected PUSH_PROMISE")
        if ftype == HEADERS:
            block = strip_padding(flags, payload, has_priority=True)
            if flags & F_END_HEADERS:
                self._back_headers(back, conn_id, sid, flags, bytes(block))
            else:
                back.hdr_sid, back.hdr_flags = sid, flags
                back.hdr_buf = bytearray(block)
            return
        if ftype == CONTINUATION:
            if back.hdr_sid != sid:
                raise H2Error("CONTINUATION on wrong stream")
            back.hdr_buf += payload
            if flags & F_END_HEADERS:
                hsid, hflags = back.hdr_sid, back.hdr_flags
                back.hdr_sid = None
                self._back_headers(back, conn_id, hsid, hflags,
                                   bytes(back.hdr_buf))
            return
        if ftype == DATA:
            st = self.bstreams.get((conn_id, sid))
            body = strip_padding(flags, payload, has_priority=False)
            if st is None:
                return
            back.grant(sid, len(payload))
            st.to_front += body
            if len(st.to_front) > MAX_PEND:
                self._reset_both(st, ERR_FLOW)
                return
            if flags & F_END_STREAM:
                st.end_to_front = True
            self._pump_front(st)
            return
        if ftype == RST_STREAM:
            st = self.bstreams.pop((conn_id, sid), None)
            if st is not None:
                self.streams.pop(st.fsid, None)
                self.front.send(frame(RST_STREAM, 0, st.fsid, payload[:4]))
            return

    def _back_headers(self, back: _Side, conn_id: int, sid: int, flags: int,
                      block: bytes) -> None:
        headers = self._decode(back, block)
        st = self.bstreams.get((conn_id, sid))
        if st is None:
            return
        end = bool(flags & F_END_STREAM)
        if st.got_response and st.to_front:
            # trailers behind pending data
            st.front_trailers = headers  # type: ignore[attr-defined]
            st.end_to_front = True
            self._pump_front(st)
            return
        st.got_response = True
        self.front.send_headers(st.fsid, headers, end_stream=end)
        if end:
            st.end_to_front = True
            st.front_closed = True
            self._maybe_done(st)

    # ------------------------------------------------------------ pumps

    def _decode(self, side: _Side, block: bytes) -> list[tuple[bytes, bytes]]:
        try:
            return side.dec.decode(block)
        except hpack.HpackError as e:
            raise H2Error(f"hpack: {e}", ERR_INTERNAL)

    def _pump_back(self, st: _Stream) -> None:
        back = self.backs.get(st.conn_id)
        if back is None or st.back_closed:
            return
        while st.to_back:
            w = min(back.window_for(st.bsid), back.peer_max_frame)
            if w <= 0:
                return
            chunk = bytes(st.to_back[:w])
            del st.to_back[:len(chunk)]
            last = st.end_to_back and not st.to_back and st.trailers is None
            back.send_data(st.bsid, chunk, end_stream=last)
        if st.end_to_back and not st.to_back and st.trailers is not None:
            tr, st.trailers = st.trailers, None
            back.send_headers(st.bsid, tr, end_stream=True)

    def _pump_front(self, st: _Stream) -> None:
        fr = self.front
        while st.to_front:
            w = min(fr.window_for(st.fsid), fr.peer_max_frame)
            if w <= 0:
                return
            chunk = bytes(st.to_front[:w])
            del st.to_front[:len(chunk)]
            last = st.end_to_front and not st.to_front and \
                st.front_trailers is None
            fr.send_data(st.fsid, chunk, end_stream=last)
            if last:
                st.front_closed = True
        if st.end_to_front and not st.to_front and st.front_trailers is not None:
            tr, st.front_trailers = st.front_trailers, None
            fr.send_headers(st.fsid, tr, end_stream=True)
            st.front_closed = True
        self._maybe_done(st)

    def _maybe_done(self, st: _Stream) -> None:
        if st.front_closed and st.end_to_back and not st.to_back:
            self.streams.pop(st.fsid, None)
            self.bstreams.pop((st.conn_id, st.bsid), None)
            self.front.stream_window.pop(st.fsid, None)
            back = self.backs.get(st.conn_id)
            if back is not None:
                back.stream_window.pop(st.bsid, None)

    def _reset_both(self, st: _Stream, code: int) -> None:
        self.streams.pop(st.fsid, None)
        self.bstreams.pop((st.conn_id, st.bsid), None)
        self.front.send(frame(RST_STREAM, 0, st.fsid, struct.pack(">I", code)))
        back = self.backs.get(st.conn_id)
        if back is not None:
            back.send(frame(RST_STREAM, 0, st.bsid, struct.pack(">I", code)))

    def _back_dead(self, conn_id: int, code: int) -> None:
        back = self.backs.pop(conn_id, None)
        if back is None:
            return
        back.goaway = True
        self.by_key = {k: v for k, v in self.by_key.items() if v != conn_id}
        for (cid, bsid), st in list(self.bstreams.items()):
            if cid != conn_id:
                continue
            self.bstreams.pop((cid, bsid), None)
            self.streams.pop(st.fsid, None)
            if not st.front_closed:
                self.front.send(frame(RST_STREAM, 0, st.fsid,
                                      struct.pack(">I", ERR_REFUSED)))
        self.engine.close_back(conn_id)

    # ------------------------------------------------------------ lifecycle

    def on_back_eof(self, conn_id: int) -> None:
        self._back_dead(conn_id, ERR_NO)

    def on_back_closed(self, conn_id: int, err: int) -> bool:
        self._back_dead(conn_id, ERR_INTERNAL)
        return True  # session survives a backend loss (silent disconnect)

    def on_front_eof(self) -> None:
        self.engine.close()


class H2Processor(Processor):
    name = "h2"
    alpn = ("h2",)

    def session(self, engine: ProcessorEngine, client_addr) -> H2Session:
        return H2Session(engine, client_addr)


register(H2Processor())
