"""HTTP/1.x request-head parsing for backend selection.

Round-1 scope of the reference's http1 processor
(processor/http1/HttpSubContext.java, 849-line char state machine): an
incremental head parser that extracts method/URI/Host from the first
request so the LB can build a Hint (HttpContext.java:63-69 — hint =
host [+ uri]), after which the session is spliced. Per-request
re-routing on a kept-alive connection (full processor SPI) is the next
iteration.
"""
from __future__ import annotations

from typing import Optional

from ..rules.ir import Hint

MAX_HEAD = 64 * 1024


class HeadParser:
    """Feed bytes; .done becomes True when the full head (incl. CRLFCRLF)
    has been seen or .error is set."""

    def __init__(self) -> None:
        self.buf = bytearray()
        self.done = False
        self.error: Optional[str] = None
        self.method: Optional[str] = None
        self.uri: Optional[str] = None
        self.version: Optional[str] = None
        self.headers: list[tuple[str, str]] = []

    def feed(self, data: bytes) -> None:
        if self.done or self.error:
            return
        self.buf += data
        if len(self.buf) > MAX_HEAD:
            self.error = "head too large"
            return
        end = self.buf.find(b"\r\n\r\n")
        if end < 0:
            # tolerate bare-LF heads
            end_lf = self.buf.find(b"\n\n")
            if end_lf < 0:
                return
            head = bytes(self.buf[:end_lf])
            self._parse(head, end_lf + 2)
            return
        self._parse(bytes(self.buf[:end]), end + 4)

    def _parse(self, head: bytes, head_len: int) -> None:
        lines = head.replace(b"\r\n", b"\n").split(b"\n")
        try:
            req = lines[0].decode("latin-1")
            parts = req.split()
            if len(parts) < 2:
                self.error = "bad request line"
                return
            self.method = parts[0]
            self.uri = parts[1]
            self.version = parts[2] if len(parts) > 2 else "HTTP/1.0"
        except Exception:
            self.error = "bad request line"
            return
        for ln in lines[1:]:
            if not ln:
                continue
            i = ln.find(b":")
            if i < 0:
                continue
            k = ln[:i].strip().decode("latin-1").lower()
            v = ln[i + 1:].strip().decode("latin-1")
            self.headers.append((k, v))
        self.head_len = head_len
        self.done = True

    def header(self, name: str) -> Optional[str]:
        for k, v in self.headers:
            if k == name:
                return v
        return None

    def hint(self) -> Optional[Hint]:
        if not self.done:
            return None
        host = self.header("host")
        if host is not None and self.uri is not None:
            return Hint.of_host_uri(host, self.uri)
        if host is not None:
            return Hint.of_host(host)
        if self.uri is not None:
            return Hint.of_uri(self.uri)
        return None
