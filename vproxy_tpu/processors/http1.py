"""HTTP/1.x processing: head parsing + the full per-request processor.

Two layers of parity with the reference's http1 machinery:

* `HeadParser` — incremental request-head parser used by the splice
  fast path and the controllers (scope of HttpSubContext's head states).
* `Http1Session` — the `http1` protocol processor
  (processor/http1/HttpProcessor.java + HttpSubContext.java 849-line
  state machine, hints per HttpContext.java:63-69): every request on a
  kept-alive frontend connection is routed independently — hint =
  Host[+URI] through the classify engine — with backend keep-alive
  pooling per target, body framing by content-length / chunked /
  read-to-close, and strict request/response serialization (the next
  pipelined request is not consumed until the current response ends).
"""
from __future__ import annotations

from typing import Optional

from ..rules.ir import Hint
from .base import Processor, ProcessorEngine, ProtoSession, register

MAX_HEAD = 64 * 1024


class HeadParser:
    """Feed bytes; .done becomes True when the full head (incl. CRLFCRLF)
    has been seen or .error is set."""

    def __init__(self) -> None:
        self.buf = bytearray()
        self.done = False
        self.error: Optional[str] = None
        self.method: Optional[str] = None
        self.uri: Optional[str] = None
        self.version: Optional[str] = None
        self.headers: list[tuple[str, str]] = []

    def feed(self, data: bytes) -> None:
        if self.done or self.error:
            return
        self.buf += data
        if len(self.buf) > MAX_HEAD:
            self.error = "head too large"
            return
        end = self.buf.find(b"\r\n\r\n")
        if end < 0:
            # tolerate bare-LF heads
            end_lf = self.buf.find(b"\n\n")
            if end_lf < 0:
                return
            head = bytes(self.buf[:end_lf])
            self._parse(head, end_lf + 2)
            return
        self._parse(bytes(self.buf[:end]), end + 4)

    def _parse(self, head: bytes, head_len: int) -> None:
        lines = head.replace(b"\r\n", b"\n").split(b"\n")
        try:
            req = lines[0].decode("latin-1")
            parts = req.split()
            if len(parts) < 2:
                self.error = "bad request line"
                return
            self.method = parts[0]
            self.uri = parts[1]
            self.version = parts[2] if len(parts) > 2 else "HTTP/1.0"
        except Exception:
            self.error = "bad request line"
            return
        for ln in lines[1:]:
            if not ln:
                continue
            i = ln.find(b":")
            if i < 0:
                continue
            k = ln[:i].strip().decode("latin-1").lower()
            v = ln[i + 1:].strip().decode("latin-1")
            self.headers.append((k, v))
        self.head_len = head_len
        self.done = True

    def header(self, name: str) -> Optional[str]:
        for k, v in self.headers:
            if k == name:
                return v
        return None

    def hint(self) -> Optional[Hint]:
        if not self.done:
            return None
        host = self.header("host")
        if host is not None and self.uri is not None:
            return Hint.of_host_uri(host, self.uri)
        if host is not None:
            return Hint.of_host(host)
        if self.uri is not None:
            return Hint.of_uri(self.uri)
        return None


# ---------------------------------------------------------------- processor


class _ChunkScanner:
    """Incremental chunked-body boundary scanner. feed() returns how many
    of the offered bytes belong to the current message and whether the
    message ended inside them. Bytes are relayed verbatim elsewhere."""

    SIZE, DATA, DATA_CRLF, TRAILER = range(4)

    def __init__(self) -> None:
        self.state = self.SIZE
        self.line = bytearray()
        self.left = 0
        self.error: Optional[str] = None

    def feed(self, data: bytes) -> tuple[int, bool]:
        pos = 0
        n = len(data)
        while pos < n:
            if self.state == self.SIZE:
                nl = data.find(b"\n", pos)
                if nl < 0:
                    self.line += data[pos:]
                    if len(self.line) > 1024:
                        self.error = "chunk size line too long"
                        return n, True
                    return n, False
                self.line += data[pos:nl]
                pos = nl + 1
                try:
                    size = int(bytes(self.line).split(b";")[0].strip() or b"0", 16)
                except ValueError:
                    self.error = "bad chunk size"
                    return pos, True
                self.line = bytearray()
                if size == 0:
                    self.state = self.TRAILER
                else:
                    self.left = size + 2  # data + CRLF
                    self.state = self.DATA
            elif self.state == self.DATA:
                take = min(self.left, n - pos)
                self.left -= take
                pos += take
                if self.left == 0:
                    self.state = self.SIZE
            else:  # TRAILER: lines until an empty line
                nl = data.find(b"\n", pos)
                if nl < 0:
                    self.line += data[pos:]
                    return n, False
                self.line += data[pos:nl]
                blank = not bytes(self.line).strip(b"\r")
                self.line = bytearray()
                pos = nl + 1
                if blank:
                    return pos, True
        return pos, False


class _MsgFramer:
    """Framing for one HTTP/1 message body after the head: mode one of
    none/len/chunked/eof."""

    def __init__(self, mode: str, length: int = 0):
        self.mode = mode
        self.left = length
        self.chunks = _ChunkScanner() if mode == "chunked" else None

    def feed(self, data: bytes) -> tuple[int, bool]:
        if self.mode == "none":
            return 0, True
        if self.mode == "len":
            take = min(self.left, len(data))
            self.left -= take
            return take, self.left == 0
        if self.mode == "chunked":
            return self.chunks.feed(data)
        return len(data), False  # eof: ends only when the peer closes


def _req_framer(parser: HeadParser) -> _MsgFramer:
    te = (parser.header("transfer-encoding") or "").lower()
    if "chunked" in te:
        return _MsgFramer("chunked")
    cl = parser.header("content-length")
    if cl is not None and int(cl) > 0:
        return _MsgFramer("len", int(cl))
    return _MsgFramer("none")


class _RespHead:
    """Incremental response-head parser (status line + headers)."""

    def __init__(self) -> None:
        self.buf = bytearray()
        self.done = False
        self.error: Optional[str] = None
        self.status = 0
        self.headers: list[tuple[str, str]] = []
        self.head_len = 0

    def feed(self, data: bytes) -> None:
        if self.done or self.error:
            return
        self.buf += data
        if len(self.buf) > MAX_HEAD:
            self.error = "head too large"
            return
        end = self.buf.find(b"\r\n\r\n")
        ln = 4
        if end < 0:
            end = self.buf.find(b"\n\n")
            ln = 2
            if end < 0:
                return
        head = bytes(self.buf[:end])
        self.head_len = end + ln
        lines = head.replace(b"\r\n", b"\n").split(b"\n")
        parts = lines[0].decode("latin-1").split()
        if len(parts) < 2 or not parts[1][:3].isdigit():
            self.error = "bad status line"
            return
        self.status = int(parts[1][:3])
        for line in lines[1:]:
            i = line.find(b":")
            if i > 0:
                self.headers.append((line[:i].strip().decode("latin-1").lower(),
                                     line[i + 1:].strip().decode("latin-1")))
        self.done = True

    def header(self, name: str) -> Optional[str]:
        for k, v in self.headers:
            if k == name:
                return v
        return None


class Http1Session(ProtoSession):
    # frontend states
    REQ_HEAD, REQ_BODY, WAIT_RESP, TUNNEL = range(4)

    def __init__(self, engine: ProcessorEngine, client_addr,
                 first_data: bytes = b""):
        self.engine = engine
        self.fbuf = bytearray()
        self.state = self.REQ_HEAD
        self.parser = HeadParser()
        self.req_framer: Optional[_MsgFramer] = None
        self.req_method = ""
        self.req_close = False
        self.cur_back: Optional[int] = None  # conn_id serving current request
        self.cur_key = None
        self.idle: dict = {}  # connector key -> conn_id (kept-alive backends)
        self.resp: Optional[_RespHead] = None
        self.resp_framer: Optional[_MsgFramer] = None
        self.resp_done_pending_close = False
        if first_data:
            self.on_front_data(first_data)

    # ------------------------------------------------------------ frontend

    def on_front_data(self, data: bytes) -> None:
        self.fbuf += data
        self._drive_front()

    def _drive_front(self) -> None:
        while self.fbuf:
            if self.state == self.TUNNEL:
                if self.cur_back is not None:
                    self.engine.send_back(self.cur_back, bytes(self.fbuf))
                self.fbuf.clear()
                return
            if self.state == self.WAIT_RESP:
                return  # strict serialization: hold pipelined requests
            if self.state == self.REQ_HEAD:
                self.parser.feed(bytes(self.fbuf))
                if self.parser.error:
                    self.engine.send_front(
                        b"HTTP/1.1 400 Bad Request\r\ncontent-length: 0\r\n"
                        b"connection: close\r\n\r\n")
                    self.engine.close()
                    return
                if not self.parser.done:
                    self.fbuf.clear()  # parser buffered everything
                    return
                # parser consumed the whole fbuf into parser.buf; bytes past
                # the head belong to the body / next request
                head_raw = bytes(self.parser.buf[:self.parser.head_len])
                leftover = bytes(self.parser.buf[self.parser.head_len:])
                self.fbuf = bytearray(leftover)
                if not self._begin_request(head_raw):
                    return
                continue
            if self.state == self.REQ_BODY:
                used, done = self.req_framer.feed(bytes(self.fbuf))
                if self.req_framer.chunks is not None and \
                        self.req_framer.chunks.error:
                    self.engine.close()
                    return
                if used and self.cur_back is not None:
                    self.engine.send_back(self.cur_back, bytes(self.fbuf[:used]))
                del self.fbuf[:used]
                if done:
                    self.state = self.WAIT_RESP
                else:
                    return

    def _begin_request(self, head_raw: bytes) -> bool:
        p = self.parser
        self.req_method = (p.method or "").upper()
        conn_hdr = (p.header("connection") or "").lower()
        self.req_close = "close" in conn_hdr or (
            p.version == "HTTP/1.0" and "keep-alive" not in conn_hdr)
        try:
            sel = self.engine.select(p.hint())
        except OSError:
            self.engine.send_front(
                b"HTTP/1.1 503 Service Unavailable\r\ncontent-length: 0\r\n"
                b"connection: close\r\n\r\n")
            self.engine.close()
            return False
        conn_id = self.idle.pop(sel.key, None)
        if conn_id is None:
            try:
                conn_id = self.engine.open(sel)
            except OSError:
                self.engine.send_front(
                    b"HTTP/1.1 503 Service Unavailable\r\ncontent-length: 0\r\n"
                    b"connection: close\r\n\r\n")
                self.engine.close()
                return False
        self.cur_back = conn_id
        self.cur_key = sel.key
        self.engine.send_back(conn_id, head_raw)
        self.resp = _RespHead()
        self.resp_framer = None
        self.req_framer = _req_framer(p)
        self.parser = HeadParser()
        if self.req_framer.mode == "none":
            self.state = self.WAIT_RESP
        else:
            self.state = self.REQ_BODY
        return True

    def on_front_eof(self) -> None:
        if self.state == self.TUNNEL and self.cur_back is not None:
            # half-close toward the backend is not modeled; tear down
            self.engine.close()
            return
        self.engine.close()

    # ------------------------------------------------------------ backend

    def on_back_data(self, conn_id: int, data: bytes) -> None:
        if conn_id != self.cur_back:
            # data on an idle pooled connection is a protocol violation;
            # drop the connection (reference closes idle conns that talk)
            self._drop_idle(conn_id)
            return
        if self.state == self.TUNNEL:
            self.engine.send_front(data)
            return
        self._drive_back(data)

    def _drive_back(self, data: bytes) -> None:
        while data:
            if self.resp_framer is None:
                self.resp.feed(data)
                if self.resp.error:
                    self.engine.close()
                    return
                if not self.resp.done:
                    return
                head_raw = bytes(self.resp.buf[:self.resp.head_len])
                data = bytes(self.resp.buf[self.resp.head_len:])
                self.engine.send_front(head_raw)
                st = self.resp.status
                if st == 101:
                    # protocol upgrade (websocket): raw tunnel from here on
                    self.state = self.TUNNEL
                    if data:
                        self.engine.send_front(data)
                    return
                if 100 <= st < 200:
                    self.resp = _RespHead()  # interim; real response follows
                    continue
                self.resp_framer = self._resp_framer(st)
                continue
            used, done = self.resp_framer.feed(data)
            if self.resp_framer.chunks is not None and self.resp_framer.chunks.error:
                self.engine.close()
                return
            if self.resp_framer.mode == "eof":
                self.engine.send_front(data)
                return
            if used:
                self.engine.send_front(data[:used])
            data = data[used:]
            if done:
                self._response_complete()
                if data:
                    # backend pipelined beyond the response: protocol error
                    self.engine.close()
                return

    def _resp_framer(self, status: int) -> _MsgFramer:
        if self.req_method == "HEAD" or status in (204, 304):
            return _MsgFramer("none")
        te = (self.resp.header("transfer-encoding") or "").lower()
        if "chunked" in te:
            return _MsgFramer("chunked")
        cl = self.resp.header("content-length")
        if cl is not None:
            n = int(cl)
            return _MsgFramer("len", n) if n > 0 else _MsgFramer("none")
        return _MsgFramer("eof")

    def _response_complete(self) -> None:
        back_close = "close" in (self.resp.header("connection") or "").lower()
        conn_id, key = self.cur_back, self.cur_key
        self.cur_back = self.cur_key = None
        self.resp = None
        self.resp_framer = None
        if back_close:
            self.engine.close_back(conn_id)
        elif key is not None:
            old = self.idle.get(key)
            if old is not None and old != conn_id:
                self.engine.close_back(old)
            self.idle[key] = conn_id
        if self.req_close:
            self.engine.close()
            return
        if self.state != self.REQ_BODY:  # normal case: request already done
            self.state = self.REQ_HEAD
            self._drive_front()

    def on_back_eof(self, conn_id: int) -> None:
        if conn_id == self.cur_back and self.resp_framer is not None and \
                self.resp_framer.mode == "eof":
            # close-delimited response ends at backend EOF: propagate
            self.engine.close()
            return
        if conn_id == self.cur_back:
            self.engine.close()
            return
        self._drop_idle(conn_id)

    def on_back_closed(self, conn_id: int, err: int) -> bool:
        if conn_id == self.cur_back or self.state == self.TUNNEL:
            return False  # mid-exchange loss kills the session
        self._drop_idle(conn_id)
        return True

    def _drop_idle(self, conn_id: int) -> None:
        for k, v in list(self.idle.items()):
            if v == conn_id:
                del self.idle[k]
        self.engine.close_back(conn_id)


class Http1Processor(Processor):
    name = "http1"
    alpn = ("http/1.1",)

    def session(self, engine: ProcessorEngine, client_addr) -> Http1Session:
        return Http1Session(engine, client_addr)


class GeneralHttpProcessor(Processor):
    """`http`: sniff h2 connection preface vs HTTP/1 (the reference's
    general-http processor registered by DefaultProcessorRegistry)."""

    name = "http"
    alpn = ("h2", "http/1.1")

    def session(self, engine: ProcessorEngine, client_addr) -> "_SniffSession":
        return _SniffSession(engine, client_addr)


class _SniffSession(ProtoSession):
    def __init__(self, engine: ProcessorEngine, client_addr):
        self.engine = engine
        self.client_addr = client_addr
        self.buf = bytearray()
        self.inner: Optional[ProtoSession] = None

    def on_front_data(self, data: bytes) -> None:
        if self.inner is not None:
            self.inner.on_front_data(data)
            return
        self.buf += data
        from .h2 import PREFACE, H2Session
        if len(self.buf) >= len(PREFACE):
            first = bytes(self.buf)
            self.buf.clear()
            if first.startswith(PREFACE):
                self.inner = H2Session(self.engine, self.client_addr, first)
            else:
                self.inner = Http1Session(self.engine, self.client_addr, first)
        elif not PREFACE.startswith(bytes(self.buf)):
            first = bytes(self.buf)
            self.buf.clear()
            self.inner = Http1Session(self.engine, self.client_addr, first)

    # backend/lifecycle events delegate to the resolved session

    def on_front_eof(self) -> None:
        if self.inner is not None:
            self.inner.on_front_eof()
        else:
            self.engine.close()

    def on_back_connected(self, conn_id: int) -> None:
        if self.inner is not None:
            self.inner.on_back_connected(conn_id)

    def on_back_data(self, conn_id: int, data: bytes) -> None:
        if self.inner is not None:
            self.inner.on_back_data(conn_id, data)

    def on_back_eof(self, conn_id: int) -> None:
        if self.inner is not None:
            self.inner.on_back_eof(conn_id)

    def on_back_closed(self, conn_id: int, err: int) -> bool:
        if self.inner is not None:
            return self.inner.on_back_closed(conn_id, err)
        return False

    def on_front_drained(self) -> None:
        if self.inner is not None:
            self.inner.on_front_drained()

    def on_back_drained(self, conn_id: int) -> None:
        if self.inner is not None:
            self.inner.on_back_drained(conn_id)


register(Http1Processor())
register(GeneralHttpProcessor())
