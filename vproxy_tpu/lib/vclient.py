"""Async HTTP/1 client + SOCKS5 client.

Parity: lib vclient (HttpClient.java:131 callback-style request API,
impl/Http1ClientConn.java:257, impl/SocksClientImpl.java:127) and the
base socks client handshake (socks/Socks5ClientHandshake.java:232).
Callbacks fire on the event loop thread.
"""
from __future__ import annotations

import struct
from typing import Callable, Optional

from ..net.connection import Connection, Handler
from ..net.eventloop import SelectorEventLoop
from ..processors.http1 import _MsgFramer, _RespHead
from ..utils.ip import is_ip_literal


class HttpResponse:
    def __init__(self, status: int, headers: list[tuple[str, str]],
                 body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def header(self, name: str) -> Optional[str]:
        for k, v in self.headers:
            if k == name.lower():
                return v
        return None


class HttpClient:
    """One-shot request API; `conn` may be supplied to reuse a kept-alive
    connection (and is handed back in the callback for transfer/reuse)."""

    def __init__(self, loop: SelectorEventLoop):
        self.loop = loop

    def request(self, method: str, host: str, port: int, uri: str,
                cb: Callable[[Optional[Exception], Optional[HttpResponse],
                              Optional[Connection]], None],
                headers: Optional[list[tuple[str, str]]] = None,
                body: bytes = b"",
                conn: Optional[Connection] = None) -> None:
        def run() -> None:
            try:
                c = conn or Connection.connect(self.loop, host, port)
            except OSError as e:
                cb(e, None, None)
                return
            _HttpReq(self, c, method, host, port, uri, headers or [], body, cb)
        self.loop.run_on_loop(run)

    def get(self, host: str, port: int, uri: str, cb, **kw) -> None:
        self.request("GET", host, port, uri, cb, **kw)

    def post(self, host: str, port: int, uri: str, body: bytes, cb, **kw) -> None:
        self.request("POST", host, port, uri, cb, body=body, **kw)


class _HttpReq(Handler):
    def __init__(self, client: HttpClient, conn: Connection, method: str,
                 host: str, port: int, uri: str, headers, body: bytes, cb):
        self.cb = cb
        self.conn = conn
        self.method = method
        self.resp = _RespHead()
        self.framer: Optional[_MsgFramer] = None
        self.body = bytearray()
        self.done = False
        conn.set_handler(self)
        names = {k.lower() for k, _ in headers}
        head = f"{method} {uri} HTTP/1.1\r\n"
        if "host" not in names:
            head += f"host: {host}:{port}\r\n"
        for k, v in headers:
            head += f"{k}: {v}\r\n"
        if body and "content-length" not in names:
            head += f"content-length: {len(body)}\r\n"
        head += "\r\n"
        conn.write(head.encode() + body)

    def on_data(self, conn: Connection, data: bytes) -> None:
        while data and not self.done:
            if self.framer is None:
                self.resp.feed(data)
                if self.resp.error:
                    self._fail(OSError(self.resp.error))
                    return
                if not self.resp.done:
                    return
                data = bytes(self.resp.buf[self.resp.head_len:])
                st = self.resp.status
                if 100 <= st < 200 and st != 101:
                    self.resp = _RespHead()
                    continue
                self.framer = self._mk_framer(st)
                continue
            used, done = self.framer.feed(data)
            if self.framer.mode == "eof":
                self.body += data
                return
            self.body += data[:used]
            data = data[used:]
            if done:
                self._finish()
                return

    def _mk_framer(self, status: int) -> _MsgFramer:
        if self.method == "HEAD" or status in (204, 304):
            return _MsgFramer("none")
        te = (self.resp.header("transfer-encoding") or "").lower()
        if "chunked" in te:
            return _MsgFramer("chunked")
        cl = self.resp.header("content-length")
        if cl is not None:
            n = int(cl)
            return _MsgFramer("len", n) if n > 0 else _MsgFramer("none")
        return _MsgFramer("eof")

    def _finish(self) -> None:
        if self.done:
            return
        self.done = True
        body = bytes(self.body)
        if self.framer is not None and self.framer.mode == "chunked":
            body = _dechunk(body)
        self.conn.set_handler(Handler())
        self.cb(None, HttpResponse(self.resp.status, self.resp.headers, body),
                self.conn)

    def _fail(self, e: Exception) -> None:
        if self.done:
            return
        self.done = True
        self.conn.close()
        self.cb(e, None, None)

    def on_eof(self, conn: Connection) -> None:
        if self.framer is not None and self.framer.mode == "eof":
            self._finish()
            self.conn.close()
        else:
            self._fail(OSError("connection closed before response end"))

    def on_closed(self, conn: Connection, err: int) -> None:
        self._fail(OSError(f"connection closed ({err})"))


def _dechunk(data: bytes) -> bytes:
    out = b""
    while data:
        ln, _, data = data.partition(b"\r\n")
        n = int(ln.split(b";")[0] or b"0", 16)
        if n == 0:
            break
        out += data[:n]
        data = data[n + 2:]
    return out


# ----------------------------------------------------------------- socks5

class SocksClient:
    """CONNECT through a SOCKS5 server; yields a transferable ConnRef to
    the target (Socks5ClientHandshake.java). A ConnRef (lib/transfer.py)
    rather than a bare Connection: bytes the target sends immediately
    after the handshake are buffered and replayed into whatever handler
    the consumer transfers the connection to."""

    def __init__(self, loop: SelectorEventLoop, socks_host: str,
                 socks_port: int):
        self.loop = loop
        self.socks = (socks_host, socks_port)

    def connect(self, target_host: str, target_port: int,
                cb: Callable[[Optional[Exception], Optional["ConnRef"]], None]
                ) -> None:
        def run() -> None:
            try:
                c = Connection.connect(self.loop, *self.socks)
            except OSError as e:
                cb(e, None)
                return
            _SocksHandshake(c, target_host, target_port, cb)
        self.loop.run_on_loop(run)


class _SocksHandshake(Handler):
    ST_GREET, ST_REP = range(2)

    def __init__(self, conn: Connection, host: str, port: int, cb):
        self.conn = conn
        self.host = host
        self.port = port
        self.cb = cb
        self.buf = bytearray()
        self.state = self.ST_GREET
        self.done = False
        conn.set_handler(self)
        conn.write(b"\x05\x01\x00")

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.buf += data
        if self.state == self.ST_GREET:
            if len(self.buf) < 2:
                return
            if self.buf[0] != 5 or self.buf[1] != 0:
                self._fail(OSError("socks5 auth rejected"))
                return
            del self.buf[:2]
            self.state = self.ST_REP
            if is_ip_literal(self.host):
                from ..utils.ip import parse_ip
                ip = parse_ip(self.host)
                atyp = b"\x01" if len(ip) == 4 else b"\x04"
                addr = atyp + ip
            else:
                hb = self.host.encode()
                addr = b"\x03" + bytes([len(hb)]) + hb
            conn.write(b"\x05\x01\x00" + addr + struct.pack(">H", self.port))
        if self.state == self.ST_REP:
            if len(self.buf) < 4:
                return
            rep, atyp = self.buf[1], self.buf[3]
            need = 4 + (4 if atyp == 1 else 16 if atyp == 4 else
                        1 + self.buf[4] if len(self.buf) > 4 else 999) + 2
            if len(self.buf) < need:
                return
            del self.buf[:need]
            if rep != 0:
                self._fail(OSError(f"socks5 connect failed: rep={rep}"))
                return
            self.done = True
            from .transfer import ConnRef
            ref = ConnRef(self.conn)  # installs the buffering holder
            if self.buf:  # early target bytes that rode with the reply
                ref._hold.buf += self.buf
                self.buf.clear()
            self.cb(None, ref)

    def _fail(self, e: Exception) -> None:
        if not self.done:
            self.done = True
            self.conn.close()
            self.cb(e, None)

    def on_closed(self, conn: Connection, err: int) -> None:
        self._fail(OSError(f"socks5 server closed ({err})"))

    def on_eof(self, conn: Connection) -> None:
        self._fail(OSError("socks5 server eof"))
