"""Connection handover utilities.

Parity: lib vlibbase (ConnRef/Conn transfer between components without
closing — impl/ConnImpl.java:288; ConnRefPool.java:166): an established
Connection can be detached from whatever component created it (e.g. an
HTTP client after its response completes) and handed to another
consumer, or parked in a pool of kept-alive idle connections.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Optional

from ..net.connection import Connection, Handler
from ..net.eventloop import SelectorEventLoop


class ConnRef:
    """A transferable reference to a live Connection. transfer() swaps in
    the next owner's handler atomically on the loop thread; any bytes
    that arrive in between are buffered and replayed."""

    def __init__(self, conn: Connection):
        self.conn = conn
        self._hold = _Holding(self)
        conn.set_handler(self._hold)

    def transfer(self, handler: Handler) -> Connection:
        conn = self.conn
        buffered = bytes(self._hold.buf)
        self._hold.buf.clear()
        conn.set_handler(handler)
        if buffered:
            handler.on_data(conn, buffered)
        if self._hold.eof:
            handler.on_eof(conn)
        return conn

    @property
    def closed(self) -> bool:
        return self.conn.closed

    def close(self) -> None:
        self.conn.close()


class _Holding(Handler):
    def __init__(self, ref: ConnRef):
        self.ref = ref
        self.buf = bytearray()
        self.eof = False

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.buf += data

    def on_eof(self, conn: Connection) -> None:
        self.eof = True


class ConnRefPool:
    """Pool of idle kept-alive connections (ConnRefPool.java): get() hands
    one out; idle connections that error/close or EOF drop silently;
    capacity-bounded."""

    def __init__(self, loop: SelectorEventLoop, capacity: int = 16):
        self.loop = loop
        self.capacity = capacity
        self._q: deque[ConnRef] = deque()

    def put(self, conn: Connection) -> bool:
        if conn.closed or conn.detached or len(self._q) >= self.capacity:
            return False
        ref = ConnRef(conn)
        watch = _IdleWatch(self, ref)
        conn.set_handler(watch)
        ref._hold = watch
        self._q.append(ref)
        return True

    def get(self) -> Optional[Connection]:
        while self._q:
            ref = self._q.popleft()
            if ref.closed or ref._hold.eof:
                ref.close()
                continue
            return ref.transfer(Handler())
        return None

    def count(self) -> int:
        return len(self._q)

    def close(self) -> None:
        while self._q:
            self._q.popleft().close()


class _IdleWatch(_Holding):
    def __init__(self, pool: ConnRefPool, ref: ConnRef):
        super().__init__(ref)
        self.pool = pool

    def on_data(self, conn: Connection, data: bytes) -> None:
        # a pooled idle conn that talks is broken: drop it
        self._drop(conn)

    def on_eof(self, conn: Connection) -> None:
        self.eof = True
        self._drop(conn)

    def on_closed(self, conn: Connection, err: int) -> None:
        try:
            self.pool._q.remove(self.ref)
        except ValueError:
            pass

    def _drop(self, conn: Connection) -> None:
        try:
            self.pool._q.remove(self.ref)
        except ValueError:
            pass
        conn.close()
