"""Embeddable async HTTP/1.x server with express-style routing.

Parity: lib vserver (HttpServer.java:5 get/pst/put/del routing with
`:param` sub-paths route/*, Http1ServerImpl.java:460): handlers are
callbacks on the event loop; routes match by segments with `:name`
captures and `*` wildcards; the first matching route wins; keep-alive
connections serve sequential requests.
"""
from __future__ import annotations

import json
from typing import Callable, Optional
from urllib.parse import parse_qs, unquote

from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..processors.http1 import HeadParser

# inbound body cap: requests to the control surface / embedded servers
# must not balloon memory on a huge (or garbage) content-length
MAX_BODY = 16 * 1024 * 1024

REASONS = {200: "OK", 201: "Created", 204: "No Content", 301: "Moved Permanently",
           302: "Found", 400: "Bad Request", 401: "Unauthorized",
           403: "Forbidden", 404: "Not Found", 405: "Method Not Allowed",
           500: "Internal Server Error", 503: "Service Unavailable"}


class Request:
    def __init__(self, parser: HeadParser, body: bytes, params: dict,
                 query: dict):
        self.method = parser.method
        self.uri = parser.uri
        self.headers = parser.headers
        self.body = body
        self.params = params  # :name captures
        self.query = query

    def header(self, name: str) -> Optional[str]:
        for k, v in self.headers:
            if k == name.lower():
                return v
        return None

    def json(self):
        return json.loads(self.body or b"{}")


class Response:
    def __init__(self, rctx: "RoutingContext"):
        self._rctx = rctx
        self.status_code = 200
        self.headers: list[tuple[str, str]] = []

    def status(self, code: int) -> "Response":
        self.status_code = code
        return self

    def header(self, k: str, v: str) -> "Response":
        self.headers.append((k, v))
        return self

    def end(self, body=b"") -> None:
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
            if not any(k.lower() == "content-type" for k, _ in self.headers):
                self.headers.append(("content-type", "application/json"))
        elif isinstance(body, str):
            body = body.encode()
        self._rctx._finish(self.status_code, self.headers, body)


class RoutingContext:
    def __init__(self, server: "HttpServer", conn: Connection, req: Request,
                 close_after: bool = False):
        self.server = server
        self.conn = conn
        self.req = req
        self.resp = Response(self)
        self._done = False
        # `connection: close` requests tear down here, after the response
        # is actually written — handlers may finish asynchronously
        self._close_after = close_after

    def _finish(self, status: int, headers: list, body: bytes) -> None:
        if self._done:
            return
        self._done = True
        reason = REASONS.get(status, "OK")
        head = f"HTTP/1.1 {status} {reason}\r\n"
        names = {k.lower() for k, _ in headers}
        if "content-length" not in names:
            headers = list(headers) + [("content-length", str(len(body)))]
        for k, v in headers:
            head += f"{k}: {v}\r\n"
        head += "\r\n"
        self.conn.write(head.encode() + body)
        self.server._request_done(self.conn)
        if self._close_after:
            self.conn.close_graceful()


def _match(route: str, path: str) -> Optional[dict]:
    """`/a/:id/b` style matching; `*` matches the rest."""
    rsegs = [s for s in route.split("/") if s]
    psegs = [s for s in path.split("/") if s]
    params: dict = {}
    i = 0
    for i, rs in enumerate(rsegs):
        if rs == "*":
            params["*"] = "/".join(psegs[i:])
            return params
        if i >= len(psegs):
            return None
        if rs.startswith(":"):
            params[rs[1:]] = unquote(psegs[i])
        elif rs != psegs[i]:
            return None
    if len(psegs) != len(rsegs):
        return None
    return params


class HttpServer:
    def __init__(self, loop: SelectorEventLoop):
        self.loop = loop
        self.routes: list[tuple[str, str, Callable]] = []  # (method, path, fn)
        self._srv: Optional[ServerSock] = None
        self._conns: set[Connection] = set()
        self.port = 0

    # ----------------------------------------------------------- routing

    def route(self, method: str, path: str, fn: Callable) -> "HttpServer":
        self.routes.append((method.upper(), path, fn))
        return self

    def get(self, path: str, fn) -> "HttpServer":
        return self.route("GET", path, fn)

    def post(self, path: str, fn) -> "HttpServer":
        return self.route("POST", path, fn)

    def put(self, path: str, fn) -> "HttpServer":
        return self.route("PUT", path, fn)

    def delete(self, path: str, fn) -> "HttpServer":
        return self.route("DELETE", path, fn)

    def all(self, path: str, fn) -> "HttpServer":
        return self.route("*", path, fn)

    # ---------------------------------------------------------- lifecycle

    def listen(self, port: int, ip: str = "127.0.0.1") -> "HttpServer":
        def mk() -> None:
            self._srv = ServerSock(self.loop, ip, port, self._accept)
            self.port = self._srv.port
        self.loop.call_sync(mk)
        return self

    def listen_unix(self, path: str) -> "HttpServer":
        """Serve over a unix-domain socket (used by the docker
        libnetwork plugin — DockerNetworkPluginController.java:56)."""
        def mk() -> None:
            self._srv = ServerSock.unix(self.loop, path, self._accept)
        self.loop.call_sync(mk)
        return self

    def close(self, sync: bool = False) -> None:
        """sync=True blocks until the listener is closed (and a unix
        socket path unlinked) — callers reporting completion to an
        operator need the fd gone, not merely scheduled to go."""
        if self._srv is not None:
            srv, self._srv = self._srv, None

            def shut() -> None:
                srv.close()
                for c in list(self._conns):
                    c.close_graceful()
                self._conns.clear()
            if sync:
                self.loop.call_sync(shut)
            else:
                self.loop.run_on_loop(shut)

    # ---------------------------------------------------------- internals

    def _accept(self, fd: int, ip: str, port: int) -> None:
        conn = Connection(self.loop, fd, (ip, port))
        self._conns.add(conn)
        _HttpSrvConn(self, conn)

    def _request_done(self, conn: Connection) -> None: ...

    def _dispatch(self, conn: Connection, parser: HeadParser,
                  body: bytes, close_after: bool = False) -> None:
        path, _, qs = (parser.uri or "/").partition("?")
        query = {k: v[-1] for k, v in parse_qs(qs).items()}
        for method, route, fn in self.routes:
            if method != "*" and method != parser.method:
                continue
            params = _match(route, path)
            if params is None:
                continue
            rctx = RoutingContext(self, conn, Request(parser, body, params,
                                                      query), close_after)
            try:
                fn(rctx)
            except Exception as e:  # handler error -> 500
                if not rctx._done:
                    rctx.resp.status(500).end({"error": f"{type(e).__name__}: {e}"})
            return
        rctx = RoutingContext(self, conn, Request(parser, body, {}, query),
                              close_after)
        rctx.resp.status(404).end({"error": f"Cannot {parser.method} {path}"})


class _HttpSrvConn(Handler):
    def __init__(self, server: HttpServer, conn: Connection):
        self.server = server
        self.conn = conn
        self.parser = HeadParser()
        self.buf = bytearray()
        conn.set_handler(self)

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.buf += data
        self._drive()

    def _drive(self) -> None:
        while True:
            if not self.parser.done:
                if not self.buf:
                    return
                self.parser.feed(bytes(self.buf))
                self.buf.clear()
                if self.parser.error:
                    self.conn.write(
                        b"HTTP/1.1 400 Bad Request\r\n"
                        b"content-length: 0\r\nconnection: close\r\n\r\n")
                    # peer may still be streaming: drain, don't RST
                    self.conn.close_draining()
                    return
                if not self.parser.done:
                    return
            elif self.buf:
                # head already parsed: bytes accumulate as body
                self.parser.buf += self.buf
                self.buf.clear()
            # chunked is unsupported here: a request bearing
            # transfer-encoding would be framed as length-0 and its body
            # parsed as the NEXT request (TE.CL desync) — reject it
            if self.parser.header("transfer-encoding") is not None:
                self.conn.write(b"HTTP/1.1 501 Not Implemented\r\n"
                                b"content-length: 0\r\n"
                                b"connection: close\r\n\r\n")
                self.conn.close_draining()
                return
            # strict 1*DIGIT and NO disagreeing duplicates (RFC 9110):
            # int()'s leniency ('+16', '1_6') or picking one of two
            # different content-lengths would disagree with a front
            # proxy on framing — a request-smuggling vector
            cls_ = {v for k, v in self.parser.headers
                    if k == "content-length"}
            if not cls_:
                cl = 0
            elif len(cls_) == 1:
                cl_s = next(iter(cls_))
                cl = (int(cl_s) if cl_s.isascii() and cl_s.isdigit()
                      else -1)
            else:
                cl = -1
            if cl < 0 or cl > MAX_BODY:
                code = (b"400 Bad Request" if cl < 0
                        else b"413 Payload Too Large")
                self.conn.write(b"HTTP/1.1 " + code +
                                b"\r\ncontent-length: 0\r\n"
                                b"connection: close\r\n\r\n")
                self.conn.close_draining()
                return
            have = len(self.parser.buf) - self.parser.head_len
            if have < cl:
                return
            total = self.parser.head_len + cl
            body = bytes(self.parser.buf[self.parser.head_len:total])
            leftover = bytes(self.parser.buf[total:])
            parser = self.parser
            self.parser = HeadParser()
            self.buf = bytearray(leftover)
            close = "close" in (parser.header("connection") or "").lower()
            self.server._dispatch(self.conn, parser, body, close)
            if close:
                return  # conn closes in _finish once the response is out

    def on_eof(self, conn: Connection) -> None:
        conn.close()

    def on_closed(self, conn: Connection, err: int) -> None:
        self.server._conns.discard(conn)
