"""Process entry — `python -m vproxy_tpu`.

Parity: app/Main.java: default controllers (resp on 16309, http on
18776, both on 127.0.0.1 — Main.java:319-337), load-last-config at boot,
hourly auto-save, signal-triggered graceful save+exit, stdio REPL.

Args (subset of the reference's op grammar, app/args/*):
  resp-controller <addr> <password>   start RESP controller there
  http-controller <addr>              start HTTP controller there
  allowSystemCommandInNonStdIOController (accepted, no-op)
  load <file>            load a config file instead of the default
  noLoadLast             do not load the last config
  noSave                 disable auto/exit saving
  noStdIOController      do not start the stdin REPL
  workers <n>            worker event loops (default: cpu count)

Env flags (the reference's -D system-property layer, Config.java):
  VPROXY_TPU_LOG=debug|info|warn|error   log level filter
  VPROXY_TPU_PROBE=ch1,ch2               targeted data-path probe channels
  VPROXY_TPU_FDTRACE=1                   trace every FD syscall (-Dvfdtrace)
  VPROXY_TPU_MATCHER=...                 classify backend override
  VPROXY_TPU_FP_MEMBER=gather|selgather|reduce
                                         fp-kernel member-eval lowering
  VPROXY_TPU_WORKERS=n                   default worker loop count
  VPROXY_TPU_HOME=dir                    config/persistence directory
  VPROXY_TPU_FD_PROVIDER=native|py       socket/pump backend
  VPROXY_TPU_NATIVE_TLS=0                force python TLS (MemoryBIO)
  VPROXY_TPU_SWITCH_FASTPATH=0           force object-path switch
  VPROXY_TPU_FASTPATH_MIN=n              burst floor for the fast path
  VPROXY_TPU_CLASSIFY=auto|device|host   dispatch-path policy
  VPROXY_TPU_CLASSIFY_BUDGET_US=n        lone-query latency budget
  VPROXY_TPU_DIST_COORD=host:port        jax.distributed coordinator
  VPROXY_TPU_DIST_NPROC=n                ... process count
  VPROXY_TPU_DIST_PROCID=i               ... this process's id
  VPROXY_TPU_DIST_TIMEOUT_S=s            ... bring-up deadline (120)

Cluster plane (docs/cluster.md):
  VPROXY_TPU_CLUSTER_PEERS=h:p[/rp],...  fleet topology (node id = index)
  VPROXY_TPU_CLUSTER_SELF=i              this node's id (default: dist
                                         process id, else 0)
  VPROXY_TPU_CLUSTER_HB_MS=ms            membership heartbeat (200)
  VPROXY_TPU_CLUSTER_UP/_DOWN=n          membership hysteresis (2 / 3)
  VPROXY_TPU_CLUSTER_POLL_MS=ms          follower replication poll (500)
  VPROXY_TPU_CLUSTER_SERVICE=name        DNS service sub-domain (cluster)
  VPROXY_TPU_CLUSTER_STEP_MS=ms          step-clock period (20)
  VPROXY_TPU_CLUSTER_STEP_TIMEOUT_MS=ms  barrier deadline (1000)
  VPROXY_TPU_CLUSTER_BATCH=n             per-host rows per step (16)

Failure-containment knobs (docs/robustness.md):
  VPROXY_TPU_CONNECT_RETRIES=n           backend connect retries (default 2)
  VPROXY_TPU_CONNECT_TIMEOUT_MS=ms       backend connect deadline (3000)
  VPROXY_TPU_RETRY_BUDGET=r              retries <= r * accepts (default .2)
  VPROXY_TPU_MAX_SESSIONS=n              per-LB overload shed threshold
  VPROXY_TPU_DRAIN_S=s                   SIGTERM/`drain` grace (default 15)
  VPROXY_TPU_EJECT_FAILURES=n            passive-eject streak (default 3)
  VPROXY_TPU_EJECT_BASE_S / _CAP_S       eject backoff base/cap (5 / 300)
  VPROXY_TPU_FAILPOINTS=spec             arm failpoints at boot
"""
from __future__ import annotations

import os
import signal
import sys
import threading

from .control import persist
from .control.app import Application
from .control.command import CmdError, Command
from .control.http_controller import HttpController
from .control.resp import RESPController

DEFAULT_RESP = ("127.0.0.1", 16309)
DEFAULT_HTTP = ("127.0.0.1", 18776)


def _addr(s: str):
    h, _, p = s.rpartition(":")
    return h or "127.0.0.1", int(p)


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    # beforeStart parity (Main.java:64-107): the OOM survival reserve
    # comes first, covering the deployable apps below too
    from .utils.oom import install as install_oom
    install_oom()

    # multi-host bring-up BEFORE any device touch: when
    # VPROXY_TPU_DIST_COORD/_NPROC/_PROCID are set, join the
    # jax.distributed job so every matcher mesh can span hosts
    # (parallel/mesh.py — tables replicated per host over DCN, rules
    # sharded within host over ICI). No-op when unset.
    from .parallel.mesh import init_distributed
    if init_distributed():
        import jax
        print(f"joined distributed job: process "
              f"{jax.process_index()}/{jax.process_count()}, "
              f"{len(jax.devices())} global devices")

    # deployable apps (reference -Deploy=...): first arg selects the app
    if argv and argv[0].lower() in ("simple", "helloworld", "daemon",
                                    "kcptun", "websocks"):
        name = argv.pop(0).lower()
        import importlib
        mod = importlib.import_module(f".apps.{name}", __package__)
        return mod.run(argv)
    opts = {"resp": DEFAULT_RESP, "resp_pass": None, "http": DEFAULT_HTTP,
            "load": None, "no_load": False, "no_save": False,
            "no_stdio": False, "workers": None, "inspect": None}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "resp-controller":
            opts["resp"] = _addr(argv[i + 1])
            opts["resp_pass"] = argv[i + 2]
            i += 3
        elif a == "http-controller":
            opts["http"] = _addr(argv[i + 1])
            i += 2
        elif a == "load":
            opts["load"] = argv[i + 1]
            i += 2
        elif a == "noLoadLast":
            opts["no_load"] = True
            i += 1
        elif a == "noSave":
            opts["no_save"] = True
            i += 1
        elif a == "noStdIOController":
            opts["no_stdio"] = True
            i += 1
        elif a == "workers":
            opts["workers"] = int(argv[i + 1])
            i += 2
        elif a == "globalInspection":
            opts["inspect"] = _addr(argv[i + 1])
            i += 2
        elif a in ("allowSystemCommandInNonStdIOController", "noStartupBindCheck"):
            i += 1
        elif a in ("version", "-version", "--version"):
            print("vproxy-tpu 0.1.0")
            return 0
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 1

    app = Application.create(workers=opts["workers"])
    try:
        resp = RESPController(app, opts["resp"][0], opts["resp"][1],
                              password=opts["resp_pass"])
        resp.start()
        http = HttpController(app, opts["http"][0], opts["http"][1])
        http.start()
    except OSError as e:
        print(f"failed to start controllers: {e}", file=sys.stderr)
        app.close()
        return 1
    print(f"resp-controller on {opts['resp'][0]}:{resp.bind_port}")
    print(f"http-controller on {opts['http'][0]}:{http.bind_port}")

    if opts["inspect"] is not None:
        from .utils.metrics import launch_inspection_http
        try:
            gi_srv = launch_inspection_http(
                app.control_loop, opts["inspect"][0], opts["inspect"][1])
        except OSError as e:
            print(f"failed to start global-inspection: {e}",
                  file=sys.stderr)
            app.close()
            return 1
        print(f"global-inspection on {opts['inspect'][0]}:{gi_srv.port}")

    if opts["load"]:
        n = persist.load(app, opts["load"])
        print(f"loaded {n} commands from {opts['load']}")
    elif not opts["no_load"] and os.path.exists(persist.LAST_CONFIG):
        n = persist.load(app)
        print(f"loaded {n} commands from {persist.LAST_CONFIG}")

    # cluster plane AFTER the config load: the leader's journal starts
    # from the restored resource graph; followers converge onto it via
    # generation-tagged replication (docs/cluster.md)
    from .cluster import ClusterNode
    try:
        app.cluster = ClusterNode.boot_from_env(app)
    except (OSError, ValueError) as e:
        print(f"failed to start cluster plane: {e}", file=sys.stderr)
        app.close()
        return 1
    if app.cluster is not None:
        m = app.cluster.membership
        print(f"cluster node {m.self_id}/{len(m.peers)} "
              f"(heartbeat :{m.peers[m.self_id].port}, replication "
              f":{app.cluster.replicator.bind_port})")

    stop = threading.Event()
    want_drain = threading.Event()  # SIGTERM/`drain`: graceful window

    # the handlers only set events: file I/O (or any lock) inside a
    # Python signal-handler frame can re-enter mid-bytecode — the save
    # now runs on the main thread after stop.wait(), post-drain
    def on_signal(signum, frame):
        if signum == signal.SIGTERM:
            want_drain.set()
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    if hasattr(signal, "SIGUSR2"):
        # the handler only sets an event (run_on_loop would take a
        # non-reentrant lock inside the signal frame); a dedicated
        # daemon thread does the actual save
        want_save = threading.Event()

        def usr2_saver() -> None:
            while True:
                want_save.wait()
                want_save.clear()
                if stop.is_set():
                    return
                try:
                    persist.save(app)
                except OSError as e:
                    print(f"save failed: {e}", file=sys.stderr)

        threading.Thread(target=usr2_saver, daemon=True,
                         name="usr2-save").start()
        signal.signal(signal.SIGUSR2, lambda s, f: want_save.set())

    # the `drain` operator command funnels to the same exit path
    app.on_drain_request.append(lambda: (want_drain.set(), stop.set()))

    if not opts["no_save"]:
        persist.start_auto_save(app)

    from .components.updater import ServerAddressUpdater
    updater = ServerAddressUpdater(lambda: app.server_groups.values())
    updater.start()

    if not opts["no_stdio"]:
        def repl() -> None:
            for line in sys.stdin:
                line = line.strip()
                if not line:
                    continue
                if line in ("exit", "quit", "System: exit"):
                    on_signal(None, None)
                    return
                try:
                    result = Command.execute(app, line)
                    if isinstance(result, list):
                        for j, item in enumerate(result):
                            print(f"{j + 1}) {item!r}")
                    else:
                        print(f"{result!r}")
                except CmdError as e:
                    print(f"error: {e}")
            on_signal(None, None)
        threading.Thread(target=repl, daemon=True, name="stdio").start()

    stop.wait()
    if want_drain.is_set():
        # graceful drain (SIGTERM / `drain`): listeners close, /healthz
        # flips to draining, pumps finish within VPROXY_TPU_DRAIN_S
        drain_s = float(os.environ.get("VPROXY_TPU_DRAIN_S", "15"))
        app.request_drain()  # no-op if the drain command already ran
        done = app.drain_wait(drain_s)
        print("drained cleanly" if done
              else f"drain window ({drain_s:.0f}s) closed; exiting",
              file=sys.stderr)
    if not opts["no_save"]:
        try:
            persist.save(app)
        except OSError as e:
            print(f"save failed: {e}", file=sys.stderr)
    updater.close()
    app.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
