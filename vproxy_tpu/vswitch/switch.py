"""Switch — the virtual L2/L3 SDN switch resource.

Parity: core vswitch/Switch.java:36 — ONE UDP socket receives every
VXLAN/encrypted frame (:50); the sender address maps to an iface in the
registry with a 60s activity timeout (:629-799, IFACE_TIMEOUT :630);
user management (add/del user = per-user AES-256 key + assigned VNI);
`handleNetworkAndGetVXLanPacket` (:643-744): plain VXLAN is gated by the
bare-access SecurityGroup, anything else must decrypt as a
VProxySwitchPacket under a known user's key; ping packets refresh the
iface and are answered. Per-VNI VpcNetwork + NetworkStack do L2/L3.
"""
from __future__ import annotations

import hashlib
import time
from typing import Optional

from ..components.secgroup import SecurityGroup
from ..net import vtl
from ..net.eventloop import SelectorEventLoop
from ..rules.ir import Proto
from ..utils.log import Logger
from ..utils.ip import Network, parse_ip
from . import swmetrics
from .iface import (BareVXLanIface, Iface, RemoteSwitchIface, TapIface,
                    UserClientIface, UserIface, tap_supported)
from .network import ARP_TABLE_TIMEOUT, MAC_TABLE_TIMEOUT, VpcNetwork
from .packets import (PacketError, VPROXY_TYPE_PING, VPROXY_TYPE_VXLAN,
                      VProxySwitchPacket, Vxlan)
from .stack import NetworkStack

_log = Logger("switch")

IFACE_TIMEOUT_MS = 60_000  # Switch.java:630


def format_user_name(user: str) -> str:
    """3-8 chars [a-zA-Z0-9], padded to 8 with '+' so the name is exactly
    8 base64 chars = the 6 raw bytes on the wire (Switch.formatUserName
    :431-446, Consts.USER_PADDING). Without this, a short name crashes
    the encrypted-packet encoder at SEND time with a base64 error."""
    if not (3 <= len(user) <= 8):
        raise ValueError("invalid user, should be at least 3 chars and "
                         "at most 8 chars")
    if not all(c.isascii() and c.isalnum() for c in user):
        raise ValueError("invalid user, should only contain a-zA-Z0-9")
    return user + "+" * (8 - len(user))


def display_user_name(user: str) -> str:
    """Wire form ('+'-padded to 8) back to the operator's name."""
    return user.rstrip("+")


def synthetic_mac(vni: int, ip: bytes) -> bytes:
    """Deterministic locally-administered mac for a synthetic ip."""
    h = hashlib.sha256(vni.to_bytes(4, "big") + ip).digest()
    return bytes([0x02]) + h[:5]


class Switch:
    def __init__(self, alias: str, loop: SelectorEventLoop, bind_ip: str,
                 bind_port: int,
                 mac_table_timeout_ms: int = MAC_TABLE_TIMEOUT,
                 arp_table_timeout_ms: int = ARP_TABLE_TIMEOUT,
                 bare_vxlan_access: Optional[SecurityGroup] = None,
                 matcher_backend: Optional[str] = None, elg=None):
        self.alias = alias
        self.loop = loop
        self.elg = elg  # attach target for loop-death re-homing
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.mac_table_timeout_ms = mac_table_timeout_ms
        self.arp_table_timeout_ms = arp_table_timeout_ms
        self.bare_access = bare_vxlan_access or SecurityGroup.allow_all()
        self.matcher_backend = matcher_backend
        self.networks: dict[int, VpcNetwork] = {}
        # user -> (key, vni, password); password kept for config persistence
        # (Shutdown.currentConfig serializes users with their passwords)
        self.users: dict[str, tuple[bytes, int, str]] = {}
        self.ifaces: dict = {}  # key -> (Iface, last_active_ts)
        # bumped on any registry mutation; the fast path's remote cache
        # (vswitch/fastpath.py) keys its validity on it
        self._reg_version = 0
        # remote (ip, port) -> registry key, so the per-datagram sender
        # lookup is O(1) instead of a scan over every registered iface
        self._remote_idx: dict[tuple[str, int], tuple] = {}
        self.stack = NetworkStack(self)
        # vectorized burst fast path (vswitch/fastpath.py); slow-path
        # leftovers keep the object pipeline. VPROXY_TPU_SWITCH_FASTPATH=0
        # forces the pure object path (A/B + debugging escape hatch).
        import os as _os
        self.fastpath = None
        if _os.environ.get("VPROXY_TPU_SWITCH_FASTPATH", "1") != "0":
            from .fastpath import SwitchFastPath
            self.fastpath = SwitchFastPath(self)
        # native flow cache (native/vtl.cpp): the in-C exact-match flow
        # table + forwarding loop. Needs the fast path (it compiles the
        # entries) and the native provider. VPROXY_TPU_FLOWCACHE=0 forces
        # the pure Python data plane (A/B + escape hatch).
        self._fc = None           # C table handle (vtl.flowcache_new)
        self._fc_active = False   # poll/install gate (bench A/B toggle)
        self._fc_enabled = (
            self.fastpath is not None
            and _os.environ.get("VPROXY_TPU_FLOWCACHE", "1") != "0")
        # multiqueue ingress: N EXTRA SO_REUSEPORT sockets, each drained
        # by a plain thread running the C forwarding loop — hits scale
        # across cores because vtl_switch_poll releases the GIL. Misses
        # are handed to the owning loop for classification. Per-entry
        # seqlocks in the C table make concurrent probe-vs-install safe.
        self._n_pollers = int(_os.environ.get("VPROXY_TPU_SWITCH_POLLERS",
                                              "0"))
        self._pollers: list = []
        self._poller_fds: list[int] = []
        self._pollers_stop = False
        self._fd: Optional[int] = None
        self._sweeper = None
        self._hh_task = None  # analytics flow-drain periodic
        self.started = False

    # ------------------------------------------------------------ control

    def start(self) -> None:
        if self.started:
            return
        self._init_flowcache()
        self.bare_access.add_listener(self._gen_bump)
        self._bind(self.loop)
        if self.elg is not None:
            self.elg.attach(self)
        self.started = True

    # ------------------------------------------------------- flow cache

    def _init_flowcache(self) -> None:
        if not self._fc_enabled or self._fc is not None:
            return
        if vtl.PROVIDER != "native" or not vtl.flowcache_supported():
            return
        import os as _os
        size = int(_os.environ.get("VPROXY_TPU_FLOWCACHE_SIZE", "65536"))
        ttl = int(_os.environ.get("VPROXY_TPU_FLOWCACHE_TTL_MS", "10000"))
        self._fc = vtl.flowcache_new(size, ttl)
        self._fc_active = True
        # analytics: the flow cache's per-entry hit tallies gate on the
        # same C atomic as the lane shards — push the current knob
        from ..utils import sketch
        sketch.push_native_knob()

    def flow_handle(self):
        """C flow-table handle for the fast path's entry compiler, or
        None when the native cache is off/inactive."""
        return self._fc if self._fc_active else None

    def set_flowcache(self, on: bool) -> None:
        """Hot A/B toggle (bench + operators). Entries survive a
        disable/enable cycle: mutations keep bumping the generation
        while inactive, so surviving entries stay correctly gated.
        Poller threads follow the toggle (their REUSEPORT sockets close
        on disable so the kernel rehashes all flows to the main sock)."""
        if on and self._fc is None:
            self._fc_enabled = True
            self._init_flowcache()
        self._fc_active = bool(on) and self._fc is not None
        if self._fc_active and self.started:
            self._start_pollers()
            self._arm_hh_task()  # a cache created by THIS hot-enable
            # missed _bind's arming — without this the per-entry hit
            # tallies would accumulate with no drain forever
        elif not self._fc_active:
            self._stop_pollers()

    def _arm_hh_task(self) -> None:
        """Arm the analytics flow-drain periodic (idempotent; on the
        owning loop). The tick itself gates on sketch.enabled()."""
        if self._fc is None or not vtl.hh_supported():
            return
        from ..utils import sketch

        def arm() -> None:
            if self._hh_task is None and self._fc is not None:
                self._hh_task = self.loop.period(
                    max(500, int(sketch.WINDOW_S * 250)),
                    self._hh_flow_tick)
        self.loop.run_on_loop(arm)

    # ------------------------------------------------ multiqueue pollers

    def _start_pollers(self) -> None:
        if (self._pollers or self._n_pollers <= 0 or self._fc is None
                or not self._fc_active or self._fd is None
                or vtl.PROVIDER != "native"):
            return
        import threading
        self._pollers_stop = False
        for i in range(self._n_pollers):
            try:
                fd = vtl.udp_bind(self.bind_ip, self.bind_port,
                                  reuseport=True)
            except OSError:
                break  # main sock not reuseport-bound: feature inactive
            vtl.set_rcvbuf(fd, 4 << 20)
            self._poller_fds.append(fd)
            th = threading.Thread(target=self._poller_main, args=(fd,),
                                  name=f"swpoll-{self.alias}-{i}",
                                  daemon=True)
            self._pollers.append(th)
            th.start()

    def _stop_pollers(self) -> None:
        if not self._pollers:
            return
        self._pollers_stop = True
        ths, self._pollers = self._pollers, []
        self._poller_fds = []
        for th in ths:
            th.join(timeout=2.0)  # wait_readable parks at most 200ms

    @staticmethod
    def _mirror_blocks() -> bool:
        """A hot mirror tapping the switch must see EVERY frame: the C
        lane is bypassed entirely while it is armed (cached hits would
        be invisible to the tap)."""
        from ..utils.mirror import Mirror
        mir = Mirror.get()
        return mir.hot and mir.wants("switch")

    def _poller_main(self, fd: int) -> None:
        """One multiqueue lane: park in poll(2), drain through the C
        forwarding loop, hand misses to the owning event loop. The
        thread closes its own socket on exit (no cross-thread close/fd
        reuse race)."""
        import errno as _errno
        try:
            while not self._pollers_stop:
                try:
                    if vtl.wait_readable(fd, 200) <= 0:
                        continue
                    if self._pollers_stop:
                        return
                    fc = self._fc
                    if fc is None or not self._fc_active:
                        return
                    if self._mirror_blocks():
                        # drain this lane straight to the object path
                        # so the mirror sees frames the cache would eat
                        got = vtl.recvmmsg(fd)
                        if got:
                            self.loop.run_on_loop(
                                lambda m=got: self._input_batch(
                                    m, small_ok=True))
                        continue
                    handled, miss = vtl.switch_poll(fc, fd)
                except OSError as e:
                    # a dead socket ends the lane (shutdown path); a
                    # transient error (ENOBUFS under pressure) must NOT
                    # silently cost 1/N ingress capacity forever
                    if self._pollers_stop or e.errno == _errno.EBADF:
                        return
                    _log.warn(f"switch {self.alias}: poller lane "
                              f"error (retrying): {e!r}")
                    time.sleep(0.01)
                    continue
                if handled:
                    swmetrics.rx(handled)
                if miss:
                    self.loop.run_on_loop(
                        lambda m=miss: self._input_batch(m, small_ok=True))
        finally:
            vtl.close(fd)

    def _hh_flow_tick(self) -> None:
        """Fold the C flow-table hit tallies into the flows dimension
        (utils/sketch). Bounded: at most 8 drain calls per tick — the
        cursor resumes next tick; each call is one quick C walk."""
        from ..net.vtl import _HH_DRAIN_MAX, hh_flow_drain
        from ..utils import sketch
        fc = self._fc
        if fc is None or not sketch.enabled():
            return
        try:
            for _ in range(8):
                recs = hh_flow_drain(fc)
                if recs:
                    sketch.ingest_hh_recs(recs)
                if len(recs) < _HH_DRAIN_MAX:
                    break
        except OSError:
            pass

    def _gen_bump(self, *_a) -> None:
        """Every route/ACL/MAC/ARP/owned-ip/iface mutation lands here:
        one C atomic bump invalidates every installed flow entry (probe
        sees a stale generation -> forced miss -> Python re-decides).
        The switch.flowcache.stale failpoint suppresses one bump to
        prove the gate is what prevents stale forwarding."""
        fc = self._fc
        if fc is None:
            return
        from ..utils import failpoint
        if failpoint.hit("switch.flowcache.stale", self.alias):
            return
        vtl.switch_gen_bump(fc)

    def _bump_registry(self) -> None:
        self._reg_version += 1
        self._gen_bump()

    def flowcache_info(self) -> Optional[dict]:
        """`list-detail switch` / tests: THIS switch's table occupancy
        and probe outcomes (an old .so reporting only 3 stat fields
        falls back to the process-global tallies)."""
        if self._fc is None:
            return None
        st = vtl.flowcache_stat(self._fc)
        size, used, gen = st[0], st[1], st[2]
        if len(st) >= 5:
            hits, misses = st[3], st[4]
        else:
            c = vtl.flowcache_counters()
            hits, misses = c[0], c[1]
        return {"active": self._fc_active, "size": size, "used": used,
                "gen": gen, "hits": hits, "misses": misses,
                "hit_rate": round(hits / (hits + misses), 4)
                if hits + misses else 0.0}

    def _bind(self, loop) -> None:
        def mk() -> None:
            # reuseport when multiqueue pollers are configured: their
            # sockets join this binding and the kernel shards flows
            self._fd = vtl.udp_bind(
                self.bind_ip, self.bind_port,
                reuseport=self._n_pollers > 0 and self._fc is not None)
            # bursty VXLAN ingress: the default ~200KB rcvbuf holds only
            # a few hundred datagrams — absorb whole bursts instead
            vtl.set_rcvbuf(self._fd, 4 << 20)
            if self.bind_port == 0:
                _, self.bind_port = vtl.sock_name(self._fd)
            loop.add(self._fd, vtl.EV_READ, self._on_readable)
            self._sweeper = loop.period(IFACE_TIMEOUT_MS // 4,
                                        self._sweep_ifaces)
            from ..utils import sketch
            if self._fc is not None and vtl.hh_supported():
                # analytics tick: drain the C per-flow hit tallies into
                # the flows dimension (a fraction of the window so the
                # epoch rotation sees fresh counts). Armed regardless
                # of the CURRENT knob — the tick itself gates on
                # sketch.enabled(), so a runtime configure(True) starts
                # flowing without a rebind (a boot-time-only gate left
                # the flows dim permanently empty after a late enable).
                # set_flowcache(True) arms via _arm_hh_task for caches
                # created after boot.
                self._hh_task = loop.period(
                    max(500, int(sketch.WINDOW_S * 250)),
                    self._hh_flow_tick)
        try:
            loop.call_sync(mk)
        except OSError as e:
            raise OSError(f"switch {self.alias}: bind failed: {e}") from e
        self._start_pollers()

    def on_loop_death(self, group, lp) -> None:
        """Re-home the switch's VXLAN sock onto a surviving loop when
        the hosting loop dies. VPC state and MAC/ARP tables are plain
        host memory and survive. Ifaces:

        * fd-less (bare-vxlan / remote-switch / user server side) —
          survive untouched; their traffic rides the re-homed sock;
        * user-client — re-arms its keepalive periodic on the new loop;
        * tap — its /dev/net/tun fd died with the loop and is dropped
          from the registry WITHOUT close() (the dead loop released the
          fd; closing the stale number could hit a reused descriptor).
        """
        from .iface import TapIface, UserClientIface
        if lp is not self.loop or not self.started:
            return
        self._fd = None
        self._sweeper = None
        self._hh_task = None  # died with the loop; _bind re-arms it
        for key, (iface, ts) in list(self.ifaces.items()):
            if isinstance(iface, TapIface):
                del self.ifaces[key]
                self._bump_registry()
                self._unindex(key, iface)
                for net in self.networks.values():
                    net.macs.remove_iface(iface)
        if not group.loops:
            self.started = False
            group.detach(self)
            return
        self.loop = group.next()
        try:
            self._bind(self.loop)
        except OSError as e:
            _log.alert(f"switch {self.alias}: re-home bind failed: {e!r}; "
                       f"switch is down")
            self.started = False
            group.detach(self)
            return
        for _key, (iface, _ts) in list(self.ifaces.items()):
            if isinstance(iface, UserClientIface):
                iface._periodic = None  # old timer died with the loop
                iface.attach(self)
        if not self.started:  # raced a concurrent stop(): undo the bind
            self._undo_rehome_bind()

    def _undo_rehome_bind(self) -> None:
        fd, self._fd = self._fd, None
        sweeper, self._sweeper = self._sweeper, None
        hh_task, self._hh_task = self._hh_task, None
        lp2 = self.loop

        def rm() -> None:
            if sweeper is not None:
                sweeper.cancel()
            if hh_task is not None:
                hh_task.cancel()
            if fd is not None:
                lp2.remove(fd)
                vtl.close(fd)
        lp2.run_on_loop(rm)

    def stop(self) -> None:
        if not self.started:
            return
        self.started = False
        if self.elg is not None:
            self.elg.detach(self)
        self.bare_access.remove_listener(self._gen_bump)
        self._stop_pollers()
        fd = self._fd
        self._fd = None
        # detach the handle first (mutation hooks stop bumping), free on
        # the loop thread where the poll/install paths run
        fc, self._fc = self._fc, None
        self._fc_active = False

        def rm() -> None:
            if self._sweeper is not None:
                self._sweeper.cancel()
            if self._hh_task is not None:
                self._hh_task.cancel()
                self._hh_task = None
            for iface, _ in list(self.ifaces.values()):
                iface.close()
            self.ifaces.clear()
            self._reg_version += 1
            self._remote_idx.clear()
            if fd is not None:
                self.loop.remove(fd)
                vtl.close(fd)
            if fc is not None:
                vtl.flowcache_free(fc)
        self.loop.run_on_loop(rm)

    # ---------------------------------------------------------- resources

    def add_network(self, vni: int, v4net: Network,
                    v6net: Optional[Network] = None,
                    annotations: Optional[dict] = None) -> VpcNetwork:
        if vni in self.networks:
            raise ValueError(f"vpc {vni} already exists")
        net = VpcNetwork(vni, v4net, v6net, self.mac_table_timeout_ms,
                         self.arp_table_timeout_ms, self.matcher_backend,
                         annotations=annotations)
        # every table mutation (mapping changes only, not timestamp
        # refreshes) invalidates the native flow cache via one atomic
        net.macs.on_change = self._gen_bump
        net.arps.on_change = self._gen_bump
        net.ips.on_change = self._gen_bump
        net.on_route_change = self._gen_bump
        self.networks[vni] = net
        self._gen_bump()
        return net

    def del_network(self, vni: int) -> None:
        if vni not in self.networks:
            raise KeyError(vni)
        del self.networks[vni]
        self._gen_bump()

    def add_user(self, user: str, password: str, vni: int) -> None:
        """user: 3-8 chars [a-zA-Z0-9], stored '+'-padded to 8 (the wire
        form); key derived from password (Aes256Key: sha256 of the
        password bytes)."""
        user = format_user_name(user)
        if user in self.users:
            raise ValueError(f"user {display_user_name(user)} already exists")
        key = hashlib.sha256(password.encode()).digest()
        self.users[user] = (key, vni, password)

    def del_user(self, user: str) -> None:
        del self.users[format_user_name(user)]

    def key_for_user(self, user: str) -> Optional[bytes]:
        ent = self.users.get(user)
        return ent[0] if ent is not None else None

    def add_remote_switch(self, alias: str, ip: str, port: int) -> RemoteSwitchIface:
        iface = RemoteSwitchIface(alias, ip, port)
        self._register(("remote", alias), iface, permanent=True)
        return iface

    def add_user_client(self, user: str, password: str, vni: int,
                        ip: str, port: int) -> UserClientIface:
        user = format_user_name(user)
        key = hashlib.sha256(password.encode()).digest()
        iface = UserClientIface(user, key, ip, port)
        iface.local_side_vni = vni
        self._register(("ucli", user, (ip, port)), iface, permanent=True)
        iface.attach(self)
        return iface

    def add_tap(self, pattern: str, vni: int,
                post_script: Optional[str] = None,
                annotations: Optional[dict] = None) -> TapIface:
        """post_script: executable run after the device exists with DEV
        set to the tap name (Switch.addTap's post-script hook — the
        docker driver uses it to move the tap into a container netns
        after a restart)."""
        if not tap_supported():
            raise OSError("tap devices not available (/dev/net/tun)")
        iface = TapIface(pattern, vni, self.loop, self._tap_frame,
                         annotations=annotations)
        iface.post_script = post_script
        if post_script:
            import os
            import subprocess
            if os.path.exists(post_script):
                try:
                    r = subprocess.run(["/bin/bash", post_script],
                                       env={**os.environ, "DEV": iface.dev},
                                       capture_output=True, timeout=10)
                except subprocess.TimeoutExpired:
                    iface.close()
                    raise OSError(f"post script {post_script} timed out "
                                  "(10s); tap removed")
                if r.returncode != 0:
                    iface.close()
                    raise OSError(
                        f"post script {post_script} failed "
                        f"({r.returncode}): {r.stderr.decode()[:200]}")
        self._register(("tap", iface.dev), iface, permanent=True)
        return iface

    def list_ifaces(self) -> list[Iface]:
        return [i for i, _ in self.ifaces.values()]

    def ifaces_for_vni(self, vni: int):
        out = []
        for iface, _ in self.ifaces.values():
            if iface.local_side_vni in (0, vni):
                out.append(iface)
        return out

    def _close_iface(self, iface: Iface) -> None:
        """Close AFTER the generation bump — and for tap ifaces (the
        only kind whose fd lives inside native flow entries) after a
        grace period longer than any in-flight C poll round, so a
        racing hit can never write() a recycled descriptor."""
        if isinstance(iface, TapIface) and self._fc is not None:
            import threading
            threading.Timer(0.2, iface.close).start()
        else:
            iface.close()

    def remove_iface(self, name: str) -> None:
        for key, (iface, _) in list(self.ifaces.items()):
            if iface.name == name:
                # generation bump BEFORE the close: a poller hitting a
                # native TAP entry must never write a recycled fd
                del self.ifaces[key]
                self._bump_registry()
                self._close_iface(iface)
                self._unindex(key, iface)
                for net in self.networks.values():
                    net.macs.remove_iface(iface)
                return
        raise KeyError(name)

    # ---------------------------------------------------------- data path

    def send_udp(self, data: bytes, remote: tuple[str, int]) -> None:
        if self._fd is not None:
            try:
                if vtl.sendto(self._fd, data, remote[0], remote[1]) < 0:
                    swmetrics.drop("egress_short_write")  # EAGAIN
            except OSError:
                swmetrics.drop("egress_short_write")

    def send_udp_many(self, datas: list, remote: tuple[str, int]) -> int:
        """Batched same-destination egress (fast-path groups): one
        sendmmsg when the native provider offers it. -> count accepted
        by the kernel (datagram drops under pressure are normal — and
        counted as egress_short_write so the drop rate is visible)."""
        if self._fd is None:
            return 0
        try:
            if vtl.PROVIDER == "native" and hasattr(vtl, "sendmmsg"):
                n = vtl.sendmmsg(self._fd, datas, remote[0], remote[1])
            else:
                n = 0
                for d in datas:
                    if vtl.sendto(self._fd, d, remote[0], remote[1]) < 0:
                        break
                    n += 1
            swmetrics.drop("egress_short_write", len(datas) - n)
            return n
        except OSError:
            swmetrics.drop("egress_short_write", len(datas))
            return 0

    def _register(self, key, iface: Iface, permanent: bool = False):
        self._bump_registry()
        self.ifaces[key] = (iface, float("inf") if permanent else time.monotonic())
        r = getattr(iface, "remote", None)
        if r is not None:
            if key[0] == "bare":
                # a configured link (remote-switch / ucli / user) for the
                # same addr keeps priority over an ad-hoc bare identity
                self._remote_idx.setdefault(r, key)
            else:
                self._remote_idx[r] = key
        return iface

    def _unindex(self, key, iface: Iface) -> None:
        r = getattr(iface, "remote", None)
        if r is None or self._remote_idx.get(r) != key:
            return
        del self._remote_idx[r]
        # repopulate from surviving ifaces with the same remote, keeping
        # configured links (remote-switch/ucli/user) ahead of bare ones —
        # identity must not be lost when a shadowing iface goes away
        fallback = None
        for k, (i, _) in self.ifaces.items():
            if getattr(i, "remote", None) == r:
                if k[0] != "bare":
                    self._remote_idx[r] = k
                    return
                fallback = k
        if fallback is not None:
            self._remote_idx[r] = fallback

    def _touch(self, key) -> None:
        ent = self.ifaces.get(key)
        if ent is not None and ent[1] != float("inf"):
            self.ifaces[key] = (ent[0], time.monotonic())

    def _sweep_ifaces(self) -> None:
        now = time.monotonic()
        for key, (iface, ts) in list(self.ifaces.items()):
            if ts == float("inf"):
                continue
            if (now - ts) * 1000 > IFACE_TIMEOUT_MS:
                del self.ifaces[key]
                self._bump_registry()  # before close: see remove_iface
                self._close_iface(iface)
                self._unindex(key, iface)
                for net in self.networks.values():
                    net.macs.remove_iface(iface)

    def _tap_frame(self, iface: TapIface, ether) -> None:
        self.stack.input_vxlan(Vxlan(iface.local_side_vni, ether), iface)

    RECV_BURST = 1024  # datagrams drained per wakeup before batch classify

    def _on_readable(self, fd: int, ev: int) -> None:
        """Drain a burst, then process it with batched ACL + LPM: the
        reference handles one datagram per handler pass
        (Switch.java:629-799); here the burst is the unit so the 5k-rule
        bare ACL and 50k-route LPM cost ONE device dispatch each per
        burst, not per packet. With the native flow cache active the
        drain runs INSIDE C (vtl_switch_poll): repeat-flow datagrams are
        forwarded without ever reaching Python and only misses surface
        here as a burst."""
        if self._fc_active and self._fc is not None \
                and not self._mirror_blocks():
            self._poll_native(fd)
            return
        batched = vtl.PROVIDER == "native" and hasattr(vtl, "recvmmsg")
        while self._fd is not None:
            burst = []
            if batched:  # one syscall per up-to-_MMSG_MAX dgrams
                while len(burst) < self.RECV_BURST:
                    got = vtl.recvmmsg(fd)
                    if not got:
                        break
                    burst.extend(got)
            else:
                while len(burst) < self.RECV_BURST:
                    r = vtl.recvfrom(fd)
                    if r is None:
                        break
                    burst.append(r)
            if not burst:
                return
            self._input_batch(burst)
            if len(burst) < self.RECV_BURST:
                return

    def _poll_native(self, fd: int) -> None:
        """The flow-cached drain: C forwards hits, misses accumulate
        into a Python burst (up to RECV_BURST before classify, so the
        cold-start all-miss case keeps today's amortization)."""
        fc = self._fc
        pending: list = []
        while self._fd is not None:
            handled, miss = vtl.switch_poll(fc, fd)
            if handled:
                swmetrics.rx(handled)
            if miss:
                pending.extend(miss)
            done = not handled and not miss
            if pending and (done or len(pending) >= self.RECV_BURST):
                # small miss bursts still classify+install (small_ok):
                # a trickle flow must compile its entry, not stay on
                # the per-packet object path forever
                self._input_batch(pending, small_ok=True)
                pending = []
            if done:
                return

    def _parse_bare(self, data: bytes) -> Optional[Vxlan]:
        """Plain VXLAN? (Switch.java:643-744 tries vxlan flags first.)"""
        if len(data) >= 8 and data[0] & 0x08 and not data[1] and not data[2]:
            try:
                return Vxlan.parse(data)
            except PacketError:
                return None
        return None

    def _resolve_remote_key(self, remote: tuple[str, int]):
        """-> (iface, registry key) for a bare sender addr, registered/
        refreshed. A configured remote-switch/ucli link for this addr
        reuses that iface identity instead of a new bare one (the index
        keeps configured links in priority — _register)."""
        key = self._remote_idx.get(remote)
        ent = self.ifaces.get(key) if key is not None else None
        if ent is None:
            key = ("bare", remote)
            ent = self.ifaces.get(key)  # unindexed survivor: reuse, don't orphan
            if ent is None:
                known = self._register(key, BareVXLanIface(*remote))
            else:
                known = ent[0]
                self._remote_idx.setdefault(remote, key)
        else:
            known = ent[0]
        self._touch(key)
        return known, key

    def _resolve_remote(self, remote: tuple[str, int]):
        return self._resolve_remote_key(remote)[0]

    def _resolve_bare(self, pkt: Vxlan, remote: tuple[str, int]):
        known = self._resolve_remote(remote)
        if known.local_side_vni:
            pkt = Vxlan(known.local_side_vni, pkt.ether)
        return pkt, known

    def _input_batch(self, burst, small_ok: bool = False) -> None:
        swmetrics.rx(len(burst))
        pending = None
        if self.fastpath is not None:
            # leftovers (control frames, non-bare, v6) run through the
            # object pipeline FIRST in arrival order, so their table
            # learns are visible to the vectorized rows flushed after
            burst, pending = self.fastpath.split(burst, small_ok)
            if not burst:
                if pending is not None:
                    self.fastpath.flush(pending)
                return
        bare: list = []    # (Vxlan, remote)
        other: list = []   # (data, remote) — encrypted / non-vxlan
        for data, ip, port in burst:
            pkt = self._parse_bare(data)
            if pkt is not None:
                bare.append((pkt, (ip, port)))
            else:
                other.append((data, (ip, port)))
        admitted = []
        if bare:
            allowed = self.bare_access.allow_batch(
                Proto.UDP, [parse_ip(r[0]) for _, r in bare],
                [self.bind_port] * len(bare))
            admitted = [self._resolve_bare(pkt, remote)
                        for (pkt, remote), ok in zip(bare, allowed) if ok]
            swmetrics.drop("acl_deny", len(bare) - len(admitted))
        if admitted:
            self.stack.input_vxlan_batch(admitted)
        for data, remote in other:
            self._input(data, remote)
        if pending is not None:
            self.fastpath.flush(pending)

    def _input(self, data: bytes, remote: tuple[str, int]) -> None:
        pkt = self._parse_bare(data)
        if pkt is not None:
            if not self.bare_access.allow(Proto.UDP, parse_ip(remote[0]),
                                          self.bind_port):
                swmetrics.drop("acl_deny")
                return
            pkt, known = self._resolve_bare(pkt, remote)
            self.stack.input_vxlan(pkt, known)
            return
        # 2) encrypted vproxy switch packet under a known user key
        def key_for(user: str):
            # server side: configured users; client side: ucli iface keys
            k = self.key_for_user(user)
            if k is not None:
                return k
            for iface, _ in self.ifaces.values():
                if isinstance(iface, UserClientIface) and iface.user == user:
                    return iface.key
            return None

        try:
            sp = VProxySwitchPacket.parse(data, key_for)
        except PacketError:
            return
        ent = self.users.get(sp.user)
        if ent is not None:
            _, vni, _pw = ent
            key = ("user", sp.user, remote)
            if key not in self.ifaces:
                self._register(key, UserIface(sp.user, remote, vni))
            self._touch(key)
            iface = self.ifaces[key][0]
        else:
            # client side receiving from the server it dialed
            iface = None
            for k, (i, _) in self.ifaces.items():
                if isinstance(i, UserClientIface) and i.user == sp.user \
                        and i.remote == remote:
                    iface, key = i, k
                    break
            if iface is None:
                return
            self._touch(key)
        if sp.type == VPROXY_TYPE_PING:
            if isinstance(iface, UserIface):
                iface.send_ping(self)  # pong so the client keeps us alive
            return
        if sp.vxlan is not None:
            pkt = sp.vxlan
            if iface.local_side_vni:
                pkt = Vxlan(iface.local_side_vni, pkt.ether)
            self.stack.input_vxlan(pkt, iface)
