"""Per-VNI network state: MAC table, ARP/neighbor table, synthetic IPs,
route table.

Parity: core vswitch/Table.java:13 (the VPC object), MacTable.java:14
(mac -> iface with TTL), ArpTable.java:13 (ip -> mac with TTL),
SyntheticIpHolder, RouteTable (the IR RouteTable from rules/ir.py keeps
the reference's most-specific-first insert order; lookups go through the
classify engine's CidrMatcher — the TPU LPM path, with the host oracle
for small tables).
"""
from __future__ import annotations

import time
from typing import Optional

from ..rules.engine import CidrMatcher
from ..rules.ir import RouteRule, RouteTable
from ..utils.ip import Network, format_ip
from .packets import mac_str

MAC_TABLE_TIMEOUT = 300_000  # ms (SwitchHandle defaults)
ARP_TABLE_TIMEOUT = 4 * 3600_000


class MacTable:
    """mac -> iface, expiring entries after timeout ms.

    `version` counts MAPPING changes (new mac, mac moved to another
    iface, removals) — NOT timestamp refreshes — so the burst fast
    path's vectorized view (vswitch/fastpath.py) stays valid across
    steady-state re-learns and rebuilds only when the topology moves."""

    def __init__(self, timeout_ms: int = MAC_TABLE_TIMEOUT):
        self.timeout_ms = timeout_ms
        self.version = 0
        # fires on every version bump (mapping change): the owning
        # switch points this at its flow-cache generation bump so a
        # topology move can never forward through a stale native entry
        self.on_change = None
        self._e: dict[bytes, tuple[object, float]] = {}

    def _bump(self) -> None:
        self.version += 1
        if self.on_change is not None:
            self.on_change()

    def record(self, mac: bytes, iface) -> None:
        old = self._e.get(mac)
        self._e[mac] = (iface, time.monotonic())
        if old is None or old[0] is not iface:
            self._bump()

    def lookup(self, mac: bytes):
        ent = self._e.get(mac)
        if ent is None:
            return None
        iface, ts = ent
        if (time.monotonic() - ts) * 1000 > self.timeout_ms:
            del self._e[mac]
            self._bump()
            return None
        return iface

    def remove_iface(self, iface) -> None:
        for mac, (i, _) in list(self._e.items()):
            if i is iface:
                del self._e[mac]
                self._bump()

    def expire(self) -> None:
        now = time.monotonic()
        for mac, (_, ts) in list(self._e.items()):
            if (now - ts) * 1000 > self.timeout_ms:
                del self._e[mac]
                self._bump()

    def entries(self) -> list[tuple[str, object]]:
        self.expire()
        return [(mac_str(m), i) for m, (i, _) in self._e.items()]


class ArpTable:
    """ip(bytes, canonical 4/16) -> mac, with TTL. `version` counts
    mapping changes only (see MacTable.version)."""

    def __init__(self, timeout_ms: int = ARP_TABLE_TIMEOUT):
        self.timeout_ms = timeout_ms
        self.version = 0
        self.on_change = None  # see MacTable.on_change
        self._e: dict[bytes, tuple[bytes, float]] = {}

    def _bump(self) -> None:
        self.version += 1
        if self.on_change is not None:
            self.on_change()

    def record(self, ip: bytes, mac: bytes) -> None:
        old = self._e.get(ip)
        self._e[ip] = (mac, time.monotonic())
        if old is None or old[0] != mac:
            self._bump()

    def lookup(self, ip: bytes) -> Optional[bytes]:
        ent = self._e.get(ip)
        if ent is None:
            return None
        mac, ts = ent
        if (time.monotonic() - ts) * 1000 > self.timeout_ms:
            del self._e[ip]
            self._bump()
            return None
        return mac

    def expire(self) -> None:
        now = time.monotonic()
        for ip, (_, ts) in list(self._e.items()):
            if (now - ts) * 1000 > self.timeout_ms:
                del self._e[ip]
                self._bump()

    def entries(self) -> list[tuple[str, str]]:
        self.expire()
        return [(format_ip(ip), mac_str(mac)) for ip, (mac, _) in self._e.items()]


class SyntheticIpHolder:
    """Virtual IPs owned by the switch inside this VPC (each with its own
    mac): ARP/NDP answered, ICMP echo answered, routed gateways."""

    _MISS = object()

    def __init__(self):
        self.version = 0
        self.on_change = None  # see MacTable.on_change
        self._ips: dict[bytes, bytes] = {}  # ip -> mac
        # first_in runs once per ROUTED PACKET (gateway source pick);
        # memoized per network, invalidated on any mutation. _by_mac is
        # the reverse index for find_by_mac (runs per L2-forwarded
        # packet): mac -> FIRST ip added with it, matching the old
        # insertion-order scan
        self._first_cache: dict = {}
        self._by_mac: dict[bytes, bytes] = {}

    def add(self, ip: bytes, mac: bytes) -> None:
        old = self._ips.get(ip)
        if old is not None and old != mac:
            self._unindex_mac(ip, old)  # re-add with a new mac
        self._ips[ip] = mac
        self._by_mac.setdefault(mac, ip)
        self._first_cache.clear()
        self.version += 1
        if self.on_change is not None:
            self.on_change()

    def remove(self, ip: bytes) -> None:
        mac = self._ips.pop(ip, None)
        if mac is not None:
            self._unindex_mac(ip, mac)
        self._first_cache.clear()
        self.version += 1
        if self.on_change is not None:
            self.on_change()

    def _unindex_mac(self, ip: bytes, mac: bytes) -> None:
        if self._by_mac.get(mac) == ip:
            del self._by_mac[mac]
            for ip2, m2 in self._ips.items():  # next-oldest takes over
                if m2 == mac and ip2 != ip:
                    self._by_mac[mac] = ip2
                    break

    def lookup_mac(self, ip: bytes) -> Optional[bytes]:
        return self._ips.get(ip)

    def find_by_mac(self, mac: bytes) -> Optional[bytes]:
        return self._by_mac.get(mac)

    def first_in(self, net: Network) -> Optional[tuple[bytes, bytes]]:
        """-> (ip, mac) of a synthetic ip inside net (gateway source pick)."""
        hit = self._first_cache.get(net, self._MISS)
        if hit is not self._MISS:
            return hit
        found = None
        for ip, mac in self._ips.items():
            if net.contains_ip(ip):
                found = (ip, mac)
                break
        self._first_cache[net] = found
        return found

    def ips(self) -> dict[bytes, bytes]:
        return dict(self._ips)


class VpcNetwork:
    """One VNI's state (Table.java)."""

    def __init__(self, vni: int, v4net: Network,
                 v6net: Optional[Network] = None,
                 mac_timeout_ms: int = MAC_TABLE_TIMEOUT,
                 arp_timeout_ms: int = ARP_TABLE_TIMEOUT,
                 matcher_backend: Optional[str] = None,
                 annotations: Optional[dict] = None):
        self.vni = vni
        self.v4net = v4net
        self.v6net = v6net
        # free-form key/value tags (Table.java annotations; the docker
        # network driver stores its networkId mapping here)
        self.annotations: dict = annotations or {}
        self.macs = MacTable(mac_timeout_ms)
        self.arps = ArpTable(arp_timeout_ms)
        self.ips = SyntheticIpHolder()
        self.routes = RouteTable()
        self._matcher_v4 = CidrMatcher(backend=matcher_backend)
        self._matcher_v6 = CidrMatcher(backend=matcher_backend)
        self.on_route_change = None  # see MacTable.on_change
        self.conntrack = None  # installed by the L4 stack

    # -------------------------------------------------------------- routes

    def add_route(self, r: RouteRule) -> None:
        self.routes.add(r)
        self._sync_routes()

    def remove_route(self, alias: str) -> None:
        self.routes.remove(alias)
        self._sync_routes()

    def _sync_routes(self) -> None:
        self._matcher_v4.set_networks([r.rule for r in self.routes.rules_v4])
        self._matcher_v6.set_networks([r.rule for r in self.routes.rules_v6])
        if self.on_route_change is not None:
            self.on_route_change()

    def route_lookup(self, ip: bytes) -> Optional[RouteRule]:
        """LPM through the classify engine (insert order = priority,
        matching RouteTable.lookup's first-contains semantics)."""
        if len(ip) == 4:
            rules, m = self.routes.rules_v4, self._matcher_v4
        else:
            rules, m = self.routes.rules_v6, self._matcher_v6
        if not rules:
            return None
        i = m.match_one(ip)
        return rules[i] if i >= 0 else None

    def route_lookup_batch(self, addrs) -> list:
        """Batched LPM for a drained packet burst: ONE matcher dispatch
        per family instead of per-packet match_one (which pays a device
        dispatch each on big tables). -> [Optional[RouteRule]] aligned
        with addrs."""
        from ..rules.engine import SMALL_TABLE
        out: list = [None] * len(addrs)
        for rules, m, fam_len in (
                (self.routes.rules_v4, self._matcher_v4, 4),
                (self.routes.rules_v6, self._matcher_v6, 16)):
            idx = [i for i, a in enumerate(addrs) if len(a) == fam_len]
            if not idx or not rules:
                continue
            if len(rules) <= SMALL_TABLE:
                # small tables: match_one's host scan beats a dispatch
                for i in idx:
                    r = m.match_one(addrs[i])
                    if r >= 0:
                        out[i] = rules[r]
                continue
            res = m.match([addrs[i] for i in idx])
            for i, r in zip(idx, res):
                if r >= 0:
                    out[i] = rules[int(r)]
        return out
