"""Vectorized switch data plane — the burst fast path.

The reference switch does its per-packet work (header parse, mac/arp
lookup, route LPM, re-encap) in compiled Java (vswitch/Switch.java:
629-799, stack/L2.java:296 -> L4); the round-4 Python data plane spread
the same work over per-packet object parse + stack logic and topped out
near 55k pps. This module re-expresses the two hot cases over a whole
drained burst as numpy array ops — the same burst-vectorization design
the device classify path uses — leaving every other case to the
existing per-packet stack:

* routed-v4: plain VXLAN, inner IPv4 (IHL=5) unicast to a switch-owned
  (synthetic) mac, dst ip not switch-owned, ttl > 1, route hit with a
  to_vni target whose arp/mac/src-mac all resolve -> header rewrite on
  the raw bytes (vni, macs, ttl-1, RFC 1624 incremental checksum) and
  raw egress. Mirrors stack.py: input_vxlan -> l3_input -> _ip_input ->
  route -> _route_with -> _deliver -> send_ether.
* known-unicast L2: dst mac known in the mac table -> forward the
  original bytes (vni patched when the ingress iface forces one).
  Mirrors input_vxlan's unicast branch.

Bare-ACL gating (Switch._input_batch's allow_batch) happens here for
v4 senders via a per-(secgroup-table, bind-port) direct-index trie:
first-match among the rules whose port range contains the (fixed) bind
port, painted min-index — exactly the ordered-scan winner. Route LPM
rides a per-VPC v4 trie built from the same `_trie4_paint_route` the
device tables use. Caches key on the published table tuple / matcher
snapshot IDENTITY, so any hot rule update rebuilds them.

Ordering: split() first classifies the burst (parse + bare ACL);
non-bare/unparseable leftovers go through the object pipeline FIRST in
arrival order, then flush() forwards the admitted rows. This keeps the
dependency direction that matters — control frames (ARP/NDP learns)
earlier in the burst update the tables the fast rows read. The inverse
(a fast data frame whose learns a leftover frame would have used) only
costs a flood-instead-of-forward, which the reference also does on any
table miss. Rows flush() finds ineligible mid-stream (multicast, v6,
ip options, ttl expiry, switch-owned dst ip [icmp/tcp stack], gateway
routes, arp/mac misses, egress without raw send) are re-injected
through stack.input_vxlan_batch so their route lookups stay amortized.

Learns match the slow path: src-mac -> iface on every admitted frame,
src-ip -> src-mac for routed IPv4, deduped per burst (same effect, the
tables store one timestamped entry either way).
"""
from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from ..net import vtl
from ..rules.ir import Proto
from ..utils.ip import parse_ip
from ..utils.log import Logger
from . import swmetrics

_log = Logger("swfast")

MIN_BURST = int(os.environ.get("VPROXY_TPU_FASTPATH_MIN", "32"))

# native flow-cache drop reasons (index contract: vtl.FLOW_DROP_REASONS)
_R_ACL_DENY, _R_SAME_IFACE, _R_ROUTE_MISS, _R_UNKNOWN_VNI = 0, 1, 2, 3
_ACT_FWD, _ACT_TAP, _ACT_DROP = 1, 2, 3
_Z4 = b"\x00\x00\x00\x00"
_Z6 = b"\x00\x00\x00\x00\x00\x00"


def _egress_target(iface):
    """-> (action, out_ip_u32, out_port, tap_fd) when `iface`'s raw
    egress is expressible as a native flow action: plain UDP to a v4
    remote (bare / remote-switch links — their raw send is exactly
    `sendto(switch fd, data, remote)`) or a tap fd write. Anything that
    transforms frames (encrypting user tunnels, custom test ifaces)
    returns None and stays on the Python path."""
    from .iface import BareVXLanIface, RemoteSwitchIface, TapIface
    if isinstance(iface, TapIface):
        return _ACT_TAP, 0, 0, iface.fd
    if isinstance(iface, (BareVXLanIface, RemoteSwitchIface)):
        ip, port = iface.remote
        try:
            b = parse_ip(ip)
        except (OSError, ValueError):
            return None
        if len(b) != 4:
            return None  # v6 egress: python path
        return _ACT_FWD, int.from_bytes(b, "big"), int(port), -1
    return None


class _FlowInstaller:
    """The flow-entry compiler's output stage: per-row verdicts from the
    numpy fast path packed into native install records, committed in ONE
    ctypes crossing per burst. Records are stamped with the generation
    read at construction (before the classification they encode); a
    mutation landing mid-flush makes the whole batch conservatively
    stale and the C side skips it — the flows simply re-miss."""

    __slots__ = ("fc", "gen", "burst", "ents", "mat", "lens", "recs")

    def __init__(self, fc, gen, burst, ents, mat, lens):
        self.fc = fc
        # the generation read BEFORE any table/ACL classification this
        # burst (split() reads it ahead of _acl_tables): a mutation
        # landing anywhere after that read voids the batch in C
        self.gen = gen
        self.burst = burst
        self.ents = ents
        self.mat = mat
        self.lens = lens
        self.recs: list = []

    def _key(self, i, wire_vni, eth_dst):
        """Key fields exactly as the C loop derives them from the wire
        bytes (vtl_switch_poll); None when the sender is not v4 (those
        frames never probe the table)."""
        e = self.ents[i]
        if e is None:
            return None
        sip = e[5]
        if sip is None or sip < 0:
            return None
        row = self.mat[i]
        ip_src = ip_dst = _Z4
        proto = 0
        if row[20] == 8 and row[21] == 0 and row[22] == 0x45:
            total = (int(row[24]) << 8) | int(row[25])
            if total >= 20 and int(self.lens[i]) >= 22 + total:
                ip_src = row[34:38].tobytes()
                ip_dst = row[38:42].tobytes()
                proto = int(row[31])
        if eth_dst is None:
            eth_dst = row[8:14].tobytes()
        return (int(sip), int(self.burst[i][2]),
                int(wire_vni).to_bytes(3, "big"), eth_dst,
                row[20:22].tobytes(), ip_src, ip_dst, proto)

    def add_fwd(self, i, wire_vni, out_iface, new_vni, eth_dst=None,
                new_dst=None, new_src=None, routed=False) -> None:
        tgt = _egress_target(out_iface)
        if tgt is None:
            return
        k = self._key(i, wire_vni, eth_dst)
        if k is None:
            return
        action, out_ip, out_port, tap_fd = tgt
        self.recs.append(vtl.FLOW_REC.pack(
            *k, action, 1 if routed else 0, 0,
            int(new_vni).to_bytes(3, "big"),
            new_dst if new_dst is not None else _Z6,
            new_src if new_src is not None else _Z6,
            out_ip, out_port, tap_fd))

    def add_drop(self, i, wire_vni, reason) -> None:
        k = self._key(i, wire_vni, None)
        if k is None:
            return
        self.recs.append(vtl.FLOW_REC.pack(
            *k, _ACT_DROP, 0, reason, b"\x00\x00\x00", _Z6, _Z6, 0, 0, -1))

    def commit(self) -> None:
        if self.recs:
            vtl.flow_install(self.fc, b"".join(self.recs), len(self.recs),
                             self.gen)

# byte offsets in a vxlan+ether+ipv4 datagram
_VNI = 4          # 3 bytes
_ETH_DST = 8      # 6
_ETH_SRC = 14     # 6
_ETYPE = 20       # 2 (0x0800)
_IP = 22          # ver/ihl
_IP_TOTAL = 24    # 2
_IP_TTL = 30
_IP_PROTO = 31
_IP_CSUM = 32     # 2
_IP_SRC = 34      # 4
_IP_DST = 38      # 4

_MAC_POW = (np.uint64(1) << (np.uint64(8) *
                             np.arange(5, -1, -1, dtype=np.uint64)))


def _contiguous_mask_len(mask4: bytes) -> Optional[int]:
    m = int.from_bytes(mask4, "big")
    inv = (~m) & 0xFFFFFFFF
    if inv & (inv + 1):
        return None  # not a contiguous prefix
    return 32 - inv.bit_length()


def _v4_pats(networks) -> Optional[list]:
    """[(key4, masklen, idx)] for the V4-family patterns, or None when
    any pattern's low mask is not a contiguous prefix (no trie)."""
    from ..ops.fphash import _expand_patterns
    from ..ops.tables import V4
    pats = []
    for i, net in enumerate(networks):
        for key, mask, fam in _expand_patterns(net):
            if fam != V4:
                continue
            ml = _contiguous_mask_len(mask[12:])
            if ml is None:
                return None
            pats.append((key[12:], ml, i))
    return pats


def _trie_of(pats: list) -> dict:
    from ..ops.fphash import _trie4_paint_route
    return _trie4_paint_route(pats, {})


def _trie_lookup_np(trie: dict, hi16: np.ndarray, b2: np.ndarray,
                    b3: np.ndarray) -> np.ndarray:
    """Vectorized 16/8/8 walk; -> rule idx + 1 per row (0 = miss)."""
    v0 = trie["t_l0"][hi16]
    s1 = np.where(v0 < 0, -v0 - 1, 0)
    v1 = trie["t_l1"][s1 * 256 + b2]
    r1 = np.where(v0 < 0, v1, v0)
    s2 = np.where(r1 < 0, -r1 - 1, 0)
    v2 = trie["t_l2"][s2 * 256 + b3]
    return np.where(r1 < 0, v2, r1)


VIEW_TTL_S = 5.0      # arp/mac numpy views re-filter expiry this often
LEARN_TTL_S = 1.0     # skip redundant same-mapping re-learns this long


class SwitchFastPath:
    def __init__(self, sw):
        self.sw = sw
        self._ip_cache: dict[str, Optional[int]] = {}  # sender str -> u32
        # bare-ACL verdict trie keyed on the published (matcher, rules)
        # tuple identity + the bind port the verdict was painted for
        self._acl_key = None
        self._acl_ref = None       # pins the published tuple for id()
        self._acl_trie = None      # False = no trie (slow); dict = trie
        self._acl_allow = None     # [n] bool per rule idx
        # per-VPC route tries keyed on the v4 matcher's snapshot identity
        self._routes: dict[int, tuple] = {}  # vni -> (snap, trie, tv, via)
        # vectorized arp/mac table views (vni -> (version, built_ts, ...))
        self._arp_views: dict[int, tuple] = {}
        self._mac_views: dict[int, tuple] = {}
        # sender iface cache: remote -> [iface, key, reg_version, touch_ts]
        self._remotes: dict[tuple, list] = {}
        # recent-learn dedupe: (vni, key) -> (mapping, ts)
        self._learned: dict[tuple, tuple] = {}
        # vectorized arp recent-learn filter: vni -> [keys, maps, born]
        self._arp_recent: dict[int, list] = {}
        # owned synthetic macs/ips arrays: vni -> (ips.version, macs, ips)
        self._owned: dict[int, tuple] = {}

    # ------------------------------------------------------------- tables

    def _acl_tables(self):
        """-> (kind, trie, allow, default) for the bare secgroup at the
        switch's bind port; kind is "none" (no rules — every bare row is
        gated by default_allow alone), "trie" (vectorized verdicts), or
        "slow" (non-prefix masks — the object path must decide). Rebuilt
        when the group publishes a new (matcher, rules) tuple."""
        sg = self.sw.bare_access
        ent = sg._tables.get(Proto.UDP)
        if ent is None:
            return "none", None, None, sg.default_allow
        key = (id(ent), self.sw.bind_port)
        if self._acl_key != key:
            m, sub = ent
            self._acl_ref = ent  # keep alive so id() stays unique
            elig = [(i, r) for i, r in enumerate(sub)
                    if r.min_port <= self.sw.bind_port <= r.max_port]
            pats = _v4_pats([r.network for _, r in elig])
            if pats is None:
                self._acl_trie = False  # non-prefix masks: no fast ACL
            else:
                # repaint with original rule indices so first-match order
                # is preserved across the eligibility filter
                remap = [i for i, _ in elig]
                pats = [(k, ml, remap[j]) for k, ml, j in pats]
                self._acl_trie = _trie_of(pats) if pats else {}
                self._acl_allow = np.array([r.allow for r in sub], bool) \
                    if sub else np.zeros(0, bool)
            self._acl_key = key
        if self._acl_trie is False:
            return "slow", None, None, sg.default_allow
        return "trie", self._acl_trie, self._acl_allow, sg.default_allow

    def _route_tables(self, net):
        """-> (trie|None, to_vni[], has_via[]) for a VPC's v4 routes."""
        snap = net._matcher_v4.snapshot()
        cached = self._routes.get(net.vni)
        if cached is not None and cached[0] is snap:
            return cached[1], cached[2], cached[3]
        rules = net.routes.rules_v4
        pats = _v4_pats([r.rule for r in rules])
        if pats is None:
            trie = tv = via = None
        else:
            trie = _trie_of(pats) if pats else {}
            tv = np.array([r.to_vni for r in rules], np.int64) \
                if rules else np.zeros(0, np.int64)
            via = np.array([r.via_ip is not None for r in rules], bool) \
                if rules else np.zeros(0, bool)
        self._routes[net.vni] = (snap, trie, tv, via)
        return trie, tv, via

    def _arp_view(self, net):
        """-> (keys u32-as-i64 sorted, macs [K,6] u8) of the VPC's
        unexpired v4 arp entries. Valid until the table's mapping
        version changes or VIEW_TTL_S passes (per-entry expiry within
        that window is slack the 4h arp timeout dwarfs)."""
        now = time.monotonic()
        c = self._arp_views.get(net.vni)
        if c is not None and c[0] == net.arps.version and \
                now - c[1] < VIEW_TTL_S:
            return c[2], c[3]
        ks, ms = [], []
        tmo = net.arps.timeout_ms
        for ip, (mac, ts) in net.arps._e.items():
            if len(ip) == 4 and (now - ts) * 1000 <= tmo:
                ks.append(int.from_bytes(ip, "big"))
                ms.append(mac)
        keys = np.asarray(ks, np.int64)
        order = np.argsort(keys)
        keys = keys[order]
        macs = np.frombuffer(b"".join(ms), np.uint8).reshape(-1, 6)[order] \
            if ms else np.zeros((0, 6), np.uint8)
        self._arp_views[net.vni] = (net.arps.version, now, keys, macs)
        return keys, macs

    def _mac_view(self, net):
        """-> (mac64 sorted, iface list aligned, raw-capable bool[])."""
        now = time.monotonic()
        c = self._mac_views.get(net.vni)
        if c is not None and c[0] == net.macs.version and \
                now - c[1] < VIEW_TTL_S:
            return c[2], c[3], c[4]
        ks, ifs = [], []
        tmo = net.macs.timeout_ms
        for mac, (iface, ts) in net.macs._e.items():
            if (now - ts) * 1000 <= tmo:
                ks.append(int.from_bytes(mac, "big"))
                ifs.append(iface)
        keys = np.asarray(ks, np.uint64)
        order = np.argsort(keys)
        keys = keys[order]
        ifs = [ifs[int(j)] for j in order]
        raw = np.array([callable(getattr(i, "send_vxlan_raw", None))
                        for i in ifs], bool) \
            if ifs else np.zeros(0, bool)
        self._mac_views[net.vni] = (net.macs.version, now, keys, ifs, raw)
        return keys, ifs, raw

    def _owned_view(self, net):
        c = self._owned.get(net.vni)
        if c is not None and c[0] == net.ips.version:
            return c[1], c[2]
        macs = np.fromiter(
            (int.from_bytes(m, "big") for m in net.ips._by_mac),
            np.uint64, len(net.ips._by_mac)) \
            if net.ips._by_mac else np.zeros(0, np.uint64)
        ips = np.fromiter(
            (int.from_bytes(ip, "big") for ip in net.ips._ips
             if len(ip) == 4), np.int64, -1) \
            if net.ips._ips else np.zeros(0, np.int64)
        self._owned[net.vni] = (net.ips.version, macs, ips)
        return macs, ips

    _MISS = object()

    def _sender4(self, ip_str: str) -> Optional[int]:
        v = self._ip_cache.get(ip_str, self._MISS)
        if v is self._MISS:
            try:
                b = parse_ip(ip_str)
                v = int.from_bytes(b, "big") if len(b) == 4 else None
            except (OSError, ValueError):
                v = None
            if len(self._ip_cache) > 65536:
                self._ip_cache.clear()
            self._ip_cache[ip_str] = v
        return v

    def _learn(self, kind: str, vni: int, key, apply, mapping,
               now: float) -> None:
        """Dedupe repeated identical learns within LEARN_TTL_S (pure
        timestamp refreshes; the mac/arp timeouts dwarf the window)."""
        k = (kind, vni, key)
        e = self._learned.get(k)
        if e is not None and e[0] == mapping and now - e[1] < LEARN_TTL_S:
            return
        if len(self._learned) > 65536:
            self._learned.clear()
        self._learned[k] = (mapping, now)
        apply()

    def _egress(self, mat, rows, row_lens, if_idx, ifaces,
                row_if=None) -> None:
        """Grouped raw egress: ONE materialization of every outgoing
        row, then cheap bytes slices per datagram (the serialized bytes
        are exactly mat's patched rows). row_if, when given, enables
        the L2 same-iface drop."""
        sw = self.sw
        blk = mat[rows].tobytes()
        w = mat.shape[1]
        rows_l = rows.tolist()
        lens_l = row_lens.tolist()
        for u in np.unique(if_idx):
            out = ifaces[int(u)]
            many = getattr(out, "send_vxlan_raw_many", None)
            if many is not None:
                group = np.nonzero(if_idx == u)[0].tolist()
                datas = [blk[j * w: j * w + lens_l[j]]
                         for j in group
                         if row_if is None or out is not row_if[rows_l[j]]]
                swmetrics.drop("same_iface", len(group) - len(datas))
                if datas:
                    many(sw, datas)  # one sendmmsg per iface group
                    swmetrics.forward("fast", len(datas))
                continue
            raw = out.send_vxlan_raw
            sent = skipped = 0
            for j in np.nonzero(if_idx == u)[0].tolist():
                if row_if is not None and out is row_if[rows_l[j]]:
                    skipped += 1
                    continue  # consumed: same-iface drop
                o = j * w
                raw(sw, blk[o: o + lens_l[j]])
                sent += 1
            swmetrics.drop("same_iface", skipped)
            swmetrics.forward("fast", sent)

    @staticmethod
    def _last_per_key(keys: np.ndarray):
        """-> (unique keys, index of the LAST occurrence of each). The
        slow path records per packet with last-wins dict semantics;
        recording only each key's last occurrence per burst leaves the
        tables in the identical end state."""
        u, first_rev = np.unique(keys[::-1], return_index=True)
        return u, len(keys) - 1 - first_rev

    # ------------------------------------------------------------ split

    def split(self, burst: list, small_ok: bool = False):
        """[(data, ip, port)] -> (leftovers, pending). Leftovers (non-
        bare frames, v6 senders, or everything when the fast path can't
        run) go through the object pipeline first — in arrival order —
        then Switch._input_batch calls flush(pending) to forward the
        admitted rows. ACL-denied v4-sender rows are consumed here.
        small_ok (native flow-cache miss bursts) waives MIN_BURST: even
        a lone miss must classify here so its flow entry gets compiled
        instead of staying per-packet forever."""
        n = len(burst)
        if n < (1 if small_ok else MIN_BURST):
            return burst, None
        from ..utils.mirror import Mirror
        mir = Mirror.get()
        if mir.hot and mir.wants("switch"):
            return burst, None  # taps want the object path
        # flow-entry stamp: MUST be read before the ACL tables so a rule
        # swap racing this burst voids every entry it compiles
        fc = self.sw.flow_handle()
        gen0 = vtl.switch_gen(fc) if fc is not None else 0
        kind, acl_trie, acl_allow, acl_default = self._acl_tables()
        if kind == "slow":
            return burst, None  # the object path must run the ACL

        datas = [b[0] for b in burst]
        lens = np.fromiter(map(len, datas), np.int64, n)
        ml = int(lens.max(initial=0))
        if ml < 42:
            return burst, None
        if int(lens.min()) == ml:  # uniform datagrams: zero-pad free
            mat = np.frombuffer(b"".join(datas),
                                np.uint8).reshape(n, ml).copy()
        else:
            pad = b"\x00" * ml
            mat = np.frombuffer(
                b"".join((d + pad)[:ml] for d in datas),
                np.uint8).reshape(n, ml).copy()

        bare = (lens >= 42) & ((mat[:, 0] & 8) != 0) & (mat[:, 1] == 0) \
            & (mat[:, 2] == 0)
        if not bare.any():
            return burst, None

        # one dict hit per bare row resolves BOTH the cached sender-v4
        # int (ACL input) and, later, the ingress iface (filled lazily
        # by _resolve_ifaces for admitted rows only — denied senders
        # must never register an iface)
        cache = self._remotes
        ents: list = [None] * n
        src32 = np.full(n, -1, np.int64)
        s4 = self._sender4
        for i in np.nonzero(bare)[0].tolist():
            b = burst[i]
            e = cache.get((b[1], b[2]))
            if e is None:
                v = s4(b[1])
                if len(cache) > 65536:
                    cache.clear()
                e = cache[(b[1], b[2])] = \
                    [None, None, -1, 0.0, 0, -1 if v is None else v]
            ents[i] = e
            src32[i] = e[5]

        denied = None
        if kind == "none":
            if not acl_default:
                # deny-all with no rules: every bare row is consumed
                admitted = np.zeros(n, bool)
                denied = bare
                swmetrics.drop("acl_deny", int(bare.sum()))
            else:
                admitted = bare
            keep = ~bare
        else:
            src_ok = src32 >= 0
            cell = _trie_lookup_np(acl_trie, src32 >> 16,
                                   (src32 >> 8) & 255, src32 & 255) \
                if acl_trie else np.zeros(n, np.int64)
            hitrule = np.clip(cell - 1, 0, max(len(acl_allow) - 1, 0))
            verdict = np.where(cell > 0,
                               acl_allow[hitrule] if len(acl_allow)
                               else acl_default, acl_default)
            admitted = bare & src_ok & verdict
            # denied v4-sender bare rows are CONSUMED (dropped), exactly
            # like the slow path's allow_batch filter; unparseable
            # senders go to the slow path whose ACL handles v6 families
            keep = ~bare | (bare & ~src_ok)
            denied = bare & src_ok & ~verdict
            swmetrics.drop("acl_deny", int(denied.sum()))
        if denied is not None and denied.any():
            # compile native DROP entries so the repeat-flow deny cost
            # is one C probe, not a Python burst — reason-counted in C
            if fc is not None:
                inst = _FlowInstaller(fc, gen0, burst, ents, mat, lens)
                for i in np.nonzero(denied)[0].tolist():
                    v = (int(mat[i, _VNI]) << 16) | \
                        (int(mat[i, _VNI + 1]) << 8) | int(mat[i, _VNI + 2])
                    inst.add_drop(i, v, _R_ACL_DENY)
                inst.commit()
        leftovers = [burst[i] for i in np.nonzero(keep)[0]]
        if not admitted.any():
            return leftovers, None
        return leftovers, (burst, mat, lens, admitted, ents, gen0)

    def flush(self, pending) -> None:
        burst, mat, lens, admitted, ents, gen0 = pending
        fc = self.sw.flow_handle()
        inst = _FlowInstaller(fc, gen0, burst, ents, mat, lens) \
            if fc is not None else None
        self._forward(burst, mat, lens, admitted, ents, inst)
        if inst is not None:
            inst.commit()

    # ------------------------------------------------- forward the admitted

    def _resolve_ifaces(self, burst, rows, ents):
        """Fill the per-remote entries' iface halves for the admitted
        rows (split already found/created the entries); activity touches
        are rate-limited to the sweep granularity."""
        sw = self.sw
        now = time.monotonic()
        ver0 = ver = sw._reg_version
        row_if = {}
        ov = np.zeros(len(rows), np.int64)
        rows_l = rows.tolist()
        for j, i in enumerate(rows_l):
            e = ents[i]
            if e[0] is None or e[2] != ver:
                b = burst[i]
                iface, key = sw._resolve_remote_key((b[1], b[2]))
                # re-read the version: registering a NEW bare iface just
                # bumped it, and stamping the stale value would mark
                # every entry invalid again next burst
                ver = sw._reg_version
                e[0], e[1], e[2], e[3] = iface, key, ver, now
                e[4] = iface.local_side_vni
            elif now - e[3] > 1.0:
                sw._touch(e[1])
                e[3] = now
                e[4] = e[0].local_side_vni
            row_if[i] = e[0]
            ov[j] = e[4]
        if ver != ver0:
            # registrations THIS burst bumped the version; every entry
            # used here is known-current, so restamp them all — without
            # this, rows validated before an in-burst newcomer would
            # re-resolve on every subsequent burst with churn
            for i in rows_l:
                ents[i][2] = ver
        return row_if, ov

    def _forward(self, burst, mat, lens, admitted, ents,
                 inst=None) -> None:
        """Forward/drop the admitted rows; admitted-but-ineligible rows
        are re-injected through the object pipeline in one batch at the
        end (their route lookups stay amortized)."""
        sw = self.sw
        n = len(burst)
        slow = np.zeros(n, bool)
        rows = np.nonzero(admitted)[0]
        if not len(rows):
            return

        row_if, ov = self._resolve_ifaces(burst, rows, ents)
        vni_parsed = (mat[:, _VNI].astype(np.int64) << 16) | \
            (mat[:, _VNI + 1].astype(np.int64) << 8) | mat[:, _VNI + 2]
        vni_eff = vni_parsed.copy()
        vni_eff[rows] = np.where(ov > 0, ov, vni_parsed[rows])

        eth_dst64 = (mat[:, _ETH_DST:_ETH_DST + 6].astype(np.uint64)
                     @ _MAC_POW)
        eth_src64 = (mat[:, _ETH_SRC:_ETH_SRC + 6].astype(np.uint64)
                     @ _MAC_POW)
        mcast = (mat[:, _ETH_DST] & 1) != 0
        src_mcast = (mat[:, _ETH_SRC] & 1) != 0
        is_ip4 = (mat[:, _ETYPE] == 8) & (mat[:, _ETYPE + 1] == 0) & \
            (mat[:, _IP] == 0x45)
        total = mat[:, _IP_TOTAL].astype(np.int64) * 256 + \
            mat[:, _IP_TOTAL + 1]
        len_ok = is_ip4 & (total >= 20) & (lens >= 22 + total)

        now = time.monotonic()
        for vni in np.unique(vni_eff[rows]):
            grp = rows[vni_eff[rows] == vni]
            net = sw.networks.get(int(vni))
            if net is None:
                swmetrics.drop("unknown_vni", len(grp))
                if inst is not None:
                    for i in grp.tolist():
                        inst.add_drop(i, int(vni_parsed[i]),
                                      _R_UNKNOWN_VNI)
                continue  # consumed: dropped like the slow path
            # learn src macs (multicast srcs are not learned): last
            # occurrence per mac — the per-packet dict writes of the
            # slow path end in the same state
            lrn = grp[~src_mcast[grp]]
            if len(lrn):
                _, last = self._last_per_key(eth_src64[lrn])
                for j in last:
                    i = lrn[j]
                    iface = row_if[int(i)]
                    self._learn(
                        "mac", net.vni, int(eth_src64[i]), lambda i=i,
                        iface=iface: net.macs.record(
                            mat[i, _ETH_SRC:_ETH_SRC + 6].tobytes(),
                            iface),
                        id(iface), now)
            slow[grp[mcast[grp]]] = True  # flood + l3 multicast path
            uni = grp[~mcast[grp]]
            if not len(uni):
                continue
            owned_macs, owned_ips = self._owned_view(net)
            to_l3 = np.isin(eth_dst64[uni], owned_macs)
            self._l2_forward(net, mat, lens, uni[~to_l3], eth_dst64,
                             vni_parsed, vni_eff, row_if, slow, inst)
            l3 = uni[to_l3]
            if not len(l3):
                continue
            bad = l3[~len_ok[l3]]
            slow[bad] = True  # v6 / options / truncated -> object path
            l3 = l3[len_ok[l3]]
            if not len(l3):
                continue
            # verify the INBOUND header checksum before the incremental
            # rewrite path touches it: the object path re-serializes via
            # Ipv4.to_bytes (fresh checksum), so a corrupt frame must go
            # there for bit parity — and gets counted while at it
            hdr = mat[l3, _IP:_IP + 20].astype(np.int64)
            hsum = (hdr[:, 0::2] * 256 + hdr[:, 1::2]).sum(axis=1)
            hsum = (hsum & 0xFFFF) + (hsum >> 16)
            hsum = (hsum & 0xFFFF) + (hsum >> 16)
            csum_ok = hsum == 0xFFFF
            if not csum_ok.all():
                swmetrics.slowpath("bad_csum", int((~csum_ok).sum()))
                slow[l3[~csum_ok]] = True
                l3 = l3[csum_ok]
                if not len(l3):
                    continue
            # arp-learn src ip -> src mac (l3_input does this for IPv4):
            # last occurrence per ip, deduped across bursts
            src32 = (mat[l3, _IP_SRC].astype(np.int64) << 24) | \
                (mat[l3, _IP_SRC + 1].astype(np.int64) << 16) | \
                (mat[l3, _IP_SRC + 2].astype(np.int64) << 8) | \
                mat[l3, _IP_SRC + 3].astype(np.int64)
            uk, last = self._last_per_key(src32)
            # vectorized recent-learn filter: (src ip, src mac) pairs
            # learned within LEARN_TTL_S are skipped wholesale
            rec = self._arp_recent.get(net.vni)
            if rec is not None and now - rec[2] < LEARN_TTL_S:
                pos = np.searchsorted(rec[0], uk) if len(rec[0]) else None
                if pos is not None:
                    posc = np.clip(pos, 0, len(rec[0]) - 1)
                    umaps = eth_src64[l3[last]].astype(np.int64)
                    fresh = ~((rec[0][posc] == uk) & (rec[1][posc] == umaps))
                else:
                    fresh = np.ones(len(uk), bool)
            else:
                rec = None
                fresh = np.ones(len(uk), bool)
            if fresh.any():
                l3l = l3.tolist()
                for j in last[fresh].tolist():
                    i = l3l[j]
                    net.arps.record(mat[i, _IP_SRC:_IP_SRC + 4].tobytes(),
                                    mat[i, _ETH_SRC:_ETH_SRC + 6].tobytes())
                newk = uk[fresh]
                newm = eth_src64[l3[last[fresh]]].astype(np.int64)
                if rec is None:
                    order = np.argsort(newk)
                    self._arp_recent[net.vni] = [newk[order], newm[order],
                                                 now]
                else:
                    # REPLACE any stale entries for the re-learned keys:
                    # appending would leave the old (ip, mac) pair first
                    # in sorted order and suppress a mapping that flaps
                    # back within the TTL window
                    keep = ~np.isin(rec[0], newk)
                    ks = np.concatenate([rec[0][keep], newk])
                    ms = np.concatenate([rec[1][keep], newm])
                    order = np.argsort(ks, kind="stable")
                    rec[0], rec[1] = ks[order], ms[order]
            # dst ip owned by the switch -> icmp/tcp stack (slow)
            dst32 = (mat[l3, _IP_DST].astype(np.int64) << 24) | \
                (mat[l3, _IP_DST + 1].astype(np.int64) << 16) | \
                (mat[l3, _IP_DST + 2].astype(np.int64) << 8) | \
                mat[l3, _IP_DST + 3].astype(np.int64)
            own = np.isin(dst32, owned_ips)
            slow[l3[own]] = True
            keep = ~own & (mat[l3, _IP_TTL] > 1)
            slow[l3[~own & (mat[l3, _IP_TTL] <= 1)]] = True  # time-exceeded
            l3, dst32 = l3[keep], dst32[keep]
            if not len(l3):
                continue
            trie, tv, via = self._route_tables(net)
            if trie is None:
                slow[l3] = True  # no v4 trie for this VPC
                continue
            if trie:
                cell = _trie_lookup_np(trie, dst32 >> 16,
                                       (dst32 >> 8) & 255, dst32 & 255)
            else:
                cell = np.zeros(len(l3), np.int64)
            # route miss = consumed drop (slow path drops too)
            swmetrics.drop("route_miss", int((cell == 0).sum()))
            if inst is not None and (cell == 0).any():
                for i in l3[cell == 0].tolist():
                    inst.add_drop(i, int(vni_parsed[i]), _R_ROUTE_MISS)
            hit = l3[cell > 0]
            ridx = cell[cell > 0] - 1
            slow[hit[via[ridx]]] = True  # gateway routes: object path
            keep = ~via[ridx]
            hit, ridx = hit[keep], ridx[keep]
            if len(hit):
                self._deliver_routed(mat, lens, hit, tv[ridx],
                                     dst32[cell > 0][keep], slow,
                                     vni_parsed, inst)
        stray = np.nonzero(slow)[0]
        if len(stray):
            self._reinject(burst, stray, vni_eff, row_if)

    def _l2_forward(self, net, mat, lens, rows, eth_dst64, vni_parsed,
                    vni_eff, row_if, slow, inst=None) -> None:
        """Known-unicast L2: forward original bytes (vni patched when
        the ingress iface forces one); mac-miss rows flood via the
        object path."""
        if not len(rows):
            return
        sw = self.sw
        mkeys, mifs, mraw = self._mac_view(net)
        d64 = eth_dst64[rows]
        if len(mkeys):
            posc = np.clip(np.searchsorted(mkeys, d64), 0, len(mkeys) - 1)
            hitm = (mkeys[posc] == d64) & mraw[posc]
        else:
            posc = np.zeros(len(d64), np.int64)
            hitm = np.zeros(len(d64), bool)
        slow[rows[~hitm]] = True  # miss -> flood; no-raw -> object path
        fwd = rows[hitm]
        ifidx = posc[hitm]
        if inst is not None and len(fwd):
            # compile L2 entries: forward-to-remote, or a reason-counted
            # DROP when the egress IS the ingress (hairpin suppression)
            for j, i in enumerate(fwd.tolist()):
                out = mifs[int(ifidx[j])]
                if out is row_if[i]:
                    inst.add_drop(i, int(vni_parsed[i]), _R_SAME_IFACE)
                else:
                    inst.add_fwd(i, int(vni_parsed[i]), out,
                                 int(vni_eff[i]))
        patch = fwd[vni_eff[fwd] != vni_parsed[fwd]]
        if len(patch):
            mat[patch, _VNI] = (vni_eff[patch] >> 16) & 255
            mat[patch, _VNI + 1] = (vni_eff[patch] >> 8) & 255
            mat[patch, _VNI + 2] = vni_eff[patch] & 255
        self._egress(mat, fwd, lens[fwd], ifidx, mifs, row_if=row_if)

    def _deliver_routed(self, mat, lens, rows, tvnis, dst32, slow,
                        vni_parsed=None, inst=None) -> None:
        """Cross-VNI delivery, vectorized: arp + mac resolution via the
        numpy table views, header rewrite in bulk (vni, macs, ttl-1,
        RFC 1624 incremental checksum), egress grouped per iface.
        Unresolvable rows go slow (the object path's arp-request/flood
        machinery applies there)."""
        sw = self.sw
        for tv in np.unique(tvnis):
            target = sw.networks.get(int(tv))
            sub = rows[tvnis == tv]
            if target is None:
                swmetrics.drop("unknown_vni", len(sub))
                continue  # consumed: _route_with drops unknown vni
            d32 = dst32[tvnis == tv]
            akeys, amacs = self._arp_view(target)
            if len(akeys):
                posc = np.clip(np.searchsorted(akeys, d32), 0,
                               len(akeys) - 1)
                hit = akeys[posc] == d32
            else:
                posc = np.zeros(len(d32), np.int64)
                hit = np.zeros(len(d32), bool)
            slow[sub[~hit]] = True  # arp miss -> object path arp-request
            sub, posc = sub[hit], posc[hit]
            if not len(sub):
                continue
            dmac = amacs[posc]  # [M, 6]
            mkeys, mifs, mraw = self._mac_view(target)
            d64 = (dmac.astype(np.uint64) @ _MAC_POW)
            if len(mkeys):
                mposc = np.clip(np.searchsorted(mkeys, d64), 0,
                                len(mkeys) - 1)
                mhit = (mkeys[mposc] == d64) & mraw[mposc]
            else:
                mposc = np.zeros(len(d64), np.int64)
                mhit = np.zeros(len(d64), bool)
            slow[sub[~mhit]] = True  # mac miss / no raw egress
            sub, dmac, mposc = sub[mhit], dmac[mhit], mposc[mhit]
            if not len(sub):
                continue
            src = target.ips.first_in(target.v4net)
            smac = src[1] if src is not None else b"\x02\x00\x00\x00\x00\x01"
            if inst is not None:
                # compile routed entries BEFORE the in-place rewrite:
                # the key reads the original eth_dst from mat, the
                # action carries the rewrite template
                for j, i in enumerate(sub.tolist()):
                    inst.add_fwd(i, int(vni_parsed[i]),
                                 mifs[int(mposc[j])], int(tv),
                                 new_dst=dmac[j].tobytes(), new_src=smac,
                                 routed=True)
            # bulk header rewrite
            mat[sub, _VNI] = (int(tv) >> 16) & 255
            mat[sub, _VNI + 1] = (int(tv) >> 8) & 255
            mat[sub, _VNI + 2] = int(tv) & 255
            mat[sub, _ETH_DST:_ETH_DST + 6] = dmac
            mat[sub, _ETH_SRC:_ETH_SRC + 6] = np.frombuffer(smac, np.uint8)
            mat[sub, _IP_TTL] -= 1
            c = mat[sub, _IP_CSUM].astype(np.int64) * 256 + \
                mat[sub, _IP_CSUM + 1]
            x = (c ^ 0xFFFF) + 0xFEFF   # RFC 1624: ~(~HC + ~m + m')
            x = (x & 0xFFFF) + (x >> 16)
            x = (x & 0xFFFF) + (x >> 16)
            c = x ^ 0xFFFF
            mat[sub, _IP_CSUM] = c >> 8
            mat[sub, _IP_CSUM + 1] = c & 255
            total = mat[sub, _IP_TOTAL].astype(np.int64) * 256 + \
                mat[sub, _IP_TOTAL + 1] + 22
            self._egress(mat, sub, total, mposc, mifs)

    def _reinject(self, burst, stray, vni_eff, row_if) -> None:
        """Object-path the admitted-but-ineligible rows in one batch
        (post-ACL, iface already resolved, vni override applied)."""
        from .packets import PacketError, Vxlan
        items = []
        for i in stray:
            try:
                pkt = Vxlan.parse(burst[i][0])
            except PacketError:
                continue
            if vni_eff[i] != pkt.vni:
                pkt = Vxlan(int(vni_eff[i]), pkt.ether)
            items.append((pkt, row_if[int(i)]))
        if items:
            self.sw.stack.input_vxlan_batch(items)
