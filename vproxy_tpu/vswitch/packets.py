"""Binary packet codecs for the virtual switch.

Parity: base vpacket/* (EthernetPacket, ArpPacket.java:227,
Ipv4Packet.java:351, Ipv6Packet.java:342, TcpPacket.java:456, VXLanPacket,
VProxyEncryptedPacket) — standard wire formats, parsed into light
dataclass-style objects and re-serialized with checksums computed.
All multi-byte fields are network byte order.
"""
from __future__ import annotations

import os
import struct
from typing import Optional

ETHER_TYPE_ARP = 0x0806
ETHER_TYPE_IPV4 = 0x0800
ETHER_TYPE_IPV6 = 0x86DD

PROTO_ICMP = 1
PROTO_TCP = 6
PROTO_UDP = 17
PROTO_ICMPV6 = 58

ARP_REQUEST = 1
ARP_REPLY = 2

ICMP_ECHO_REPLY = 0
ICMP_ECHO_REQ = 8
ICMP_TIME_EXCEEDED = 11
ICMP_UNREACHABLE = 3

ICMPV6_ECHO_REQ = 128
ICMPV6_ECHO_REPLY = 129
ICMPV6_NDP_NS = 135  # neighbor solicitation
ICMPV6_NDP_NA = 136  # neighbor advertisement

BROADCAST_MAC = b"\xff\xff\xff\xff\xff\xff"

# vproxy-encrypted switch packet (VProxyEncryptedPacket.java wire layout)
VPROXY_SWITCH_MAGIC = 0x8776
VPROXY_TYPE_VXLAN = 1
VPROXY_TYPE_PING = 2


class PacketError(Exception):
    pass


def checksum(data: bytes) -> int:
    """Internet (ones'-complement) checksum."""
    if len(data) % 2:
        data += b"\x00"
    s = sum(struct.unpack(f">{len(data) // 2}H", data))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return (~s) & 0xFFFF


def _pseudo_v4(src: bytes, dst: bytes, proto: int, length: int) -> bytes:
    return src + dst + struct.pack(">BBH", 0, proto, length)


def _pseudo_v6(src: bytes, dst: bytes, proto: int, length: int) -> bytes:
    return src + dst + struct.pack(">IHBB", length, 0, 0, proto)


class Ethernet:
    """Inner layers parse LAZILY: the switch forwards most frames
    without ever looking past the header it needs, and to_bytes passes
    untouched payloads through as the original bytes — the router fast
    path (no re-serialization, no checksum recompute)."""

    __slots__ = ("dst", "src", "ether_type", "payload", "_pkt",
                 "_pkt_parsed")

    def __init__(self, dst: bytes, src: bytes, ether_type: int, payload,
                 packet=None):
        self.dst = dst
        self.src = src
        self.ether_type = ether_type
        self.payload = payload  # bytes
        self._pkt = packet      # parsed upper packet or None
        # builders that pass an object are "parsed"; parse() defers
        self._pkt_parsed = packet is not None or not payload

    @property
    def packet(self):
        if not self._pkt_parsed:
            self._pkt_parsed = True
            try:
                if self.ether_type == ETHER_TYPE_ARP:
                    self._pkt = Arp.parse(self.payload)
                elif self.ether_type == ETHER_TYPE_IPV4:
                    self._pkt = Ipv4.parse(self.payload)
                elif self.ether_type == ETHER_TYPE_IPV6:
                    self._pkt = Ipv6.parse(self.payload)
            except PacketError:
                self._pkt = None
        return self._pkt

    @packet.setter
    def packet(self, v) -> None:
        self._pkt = v
        self._pkt_parsed = True

    @classmethod
    def parse(cls, data: bytes) -> "Ethernet":
        if len(data) < 14:
            raise PacketError("ethernet too short")
        return cls(data[:6], data[6:12],
                   struct.unpack(">H", data[12:14])[0], data[14:])

    def to_bytes(self) -> bytes:
        body = self._pkt.to_bytes() if self._pkt is not None else self.payload
        return self.dst + self.src + struct.pack(">H", self.ether_type) + body


class Arp:
    __slots__ = ("op", "sha", "spa", "tha", "tpa")

    def __init__(self, op: int, sha: bytes, spa: bytes, tha: bytes, tpa: bytes):
        self.op = op
        self.sha = sha  # sender mac
        self.spa = spa  # sender ipv4
        self.tha = tha
        self.tpa = tpa

    @classmethod
    def parse(cls, data: bytes) -> "Arp":
        if len(data) < 28:
            raise PacketError("arp too short")
        htype, ptype, hlen, plen, op = struct.unpack(">HHBBH", data[:8])
        if htype != 1 or ptype != ETHER_TYPE_IPV4 or hlen != 6 or plen != 4:
            raise PacketError("unsupported arp")
        return cls(op, data[8:14], data[14:18], data[18:24], data[24:28])

    def to_bytes(self) -> bytes:
        return struct.pack(">HHBBH", 1, ETHER_TYPE_IPV4, 6, 4, self.op) + \
            self.sha + self.spa + self.tha + self.tpa


class Ipv4:
    __slots__ = ("tos", "ident", "flags_frag", "ttl", "proto", "src", "dst",
                 "options", "payload", "_pkt", "_pkt_parsed")

    def __init__(self, src: bytes, dst: bytes, proto: int, payload,
                 ttl: int = 64, tos: int = 0, ident: int = 0,
                 flags_frag: int = 0x4000, options: bytes = b"", packet=None):
        self.src = src
        self.dst = dst
        self.proto = proto
        self.payload = payload
        self.ttl = ttl
        self.tos = tos
        self.ident = ident
        self.flags_frag = flags_frag
        self.options = options
        self._pkt = packet
        self._pkt_parsed = packet is not None or not payload

    @property
    def packet(self):
        """Transport layer, parsed LAZILY: a routed packet never pays
        the ICMP/TCP/UDP parse (or the re-serialization on egress)."""
        if not self._pkt_parsed:
            self._pkt_parsed = True
            try:
                if self.proto == PROTO_ICMP:
                    self._pkt = Icmp.parse(self.payload)
                elif self.proto == PROTO_TCP:
                    self._pkt = Tcp.parse(self.payload)
                elif self.proto == PROTO_UDP:
                    self._pkt = Udp.parse(self.payload)
            except PacketError:
                self._pkt = None
        return self._pkt

    @packet.setter
    def packet(self, v) -> None:
        self._pkt = v
        self._pkt_parsed = True

    @classmethod
    def parse(cls, data: bytes) -> "Ipv4":
        if len(data) < 20:
            raise PacketError("ipv4 too short")
        ver_ihl = data[0]
        if ver_ihl >> 4 != 4:
            raise PacketError("not ipv4")
        ihl = (ver_ihl & 0xF) * 4
        if ihl < 20 or len(data) < ihl:
            raise PacketError("bad ihl")
        tos = data[1]
        total = struct.unpack(">H", data[2:4])[0]
        if total < ihl or total > len(data):
            raise PacketError("bad total length")
        ident, flags_frag = struct.unpack(">HH", data[4:8])
        ttl, proto = data[8], data[9]
        return cls(data[12:16], data[16:20], proto, data[ihl:total], ttl,
                   tos, ident, flags_frag, data[20:ihl])

    def to_bytes(self) -> bytes:
        body = self.payload if self._pkt is None else \
            self._pkt.to_bytes(self.src, self.dst, v6=False)
        ihl = 20 + len(self.options)
        total = ihl + len(body)
        head = bytearray(struct.pack(
            ">BBHHHBBH", (4 << 4) | (ihl // 4), self.tos, total, self.ident,
            self.flags_frag, self.ttl, self.proto, 0))
        head += self.src + self.dst + self.options
        csum = checksum(bytes(head))
        head[10:12] = struct.pack(">H", csum)
        return bytes(head) + body

    def proto_num(self) -> int:
        return self.proto


class Ipv6:
    __slots__ = ("src", "dst", "next_header", "hop_limit", "payload",
                 "_pkt", "_pkt_parsed", "flow")

    def __init__(self, src: bytes, dst: bytes, next_header: int, payload,
                 hop_limit: int = 64, flow: int = 0, packet=None):
        self.src = src
        self.dst = dst
        self.next_header = next_header
        self.payload = payload
        self.hop_limit = hop_limit
        self.flow = flow
        self._pkt = packet
        self._pkt_parsed = packet is not None or not payload

    @property
    def packet(self):
        if not self._pkt_parsed:
            self._pkt_parsed = True
            try:
                if self.next_header == PROTO_ICMPV6:
                    self._pkt = Icmpv6.parse(self.payload)
                elif self.next_header == PROTO_TCP:
                    self._pkt = Tcp.parse(self.payload)
                elif self.next_header == PROTO_UDP:
                    self._pkt = Udp.parse(self.payload)
            except PacketError:
                self._pkt = None
        return self._pkt

    @packet.setter
    def packet(self, v) -> None:
        self._pkt = v
        self._pkt_parsed = True

    @classmethod
    def parse(cls, data: bytes) -> "Ipv6":
        if len(data) < 40:
            raise PacketError("ipv6 too short")
        first = struct.unpack(">I", data[:4])[0]
        if first >> 28 != 6:
            raise PacketError("not ipv6")
        plen, nh, hl = struct.unpack(">HBB", data[4:8])
        if len(data) < 40 + plen:
            raise PacketError("short payload")
        return cls(data[8:24], data[24:40], nh, data[40:40 + plen], hl,
                   first & 0x0FFFFFFF)

    def to_bytes(self) -> bytes:
        body = self.payload if self._pkt is None else \
            self._pkt.to_bytes(self.src, self.dst, v6=True)
        return struct.pack(">IHBB", (6 << 28) | self.flow, len(body),
                           self.next_header, self.hop_limit) + \
            self.src + self.dst + body

    def proto_num(self) -> int:
        return self.next_header


class Icmp:
    __slots__ = ("type", "code", "body")

    def __init__(self, type_: int, code: int, body: bytes):
        self.type = type_
        self.code = code
        self.body = body  # rest-of-header + data

    @classmethod
    def parse(cls, data: bytes) -> "Icmp":
        if len(data) < 4:
            raise PacketError("icmp too short")
        return cls(data[0], data[1], data[4:])

    def to_bytes(self, src: bytes = b"", dst: bytes = b"",
                 v6: bool = False) -> bytes:
        raw = bytearray(struct.pack(">BBH", self.type, self.code, 0) + self.body)
        raw[2:4] = struct.pack(">H", checksum(bytes(raw)))
        return bytes(raw)


class Icmpv6:
    __slots__ = ("type", "code", "body")

    def __init__(self, type_: int, code: int, body: bytes):
        self.type = type_
        self.code = code
        self.body = body

    @classmethod
    def parse(cls, data: bytes) -> "Icmpv6":
        if len(data) < 4:
            raise PacketError("icmpv6 too short")
        return cls(data[0], data[1], data[4:])

    def to_bytes(self, src: bytes, dst: bytes, v6: bool = True) -> bytes:
        raw = bytearray(struct.pack(">BBH", self.type, self.code, 0) + self.body)
        ps = _pseudo_v6(src, dst, PROTO_ICMPV6, len(raw))
        raw[2:4] = struct.pack(">H", checksum(ps + bytes(raw)))
        return bytes(raw)

    # --- NDP helpers (RFC 4861) ---

    @property
    def ndp_target(self) -> Optional[bytes]:
        if self.type in (ICMPV6_NDP_NS, ICMPV6_NDP_NA) and len(self.body) >= 20:
            return self.body[4:20]
        return None

    def ndp_lladdr_option(self) -> Optional[bytes]:
        """source (NS) / target (NA) link-layer address option."""
        off = 20
        want = 1 if self.type == ICMPV6_NDP_NS else 2
        while off + 8 <= len(self.body):
            t, ln = self.body[off], self.body[off + 1]
            if ln == 0:
                return None
            if t == want:
                return self.body[off + 2:off + 8]
            off += ln * 8
        return None


class Udp:
    __slots__ = ("sport", "dport", "data", "csum_ok")

    def __init__(self, sport: int, dport: int, data: bytes):
        self.sport = sport
        self.dport = dport
        self.data = data

    @classmethod
    def parse(cls, data: bytes) -> "Udp":
        if len(data) < 8:
            raise PacketError("udp too short")
        sport, dport, ln, _ = struct.unpack(">HHHH", data[:8])
        if ln < 8 or ln > len(data):
            raise PacketError("bad udp length")
        return cls(sport, dport, data[8:ln])

    def to_bytes(self, src: bytes, dst: bytes, v6: bool) -> bytes:
        ln = 8 + len(self.data)
        raw = bytearray(struct.pack(">HHHH", self.sport, self.dport, ln, 0))
        raw += self.data
        ps = (_pseudo_v6 if v6 else _pseudo_v4)(src, dst, PROTO_UDP, ln)
        cs = checksum(ps + bytes(raw)) or 0xFFFF
        raw[6:8] = struct.pack(">H", cs)
        return bytes(raw)


TCP_FIN, TCP_SYN, TCP_RST, TCP_PSH, TCP_ACK, TCP_URG = 1, 2, 4, 8, 16, 32


class Tcp:
    __slots__ = ("sport", "dport", "seq", "ack", "flags", "window", "options",
                 "data")

    def __init__(self, sport: int, dport: int, seq: int, ack: int, flags: int,
                 window: int, data: bytes = b"", options: bytes = b""):
        self.sport = sport
        self.dport = dport
        self.seq = seq
        self.ack = ack
        self.flags = flags
        self.window = window
        self.options = options
        self.data = data

    @classmethod
    def parse(cls, data: bytes) -> "Tcp":
        if len(data) < 20:
            raise PacketError("tcp too short")
        sport, dport, seq, ack = struct.unpack(">HHII", data[:12])
        off = (data[12] >> 4) * 4
        flags = data[13]
        window = struct.unpack(">H", data[14:16])[0]
        if off < 20 or off > len(data):
            raise PacketError("bad tcp offset")
        return cls(sport, dport, seq, ack, flags, window, data[off:],
                   data[20:off])

    def to_bytes(self, src: bytes, dst: bytes, v6: bool) -> bytes:
        opts = self.options
        if len(opts) % 4:
            opts += b"\x00" * (4 - len(opts) % 4)
        off = 20 + len(opts)
        raw = bytearray(struct.pack(
            ">HHIIBBHHH", self.sport, self.dport, self.seq, self.ack,
            (off // 4) << 4, self.flags, self.window, 0, 0))
        raw += opts + self.data
        ps = (_pseudo_v6 if v6 else _pseudo_v4)(src, dst, PROTO_TCP, len(raw))
        raw[16:18] = struct.pack(">H", checksum(ps + bytes(raw)))
        return bytes(raw)

    def mss_option(self) -> Optional[int]:
        off = 0
        while off < len(self.options):
            k = self.options[off]
            if k == 0:
                return None
            if k == 1:
                off += 1
                continue
            if off + 1 >= len(self.options):
                return None
            ln = self.options[off + 1]
            if ln < 2:
                return None
            if k == 2 and ln == 4:
                return struct.unpack(">H", self.options[off + 2:off + 4])[0]
            off += ln
        return None


class Vxlan:
    __slots__ = ("vni", "ether")

    def __init__(self, vni: int, ether: Ethernet):
        self.vni = vni
        self.ether = ether

    @classmethod
    def parse(cls, data: bytes) -> "Vxlan":
        if len(data) < 8:
            raise PacketError("vxlan too short")
        flags = data[0]
        if not flags & 0x08:
            raise PacketError("vxlan I flag not set")
        vni = int.from_bytes(data[4:7], "big")
        return cls(vni, Ethernet.parse(data[8:]))

    def to_bytes(self) -> bytes:
        return bytes([0x08, 0, 0, 0]) + self.vni.to_bytes(3, "big") + b"\x00" + \
            self.ether.to_bytes()


# ------------------------------------------------- encrypted switch packet

def _aes_cfb(key: bytes, iv: bytes, data: bytes, encrypt: bool) -> bytes:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
    c = Cipher(algorithms.AES(key), modes.CFB(iv))
    op = c.encryptor() if encrypt else c.decryptor()
    return op.update(data) + op.finalize()


class VProxySwitchPacket:
    """User-authenticated encrypted VXLAN tunnel packet
    (VProxyEncryptedPacket.java layout): user(6) iv(16) then
    AES-256-CFB(magic(4) type(2) [vxlan])."""

    __slots__ = ("user", "type", "vxlan")

    def __init__(self, user: str, type_: int, vxlan: Optional[Vxlan]):
        self.user = user  # base64 (no padding) of the 6 raw bytes
        self.type = type_
        self.vxlan = vxlan

    @classmethod
    def parse(cls, data: bytes, key_for) -> "VProxySwitchPacket":
        import base64
        if len(data) < 28:
            raise PacketError("switch packet too short")
        user = base64.b64encode(data[:6]).decode().replace("=", "")
        key = key_for(user)
        if key is None:
            raise PacketError(f"no key for user {user}")
        iv = data[6:22]
        plain = _aes_cfb(key, iv, data[22:], encrypt=False)
        magic = struct.unpack(">I", plain[:4])[0]
        if magic != VPROXY_SWITCH_MAGIC:
            raise PacketError("wrong magic (bad key?)")
        type_ = struct.unpack(">H", plain[4:6])[0]
        if type_ == VPROXY_TYPE_VXLAN:
            return cls(user, type_, Vxlan.parse(plain[6:]))
        if type_ == VPROXY_TYPE_PING:
            if len(plain) != 6:
                raise PacketError("extra bytes in ping")
            return cls(user, type_, None)
        raise PacketError(f"bad switch packet type {type_}")

    def to_bytes(self, key_for) -> bytes:
        import base64
        import binascii
        pad = self.user + "=" * (-len(self.user) % 4)
        try:
            raw_user = base64.b64decode(pad)
        except binascii.Error as e:
            raise PacketError(f"user is not wire-encodable: {e}") from e
        if len(raw_user) != 6:
            raise PacketError("user must decode to 6 bytes")
        key = key_for(self.user)
        if key is None:
            raise PacketError(f"no key for user {self.user}")
        iv = os.urandom(16)
        plain = struct.pack(">IH", VPROXY_SWITCH_MAGIC, self.type)
        if self.vxlan is not None:
            plain += self.vxlan.to_bytes()
        return raw_user + iv + _aes_cfb(key, iv, plain, encrypt=True)


def mac_str(mac: bytes) -> str:
    return ":".join(f"{b:02x}" for b in mac)


def parse_mac(s: str) -> bytes:
    parts = s.split(":")
    if len(parts) != 6:
        raise PacketError(f"bad mac {s!r}")
    return bytes(int(p, 16) for p in parts)
