"""Socket-style API over the user-space TCP stack.

Parity: core vswitch/stack/fd (VSwitchFDs/VSwitchSocketFD/
VSwitchServerSocketFD — stack/fd/VSwitchSocketFD.java:274): components
can listen and connect INSIDE a VPC of the virtual switch. The surface
mirrors net/connection.py's handler style so code written against
Connection/ServerSock ports over with a one-line change.

All callbacks fire on the switch's event loop thread.
"""
from __future__ import annotations

from typing import Callable, Optional

from .switch import Switch, synthetic_mac
from .tcp import L4, TcpConn, TcpHandler


def get_l4(sw: Switch) -> L4:
    if sw.stack.l4 is None:
        L4(sw)
    return sw.stack.l4


class VServerSock:
    """Listen on ip:port inside a VPC. The listen ip is added as a
    synthetic ip (the switch answers ARP for it)."""

    def __init__(self, sw: Switch, vni: int, ip: bytes, port: int,
                 on_accept: Callable[["VConn"], None]):
        self.sw = sw
        net = sw.networks.get(vni)
        if net is None:
            raise OSError(f"no vpc {vni}")
        self.net = net
        self.ip = ip
        self.port = port
        if net.ips.lookup_mac(ip) is None:
            net.ips.add(ip, synthetic_mac(vni, ip))
        self.l4 = get_l4(sw)
        self._on_accept = on_accept
        self.l4.conntrack(net).listen(ip, port, self._accept)
        self.closed = False

    def _accept(self, conn: TcpConn) -> None:
        vc = VConn(conn, connected=True)
        self._on_accept(vc)
        if vc.handler is not None:
            vc.handler.on_connected(vc)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.l4.conntrack(self.net).stop_listen(self.ip, self.port)


class VConn:
    """Connection-style wrapper over a user-space TcpConn."""

    def __init__(self, conn: TcpConn, connected: bool):
        self.conn = conn
        self.connected = connected
        self.handler = None  # object with on_data/on_eof/on_closed/...
        self.closed = False
        conn.set_handler(_Adapter(self))

    @classmethod
    def connect(cls, sw: Switch, vni: int, local_ip: bytes,
                remote_ip: bytes, remote_port: int) -> "VConn":
        net = sw.networks.get(vni)
        if net is None:
            raise OSError(f"no vpc {vni}")
        if net.ips.lookup_mac(local_ip) is None:
            net.ips.add(local_ip, synthetic_mac(vni, local_ip))
        l4 = get_l4(sw)
        conn = l4.connect(net, local_ip, (remote_ip, remote_port))
        return cls(conn, connected=False)

    @property
    def remote(self):
        return self.conn.remote

    @property
    def local(self):
        return self.conn.local

    def set_handler(self, h) -> None:
        self.handler = h

    def write(self, data: bytes) -> None:
        self.conn.write(data)

    @property
    def out(self) -> bytes:
        """Unsent bytes (backpressure signal, like Connection.out)."""
        return self.conn.pending

    def shutdown_write(self) -> None:
        self.conn.shutdown_write()

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.conn.close()

    def abort(self) -> None:
        self.conn.abort()


class _Adapter(TcpHandler):
    def __init__(self, v: VConn):
        self.v = v

    def on_connected(self, conn: TcpConn) -> None:
        self.v.connected = True
        if self.v.handler is not None:
            self.v.handler.on_connected(self.v)

    def on_data(self, conn: TcpConn, data: bytes) -> None:
        if self.v.handler is not None:
            self.v.handler.on_data(self.v, data)

    def on_eof(self, conn: TcpConn) -> None:
        if self.v.handler is not None:
            self.v.handler.on_eof(self.v)

    def on_closed(self, conn: TcpConn) -> None:
        self.v.closed = True
        if self.v.handler is not None:
            self.v.handler.on_closed(self.v, 0)

    def on_drained(self, conn: TcpConn) -> None:
        if self.v.handler is not None:
            self.v.handler.on_drained(self.v)
