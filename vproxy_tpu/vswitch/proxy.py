"""Switch `proxy` resource — in-VPC listener bridged to a host address.

Parity: vswitch/ProxyHolder (reference `add proxy <ip:port> to vpc N in
switch sw address <target>`): the switch listens on ip:port INSIDE the
VPC via the user-space TCP stack and proxies each accepted virtual
connection to a real (host-network) address, so workloads living only
in the overlay can reach services on the host network.

Both ends ride the switch's event loop: the VConn callbacks already
fire there, and the host Connection is created on the same loop, so the
bridge is loop-confined with no locking.

Backpressure: host->VPC pauses the host connection while the user-space
TCP send buffer drains (peer-window pacing). VPC->host has no pause
surface on the user-space conn; bursts are bounded per-RTT by the
advertised 64KB window and the host Connection's MAX_OUT close is the
final safety valve.
"""
from __future__ import annotations

from typing import Optional

from ..net.connection import Connection, Handler
from ..utils.ip import parse_ip
from ..utils.log import Logger
from .fds import VConn, VServerSock
from .switch import Switch

_log = Logger("vpc-proxy")


class VpcProxy:
    def __init__(self, sw: Switch, vni: int, listen_ip: str, listen_port: int,
                 target_ip: str, target_port: int):
        self.sw = sw
        self.vni = vni
        self.listen = (listen_ip, listen_port)
        self.target = (target_ip, target_port)
        self.sessions = 0
        self.accepted = 0
        self.closed = False
        self.sock: VServerSock = sw.loop.call_sync(lambda: VServerSock(
            sw, vni, parse_ip(listen_ip), listen_port, self._on_accept))

    @property
    def alias(self) -> str:
        return f"{self.listen[0]}:{self.listen[1]}"

    def _on_accept(self, vc: VConn) -> None:
        self.accepted += 1
        self.sessions += 1
        proxy = self

        try:
            back = Connection.connect(self.sw.loop, self.target[0],
                                      self.target[1])
        except OSError as e:
            _log.alert(f"vpc-proxy {self.alias}: target connect failed {e!r}")
            self.sessions -= 1
            vc.close()
            return

        done = []

        def teardown() -> None:
            if done:
                return
            done.append(1)
            proxy.sessions -= 1
            vc.close()
            back.close()

        class VSide:
            def on_connected(self, _v) -> None: ...

            def on_drained(self, _v) -> None:
                if not done:
                    back.resume_reading()  # vc send buffer flushed

            def on_data(self, _v, data: bytes) -> None:
                back.write(data)

            def on_eof(self, _v) -> None:
                teardown()

            def on_closed(self, _v, err: int = 0) -> None:
                teardown()

        class HostSide(Handler):
            def on_data(self, c: Connection, data: bytes) -> None:
                vc.write(data)
                if vc.out:  # pace the host to the in-VPC peer's window
                    c.pause_reading()

            def on_eof(self, c: Connection) -> None:
                teardown()

            def on_closed(self, c: Connection, err: int) -> None:
                teardown()

        vc.set_handler(VSide())
        back.set_handler(HostSide())

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.sw.loop.run_on_loop(self.sock.close)

    def detail(self) -> dict:
        return {"name": self.alias, "vni": self.vni,
                "target": f"{self.target[0]}:{self.target[1]}",
                "sessions": self.sessions, "accepted": self.accepted}
