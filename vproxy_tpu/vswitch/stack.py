"""L2/L3 pipeline of the virtual switch.

Parity: core vswitch/stack/L2.java:296 (mac learn / known-unicast
forward / flood) and stack/L3.java:822 (ARP request/reply handling
:119-206, NDP NS/NA :207-327, ICMP echo for synthetic IPs :224-311,
route() :423-517 — synthetic-IP gate, LPM lookup through the VPC's
route matcher, cross-VNI delivery and gateway resolution :573-601).
L4 (user-space TCP) attaches via VpcNetwork.conntrack (stack/L4.java).
"""
from __future__ import annotations

import struct
from typing import Optional

from ..utils.log import Logger
from . import swmetrics
from .network import VpcNetwork
from .packets import (ARP_REPLY, ARP_REQUEST, BROADCAST_MAC, ETHER_TYPE_ARP,
                      ETHER_TYPE_IPV4, ETHER_TYPE_IPV6, ICMP_ECHO_REPLY,
                      ICMP_ECHO_REQ, ICMP_TIME_EXCEEDED, ICMPV6_ECHO_REPLY,
                      ICMPV6_ECHO_REQ, ICMPV6_NDP_NA, ICMPV6_NDP_NS,
                      PROTO_ICMP, PROTO_ICMPV6, PROTO_TCP, Arp, Ethernet,
                      Icmp, Icmpv6, Ipv4, Ipv6, Vxlan)


_log = Logger("vswitch")


def _is_multicast(mac: bytes) -> bool:
    return bool(mac[0] & 1)


class NetworkStack:
    def __init__(self, sw):
        self.sw = sw  # Switch
        self.l4 = None  # installed by stack_tcp (task: user-space TCP)
        # active burst collector: route() appends instead of looking up
        self._route_pend: Optional[list] = None

    # ----------------------------------------------------------------- L2

    def input_vxlan_batch(self, items) -> None:
        """Process a drained burst [(Vxlan, iface)]: L2/ARP/ICMP run per
        packet, but every route-needing packet's LPM lookup is collected
        and classified in ONE matcher dispatch per (vpc, family) — on a
        50k-route device table, per-packet match_one would pay a device
        dispatch each; the burst amortizes it."""
        pend: list = []
        self._route_pend = pend
        try:
            for pkt, iface in items:
                try:
                    self.input_vxlan(pkt, iface)
                except Exception as e:  # one bad frame must not kill the burst
                    _log.warn(f"dropping frame from {iface.name}: {e!r}")
        finally:
            # flush inside finally: already-accepted packets' routes must
            # not be dropped retroactively by a later failure
            self._route_pend = None
            if pend:
                self._route_flush(pend)

    def input_vxlan(self, pkt: Vxlan, src_iface) -> None:
        net = self.sw.networks.get(pkt.vni)
        if net is None:
            swmetrics.drop("unknown_vni")
            return
        ether = pkt.ether
        from ..utils.mirror import Mirror
        mir = Mirror.get()
        # wants() (not just .active/.hot) BEFORE serializing: an ssl-only
        # config must not tax the forwarding path with to_bytes()
        if mir.hot and mir.wants("switch"):
            Mirror.get().mirror("switch", ether.to_bytes(), raw_ether=True)
        if not _is_multicast(ether.src):
            net.macs.record(ether.src, src_iface)
        if _is_multicast(ether.dst):
            self._flood(net, pkt, src_iface)
            self.l3_input(net, ether, src_iface)
            return
        # unicast to a switch-owned (synthetic) mac -> L3
        if net.ips.find_by_mac(ether.dst) is not None:
            self.l3_input(net, ether, src_iface)
            return
        out = net.macs.lookup(ether.dst)
        if out is not None:
            if out is not src_iface:
                out.send_vxlan(self.sw, pkt)
                swmetrics.forward("slow")
            else:
                swmetrics.drop("same_iface")
            return
        self._flood(net, pkt, src_iface)

    def _flood(self, net: VpcNetwork, pkt: Vxlan, src_iface) -> None:
        sent = 0
        for iface in self.sw.ifaces_for_vni(net.vni):
            if iface is not src_iface:
                iface.send_vxlan(self.sw, pkt)
                sent += 1
        swmetrics.forward("slow", sent)

    def send_ether(self, net: VpcNetwork, ether: Ethernet) -> None:
        """Emit a switch-originated frame into the VPC (L2 path)."""
        pkt = Vxlan(net.vni, ether)
        if _is_multicast(ether.dst):
            self._flood(net, pkt, None)
            return
        if net.ips.find_by_mac(ether.dst) is not None:
            # switch-owned destination (e.g. two user-space TCP endpoints
            # inside the same VPC): loop back into L3 on the next tick to
            # keep the stack re-entrancy-free
            self.sw.loop.next_tick(lambda: self.l3_input(net, ether, None))
            return
        out = net.macs.lookup(ether.dst)
        if out is not None:
            out.send_vxlan(self.sw, pkt)
            swmetrics.forward("slow")
        else:
            self._flood(net, pkt, None)

    # ----------------------------------------------------------------- L3

    def l3_input(self, net: VpcNetwork, ether: Ethernet, src_iface) -> None:
        p = ether.packet
        if isinstance(p, Arp):
            self._arp(net, ether, p)
        elif isinstance(p, Ipv4):
            net.arps.record(p.src, ether.src)
            self._ip_input(net, ether, p, v6=False)
        elif isinstance(p, Ipv6):
            if isinstance(p.packet, Icmpv6) and p.packet.type in (
                    ICMPV6_NDP_NS, ICMPV6_NDP_NA):
                self._ndp(net, ether, p, p.packet)
                return
            net.arps.record(p.src, ether.src)
            self._ip_input(net, ether, p, v6=True)

    # --- arp/ndp ---

    def _arp(self, net: VpcNetwork, ether: Ethernet, arp: Arp) -> None:
        net.arps.record(arp.spa, arp.sha)
        if arp.op != ARP_REQUEST:
            return
        mac = net.ips.lookup_mac(arp.tpa)
        if mac is None:
            return
        reply = Ethernet(ether.src, mac, ETHER_TYPE_ARP, b"", Arp(
            ARP_REPLY, sha=mac, spa=arp.tpa, tha=arp.sha, tpa=arp.spa))
        self.send_ether(net, reply)

    def _ndp(self, net: VpcNetwork, ether: Ethernet, ip6: Ipv6,
             icmp: Icmpv6) -> None:
        target = icmp.ndp_target
        lladdr = icmp.ndp_lladdr_option()
        if icmp.type == ICMPV6_NDP_NA and target is not None:
            net.arps.record(target, lladdr or ether.src)
            return
        if icmp.type != ICMPV6_NDP_NS or target is None:
            return
        if lladdr is not None:
            net.arps.record(ip6.src, lladdr)
        mac = net.ips.lookup_mac(target)
        if mac is None:
            return
        # neighbor advertisement: R=0 S=1 O=1, target lladdr option
        body = struct.pack(">I", 0x60000000) + target + b"\x02\x01" + mac
        na = Icmpv6(ICMPV6_NDP_NA, 0, body)
        reply = Ethernet(ether.src, mac, ETHER_TYPE_IPV6, b"", Ipv6(
            src=target, dst=ip6.src, next_header=PROTO_ICMPV6, payload=b"",
            hop_limit=255, packet=na))
        self.send_ether(net, reply)

    # --- ip ---

    def _ip_input(self, net: VpcNetwork, ether: Ethernet, ip, v6: bool) -> None:
        dst = ip.dst
        my_mac = net.ips.lookup_mac(dst)
        if my_mac is not None:
            inner = ip.packet
            if not v6 and isinstance(inner, Icmp) and inner.type == ICMP_ECHO_REQ:
                self._echo_reply(net, ether, ip, inner, v6=False)
                return
            if v6 and isinstance(inner, Icmpv6) and inner.type == ICMPV6_ECHO_REQ:
                self._echo_reply(net, ether, ip, inner, v6=True)
                return
            if ip.proto_num() == PROTO_TCP and self.l4 is not None:
                self.l4.input(net, ether, ip, v6)
                return
            return
        self.route(net, ether, ip, v6)

    def _echo_reply(self, net: VpcNetwork, ether: Ethernet, ip, icmp,
                    v6: bool) -> None:
        if v6:
            resp_icmp = Icmpv6(ICMPV6_ECHO_REPLY, 0, icmp.body)
            resp_ip = Ipv6(src=ip.dst, dst=ip.src, next_header=PROTO_ICMPV6,
                           payload=b"", hop_limit=64, packet=resp_icmp)
            et = ETHER_TYPE_IPV6
        else:
            resp_icmp = Icmp(ICMP_ECHO_REPLY, 0, icmp.body)
            resp_ip = Ipv4(src=ip.dst, dst=ip.src, proto=PROTO_ICMP,
                           payload=b"", packet=resp_icmp)
            et = ETHER_TYPE_IPV4
        mac = net.ips.lookup_mac(ip.dst)
        self.send_ether(net, Ethernet(ether.src, mac, et, b"", resp_ip))

    # --- routing ---

    def route(self, net: VpcNetwork, ether: Ethernet, ip, v6: bool) -> None:
        """L3.route(): LPM through the VPC route matcher; targets are
        another VNI (cross-VPC delivery) or a gateway IP."""
        if self._route_pend is not None:  # burst mode: defer the lookup
            self._route_pend.append((net, ether, ip, v6))
            return
        self._route_with(net, ether, ip, v6, net.route_lookup(ip.dst))

    def _route_flush(self, pend: list) -> None:
        groups: dict[int, list[int]] = {}
        nets: dict[int, VpcNetwork] = {}
        for i, (net, _e, _ip, _v) in enumerate(pend):
            groups.setdefault(id(net), []).append(i)
            nets[id(net)] = net
        for key, idxs in groups.items():
            net = nets[key]
            rules = net.route_lookup_batch([pend[i][2].dst for i in idxs])
            for i, rule in zip(idxs, rules):
                n_, e_, ip_, v6_ = pend[i]
                self._route_with(n_, e_, ip_, v6_, rule)

    def _route_with(self, net: VpcNetwork, ether: Ethernet, ip, v6: bool,
                    rule) -> None:
        if rule is None:
            swmetrics.drop("route_miss")
            return
        # ttl/hop-limit handling
        if v6:
            if ip.hop_limit <= 1:
                return
            ip.hop_limit -= 1
        else:
            if ip.ttl <= 1:
                self._time_exceeded(net, ether, ip)
                return
            ip.ttl -= 1
        if rule.to_vni:
            target = self.sw.networks.get(rule.to_vni)
            if target is None:
                swmetrics.drop("unknown_vni")
                return
            self._deliver(target, ip, v6)
            return
        if rule.via_ip is not None:
            gw_mac = net.arps.lookup(rule.via_ip)
            src = net.ips.first_in(net.v6net if v6 and net.v6net else net.v4net)
            if gw_mac is None:
                swmetrics.drop("arp_unresolved")
                if src is not None and not v6:
                    self._arp_request(net, src[1], src[0], rule.via_ip)
                return
            src_mac = src[1] if src is not None else ether.dst
            out = Ethernet(gw_mac, src_mac,
                           ETHER_TYPE_IPV6 if v6 else ETHER_TYPE_IPV4, b"", ip)
            self.send_ether(net, out)

    def _deliver(self, net: VpcNetwork, ip, v6: bool) -> None:
        """Deliver a routed packet inside `net`: resolve the target mac,
        source mac is a synthetic ip in that network."""
        dst_mac = net.arps.lookup(ip.dst)
        src = net.ips.first_in(net.v6net if v6 and net.v6net else net.v4net)
        src_mac = src[1] if src is not None else b"\x02\x00\x00\x00\x00\x01"
        if dst_mac is None:
            swmetrics.drop("arp_unresolved")
            if not v6 and src is not None:
                self._arp_request(net, src[1], src[0], ip.dst)
            return
        out = Ethernet(dst_mac, src_mac,
                       ETHER_TYPE_IPV6 if v6 else ETHER_TYPE_IPV4, b"", ip)
        self.send_ether(net, out)

    def _arp_request(self, net: VpcNetwork, src_mac: bytes, src_ip: bytes,
                     target_ip: bytes) -> None:
        req = Ethernet(BROADCAST_MAC, src_mac, ETHER_TYPE_ARP, b"", Arp(
            ARP_REQUEST, sha=src_mac, spa=src_ip,
            tha=b"\x00" * 6, tpa=target_ip))
        self.send_ether(net, req)

    def _time_exceeded(self, net: VpcNetwork, ether: Ethernet, ip) -> None:
        src = net.ips.first_in(net.v4net)
        if src is None:
            return
        body = b"\x00" * 4 + ip.to_bytes()[:28]
        icmp = Icmp(ICMP_TIME_EXCEEDED, 0, body[4:])
        resp = Ipv4(src=src[0], dst=ip.src, proto=PROTO_ICMP, payload=b"",
                    packet=icmp)
        self.send_ether(net, Ethernet(ether.src, src[1], ETHER_TYPE_IPV4,
                                      b"", resp))
