"""Switch ifaces — how VXLAN frames enter and leave the switch.

Parity: core vswitch/iface/* — `Iface` SPI; `BareVXLanIface` (plain
VXLAN peer), `RemoteSwitchIface` (switch-to-switch link),
`UserIface`/`UserClientIface` (encrypted tunnel with per-user AES-256
key and ping keepalive, VProxyEncryptedPacket), `TapIface` (OS tap via
/dev/net/tun ioctl — the FDsWithTap/JNI path done with fcntl, no JNI
needed on linux).
"""
from __future__ import annotations

import fcntl
import os
import struct
from typing import Optional

from ..net import vtl
from .packets import (VPROXY_TYPE_PING, VPROXY_TYPE_VXLAN, Ethernet,
                      PacketError, VProxySwitchPacket, Vxlan)


class Iface:
    """send_vxlan delivers an encapsulated frame out this iface; `vni`
    restriction 0 means untagged (use packet vni)."""

    name: str = ""
    local_side_vni: int = 0  # forced vni for frames entering via this iface

    def send_vxlan(self, sw, pkt: Vxlan) -> None:
        raise NotImplementedError

    # send_vxlan_raw(sw, data) — OPTIONAL: emit an already-serialized
    # vxlan datagram without re-parsing (the burst fast path's egress,
    # vswitch/fastpath.py). Ifaces that must transform the frame
    # (encrypting user tunnels) simply don't define it and the fast
    # path routes their traffic through the object pipeline.

    def close(self) -> None: ...


class BareVXLanIface(Iface):
    """A plain VXLAN endpoint (e.g. a hypervisor VTEP) at ip:port."""

    def __init__(self, remote_ip: str, remote_port: int):
        self.remote = (remote_ip, remote_port)
        self.name = f"bare-vxlan:{remote_ip}:{remote_port}"

    def send_vxlan(self, sw, pkt: Vxlan) -> None:
        sw.send_udp(pkt.to_bytes(), self.remote)

    def send_vxlan_raw(self, sw, data: bytes) -> None:
        sw.send_udp(data, self.remote)

    def send_vxlan_raw_many(self, sw, datas: list) -> None:
        sw.send_udp_many(datas, self.remote)


class RemoteSwitchIface(Iface):
    """Link to another vproxy-style switch (plain VXLAN, any vni)."""

    def __init__(self, alias: str, remote_ip: str, remote_port: int,
                 add_switch_flag: bool = True):
        self.alias = alias
        self.remote = (remote_ip, remote_port)
        self.name = f"remote:{alias}"

    def send_vxlan(self, sw, pkt: Vxlan) -> None:
        sw.send_udp(pkt.to_bytes(), self.remote)

    def send_vxlan_raw(self, sw, data: bytes) -> None:
        sw.send_udp(data, self.remote)

    def send_vxlan_raw_many(self, sw, datas: list) -> None:
        sw.send_udp_many(datas, self.remote)


class UserIface(Iface):
    """Server side of an encrypted user tunnel: a remote client
    authenticated as `user`; frames are AES-256-CFB encrypted switch
    packets; the client's vni is forced to the user's assigned vni."""

    def __init__(self, user: str, remote: tuple[str, int], vni: int):
        from .switch import display_user_name  # call-time: import cycle
        self.user = user  # wire form ('+'-padded to 8)
        self.remote = remote
        self.local_side_vni = vni
        self.name = f"user:{display_user_name(user)}"

    def send_vxlan(self, sw, pkt: Vxlan) -> None:
        p = VProxySwitchPacket(self.user, VPROXY_TYPE_VXLAN, pkt)
        sw.send_udp(p.to_bytes(sw.key_for_user), self.remote)

    def send_ping(self, sw) -> None:
        p = VProxySwitchPacket(self.user, VPROXY_TYPE_PING, None)
        sw.send_udp(p.to_bytes(sw.key_for_user), self.remote)


class UserClientIface(Iface):
    """Client side of an encrypted user tunnel: dials a remote switch and
    keeps the link alive with periodic pings (UserClientIface.java)."""

    PING_PERIOD_MS = 20_000

    def __init__(self, user: str, key: bytes, remote_ip: str, remote_port: int):
        from .switch import display_user_name  # call-time: import cycle
        self.user = user  # wire form ('+'-padded to 8)
        self.key = key
        self.remote = (remote_ip, remote_port)
        self.name = f"ucli:{display_user_name(user)}"
        self._periodic = None

    def attach(self, sw) -> None:
        self._periodic = sw.loop.period(self.PING_PERIOD_MS,
                                        lambda: self.send_ping(sw))
        self.send_ping(sw)

    def key_for(self, user: str) -> Optional[bytes]:
        return self.key if user == self.user else None

    def send_vxlan(self, sw, pkt: Vxlan) -> None:
        p = VProxySwitchPacket(self.user, VPROXY_TYPE_VXLAN, pkt)
        sw.send_udp(p.to_bytes(self.key_for), self.remote)

    def send_ping(self, sw) -> None:
        p = VProxySwitchPacket(self.user, VPROXY_TYPE_PING, None)
        sw.send_udp(p.to_bytes(self.key_for), self.remote)

    def close(self) -> None:
        if self._periodic is not None:
            self._periodic.cancel()


# --------------------------------------------------------------------- tap

TUNSETIFF = 0x400454CA
IFF_TAP = 0x0002
IFF_NO_PI = 0x1000


class TapIface(Iface):
    """OS tap device bridged into a VPC: raw ethernet frames from the
    kernel enter the switch tagged with `vni` (TapIface.java +
    vfd_posix createTapFD :766). Requires /dev/net/tun access (root)."""

    post_script: Optional[str] = None

    def __init__(self, pattern: str, vni: int, loop, on_frame,
                 annotations: Optional[dict] = None):
        """on_frame(tap_iface, Ethernet) delivers inbound frames."""
        self.local_side_vni = vni
        self.annotations: dict = annotations or {}
        self.fd = os.open("/dev/net/tun", os.O_RDWR | os.O_NONBLOCK)
        ifr = struct.pack("16sH", pattern.encode(), IFF_TAP | IFF_NO_PI)
        out = fcntl.ioctl(self.fd, TUNSETIFF, ifr)
        self.dev = out[:16].rstrip(b"\x00").decode()
        self.name = f"tap:{self.dev}"
        self.loop = loop
        self.on_frame = on_frame
        loop.add(self.fd, vtl.EV_READ, self._readable)

    def _readable(self, fd: int, ev: int) -> None:
        while True:
            try:
                data = os.read(self.fd, 65536)
            except BlockingIOError:
                return
            except OSError:
                return
            if not data:
                return
            try:
                ether = Ethernet.parse(data)
            except PacketError:
                continue
            self.on_frame(self, ether)

    def send_vxlan(self, sw, pkt: Vxlan) -> None:
        try:
            os.write(self.fd, pkt.ether.to_bytes())
        except OSError:
            pass

    def send_vxlan_raw(self, sw, data: bytes) -> None:
        try:
            os.write(self.fd, data[8:])  # strip the vxlan header
        except OSError:
            pass

    def close(self) -> None:
        try:
            self.loop.remove(self.fd)
        except Exception:
            pass
        try:
            os.close(self.fd)
        except OSError:
            pass


def tap_supported() -> bool:
    return os.path.exists("/dev/net/tun") and os.access("/dev/net/tun", os.W_OK)
