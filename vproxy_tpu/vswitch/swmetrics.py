"""Labeled switch data-plane counters.

The switch consumes frames at six distinct points (ACL deny, unknown
VNI, route miss, same-iface suppression, egress backpressure) with —
until now — zero accounting, which is how a 68% drop rate stays a
mystery. Every consumed frame increments
`vproxy_switch_drops_total{reason=...}`; frames demoted from the
vectorized fast path to the object pipeline increment
`vproxy_switch_slowpath_total{reason=...}` (not drops — they are still
forwarded); egressed datagrams land in
`vproxy_switch_forwards_total{path=fast|slow}` and drained ones in
`vproxy_switch_rx_total`, so drop RATE is computable from /metrics
alone.

Counters are process-global (utils/metrics GlobalInspection) with a
module-local memo so the hot path pays one dict hit, no lock.
"""
from __future__ import annotations

from ..utils.metrics import Counter, GlobalInspection

_memo: dict = {}


def _ctr(name: str, **labels) -> Counter:
    key = (name, tuple(sorted(labels.items())))
    c = _memo.get(key)
    if c is None:
        c = _memo[key] = GlobalInspection.get().get_counter(name, **labels)
    return c


def drop(reason: str, n: int = 1) -> None:
    if n > 0:
        _ctr("vproxy_switch_drops_total", reason=reason).incr(n)


def slowpath(reason: str, n: int = 1) -> None:
    if n > 0:
        _ctr("vproxy_switch_slowpath_total", reason=reason).incr(n)


def forward(path: str, n: int = 1) -> None:
    if n > 0:
        _ctr("vproxy_switch_forwards_total", path=path).incr(n)


def rx(n: int) -> None:
    if n > 0:
        _ctr("vproxy_switch_rx_total").incr(n)


# Pre-register the full reason/path vocabularies at import (the PR-9
# silent-drops rule, enforced by tools/vlint's registry audit): a
# scrape of a freshly-booted switch must show the ZEROS, so dashboards
# can tell "no drops" from "drop counter not wired". Adding a new
# reason literal at a call site without extending these tuples is a
# vlint finding by construction — the audit's eager set is what a
# fresh import of this module registers.
DROP_REASONS = ("acl_deny", "arp_unresolved", "egress_short_write",
                "route_miss", "same_iface", "unknown_vni")
SLOWPATH_REASONS = ("bad_csum",)
FORWARD_PATHS = ("fast", "slow")
for _r in DROP_REASONS:
    _ctr("vproxy_switch_drops_total", reason=_r)
for _r in SLOWPATH_REASONS:
    _ctr("vproxy_switch_slowpath_total", reason=_r)
for _p in FORWARD_PATHS:
    _ctr("vproxy_switch_forwards_total", path=_p)
_ctr("vproxy_switch_rx_total")
