"""Labeled switch data-plane counters.

The switch consumes frames at six distinct points (ACL deny, unknown
VNI, route miss, same-iface suppression, egress backpressure) with —
until now — zero accounting, which is how a 68% drop rate stays a
mystery. Every consumed frame increments
`vproxy_switch_drops_total{reason=...}`; frames demoted from the
vectorized fast path to the object pipeline increment
`vproxy_switch_slowpath_total{reason=...}` (not drops — they are still
forwarded); egressed datagrams land in
`vproxy_switch_forwards_total{path=fast|slow}` and drained ones in
`vproxy_switch_rx_total`, so drop RATE is computable from /metrics
alone.

Counters are process-global (utils/metrics GlobalInspection) with a
module-local memo so the hot path pays one dict hit, no lock.
"""
from __future__ import annotations

from ..utils.metrics import Counter, GlobalInspection

_memo: dict = {}


def _ctr(name: str, **labels) -> Counter:
    key = (name, tuple(sorted(labels.items())))
    c = _memo.get(key)
    if c is None:
        c = _memo[key] = GlobalInspection.get().get_counter(name, **labels)
    return c


def drop(reason: str, n: int = 1) -> None:
    if n > 0:
        _ctr("vproxy_switch_drops_total", reason=reason).incr(n)


def slowpath(reason: str, n: int = 1) -> None:
    if n > 0:
        _ctr("vproxy_switch_slowpath_total", reason=reason).incr(n)


def forward(path: str, n: int = 1) -> None:
    if n > 0:
        _ctr("vproxy_switch_forwards_total", path=path).incr(n)


def rx(n: int) -> None:
    if n > 0:
        _ctr("vproxy_switch_rx_total").incr(n)
