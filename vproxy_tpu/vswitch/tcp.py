"""User-space TCP inside the virtual network.

Parity: base vpacket/conntrack (Conntrack.java:12 lookup/listen/create,
tcp/TcpEntry.java:443 per-connection seq/ack state machine with send/
recv queues and SYN backlog, tcp/TcpState) driven by core
stack/L4.java:544 (input dispatch: established lookup -> listen backlog
-> RST :25-90; ack + retransmission timers :408-517). Segments enter
from the L3 stack and leave through stack.send_ether; endpoints are
exposed to applications via fds.py (the stack/fd VSwitchFD analog).
"""
from __future__ import annotations

import os
import struct
from collections import deque
from typing import Callable, Optional

from .packets import (ETHER_TYPE_IPV4, ETHER_TYPE_IPV6, PROTO_TCP, TCP_ACK,
                      TCP_FIN, TCP_PSH, TCP_RST, TCP_SYN, Ethernet, Ipv4,
                      Ipv6, Tcp)

MAX_SYN_BACKLOG = 128  # ListenEntry.MAX_SYN_BACKLOG_SIZE
RTO_MS = 400
MAX_RETRIES = 8
TIME_WAIT_MS = 5_000
MSS = 1360
WINDOW = 65535  # no window scaling: the 16-bit field is the whole window


def _seq_lt(a: int, b: int) -> bool:
    return ((a - b) & 0xFFFFFFFF) > 0x7FFFFFFF


def _seq_add(a: int, n: int) -> int:
    return (a + n) & 0xFFFFFFFF


# TcpState (tcp/TcpState.java)
CLOSED, LISTEN, SYN_SENT, SYN_RECEIVED, ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2, \
    CLOSING, CLOSE_WAIT, LAST_ACK, TIME_WAIT = range(11)


class ListenEntry:
    def __init__(self, local: tuple[bytes, int],
                 on_accept: Callable[["TcpConn"], None]):
        self.local = local  # (ip, port); ip may be None for any
        self.on_accept = on_accept
        self.syn_backlog: dict = {}  # conn key -> TcpConn in SYN_RECEIVED


class TcpHandler:
    def on_connected(self, conn: "TcpConn") -> None: ...

    def on_data(self, conn: "TcpConn", data: bytes) -> None: ...

    def on_eof(self, conn: "TcpConn") -> None: ...

    def on_closed(self, conn: "TcpConn") -> None: ...

    def on_drained(self, conn: "TcpConn") -> None: ...


class _Seg:
    __slots__ = ("seq", "data", "flags", "retries")

    def __init__(self, seq: int, data: bytes, flags: int):
        self.seq = seq
        self.data = data
        self.flags = flags
        self.retries = 0

    def length(self) -> int:
        n = len(self.data)
        if self.flags & (TCP_SYN | TCP_FIN):
            n += 1
        return n


class TcpConn:
    def __init__(self, l4: "L4", net, local: tuple[bytes, int],
                 remote: tuple[bytes, int], state: int):
        self.l4 = l4
        self.net = net
        self.local = local
        self.remote = remote
        self.state = state
        self.handler: TcpHandler = TcpHandler()
        iss = struct.unpack(">I", os.urandom(4))[0]
        self.snd_una = iss  # oldest unacked
        self.snd_nxt = iss
        self.rcv_nxt = 0
        self.snd_wnd = MSS  # peer window (learned from segments)
        self.mss = MSS
        self.rtx: deque[_Seg] = deque()  # sent, unacked
        self.pending = bytearray()  # app bytes not yet segmented
        self.fin_queued = False
        self.fin_sent = False
        self.closed = False
        self._timer = None
        self.bytes_in = 0
        self.bytes_out = 0

    @property
    def key(self):
        return (self.remote[0], self.remote[1], self.local[0], self.local[1])

    # ----------------------------------------------------------- app side

    def set_handler(self, h: TcpHandler) -> None:
        self.handler = h

    def write(self, data: bytes) -> None:
        if self.closed or self.fin_queued:
            return
        self.pending += data
        self._push()

    def shutdown_write(self) -> None:
        """Queue FIN after pending data (active close, half-close ok)."""
        if self.closed or self.fin_queued:
            return
        self.fin_queued = True
        self._push()

    def close(self) -> None:
        if self.state in (ESTABLISHED, SYN_RECEIVED):
            self.state = FIN_WAIT_1
            self.shutdown_write()
        elif self.state == CLOSE_WAIT:
            self.state = LAST_ACK
            self.shutdown_write()
        else:
            self.abort()

    def abort(self) -> None:
        if not self.closed:
            self._emit(TCP_RST | TCP_ACK, self.snd_nxt, self.rcv_nxt, b"")
            self._dead()

    # --------------------------------------------------------- tcp engine

    def _push(self) -> None:
        """Segment pending bytes within the peer's window and send."""
        if self.state not in (ESTABLISHED, CLOSE_WAIT, FIN_WAIT_1, LAST_ACK):
            return
        in_flight = (self.snd_nxt - self.snd_una) & 0xFFFFFFFF
        budget = max(0, self.snd_wnd - in_flight)
        while self.pending and budget > 0:
            chunk = bytes(self.pending[:min(self.mss, budget)])
            del self.pending[:len(chunk)]
            budget -= len(chunk)
            seg = _Seg(self.snd_nxt, chunk, TCP_ACK | TCP_PSH)
            self.rtx.append(seg)
            self.snd_nxt = _seq_add(self.snd_nxt, len(chunk))
            self._emit(seg.flags, seg.seq, self.rcv_nxt, chunk)
        if self.fin_queued and not self.pending and not self.fin_sent:
            seg = _Seg(self.snd_nxt, b"", TCP_FIN | TCP_ACK)
            self.rtx.append(seg)
            self.snd_nxt = _seq_add(self.snd_nxt, 1)
            self.fin_sent = True
            self._emit(seg.flags, seg.seq, self.rcv_nxt, b"")
        self._arm_timer()

    def send_syn(self) -> None:
        seg = _Seg(self.snd_nxt, b"", TCP_SYN)
        self.rtx.append(seg)
        self.snd_nxt = _seq_add(self.snd_nxt, 1)
        self.state = SYN_SENT
        self._emit(TCP_SYN, seg.seq, 0, b"",
                   options=struct.pack(">BBH", 2, 4, self.mss))
        self._arm_timer()

    def _send_syn_ack(self) -> None:
        seg = _Seg(self.snd_nxt, b"", TCP_SYN | TCP_ACK)
        self.rtx.append(seg)
        self.snd_nxt = _seq_add(self.snd_nxt, 1)
        self._emit(TCP_SYN | TCP_ACK, seg.seq, self.rcv_nxt, b"",
                   options=struct.pack(">BBH", 2, 4, self.mss))
        self._arm_timer()

    def segment(self, tcp: Tcp) -> None:
        """One inbound segment for this connection (L4.input)."""
        if self.closed:
            return
        if tcp.flags & TCP_RST:
            self._dead()
            return
        self.snd_wnd = max(tcp.window, 1)

        if self.state == SYN_SENT:
            if tcp.flags & TCP_SYN and tcp.flags & TCP_ACK:
                if tcp.ack != self.snd_nxt:
                    self.abort()
                    return
                self.rcv_nxt = _seq_add(tcp.seq, 1)
                self._acked(tcp.ack)
                self.state = ESTABLISHED
                mss = tcp.mss_option()
                if mss:
                    self.mss = min(self.mss, mss)
                self._emit(TCP_ACK, self.snd_nxt, self.rcv_nxt, b"")
                self.handler.on_connected(self)
                self._push()
            return

        if self.state == SYN_RECEIVED:
            if tcp.flags & TCP_ACK and tcp.ack == self.snd_nxt:
                self._acked(tcp.ack)
                self.state = ESTABLISHED
                self.l4.established(self)
            # fall through: first ACK may carry data

        if tcp.flags & TCP_ACK:
            self._acked(tcp.ack)

        # --- receive data ---
        data = tcp.data
        if data:
            if tcp.seq == self.rcv_nxt:
                self.rcv_nxt = _seq_add(self.rcv_nxt, len(data))
                self.bytes_in += len(data)
                self._emit(TCP_ACK, self.snd_nxt, self.rcv_nxt, b"")
                self.handler.on_data(self, data)
            else:
                # out-of-order or retransmission: re-ack what we have
                self._emit(TCP_ACK, self.snd_nxt, self.rcv_nxt, b"")
                return
        if tcp.flags & TCP_FIN:
            expected = _seq_add(tcp.seq, len(data))
            if expected != self.rcv_nxt and tcp.seq != self.rcv_nxt:
                return
            self.rcv_nxt = _seq_add(self.rcv_nxt, 1)
            self._emit(TCP_ACK, self.snd_nxt, self.rcv_nxt, b"")
            if self.state == ESTABLISHED:
                self.state = CLOSE_WAIT
                self.handler.on_eof(self)
            elif self.state == FIN_WAIT_1:
                self.state = CLOSING if self.rtx else TIME_WAIT
                self.handler.on_eof(self)
                self._maybe_time_wait()
            elif self.state == FIN_WAIT_2:
                self.state = TIME_WAIT
                self.handler.on_eof(self)
                self._maybe_time_wait()

    def _acked(self, ack: int) -> None:
        progressed = False
        while self.rtx:
            seg = self.rtx[0]
            end = _seq_add(seg.seq, seg.length())
            if _seq_lt(ack, end):
                break
            self.rtx.popleft()
            progressed = True
        if _seq_lt(self.snd_una, ack):
            self.snd_una = ack
        if progressed:
            self._arm_timer()
            self._push()
            if not self.rtx and not self.pending:
                if self.state == FIN_WAIT_1 and self.fin_sent:
                    self.state = FIN_WAIT_2
                elif self.state == CLOSING:
                    self.state = TIME_WAIT
                    self._maybe_time_wait()
                elif self.state == LAST_ACK and self.fin_sent:
                    self._dead()
                    return
                if not self.fin_queued:
                    self.handler.on_drained(self)

    def _maybe_time_wait(self) -> None:
        if self.state == TIME_WAIT:
            self._cancel_timer()
            self.l4.loop.delay(TIME_WAIT_MS, self._dead)

    # ------------------------------------------------------------- timers

    def _arm_timer(self) -> None:
        self._cancel_timer()
        if self.rtx and not self.closed:
            self._timer = self.l4.loop.delay(RTO_MS, self._retransmit)

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _retransmit(self) -> None:
        if self.closed or not self.rtx:
            return
        seg = self.rtx[0]
        seg.retries += 1
        if seg.retries > MAX_RETRIES:
            self.abort()
            return
        opts = b""
        if seg.flags & TCP_SYN:
            opts = struct.pack(">BBH", 2, 4, self.mss)
        self._emit(seg.flags, seg.seq, self.rcv_nxt if seg.flags & TCP_ACK
                   else 0, seg.data, options=opts)
        self._timer = self.l4.loop.delay(
            min(RTO_MS * (1 << seg.retries), 6000), self._retransmit)

    # -------------------------------------------------------------- wire

    def _emit(self, flags: int, seq: int, ack: int, data: bytes,
              options: bytes = b"") -> None:
        if data:
            self.bytes_out += len(data)
        self.l4.emit(self.net, self.local, self.remote, flags, seq, ack,
                     data, options)

    def _dead(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._cancel_timer()
        self.state = CLOSED
        self.l4.conn_closed(self)
        self.handler.on_closed(self)


class Conntrack:
    """Listen table + connection table for one VPC (Conntrack.java:45-91)."""

    def __init__(self):
        self.listens: dict[tuple[Optional[bytes], int], ListenEntry] = {}
        self.conns: dict = {}  # (rip, rport, lip, lport) -> TcpConn

    def listen(self, ip: Optional[bytes], port: int,
               on_accept) -> ListenEntry:
        key = (ip, port)
        if key in self.listens:
            raise OSError(f"port {port} already listening")
        le = ListenEntry((ip, port), on_accept)
        self.listens[key] = le
        return le

    def stop_listen(self, ip: Optional[bytes], port: int) -> None:
        self.listens.pop((ip, port), None)

    def lookup(self, rip: bytes, rport: int, lip: bytes, lport: int):
        return self.conns.get((rip, rport, lip, lport))

    def lookup_listen(self, lip: bytes, lport: int) -> Optional[ListenEntry]:
        le = self.listens.get((lip, lport))
        if le is None:
            le = self.listens.get((None, lport))
        return le


class L4:
    """The TCP dispatch attached to a switch's NetworkStack
    (stack/L4.java:25-90)."""

    def __init__(self, sw):
        self.sw = sw
        self.loop = sw.loop
        sw.stack.l4 = self

    def conntrack(self, net) -> Conntrack:
        if net.conntrack is None:
            net.conntrack = Conntrack()
        return net.conntrack

    # ---------------------------------------------------------- dispatch

    def input(self, net, ether: Ethernet, ip, v6: bool) -> None:
        tcp = ip.packet
        if not isinstance(tcp, Tcp):
            return
        ct = self.conntrack(net)
        conn = ct.lookup(ip.src, tcp.sport, ip.dst, tcp.dport)
        if conn is not None:
            conn.segment(tcp)
            return
        le = ct.lookup_listen(ip.dst, tcp.dport)
        if le is not None and tcp.flags & TCP_SYN and not tcp.flags & TCP_ACK:
            if len(le.syn_backlog) >= MAX_SYN_BACKLOG:
                return
            conn = TcpConn(self, net, (ip.dst, tcp.dport),
                           (ip.src, tcp.sport), SYN_RECEIVED)
            conn.rcv_nxt = _seq_add(tcp.seq, 1)
            mss = tcp.mss_option()
            if mss:
                conn.mss = min(conn.mss, mss)
            ct.conns[conn.key] = conn
            le.syn_backlog[conn.key] = conn
            conn._send_syn_ack()
            return
        if not tcp.flags & TCP_RST:
            # no matching conn/listen: RST (L4.java:80-90)
            self.emit(net, (ip.dst, tcp.dport), (ip.src, tcp.sport),
                      TCP_RST | TCP_ACK, 0,
                      _seq_add(tcp.seq, len(tcp.data) + 1), b"")

    def established(self, conn: TcpConn) -> None:
        """SYN_RECEIVED -> ESTABLISHED: move from backlog to accept."""
        ct = self.conntrack(conn.net)
        le = ct.lookup_listen(conn.local[0], conn.local[1])
        if le is not None and conn.key in le.syn_backlog:
            del le.syn_backlog[conn.key]
            le.on_accept(conn)

    def connect(self, net, local_ip: bytes, remote: tuple[bytes, int],
                local_port: int = 0) -> TcpConn:
        ct = self.conntrack(net)
        if not local_port:
            for _ in range(64):
                local_port = 20000 + struct.unpack(">H", os.urandom(2))[0] % 40000
                if (remote[0], remote[1], local_ip, local_port) not in ct.conns:
                    break
        conn = TcpConn(self, net, (local_ip, local_port), remote, CLOSED)
        ct.conns[conn.key] = conn
        conn.send_syn()
        return conn

    def conn_closed(self, conn: TcpConn) -> None:
        ct = self.conntrack(conn.net)
        ct.conns.pop(conn.key, None)
        le = ct.lookup_listen(conn.local[0], conn.local[1])
        if le is not None:
            le.syn_backlog.pop(conn.key, None)

    # -------------------------------------------------------------- wire

    def emit(self, net, local: tuple[bytes, int], remote: tuple[bytes, int],
             flags: int, seq: int, ack: int, data: bytes,
             options: bytes = b"") -> None:
        tcp = Tcp(local[1], remote[1], seq, ack, flags, WINDOW, data, options)
        v6 = len(local[0]) == 16
        if v6:
            pkt = Ipv6(local[0], remote[0], PROTO_TCP, b"", packet=tcp)
            et = ETHER_TYPE_IPV6
        else:
            pkt = Ipv4(local[0], remote[0], PROTO_TCP, b"", packet=tcp)
            et = ETHER_TYPE_IPV4
        src_mac = net.ips.lookup_mac(local[0]) or b"\x02\x00\x00\x00\x00\x02"
        dst_mac = net.ips.lookup_mac(remote[0]) or net.arps.lookup(remote[0])
        if dst_mac is None:
            # trigger resolution; handshake retransmit will retry
            src = net.ips.first_in(net.v4net)
            if src is not None and not v6:
                self.sw.stack._arp_request(net, src[1], src[0], remote[0])
            return
        self.sw.stack.send_ether(net, Ethernet(dst_mac, src_mac, et, b"", pkt))
