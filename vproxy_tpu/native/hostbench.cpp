// hostbench — epoll HTTP/1.1 load tool for the host-path req/s bench.
//
// The framework's TcpLB data path is the native splice pump
// (vtl.cpp:342-537); measuring it through Python clients would measure
// the GIL instead. This file provides the two native endpoints of the
// harness (bench_host.py owns orchestration):
//
//   hostbench server <port>
//       single-thread epoll HTTP server: reads until CRLFCRLF, writes a
//       fixed keep-alive response (RESP below, constant byte length).
//   hostbench client <ip> <port> <conns> <seconds> <pipeline>
//       opens <conns> keep-alive connections, keeps <pipeline> requests
//       in flight on each, counts completed responses by exact byte
//       framing. Prints one JSON line on stdout when done.
//
// Both sides keep a per-connection out-buffer and flush via EPOLLOUT —
// an EAGAIN/partial write must never drop bytes, or conns deadlock
// under the LB's splice backpressure.
//
// Analog of the reference's wrk/bench.md harness
// (benchmark/report/2019/06/05/bench.md:17-19) rebuilt self-contained.
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <string>

static const char RESP[] =
    "HTTP/1.1 200 OK\r\n"
    "Content-Length: 13\r\n"
    "Connection: keep-alive\r\n"
    "\r\n"
    "hello, world\n";
static const size_t RESP_LEN = sizeof(RESP) - 1;

static const char REQ[] =
    "GET / HTTP/1.1\r\n"
    "Host: bench.example.com\r\n"
    "Connection: keep-alive\r\n"
    "\r\n";
static const size_t REQ_LEN = sizeof(REQ) - 1;

static const int MAXFD = 65536;

static int set_nonblock(int fd) {
    int fl = fcntl(fd, F_GETFL, 0);
    return fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

static double now_s() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

struct Conn {
    std::string out;     // unsent bytes
    size_t inflight = 0; // client: requests awaiting a response
    size_t rxbytes = 0;  // client: bytes of current response received
    size_t reqpos = 0;   // server: progress through "\r\n\r\n"
    bool want_out = false;
};

static Conn conns[MAXFD];

// flush c.out; keeps EPOLLIN|EPOLLOUT registration in sync. Returns
// false if the connection died.
static bool flush_out(int ep, int fd, Conn &c) {
    while (!c.out.empty()) {
        ssize_t w = write(fd, c.out.data(), c.out.size());
        if (w > 0) {
            c.out.erase(0, (size_t)w);
            continue;
        }
        if (errno == EAGAIN || errno == EINTR) break;
        return false;
    }
    bool want = !c.out.empty();
    if (want != c.want_out) {
        c.want_out = want;
        epoll_event ev{};
        ev.events = EPOLLIN | (want ? EPOLLOUT : 0);
        ev.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
    }
    return true;
}

static void drop(int ep, int fd) {
    epoll_ctl(ep, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    conns[fd] = Conn{};
}

// --------------------------------------------------------------- server

static int run_server(int port) {
    signal(SIGPIPE, SIG_IGN);
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sa.sin_port = htons((uint16_t)port);
    if (bind(lfd, (sockaddr *)&sa, sizeof(sa)) != 0 || listen(lfd, 1024) != 0) {
        perror("bind/listen");
        return 1;
    }
    set_nonblock(lfd);
    socklen_t slen = sizeof(sa);
    getsockname(lfd, (sockaddr *)&sa, &slen);
    printf("{\"listening\": %d}\n", ntohs(sa.sin_port));
    fflush(stdout);

    int ep = epoll_create1(0);
    epoll_event ev{}, evs[256];
    ev.events = EPOLLIN;
    ev.data.fd = lfd;
    epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);
    char buf[65536];

    for (;;) {
        int n = epoll_wait(ep, evs, 256, 1000);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            if (fd == lfd) {
                for (;;) {
                    int cfd = accept(lfd, nullptr, nullptr);
                    if (cfd < 0) break;
                    if (cfd >= MAXFD) { close(cfd); continue; }
                    set_nonblock(cfd);
                    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
                    conns[cfd] = Conn{};
                    epoll_event ce{};
                    ce.events = EPOLLIN;
                    ce.data.fd = cfd;
                    epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &ce);
                }
                continue;
            }
            Conn &c = conns[fd];
            if (evs[i].events & EPOLLOUT) {
                if (!flush_out(ep, fd, c)) { drop(ep, fd); continue; }
            }
            if (!(evs[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)))
                continue;
            ssize_t r = read(fd, buf, sizeof(buf));
            if (r == 0 || (r < 0 && errno != EAGAIN && errno != EINTR)) {
                drop(ep, fd);
                continue;
            }
            if (r < 0) continue;
            static const char T[] = "\r\n\r\n";
            for (ssize_t j = 0; j < r; j++) {
                if (buf[j] == T[c.reqpos]) {
                    if (++c.reqpos == 4) {
                        c.out.append(RESP, RESP_LEN);
                        c.reqpos = 0;
                    }
                } else {
                    c.reqpos = (buf[j] == '\r') ? 1 : 0;
                }
            }
            if (!flush_out(ep, fd, c)) drop(ep, fd);
        }
    }
    return 0;
}

// --------------------------------------------------------------- client

static int run_client(const char *ip, int port, int nconn, double secs,
                      int pipeline) {
    signal(SIGPIPE, SIG_IGN);
    int ep = epoll_create1(0);
    long long done = 0, errors = 0;
    int one = 1;
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, ip, &sa.sin_addr);

    for (int i = 0; i < nconn; i++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0 || fd >= MAXFD) {  // same bound guard as the server
            if (fd >= 0) close(fd);
            errors++;
            continue;
        }
        if (connect(fd, (sockaddr *)&sa, sizeof(sa)) != 0) {
            fprintf(stderr, "connect: %s\n", strerror(errno));
            close(fd);
            errors++;
            continue;
        }
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        set_nonblock(fd);
        conns[fd] = Conn{};
        epoll_event ce{};
        ce.events = EPOLLIN;
        ce.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ce);
        Conn &c = conns[fd];
        for (int p = 0; p < pipeline; p++) {
            c.out.append(REQ, REQ_LEN);
            c.inflight++;
        }
        if (!flush_out(ep, fd, c)) { drop(ep, fd); errors++; }
    }

    char buf[65536];
    epoll_event evs[256];
    double t0 = now_s(), tend = t0 + secs;
    while (now_s() < tend) {
        int n = epoll_wait(ep, evs, 256, 100);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            Conn &c = conns[fd];
            if (evs[i].events & EPOLLOUT) {
                if (!flush_out(ep, fd, c)) {
                    drop(ep, fd);
                    errors++;
                    continue;
                }
            }
            if (!(evs[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)))
                continue;
            ssize_t r = read(fd, buf, sizeof(buf));
            if (r == 0 || (r < 0 && errno != EAGAIN && errno != EINTR)) {
                drop(ep, fd);
                errors++;
                continue;
            }
            if (r < 0) continue;
            c.rxbytes += (size_t)r;
            while (c.rxbytes >= RESP_LEN && c.inflight > 0) {
                c.rxbytes -= RESP_LEN;
                c.inflight--;
                done++;
                c.out.append(REQ, REQ_LEN);
                c.inflight++;
            }
            if (!flush_out(ep, fd, c)) {
                drop(ep, fd);
                errors++;
            }
        }
    }
    double el = now_s() - t0;
    printf("{\"reqs\": %lld, \"secs\": %.3f, \"rps\": %.1f, "
           "\"errors\": %lld, \"conns\": %d, \"pipeline\": %d}\n",
           done, el, done / el, errors, nconn, pipeline);
    fflush(stdout);
    return 0;
}

// ----------------------------------------------------------- tls client
//
// TLS load mode for the TLS-terminating TcpLB bench: OpenSSL resolved
// with dlopen (no dev headers in this image; the ABI is stable), client
// handshakes run BEFORE the timed window, then the same pipelined
// request loop rides SSL_read/SSL_write nonblocking.

#include <dlfcn.h>

typedef struct ssl_ctx_st SSL_CTX_;
typedef struct ssl_st SSL_;
static struct {
    const void *(*TLS_client_method)(void);
    SSL_CTX_ *(*SSL_CTX_new)(const void *);
    long (*SSL_CTX_ctrl)(SSL_CTX_ *, int, long, void *);
    SSL_ *(*SSL_new)(SSL_CTX_ *);
    int (*SSL_set_fd)(SSL_ *, int);
    int (*SSL_connect)(SSL_ *);
    int (*SSL_read)(SSL_ *, void *, int);
    int (*SSL_write)(SSL_ *, const void *, int);
    int (*SSL_get_error)(const SSL_ *, int);
    long (*SSL_ctrl)(SSL_ *, int, long, void *);
} T;

static int tls_load() {
    void *h = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (!h) return -1;
    dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
#define S(n)                                   \
    *(void **)(&T.n) = dlsym(h, #n);           \
    if (!T.n) return -1;
    S(TLS_client_method) S(SSL_CTX_new) S(SSL_CTX_ctrl) S(SSL_new)
    S(SSL_set_fd) S(SSL_connect) S(SSL_read) S(SSL_write) S(SSL_get_error)
    S(SSL_ctrl)
#undef S
    return 0;
}

static SSL_ *tlss[MAXFD];

static int run_tls_client(const char *ip, int port, const char *sni,
                          int nconn, double secs, int pipeline) {
    signal(SIGPIPE, SIG_IGN);
    if (tls_load() != 0) {
        fprintf(stderr, "libssl unavailable\n");
        return 3;
    }
    SSL_CTX_ *ctx = T.SSL_CTX_new(T.TLS_client_method());
    T.SSL_CTX_ctrl(ctx, 33 /*SSL_CTRL_MODE*/, 1L | 2L /*partial+moving*/,
                   nullptr);
    int ep = epoll_create1(0);
    long long done = 0, errors = 0;
    int one = 1;
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, ip, &sa.sin_addr);

    for (int i = 0; i < nconn; i++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0 || fd >= MAXFD) {
            if (fd >= 0) close(fd);
            errors++;
            continue;
        }
        if (connect(fd, (sockaddr *)&sa, sizeof(sa)) != 0) {
            close(fd);
            errors++;
            continue;
        }
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        SSL_ *ssl = T.SSL_new(ctx);
        T.SSL_set_fd(ssl, fd);
        // SSL_set_tlsext_host_name = SSL_ctrl(ssl, 55, 0, name)
        T.SSL_ctrl(ssl, 55, 0, (void *)sni);
        if (T.SSL_connect(ssl) != 1) {  // blocking handshake (pre-window)
            close(fd);
            errors++;
            continue;
        }
        set_nonblock(fd);
        tlss[fd] = ssl;
        conns[fd] = Conn{};
        epoll_event ce{};
        ce.events = EPOLLIN;
        ce.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ce);
        Conn &c = conns[fd];
        for (int p = 0; p < pipeline; p++) {
            c.out.append(REQ, REQ_LEN);
            c.inflight++;
        }
    }
    // helper: flush c.out through SSL_write; -1 fatal, 0 would-block-write
    auto tls_flush = [&](int fd, Conn &c) -> int {
        while (!c.out.empty()) {
            int w = T.SSL_write(tlss[fd], c.out.data(), (int)c.out.size());
            if (w > 0) {
                c.out.erase(0, (size_t)w);
            } else {
                int e = T.SSL_get_error(tlss[fd], w);
                if (e == 3) return 0;   // WANT_WRITE
                if (e == 2) return 1;   // WANT_READ: retry on next read ev
                return -1;
            }
        }
        return 1;
    };
    for (int fd = 0; fd < MAXFD; fd++)
        if (tlss[fd]) {
            int r = tls_flush(fd, conns[fd]);
            if (r < 0) { drop(ep, fd); tlss[fd] = nullptr; errors++; }
            else if (r == 0) {
                epoll_event ce{};
                ce.events = EPOLLIN | EPOLLOUT;
                ce.data.fd = fd;
                epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ce);
            }
        }

    char buf[65536];
    epoll_event evs[256];
    double t0 = now_s(), tend = t0 + secs;
    while (now_s() < tend) {
        int n = epoll_wait(ep, evs, 256, 100);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            Conn &c = conns[fd];
            bool dead = false;
            for (;;) {
                int r = T.SSL_read(tlss[fd], buf, sizeof(buf));
                if (r > 0) {
                    c.rxbytes += (size_t)r;
                    continue;
                }
                int e = T.SSL_get_error(tlss[fd], r);
                if (e == 2 || e == 3) break;  // drained
                dead = true;
                break;
            }
            if (dead) {
                drop(ep, fd);
                tlss[fd] = nullptr;
                errors++;
                continue;
            }
            while (c.rxbytes >= RESP_LEN && c.inflight > 0) {
                c.rxbytes -= RESP_LEN;
                c.inflight--;
                done++;
                c.out.append(REQ, REQ_LEN);
                c.inflight++;
            }
            int fr = tls_flush(fd, c);
            if (fr < 0) {
                drop(ep, fd);
                tlss[fd] = nullptr;
                errors++;
            } else {
                epoll_event ce{};
                ce.events = fr == 0 ? (EPOLLIN | EPOLLOUT) : EPOLLIN;
                ce.data.fd = fd;
                epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ce);
            }
        }
    }
    double el = now_s() - t0;
    printf("{\"reqs\": %lld, \"secs\": %.3f, \"rps\": %.1f, "
           "\"errors\": %lld, \"conns\": %d, \"pipeline\": %d}\n",
           done, el, done / el, errors, nconn, pipeline);
    fflush(stdout);
    return 0;
}

// ---------------------------------------------------------- short client
//
// Connection-per-request load (the reference's short-connection rows,
// benchmark/report/2019/06/05/bench.md:19): each slot loops
// connect -> one request -> full response -> close. Measures the
// accept path (ACL + classify + backend pick + pump setup/teardown).

static int run_short_client(const char *ip, int port, int nconn,
                            double secs) {
    signal(SIGPIPE, SIG_IGN);
    int ep = epoll_create1(0);
    long long done = 0, errors = 0;
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, ip, &sa.sin_addr);
    // state per fd: 0 = connecting (EPOLLOUT pending), 1 = sent/reading
    static int st[MAXFD];

    auto open_one = [&]() -> bool {
        int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
        if (fd < 0 || fd >= MAXFD) {
            if (fd >= 0) close(fd);
            return false;
        }
        int r = connect(fd, (sockaddr *)&sa, sizeof(sa));
        if (r != 0 && errno != EINPROGRESS) {
            close(fd);
            return false;
        }
        conns[fd] = Conn{};
        st[fd] = 0;
        epoll_event ce{};
        ce.events = EPOLLOUT;
        ce.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ce);
        return true;
    };

    for (int i = 0; i < nconn; i++)
        if (!open_one()) errors++;

    char buf[65536];
    epoll_event evs[256];
    double t0 = now_s(), tend = t0 + secs;
    while (now_s() < tend) {
        int n = epoll_wait(ep, evs, 256, 100);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            Conn &c = conns[fd];
            if (evs[i].events & (EPOLLERR | EPOLLHUP)) {
                drop(ep, fd);
                errors++;
                open_one();
                continue;
            }
            if (st[fd] == 0 && (evs[i].events & EPOLLOUT)) {
                int err = 0;
                socklen_t el = sizeof(err);
                getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &el);
                if (err) {
                    drop(ep, fd);
                    errors++;
                    open_one();
                    continue;
                }
                st[fd] = 1;
                c.out.assign(REQ, REQ_LEN);
                if (!flush_out(ep, fd, c)) {
                    drop(ep, fd);
                    errors++;
                    open_one();
                    continue;
                }
                epoll_event ce{};
                ce.events = EPOLLIN | (c.out.empty() ? 0 : EPOLLOUT);
                ce.data.fd = fd;
                epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ce);
                continue;
            }
            if (!(evs[i].events & EPOLLIN)) {
                if (!flush_out(ep, fd, c)) {
                    drop(ep, fd);
                    errors++;
                    open_one();
                }
                continue;
            }
            ssize_t r = read(fd, buf, sizeof(buf));
            if (r == 0 || (r < 0 && errno != EAGAIN && errno != EINTR)) {
                drop(ep, fd);
                errors++;
                open_one();
                continue;
            }
            if (r < 0) continue;
            c.rxbytes += (size_t)r;
            if (c.rxbytes >= RESP_LEN) {
                done++;
                drop(ep, fd);  // close; fresh connection next
                open_one();
            }
        }
    }
    double el = now_s() - t0;
    printf("{\"reqs\": %lld, \"secs\": %.3f, \"rps\": %.1f, "
           "\"errors\": %lld, \"conns\": %d, \"pipeline\": 0}\n",
           done, el, done / el, errors, nconn);
    fflush(stdout);
    return 0;
}

int main(int argc, char **argv) {
    if (argc >= 3 && strcmp(argv[1], "server") == 0)
        return run_server(atoi(argv[2]));
    if (argc >= 7 && strcmp(argv[1], "client") == 0)
        return run_client(argv[2], atoi(argv[3]), atoi(argv[4]),
                          atof(argv[5]), atoi(argv[6]));
    if (argc >= 6 && strcmp(argv[1], "shortclient") == 0)
        return run_short_client(argv[2], atoi(argv[3]), atoi(argv[4]),
                                atof(argv[5]));
    if (argc >= 8 && strcmp(argv[1], "tlsclient") == 0)
        return run_tls_client(argv[2], atoi(argv[3]), argv[4],
                              atoi(argv[5]), atof(argv[6]), atoi(argv[7]));
    fprintf(stderr,
            "usage: hostbench server <port>\n"
            "       hostbench client <ip> <port> <conns> <secs> <pipeline>\n"
            "       hostbench tlsclient <ip> <port> <sni> <conns> <secs> "
            "<pipeline>\n"
            "       hostbench shortclient <ip> <port> <conns> <secs>\n");
    return 2;
}
