// hostbench — epoll HTTP/1.1 load tool for the host-path req/s bench.
//
// The framework's TcpLB data path is the native splice pump
// (vtl.cpp:342-537); measuring it through Python clients would measure
// the GIL instead. This file provides the two native endpoints of the
// harness (bench_host.py owns orchestration):
//
//   hostbench server <port>
//       single-thread epoll HTTP server: reads until CRLFCRLF, writes a
//       fixed keep-alive response (RESP below, constant byte length).
//   hostbench client <ip> <port> <conns> <seconds> <pipeline>
//       opens <conns> keep-alive connections, keeps <pipeline> requests
//       in flight on each, counts completed responses by exact byte
//       framing. Prints one JSON line on stdout when done.
//
// Both sides keep a per-connection out-buffer and flush via EPOLLOUT —
// an EAGAIN/partial write must never drop bytes, or conns deadlock
// under the LB's splice backpressure.
//
// Analog of the reference's wrk/bench.md harness
// (benchmark/report/2019/06/05/bench.md:17-19) rebuilt self-contained.
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <string>

static const char RESP[] =
    "HTTP/1.1 200 OK\r\n"
    "Content-Length: 13\r\n"
    "Connection: keep-alive\r\n"
    "\r\n"
    "hello, world\n";
static const size_t RESP_LEN = sizeof(RESP) - 1;

static const char REQ[] =
    "GET / HTTP/1.1\r\n"
    "Host: bench.example.com\r\n"
    "Connection: keep-alive\r\n"
    "\r\n";
static const size_t REQ_LEN = sizeof(REQ) - 1;

static const int MAXFD = 65536;

static int set_nonblock(int fd) {
    int fl = fcntl(fd, F_GETFL, 0);
    return fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

static double now_s() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

struct Conn {
    std::string out;     // unsent bytes
    size_t inflight = 0; // client: requests awaiting a response
    size_t rxbytes = 0;  // client: bytes of current response received
    size_t reqpos = 0;   // server: progress through "\r\n\r\n"
    bool want_out = false;
};

static Conn conns[MAXFD];

// flush c.out; keeps EPOLLIN|EPOLLOUT registration in sync. Returns
// false if the connection died.
static bool flush_out(int ep, int fd, Conn &c) {
    while (!c.out.empty()) {
        ssize_t w = write(fd, c.out.data(), c.out.size());
        if (w > 0) {
            c.out.erase(0, (size_t)w);
            continue;
        }
        if (errno == EAGAIN || errno == EINTR) break;
        return false;
    }
    bool want = !c.out.empty();
    if (want != c.want_out) {
        c.want_out = want;
        epoll_event ev{};
        ev.events = EPOLLIN | (want ? EPOLLOUT : 0);
        ev.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
    }
    return true;
}

static void drop(int ep, int fd) {
    epoll_ctl(ep, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    conns[fd] = Conn{};
}

// --------------------------------------------------------------- server

static int run_server(int port) {
    signal(SIGPIPE, SIG_IGN);
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sa.sin_port = htons((uint16_t)port);
    if (bind(lfd, (sockaddr *)&sa, sizeof(sa)) != 0 || listen(lfd, 1024) != 0) {
        perror("bind/listen");
        return 1;
    }
    set_nonblock(lfd);
    socklen_t slen = sizeof(sa);
    getsockname(lfd, (sockaddr *)&sa, &slen);
    printf("{\"listening\": %d}\n", ntohs(sa.sin_port));
    fflush(stdout);

    int ep = epoll_create1(0);
    epoll_event ev{}, evs[256];
    ev.events = EPOLLIN;
    ev.data.fd = lfd;
    epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);
    char buf[65536];

    for (;;) {
        int n = epoll_wait(ep, evs, 256, 1000);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            if (fd == lfd) {
                for (;;) {
                    int cfd = accept(lfd, nullptr, nullptr);
                    if (cfd < 0) break;
                    if (cfd >= MAXFD) { close(cfd); continue; }
                    set_nonblock(cfd);
                    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
                    conns[cfd] = Conn{};
                    epoll_event ce{};
                    ce.events = EPOLLIN;
                    ce.data.fd = cfd;
                    epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &ce);
                }
                continue;
            }
            Conn &c = conns[fd];
            if (evs[i].events & EPOLLOUT) {
                if (!flush_out(ep, fd, c)) { drop(ep, fd); continue; }
            }
            if (!(evs[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)))
                continue;
            ssize_t r = read(fd, buf, sizeof(buf));
            if (r == 0 || (r < 0 && errno != EAGAIN && errno != EINTR)) {
                drop(ep, fd);
                continue;
            }
            if (r < 0) continue;
            static const char T[] = "\r\n\r\n";
            for (ssize_t j = 0; j < r; j++) {
                if (buf[j] == T[c.reqpos]) {
                    if (++c.reqpos == 4) {
                        c.out.append(RESP, RESP_LEN);
                        c.reqpos = 0;
                    }
                } else {
                    c.reqpos = (buf[j] == '\r') ? 1 : 0;
                }
            }
            if (!flush_out(ep, fd, c)) drop(ep, fd);
        }
    }
    return 0;
}

// --------------------------------------------------------------- client

static int run_client(const char *ip, int port, int nconn, double secs,
                      int pipeline) {
    signal(SIGPIPE, SIG_IGN);
    int ep = epoll_create1(0);
    long long done = 0, errors = 0;
    int one = 1;
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, ip, &sa.sin_addr);

    for (int i = 0; i < nconn; i++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0 || fd >= MAXFD) {  // same bound guard as the server
            if (fd >= 0) close(fd);
            errors++;
            continue;
        }
        if (connect(fd, (sockaddr *)&sa, sizeof(sa)) != 0) {
            fprintf(stderr, "connect: %s\n", strerror(errno));
            close(fd);
            errors++;
            continue;
        }
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        set_nonblock(fd);
        conns[fd] = Conn{};
        epoll_event ce{};
        ce.events = EPOLLIN;
        ce.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ce);
        Conn &c = conns[fd];
        for (int p = 0; p < pipeline; p++) {
            c.out.append(REQ, REQ_LEN);
            c.inflight++;
        }
        if (!flush_out(ep, fd, c)) { drop(ep, fd); errors++; }
    }

    char buf[65536];
    epoll_event evs[256];
    double t0 = now_s(), tend = t0 + secs;
    while (now_s() < tend) {
        int n = epoll_wait(ep, evs, 256, 100);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            Conn &c = conns[fd];
            if (evs[i].events & EPOLLOUT) {
                if (!flush_out(ep, fd, c)) {
                    drop(ep, fd);
                    errors++;
                    continue;
                }
            }
            if (!(evs[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)))
                continue;
            ssize_t r = read(fd, buf, sizeof(buf));
            if (r == 0 || (r < 0 && errno != EAGAIN && errno != EINTR)) {
                drop(ep, fd);
                errors++;
                continue;
            }
            if (r < 0) continue;
            c.rxbytes += (size_t)r;
            while (c.rxbytes >= RESP_LEN && c.inflight > 0) {
                c.rxbytes -= RESP_LEN;
                c.inflight--;
                done++;
                c.out.append(REQ, REQ_LEN);
                c.inflight++;
            }
            if (!flush_out(ep, fd, c)) {
                drop(ep, fd);
                errors++;
            }
        }
    }
    double el = now_s() - t0;
    printf("{\"reqs\": %lld, \"secs\": %.3f, \"rps\": %.1f, "
           "\"errors\": %lld, \"conns\": %d, \"pipeline\": %d}\n",
           done, el, done / el, errors, nconn, pipeline);
    fflush(stdout);
    return 0;
}

int main(int argc, char **argv) {
    if (argc >= 3 && strcmp(argv[1], "server") == 0)
        return run_server(atoi(argv[2]));
    if (argc >= 7 && strcmp(argv[1], "client") == 0)
        return run_client(argv[2], atoi(argv[3]), atoi(argv[4]),
                          atof(argv[5]), atoi(argv[6]));
    fprintf(stderr,
            "usage: hostbench server <port>\n"
            "       hostbench client <ip> <port> <conns> <secs> <pipeline>\n");
    return 2;
}
