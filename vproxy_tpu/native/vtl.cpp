// vtl — vproxy-tpu host runtime: epoll event loop, nonblocking socket
// syscall layer, and a native bidirectional splice pump.
//
// Role: the C++ equivalent of the reference's native layer (redis-ae event
// loop dep/ae/ae.c + JNI socket layer vfd_posix_GeneralPosix.c — see
// SURVEY.md §2.7), redesigned for a Python-orchestrated data plane: Python
// owns accept/classify/connect decisions; byte shoveling for spliced TCP
// sessions runs entirely in C (vtl_pump), so the per-byte path never
// crosses into the interpreter.
//
// C ABI only (ctypes-friendly). Level-triggered epoll with explicit
// interest management.
#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <algorithm>
#include <vector>

#define VTL_EV_READ 1u
#define VTL_EV_WRITE 2u
#define VTL_EV_ERROR 4u
// pump lifecycle notifications delivered through vtl_poll
#define VTL_EV_PUMP_DONE 8u

extern "C" {

// ---------------------------------------------------------------- sockets


static int mk_addr(const char* ip, int port, int v6, sockaddr_storage* ss,
                   socklen_t* len) {
  memset(ss, 0, sizeof(*ss));
  if (v6) {
    auto* a = (sockaddr_in6*)ss;
    a->sin6_family = AF_INET6;
    a->sin6_port = htons((uint16_t)port);
    if (inet_pton(AF_INET6, ip, &a->sin6_addr) != 1) return -EINVAL;
    *len = sizeof(sockaddr_in6);
  } else {
    auto* a = (sockaddr_in*)ss;
    a->sin_family = AF_INET;
    a->sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, ip, &a->sin_addr) != 1) return -EINVAL;
    *len = sizeof(sockaddr_in);
  }
  return 0;
}

int vtl_tcp_listen(const char* ip, int port, int backlog, int reuseport,
                   int v6) {
  int fd = socket(v6 ? AF_INET6 : AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (reuseport) setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  sockaddr_storage ss;
  socklen_t len;
  int r = mk_addr(ip, port, v6, &ss, &len);
  if (r < 0) { close(fd); return r; }
  if (bind(fd, (sockaddr*)&ss, len) < 0) { r = -errno; close(fd); return r; }
  if (listen(fd, backlog) < 0) { r = -errno; close(fd); return r; }
  return fd;
}

// returns client fd; fills ip string (INET6_ADDRSTRLEN) and port
int vtl_accept(int lfd, char* ipbuf, int ipbuflen, int* port) {
  sockaddr_storage ss;
  socklen_t len = sizeof(ss);
  int fd = accept4(lfd, (sockaddr*)&ss, &len, SOCK_NONBLOCK | SOCK_CLOEXEC);
  if (fd < 0) return -errno;
  if (ss.ss_family == AF_INET) {
    auto* a = (sockaddr_in*)&ss;
    inet_ntop(AF_INET, &a->sin_addr, ipbuf, ipbuflen);
    *port = ntohs(a->sin_port);
  } else if (ss.ss_family == AF_INET6) {
    auto* a = (sockaddr_in6*)&ss;
    inet_ntop(AF_INET6, &a->sin6_addr, ipbuf, ipbuflen);
    *port = ntohs(a->sin6_port);
  } else {  // AF_UNIX peer: no address to report
    if (ipbuflen > 0) ipbuf[0] = 0;
    *port = 0;
  }
  return fd;
}

// unix-domain stream listener (UDSPath analog). Removes a stale socket
// file first if nothing is accepting on it.
int vtl_unix_listen(const char* path, int backlog) {
  sockaddr_un sa;
  if (strlen(path) >= sizeof(sa.sun_path)) return -ENAMETOOLONG;
  struct stat st;
  if (stat(path, &st) == 0) {
    if (!S_ISSOCK(st.st_mode)) return -EADDRINUSE;  // never unlink non-sockets
    int probe = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (probe >= 0) {
      memset(&sa, 0, sizeof(sa));
      sa.sun_family = AF_UNIX;
      strcpy(sa.sun_path, path);
      if (connect(probe, (sockaddr*)&sa, sizeof(sa)) < 0 &&
          (errno == ECONNREFUSED || errno == ENOENT)) {
        unlink(path);  // dead leftover from a previous process
      }
      close(probe);
    }
  }
  int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  memset(&sa, 0, sizeof(sa));
  sa.sun_family = AF_UNIX;
  strcpy(sa.sun_path, path);
  int r;
  if (bind(fd, (sockaddr*)&sa, sizeof(sa)) < 0) { r = -errno; close(fd); return r; }
  if (listen(fd, backlog) < 0) { r = -errno; close(fd); return r; }
  return fd;
}

int vtl_unix_connect(const char* path) {
  sockaddr_un sa;
  if (strlen(path) >= sizeof(sa.sun_path)) return -ENAMETOOLONG;
  int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  memset(&sa, 0, sizeof(sa));
  sa.sun_family = AF_UNIX;
  strcpy(sa.sun_path, path);
  if (connect(fd, (sockaddr*)&sa, sizeof(sa)) < 0 && errno != EINPROGRESS) {
    int r = -errno;
    close(fd);
    return r;
  }
  return fd;
}

int vtl_tcp_connect(const char* ip, int port, int v6) {
  int fd = socket(v6 ? AF_INET6 : AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  sockaddr_storage ss;
  socklen_t len;
  int r = mk_addr(ip, port, v6, &ss, &len);
  if (r < 0) { close(fd); return r; }
  if (connect(fd, (sockaddr*)&ss, len) < 0 && errno != EINPROGRESS) {
    r = -errno;
    close(fd);
    return r;
  }
  return fd;
}

int vtl_finish_connect(int fd) {
  int err = 0;
  socklen_t len = sizeof(err);
  if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) < 0) return -errno;
  return -err;  // 0 ok, else -errno of the failed connect
}

int vtl_udp_bind(const char* ip, int port, int v6, int reuseport) {
  int fd = socket(v6 ? AF_INET6 : AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (reuseport) setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  sockaddr_storage ss;
  socklen_t len;
  int r = mk_addr(ip, port, v6, &ss, &len);
  if (r < 0) { close(fd); return r; }
  if (bind(fd, (sockaddr*)&ss, len) < 0) { r = -errno; close(fd); return r; }
  return fd;
}

int vtl_udp_socket(int v6) {
  int fd = socket(v6 ? AF_INET6 : AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  return fd < 0 ? -errno : fd;
}

int vtl_recvfrom(int fd, void* buf, int len, char* ipbuf, int ipbuflen,
                 int* port) {
  sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  ssize_t n = recvfrom(fd, buf, (size_t)len, 0, (sockaddr*)&ss, &slen);
  if (n < 0) return -errno;
  if (ss.ss_family == AF_INET) {
    auto* a = (sockaddr_in*)&ss;
    inet_ntop(AF_INET, &a->sin_addr, ipbuf, ipbuflen);
    *port = ntohs(a->sin_port);
  } else if (ss.ss_family == AF_INET6) {
    auto* a = (sockaddr_in6*)&ss;
    inet_ntop(AF_INET6, &a->sin6_addr, ipbuf, ipbuflen);
    *port = ntohs(a->sin6_port);
  }
  return (int)n;
}

int vtl_sendto(int fd, const void* buf, int len, const char* ip, int port,
               int v6) {
  sockaddr_storage ss;
  socklen_t slen;
  int r = mk_addr(ip, port, v6, &ss, &slen);
  if (r < 0) return r;
  ssize_t n = sendto(fd, buf, (size_t)len, 0, (sockaddr*)&ss, slen);
  return n < 0 ? -errno : (int)n;
}

// Batched datagram ingress: one syscall (and one ctypes crossing)
// drains up to `maxmsgs` datagrams into `buf` sliced as fixed `slot`-
// byte cells. lens[i] = datagram size (truncated to slot), ips is a
// maxmsgs x ipstride char matrix, ports[i] the sender port. Returns
// message count, 0 on EAGAIN, -errno on error.
int vtl_recvmmsg(int fd, void* buf, int slot, int maxmsgs, int* lens,
                 char* ips, int ipstride, int* ports) {
  if (maxmsgs > 512) maxmsgs = 512;
  static thread_local mmsghdr hdrs[512];
  static thread_local iovec iovs[512];
  static thread_local sockaddr_storage addrs[512];
  for (int i = 0; i < maxmsgs; ++i) {
    iovs[i].iov_base = (char*)buf + (size_t)i * slot;
    iovs[i].iov_len = (size_t)slot;
    memset(&hdrs[i].msg_hdr, 0, sizeof(msghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
    hdrs[i].msg_hdr.msg_name = &addrs[i];
    hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_storage);
  }
  int n = recvmmsg(fd, hdrs, (unsigned)maxmsgs, MSG_DONTWAIT, nullptr);
  if (n < 0) return (errno == EAGAIN || errno == EWOULDBLOCK) ? 0 : -errno;
  for (int i = 0; i < n; ++i) {
    lens[i] = (int)hdrs[i].msg_len;
    char* ip = ips + (size_t)i * ipstride;
    ip[0] = 0;
    ports[i] = 0;
    if (addrs[i].ss_family == AF_INET) {
      auto* a = (sockaddr_in*)&addrs[i];
      inet_ntop(AF_INET, &a->sin_addr, ip, ipstride);
      ports[i] = ntohs(a->sin_port);
    } else if (addrs[i].ss_family == AF_INET6) {
      auto* a = (sockaddr_in6*)&addrs[i];
      inet_ntop(AF_INET6, &a->sin6_addr, ip, ipstride);
      ports[i] = ntohs(a->sin6_port);
    }
  }
  return n;
}

// Batched same-destination egress (the fast path's per-iface groups):
// one sendmmsg for n datagrams given as (ptrs[i], lens[i]). Returns
// the number actually sent (datagram sockets: the rest were dropped
// by buffer pressure — acceptable for a switch) or -errno.
int vtl_sendmmsg(int fd, const void* const* ptrs, const int* lens, int n,
                 const char* ip, int port, int v6) {
  if (n > 512) n = 512;
  sockaddr_storage ss;
  socklen_t slen;
  int r = mk_addr(ip, port, v6, &ss, &slen);
  if (r < 0) return r;
  static thread_local mmsghdr hdrs[512];
  static thread_local iovec iovs[512];
  for (int i = 0; i < n; ++i) {
    iovs[i].iov_base = (void*)ptrs[i];
    iovs[i].iov_len = (size_t)lens[i];
    memset(&hdrs[i].msg_hdr, 0, sizeof(msghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
    hdrs[i].msg_hdr.msg_name = &ss;
    hdrs[i].msg_hdr.msg_namelen = slen;
  }
  int sent = sendmmsg(fd, hdrs, (unsigned)n, 0);
  if (sent < 0)
    return (errno == EAGAIN || errno == EWOULDBLOCK) ? 0 : -errno;
  return sent;
}

int vtl_read(int fd, void* buf, int len) {
  ssize_t n = read(fd, buf, (size_t)len);
  return n < 0 ? -errno : (int)n;
}

int vtl_write(int fd, const void* buf, int len) {
  ssize_t n = write(fd, buf, (size_t)len);
  return n < 0 ? -errno : (int)n;
}

int vtl_close(int fd) { return close(fd) < 0 ? -errno : 0; }

// RST close (SO_LINGER{1,0}): the overload-shed path — one call, no
// python socket-object round trip per refused connection
int vtl_close_rst(int fd) {
  struct linger lg = {1, 0};
  setsockopt(fd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
  return close(fd) < 0 ? -errno : 0;
}

int vtl_shutdown_wr(int fd) { return shutdown(fd, SHUT_WR) < 0 ? -errno : 0; }

int vtl_set_rcvbuf(int fd, int bytes) {
  return setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes)) < 0
             ? -errno : 0;
}

int vtl_set_nodelay(int fd, int on) {
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &on, sizeof(on)) < 0
             ? -errno : 0;
}

// TCP_DEFER_ACCEPT on a listener: the kernel completes the handshake but
// only surfaces the connection to accept() once data arrives (or the
// timeout expires) — empty accepts never wake the accept loop. For
// client-speaks-first workloads only; a server-first protocol behind a
// deferred listener waits out `seconds` before its first byte.
int vtl_set_defer_accept(int fd, int seconds) {
  return setsockopt(fd, IPPROTO_TCP, TCP_DEFER_ACCEPT, &seconds,
                    sizeof(seconds)) < 0 ? -errno : 0;
}

int vtl_sock_name(int fd, int peer, char* ipbuf, int ipbuflen, int* port) {
  sockaddr_storage ss;
  socklen_t len = sizeof(ss);
  int r = peer ? getpeername(fd, (sockaddr*)&ss, &len)
               : getsockname(fd, (sockaddr*)&ss, &len);
  if (r < 0) return -errno;
  if (ss.ss_family == AF_INET) {
    auto* a = (sockaddr_in*)&ss;
    inet_ntop(AF_INET, &a->sin_addr, ipbuf, ipbuflen);
    *port = ntohs(a->sin_port);
  } else if (ss.ss_family == AF_INET6) {
    auto* a = (sockaddr_in6*)&ss;
    inet_ntop(AF_INET6, &a->sin6_addr, ipbuf, ipbuflen);
    *port = ntohs(a->sin6_port);
  } else {  // AF_UNIX: report the bound path (empty for the peer side)
    auto* a = (sockaddr_un*)&ss;
    strncpy(ipbuf, len > sizeof(sa_family_t) ? a->sun_path : "", ipbuflen - 1);
    ipbuf[ipbuflen - 1] = 0;
    *port = 0;
  }
  return 0;
}

// ---------------------------------------------------------------- loop

struct Pump;
struct Lane;

struct Handler {
  enum Kind { PY = 0, WAKE = 1, PUMP_A = 2, PUMP_B = 3, LANE = 4 } kind;
  uint64_t tag;   // PY: python tag; PUMP_*: owning pump id
  Pump* pump;     // PUMP_* only
  int fd;
  uint32_t interest;  // current epoll interest (VTL_EV_*)
  // --- io_uring engine bookkeeping (accept lanes; zero-cost on epoll)
  // One oneshot POLL_ADD at a time per fd; pending_ev remembers what it
  // was armed for so an interest change mid-flight cancels + re-arms.
  // inflight counts CQEs still owed to this handler — its memory must
  // not be freed until they have all drained (uring user_data holds the
  // raw pointer; see lane garbage collection).
  uint16_t pending_ev = 0;
  bool poll_pending = false;
  bool ms_accept = false;   // LANE: multishot accept currently armed
  int inflight = 0;
};

struct Ring {
  std::vector<char> buf;
  size_t head = 0, size = 0;  // ring of buf.size()
  explicit Ring(size_t cap) : buf(cap) {}
  size_t cap() const { return buf.size(); }
  size_t free_() const { return cap() - size; }
  bool empty() const { return size == 0; }
  bool full() const { return size == cap(); }
};

struct Pump {
  uint64_t id;
  int fd_a, fd_b;
  Ring a2b, b2a;
  bool a_eof = false, b_eof = false;       // read side closed
  bool a_wr_shut = false, b_wr_shut = false;
  bool dead = false;
  // accept fast lane (vtl_pump_connect): B is still mid-connect; the
  // pump idles until the handshake resolves. A failed connect reports
  // connect_failed and leaves fd_a OPEN for the python retry layer.
  // created_us/connect_us let python report the TRUE backend-connect
  // span (the classic path measures it in on_connected; the fast lane
  // only hears back at DONE, so the duration rides the stat).
  bool b_connecting = false;
  bool connect_failed = false;
  uint64_t created_us = 0;
  uint64_t connect_us = (uint64_t)-1;  // -1 = not resolved yet
  int err = 0;
  uint64_t bytes_a2b = 0, bytes_b2a = 0;
  // TLS-terminating pumps (vtl_tls_pump_new): side A is a TLS client
  // (this process is the server), side B plaintext; ssl owns the
  // record layer over fd_a via SSL_set_fd.
  void* ssl = nullptr;
  bool handshaking = false;
  // A-side SSL demands, split by direction: SSL_read's WANT_READ is the
  // NORMAL idle state (no complete record) and must not stall B->A;
  // only SSL_write's wants gate the write flush.
  bool rd_want_write = false;               // SSL_read needs fd writable
  bool wr_want_read = false, wr_want_write = false;  // SSL_write stalled
  bool hs_want_write = false;
  Pump(uint64_t i, int a, int b, size_t cap)
      : id(i), fd_a(a), fd_b(b), a2b(cap), b2a(cap) {}
};

struct Uring;

struct Loop {
  int ep = -1;
  int wakefd = -1;
  std::unordered_map<int, Handler*> handlers;  // by fd
  std::unordered_map<uint64_t, Pump*> pumps;   // by pump id
  std::vector<uint64_t> done_pumps;            // report via poll
  uint64_t next_pump_id = 1;
  // Handlers can be torn down (pump_kill) while later events in the same
  // epoll batch still hold their pointers; removals defer the delete and
  // the poll loop checks membership here before dereferencing.
  std::unordered_set<Handler*> valid;
  std::vector<Handler*> garbage;
  // accept lanes may run this loop on the io_uring engine instead of
  // epoll: readiness is then delivered as batched oneshot-POLL CQEs
  // through one ring (ur != nullptr) and ep stays unused.
  Uring* ur = nullptr;
};

static void drop_handler(Loop* l, Handler* h) {
  l->valid.erase(h);
  l->garbage.push_back(h);
}

static uint32_t to_ep(uint32_t ev) {
  uint32_t e = 0;
  if (ev & VTL_EV_READ) e |= EPOLLIN;
  if (ev & VTL_EV_WRITE) e |= EPOLLOUT;
  return e;
}

static int uring_set_interest(Loop* l, Handler* h, uint32_t interest);
static void uring_detach(Loop* l, Handler* h);
static void uring_free(Uring* u);

static int ep_set(Loop* l, Handler* h, uint32_t interest) {
  if (l->ur) return uring_set_interest(l, h, interest);
  epoll_event e;
  memset(&e, 0, sizeof(e));
  e.events = to_ep(interest);
  e.data.ptr = h;
  int op = h->interest == (uint32_t)-1 ? EPOLL_CTL_ADD : EPOLL_CTL_MOD;
  if (epoll_ctl(l->ep, op, h->fd, &e) < 0) return -errno;
  h->interest = interest;
  return 0;
}

// unregister an fd's readiness source before it closes: epoll_ctl DEL,
// or (uring) cancel the outstanding poll so the ring drops its file
// reference — an fd closed with a live uring poll would never be
// released by the kernel.
static void loop_detach(Loop* l, Handler* h) {
  if (l->ur) {
    uring_detach(l, h);
    return;
  }
  epoll_ctl(l->ep, EPOLL_CTL_DEL, h->fd, nullptr);
}

// NOTE: an earlier round skipped the epoll_ctl DEL for fds that close
// immediately after (Linux auto-removes a closed fd's registration) —
// REVERTED: under full-suite fd-reuse load this sandbox kernel
// surfaced stale registrations as EPOLLERR/EIO on live pumps. The DEL
// stays explicit on both engines.

void* vtl_new() {
  Loop* l = new Loop();
  l->ep = epoll_create1(EPOLL_CLOEXEC);
  l->wakefd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  Handler* h = new Handler{Handler::WAKE, 0, nullptr, l->wakefd, (uint32_t)-1};
  l->handlers[l->wakefd] = h;
  l->valid.insert(h);
  ep_set(l, h, VTL_EV_READ);
  return l;
}

int vtl_wakeup(void* lp) {
  Loop* l = (Loop*)lp;
  uint64_t one = 1;
  ssize_t n = write(l->wakefd, &one, 8);
  return n == 8 ? 0 : -errno;
}

int vtl_add(void* lp, int fd, uint32_t events, uint64_t tag) {
  Loop* l = (Loop*)lp;
  if (l->handlers.count(fd)) return -EEXIST;
  Handler* h = new Handler{Handler::PY, tag, nullptr, fd, (uint32_t)-1};
  int r = ep_set(l, h, events);
  if (r < 0) { delete h; return r; }
  l->handlers[fd] = h;
  l->valid.insert(h);
  return 0;
}

int vtl_mod(void* lp, int fd, uint32_t events, uint64_t tag) {
  Loop* l = (Loop*)lp;
  auto it = l->handlers.find(fd);
  if (it == l->handlers.end()) return -ENOENT;
  it->second->tag = tag;
  return ep_set(l, it->second, events);
}

int vtl_del(void* lp, int fd) {
  Loop* l = (Loop*)lp;
  auto it = l->handlers.find(fd);
  if (it == l->handlers.end()) return -ENOENT;
  epoll_ctl(l->ep, EPOLL_CTL_DEL, fd, nullptr);
  drop_handler(l, it->second);
  l->handlers.erase(it);
  return 0;
}

// ---------------------------------------------------------------- openssl
//
// The image ships libssl.so.3 but no development headers, so the needed
// OpenSSL 3 ABI (stable) is declared here and resolved with dlopen at
// vtl_tls_init() time. TLS stays strictly optional: without the library
// every vtl_tls_* call reports -ENOSYS and the plain pump is unaffected.

typedef struct ssl_ctx_st SSL_CTX_;
typedef struct ssl_st SSL_;

#define SSL_FILETYPE_PEM_ 1
#define SSL_CTRL_MODE_ 33
#define SSL_MODE_ENABLE_PARTIAL_WRITE_ 1L
#define SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER_ 2L
#define SSL_ERROR_WANT_READ_ 2
#define SSL_ERROR_WANT_WRITE_ 3
#define SSL_ERROR_SYSCALL_ 5
#define SSL_ERROR_ZERO_RETURN_ 6

static struct {
  bool ready = false;
  const void* (*TLS_server_method)(void);
  SSL_CTX_* (*SSL_CTX_new)(const void*);
  void (*SSL_CTX_free)(SSL_CTX_*);
  int (*SSL_CTX_use_certificate_chain_file)(SSL_CTX_*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(SSL_CTX_*, const char*, int);
  int (*SSL_CTX_check_private_key)(const SSL_CTX_*);
  long (*SSL_CTX_ctrl)(SSL_CTX_*, int, long, void*);
  SSL_* (*SSL_new)(SSL_CTX_*);
  void (*SSL_free)(SSL_*);
  int (*SSL_set_fd)(SSL_*, int);
  void (*SSL_set_accept_state)(SSL_*);
  int (*SSL_do_handshake)(SSL_*);
  int (*SSL_read)(SSL_*, void*, int);
  int (*SSL_write)(SSL_*, const void*, int);
  int (*SSL_get_error)(const SSL_*, int);
  int (*SSL_shutdown)(SSL_*);
  void (*ERR_clear_error)(void);
} TLSA;

int vtl_tls_init(void) {
  if (TLSA.ready) return 0;
  void* h = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
  if (!h) h = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
  if (!h) return -ENOSYS;
  void* hc = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
  if (!hc) hc = dlopen("libcrypto.so", RTLD_NOW | RTLD_GLOBAL);
#define VTL_SYM(lib, name)                                        \
  *(void**)(&TLSA.name) = dlsym(lib, #name);                      \
  if (!TLSA.name) return -ENOSYS;
  VTL_SYM(h, TLS_server_method)
  VTL_SYM(h, SSL_CTX_new)
  VTL_SYM(h, SSL_CTX_free)
  VTL_SYM(h, SSL_CTX_use_certificate_chain_file)
  VTL_SYM(h, SSL_CTX_use_PrivateKey_file)
  VTL_SYM(h, SSL_CTX_check_private_key)
  VTL_SYM(h, SSL_CTX_ctrl)
  VTL_SYM(h, SSL_new)
  VTL_SYM(h, SSL_free)
  VTL_SYM(h, SSL_set_fd)
  VTL_SYM(h, SSL_set_accept_state)
  VTL_SYM(h, SSL_do_handshake)
  VTL_SYM(h, SSL_read)
  VTL_SYM(h, SSL_write)
  VTL_SYM(h, SSL_get_error)
  VTL_SYM(h, SSL_shutdown)
  if (hc) {
    *(void**)(&TLSA.ERR_clear_error) = dlsym(hc, "ERR_clear_error");
  }
  if (!TLSA.ERR_clear_error)
    *(void**)(&TLSA.ERR_clear_error) = dlsym(h, "ERR_clear_error");
  if (!TLSA.ERR_clear_error) return -ENOSYS;
#undef VTL_SYM
  TLSA.ready = true;
  return 0;
}

// -> SSL_CTX handle (as int64) or -errno. One ctx per cert-key; SSL
// objects refcount it, so freeing the ctx after a holder swap is safe
// while sessions created from it live on.
long long vtl_tls_ctx_new(const char* cert_path, const char* key_path) {
  if (!TLSA.ready && vtl_tls_init() < 0) return -ENOSYS;
  SSL_CTX_* ctx = TLSA.SSL_CTX_new(TLSA.TLS_server_method());
  if (!ctx) return -ENOMEM;
  if (TLSA.SSL_CTX_use_certificate_chain_file(ctx, cert_path) != 1 ||
      TLSA.SSL_CTX_use_PrivateKey_file(ctx, key_path, SSL_FILETYPE_PEM_) != 1 ||
      TLSA.SSL_CTX_check_private_key(ctx) != 1) {
    TLSA.SSL_CTX_free(ctx);
    return -EINVAL;
  }
  // SSL_write retries may pass a different (advanced) pointer after a
  // short write — both modes are required for ring-buffer flushing
  TLSA.SSL_CTX_ctrl(ctx, SSL_CTRL_MODE_,
                    SSL_MODE_ENABLE_PARTIAL_WRITE_ |
                        SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER_,
                    nullptr);
  return (long long)(intptr_t)ctx;
}

int vtl_tls_ctx_free(long long ctx) {
  if (!TLSA.ready || !ctx) return -EINVAL;
  TLSA.SSL_CTX_free((SSL_CTX_*)(intptr_t)ctx);
  return 0;
}

// MSG_PEEK (the SNI sniffer reads the ClientHello without consuming it)
int vtl_recv_peek(int fd, void* buf, int len) {
  ssize_t n = recv(fd, buf, (size_t)len, MSG_PEEK);
  return n < 0 ? -errno : (int)n;
}

// ------------------------------------------------------------ pump engine

// Process-global pump counters (all loops/threads): total payload bytes
// moved, write syscalls issued, writes that moved fewer bytes than
// requested (incl. EAGAIN — the backpressure signal), and completed TLS
// handshakes. Exposed to Python through vtl_pump_counters() and
// surfaced on /metrics as vproxy_pump_*_total.
static std::atomic<uint64_t> g_pump_bytes(0), g_pump_writes(0),
    g_pump_short_writes(0), g_tls_handshakes(0);

static inline void count_write(ssize_t wrote, size_t wanted) {
  g_pump_writes.fetch_add(1, std::memory_order_relaxed);
  if (wrote > 0)
    g_pump_bytes.fetch_add((uint64_t)wrote, std::memory_order_relaxed);
  if (wrote < (ssize_t)wanted)
    g_pump_short_writes.fetch_add(1, std::memory_order_relaxed);
}

// out[0]=bytes, out[1]=write calls, out[2]=short writes, out[3]=tls
// handshakes; returns 4 (the counter count)
int vtl_pump_counters(uint64_t* out) {
  out[0] = g_pump_bytes.load(std::memory_order_relaxed);
  out[1] = g_pump_writes.load(std::memory_order_relaxed);
  out[2] = g_pump_short_writes.load(std::memory_order_relaxed);
  out[3] = g_tls_handshakes.load(std::memory_order_relaxed);
  return 4;
}

static void pump_update_interest(Loop* l, Pump* p);

static void pump_kill(Loop* l, Pump* p, int err) {
  if (p->dead) return;
  p->dead = true;
  p->err = err;
  if (p->ssl) {
    TLSA.SSL_free((SSL_*)p->ssl);  // does not close fd_a (SSL_set_fd)
    p->ssl = nullptr;
  }
  for (int fd : {p->fd_a, p->fd_b}) {
    auto it = l->handlers.find(fd);
    if (it != l->handlers.end()) {
      loop_detach(l, it->second);
      drop_handler(l, it->second);
      l->handlers.erase(it);
    }
    close(fd);
  }
  l->done_pumps.push_back(p->id);
}

// move bytes: read src->ring, write ring->dst. returns false on fatal
// error. peer_done = the opposite direction already hit EOF with its
// ring drained: the pump dies the moment THIS direction finishes, and
// the close() carries the FIN — the explicit shutdown would be a
// wasted syscall per short connection.
static bool pump_flow(Loop* l, Pump* p, int src, int dst, Ring& ring,
                      bool& src_eof, bool& dst_shut, uint64_t& counter,
                      bool peer_done) {
  // write pending data first
  while (!ring.empty()) {
    size_t chunk = std::min(ring.size, ring.cap() - ring.head);
    ssize_t n = write(dst, ring.buf.data() + ring.head, chunk);
    count_write(n, chunk);
    if (n > 0) {
      ring.head = (ring.head + (size_t)n) % ring.cap();
      ring.size -= (size_t)n;
      counter += (uint64_t)n;
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      break;
    } else {
      pump_kill(l, p, errno ? errno : EPIPE);
      return false;
    }
  }
  // then refill from src
  while (!src_eof && !ring.full()) {
    size_t tail = (ring.head + ring.size) % ring.cap();
    size_t chunk = std::min(ring.free_(), ring.cap() - tail);
    ssize_t n = read(src, ring.buf.data() + tail, chunk);
    if (n > 0) {
      ring.size += (size_t)n;
      // opportunistic immediate write-through (zero-latency splice)
      while (!ring.empty()) {
        size_t c2 = std::min(ring.size, ring.cap() - ring.head);
        ssize_t w = write(dst, ring.buf.data() + ring.head, c2);
        count_write(w, c2);
        if (w > 0) {
          ring.head = (ring.head + (size_t)w) % ring.cap();
          ring.size -= (size_t)w;
          counter += (uint64_t)w;
        } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          break;
        } else {
          pump_kill(l, p, errno ? errno : EPIPE);
          return false;
        }
      }
    } else if (n == 0) {
      src_eof = true;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      break;
    } else {
      pump_kill(l, p, errno);
      return false;
    }
  }
  // src closed and everything flushed -> propagate FIN (unless the
  // whole pump is about to die: close() sends it for free)
  if (src_eof && ring.empty() && !dst_shut) {
    if (!peer_done) shutdown(dst, SHUT_WR);
    dst_shut = true;
  }
  return true;
}

// ---- TLS-terminating pump: A = TLS client side (SSL owns the record
// layer over fd_a), B = plaintext backend. The same ring discipline as
// pump_flow, with SSL_read/SSL_write in place of read/write on A and
// WANT_READ/WANT_WRITE driving A's epoll interest (renegotiations and
// mid-write stalls included).

static void tls_update_interest(Loop* l, Pump* p);

// classify an SSL_* return: 0 = want/eof handled (flags set), -1 = killed
static int tls_err(Loop* l, Pump* p, int r, bool* eof_out,
                   bool* want_read, bool* want_write) {
  int e = TLSA.SSL_get_error((SSL_*)p->ssl, r);
  if (e == SSL_ERROR_WANT_READ_) {
    if (want_read) *want_read = true;
    return 0;
  }
  if (e == SSL_ERROR_WANT_WRITE_) {
    if (want_write) *want_write = true;
    return 0;
  }
  if (e == SSL_ERROR_ZERO_RETURN_ && eof_out) { *eof_out = true; return 0; }
  if (e == SSL_ERROR_SYSCALL_ && eof_out && (errno == 0 || r == 0)) {
    *eof_out = true;  // peer dropped without close_notify
    return 0;
  }
  pump_kill(l, p, e == SSL_ERROR_SYSCALL_ && errno ? errno : EPROTO);
  return -1;
}

static void tls_pump_run(Loop* l, Pump* p) {
  if (p->dead) return;
  p->rd_want_write = p->wr_want_read = p->wr_want_write = false;
  p->hs_want_write = false;
  TLSA.ERR_clear_error();
  SSL_* ssl = (SSL_*)p->ssl;
  if (p->handshaking) {
    int r = TLSA.SSL_do_handshake(ssl);
    if (r == 1) {
      p->handshaking = false;
      g_tls_handshakes.fetch_add(1, std::memory_order_relaxed);
    } else {
      bool dummy = false;
      if (tls_err(l, p, r, nullptr, &dummy, &p->hs_want_write) < 0) return;
      tls_update_interest(l, p);
      return;
    }
  }
  // flush decrypted a2b -> B
  Ring& ab = p->a2b;
  while (!ab.empty()) {
    size_t chunk = std::min(ab.size, ab.cap() - ab.head);
    ssize_t n = write(p->fd_b, ab.buf.data() + ab.head, chunk);
    count_write(n, chunk);
    if (n > 0) {
      ab.head = (ab.head + (size_t)n) % ab.cap();
      ab.size -= (size_t)n;
      p->bytes_a2b += (uint64_t)n;
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      break;
    } else {
      pump_kill(l, p, errno ? errno : EPIPE);
      return;
    }
  }
  // SSL_read A -> a2b (with plaintext write-through to B)
  while (!p->a_eof && !ab.full()) {
    size_t tail = (ab.head + ab.size) % ab.cap();
    size_t chunk = std::min(ab.free_(), ab.cap() - tail);
    int n = TLSA.SSL_read(ssl, ab.buf.data() + tail, (int)chunk);
    if (n > 0) {
      ab.size += (size_t)n;
      while (!ab.empty()) {
        size_t c2 = std::min(ab.size, ab.cap() - ab.head);
        ssize_t w = write(p->fd_b, ab.buf.data() + ab.head, c2);
        count_write(w, c2);
        if (w > 0) {
          ab.head = (ab.head + (size_t)w) % ab.cap();
          ab.size -= (size_t)w;
          p->bytes_a2b += (uint64_t)w;
        } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          break;
        } else {
          pump_kill(l, p, errno ? errno : EPIPE);
          return;
        }
      }
    } else {
      bool dummy = false;
      if (tls_err(l, p, n, &p->a_eof, &dummy, &p->rd_want_write) < 0)
        return;
      break;  // WANT_READ here is the normal no-more-records state
    }
  }
  if (p->a_eof && ab.empty() && !p->b_wr_shut) {
    shutdown(p->fd_b, SHUT_WR);
    p->b_wr_shut = true;
  }
  // flush b2a -> SSL_write A
  Ring& ba = p->b2a;
  while (!ba.empty() && !p->wr_want_read && !p->wr_want_write) {
    size_t chunk = std::min(ba.size, ba.cap() - ba.head);
    int n = TLSA.SSL_write(ssl, ba.buf.data() + ba.head, (int)chunk);
    count_write(n, chunk);
    if (n > 0) {
      ba.head = (ba.head + (size_t)n) % ba.cap();
      ba.size -= (size_t)n;
      p->bytes_b2a += (uint64_t)n;
    } else {
      if (tls_err(l, p, n, nullptr, &p->wr_want_read,
                  &p->wr_want_write) < 0)
        return;
      break;
    }
  }
  // read B -> b2a (with SSL_write-through); the ring gives backpressure
  while (!p->b_eof && !ba.full()) {
    size_t tail = (ba.head + ba.size) % ba.cap();
    size_t chunk = std::min(ba.free_(), ba.cap() - tail);
    ssize_t n = read(p->fd_b, ba.buf.data() + tail, chunk);
    if (n > 0) {
      ba.size += (size_t)n;
      while (!ba.empty() && !p->wr_want_read && !p->wr_want_write) {
        size_t c2 = std::min(ba.size, ba.cap() - ba.head);
        int w = TLSA.SSL_write(ssl, ba.buf.data() + ba.head, (int)c2);
        count_write(w, c2);
        if (w > 0) {
          ba.head = (ba.head + (size_t)w) % ba.cap();
          ba.size -= (size_t)w;
          p->bytes_b2a += (uint64_t)w;
        } else {
          if (tls_err(l, p, w, nullptr, &p->wr_want_read,
                      &p->wr_want_write) < 0)
            return;
          break;
        }
      }
    } else if (n == 0) {
      p->b_eof = true;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      break;
    } else {
      pump_kill(l, p, errno);
      return;
    }
  }
  if (p->b_eof && ba.empty() && !p->a_wr_shut) {
    TLSA.SSL_shutdown(ssl);  // close_notify (best effort, nonblocking)
    shutdown(p->fd_a, SHUT_WR);
    p->a_wr_shut = true;
  }
  if (p->a_eof && p->b_eof && ab.empty() && ba.empty()) {
    pump_kill(l, p, 0);
    return;
  }
  tls_update_interest(l, p);
}

static void tls_update_interest(Loop* l, Pump* p) {
  auto ha = l->handlers.find(p->fd_a);
  auto hb = l->handlers.find(p->fd_b);
  if (ha == l->handlers.end() || hb == l->handlers.end()) return;
  uint32_t ia = 0, ib = 0;
  if (p->handshaking) {
    ia = p->hs_want_write ? VTL_EV_WRITE : VTL_EV_READ;
  } else {
    if (p->wr_want_read || (!p->a_eof && !p->a2b.full()))
      ia |= VTL_EV_READ;
    if (p->rd_want_write || p->wr_want_write) ia |= VTL_EV_WRITE;
    if (!p->b_eof && !p->b2a.full()) ib |= VTL_EV_READ;
    if (!p->a2b.empty()) ib |= VTL_EV_WRITE;
  }
  if (ha->second->interest != ia) ep_set(l, ha->second, ia);
  if (hb->second->interest != ib) ep_set(l, hb->second, ib);
}

static void pump_run(Loop* l, Pump* p) {
  if (p->dead) return;
  if (p->ssl) {
    tls_pump_run(l, p);
    return;
  }
  if (!pump_flow(l, p, p->fd_a, p->fd_b, p->a2b, p->a_eof, p->b_wr_shut,
                 p->bytes_a2b, p->b_eof && p->b2a.empty()))
    return;
  if (!pump_flow(l, p, p->fd_b, p->fd_a, p->b2a, p->b_eof, p->a_wr_shut,
                 p->bytes_b2a, p->a_eof && p->a2b.empty()))
    return;
  if (p->a_eof && p->b_eof && p->a2b.empty() && p->b2a.empty()) {
    pump_kill(l, p, 0);
    return;
  }
  pump_update_interest(l, p);
}

static void pump_update_interest(Loop* l, Pump* p) {
  auto ha = l->handlers.find(p->fd_a);
  auto hb = l->handlers.find(p->fd_b);
  if (ha == l->handlers.end() || hb == l->handlers.end()) return;
  uint32_t ia = 0, ib = 0;
  if (!p->a_eof && !p->a2b.full()) ia |= VTL_EV_READ;
  if (!p->b2a.empty()) ia |= VTL_EV_WRITE;
  if (!p->b_eof && !p->b2a.full()) ib |= VTL_EV_READ;
  if (!p->a2b.empty()) ib |= VTL_EV_WRITE;
  if (ha->second->interest != ia) ep_set(l, ha->second, ia);
  if (hb->second->interest != ib) ep_set(l, hb->second, ib);
}

// connect-failure teardown: like pump_kill but fd_a stays OPEN and
// unregistered — the python retry layer owns the client fd again and
// either re-dials another backend or closes it.
static void pump_fail_connect(Loop* l, Pump* p, int err) {
  if (p->dead) return;
  p->dead = true;
  p->err = err;
  p->connect_failed = true;
  {  // fd_a stays OPEN for the retry layer: a real DEL is required
    auto it = l->handlers.find(p->fd_a);
    if (it != l->handlers.end()) {
      if (it->second->interest != (uint32_t)-1) loop_detach(l, it->second);
      drop_handler(l, it->second);
      l->handlers.erase(it);
    }
  }
  {
    auto it = l->handlers.find(p->fd_b);
    if (it != l->handlers.end()) {
      loop_detach(l, it->second);
      drop_handler(l, it->second);
      l->handlers.erase(it);
    }
  }
  close(p->fd_b);
  l->done_pumps.push_back(p->id);
}

static uint64_t mono_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000u + (uint64_t)(ts.tv_nsec / 1000);
}

static uint64_t mono_ns() {
  // CLOCK_MONOTONIC ns — the SAME clock python's time.monotonic_ns()
  // reads on linux, so C-plane and python-plane spans of one trace
  // order consistently without any epoch translation
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

static void pump_set_nodelay(int fd_a, int fd_b) {
  // both sockets, in C: two fewer python->C crossings per session than
  // the old explicit vtl_set_nodelay pair (non-TCP fds just ENOPROTOOPT)
  int one = 1;
  setsockopt(fd_a, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  setsockopt(fd_b, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

uint64_t vtl_pump_new(void* lp, int fd_a, int fd_b, int bufsize) {
  Loop* l = (Loop*)lp;
  if (l->handlers.count(fd_a) || l->handlers.count(fd_b)) return 0;
  pump_set_nodelay(fd_a, fd_b);
  uint64_t id = l->next_pump_id++;
  Pump* p = new Pump(id, fd_a, fd_b, (size_t)bufsize);
  Handler* ha = new Handler{Handler::PUMP_A, id, p, fd_a, (uint32_t)-1};
  Handler* hb = new Handler{Handler::PUMP_B, id, p, fd_b, (uint32_t)-1};
  l->handlers[fd_a] = ha;
  l->handlers[fd_b] = hb;
  l->valid.insert(ha);
  l->valid.insert(hb);
  l->pumps[id] = p;
  ep_set(l, ha, VTL_EV_READ);
  ep_set(l, hb, VTL_EV_READ);
  pump_run(l, p);  // kick: there may be buffered bytes ready to read
  return id;
}

// The accept fast lane: socket + TCP_NODELAY + nonblocking connect +
// pump registration in ONE python->C crossing (the python path costs
// ~8: tcp_connect, epoll add/mod x3, finish_connect, nodelay x2, pump).
// The pump idles until the connect resolves — the client's early bytes
// wait in the kernel, exactly like the python path's pause_reading —
// then splices as if vtl_pump_new had been called. A refused/unreachable
// backend surfaces as PUMP_DONE with the connect_failed flag
// (vtl_pump_stat2 out[3] bit0) and fd_a left open for the retry layer.
static uint64_t pump_connect_impl(Loop* l, int fd_a, const sockaddr* sa,
                                  socklen_t slen, int bufsize) {
  if (l->handlers.count(fd_a)) return 0;
  int v6 = sa->sa_family == AF_INET6;
  int fd_b = socket(v6 ? AF_INET6 : AF_INET,
                    SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd_b < 0) return 0;
  pump_set_nodelay(fd_a, fd_b);
  int r = connect(fd_b, sa, slen);
  if (r < 0 && errno != EINPROGRESS) {
    int saved = errno;
    close(fd_b);
    errno = saved;  // lanes report the sync refusal's errno in the punt
    return 0;  // sync refusal: caller falls back to the python path
  }
  uint64_t id = l->next_pump_id++;
  Pump* p = new Pump(id, fd_a, fd_b, (size_t)bufsize);
  p->b_connecting = (r < 0);
  p->created_us = mono_us();
  if (!p->b_connecting) p->connect_us = 0;  // resolved synchronously
  Handler* ha = new Handler{Handler::PUMP_A, id, p, fd_a, (uint32_t)-1};
  Handler* hb = new Handler{Handler::PUMP_B, id, p, fd_b, (uint32_t)-1};
  l->handlers[fd_a] = ha;
  l->handlers[fd_b] = hb;
  l->valid.insert(ha);
  l->valid.insert(hb);
  l->pumps[id] = p;
  if (p->b_connecting) {
    // fd_a stays UNREGISTERED until the backend resolves (its interest
    // is -1; the resolve path's ep_set does the ADD): one epoll_ctl
    // fewer per session, and the client's early bytes wait in the
    // kernel either way. A client RST mid-connect is noticed at
    // resolve time (read error) or the connect deadline — bounded.
    ep_set(l, hb, VTL_EV_WRITE);  // connect completion
  } else {  // loopback can complete synchronously
    ep_set(l, ha, VTL_EV_READ);
    ep_set(l, hb, VTL_EV_READ);
    pump_run(l, p);
  }
  return id;
}

uint64_t vtl_pump_connect(void* lp, int fd_a, const char* ip, int port,
                          int v6, int bufsize) {
  sockaddr_storage ss;
  socklen_t slen;
  if (mk_addr(ip, port, v6, &ss, &slen) < 0) return 0;
  return pump_connect_impl((Loop*)lp, fd_a, (sockaddr*)&ss, slen, bufsize);
}

// connect-timeout hook: if `id` is still mid-connect, fail it like a
// refused connect (DONE + connect_failed, fd_a kept). No-op otherwise.
int vtl_pump_abort_connect(void* lp, uint64_t id) {
  Loop* l = (Loop*)lp;
  auto it = l->pumps.find(id);
  if (it == l->pumps.end() || !it->second->b_connecting ||
      it->second->dead)
    return 0;
  pump_fail_connect(l, it->second, ETIMEDOUT);
  return 1;
}

// TLS-terminating pump: fd_tls speaks TLS (server role, handshake
// included — the ClientHello is still queued in the socket thanks to
// the MSG_PEEK sniffer), fd_plain is the backend. Same id space /
// stat / close / free / DONE notification as the plain pump.
uint64_t vtl_tls_pump_new(void* lp, int fd_tls, int fd_plain, int bufsize,
                          long long ctx) {
  if (!TLSA.ready || !ctx) return 0;
  Loop* l = (Loop*)lp;
  if (l->handlers.count(fd_tls) || l->handlers.count(fd_plain)) return 0;
  pump_set_nodelay(fd_tls, fd_plain);
  SSL_* ssl = TLSA.SSL_new((SSL_CTX_*)(intptr_t)ctx);
  if (!ssl) return 0;
  if (TLSA.SSL_set_fd(ssl, fd_tls) != 1) {
    TLSA.SSL_free(ssl);
    return 0;
  }
  TLSA.SSL_set_accept_state(ssl);
  uint64_t id = l->next_pump_id++;
  Pump* p = new Pump(id, fd_tls, fd_plain, (size_t)bufsize);
  p->ssl = ssl;
  p->handshaking = true;
  Handler* ha = new Handler{Handler::PUMP_A, id, p, fd_tls, (uint32_t)-1};
  Handler* hb = new Handler{Handler::PUMP_B, id, p, fd_plain, (uint32_t)-1};
  l->handlers[fd_tls] = ha;
  l->handlers[fd_plain] = hb;
  l->valid.insert(ha);
  l->valid.insert(hb);
  l->pumps[id] = p;
  ep_set(l, ha, VTL_EV_READ);
  ep_set(l, hb, VTL_EV_READ);
  pump_run(l, p);  // the peeked ClientHello is already readable
  return id;
}

// stats: out[0]=bytes_a2b, out[1]=bytes_b2a, out[2]=err, returns 0/-ENOENT
int vtl_pump_stat(void* lp, uint64_t id, uint64_t* out) {
  Loop* l = (Loop*)lp;
  auto it = l->pumps.find(id);
  if (it == l->pumps.end()) return -ENOENT;
  out[0] = it->second->bytes_a2b;
  out[1] = it->second->bytes_b2a;
  out[2] = (uint64_t)it->second->err;
  return 0;
}

// stat + flags: out[3] bit0 = connect_failed (vtl_pump_connect pumps
// whose backend never came up — fd_a is still open, python retries),
// bit1 = still mid-connect; out[4] = resolved backend-connect duration
// in us (0 when unresolved/unknown — callers gate on the flags)
int vtl_pump_stat2(void* lp, uint64_t id, uint64_t* out) {
  Loop* l = (Loop*)lp;
  auto it = l->pumps.find(id);
  if (it == l->pumps.end()) return -ENOENT;
  Pump* p = it->second;
  out[0] = p->bytes_a2b;
  out[1] = p->bytes_b2a;
  out[2] = (uint64_t)p->err;
  out[3] = (p->connect_failed ? 1u : 0u) | (p->b_connecting ? 2u : 0u);
  out[4] = p->connect_us == (uint64_t)-1 ? 0 : p->connect_us;
  return 0;
}

int vtl_pump_close(void* lp, uint64_t id) {
  Loop* l = (Loop*)lp;
  auto it = l->pumps.find(id);
  if (it == l->pumps.end()) return -ENOENT;
  pump_kill(l, it->second, 0);
  return 0;
}

// free a DONE pump's memory (after python saw VTL_EV_PUMP_DONE)
int vtl_pump_free(void* lp, uint64_t id) {
  Loop* l = (Loop*)lp;
  auto it = l->pumps.find(id);
  if (it == l->pumps.end()) return -ENOENT;
  if (!it->second->dead) pump_kill(l, it->second, 0);
  delete it->second;
  l->pumps.erase(it);
  return 0;
}

// ------------------------------------------------------------------ poll

int vtl_poll(void* lp, uint64_t* tags, uint32_t* evs, int max,
             int timeout_ms) {
  Loop* l = (Loop*)lp;
  for (Handler* g : l->garbage) delete g;
  l->garbage.clear();
  // deliver pending pump-done notifications first
  int out = 0;
  auto flush_done = [&]() {
    while (!l->done_pumps.empty() && out < max) {
      tags[out] = l->done_pumps.back();
      evs[out] = VTL_EV_PUMP_DONE;
      l->done_pumps.pop_back();
      ++out;
    }
  };
  flush_done();
  if (out > 0) return out;

  epoll_event eps[256];
  int cap = 256 < max ? 256 : max;
  int n = epoll_wait(l->ep, eps, cap, timeout_ms);
  if (n < 0) return errno == EINTR ? 0 : -errno;
  for (int i = 0; i < n; ++i) {
    Handler* h = (Handler*)eps[i].data.ptr;
    if (!l->valid.count(h)) continue;  // torn down earlier in this batch
    uint32_t e = eps[i].events;
    switch (h->kind) {
      case Handler::WAKE: {
        uint64_t v;
        while (read(l->wakefd, &v, 8) == 8) {}
        break;
      }
      case Handler::PY: {
        uint32_t ve = 0;
        if (e & (EPOLLIN | EPOLLHUP)) ve |= VTL_EV_READ;
        if (e & EPOLLOUT) ve |= VTL_EV_WRITE;
        if (e & EPOLLERR) ve |= VTL_EV_ERROR;
        if (ve && out < max) {
          tags[out] = h->tag;
          evs[out] = ve;
          ++out;
        }
        break;
      }
      case Handler::PUMP_A:
      case Handler::PUMP_B: {
        Pump* p = h->pump;
        if (h->kind == Handler::PUMP_B && p->b_connecting) {
          // fast-lane connect resolution: SO_ERROR decides. EPOLLHUP
          // with SO_ERROR==0 is a SUCCESSFUL connect whose peer already
          // closed (e.g. a draining backend shedding on accept) — that
          // must flow as a normal short session (EOF through the pump),
          // NOT as connect_failed: the python path treats the same
          // event as connected-then-closed, and a report_failure here
          // would feed a healthy backend's ejection streak.
          int err = 0;
          socklen_t elen = sizeof(err);
          getsockopt(h->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
          if (err) {
            pump_fail_connect(l, p, err);
          } else {
            p->b_connecting = false;
            p->connect_us = mono_us() - p->created_us;
            Handler* ha = l->handlers.count(p->fd_a)
                              ? l->handlers[p->fd_a] : nullptr;
            if (ha) ep_set(l, ha, VTL_EV_READ);
            ep_set(l, h, VTL_EV_READ);
            pump_run(l, p);  // early client bytes may already be queued
          }
          break;
        }
        if (e & EPOLLERR) {
          int err = 0;
          socklen_t elen = sizeof(err);
          getsockopt(h->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
          pump_kill(l, p, err ? err : EIO);
        } else {
          pump_run(l, p);
        }
        break;
      }
      default:
        break;  // LANE handlers never live on python loops
    }
  }
  flush_done();
  return out;
}

void vtl_free(void* lp) {
  Loop* l = (Loop*)lp;
  for (Handler* g : l->garbage) delete g;
  for (auto& kv : l->pumps) {
    if (!kv.second->dead) {  // live spliced sessions: close both fds
      if (kv.second->ssl) TLSA.SSL_free((SSL_*)kv.second->ssl);
      close(kv.second->fd_a);
      close(kv.second->fd_b);
    }
    delete kv.second;
  }
  for (auto& kv : l->handlers) delete kv.second;
  if (l->ur) uring_free(l->ur);
  if (l->ep >= 0) close(l->ep);
  if (l->wakefd >= 0) close(l->wakefd);
  delete l;
}

int vtl_errno_eagain() { return EAGAIN; }

// ------------------------------------------------------ switch flow cache
//
// The switch's repeat-flow fast lane (the Maglev/Ananta split: a slow
// "first packet" policy path in Python, a cached-flow path that never
// leaves C). vtl_switch_poll drains the switch's UDP socket with
// recvmmsg, probes an open-addressed exact-match flow table keyed on
// (sender, vni, eth_dst, eth_type, v4 src/dst/proto), and for hits
// applies the resolved action — raw forward, routed header rewrite
// (vni + macs + ttl-1 + RFC 1624 incremental checksum), or DROP with a
// reason — batching forwards into one sendmmsg per egress destination.
// Misses and non-fast frames are compacted into the caller's buffers
// exactly like vtl_recvmmsg output, so Python consumes them as a normal
// burst and (after classifying) installs entries via vtl_flow_install.
//
// Correctness is generation-gated: every route/ACL/MAC/ARP/iface
// mutation bumps the table's generation (vtl_switch_gen_bump, a single
// atomic — callable from any thread); entries carry the generation they
// were compiled under and a mismatched probe is a forced miss, so a
// rule change can never forward through a stale action. Entries also
// expire after a TTL and evict LRU-ish within the probe window.
// Table memory is only touched from the owning loop thread (poll +
// install both run there); only the generation atomic crosses threads.

// traffic-analytics knob + process tallies (the heavy-hitter plane,
// full machinery below at "traffic analytics"): the flow cache's
// per-entry hit tallies and the lanes' HH shards both gate on this one
// relaxed load — knob-off cost on every C hot path is exactly that.
static std::atomic<int> g_hh_on(0);
static std::atomic<uint64_t> g_hh_updates(0), g_hh_overflow(0);

// workload-capture knob (r16): the accept lanes' inter-arrival and
// per-connection bytes/duration histograms gate on this one relaxed
// load, exactly like g_hh_on gates the HH shards — knob-off cost on
// the accept/reap paths is that single load. Python pushes it from
// utils/workload.configure() (same idiom as sketch.push_native_knob).
static std::atomic<int> g_wl_on(0);

// policing knob (r19): the accept lanes' POLICE_REC probe gates on this
// one relaxed load — the knob-off cost per C site, exactly like g_hh_on
// gates the HH shards. Python pushes it from policing/engine.configure()
// (same idiom as sketch/workload push_native_knob).
static std::atomic<int> g_police_on(0);

#pragma pack(push, 1)
struct FlowKey {          // 26 bytes; must match vtl.py FLOW_REC prefix
  uint32_t sender_ip;     // host-order u32 of the v4 sender addr
  uint16_t sender_port;
  uint8_t vni[3];         // wire vni bytes (pre-override)
  uint8_t eth_dst[6];
  uint8_t eth_type[2];
  uint8_t ip_src[4];      // zeros unless v4/IHL=5 with a sane length
  uint8_t ip_dst[4];
  uint8_t proto;
};
struct FlowRec {          // install record; must match vtl.py FLOW_REC
  FlowKey key;
  uint8_t action;         // FC_ACT_*
  uint8_t flags;          // bit0 = routed rewrite
  uint8_t drop_reason;    // index into the shared drop-reason table
  uint8_t new_vni[3];     // effective/target vni to stamp on egress
  uint8_t new_dst[6];     // routed rewrite template
  uint8_t new_src[6];
  uint32_t out_ip;        // host-order u32 v4 egress addr (FC_ACT_FWD)
  uint16_t out_port;
  int32_t tap_fd;         // FC_ACT_TAP egress fd
};
#pragma pack(pop)
static_assert(sizeof(FlowKey) == 26, "FlowKey ABI drifted");
static_assert(sizeof(FlowRec) == 54, "FlowRec ABI drifted");

#define FC_ACT_EMPTY 0
#define FC_ACT_FWD 1
#define FC_ACT_TAP 2
#define FC_ACT_DROP 3
#define FC_FLAG_ROUTED 1u
// drop reasons (shared contract with net/vtl.py FLOW_DROP_REASONS):
// 0 acl_deny, 1 same_iface, 2 route_miss, 3 unknown_vni,
// 4 egress_short_write, 5 other
#define FC_DROP_REASONS 6
#define FC_R_EGRESS 4
#define FC_PROBE 8

struct FlowEntry {
  FlowKey key;
  uint8_t action, flags, drop_reason;
  uint8_t new_vni[3], new_dst[6], new_src[6];
  uint32_t out_ip;
  uint16_t out_port;
  int32_t tap_fd;
  uint64_t gen, expire_us, last_hit_us;
  // per-flow hit tally for the analytics plane (vtl_hh_flow_drain):
  // bumped by probe hits (atomic relaxed — N poller threads), drained
  // with exchange(0) by the switch's analytics tick. Like last_hit_us
  // it is mutated from both sides, so it is atomic everywhere.
  uint64_t hh_hits;
  // per-entry seqlock: the table is probed by N poller threads
  // (SO_REUSEPORT multiqueue) while the loop thread installs. Writers
  // (install only — probes never mutate entries beyond the benign
  // last_hit_us stat) bump to odd, write, bump to even; readers retry
  // as a miss on any seq movement. Entries are 1 writer / N readers.
  uint32_t seq;
};

struct FlowCache {
  std::vector<FlowEntry> slots;
  uint32_t mask = 0;
  uint64_t ttl_us = 0;
  std::atomic<uint64_t> gen{0};
  uint64_t used = 0;
  // maglev slot->candidate table (vtl_flow_maglev_install): loop-thread
  // only, like the slot vector — the compiler installs and picks from
  // the same thread that polls
  std::vector<int32_t> maglev;
  // per-table probe outcomes (the globals blend every switch in the
  // process; list-detail switch wants THIS switch's hit rate)
  std::atomic<uint64_t> hits{0}, misses{0};
  // vtl_hh_flow_drain's walk cursor (one caller by contract: the
  // owning switch's analytics tick)
  uint64_t hh_cursor = 0;
};

// process-global counters (all switches), pump_counters idiom
static std::atomic<uint64_t> g_fc_hit(0), g_fc_miss(0), g_fc_evict(0),
    g_fc_stale(0), g_fc_fwd(0);
static std::atomic<uint64_t> g_fc_drop[FC_DROP_REASONS];

// ------------- seqlock data plane: intentionally-racy, confined -------------
//
// The flow table is 1 writer (vtl_flow_install, the owning loop
// thread) / N readers (fc_probe on the SO_REUSEPORT poller threads).
// The seq word plus the fences carry all ordering; the entry PAYLOAD
// is read while the writer may be mid-write BY DESIGN — a torn read
// is discarded by the seq re-check that brackets the copy, and a
// discarded probe is a miss (always safe: Python re-decides). C++
// cannot express "benign under a seqlock" short of making every field
// atomic, so the racy accesses are confined to these two helpers and
// compiled without TSan instrumentation; everything OUTSIDE them
// operates on the consistent copy and stays fully checked (`make
// sanitize` + tests/test_sanitize.py, docs/static-analysis.md).
// last_hit_us is the one field mutated from BOTH sides (probes stamp
// hits, install reads it for LRU picks), so it is atomic everywhere.

// GCC defines __SANITIZE_THREAD__; clang spells it __has_feature
#if defined(__has_feature)
#if __has_feature(thread_sanitizer) && !defined(__SANITIZE_THREAD__)
#define __SANITIZE_THREAD__ 1
#endif
#endif
#if defined(__SANITIZE_THREAD__)
// noinline matters: inlined into an instrumented caller, the body
// would be re-instrumented and the annotation silently dropped
#define VTL_NO_TSAN __attribute__((no_sanitize("thread"), noinline))
#else
#define VTL_NO_TSAN
#endif

VTL_NO_TSAN static void fc_racy_copy(FlowEntry* out,
                                     const FlowEntry& e) {
#if defined(__SANITIZE_THREAD__)
  // volatile word loop: libc memcpy would report through the
  // annotation via the TSan interceptor (production keeps memcpy)
  const volatile unsigned char* s = (const volatile unsigned char*)&e;
  unsigned char* d = (unsigned char*)out;
  for (size_t i = 0; i < sizeof(FlowEntry); ++i) d[i] = s[i];
#else
  memcpy(out, &e, sizeof(FlowEntry));
#endif
}

VTL_NO_TSAN static void fc_racy_write(FlowEntry* dst, const FlowRec& rec,
                                      uint64_t gen, uint64_t now,
                                      uint64_t expire) {
  dst->key = rec.key;
  dst->action = rec.action;
  dst->flags = rec.flags;
  dst->drop_reason = rec.drop_reason < FC_DROP_REASONS
                         ? rec.drop_reason : FC_DROP_REASONS - 1;
  memcpy(dst->new_vni, rec.new_vni, 3);
  memcpy(dst->new_dst, rec.new_dst, 6);
  memcpy(dst->new_src, rec.new_src, 6);
  dst->out_ip = rec.out_ip;
  dst->out_port = rec.out_port;
  dst->tap_fd = rec.tap_fd;
  dst->gen = gen;
  dst->expire_us = expire;
  __atomic_store_n(&dst->last_hit_us, now, __ATOMIC_RELAXED);
  // a reused slot must not credit the new flow with the old flow's
  // pending analytics hits (one drain interval of misattribution)
  __atomic_store_n(&dst->hh_hits, 0ull, __ATOMIC_RELAXED);
}

static uint64_t fc_hash(const FlowKey& k) {
  const uint8_t* p = (const uint8_t*)&k;
  uint64_t h = 1469598103934665603ull;  // FNV-1a 64
  for (size_t i = 0; i < sizeof(FlowKey); ++i) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

void* vtl_flowcache_new(int size, int ttl_ms) {
  uint32_t cap = 256;
  while (cap < (uint32_t)size && cap < (1u << 22)) cap <<= 1;
  FlowCache* fc = new FlowCache();
  fc->slots.assign(cap, FlowEntry());  // value-init: action == EMPTY
  fc->mask = cap - 1;
  fc->ttl_us = (uint64_t)(ttl_ms > 0 ? ttl_ms : 10000) * 1000u;
  return fc;
}

void vtl_flowcache_free(void* p) { delete (FlowCache*)p; }

void vtl_switch_gen_bump(void* p) {
  ((FlowCache*)p)->gen.fetch_add(1, std::memory_order_relaxed);
}

uint64_t vtl_switch_gen(void* p) {
  return ((FlowCache*)p)->gen.load(std::memory_order_relaxed);
}

int vtl_flow_rec_size(void) { return (int)sizeof(FlowRec); }

// out: hit, miss, evict, stale, fwd, drop[FC_DROP_REASONS]; -> count
int vtl_flowcache_counters(uint64_t* out) {
  out[0] = g_fc_hit.load(std::memory_order_relaxed);
  out[1] = g_fc_miss.load(std::memory_order_relaxed);
  out[2] = g_fc_evict.load(std::memory_order_relaxed);
  out[3] = g_fc_stale.load(std::memory_order_relaxed);
  out[4] = g_fc_fwd.load(std::memory_order_relaxed);
  for (int i = 0; i < FC_DROP_REASONS; ++i)
    out[5 + i] = g_fc_drop[i].load(std::memory_order_relaxed);
  return 5 + FC_DROP_REASONS;
}

// out[0]=capacity, out[1]=used slots, out[2]=generation,
// out[3]=hits, out[4]=misses (this table only); -> 5
int vtl_flowcache_stat(void* p, uint64_t* out) {
  FlowCache* fc = (FlowCache*)p;
  out[0] = fc->mask + 1;
  out[1] = fc->used;
  out[2] = fc->gen.load(std::memory_order_relaxed);
  out[3] = fc->hits.load(std::memory_order_relaxed);
  out[4] = fc->misses.load(std::memory_order_relaxed);
  return 5;
}

// Install n FlowRecs compiled by the Python fast path, stamped with the
// generation read BEFORE classification began: if anything mutated
// since, the whole batch is conservatively skipped (the flows re-miss
// and recompile against current tables). -> entries installed.
int vtl_flow_install(void* p, const void* recs, int n, uint64_t gen) {
  FlowCache* fc = (FlowCache*)p;
  uint64_t cur = fc->gen.load(std::memory_order_relaxed);
  if (gen != cur) return 0;
  uint64_t now = mono_us();
  const FlowRec* r = (const FlowRec*)recs;
  int installed = 0;
  for (int i = 0; i < n; ++i) {
    const FlowRec& rec = r[i];
    if (rec.action == FC_ACT_EMPTY) continue;
    uint64_t h = fc_hash(rec.key);
    FlowEntry *match = nullptr, *freeslot = nullptr, *lru = nullptr;
    for (int k = 0; k < FC_PROBE; ++k) {
      FlowEntry& e = fc->slots[(h + (uint64_t)k) & fc->mask];
      if (e.action == FC_ACT_EMPTY) {
        if (!freeslot) freeslot = &e;
        continue;
      }
      if (!memcmp(&e.key, &rec.key, sizeof(FlowKey))) {
        match = &e;
        break;
      }
      if (e.gen != cur || now >= e.expire_us) {
        if (!freeslot) freeslot = &e;
        continue;
      }
      // atomic: probes on other threads stamp last_hit_us on hits
      if (!lru || __atomic_load_n(&e.last_hit_us, __ATOMIC_RELAXED)
                      < __atomic_load_n(&lru->last_hit_us,
                                        __ATOMIC_RELAXED))
        lru = &e;
    }
    FlowEntry* dst = match ? match : (freeslot ? freeslot : lru);
    if (!dst) continue;
    if (!match && !freeslot)
      g_fc_evict.fetch_add(1, std::memory_order_relaxed);
    if (!match && freeslot && freeslot->action == FC_ACT_EMPTY) fc->used++;
    // seqlock write (install is the only entry mutator, loop thread)
    uint32_t s = __atomic_load_n(&dst->seq, __ATOMIC_RELAXED);
    __atomic_store_n(&dst->seq, s + 1, __ATOMIC_RELAXED);
    __atomic_thread_fence(__ATOMIC_SEQ_CST);
    fc_racy_write(dst, rec, gen, now, now + fc->ttl_us);
    __atomic_thread_fence(__ATOMIC_SEQ_CST);
    __atomic_store_n(&dst->seq, s + 2, __ATOMIC_RELEASE);
    ++installed;
  }
  return installed;
}

// Probe from any poller thread: copies the candidate entry out under
// its seqlock FIRST, then interprets only the consistent copy (any
// concurrent install movement degrades to a miss — safe, Python
// re-decides). Stale and expired entries are left for the install
// path to reclaim — readers never mutate table state beyond the
// atomic last_hit_us stat.
static bool fc_probe(FlowCache* fc, const FlowKey& key, uint64_t cur,
                     uint64_t now, FlowEntry* out) {
  uint64_t h = fc_hash(key);
  for (int k = 0; k < FC_PROBE; ++k) {
    FlowEntry& e = fc->slots[(h + (uint64_t)k) & fc->mask];
    uint32_t s1 = __atomic_load_n(&e.seq, __ATOMIC_ACQUIRE);
    if (s1 & 1) continue;  // mid-install: miss, reinstall will follow
    fc_racy_copy(out, e);  // seqlock-bracketed payload copy
    __atomic_thread_fence(__ATOMIC_ACQUIRE);
    if (__atomic_load_n(&e.seq, __ATOMIC_RELAXED) != s1)
      continue;  // THIS slot moved mid-copy (torn copy, untrusted):
                 // skip it — each slot's seqlock is independent, and
                 // the flow may live in a later, untouched slot
    if (out->action == FC_ACT_EMPTY) return false;
    if (memcmp(&out->key, &key, sizeof(FlowKey))) continue;
    if (out->gen != cur) {
      // the generation gate: a mutation since install forces a miss so
      // the Python policy path re-decides against current tables
      g_fc_stale.fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    if (now >= out->expire_us) return false;
    __atomic_store_n(&e.last_hit_us, now, __ATOMIC_RELAXED);
    if (g_hh_on.load(std::memory_order_relaxed))
      __atomic_fetch_add(&e.hh_hits, 1ull, __ATOMIC_RELAXED);
    return true;
  }
  return false;
}

static bool fc_ip4_csum_ok(const uint8_t* b) {
  uint32_t s = 0;
  for (int k = 0; k < 20; k += 2)
    s += ((uint32_t)b[22 + k] << 8) | b[23 + k];
  s = (s & 0xFFFF) + (s >> 16);
  s = (s & 0xFFFF) + (s >> 16);
  return s == 0xFFFF;
}

// The native forwarding loop: drain recvmmsg from the switch's UDP
// socket, forward/drop flow-table hits entirely in C, return misses in
// vtl_recvmmsg's output format (compacted to the front of the buffers).
// Returns the miss count; *drained = total datagrams consumed from the
// socket this call (hits + drops + misses). Loops until the socket is
// dry, a batch contains misses (those must reach Python in arrival
// order before we read more), or a 1024-datagram budget (the Python
// loop keeps calling while progress is made).
int vtl_switch_poll(void* fcp, int fd, void* buf, int slot, int maxmsgs,
                    int* lens, char* ips, int ipstride, int* ports,
                    int* drained) {
  FlowCache* fc = (FlowCache*)fcp;
  if (maxmsgs > 512) maxmsgs = 512;
  static thread_local mmsghdr hdrs[512];
  static thread_local iovec iovs[512];
  static thread_local sockaddr_storage addrs[512];
  static thread_local mmsghdr ehdrs[512];
  static thread_local iovec eiovs[512];
  uint64_t now = mono_us();
  int total = 0;
  *drained = 0;
  while (total < 1024) {
    // re-read per batch: a mutation landing mid-call (iface removal on
    // another thread) stops being forwarded within one recvmmsg round
    uint64_t cur = fc->gen.load(std::memory_order_relaxed);
    for (int i = 0; i < maxmsgs; ++i) {
      iovs[i].iov_base = (char*)buf + (size_t)i * slot;
      iovs[i].iov_len = (size_t)slot;
      memset(&hdrs[i].msg_hdr, 0, sizeof(msghdr));
      hdrs[i].msg_hdr.msg_iov = &iovs[i];
      hdrs[i].msg_hdr.msg_iovlen = 1;
      hdrs[i].msg_hdr.msg_name = &addrs[i];
      hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_storage);
    }
    int n = recvmmsg(fd, hdrs, (unsigned)maxmsgs, MSG_DONTWAIT, nullptr);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (total == 0) return -errno;
      break;
    }
    if (n == 0) break;
    total += n;
    int miss_idx[512];
    int nmiss = 0;
    struct Dest { uint32_t ip; uint16_t port; };
    Dest dests[64];
    int ndests = 0;
    struct Out { uint8_t* p; size_t len; int dest; };
    Out outs[512];
    int nouts = 0;
    for (int i = 0; i < n; ++i) {
      uint8_t* b = (uint8_t*)buf + (size_t)i * slot;
      int ln = (int)hdrs[i].msg_len;
      bool probed = false, consumed = false;
      // fast-eligible: a bare VXLAN frame (flags byte + reserved zeros,
      // big enough to carry eth+ipv4) from a v4 sender — everything
      // else (encrypted user frames, v6 senders, runts) goes to Python
      if (ln >= 42 && (b[0] & 0x08) && !b[1] && !b[2] &&
          addrs[i].ss_family == AF_INET) {
        probed = true;
        auto* sa = (sockaddr_in*)&addrs[i];
        FlowKey key;
        memset(&key, 0, sizeof(key));
        key.sender_ip = ntohl(sa->sin_addr.s_addr);
        key.sender_port = ntohs(sa->sin_port);
        memcpy(key.vni, b + 4, 3);
        memcpy(key.eth_dst, b + 8, 6);
        memcpy(key.eth_type, b + 20, 2);
        int ip_total = 0;
        if (b[20] == 0x08 && b[21] == 0x00 && b[22] == 0x45) {
          ip_total = (b[24] << 8) | b[25];
          if (ip_total >= 20 && ln >= 22 + ip_total) {
            memcpy(key.ip_src, b + 34, 4);
            memcpy(key.ip_dst, b + 38, 4);
            key.proto = b[31];
          } else {
            ip_total = 0;  // key stays zero-filled, like the compiler's
          }
        }
        FlowEntry ecopy;
        FlowEntry* e = fc_probe(fc, key, cur, now, &ecopy) ? &ecopy
                                                           : nullptr;
        if (e) {
          if (e->action == FC_ACT_DROP) {
            g_fc_drop[e->drop_reason].fetch_add(
                1, std::memory_order_relaxed);
            consumed = true;
          } else if ((e->flags & FC_FLAG_ROUTED) &&
                     (b[30] <= 1 || !fc_ip4_csum_ok(b))) {
            // ttl expiry (ICMP time-exceeded) and corrupt headers are
            // Python's: the object path answers/recomputes for parity
          } else {
            int outlen = ln;
            memcpy(b + 4, e->new_vni, 3);
            if (e->flags & FC_FLAG_ROUTED) {
              memcpy(b + 8, e->new_dst, 6);
              memcpy(b + 14, e->new_src, 6);
              b[30] -= 1;
              // RFC 1624 incremental update for the ttl decrement
              uint32_t c = ((uint32_t)b[32] << 8) | b[33];
              uint32_t x = (c ^ 0xFFFFu) + 0xFEFFu;
              x = (x & 0xFFFF) + (x >> 16);
              x = (x & 0xFFFF) + (x >> 16);
              c = x ^ 0xFFFFu;
              b[32] = (uint8_t)(c >> 8);
              b[33] = (uint8_t)(c & 0xFF);
              outlen = 22 + ip_total;  // the object path trims trailers
            }
            if (e->action == FC_ACT_TAP) {
              ssize_t w = write(e->tap_fd, b + 8, (size_t)(outlen - 8));
              if (w < 0)
                g_fc_drop[FC_R_EGRESS].fetch_add(
                    1, std::memory_order_relaxed);
              else
                g_fc_fwd.fetch_add(1, std::memory_order_relaxed);
              consumed = true;
            } else {
              int d = -1;
              for (int k = 0; k < ndests; ++k)
                if (dests[k].ip == e->out_ip &&
                    dests[k].port == e->out_port) {
                  d = k;
                  break;
                }
              if (d < 0 && ndests < 64) {
                dests[ndests].ip = e->out_ip;
                dests[ndests].port = e->out_port;
                d = ndests++;
              }
              if (d >= 0) {
                outs[nouts].p = b;
                outs[nouts].len = (size_t)outlen;
                outs[nouts].dest = d;
                ++nouts;
                consumed = true;
              }
              // >64 destinations in one batch: fall through as a miss,
              // Python's grouped egress handles it (never drop silently)
            }
          }
        }
      }
      if (consumed) {
        g_fc_hit.fetch_add(1, std::memory_order_relaxed);
        fc->hits.fetch_add(1, std::memory_order_relaxed);
      } else {
        if (probed) {
          g_fc_miss.fetch_add(1, std::memory_order_relaxed);
          fc->misses.fetch_add(1, std::memory_order_relaxed);
        }
        miss_idx[nmiss++] = i;
      }
    }
    // grouped egress: ONE sendmmsg per destination. Must flush before
    // the next recvmmsg round overwrites the datagram buffers.
    for (int d = 0; d < ndests; ++d) {
      sockaddr_in sa;
      memset(&sa, 0, sizeof(sa));
      sa.sin_family = AF_INET;
      sa.sin_addr.s_addr = htonl(dests[d].ip);
      sa.sin_port = htons(dests[d].port);
      int m = 0;
      for (int j = 0; j < nouts; ++j) {
        if (outs[j].dest != d) continue;
        eiovs[m].iov_base = outs[j].p;
        eiovs[m].iov_len = outs[j].len;
        memset(&ehdrs[m].msg_hdr, 0, sizeof(msghdr));
        ehdrs[m].msg_hdr.msg_iov = &eiovs[m];
        ehdrs[m].msg_hdr.msg_iovlen = 1;
        ehdrs[m].msg_hdr.msg_name = &sa;
        ehdrs[m].msg_hdr.msg_namelen = sizeof(sa);
        ++m;
      }
      int sent = sendmmsg(fd, ehdrs, (unsigned)m, 0);
      if (sent < 0) sent = 0;
      if (sent > 0) g_fc_fwd.fetch_add((uint64_t)sent,
                                       std::memory_order_relaxed);
      if (sent < m)  // datagram backpressure: dropped, and counted
        g_fc_drop[FC_R_EGRESS].fetch_add((uint64_t)(m - sent),
                                         std::memory_order_relaxed);
    }
    if (nmiss) {
      // compact misses into the caller's vtl_recvmmsg-shaped output;
      // inet_ntop only runs for misses (hits never pay it)
      for (int j = 0; j < nmiss; ++j) {
        int i = miss_idx[j];
        if (j != i)
          memmove((char*)buf + (size_t)j * slot,
                  (char*)buf + (size_t)i * slot, hdrs[i].msg_len);
        lens[j] = (int)hdrs[i].msg_len;
        char* ip = ips + (size_t)j * ipstride;
        ip[0] = 0;
        ports[j] = 0;
        if (addrs[i].ss_family == AF_INET) {
          auto* a = (sockaddr_in*)&addrs[i];
          inet_ntop(AF_INET, &a->sin_addr, ip, ipstride);
          ports[j] = ntohs(a->sin_port);
        } else if (addrs[i].ss_family == AF_INET6) {
          auto* a = (sockaddr_in6*)&addrs[i];
          inet_ntop(AF_INET6, &a->sin6_addr, ip, ipstride);
          ports[j] = ntohs(a->sin6_port);
        }
      }
      *drained = total;
      return nmiss;
    }
    if (n < maxmsgs) break;  // socket likely dry
  }
  *drained = total;
  return 0;
}

// ------------------------------------------------------ maglev lookup
//
// Maglev consistent-hash pick (Eisenbud NSDI'16): Python compiles the
// permutation-fill slot->backend table (rules/maglev.py) and installs
// it C-resident; the hot-path pick is one FNV-1a 64 over the client
// address bytes (+ port, big-endian, when per-connection spread is
// wanted — hash_port=0 is source affinity) and one table load. The
// SAME hash runs in rules/maglev.py and on the device gather column;
// tests/test_maglev.py proves all three planes pick identically.

static uint64_t maglev_fnv64(const uint8_t* p, size_t n) {
  // FNV-1a 64 with the REAL offset basis 0xCBF29CE484222325 — NOT
  // fc_hash's (that constant dropped a digit; harmless for an internal
  // table hash, fatal for the cross-plane pick parity contract with
  // rules/maglev.fnv64 and the device column)
  uint64_t h = 14695981039346656037ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

static int32_t maglev_lookup(const int32_t* tab, int m, const uint8_t* ip,
                             int iplen, int port, int hash_port) {
  if (!tab || m <= 0 || iplen <= 0 || iplen > 16) return -1;
  uint8_t buf[18];
  memcpy(buf, ip, (size_t)iplen);
  size_t n = (size_t)iplen;
  if (hash_port) {
    buf[n++] = (uint8_t)((port >> 8) & 0xFF);
    buf[n++] = (uint8_t)(port & 0xFF);
  }
  return tab[maglev_fnv64(buf, n) % (uint64_t)m];
}

// the parity surface: tests (and any host-side caller) pick through the
// EXACT code path the lanes use
int vtl_maglev_pick(const int32_t* table, int m, const void* ip, int iplen,
                    int port, int hash_port) {
  return maglev_lookup(table, m, (const uint8_t*)ip, iplen, port,
                       hash_port);
}

// sockaddr -> (raw addr bytes, port) for the pick; false for families
// with no address to hash (AF_UNIX)
static bool maglev_addr_bytes(const sockaddr_storage* ss, uint8_t* out,
                              int* iplen, int* port) {
  if (ss->ss_family == AF_INET) {
    auto* a = (const sockaddr_in*)ss;
    memcpy(out, &a->sin_addr, 4);  // network order == parse_ip bytes
    *iplen = 4;
    *port = ntohs(a->sin_port);
    return true;
  }
  if (ss->ss_family == AF_INET6) {
    auto* a = (const sockaddr_in6*)ss;
    memcpy(out, &a->sin6_addr, 16);
    *iplen = 16;
    *port = ntohs(a->sin6_port);
    return true;
  }
  return false;
}

// -------------------------------------- flow-cache maglev table attach
//
// The switch flow cache's consistent-rehash primitive: a flow compiler
// can install the current generation's slot table and pick egress
// candidates through it, so conntrack-free flows that re-miss after a
// mutation (generation bump) rehash to the SAME destination unless the
// destination set itself changed. Today's switch flow entries carry a
// single resolved destination (no pick to make), so the live compiler
// does not attach a table yet — this is the ABI the conntrack/NAT/DSR
// roadmap item picks through (parity-tested in tests/test_maglev.py).
// Install is generation-gated exactly like vtl_flow_install (a raced
// bump skips the install wholesale); both calls run on the owning loop
// thread per the flow cache's threading contract.

int vtl_flow_maglev_install(void* p, const int32_t* table, int m,
                            uint64_t gen) {
  FlowCache* fc = (FlowCache*)p;
  if (m < 0) return -EINVAL;
  if (gen != fc->gen.load(std::memory_order_relaxed)) return 0;
  fc->maglev.assign(table, table + m);
  return m;
}

int vtl_flow_maglev_pick(void* p, const void* ip, int iplen, int port,
                         int hash_port) {
  FlowCache* fc = (FlowCache*)p;
  if (fc->maglev.empty()) return -1;
  return maglev_lookup(fc->maglev.data(), (int)fc->maglev.size(),
                       (const uint8_t*)ip, iplen, port, hash_port);
}

// ------------------------------------------------------- io_uring engine
//
// The accept lanes' batched-completion engine. The ABI structs and
// syscall numbers are defined HERE (not via <linux/io_uring.h>): this
// container's 4.4-era kernel headers predate io_uring entirely, and the
// build must produce BOTH engine paths everywhere — the runtime probe
// (vtl_uring_probe) decides which one actually runs. Compiling with
// -DVTL_NO_URING compiles the engine out (probe reports 0, lanes run
// epoll) — the build guard compiles both configurations.
//
// Engine shape: one ring per lane. Readiness is delivered as oneshot
// IORING_OP_POLL_ADD completions (re-armed per interest change), new
// connections via multishot IORING_OP_ACCEPT (EINVAL falls back to
// poll+accept4), and each lane_poll round is ONE io_uring_enter that
// both submits every queued SQE (poll re-arms, cancels, the accept
// re-arm) and reaps the whole completion batch — replacing
// epoll_wait + one epoll_ctl syscall per interest flip. Splice/send-zc
// opcodes are probed and reported (BENCH honesty) but the data path
// keeps the shared ring-buffer pump; offloading it onto SPLICE/SEND_ZC
// SQEs is future work, documented in docs/perf.md.

#pragma pack(push, 1)
struct vtl_uring_sqe {
  uint8_t opcode, flags;
  uint16_t ioprio;       // IORING_ACCEPT_MULTISHOT rides here
  int32_t fd;
  uint64_t off;          // TIMEOUT: completion count
  uint64_t addr;         // POLL_REMOVE/ASYNC_CANCEL: target user_data
  uint32_t len;
  uint32_t op_flags;     // poll_events / accept_flags / timeout_flags
  uint64_t user_data;
  uint16_t buf_index, personality;
  int32_t splice_fd_in;
  uint64_t pad2[2];
};
struct vtl_uring_cqe { uint64_t user_data; int32_t res; uint32_t flags; };
struct vtl_io_sqring_offsets {
  uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array,
      resv1;
  uint64_t resv2;
};
struct vtl_io_cqring_offsets {
  uint32_t head, tail, ring_mask, ring_entries, overflow, cqes, flags,
      resv1;
  uint64_t resv2;
};
struct vtl_io_uring_params {
  uint32_t sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle,
      features, wq_fd, resv[3];
  vtl_io_sqring_offsets sq_off;
  vtl_io_cqring_offsets cq_off;
};
struct vtl_uring_probe_op { uint8_t op, resv; uint16_t flags; uint32_t resv2; };
struct vtl_uring_probe_s {
  uint8_t last_op, ops_len;
  uint16_t resv;
  uint32_t resv2[3];
  vtl_uring_probe_op ops[64];
};
#pragma pack(pop)
static_assert(sizeof(vtl_uring_sqe) == 64, "io_uring sqe ABI drifted");
static_assert(sizeof(vtl_uring_cqe) == 16, "io_uring cqe ABI drifted");
static_assert(sizeof(vtl_io_uring_params) == 120,
              "io_uring params ABI drifted");

#define VTL_IORING_OFF_SQ_RING 0ULL
#define VTL_IORING_OFF_CQ_RING 0x8000000ULL
#define VTL_IORING_OFF_SQES 0x10000000ULL
#define VTL_IORING_ENTER_GETEVENTS 1u
#define VTL_IORING_FEAT_SINGLE_MMAP 1u
#define VTL_IORING_OP_POLL_ADD 6
#define VTL_IORING_OP_POLL_REMOVE 7
#define VTL_IORING_OP_TIMEOUT 11
#define VTL_IORING_OP_ACCEPT 13
#define VTL_IORING_OP_ASYNC_CANCEL 14
#define VTL_IORING_OP_CONNECT 16
#define VTL_IORING_OP_SPLICE 30
#define VTL_IORING_OP_SEND_ZC 47
#define VTL_IORING_ACCEPT_MULTISHOT 1u
#define VTL_IORING_CQE_F_MORE 2u
#define VTL_IORING_REGISTER_PROBE 8
#define VTL_IO_URING_OP_SUPPORTED 1u
#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
#ifndef __NR_io_uring_register
#define __NR_io_uring_register 427
#endif

// user_data low bits tag the op; handlers come from new (>=8-aligned)
#define VTL_UTAG_POLL 0ull
#define VTL_UTAG_ACCEPT 1ull
#define VTL_UTAG_CANCEL 2ull
#define VTL_UTAG_TIMEOUT 3ull

#ifdef VTL_NO_URING

// probe bitmask: bit0 io_uring_setup works, bit1 ACCEPT, bit2 CONNECT,
// bit3 POLL_ADD, bit4 SPLICE, bit5 SEND_ZC
int vtl_uring_probe(void) { return 0; }
static Uring* uring_new(unsigned) { return nullptr; }
static void uring_free(Uring*) {}
static int uring_set_interest(Loop*, Handler* h, uint32_t interest) {
  h->interest = interest;
  return -ENOSYS;
}
static void uring_detach(Loop*, Handler*) {}

#else  // !VTL_NO_URING

struct Uring {
  int fd = -1;
  unsigned sq_entries = 0;
  unsigned *sq_head = nullptr, *sq_tail = nullptr, *sq_mask = nullptr,
           *sq_array = nullptr;
  unsigned *cq_head = nullptr, *cq_tail = nullptr, *cq_mask = nullptr;
  vtl_uring_sqe* sqes = nullptr;
  vtl_uring_cqe* cqes = nullptr;
  void *sq_ring = nullptr, *cq_ring = nullptr;
  size_t sq_ring_sz = 0, cq_ring_sz = 0, sqes_sz = 0;
  unsigned to_submit = 0;
  bool single_mmap = false;
};

static int sys_uring_setup(unsigned entries, vtl_io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
static int sys_uring_enter(int fd, unsigned to_submit,
                           unsigned min_complete, unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                      flags, nullptr, (size_t)0);
}
static int sys_uring_register(int fd, unsigned op, void* arg, unsigned n) {
  return (int)syscall(__NR_io_uring_register, fd, op, arg, n);
}

int vtl_uring_probe(void) {
  static std::atomic<int> cached(-1);
  int c = cached.load(std::memory_order_relaxed);
  if (c >= 0) return c;
  int mask = 0;
  vtl_io_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = sys_uring_setup(4, &p);
  if (fd >= 0) {
    mask |= 1;
    vtl_uring_probe_s pr;
    memset(&pr, 0, sizeof(pr));
    if (sys_uring_register(fd, VTL_IORING_REGISTER_PROBE, &pr, 64) == 0) {
      auto sup = [&](unsigned op) {
        return op <= pr.last_op &&
               (pr.ops[op].flags & VTL_IO_URING_OP_SUPPORTED);
      };
      if (sup(VTL_IORING_OP_ACCEPT)) mask |= 2;
      if (sup(VTL_IORING_OP_CONNECT)) mask |= 4;
      if (sup(VTL_IORING_OP_POLL_ADD)) mask |= 8;
      if (sup(VTL_IORING_OP_SPLICE)) mask |= 16;
      if (sup(VTL_IORING_OP_SEND_ZC)) mask |= 32;
    }
    close(fd);
  }
  cached.store(mask, std::memory_order_relaxed);
  return mask;
}

static void uring_free(Uring* u) {
  if (!u) return;
  if (u->sqes && u->sqes != MAP_FAILED) munmap(u->sqes, u->sqes_sz);
  if (u->cq_ring && u->cq_ring != u->sq_ring) munmap(u->cq_ring, u->cq_ring_sz);
  if (u->sq_ring && u->sq_ring != MAP_FAILED) munmap(u->sq_ring, u->sq_ring_sz);
  if (u->fd >= 0) close(u->fd);
  delete u;
}

static Uring* uring_new(unsigned entries) {
  vtl_io_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = sys_uring_setup(entries, &p);
  if (fd < 0) return nullptr;
  Uring* u = new Uring();
  u->fd = fd;
  u->sq_entries = p.sq_entries;
  u->sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(uint32_t);
  u->cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(vtl_uring_cqe);
  u->single_mmap = (p.features & VTL_IORING_FEAT_SINGLE_MMAP) != 0;
  if (u->single_mmap)
    u->sq_ring_sz = u->cq_ring_sz = std::max(u->sq_ring_sz, u->cq_ring_sz);
  u->sq_ring = mmap(nullptr, u->sq_ring_sz, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, VTL_IORING_OFF_SQ_RING);
  if (u->sq_ring == MAP_FAILED) { uring_free(u); return nullptr; }
  u->cq_ring = u->single_mmap
                   ? u->sq_ring
                   : mmap(nullptr, u->cq_ring_sz, PROT_READ | PROT_WRITE,
                          MAP_SHARED | MAP_POPULATE, fd,
                          VTL_IORING_OFF_CQ_RING);
  if (u->cq_ring == MAP_FAILED) { uring_free(u); return nullptr; }
  u->sqes_sz = p.sq_entries * sizeof(vtl_uring_sqe);
  u->sqes = (vtl_uring_sqe*)mmap(nullptr, u->sqes_sz,
                                 PROT_READ | PROT_WRITE,
                                 MAP_SHARED | MAP_POPULATE, fd,
                                 VTL_IORING_OFF_SQES);
  if (u->sqes == MAP_FAILED) { uring_free(u); return nullptr; }
  char* s = (char*)u->sq_ring;
  u->sq_head = (unsigned*)(s + p.sq_off.head);
  u->sq_tail = (unsigned*)(s + p.sq_off.tail);
  u->sq_mask = (unsigned*)(s + p.sq_off.ring_mask);
  u->sq_array = (unsigned*)(s + p.sq_off.array);
  char* c = (char*)u->cq_ring;
  u->cq_head = (unsigned*)(c + p.cq_off.head);
  u->cq_tail = (unsigned*)(c + p.cq_off.tail);
  u->cq_mask = (unsigned*)(c + p.cq_off.ring_mask);
  u->cqes = (vtl_uring_cqe*)(c + p.cq_off.cqes);
  return u;
}

// next free SQE (flushing the queue if the SQ ring is full); nullptr
// only when the kernel refuses to drain — callers degrade gracefully
static vtl_uring_sqe* uring_sqe(Loop* l) {
  Uring* u = l->ur;
  unsigned head = __atomic_load_n(u->sq_head, __ATOMIC_ACQUIRE);
  unsigned tail = *u->sq_tail;  // single producer: the lane thread
  if (tail - head >= u->sq_entries) {
    if (sys_uring_enter(u->fd, u->to_submit, 0, 0) >= 0) u->to_submit = 0;
    head = __atomic_load_n(u->sq_head, __ATOMIC_ACQUIRE);
    if (tail - head >= u->sq_entries) return nullptr;
  }
  unsigned idx = tail & *u->sq_mask;
  vtl_uring_sqe* e = &u->sqes[idx];
  memset(e, 0, sizeof(*e));
  u->sq_array[idx] = idx;
  __atomic_store_n(u->sq_tail, tail + 1, __ATOMIC_RELEASE);
  u->to_submit++;
  return e;
}

static int uring_arm_poll(Loop* l, Handler* h, uint16_t ev) {
  vtl_uring_sqe* e = uring_sqe(l);
  if (!e) return -EBUSY;
  e->opcode = VTL_IORING_OP_POLL_ADD;
  e->fd = h->fd;
  e->op_flags = (uint32_t)(ev | POLLERR | POLLHUP);
  e->user_data = (uint64_t)(uintptr_t)h | VTL_UTAG_POLL;
  h->poll_pending = true;
  h->pending_ev = ev;
  h->inflight++;
  return 0;
}

static int uring_set_interest(Loop* l, Handler* h, uint32_t interest) {
  h->interest = interest;
  if (h->kind == Handler::LANE && h->ms_accept)
    return 0;  // multishot accept IS the readiness source
  uint16_t ev = 0;
  if (interest & VTL_EV_READ) ev |= POLLIN;
  if (interest & VTL_EV_WRITE) ev |= POLLOUT;
  if (h->poll_pending) {
    if (h->pending_ev != ev) {
      // armed for the wrong events: cancel; the -ECANCELED completion
      // re-arms from the then-current interest
      vtl_uring_sqe* e = uring_sqe(l);
      if (!e) return -EBUSY;
      e->opcode = VTL_IORING_OP_POLL_REMOVE;
      e->addr = (uint64_t)(uintptr_t)h | VTL_UTAG_POLL;
      e->user_data = (uint64_t)(uintptr_t)h | VTL_UTAG_CANCEL;
      h->inflight++;
      h->pending_ev = ev;  // dedupe further same-target removes
    }
    return 0;
  }
  if (!ev) return 0;
  return uring_arm_poll(l, h, ev);
}

// before an fd closes: cancel its outstanding ring ops so the kernel
// drops the file reference (a closed fd with a live uring poll leaks)
static void uring_detach(Loop* l, Handler* h) {
  if (h->poll_pending) {
    vtl_uring_sqe* e = uring_sqe(l);
    if (e) {
      e->opcode = VTL_IORING_OP_POLL_REMOVE;
      e->addr = (uint64_t)(uintptr_t)h | VTL_UTAG_POLL;
      e->user_data = (uint64_t)(uintptr_t)h | VTL_UTAG_CANCEL;
      h->inflight++;
    }
  }
  if (h->ms_accept) {
    vtl_uring_sqe* e = uring_sqe(l);
    if (e) {
      e->opcode = VTL_IORING_OP_ASYNC_CANCEL;
      e->addr = (uint64_t)(uintptr_t)h | VTL_UTAG_ACCEPT;
      e->user_data = (uint64_t)(uintptr_t)h | VTL_UTAG_CANCEL;
      h->inflight++;
    }
  }
}

#endif  // VTL_NO_URING

// Block until fd is readable or timeout_ms passes — the poller
// threads' park (they call vtl_switch_poll on wake). ctypes releases
// the GIL for the duration, so N pollers wait/forward in parallel.
// -> 1 readable, 0 timeout, -errno.
int vtl_wait_readable(int fd, int timeout_ms) {
  pollfd p;
  p.fd = fd;
  p.events = POLLIN;
  p.revents = 0;
  int r = poll(&p, 1, timeout_ms);
  if (r < 0) return errno == EINTR ? 0 : -errno;
  if (r == 0) return 0;
  if (p.revents & (POLLERR | POLLNVAL)) return -EBADF;
  return 1;
}

// --------------------------------------------------------- span tracing
//
// Per-request tracing for the C accept plane (utils/trace.py is the
// process-wide collector). When sampling is on (vtl_trace_set_sample,
// 1-in-N), each sampled lane connection gets an EVEN trace id from one
// global atomic (python allocates odd ids — disjoint namespaces, no
// coordination) and its lifetime stages are written as fixed binary
// TraceRec records into the owning lane's lock-free SPSC span ring
// (producer = the lane thread, consumer = the python drain through
// vtl_trace_drain — one consumer per ring by contract). Ring overflow
// bumps a counter and drops the record: counted, never silent, never
// blocking the accept path. Knob-off cost is one relaxed atomic load
// per accept.

#pragma pack(push, 1)
struct TraceRec {  // must match net/vtl.py TRACE_REC
  uint64_t trace_id;
  uint64_t t_start_ns;  // CLOCK_MONOTONIC
  uint64_t dur_ns;
  uint64_t aux;         // span-dependent: bytes (splice), punt kind
  uint32_t lane;
  uint8_t span;         // TR_* below; contract with vtl.py TRACE_SPANS
  uint8_t flags;        // bit0 = connect_failed teardown
  uint16_t err;
};
#pragma pack(pop)
static_assert(sizeof(TraceRec) == 40, "TraceRec ABI drifted");

// span-id contract with net/vtl.py TRACE_SPANS (index == id)
#define TR_ACCEPT 0
#define TR_PICK 1
#define TR_CONNECT 2
#define TR_SPLICE 3
#define TR_CLOSE 4
#define TR_PUNT 5
#define TR_POLICE 6  // a policed rejection: aux = action code

static std::atomic<uint64_t> g_trace_sample(0);   // 0 = off, N = 1-in-N
static std::atomic<uint64_t> g_trace_next(2);     // even ids (python: odd)
static std::atomic<uint64_t> g_trace_spans(0), g_trace_drops(0);
static std::atomic<int> g_trace_ring_cap(8192);   // pow2; read at lanes_new

struct TraceRing {
  std::vector<TraceRec> buf;
  std::atomic<uint64_t> head{0}, tail{0};  // head consumer, tail producer
  uint64_t mask;
  explicit TraceRing(int cap) : buf((size_t)cap), mask((uint64_t)cap - 1) {}
};

static void tr_push(TraceRing* r, const TraceRec& rec) {
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  uint64_t h = r->head.load(std::memory_order_acquire);
  if (t - h > r->mask) {  // full: count the drop, never block the lane
    g_trace_drops.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  r->buf[t & r->mask] = rec;
  r->tail.store(t + 1, std::memory_order_release);
  g_trace_spans.fetch_add(1, std::memory_order_relaxed);
}

int vtl_trace_rec_size(void) { return (int)sizeof(TraceRec); }

void vtl_trace_set_sample(unsigned long long n) {
  g_trace_sample.store(n, std::memory_order_relaxed);
}

// ring capacity for lanes created AFTER this call (tests shrink it to
// exercise overflow without thousands of connections); clamped pow2
void vtl_trace_set_ring_cap(int cap) {
  int c = 64;
  while (c < cap && c < (1 << 20)) c <<= 1;
  g_trace_ring_cap.store(c, std::memory_order_relaxed);
}

// out[0] = spans written (all rings), out[1] = ring-overflow drops
int vtl_trace_counters(uint64_t* out) {
  out[0] = g_trace_spans.load(std::memory_order_relaxed);
  out[1] = g_trace_drops.load(std::memory_order_relaxed);
  return 2;
}

// ----------------------------------------------------- traffic analytics
//
// Heavy-hitter shards for the C planes (utils/sketch.py is the
// process-wide sketch owner). Each accept lane owns one HHShard — a
// small open-addressed (hash, key, count) table the lane thread updates
// inline (client address + picked backend per accept, coalescing
// repeats between drains); the lane's OWN python thread drains it
// through vtl_hh_drain after each vtl_lane_poll return, so producer and
// consumer are the same OS thread — no locks, no atomics, no races by
// construction. The flow cache's per-entry hit tallies drain through
// vtl_hh_flow_drain the same HH_REC shape. A full probe window bumps
// the overflow counter and drops the update: counted, never silent,
// never blocking the accept path. ONE hash contract: maglev_fnv64
// (FNV-1a 64) over raw key bytes, exported as vtl_hh_hash so python
// parity is testable bit for bit.

// dim-id contract with net/vtl.py HH_DIMS (index == id)
#define HH_DIM_CLIENT 0
#define HH_DIM_BACKEND 1
#define HH_DIM_FLOW 2
#define HH_KEY_MAX 54
#define HH_SHARD_SLOTS 512  // pow2; per-lane, drained every poll tick
#define HH_PROBE 8

#pragma pack(push, 1)
struct HHRec {  // drain record; must match net/vtl.py HH_REC
  uint64_t count;
  uint32_t lane;
  uint8_t dim;   // HH_DIM_*; contract with vtl.py HH_DIMS
  uint8_t klen;
  char key[54];  // raw client addr bytes / "ip:port" / FlowKey bytes
};
#pragma pack(pop)
static_assert(sizeof(HHRec) == 68, "HHRec ABI drifted");

struct HHSlot {
  uint64_t hash = 0;
  uint64_t count = 0;
  uint8_t dim = 0, klen = 0;
  char key[HH_KEY_MAX];
};
struct HHShard {
  HHSlot slots[HH_SHARD_SLOTS];
};

int vtl_hh_rec_size(void) { return (int)sizeof(HHRec); }

void vtl_hh_set_enabled(int on) {
  g_hh_on.store(on ? 1 : 0, std::memory_order_relaxed);
}

void vtl_workload_set_enabled(int on) {
  g_wl_on.store(on ? 1 : 0, std::memory_order_relaxed);
}

// the parity surface: python's sketch.fnv64 must agree bit for bit
unsigned long long vtl_hh_hash(const void* p, int n) {
  return maglev_fnv64((const uint8_t*)p, (size_t)(n > 0 ? n : 0));
}

// out[0] = shard updates absorbed, out[1] = probe-window overflows
int vtl_hh_counters(uint64_t* out) {
  out[0] = g_hh_updates.load(std::memory_order_relaxed);
  out[1] = g_hh_overflow.load(std::memory_order_relaxed);
  return 2;
}

static void hh_shard_update(HHShard* sh, uint8_t dim, const void* key,
                            int klen, uint64_t w) {
  if (klen <= 0) return;
  if (klen > HH_KEY_MAX) klen = HH_KEY_MAX;  // truncate, both sides see
                                             // the same truncated key
  uint64_t h = maglev_fnv64((const uint8_t*)key, (size_t)klen) ^
               ((uint64_t)(dim + 1) << 56);
  if (!h) h = 1;
  for (int i = 0; i < HH_PROBE; ++i) {
    HHSlot& s = sh->slots[(h + (uint64_t)i) & (HH_SHARD_SLOTS - 1)];
    if (s.count == 0) {
      s.hash = h;
      s.dim = dim;
      s.klen = (uint8_t)klen;
      memcpy(s.key, key, (size_t)klen);
      s.count = w;
      g_hh_updates.fetch_add(w, std::memory_order_relaxed);
      return;
    }
    if (s.hash == h && s.dim == dim && s.klen == (uint8_t)klen &&
        !memcmp(s.key, key, (size_t)klen)) {
      s.count += w;
      g_hh_updates.fetch_add(w, std::memory_order_relaxed);
      return;
    }
  }
  // probe window full between two drains: drop THIS update, loudly
  g_hh_overflow.fetch_add(w, std::memory_order_relaxed);
}

// Drain one flow cache's pending per-entry hit tallies as HH_REC
// records keyed by the 26-byte FlowKey. Resumes its walk across calls
// (hh_cursor); one caller per cache by contract — the owning switch's
// analytics tick. Entry keys are read under the per-entry seqlock
// (fc_racy_copy) so a concurrent install never yields a torn key; a
// slot moving mid-read keeps its tally for the next tick.
int vtl_hh_flow_drain(void* fcp, void* out, int max) {
  FlowCache* fc = (FlowCache*)fcp;
  if (!fc || !out || max <= 0) return -EINVAL;
  HHRec* o = (HHRec*)out;
  int n = 0;
  uint32_t cap = fc->mask + 1;
  uint32_t step = 0;
  for (; step < cap && n < max; ++step) {
    FlowEntry& e = fc->slots[(fc->hh_cursor + step) & fc->mask];
    if (!__atomic_load_n(&e.hh_hits, __ATOMIC_RELAXED)) continue;
    uint32_t s1 = __atomic_load_n(&e.seq, __ATOMIC_ACQUIRE);
    if (s1 & 1) continue;  // mid-install: pick it up next tick
    FlowEntry copy;
    fc_racy_copy(&copy, e);
    __atomic_thread_fence(__ATOMIC_ACQUIRE);
    if (__atomic_load_n(&e.seq, __ATOMIC_RELAXED) != s1) continue;
    uint64_t pend = __atomic_exchange_n(&e.hh_hits, 0ull,
                                        __ATOMIC_RELAXED);
    if (!pend) continue;
    o[n].count = pend;
    o[n].lane = 0;
    o[n].dim = HH_DIM_FLOW;
    o[n].klen = (uint8_t)sizeof(FlowKey);
    memset(o[n].key, 0, HH_KEY_MAX);
    memcpy(o[n].key, &copy.key, sizeof(FlowKey));
    ++n;
  }
  fc->hh_cursor += step;
  return n;
}

// ---------------------------------------------------------- accept lanes
//
// The PR-5 switch-poller idiom applied to TCP: N lane threads (plain
// Python threads parked inside vtl_lane_poll — ctypes releases the GIL)
// each own a SO_REUSEPORT listener and run the WHOLE short-connection
// lifetime in C: accept4 batch -> route lookup against the C-resident
// lane entry (the compiled backend set + WRR sequence Python installs)
// -> pump_connect_impl -> splice -> close. Python is the lane-entry
// COMPILER: only punts cross ctypes — connections the lane must not
// decide (no entry, stale generation, armed failpoints, overload) and
// backend-connect failures (fd_a intact, feeding the retry/ejection
// machinery exactly like vtl_pump_connect's connect_failed DONE).
//
// Correctness is generation-gated like the switch flow cache: every
// upstream/ACL/backend-health mutation bumps ONE atomic
// (vtl_lane_gen_bump, any thread); the installed entry is stamped with
// the generation read before compilation began, and a mismatched stamp
// is a forced punt — zero stale routing by construction.

#pragma pack(push, 1)
struct LaneRec {  // install record; must match net/vtl.py LANE_REC
  char ip[46];
  uint16_t port;
  uint8_t v6;
  uint8_t weight;  // informational (Python pre-expands the WRR seq)
};
struct LanePunt {  // punt record; must match net/vtl.py LANE_PUNT
  int32_t fd;
  int32_t kind;  // 0 classic (serve via Python), 1 connect_failed
  int32_t err;
  uint16_t cport, bport;
  char cip[46];
  char bip[46];
  uint64_t trace_id;  // 0 = unsampled; else python CONTINUES the trace
};
struct MaglevRec {  // maglev install record; must match net/vtl.py MAGLEV_REC
  char ip[46];
  uint16_t port;
  uint8_t v6;
  uint8_t weight;  // informational (the table already encodes weight)
};
struct PoliceRec {  // policing install record; must match net/vtl.py POLICE_REC
  uint64_t key_hash;    // fnv64 over the raw client addr bytes; 0 = unused
  uint32_t rate_mtok;   // refill rate, milli-tokens / second
  uint32_t burst_mtok;  // bucket capacity, milli-tokens
  uint8_t action;       // POLICE_ACT_*
  uint8_t dim;          // 0 = clients (the only lane-enforced dimension)
  uint8_t pad[2];
};
#pragma pack(pop)
static_assert(sizeof(LaneRec) == 50, "LaneRec ABI drifted");
static_assert(sizeof(LanePunt) == 116, "LanePunt ABI drifted");
static_assert(sizeof(MaglevRec) == 50, "MaglevRec ABI drifted");
static_assert(sizeof(PoliceRec) == 20, "PoliceRec ABI drifted");

// action-code contract with policing/engine.ACTIONS (index == id)
#define POLICE_ACT_MONITOR 0
#define POLICE_ACT_THROTTLE 1
#define POLICE_ACT_SHED 2

// One policed key's live bucket state. The spinlock serializes the
// debit read-modify-write across lane threads (the same client can
// land on every SO_REUSEPORT listener at once); contention is
// per-HOT-KEY, not per-accept, and the critical section is a handful
// of integer ops — a std::mutex per slot would dominate the table.
struct PoliceSlot {
  uint64_t key_hash = 0;  // 0 = empty (open addressing sentinel)
  uint32_t rate_mtok = 0, burst_mtok = 0;
  uint8_t action = POLICE_ACT_MONITOR;
  std::atomic<int> lk{0};
  int64_t level_mtok = 0;
  uint64_t t_ns = 0;
};

struct PoliceTable {  // immutable layout after install; slots mutate
  uint64_t gen = 0;   // generation stamp: mismatch = forced consult-miss
  std::vector<PoliceSlot> slots;  // power-of-two, <= 50% loaded
};

static PoliceSlot* police_find(PoliceTable* pt, uint64_t h) {
  if (!pt || pt->slots.empty() || !h) return nullptr;
  uint32_t cap = (uint32_t)pt->slots.size();
  uint32_t idx = (uint32_t)h & (cap - 1);
  for (uint32_t p = 0; p < cap; ++p, idx = (idx + 1) & (cap - 1)) {
    PoliceSlot& s = pt->slots[idx];
    if (!s.key_hash) return nullptr;  // empty slot ends the probe chain
    if (s.key_hash == h) return &s;
  }
  return nullptr;
}

// THE bucket law — integer milli-tokens against explicit monotonic ns,
// arithmetic mirrored statement-for-statement by python
// policing/engine.TokenBucket.debit (the C==python parity test drives
// both with the same timestamp sequence and asserts bit-equality).
// -> 1 in quota (token taken), 0 over quota.
static inline int police_debit(PoliceSlot& s, uint64_t now_ns) {
  while (s.lk.exchange(1, std::memory_order_acquire)) {}
  if (now_ns > s.t_ns) {
    // 128-bit product: rate * a minutes-long gap overflows u64 and the
    // python side (arbitrary precision) would not — parity demands care
    unsigned __int128 add =
        (unsigned __int128)s.rate_mtok * (now_ns - s.t_ns) /
        1000000000ull;
    uint64_t a = add > (unsigned __int128)s.burst_mtok
                     ? s.burst_mtok
                     : (uint64_t)add;
    int64_t lvl = s.level_mtok + (int64_t)a;
    s.level_mtok = lvl > (int64_t)s.burst_mtok ? (int64_t)s.burst_mtok
                                               : lvl;
    s.t_ns = now_ns;
  }
  int ok = 0;
  if (s.level_mtok >= 1000) {
    s.level_mtok -= 1000;
    ok = 1;
  }
  s.lk.store(0, std::memory_order_release);
  return ok;
}

#define LANE_PUNT_CLASSIC 0
#define LANE_PUNT_CONNECT_FAIL 1

struct LaneRoute {
  uint64_t gen = 0;
  std::vector<LaneRec> backends;
  std::vector<sockaddr_storage> addrs;  // pre-resolved: no per-accept
  std::vector<socklen_t> lens;          // string parsing on the hot path
  std::vector<int32_t> seq;             // WRR pick sequence
  // maglev slot->backend table (vtl_lane_maglev_install); when present
  // it IS the pick path (seq stays empty) — one hash + one load per
  // accept, hash_port=0 for source-affinity groups
  std::vector<int32_t> maglev;
  int maglev_hash_port = 1;
  // "ip:port" analytics keys, index-aligned with backends: precomputed
  // at install so the accept path's HH update is a hash + memcpy, not
  // a snprintf
  std::vector<std::string> bkeys;
};

struct ConnMeta {  // per live lane pump (owning lane thread only)
  std::shared_ptr<LaneRoute> route;
  int bidx;
  uint64_t last_total, last_ts_us;
  uint64_t trace_id = 0;   // 0 = unsampled
  uint64_t t_acc_ns = 0;   // accept stamp (stage totals + spans)
  uint64_t t_conn_ns = 0;  // connect-resolved stamp (splice span start)
};

struct Lanes;

struct Lane {
  Lanes* owner = nullptr;
  Loop* loop = nullptr;
  int idx = 0;
  int lfd = -1;
  Handler* lh = nullptr;
  bool listener_closed = false;
  std::deque<LanePunt> punt_q;
  std::unordered_map<uint64_t, ConnMeta> meta;
  uint64_t next_sweep_us = 0;
  TraceRing* tring = nullptr;  // SPSC span ring (this thread produces)
  HHShard* hh = nullptr;       // analytics shard (this thread's alone:
                               // produced in-poll, drained post-poll
                               // by the SAME python thread)
#ifndef VTL_NO_URING
  bool to_pending = false;  // outstanding IORING_OP_TIMEOUT
  struct { int64_t sec, nsec; } to_ts {0, 0};  // __kernel_timespec
#endif
};

struct Lanes {
  std::atomic<uint64_t> gen{1};
  std::atomic<int> punt_all{0};         // armed failpoints force classic
  std::atomic<int> close_listeners{0};  // drain: stop accepting
  std::atomic<int> shutting{0};
  std::atomic<uint64_t> abort_at_us{0};
  std::atomic<int64_t> max_active{1ll << 30};
  std::atomic<uint64_t> wrr{0};  // shared cursor: even spread across lanes
  std::mutex mu;                 // guards the route + police swaps
  std::shared_ptr<LaneRoute> route;
  std::shared_ptr<PoliceTable> police;  // r19 admission table (may be null)
  int engine = 0;  // 0 epoll, 1 uring
  int port = 0, bufsize = 65536;
  std::atomic<int> timeout_ms{900000};  // hot-settable (update timeout)
  int connect_timeout_ms = 3000;
  std::vector<Lane*> lanes;
  // adaptive overload (components/overload.py): when shed_rst is set,
  // over-limit accepts are RST-closed (SO_LINGER{1,0}) right here in C
  // instead of punting — a flash crowd must not buy a GIL crossing per
  // shed connection, and FIN closes would stack one TIME_WAIT each.
  std::atomic<int> shed_rst{0};
  std::atomic<uint64_t> accepted{0}, served{0}, active{0},
      punt_classic{0}, punt_stale{0}, punt_fail{0}, bytes{0},
      killed{0},  // idle-expired + shutdown-aborted (NOT served)
      shed{0};    // over-limit accepts RST-closed in C (shed_rst mode)
  // accept-latency EWMA (us): the accept->backend-connected span of
  // lane-owned sessions, alpha 1/8 — the C-plane analog of the python
  // accept EWMA the adaptive overload controller steers on (which was
  // blind to lane-served traffic before r11). Relaxed read-modify-write
  // races between lanes lose one sample, never corrupt the value.
  std::atomic<uint64_t> lat_ewma_us{0};
  // per-stage latency accounting for EVERY lane connection (sampled or
  // not): the vproxy_accept_stage_us ABI widening — log2 buckets with
  // the SAME rule as utils/metrics.Histogram._bucket_of, drained by
  // Python as deltas and merged into the stage histograms so lane
  // connections stop being invisible to them. Stage index contract
  // with vtl.py LANE_STAGES: 0 backend_pick, 1 handover, 2 total.
  unsigned long long stage_count[3] = {};
  unsigned long long stage_sum_us[3] = {};
  unsigned long long stage_bkt[3][28] = {};
  // workload capture (r16): lane-plane arrival process + per-connection
  // size/duration, same log2 bucket rule and the same delta-fold drain
  // as the stage histograms (lane 0's tick merges into the python
  // histograms). Index contract with vtl.py LANE_CAPTURES:
  // 0 interarrival_us, 1 conn_bytes, 2 conn_duration_ms. Gated on
  // g_wl_on so the capture-off A/B gate has a real knob to toggle.
  unsigned long long cap_count[3] = {};
  unsigned long long cap_sum[3] = {};
  unsigned long long cap_bkt[3][28] = {};
  std::atomic<uint64_t> cap_last_accept_us{0};
  // trace sampling cursor (1-in-N across this Lanes object's threads)
  std::atomic<uint64_t> trace_seq{0};
  // policing probe tallies (r19), drained as deltas by lane 0's python
  // thread (the _fold_lane_sheds contract): checked counts entries
  // FOUND in the table; shed = RST-closed here; throttled = over-quota
  // punts (python's mirror re-decides against the overload ceiling, so
  // the fold deliberately skips this one — python counts it once);
  // monitored = over-quota admits; stale = consult-misses forced by a
  // generation mismatch (the fail-open gate).
  std::atomic<uint64_t> pol_checked{0}, pol_shed{0}, pol_throttled{0},
      pol_monitored{0}, pol_stale{0};
};

#define LANE_STAGE_PICK 0
#define LANE_STAGE_HANDOVER 1
#define LANE_STAGE_TOTAL 2

static inline int lanes_bucket(unsigned long long us) {
  // utils/metrics.Histogram._bucket_of, integer-us form: v<=1 -> 0,
  // else min(bit_length(v-1), 27) — 28 buckets incl. the +Inf tail
  if (us <= 1) return 0;
  int b = 64 - __builtin_clzll(us - 1);
  return b > 27 ? 27 : b;
}

static inline void lanes_stage_obs(Lanes* ow, int st,
                                   unsigned long long us) {
  __atomic_fetch_add(&ow->stage_count[st], 1ull, __ATOMIC_RELAXED);
  __atomic_fetch_add(&ow->stage_sum_us[st], us, __ATOMIC_RELAXED);
  __atomic_fetch_add(&ow->stage_bkt[st][lanes_bucket(us)], 1ull,
                     __ATOMIC_RELAXED);
}

// out = [count, sum_us, bucket0..bucket27] for one stage -> 30
int vtl_lanes_stage_stat(void* lp, int stage, uint64_t* out) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || stage < 0 || stage > 2) return -EINVAL;
  out[0] = __atomic_load_n(&ow->stage_count[stage], __ATOMIC_RELAXED);
  out[1] = __atomic_load_n(&ow->stage_sum_us[stage], __ATOMIC_RELAXED);
  for (int i = 0; i < 28; ++i)
    out[2 + i] = __atomic_load_n(&ow->stage_bkt[stage][i],
                                 __ATOMIC_RELAXED);
  return 30;
}

#define LANE_CAP_INTERARRIVAL 0
#define LANE_CAP_CONN_BYTES 1
#define LANE_CAP_CONN_MS 2

static inline void lanes_cap_obs(Lanes* ow, int w, unsigned long long v) {
  __atomic_fetch_add(&ow->cap_count[w], 1ull, __ATOMIC_RELAXED);
  __atomic_fetch_add(&ow->cap_sum[w], v, __ATOMIC_RELAXED);
  __atomic_fetch_add(&ow->cap_bkt[w][lanes_bucket(v)], 1ull,
                     __ATOMIC_RELAXED);
}

// out = [count, sum, bucket0..bucket27] for one capture series -> 30
int vtl_lanes_capture_stat(void* lp, int which, uint64_t* out) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || which < 0 || which > 2) return -EINVAL;
  out[0] = __atomic_load_n(&ow->cap_count[which], __ATOMIC_RELAXED);
  out[1] = __atomic_load_n(&ow->cap_sum[which], __ATOMIC_RELAXED);
  for (int i = 0; i < 28; ++i)
    out[2 + i] = __atomic_load_n(&ow->cap_bkt[which][i],
                                 __ATOMIC_RELAXED);
  return 30;
}

static inline void lane_trace(Lane* ln, uint64_t tid, uint8_t span,
                              uint64_t t0, uint64_t dur, uint64_t aux,
                              uint16_t err, uint8_t flags = 0) {
  if (!tid || !ln->tring) return;
  TraceRec r;
  r.trace_id = tid;
  r.t_start_ns = t0;
  r.dur_ns = dur;
  r.aux = aux;
  r.lane = (uint32_t)ln->idx;
  r.span = span;
  r.flags = flags;
  r.err = err;
  tr_push(ln->tring, r);
}

// drain one lane's span ring into `out` (TraceRec array, max slots);
// SPSC: at most one concurrent caller per (lanes, idx) by contract —
// components/lanes.py drains from that lane's own python thread
int vtl_trace_drain(void* lp, int idx, void* out, int max) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || idx < 0 || idx >= (int)ow->lanes.size() || max <= 0)
    return -EINVAL;
  TraceRing* r = ow->lanes[idx]->tring;
  if (!r) return 0;
  uint64_t h = r->head.load(std::memory_order_relaxed);
  uint64_t t = r->tail.load(std::memory_order_acquire);
  TraceRec* o = (TraceRec*)out;
  int n = 0;
  while (h != t && n < max) {
    o[n++] = r->buf[h & r->mask];
    ++h;
  }
  r->head.store(h, std::memory_order_release);
  return n;
}

// per-accept analytics: client address + picked backend into this
// lane's shard. Knob-off cost is the one relaxed load in the caller.
static void lane_hh_note(Lane* ln, const sockaddr_storage* ss, int cfd,
                         const LaneRoute* rt, int bidx) {
  if (!ln->hh) return;
  sockaddr_storage local;
  if (!ss) {  // uring multishot accept reports no peer address
    socklen_t sl = sizeof(local);
    if (getpeername(cfd, (sockaddr*)&local, &sl) == 0) ss = &local;
  }
  uint8_t ipb[16];
  int iplen = 0, cport = 0;
  if (ss && maglev_addr_bytes(ss, ipb, &iplen, &cport))
    hh_shard_update(ln->hh, HH_DIM_CLIENT, ipb, iplen, 1);
  if (rt && bidx >= 0 && bidx < (int)rt->bkeys.size())
    hh_shard_update(ln->hh, HH_DIM_BACKEND, rt->bkeys[bidx].data(),
                    (int)rt->bkeys[bidx].size(), 1);
}

// Drain one lane's shard into `out` (HHRec array). Same-thread
// contract as the shard updates: the lane's own python thread, after
// its vtl_lane_poll returned — there is no concurrent producer.
int vtl_hh_drain(void* lp, int idx, void* out, int max) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || idx < 0 || idx >= (int)ow->lanes.size() || !out || max <= 0)
    return -EINVAL;
  HHShard* sh = ow->lanes[idx]->hh;
  if (!sh) return 0;
  HHRec* o = (HHRec*)out;
  int n = 0;
  for (int i = 0; i < HH_SHARD_SLOTS && n < max; ++i) {
    HHSlot& s = sh->slots[i];
    if (!s.count) continue;
    o[n].count = s.count;
    o[n].lane = (uint32_t)idx;
    o[n].dim = s.dim;
    o[n].klen = s.klen;
    memset(o[n].key, 0, HH_KEY_MAX);
    memcpy(o[n].key, s.key, s.klen);
    ++n;
    s.count = 0;  // slot reclaimed; undrained slots keep their tallies
  }
  return n;
}

static inline void lanes_lat_obs(Lanes* ow, uint64_t us) {
  uint64_t old = ow->lat_ewma_us.load(std::memory_order_relaxed);
  ow->lat_ewma_us.store(old - old / 8 + us / 8,
                        std::memory_order_relaxed);
}

// process-global tallies (every LB's lanes), pump_counters idiom —
// /metrics surfaces them as vproxy_lane_*_total
static std::atomic<uint64_t> g_lane_accepted(0), g_lane_served(0),
    g_lane_punt_classic(0), g_lane_punt_stale(0), g_lane_punt_fail(0);

int vtl_lane_rec_size(void) { return (int)sizeof(LaneRec); }
int vtl_lane_punt_size(void) { return (int)sizeof(LanePunt); }
int vtl_maglev_rec_size(void) { return (int)sizeof(MaglevRec); }

static void addr_str(const sockaddr_storage* ss, char* ip, int iplen,
                     uint16_t* port) {
  ip[0] = 0;
  *port = 0;
  if (ss->ss_family == AF_INET) {
    auto* a = (const sockaddr_in*)ss;
    inet_ntop(AF_INET, &a->sin_addr, ip, iplen);
    *port = ntohs(a->sin_port);
  } else if (ss->ss_family == AF_INET6) {
    auto* a = (const sockaddr_in6*)ss;
    inet_ntop(AF_INET6, &a->sin6_addr, ip, iplen);
    *port = ntohs(a->sin6_port);
  }
}

static void lane_emit_punt(Lane* ln, int cfd, int kind, int err,
                           const sockaddr_storage* ss, const LaneRec* b,
                           uint64_t tid = 0) {
  LanePunt p;
  memset(&p, 0, sizeof(p));
  p.fd = cfd;
  p.kind = kind;
  p.err = err;
  p.trace_id = tid;
  sockaddr_storage local;
  if (!ss) {  // uring multishot accept reports no peer address
    socklen_t sl = sizeof(local);
    if (getpeername(cfd, (sockaddr*)&local, &sl) == 0) ss = &local;
  }
  if (ss) addr_str(ss, p.cip, sizeof(p.cip), &p.cport);
  if (b) {
    memcpy(p.bip, b->ip, sizeof(p.bip));
    p.bport = b->port;
  }
  ln->punt_q.push_back(p);
}

// a sampled accept leaving through a punt: close out the C-side spans
// (accept + the punt marker); the trace id rides the punt record so
// the python path CONTINUES the same trace (the cross-plane stitch)
static inline void lane_trace_punt(Lane* ln, uint64_t tid,
                                   uint64_t t_acc, int kind) {
  if (!tid) return;
  uint64_t now = mono_ns();
  lane_trace(ln, tid, TR_ACCEPT, t_acc, now - t_acc, 0, 0);
  lane_trace(ln, tid, TR_PUNT, now, 0, (uint64_t)kind, 0);
}

static void lane_client(Lane* ln, int cfd, const sockaddr_storage* ss) {
  Lanes* ow = ln->owner;
  uint64_t t_acc = mono_ns();  // stage histograms need it on every path
  ow->accepted.fetch_add(1, std::memory_order_relaxed);
  g_lane_accepted.fetch_add(1, std::memory_order_relaxed);
  if (g_wl_on.load(std::memory_order_relaxed)) {
    // lane-plane arrival process: one exchange on a shared cursor, the
    // delta is the inter-arrival gap across ALL lanes of this Lanes
    // object (the workload model wants the plane's merged process, not
    // per-thread ones). A relaxed-exchange race reorders two nearby
    // accepts — it perturbs one sample, never corrupts the histogram.
    uint64_t now_us = t_acc / 1000;
    uint64_t prev = ow->cap_last_accept_us.exchange(
        now_us, std::memory_order_relaxed);
    if (prev)
      lanes_cap_obs(ow, LANE_CAP_INTERARRIVAL,
                    now_us > prev ? now_us - prev : 0);
  }
  // deterministic 1-in-N sampling: one relaxed load when the knob is
  // off; a sampled accept allocates an even trace id (python: odd)
  uint64_t samp = g_trace_sample.load(std::memory_order_relaxed);
  uint64_t tid = 0;
  if (samp && ln->tring &&
      ow->trace_seq.fetch_add(1, std::memory_order_relaxed) % samp == 0)
    tid = g_trace_next.fetch_add(2, std::memory_order_relaxed);
  std::shared_ptr<LaneRoute> rt;
  std::shared_ptr<PoliceTable> pt;
  // the policing knob-off cost on this path is exactly this one
  // relaxed load (the g_hh_on contract)
  bool police = g_police_on.load(std::memory_order_relaxed) != 0;
  {
    std::lock_guard<std::mutex> g(ow->mu);
    rt = ow->route;
    if (police) pt = ow->police;
  }
  uint64_t cur = ow->gen.load(std::memory_order_relaxed);
  if ((int64_t)ow->active.load(std::memory_order_relaxed) >=
          ow->max_active.load(std::memory_order_relaxed) &&
      ow->shed_rst.load(std::memory_order_relaxed) &&
      !ow->close_listeners.load(std::memory_order_relaxed)) {
    // over the (adaptive) ceiling with RST-shed on: refuse HERE — no
    // punt, no Python, no TIME_WAIT. Python folds the counter into
    // vproxy_lb_shed_total{reason=adaptive} on the guard tick.
    struct linger lg = {1, 0};
    setsockopt(cfd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
    close(cfd);
    ow->shed.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  if (ow->punt_all.load(std::memory_order_relaxed) ||
      ow->close_listeners.load(std::memory_order_relaxed) || !rt ||
      (rt->seq.empty() && rt->maglev.empty()) ||
      (int64_t)ow->active.load(std::memory_order_relaxed) >=
          ow->max_active.load(std::memory_order_relaxed)) {
    ow->punt_classic.fetch_add(1, std::memory_order_relaxed);
    g_lane_punt_classic.fetch_add(1, std::memory_order_relaxed);
    lane_trace_punt(ln, tid, t_acc, 0);
    lane_emit_punt(ln, cfd, LANE_PUNT_CLASSIC, 0, ss, nullptr, tid);
    return;
  }
  if (rt->gen != cur) {
    // the generation gate: a mutation since compile forces the classic
    // path; Python re-decides against current tables and re-installs
    ow->punt_stale.fetch_add(1, std::memory_order_relaxed);
    g_lane_punt_stale.fetch_add(1, std::memory_order_relaxed);
    lane_trace_punt(ln, tid, t_acc, 0);
    lane_emit_punt(ln, cfd, LANE_PUNT_CLASSIC, 0, ss, nullptr, tid);
    return;
  }
  // function-scope storage for a late-resolved peer address: `ss` may
  // be re-pointed at it inside the police/maglev branches and is read
  // after the branch ends (lane_hh_note, the connect-fail punt) — a
  // block-local would leave those reads dangling
  sockaddr_storage peer;
  if (police && pt) {
    // the POLICE_REC probe: ONE open-addressed lookup + bucket debit.
    // A generation mismatch is a forced consult-miss -> ADMIT: a stale
    // verdict must fail open (the opposite polarity of the route gate,
    // which fails closed to python) — refusing paying traffic on stale
    // evidence is the one thing a policer must never do.
    if (pt->gen != cur) {
      ow->pol_stale.fetch_add(1, std::memory_order_relaxed);
    } else {
      if (!ss) {  // uring multishot accept reports no peer address
        socklen_t sl = sizeof(peer);
        if (getpeername(cfd, (sockaddr*)&peer, &sl) == 0) ss = &peer;
      }
      uint8_t ipb[16];
      int iplen = 0, cport = 0;
      if (ss && maglev_addr_bytes(ss, ipb, &iplen, &cport)) {
        PoliceSlot* s = police_find(pt.get(), maglev_fnv64(ipb, iplen));
        if (s) {
          ow->pol_checked.fetch_add(1, std::memory_order_relaxed);
          if (!police_debit(*s, t_acc)) {  // over quota
            if (s->action == POLICE_ACT_SHED) {
              // refuse HERE: RST (no TIME_WAIT), no punt, no python —
              // an attacking herd must not buy a GIL crossing each
              if (tid)
                lane_trace(ln, tid, TR_POLICE, t_acc,
                           mono_ns() - t_acc, POLICE_ACT_SHED, 0);
              struct linger lg = {1, 0};
              setsockopt(cfd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
              close(cfd);
              ow->pol_shed.fetch_add(1, std::memory_order_relaxed);
              return;
            }
            if (s->action == POLICE_ACT_THROTTLE) {
              // throttle defers to the overload ceiling: punt so the
              // python mirror decides (shed iff at/over the ceiling)
              ow->pol_throttled.fetch_add(1, std::memory_order_relaxed);
              ow->punt_classic.fetch_add(1, std::memory_order_relaxed);
              g_lane_punt_classic.fetch_add(1, std::memory_order_relaxed);
              lane_trace_punt(ln, tid, t_acc, 0);
              lane_emit_punt(ln, cfd, LANE_PUNT_CLASSIC, 0, ss, nullptr,
                             tid);
              return;
            }
            // monitor: count the over-quota arrival, admit it
            ow->pol_monitored.fetch_add(1, std::memory_order_relaxed);
          }
        }
      }
    }
  }
  uint64_t t_pick0 = mono_ns();
  int bidx;
  if (!rt->maglev.empty()) {
    // consistent-hash pick: one FNV over the client addr (+port when
    // per-connection spread is configured) + one table load. The uring
    // multishot accept reports no peer address — resolve it here.
    if (!ss) {
      socklen_t sl = sizeof(peer);
      if (getpeername(cfd, (sockaddr*)&peer, &sl) == 0) ss = &peer;
    }
    uint8_t ipb[16];
    int iplen = 0, cport = 0;
    if (!ss || !maglev_addr_bytes(ss, ipb, &iplen, &cport)) {
      // no hashable address (AF_UNIX peer): the python path decides
      ow->punt_classic.fetch_add(1, std::memory_order_relaxed);
      g_lane_punt_classic.fetch_add(1, std::memory_order_relaxed);
      lane_trace_punt(ln, tid, t_acc, 0);
      lane_emit_punt(ln, cfd, LANE_PUNT_CLASSIC, 0, ss, nullptr, tid);
      return;
    }
    bidx = maglev_lookup(rt->maglev.data(), (int)rt->maglev.size(), ipb,
                         iplen, cport, rt->maglev_hash_port);
    if (bidx < 0 || bidx >= (int)rt->backends.size()) {
      // slot owned by a backend whose address failed to resolve at
      // install time: punt, never guess
      ow->punt_classic.fetch_add(1, std::memory_order_relaxed);
      g_lane_punt_classic.fetch_add(1, std::memory_order_relaxed);
      lane_trace_punt(ln, tid, t_acc, 0);
      lane_emit_punt(ln, cfd, LANE_PUNT_CLASSIC, 0, ss, nullptr, tid);
      return;
    }
  } else {
    bidx = rt->seq[ow->wrr.fetch_add(1, std::memory_order_relaxed) %
                   rt->seq.size()];
  }
  uint64_t t_pick1 = mono_ns();
  lanes_stage_obs(ow, LANE_STAGE_PICK, (t_pick1 - t_pick0) / 1000);
  if (g_hh_on.load(std::memory_order_relaxed))
    lane_hh_note(ln, ss, cfd, rt.get(), bidx);
  if (tid) {
    lane_trace(ln, tid, TR_ACCEPT, t_acc, t_pick0 - t_acc, 0, 0);
    lane_trace(ln, tid, TR_PICK, t_pick0, t_pick1 - t_pick0,
               (uint64_t)bidx, 0);
  }
  errno = 0;
  uint64_t pid = pump_connect_impl(ln->loop, cfd,
                                   (sockaddr*)&rt->addrs[bidx],
                                   rt->lens[bidx], ow->bufsize);
  if (!pid) {  // sync refusal: punt as connect failure (retry machinery)
    ow->punt_fail.fetch_add(1, std::memory_order_relaxed);
    g_lane_punt_fail.fetch_add(1, std::memory_order_relaxed);
    if (tid)
      lane_trace(ln, tid, TR_PUNT, mono_ns(), 0, 1,
                 (uint16_t)(errno ? errno : ECONNREFUSED));
    lane_emit_punt(ln, cfd, LANE_PUNT_CONNECT_FAIL,
                   errno ? errno : ECONNREFUSED, ss, &rt->backends[bidx],
                   tid);
    return;
  }
  ConnMeta& m = ln->meta[pid];
  m = ConnMeta{rt, bidx, 0, mono_us()};
  m.trace_id = tid;
  m.t_acc_ns = t_acc;
  {
    auto pit = ln->loop->pumps.find(pid);
    if (pit != ln->loop->pumps.end() && !pit->second->b_connecting) {
      // loopback connect resolved synchronously inside pump_connect
      Pump* p = pit->second;
      lanes_lat_obs(ow, p->connect_us);  // sync connect: ~0us
      uint64_t now = mono_ns();
      lanes_stage_obs(ow, LANE_STAGE_HANDOVER, p->connect_us);
      lanes_stage_obs(ow, LANE_STAGE_TOTAL, (now - t_acc) / 1000);
      if (tid) {
        lane_trace(ln, tid, TR_CONNECT, t_pick1, now - t_pick1, 0, 0);
        m.t_conn_ns = now;
      }
    }
  }
  ow->active.fetch_add(1, std::memory_order_relaxed);
}

static void lane_accept_batch(Lane* ln) {
  for (;;) {  // drain the backlog: one wake pays for the whole burst
    sockaddr_storage ss;
    socklen_t sl = sizeof(ss);
    int cfd = accept4(ln->lfd, (sockaddr*)&ss, &sl,
                      SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (cfd < 0) break;  // EAGAIN (or EMFILE — retried on the next wake)
    lane_client(ln, cfd, &ss);
  }
}

#ifndef VTL_NO_URING
static void lane_arm_accept(Lane* ln) {
  Handler* h = ln->lh;
  vtl_uring_sqe* e = uring_sqe(ln->loop);
  if (!e) return;
  e->opcode = VTL_IORING_OP_ACCEPT;
  e->fd = h->fd;
  e->ioprio = VTL_IORING_ACCEPT_MULTISHOT;
  e->op_flags = SOCK_NONBLOCK | SOCK_CLOEXEC;
  e->user_data = (uint64_t)(uintptr_t)h | VTL_UTAG_ACCEPT;
  h->ms_accept = true;
  h->inflight++;
}
#endif

// reap DONE pumps: connect failures become punts (fd_a intact), clean
// completions count as served — Python never sees these pump ids
static void lane_reap(Lane* ln) {
  Loop* l = ln->loop;
  Lanes* ow = ln->owner;
  for (uint64_t id : l->done_pumps) {
    auto it = l->pumps.find(id);
    if (it == l->pumps.end()) continue;
    Pump* p = it->second;
    auto mit = ln->meta.find(id);
    uint64_t tid = mit != ln->meta.end() ? mit->second.trace_id : 0;
    if (p->connect_failed) {
      ow->punt_fail.fetch_add(1, std::memory_order_relaxed);
      g_lane_punt_fail.fetch_add(1, std::memory_order_relaxed);
      const LaneRec* b = (mit != ln->meta.end() && mit->second.route)
                             ? &mit->second.route->backends[mit->second.bidx]
                             : nullptr;
      if (tid)  // the trace rides the punt: python continues it
        lane_trace(ln, tid, TR_PUNT, mono_ns(), 0, 1, (uint16_t)p->err,
                   1);
      lane_emit_punt(ln, p->fd_a, LANE_PUNT_CONNECT_FAIL, p->err, nullptr,
                     b, tid);
    } else if (p->err == ECANCELED) {
      // lane-initiated kill (idle expiry / shutdown abort): a real
      // session, but NOT a served one — hit_rate must not count it
      ow->killed.fetch_add(1, std::memory_order_relaxed);
      ow->bytes.fetch_add(p->bytes_a2b + p->bytes_b2a,
                          std::memory_order_relaxed);
    } else {
      ow->served.fetch_add(1, std::memory_order_relaxed);
      g_lane_served.fetch_add(1, std::memory_order_relaxed);
      ow->bytes.fetch_add(p->bytes_a2b + p->bytes_b2a,
                          std::memory_order_relaxed);
    }
    if (!p->connect_failed && g_wl_on.load(std::memory_order_relaxed)) {
      // per-connection size/duration for the workload model: killed
      // sessions count too (they carried bytes), connect failures
      // never reached the serving distribution
      lanes_cap_obs(ow, LANE_CAP_CONN_BYTES, p->bytes_a2b + p->bytes_b2a);
      if (mit != ln->meta.end() && mit->second.t_acc_ns) {
        uint64_t now = mono_ns();
        uint64_t acc = mit->second.t_acc_ns;
        lanes_cap_obs(ow, LANE_CAP_CONN_MS,
                      now > acc ? (now - acc) / 1000000ull : 0);
      }
    }
    if (tid && !p->connect_failed) {
      // whole-lifetime close-out: the splice span covers connected ->
      // death (bytes in aux), the close span marks teardown + errno
      uint64_t now = mono_ns();
      ConnMeta& m = mit->second;
      uint64_t t0 = m.t_conn_ns ? m.t_conn_ns : m.t_acc_ns;
      lane_trace(ln, tid, TR_SPLICE, t0, now > t0 ? now - t0 : 0,
                 p->bytes_a2b + p->bytes_b2a, 0);
      lane_trace(ln, tid, TR_CLOSE, now, 0, 0, (uint16_t)p->err);
    }
    if (mit != ln->meta.end()) {
      ow->active.fetch_sub(1, std::memory_order_relaxed);
      ln->meta.erase(mit);
    }
    delete p;
    l->pumps.erase(it);
  }
  l->done_pumps.clear();
}

// connect deadline + idle timeout, the lane-local analog of the python
// sweep in TcpLB._arm_sweep (250ms cadence)
static void lane_sweep(Lane* ln, uint64_t now) {
  if (now < ln->next_sweep_us) return;
  ln->next_sweep_us = now + 250000;
  Lanes* ow = ln->owner;
  uint64_t cto = (uint64_t)ow->connect_timeout_ms * 1000;
  uint64_t idle =
      (uint64_t)ow->timeout_ms.load(std::memory_order_relaxed) * 1000;
  for (auto& kv : ln->meta) {
    auto pit = ln->loop->pumps.find(kv.first);
    if (pit == ln->loop->pumps.end()) continue;
    Pump* p = pit->second;
    if (p->dead) continue;
    if (p->b_connecting) {
      if (now - p->created_us >= cto)
        pump_fail_connect(ln->loop, p, ETIMEDOUT);
      continue;
    }
    uint64_t total = p->bytes_a2b + p->bytes_b2a;
    if (total != kv.second.last_total) {
      kv.second.last_total = total;
      kv.second.last_ts_us = now;
    } else if (now - kv.second.last_ts_us >= idle) {
      // ECANCELED marks lane-initiated kills (idle expiry here, the
      // shutdown grace abort) so reap counts them as killed, not served
      pump_kill(ln->loop, p, ECANCELED);
    }
  }
}

// free torn-down handlers — but never while the ring still owes them
// CQEs (uring user_data holds the raw pointer)
static void lane_gc(Loop* l) {
  size_t w = 0;
  for (size_t i = 0; i < l->garbage.size(); ++i) {
    Handler* h = l->garbage[i];
    if (h->inflight == 0)
      delete h;
    else
      l->garbage[w++] = h;
  }
  l->garbage.resize(w);
}

static void lane_event(Lane* ln, Handler* h, uint32_t e) {
  Loop* l = ln->loop;
  switch (h->kind) {
    case Handler::WAKE: {
      uint64_t v;
      while (read(l->wakefd, &v, 8) == 8) {}
      break;
    }
    case Handler::LANE:
      if (!ln->listener_closed) lane_accept_batch(ln);
      break;
    case Handler::PUMP_A:
    case Handler::PUMP_B: {
      Pump* p = h->pump;
      if (h->kind == Handler::PUMP_B && p->b_connecting) {
        // same contract as vtl_poll: SO_ERROR decides; EPOLLHUP with
        // SO_ERROR==0 is a successful connect whose peer already closed
        int err = 0;
        socklen_t elen = sizeof(err);
        getsockopt(h->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
        if (err) {
          pump_fail_connect(l, p, err);
        } else {
          p->b_connecting = false;
          p->connect_us = mono_us() - p->created_us;
          lanes_lat_obs(ln->owner, p->connect_us);
          {  // stage histograms + the sampled trace's connect span
            auto mit = ln->meta.find(p->id);
            if (mit != ln->meta.end()) {
              ConnMeta& m = mit->second;
              uint64_t now = mono_ns();
              lanes_stage_obs(ln->owner, LANE_STAGE_HANDOVER,
                              p->connect_us);
              lanes_stage_obs(ln->owner, LANE_STAGE_TOTAL,
                              (now - m.t_acc_ns) / 1000);
              if (m.trace_id) {
                uint64_t dur = p->connect_us * 1000ull;
                lane_trace(ln, m.trace_id, TR_CONNECT,
                           now > dur ? now - dur : now, dur, 0, 0);
                m.t_conn_ns = now;
              }
            }
          }
          Handler* ha =
              l->handlers.count(p->fd_a) ? l->handlers[p->fd_a] : nullptr;
          if (ha) ep_set(l, ha, VTL_EV_READ);
          ep_set(l, h, VTL_EV_READ);
          pump_run(l, p);
        }
        break;
      }
      if (e & EPOLLERR) {
        int err = 0;
        socklen_t elen = sizeof(err);
        getsockopt(h->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
        pump_kill(l, p, err ? err : EIO);
      } else {
        pump_run(l, p);
      }
      break;
    }
    default:
      break;
  }
}

static void lane_wait_epoll(Lane* ln, int timeout_ms) {
  epoll_event eps[256];
  int n = epoll_wait(ln->loop->ep, eps, 256, timeout_ms);
  for (int i = 0; i < n; ++i) {
    Handler* h = (Handler*)eps[i].data.ptr;
    if (!ln->loop->valid.count(h)) continue;
    lane_event(ln, h, eps[i].events);
  }
}

#ifndef VTL_NO_URING
static void lane_cqe(Lane* ln, vtl_uring_cqe* c) {
  Loop* l = ln->loop;
  uint64_t ud = c->user_data;
  if ((ud & 7) == VTL_UTAG_TIMEOUT) {
    ln->to_pending = false;
    return;
  }
  Handler* h = (Handler*)(uintptr_t)(ud & ~7ull);
  int tag = (int)(ud & 7);
  bool valid = l->valid.count(h) != 0;
  if (tag == (int)VTL_UTAG_CANCEL) {
    h->inflight--;
    return;
  }
  if (tag == (int)VTL_UTAG_ACCEPT) {
    bool more = (c->flags & VTL_IORING_CQE_F_MORE) != 0;
    if (!more) {
      h->inflight--;
      h->ms_accept = false;
    }
    if (c->res >= 0) {
      if (valid && !ln->listener_closed)
        lane_client(ln, c->res, nullptr);
      else
        close(c->res);
    } else if (c->res == -EINVAL && valid && !ln->listener_closed) {
      // kernel without multishot accept: poll + accept4 batch instead
      ep_set(l, h, VTL_EV_READ);
      return;
    }
    if (!more && valid && !ln->listener_closed && !h->ms_accept &&
        c->res != -ECANCELED)
      lane_arm_accept(ln);
    return;
  }
  // oneshot poll completion
  h->inflight--;
  h->poll_pending = false;
  if (!valid) return;
  if (c->res > 0) lane_event(ln, h, (uint32_t)c->res);
  if (l->valid.count(h) && !h->poll_pending) {
    // re-arm per the CURRENT interest (dispatch may have changed it)
    uint16_t ev = 0;
    if (h->interest != (uint32_t)-1) {
      if (h->interest & VTL_EV_READ) ev |= POLLIN;
      if (h->interest & VTL_EV_WRITE) ev |= POLLOUT;
    }
    if (ev) uring_arm_poll(l, h, ev);
  }
}

static void lane_wait_uring(Lane* ln, int timeout_ms) {
  Loop* l = ln->loop;
  Uring* u = l->ur;
  if (!ln->to_pending) {
    // a TIMEOUT op bounds the enter (completes after 1 CQE or timeout);
    // ts lives on the Lane so the kernel's reference stays valid
    vtl_uring_sqe* e = uring_sqe(l);
    if (e) {
      ln->to_ts.sec = timeout_ms / 1000;
      ln->to_ts.nsec = (int64_t)(timeout_ms % 1000) * 1000000;
      e->opcode = VTL_IORING_OP_TIMEOUT;
      e->fd = -1;
      e->addr = (uint64_t)(uintptr_t)&ln->to_ts;
      e->len = 1;
      e->off = 1;
      e->user_data = VTL_UTAG_TIMEOUT;
      ln->to_pending = true;
    }
  }
  int r = sys_uring_enter(u->fd, u->to_submit, 1,
                          VTL_IORING_ENTER_GETEVENTS);
  if (r >= 0) u->to_submit = 0;
  unsigned head = *u->cq_head;
  unsigned tail = __atomic_load_n(u->cq_tail, __ATOMIC_ACQUIRE);
  while (head != tail) {
    lane_cqe(ln, &u->cqes[head & *u->cq_mask]);
    ++head;
  }
  __atomic_store_n(u->cq_head, head, __ATOMIC_RELEASE);
}
#endif  // !VTL_NO_URING

static void lane_abort_all(Lane* ln) {
  for (auto& kv : ln->loop->pumps)
    if (!kv.second->dead) pump_kill(ln->loop, kv.second, ECANCELED);
}

static Loop* lane_loop_new(bool uring) {
  Loop* l = new Loop();
  l->ep = epoll_create1(EPOLL_CLOEXEC);
  l->wakefd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  if (uring) l->ur = uring_new(256);  // nullptr -> epoll fallback
  Handler* h = new Handler{Handler::WAKE, 0, nullptr, l->wakefd,
                           (uint32_t)-1};
  l->handlers[l->wakefd] = h;
  l->valid.insert(h);
  ep_set(l, h, VTL_EV_READ);
  return l;
}

int vtl_lanes_free(void* lp);

// why the last vtl_lanes_new on THIS thread returned NULL: a real
// errno (bind/EMFILE) or EINVAL for bad args — Python surfaces it so
// a config error is not misreported as a port conflict
static thread_local int g_lanes_err = 0;
int vtl_lanes_errno(void) { return g_lanes_err; }

// -> Lanes handle or NULL (bind failure / bad args). engine_req: 0
// forces epoll, 1 uses io_uring when the runtime probe allows it.
// defer_accept_s > 0 arms TCP_DEFER_ACCEPT on every lane listener
// (client-speaks-first workloads: empty accepts never wake a lane).
void* vtl_lanes_new(const char* ip, int port, int backlog, int nlanes,
                    int bufsize, int engine_req, int timeout_ms,
                    int connect_timeout_ms, int defer_accept_s) {
  if (nlanes <= 0 || nlanes > 64) {
    g_lanes_err = EINVAL;
    return nullptr;
  }
  Lanes* ow = new Lanes();
  if (bufsize > 0) ow->bufsize = bufsize;
  if (timeout_ms > 0) ow->timeout_ms = timeout_ms;
  if (connect_timeout_ms > 0) ow->connect_timeout_ms = connect_timeout_ms;
  int probe = vtl_uring_probe();
  bool uring = engine_req && (probe & 1) && (probe & 2) && (probe & 8);
  int v6 = strchr(ip, ':') != nullptr;
  for (int i = 0; i < nlanes; ++i) {
    int lfd = vtl_tcp_listen(ip, port, backlog, 1, v6);
    if (lfd < 0) {
      g_lanes_err = -lfd;
      vtl_lanes_free(ow);
      return nullptr;
    }
    if (defer_accept_s > 0)
      setsockopt(lfd, IPPROTO_TCP, TCP_DEFER_ACCEPT, &defer_accept_s,
                 sizeof(defer_accept_s));
    if (port == 0) {  // first lane resolves the ephemeral port
      sockaddr_storage ss;
      socklen_t sl = sizeof(ss);
      if (getsockname(lfd, (sockaddr*)&ss, &sl) == 0)
        port = ss.ss_family == AF_INET6
                   ? ntohs(((sockaddr_in6*)&ss)->sin6_port)
                   : ntohs(((sockaddr_in*)&ss)->sin_port);
    }
    Lane* ln = new Lane();
    ln->owner = ow;
    ln->idx = i;
    ln->lfd = lfd;
    ln->loop = lane_loop_new(uring);
    ln->tring = new TraceRing(
        g_trace_ring_cap.load(std::memory_order_relaxed));
    ln->hh = new HHShard();
    if (i == 0 && uring && !ln->loop->ur) uring = false;  // setup refused
    Handler* h = new Handler{Handler::LANE, (uint64_t)i, nullptr, lfd,
                             (uint32_t)-1};
    ln->lh = h;
    ln->loop->handlers[lfd] = h;
    ln->loop->valid.insert(h);
#ifndef VTL_NO_URING
    if (ln->loop->ur)
      lane_arm_accept(ln);
    else
#endif
      ep_set(ln->loop, h, VTL_EV_READ);
    ow->lanes.push_back(ln);
  }
  // engine honesty: report uring ONLY when every lane actually got a
  // ring (a tight RLIMIT_MEMLOCK can fail ring N after ring 0 worked;
  // that lane runs epoll and the artifact must not claim otherwise)
  ow->engine = 1;
  for (Lane* ln : ow->lanes)
    if (!ln->loop->ur) ow->engine = 0;
  if (ow->lanes.empty()) ow->engine = 0;
  ow->port = port;
  return ow;
}

int vtl_lanes_port(void* lp) { return ((Lanes*)lp)->port; }

// one atomic load — the per-accept overload check's read (the 11-field
// stat is for list-detail/HTTP, not the hot path)
long long vtl_lanes_active(void* lp) {
  if (!lp) return 0;
  return (long long)((Lanes*)lp)->active.load(std::memory_order_relaxed);
}
int vtl_lanes_engine(void* lp) { return ((Lanes*)lp)->engine; }

uint64_t vtl_lane_gen(void* lp) {
  return ((Lanes*)lp)->gen.load(std::memory_order_relaxed);
}

// ONE atomic — safe from any thread; every upstream/ACL/backend-health
// mutation calls this (the lane-entry analog of vtl_switch_gen_bump)
void vtl_lane_gen_bump(void* lp) {
  ((Lanes*)lp)->gen.fetch_add(1, std::memory_order_relaxed);
}

// Install the compiled lane entry, stamped with the generation read
// BEFORE compilation began. -EAGAIN when a mutation raced the compile
// (Python recompiles against current state); otherwise the usable WRR
// sequence length (0 = punt-everything entry, e.g. non-trivial ACL).
int vtl_lane_install(void* lp, const void* recs, int n,
                     const int32_t* seq, int nseq, uint64_t gen) {
  Lanes* ow = (Lanes*)lp;
  if (gen != ow->gen.load(std::memory_order_relaxed)) return -EAGAIN;
  auto rt = std::make_shared<LaneRoute>();
  rt->gen = gen;
  const LaneRec* r = (const LaneRec*)recs;
  std::vector<int32_t> remap((size_t)(n > 0 ? n : 0), -1);
  for (int i = 0; i < n; ++i) {
    char ipb[48];
    memcpy(ipb, r[i].ip, 46);
    ipb[46] = 0;
    sockaddr_storage ss;
    socklen_t sl;
    if (mk_addr(ipb, r[i].port, r[i].v6, &ss, &sl) < 0) continue;
    remap[i] = (int32_t)rt->backends.size();
    rt->backends.push_back(r[i]);
    rt->addrs.push_back(ss);
    rt->lens.push_back(sl);
    char kb[64];
    int kl = snprintf(kb, sizeof(kb), "%s:%u", ipb,
                      (unsigned)r[i].port);
    rt->bkeys.emplace_back(kb, (size_t)(kl > 0 ? kl : 0));
  }
  for (int j = 0; j < nseq; ++j)
    if (seq[j] >= 0 && seq[j] < n && remap[seq[j]] >= 0)
      rt->seq.push_back(remap[seq[j]]);
  {
    std::lock_guard<std::mutex> g(ow->mu);
    ow->route = rt;
  }
  return (int)rt->seq.size();
}

// Install the compiled maglev route: n MaglevRec backends plus the
// slot->backend table (m entries, values indexing recs; -1 = unowned).
// Stamped + raced exactly like vtl_lane_install (-EAGAIN recompiles);
// returns the usable table size. hash_port=0 gives source affinity
// (client address only), 1 per-connection spread (address + port).
int vtl_lane_maglev_install(void* lp, const void* recs, int n,
                            const int32_t* table, int m, int hash_port,
                            uint64_t gen) {
  Lanes* ow = (Lanes*)lp;
  if (m < 0 || n < 0) return -EINVAL;
  if (gen != ow->gen.load(std::memory_order_relaxed)) return -EAGAIN;
  auto rt = std::make_shared<LaneRoute>();
  rt->gen = gen;
  rt->maglev_hash_port = hash_port ? 1 : 0;
  const MaglevRec* r = (const MaglevRec*)recs;
  std::vector<int32_t> remap((size_t)(n > 0 ? n : 0), -1);
  for (int i = 0; i < n; ++i) {
    char ipb[48];
    memcpy(ipb, r[i].ip, 46);
    ipb[46] = 0;
    sockaddr_storage ss;
    socklen_t sl;
    if (mk_addr(ipb, r[i].port, r[i].v6, &ss, &sl) < 0) continue;
    remap[i] = (int32_t)rt->backends.size();
    LaneRec lr;
    memcpy(lr.ip, r[i].ip, 46);
    lr.port = r[i].port;
    lr.v6 = r[i].v6;
    lr.weight = r[i].weight;
    rt->backends.push_back(lr);
    rt->addrs.push_back(ss);
    rt->lens.push_back(sl);
    char kb[64];
    int kl = snprintf(kb, sizeof(kb), "%s:%u", ipb,
                      (unsigned)r[i].port);
    rt->bkeys.emplace_back(kb, (size_t)(kl > 0 ? kl : 0));
  }
  rt->maglev.resize((size_t)m, -1);
  for (int j = 0; j < m; ++j)
    if (table[j] >= 0 && table[j] < n) rt->maglev[j] = remap[table[j]];
  if (rt->backends.empty()) rt->maglev.clear();  // punt-everything entry
  {
    std::lock_guard<std::mutex> g(ow->mu);
    ow->route = rt;
  }
  return (int)rt->maglev.size();
}

int vtl_police_rec_size(void) { return (int)sizeof(PoliceRec); }

void vtl_police_set_enabled(int on) {
  g_police_on.store(on ? 1 : 0, std::memory_order_relaxed);
}

// Install the compiled policing table, stamped with the generation read
// BEFORE the engine's compile began (the vtl_lane_install contract):
// -EAGAIN when a mutation raced it — python re-reads the generation and
// recompiles. Live bucket state carries over from the previous table
// for keys that persist across ticks (a reinstall must not hand every
// hot client a fresh burst). -> entries installed.
int vtl_police_install(void* lp, const void* recs, int n, uint64_t gen) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || n < 0 || (n > 0 && !recs)) return -EINVAL;
  if (gen != ow->gen.load(std::memory_order_relaxed)) return -EAGAIN;
  std::shared_ptr<PoliceTable> old;
  {
    std::lock_guard<std::mutex> g(ow->mu);
    old = ow->police;
  }
  auto pt = std::make_shared<PoliceTable>();
  pt->gen = gen;
  uint32_t cap = 8;
  while (cap < (uint32_t)(n * 2 + 1)) cap <<= 1;
  pt->slots = std::vector<PoliceSlot>(cap);
  const PoliceRec* r = (const PoliceRec*)recs;
  uint64_t now = mono_ns();
  int installed = 0;
  for (int i = 0; i < n; ++i) {
    if (!r[i].key_hash) continue;  // 0 is the empty-slot sentinel
    uint32_t idx = (uint32_t)r[i].key_hash & (cap - 1);
    for (uint32_t p = 0; p < cap; ++p, idx = (idx + 1) & (cap - 1)) {
      PoliceSlot& s = pt->slots[idx];
      if (s.key_hash && s.key_hash != r[i].key_hash) continue;
      bool fresh = !s.key_hash;
      s.key_hash = r[i].key_hash;
      s.rate_mtok = r[i].rate_mtok;
      s.burst_mtok = r[i].burst_mtok;
      s.action = r[i].action;
      s.level_mtok = (int64_t)r[i].burst_mtok;  // full (the engine law)
      s.t_ns = now;
      PoliceSlot* prev = police_find(old.get(), r[i].key_hash);
      if (prev && prev->rate_mtok == s.rate_mtok &&
          prev->burst_mtok == s.burst_mtok) {
        // same policy parameters: the bucket survives the reinstall
        // (read under the slot lock — lanes still debit the old table)
        while (prev->lk.exchange(1, std::memory_order_acquire)) {}
        s.level_mtok = prev->level_mtok;
        s.t_ns = prev->t_ns;
        prev->lk.store(0, std::memory_order_release);
      }
      if (fresh) ++installed;
      break;
    }
  }
  {
    std::lock_guard<std::mutex> g(ow->mu);
    ow->police = pt;
  }
  return installed;
}

// out: checked, shed, throttled, monitored, stale -> 5 (this Lanes
// object only; python drains as deltas on lane 0's tick)
int vtl_police_counters(void* lp, uint64_t* out) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || !out) return -EINVAL;
  out[0] = ow->pol_checked.load(std::memory_order_relaxed);
  out[1] = ow->pol_shed.load(std::memory_order_relaxed);
  out[2] = ow->pol_throttled.load(std::memory_order_relaxed);
  out[3] = ow->pol_monitored.load(std::memory_order_relaxed);
  out[4] = ow->pol_stale.load(std::memory_order_relaxed);
  return 5;
}

// Deterministic probe at an explicit timestamp — the C==python parity
// surface (tests drive this and engine.check_at with the same key/ns
// sequence and assert identical verdicts) and the TSan driver's churn
// target. Runs the EXACT accept-path logic including the knob and the
// generation gate, and bumps the same counters: -2 knob off, -1 forced
// consult-miss (no table / stale stamp / unknown key -> admit),
// else 0 admit, or 1 + action code when over quota (1 monitor,
// 2 throttle, 3 shed).
int vtl_police_check(void* lp, const void* key, int klen,
                     uint64_t now_ns) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || !key || klen <= 0) return -EINVAL;
  if (!g_police_on.load(std::memory_order_relaxed)) return -2;
  std::shared_ptr<PoliceTable> pt;
  {
    std::lock_guard<std::mutex> g(ow->mu);
    pt = ow->police;
  }
  if (!pt) return -1;
  if (pt->gen != ow->gen.load(std::memory_order_relaxed)) {
    ow->pol_stale.fetch_add(1, std::memory_order_relaxed);
    return -1;
  }
  PoliceSlot* s = police_find(pt.get(),
                              maglev_fnv64((const uint8_t*)key,
                                           (size_t)klen));
  if (!s) return -1;
  ow->pol_checked.fetch_add(1, std::memory_order_relaxed);
  if (police_debit(*s, now_ns)) return 0;
  if (s->action == POLICE_ACT_SHED)
    ow->pol_shed.fetch_add(1, std::memory_order_relaxed);
  else if (s->action == POLICE_ACT_THROTTLE)
    ow->pol_throttled.fetch_add(1, std::memory_order_relaxed);
  else
    ow->pol_monitored.fetch_add(1, std::memory_order_relaxed);
  return 1 + (int)s->action;
}

int vtl_lanes_set_punt_all(void* lp, int on) {
  ((Lanes*)lp)->punt_all.store(on ? 1 : 0, std::memory_order_relaxed);
  return 0;
}

// hot-set the idle timeout (`update tcp-lb ... timeout` must govern
// lane-owned sessions too; the sweep reads it per pass)
int vtl_lanes_set_timeout(void* lp, int timeout_ms) {
  if (!lp || timeout_ms <= 0) return -EINVAL;
  ((Lanes*)lp)->timeout_ms.store(timeout_ms, std::memory_order_relaxed);
  return 0;
}

// n >= 0 is the REMAINING session budget (Python forwards
// max_sessions - its own active count, so the ceiling is shared across
// both admission paths); 0 = admit none (punt everything); n < 0
// restores the effectively-unlimited default.
int vtl_lanes_set_limit(void* lp, long long n) {
  ((Lanes*)lp)->max_active.store(n >= 0 ? n : (1ll << 30),
                                 std::memory_order_relaxed);
  return 0;
}

// adaptive-overload shed mode: on != 0 makes over-limit accepts
// RST-close in C (counted `shed`); off restores the classic punt so
// Python's shed path — with its drain/static accounting — decides.
int vtl_lanes_set_shed(void* lp, int on) {
  if (!lp) return -EINVAL;
  ((Lanes*)lp)->shed_rst.store(on ? 1 : 0, std::memory_order_relaxed);
  return 0;
}

// out: accepted, served, active, punt_classic, punt_stale, punt_fail,
// bytes, gen, engine, port, killed, shed, accept-latency EWMA us
// -> 13 (this Lanes object only)
int vtl_lanes_stat(void* lp, uint64_t* out) {
  Lanes* ow = (Lanes*)lp;
  if (!ow) return -EINVAL;
  out[0] = ow->accepted.load(std::memory_order_relaxed);
  out[1] = ow->served.load(std::memory_order_relaxed);
  out[2] = ow->active.load(std::memory_order_relaxed);
  out[3] = ow->punt_classic.load(std::memory_order_relaxed);
  out[4] = ow->punt_stale.load(std::memory_order_relaxed);
  out[5] = ow->punt_fail.load(std::memory_order_relaxed);
  out[6] = ow->bytes.load(std::memory_order_relaxed);
  out[7] = ow->gen.load(std::memory_order_relaxed);
  out[8] = (uint64_t)ow->engine;
  out[9] = (uint64_t)ow->port;
  out[10] = ow->killed.load(std::memory_order_relaxed);
  out[11] = ow->shed.load(std::memory_order_relaxed);
  out[12] = ow->lat_ewma_us.load(std::memory_order_relaxed);
  return 13;
}

// process-global: accepted, served, punt_classic, punt_stale, punt_fail
int vtl_lane_counters(uint64_t* out) {
  out[0] = g_lane_accepted.load(std::memory_order_relaxed);
  out[1] = g_lane_served.load(std::memory_order_relaxed);
  out[2] = g_lane_punt_classic.load(std::memory_order_relaxed);
  out[3] = g_lane_punt_stale.load(std::memory_order_relaxed);
  out[4] = g_lane_punt_fail.load(std::memory_order_relaxed);
  return 5;
}

static void lanes_wake(Lanes* ow) {
  for (Lane* ln : ow->lanes) {
    uint64_t one = 1;
    ssize_t r = write(ln->loop->wakefd, &one, 8);
    (void)r;
  }
}

// drain: each lane closes its OWN listener at the next poll tick (a
// cross-thread close would race fd reuse); live pumps run on
int vtl_lanes_close_listeners(void* lp) {
  Lanes* ow = (Lanes*)lp;
  ow->close_listeners.store(1, std::memory_order_relaxed);
  lanes_wake(ow);
  return 0;
}

// stop: listeners close, pumps get grace_ms to finish, then die; each
// lane thread's vtl_lane_poll returns -ESHUTDOWN once its loop is empty
int vtl_lanes_shutdown(void* lp, int grace_ms) {
  Lanes* ow = (Lanes*)lp;
  ow->close_listeners.store(1, std::memory_order_relaxed);
  ow->abort_at_us.store(mono_us() + (uint64_t)(grace_ms > 0 ? grace_ms : 0)
                                        * 1000,
                        std::memory_order_relaxed);
  ow->shutting.store(1, std::memory_order_relaxed);
  lanes_wake(ow);
  return 0;
}

// after every lane thread observed -ESHUTDOWN (python joins them first)
int vtl_lanes_free(void* lp) {
  Lanes* ow = (Lanes*)lp;
  for (Lane* ln : ow->lanes) {
    if (ln->lfd >= 0) close(ln->lfd);
    vtl_free(ln->loop);
    delete ln->tring;
    delete ln->hh;
    delete ln;
  }
  delete ow;
  return 0;
}

// The lane thread's park: runs the whole accept->route->splice lifetime
// in C for up to timeout_ms, returning early with punt records the
// moment any connection needs Python. -> punt count, 0 on timeout,
// -ESHUTDOWN when the lane drained after vtl_lanes_shutdown.
int vtl_lane_poll(void* lp, int idx, void* punts_out, int max_punts,
                  int timeout_ms) {
  Lanes* ow = (Lanes*)lp;
  if (!ow || idx < 0 || idx >= (int)ow->lanes.size() || max_punts <= 0)
    return -EINVAL;
  Lane* ln = ow->lanes[idx];
  Loop* l = ln->loop;
  uint64_t deadline =
      mono_us() + (uint64_t)(timeout_ms > 0 ? timeout_ms : 0) * 1000;
  LanePunt* out = (LanePunt*)punts_out;
  for (;;) {
    lane_gc(l);
    lane_reap(ln);
    if (!ln->punt_q.empty()) {
      int n = 0;
      while (n < max_punts && !ln->punt_q.empty()) {
        out[n++] = ln->punt_q.front();
        ln->punt_q.pop_front();
      }
      return n;
    }
    if (ow->close_listeners.load(std::memory_order_relaxed) &&
        !ln->listener_closed) {
      ln->listener_closed = true;
      auto it = l->handlers.find(ln->lfd);
      if (it != l->handlers.end()) {
        loop_detach(l, it->second);
        drop_handler(l, it->second);
        l->handlers.erase(it);
      }
      close(ln->lfd);
      ln->lfd = -1;
      ln->lh = nullptr;
    }
    if (ow->shutting.load(std::memory_order_relaxed)) {
      uint64_t ab = ow->abort_at_us.load(std::memory_order_relaxed);
      if (ab && mono_us() >= ab && !l->pumps.empty()) {
        lane_abort_all(ln);
        lane_reap(ln);
        if (!ln->punt_q.empty()) continue;  // deliver before exiting
      }
      if (l->pumps.empty()) return -ESHUTDOWN;
    }
    uint64_t now = mono_us();
    lane_sweep(ln, now);
    lane_reap(ln);
    if (!ln->punt_q.empty()) continue;
    if (now >= deadline) return 0;
    uint64_t until = std::min(deadline, ln->next_sweep_us);
    int wait_ms = until > now ? (int)((until - now) / 1000) : 0;
    if (wait_ms < 1) wait_ms = 1;
    if (wait_ms > 250) wait_ms = 250;
#ifndef VTL_NO_URING
    if (l->ur)
      lane_wait_uring(ln, wait_ms);
    else
#endif
      lane_wait_epoll(ln, wait_ms);
  }
}

}  // extern "C"
