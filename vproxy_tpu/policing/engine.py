"""Policing engine — detection → decision → enforcement.

The decision plane. Operators declare policies over the analytics
dimensions (`add policy gold dim=clients rate=50 burst=100 action=shed
[tenant=10.0.0.0/8]`); each tick the engine reads the rotating sketch
windows (utils/sketch), takes every policy's dimension top-K, and
compiles the matching keys into a compact enforcement table: one token
bucket per (dim, key). The hot paths then consult that table in O(1):

* C accept lanes — `compile_recs()` packs the clients-dimension entries
  into the generation-stamped POLICE_REC ABI and every registered
  installer (components/lanes.py) pushes them into the .so, where the
  probe is one open-addressed lookup + bucket debit in `lane_client`.
* python accept path — components/tcplb._on_accept calls `check()`
  with the same integer bucket math, so a punted or laned-off accept
  reaches the same verdict the C probe would have.
* AIMD shed order — when AdaptiveOverload's ceiling sheds, tcplb asks
  `overload_spare()`: over-quota keys are never spared, in-quota
  tenants draw on a deficit-round-robin budget refilled each tick in
  proportion to their policy rate (weighted-fair: a 3:1 rate ratio
  buys a 3:1 spare ratio under pressure).
* DNS — `quarantined()` turns a shed verdict on the qnames dimension
  into a pre-packed REFUSED answer that never re-walks the group.

Verdict vocabulary (closed — the vproxy_lb_policed_total `action`
label): `monitor` counts over-quota arrivals without refusing them (the
right default while calibrating a rate), `throttle` defers to the
overload ceiling (shed only when the LB is already at its limit),
`shed` refuses outright.

Determinism: bucket state is integer milli-tokens against explicit
monotonic nanoseconds — the exact arithmetic the C probe uses — so the
same arrival sequence at the same timestamps reaches the same verdict
sequence on either side (tests/test_policing.py drives both through
`vtl.police_check` and `check_at` and asserts bit-equality). The
`policing.decision.force` failpoint pins a verdict without traffic
shaping, and inherits VPROXY_TPU_FAILPOINT_SEED like every other site.

Fleet: `gossip_summary()` rides the membership heartbeat meta (the
PR-14 `hh` field idiom, cluster/__init__._hb_meta) and
`ingest_peer_tables()` merges what peers enforce into the local table
with a tick-TTL — a crowd seen by one node sheds on all within one
heartbeat period, and expires everywhere within TTL ticks of the
origin dropping it.

Knob: VPROXY_TPU_POLICING=0 disables every site for exactly one module
bool read per python site and one relaxed atomic per C site — the
workload.py/sketch.py knob contract, enforced by the knob-off test.
"""
from __future__ import annotations

import hashlib
import ipaddress
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import events, failpoint, sketch
from ..utils.trace import fnv64

ON = os.environ.get("VPROXY_TPU_POLICING", "1") != "0"
# tick cadence: half an analytics window keeps enforcement at most one
# rotation behind detection without a dedicated thread (ticks are lazy,
# piggybacked on check()/drain callers — the sketch rotation idiom)
TICK_S = float(os.environ.get("VPROXY_TPU_POLICING_TICK_S", "1.0"))
# gossip-merged entries survive this many ticks without a refresh from
# the origin node — the fleet forget bound
TTL_TICKS = int(os.environ.get("VPROXY_TPU_POLICING_TTL_TICKS", "5"))

ACTIONS = ("monitor", "throttle", "shed")
ACTION_CODE = {a: i for i, a in enumerate(ACTIONS)}

_NS = 1_000_000_000
_COST_MTOK = 1000  # one arrival = one token, in milli-tokens


def _client_key_bytes(key: str) -> bytes:
    """The hash-input contract for the clients dimension: the RAW 4/16
    address bytes, NOT the rendered string — the C probe hashes what
    maglev_addr_bytes hands it, and parity lives or dies here."""
    try:
        return socket.inet_pton(socket.AF_INET, key)
    except OSError:
        pass
    try:
        return socket.inet_pton(socket.AF_INET6, key)
    except OSError:
        return key.encode("utf-8", "replace")


def key_hash(dim: str, key: str) -> int:
    """POLICE_REC.key_hash — fnv64 over the dimension's canonical key
    bytes (raw address for clients, utf-8 for everything else)."""
    kb = _client_key_bytes(key) if dim == "clients" else \
        key.encode("utf-8", "replace")
    return fnv64(kb)


class TokenBucket:
    """Integer milli-token bucket against explicit monotonic ns — the
    ONE bucket law, duplicated (deliberately, with a parity test) in
    vtl.cpp police_debit. Starts full: a key's first appearance in the
    top-K is evidence of volume, but burst is the operator's grace."""

    __slots__ = ("rate_mtok", "burst_mtok", "level_mtok", "t_ns")

    def __init__(self, rate: float, burst: float, now_ns: int):
        self.rate_mtok = max(0, int(rate * 1000))
        self.burst_mtok = max(_COST_MTOK, int(burst * 1000))
        self.level_mtok = self.burst_mtok
        self.t_ns = now_ns

    def debit(self, now_ns: int, cost_mtok: int = _COST_MTOK) -> bool:
        """True = in quota (token taken), False = over quota."""
        dt = now_ns - self.t_ns
        if dt > 0:
            self.level_mtok = min(
                self.burst_mtok,
                self.level_mtok + self.rate_mtok * dt // _NS)
            self.t_ns = now_ns
        if self.level_mtok >= cost_mtok:
            self.level_mtok -= cost_mtok
            return True
        return False


class Policy:
    """One operator-declared rule: keys surfacing in `dim`'s top-K get
    a rate/burst bucket and `action` on over-quota. `tenant` scopes the
    policy (clients: a CIDR; other dims: an exact key match) and names
    a weight class for the fair-shed order."""

    __slots__ = ("name", "dim", "rate", "burst", "action", "tenant",
                 "_net")

    def __init__(self, name: str, dim: str, rate: float, burst: float,
                 action: str, tenant: Optional[str] = None):
        if dim not in sketch.DIMS:
            raise ValueError(f"unknown policy dimension {dim!r} "
                             f"(one of {', '.join(sketch.DIMS)})")
        if action not in ACTIONS:
            raise ValueError(f"unknown policy action {action!r} "
                             f"(one of {', '.join(ACTIONS)})")
        if rate <= 0:
            raise ValueError(f"policy rate must be positive, got {rate}")
        if burst < 1:
            raise ValueError(f"policy burst must be >= 1, got {burst}")
        self.name = name
        self.dim = dim
        self.rate = float(rate)
        self.burst = float(burst)
        self.action = action
        self.tenant = tenant
        self._net = None
        if tenant and dim == "clients":
            try:
                self._net = ipaddress.ip_network(tenant, strict=False)
            except ValueError:
                pass  # route-name tenant on a clients policy: no scope

    def matches(self, key: str) -> bool:
        if self.tenant is None:
            return True
        if self._net is not None:
            try:
                return ipaddress.ip_address(key) in self._net
            except ValueError:
                return False
        return key == self.tenant

    def describe(self) -> dict:
        return {"name": self.name, "dim": self.dim, "rate": self.rate,
                "burst": self.burst, "action": self.action,
                "tenant": self.tenant}


class _Entry:
    __slots__ = ("dim", "key", "policy", "action", "rate_mtok",
                 "burst_mtok", "bucket", "origin", "ttl")

    def __init__(self, dim, key, policy, action, rate_mtok, burst_mtok,
                 bucket, origin, ttl):
        self.dim = dim
        self.key = key
        self.policy = policy        # policy name (or peer node id)
        self.action = action
        self.rate_mtok = rate_mtok
        self.burst_mtok = burst_mtok
        self.bucket = bucket
        self.origin = origin        # "local" | "peer"
        self.ttl = ttl


class PolicingEngine:
    """One node's decision plane. The module-level `default()` instance
    serves the hot paths; tests build extras to model a fleet in one
    process."""

    def __init__(self):
        self.lock = threading.RLock()
        self.policies: Dict[str, Policy] = {}
        self._table: Dict[Tuple[str, str], _Entry] = {}
        self._deficit: Dict[str, float] = {}
        self._last_tick = 0.0
        self.seq = 0
        # counters — read by the metric families and GET /policing
        self.policed: Dict[Tuple[str, str, str], int] = {}  # (lb,act,dim)
        self.tables_installed = 0
        self.gossip_merges = 0
        self.ticks = 0
        # installers: callables(recs: List[bytes]) -> bool, registered
        # by every owner of a C lane table (components/lanes.py)
        self.on_install: List[Callable] = []

    # ---------------- policy set ----------------

    def set_policy(self, pol: Policy) -> None:
        with self.lock:
            self.policies[pol.name] = pol
            self._deficit.setdefault(self._tenant_name(pol), 0.0)

    def remove_policy(self, name: str) -> bool:
        with self.lock:
            return self.policies.pop(name, None) is not None

    def set_policies(self, pols) -> None:
        """Replace the whole set (the command/replication handler)."""
        with self.lock:
            self.policies = {p.name: p for p in pols}

    def list_policies(self) -> List[dict]:
        with self.lock:
            return [p.describe() for p in self.policies.values()]

    @staticmethod
    def _tenant_name(pol: Policy) -> str:
        return pol.tenant if pol.tenant is not None else ""

    # ---------------- tick: detection -> table ----------------

    def maybe_tick(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = time.monotonic()
        if now - self._last_tick < TICK_S:
            return False
        self.tick(now=now)
        return True

    def tick(self, now: Optional[float] = None,
             now_ns: Optional[int] = None) -> None:
        """Recompile the enforcement table from the current sketch
        windows, refill the fair-shed deficits, refresh TTLs, and push
        the clients-dimension slice into every registered C lane."""
        if now is None:
            now = time.monotonic()
        if now_ns is None:
            now_ns = time.monotonic_ns()
        with self.lock:
            self._last_tick = now
            self.seq += 1
            self.ticks += 1
            new: Dict[Tuple[str, str], _Entry] = {}
            dims_seen = set()
            for pol in self.policies.values():
                # refill the tenant's DRR budget: rate * tick worth of
                # spares, capped at one burst — weighted-fair by
                # construction (budget proportional to declared rate)
                tn = self._tenant_name(pol)
                self._deficit[tn] = min(
                    self._deficit.get(tn, 0.0) + pol.rate * TICK_S,
                    max(pol.burst, pol.rate * TICK_S))
                if pol.dim not in dims_seen:
                    dims_seen.add(pol.dim)
                for row in sketch.top_table(pol.dim, 0):
                    key = row["key"]
                    if not pol.matches(key):
                        continue
                    ent = self._table.get((pol.dim, key))
                    rate_mtok = int(pol.rate * 1000)
                    burst_mtok = max(_COST_MTOK, int(pol.burst * 1000))
                    if (ent is not None and ent.policy == pol.name
                            and ent.rate_mtok == rate_mtok
                            and ent.burst_mtok == burst_mtok):
                        ent.ttl = TTL_TICKS  # carry bucket state over
                        new[(pol.dim, key)] = ent
                    else:
                        new[(pol.dim, key)] = _Entry(
                            pol.dim, key, pol.name, pol.action,
                            rate_mtok, burst_mtok,
                            TokenBucket(pol.rate, pol.burst, now_ns),
                            "local", TTL_TICKS)
            # peer-merged entries age out instead of recompiling — the
            # origin node's next gossip refreshes them
            for k, ent in self._table.items():
                if ent.origin != "peer" or k in new:
                    continue
                ent.ttl -= 1
                if ent.ttl > 0:
                    new[k] = ent
            self._table = new
            installers = list(self.on_install)
            recs = self._compile_recs_locked()
        installed = 0
        for cb in installers:
            try:
                if cb(recs):
                    installed += 1
            except Exception:
                pass
        if installed:
            with self.lock:
                self.tables_installed += installed
        if ON:
            events.record(
                "policy_install",
                f"policing table seq={self.seq} keys={len(new)} "
                f"lanes={installed}",
                plane="policing", seq=self.seq, keys=len(new),
                lanes=installed)

    # ---------------- the verdict ----------------

    def check(self, dim: str, key: str, lb: str = "",
              trace_id: int = 0,
              now_ns: Optional[int] = None) -> str:
        """The python accept mirror: one dict probe + one bucket debit.
        Returns one of "admit" | ACTIONS. Accounts every non-admit
        verdict under (lb, action, dim)."""
        if not ON:
            return "admit"
        if failpoint.hit("policing.decision.force", f"{dim}:{key}"):
            self._account(lb, "shed", dim)
            self._shed_event(dim, key, lb, "shed", trace_id,
                             forced=True)
            return "shed"
        with self.lock:
            ent = self._table.get((dim, key))
            if ent is None:
                return "admit"
            if now_ns is None:
                now_ns = time.monotonic_ns()
            if ent.bucket.debit(now_ns):
                return "admit"
            action = ent.action
            self._account_locked(lb, action, dim)
        if action != "monitor":
            self._shed_event(dim, key, lb, action, trace_id)
        return action

    def check_at(self, dim: str, key: str, now_ns: int) -> str:
        """Deterministic probe at an explicit timestamp — the parity
        test's python half (no accounting, no failpoint, mirrors
        vtl.police_check exactly)."""
        with self.lock:
            ent = self._table.get((dim, key))
            if ent is None:
                return "admit"
            if ent.bucket.debit(now_ns):
                return "admit"
            return ent.action

    def quarantined(self, qname: str, lb: str = "",
                    trace_id: int = 0) -> bool:
        """DNS hook: True = answer REFUSED from the packed cache layer,
        never re-walk the group."""
        if not ON:
            return False
        v = self.check("qnames", qname, lb=lb, trace_id=trace_id)
        if v == "shed":
            events.record("quarantine",
                          f"qname {qname} quarantined on {lb}",
                          plane="policing", qname=qname, lb=lb,
                          trace_id=trace_id)
            return True
        return False

    def overload_spare(self, ip: str, lb: str = "",
                       trace_id: int = 0) -> bool:
        """The weighted-fair shed order. Called when the AIMD ceiling
        would shed this arrival: True = spare it (in-quota tenant with
        deficit budget left), False = shed as planned. Over-quota keys
        are NEVER spared — they are what the ceiling should be shedding
        first."""
        if not ON:
            return False
        with self.lock:
            ent = self._table.get(("clients", ip))
            if ent is not None:
                if not ent.bucket.debit(time.monotonic_ns()):
                    # over quota: the preferred victim
                    self._account_locked(lb, ent.action, "clients")
                    return False
            pol = self._tenant_policy(ip)
            if pol is None:
                return False  # unclassed traffic draws no spare budget
            tn = self._tenant_name(pol)
            if self._deficit.get(tn, 0.0) >= 1.0:
                self._deficit[tn] -= 1.0
                return True
            return False

    def _tenant_policy(self, ip: str) -> Optional[Policy]:
        for pol in self.policies.values():
            if pol.dim == "clients" and pol.tenant is not None \
                    and pol.matches(ip):
                return pol
        return None

    # ---------------- accounting ----------------

    def _account(self, lb: str, action: str, dim: str,
                 n: int = 1) -> None:
        with self.lock:
            self._account_locked(lb, action, dim, n)

    def _account_locked(self, lb, action, dim, n: int = 1) -> None:
        k = (lb, action, dim)
        self.policed[k] = self.policed.get(k, 0) + n

    def account_native(self, lb: str, action: str, dim: str,
                       n: int) -> None:
        """Fold a C-lane counter delta (lane 0's drain merges the .so
        tallies exactly once — the _fold_lane_sheds contract)."""
        if n > 0:
            self._account(lb, action, dim, n)

    def _shed_event(self, dim, key, lb, action, trace_id,
                    forced=False) -> None:
        events.record("policy_shed",
                      f"policing {action} {dim}:{key} on {lb}",
                      plane="policing", dim=dim, key=key, lb=lb,
                      action=action, forced=forced, trace_id=trace_id)

    def policed_total(self, lb: Optional[str] = None,
                      action: Optional[str] = None,
                      dim: Optional[str] = None) -> int:
        with self.lock:
            return sum(
                v for (l, a, d), v in self.policed.items()
                if (lb is None or l == lb)
                and (action is None or a == action)
                and (dim is None or d == dim))

    # ---------------- the C table ----------------

    def _compile_recs_locked(self) -> List[bytes]:
        from ..net import vtl
        recs = []
        for (dim, key), ent in self._table.items():
            if dim != "clients":
                continue  # the lanes only see client addresses
            recs.append(vtl.POLICE_REC.pack(
                key_hash(dim, key), ent.rate_mtok, ent.burst_mtok,
                ACTION_CODE[ent.action], 0, b"\x00\x00"))
        return recs

    def compile_recs(self) -> List[bytes]:
        with self.lock:
            return self._compile_recs_locked()

    # ---------------- fleet ----------------

    def gossip_summary(self) -> dict:
        """The heartbeat-meta payload: locally-compiled entries only
        (peer-merged state is never re-gossiped — no echo
        amplification). Always small: bounded by K per policed dim."""
        with self.lock:
            return {"seq": self.seq,
                    "t": [[e.dim, e.key, e.rate_mtok, e.burst_mtok,
                           ACTION_CODE[e.action]]
                          for e in self._table.values()
                          if e.origin == "local"]}

    def ingest_peer_tables(self, peers: dict) -> int:
        """Merge what UP peers enforce ({node_id: gossip_summary()}).
        Local entries always win (this node has its own evidence);
        peer entries enter with a fresh TTL and age out unless
        re-gossiped. Returns newly-merged key count."""
        if not ON:
            return 0
        merged = 0
        now_ns = time.monotonic_ns()
        with self.lock:
            for nid, summ in (peers or {}).items():
                for row in (summ or {}).get("t", ()):
                    try:
                        dim, key, rate_mtok, burst_mtok, act = row[:5]
                        action = ACTIONS[int(act)]
                        rate_mtok = int(rate_mtok)
                        burst_mtok = int(burst_mtok)
                    except (ValueError, IndexError, TypeError):
                        continue
                    ent = self._table.get((dim, key))
                    if ent is not None and ent.origin == "local":
                        continue
                    if (ent is not None and ent.rate_mtok == rate_mtok
                            and ent.burst_mtok == burst_mtok
                            and ACTION_CODE[ent.action] == act):
                        ent.ttl = TTL_TICKS  # refresh, keep bucket
                        continue
                    tb = TokenBucket(rate_mtok / 1000.0,
                                     burst_mtok / 1000.0, now_ns)
                    self._table[(dim, key)] = _Entry(
                        dim, key, str(nid), action, rate_mtok,
                        burst_mtok, tb, "peer", TTL_TICKS)
                    merged += 1
            if merged:
                self.gossip_merges += merged
        return merged

    # ---------------- introspection ----------------

    def table_snapshot(self) -> List[dict]:
        with self.lock:
            return [{"dim": e.dim, "key": e.key, "policy": e.policy,
                     "action": e.action,
                     "rate": e.rate_mtok / 1000.0,
                     "burst": e.burst_mtok / 1000.0,
                     "level": e.bucket.level_mtok / 1000.0,
                     "origin": e.origin, "ttl": e.ttl}
                    for e in self._table.values()]

    def status(self) -> dict:
        with self.lock:
            return {"enabled": ON, "seq": self.seq,
                    "keys": len(self._table),
                    "policies": len(self.policies),
                    "ticks": self.ticks,
                    "tables_installed_total": self.tables_installed,
                    "gossip_merges_total": self.gossip_merges,
                    "policed_total": sum(self.policed.values())}

    def policed_by_node(self) -> dict:
        """The per-node `policed` attribution merged into
        GET /analytics: {action: count} for this node."""
        with self.lock:
            out: Dict[str, int] = {}
            for (_lb, action, _dim), v in self.policed.items():
                out[action] = out.get(action, 0) + v
            return out

    def shed_receipt(self) -> str:
        """Order-independent hash over the policed key set — the storm
        row's determinism receipt (same capture + same seed => same
        receipt)."""
        with self.lock:
            keys = sorted(f"{l}|{a}|{d}|{n}"
                          for (l, a, d), n in self.policed.items())
        return hashlib.sha256("\n".join(keys).encode()).hexdigest()[:16]

    def reset(self) -> None:
        """Test/bench hook: drop table + counters, keep policies."""
        with self.lock:
            self._table.clear()
            self._deficit = {self._tenant_name(p): 0.0
                             for p in self.policies.values()}
            self.policed.clear()
            self.tables_installed = 0
            self.gossip_merges = 0
            self.ticks = 0
            self.seq = 0
            self._last_tick = 0.0


# ---------------- the module-level default engine ----------------

_default = PolicingEngine()


def default() -> PolicingEngine:
    return _default


def enabled() -> bool:
    return ON


def configure(on: Optional[bool] = None) -> None:
    """Runtime knob (bench/test hook; production uses the env). Pushes
    the on/off state into the C lanes so both planes flip together."""
    global ON
    if on is not None:
        ON = bool(on)
        try:
            from ..net import vtl
            vtl.police_set_enabled(ON)
        except Exception:
            pass  # py provider / pre-policing .so: python sites only


def push_native_knob() -> None:
    """Push the current on/off state into the C atomic — called by
    every owner of a C lane table at start (the trace_set_sample
    idiom)."""
    try:
        from ..net import vtl
        vtl.police_set_enabled(ON)
    except Exception:
        pass


def check(dim: str, key: str, lb: str = "", trace_id: int = 0) -> str:
    if not ON:
        return "admit"  # the one-branch knob-off contract
    return _default.check(dim, key, lb=lb, trace_id=trace_id)


def quarantined(qname: str, lb: str = "", trace_id: int = 0) -> bool:
    if not ON:
        return False
    return _default.quarantined(qname, lb=lb, trace_id=trace_id)


def overload_spare(ip: str, lb: str = "") -> bool:
    if not ON:
        return False
    return _default.overload_spare(ip, lb=lb)


def maybe_tick() -> bool:
    if not ON:
        return False
    return _default.maybe_tick()


def tick() -> None:
    _default.tick()


def gossip_summary() -> dict:
    return _default.gossip_summary()


def ingest_peer_tables(peers: dict) -> int:
    return _default.ingest_peer_tables(peers)


def account_native(lb: str, action: str, dim: str, n: int) -> None:
    _default.account_native(lb, action, dim, n)


def status() -> dict:
    return _default.status()


def reset() -> None:
    _default.reset()
