"""Guardian — the sketch-driven admission & policing plane.

PR 14 gave every plane a NAME for its heavy hitters (10s Space-Saving
windows); PR 16 can replay a recorded crowd at 2x. This package is what
finally ACTS on both: operator-declared policies compile the top-K
tables into O(1) enforcement state — per-key token buckets consulted at
accept time in the C lanes (POLICE_REC ABI), mirrored on the python
accept path, biased into the AIMD overload shed order (weighted-fair,
deficit-round-robin over tenant weights), and answered as REFUSED for
quarantined qnames in the DNS server.

Call sites import the engine module (`from ..policing import engine as
policing`) — the module-level default engine serves the hot paths; the
class exists so tests can run N independent nodes in one process.
"""
from . import engine  # noqa: F401
