"""WebSocksProxyAgent — local SOCKS5/HTTP-CONNECT/PAC endpoint that
tunnels selected domains through WebSocks servers.

Parity: vproxyx/WebSocksProxyAgent.java:398 + the connector provider
websocks/WebSocksProxyAgentConnectorProvider.java:826, PAC server
pac/PACHandler.java:145, per-domain rules DomainChecker.java:

* local SOCKS5 front (no auth) and HTTP CONNECT front;
* DomainChecker decides proxy-vs-direct per target (suffix rules,
  ":port" suffixes, regex patterns, wildcard);
* a weighted healthy server list (health checks ride ServerGroup's
  checker exactly like any backend group);
* transport per server: plain TCP or KCP-streamed mux (the agent's
  "UDP over KCP" option); the WebSocks handshake (upgrade + auth +
  10-byte frame + socks5) runs over either;
* plain-TCP tunnels hand both fds to the native splice pump after the
  handshake; KCP tunnels bridge through the stream mux;
* PAC endpoint serving the auto-config script.
"""
from __future__ import annotations

import re
import socket
import struct
from typing import Callable, Optional

from ..components.elgroup import EventLoopGroup
from ..components.servergroup import HealthCheckConfig, ServerGroup
from ..lib.vserver import HttpServer
from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..net.kcp import KcpConn
from ..net.splice import detach_when_drained
from ..net.streamed import Stream, StreamedSession, StreamHandler
from ..net.udp import UdpSock
from ..processors.http1 import HeadParser
from ..utils.log import Logger
from . import common
from .server import KCP_CONV

_log = Logger("websocks-agent")


class DomainChecker:
    """Which targets go through the proxy (DomainChecker.java).

    rules: strings —
      "example.com"      suffix match (and exact)
      ":443"             port suffix rule
      "/regex/"          regex on the hostname
      "*"                everything
    """

    def __init__(self, rules=()):
        self.suffixes: list[str] = []
        self.ports: set[int] = set()
        self.patterns: list[re.Pattern] = []
        self.match_all = False
        for r in rules:
            self.add(r)

    def add(self, rule: str) -> None:
        if rule == "*":
            self.match_all = True
        elif rule.startswith(":"):
            self.ports.add(int(rule[1:]))
        elif len(rule) > 1 and rule.startswith("/") and rule.endswith("/"):
            self.patterns.append(re.compile(rule[1:-1]))
        else:
            self.suffixes.append(rule.lstrip("."))

    def needs_proxy(self, host: str, port: int) -> bool:
        if self.match_all or port in self.ports:
            return True
        for s in self.suffixes:
            if host == s or host.endswith("." + s):
                return True
        return any(p.search(host) for p in self.patterns)


class WebSocksServerRef:
    def __init__(self, host: str, port: int, user: str, password: str,
                 kcp: bool = False, weight: int = 10, tls: bool = False,
                 tls_verify: bool = True,
                 tls_sni: Optional[str] = None):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.kcp = kcp
        self.weight = weight
        self.tls = tls            # wss:// — TLS to the websocks server
        self.tls_verify = tls_verify
        self.tls_sni = tls_sni or host
        self._ctx = None

    def client_ctx(self):
        """One SSLContext per server ref: creating one per tunnel would
        re-load the CA bundle on the loop thread for every connection
        and discard TLS session-resumption state."""
        if self._ctx is None:
            from ..net.tls import client_context
            self._ctx = client_context(verify=self.tls_verify)
        return self._ctx


class _KcpTransport:
    """One shared KCP-streamed session per server; streams carry the
    individual tunnels (round-1 streamed mux reused as agent transport)."""

    def __init__(self, loop: SelectorEventLoop, ref: WebSocksServerRef):
        self.loop = loop
        self.ref = ref
        self.sess: Optional[StreamedSession] = None
        self.sock: Optional[UdpSock] = None

    def stream(self) -> Optional[Stream]:
        if self.sess is None or self.sess.broken:
            self._dial()
        if self.sess is None or self.sess.broken:
            return None
        return self.sess.open_stream()

    def _dial(self) -> None:
        if self.sock is not None:
            self.sock.close()
        try:
            self.sock = UdpSock(self.loop)
        except OSError:
            self.sock = None
            return
        kcp = KcpConn(self.loop, KCP_CONV,
                      lambda d: self.sock.send(d, self.ref.host,
                                               self.ref.port))
        self.sock.on_packet = lambda d, ip, p: kcp.feed(d)
        self.sess = StreamedSession(self.loop, kcp, is_client=True,
                                    on_broken=lambda: None)

    def close(self) -> None:
        if self.sess is not None:
            self.sess.close()
        if self.sock is not None:
            self.sock.close()


class WebSocksProxyAgent:
    def __init__(self, elg: EventLoopGroup, servers: list,
                 proxy_rules=("*",), socks_port: int = 0,
                 http_connect_port: Optional[int] = None,
                 pac_port: Optional[int] = None,
                 hc: Optional[HealthCheckConfig] = None):
        self.elg = elg
        self.loop = elg.next()
        self.checker = DomainChecker(proxy_rules)
        self.refs: dict[str, WebSocksServerRef] = {}
        # health checks ride the standard ServerGroup machinery
        self.group = ServerGroup("websocks-servers", elg,
                                 hc or HealthCheckConfig(), "wrr")
        for i, ref in enumerate(servers):
            self.refs[f"{ref.host}:{ref.port}"] = ref
            self.group.add(f"s{i}", ref.host, ref.port, weight=ref.weight)
        self._kcp: dict[str, _KcpTransport] = {}

        self.socks = self.loop.call_sync(lambda: ServerSock(
            self.loop, "127.0.0.1", socks_port, self._on_socks))
        self.socks_port = self.socks.port
        self.http_connect: Optional[ServerSock] = None
        self.http_connect_port = None
        if http_connect_port is not None:
            self.http_connect = self.loop.call_sync(lambda: ServerSock(
                self.loop, "127.0.0.1", http_connect_port, self._on_connect))
            self.http_connect_port = self.http_connect.port
        self.pac: Optional[HttpServer] = None
        self.pac_port = None
        if pac_port is not None:
            self.pac = HttpServer(self.loop)
            self.pac.get("/pac", self._pac)
            self.pac.get("/proxy.pac", self._pac)
            self.pac.listen(pac_port, "127.0.0.1")
            self.pac_port = self.pac.port

    def close(self) -> None:
        self.loop.run_on_loop(self.socks.close)
        if self.http_connect is not None:
            self.loop.run_on_loop(self.http_connect.close)
        if self.pac is not None:
            self.pac.close()
        for t in self._kcp.values():
            t.close()
        self.group.close()

    # ------------------------------------------------------------ fronts

    def _on_socks(self, fd: int, ip: str, port: int) -> None:
        _SocksFront(self, Connection(self.loop, fd, (ip, port)))

    def _on_connect(self, fd: int, ip: str, port: int) -> None:
        _ConnectFront(self, Connection(self.loop, fd, (ip, port)))

    def _pac(self, rctx) -> None:
        js = ("function FindProxyForURL(url, host) {\n"
              f'  return "SOCKS5 127.0.0.1:{self.socks_port}; '
              f'SOCKS 127.0.0.1:{self.socks_port}";\n}}\n')
        rctx.resp.header("content-type",
                         "application/x-ns-proxy-autoconfig").end(js.encode())

    # ------------------------------------------------------ tunnel setup

    def pick_server(self) -> Optional[WebSocksServerRef]:
        c = self.group.next(b"\x7f\x00\x00\x01")
        if c is None:
            return None
        return self.refs.get(f"{c.ip}:{c.port}")

    def open_tunnel(self, host: str, port: int,
                    cb: Callable[[Optional["_Tunnel"]], None]) -> None:
        """Handshake a tunnel to host:port through a healthy server (or
        direct if the rules say so); cb(tunnel|None) on the agent loop."""
        if not self.checker.needs_proxy(host, port):
            _DirectTunnel.open(self, host, port, cb)
            return
        ref = self.pick_server()
        if ref is None:
            cb(None)
            return
        if ref.kcp:
            t = self._kcp.get(f"{ref.host}:{ref.port}")
            if t is None:
                t = _KcpTransport(self.loop, ref)
                self._kcp[f"{ref.host}:{ref.port}"] = t
            s = t.stream()
            if s is None:
                cb(None)
                return
            _StreamTunnel(self, ref, s, host, port, cb)
        else:
            _TcpTunnel.open(self, ref, host, port, cb)


class _Tunnel:
    """Established path to the target: write()/close() + a data/closed
    sink set by the front; pump_fd() is non-None when the tunnel is a
    plain fd ready for the native pump. Target bytes arriving before
    the sink is attached (e.g. a server that talks first, racing the
    front's reply flush) are buffered, never dropped."""

    def __init__(self):
        self._pending: list[bytes] = []
        self._sink: Optional[Callable[[bytes], None]] = None
        self._closed_cb: Optional[Callable[[], None]] = None
        self._dead = False

    # transports deliver through these
    def _emit(self, data: bytes) -> None:
        if self._sink is not None:
            self._sink(data)
        else:
            self._pending.append(data)

    def _emit_closed(self) -> None:
        self._dead = True
        if self._closed_cb is not None:
            self._closed_cb()

    # fronts consume through these
    def set_sink(self, on_data: Callable[[bytes], None],
                 on_closed: Callable[[], None]) -> None:
        self._sink = on_data
        self._closed_cb = on_closed
        pending, self._pending = self._pending, []
        for d in pending:
            on_data(d)
        if self._dead:
            on_closed()

    def take_pending(self) -> bytes:
        """Drain buffered target bytes (pump-handover path: the caller
        writes them to the front before detaching it)."""
        out = b"".join(self._pending)
        self._pending.clear()
        return out

    def write(self, data: bytes) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def pump_fd(self) -> Optional[int]:
        return None


class _DirectTunnel(_Tunnel):
    @staticmethod
    def open(agent: WebSocksProxyAgent, host: str, port: int, cb) -> None:
        from ..utils.ip import is_ip_literal

        def connect(ip: Optional[str]) -> None:
            if ip is None:
                cb(None)
                return
            try:
                conn = Connection.connect(agent.loop, ip, port)
            except OSError:
                cb(None)
                return
            t = _DirectTunnel()
            t.conn = conn

            class H(Handler):
                def on_connected(self, c):
                    cb(t)

                def on_data(self, c, data):
                    t._emit(data)

                def on_closed(self, c, err):
                    t._emit_closed()

                def on_eof(self, c):
                    t._emit_closed()

            conn.set_handler(H())

        if is_ip_literal(host):
            connect(host)
        else:
            def work():
                try:
                    ip = socket.getaddrinfo(
                        host, None, type=socket.SOCK_STREAM)[0][4][0]
                except OSError:
                    ip = None
                agent.loop.run_on_loop(lambda: connect(ip))
            import threading
            threading.Thread(target=work, daemon=True).start()

    def write(self, data: bytes) -> None:
        self.conn.write(data)

    def close(self) -> None:
        self.conn.close()

    def pump_fd(self) -> Optional[int]:
        if self.conn.closed or self.conn.detached or self.conn.out:
            return None
        return self.conn.detach()


def _socks5_connect_req(host: str, port: int) -> bytes:
    """Greeting (no-auth) + CONNECT in one packet (combined packets are
    explicitly allowed by the spec)."""
    try:
        a4 = socket.inet_pton(socket.AF_INET, host)
        addr = b"\x01" + a4
    except OSError:
        try:
            a6 = socket.inet_pton(socket.AF_INET6, host)
            addr = b"\x04" + a6
        except OSError:
            hb = host.encode("idna" if any(ord(ch) > 127 for ch in host)
                             else "latin-1")
            addr = b"\x03" + bytes([len(hb)]) + hb
    return (b"\x05\x01\x00" +
            b"\x05\x01\x00" + addr + struct.pack(">H", port))


class _HandshakeMachine:
    """Client-side WebSocks handshake over any duplex. Sends the
    upgrade at construction; on 101 sends the 10-byte frame + the
    combined socks5 greeting/CONNECT (combined packets are explicitly
    allowed AFTER the upgrade round trip); then parses the server's
    10-byte frame, method choice and reply. Calls done(ok, leftover)."""

    ST_HTTP, ST_FRAME10, ST_METHOD, ST_REPLY, ST_DONE = range(5)

    def __init__(self, ref: WebSocksServerRef,
                 write: Callable[[bytes], None], socks_payload: bytes, done):
        self.write = write
        self.done = done
        self.payload = socks_payload
        self.buf = bytearray()
        self.state = self.ST_HTTP
        self.write(common.upgrade_request(ref.host, ref.user, ref.password))

    def feed(self, data: bytes) -> None:
        self.buf += data
        if self.state == self.ST_HTTP:
            i = self.buf.find(b"\r\n\r\n")
            if i < 0:
                return
            head = bytes(self.buf[:i])
            del self.buf[: i + 4]
            if b" 101 " not in head.split(b"\r\n", 1)[0]:
                self._fail()
                return
            self.write(common.MAX_PAYLOAD_FRAME + self.payload)
            self.state = self.ST_FRAME10
        if self.state == self.ST_FRAME10:
            while len(self.buf) >= 2 and self.buf[0] == 0x8A:
                del self.buf[:2]  # unsolicited PONG
            if len(self.buf) < 10:
                return
            del self.buf[:10]
            self.state = self.ST_METHOD
        if self.state == self.ST_METHOD:
            if len(self.buf) < 2:
                return
            if self.buf[0] != 5 or self.buf[1] != 0:
                self._fail()
                return
            del self.buf[:2]
            self.state = self.ST_REPLY
        if self.state == self.ST_REPLY:
            if len(self.buf) < 4:
                return
            if self.buf[1] != 0:
                self._fail()
                return
            atyp = self.buf[3]
            need = 4 + (4 if atyp == 1 else 16 if atyp == 4 else
                        1 + self.buf[4] if len(self.buf) > 4 else 256) + 2
            if len(self.buf) < need:
                return
            del self.buf[:need]
            self.state = self.ST_DONE
            self.done(True, bytes(self.buf))

    def _fail(self) -> None:
        self.state = self.ST_DONE
        self.done(False, b"")


class _TcpTunnel(_Tunnel):
    """Plain-TCP or TLS (wss) transport to the websocks server. In TLS
    mode `self.conn` is the TlsSocket (same write/close surface) and the
    tunnel never upgrades to the native pump — the TLS state lives in
    Python (WebSocksProxyAgentConnectorProvider.java:826's SSL branch).
    """

    @staticmethod
    def open(agent: WebSocksProxyAgent, ref: WebSocksServerRef,
             host: str, port: int, cb) -> None:
        try:
            raw = Connection.connect(agent.loop, ref.host, ref.port)
        except OSError:
            cb(None)
            return
        if ref.tls:
            from ..net.tls import TlsSocket
            conn = TlsSocket(raw, ref.client_ctx(),
                             server_side=False, server_hostname=ref.tls_sni)
        else:
            conn = raw
        t = _TcpTunnel()
        t.conn = conn
        t._tls = ref.tls
        hs_req = _socks5_connect_req(host, port)

        class H(Handler):
            def __init__(self):
                self.hs: Optional[_HandshakeMachine] = None
                self.notified = False  # cb fired (tunnel or None)

            def on_connected(self, c):
                self.hs = _HandshakeMachine(ref, c.write, hs_req,
                                            self._done)

            def _done(self, ok: bool, leftover: bytes) -> None:
                # hs cleared FIRST: c.close() below re-enters via
                # on_closed -> _dead, which must not re-run the machine
                self.hs = None
                if self.notified:
                    return
                self.notified = True
                if not ok:
                    c = t.conn
                    t.conn = None
                    if c is not None:
                        c.close()
                    cb(None)
                    return
                if leftover:
                    t._emit(leftover)
                cb(t)

            def on_data(self, c, data):
                if self.hs is not None:
                    self.hs.feed(data)
                else:
                    t._emit(data)

            def on_eof(self, c):
                self._dead()

            def on_closed(self, c, err):
                self._dead()

            def _dead(self):
                if self.hs is not None:
                    hs, self.hs = self.hs, None
                    hs.done(False, b"")
                elif not self.notified:
                    # died before the handshake even started (TCP
                    # refusal after connect(), TLS handshake/verify
                    # failure) — the front must still hear about it
                    self.notified = True
                    cb(None)
                else:
                    t._emit_closed()

        conn.set_handler(H())

    def write(self, data: bytes) -> None:
        if self.conn is not None:
            self.conn.write(data)

    def close(self) -> None:
        if self.conn is not None:
            self.conn.close()

    def pump_fd(self) -> Optional[int]:
        if getattr(self, "_tls", False):
            return None  # TLS state is Python-resident: no pump handover
        if self.conn is None or self.conn.closed or self.conn.detached \
                or self.conn.out:
            return None
        return self.conn.detach()


class _StreamTunnel(_Tunnel):
    def __init__(self, agent, ref, stream: Stream, host, port, cb):
        super().__init__()
        self.stream = stream
        self.cb = cb
        self.hs: Optional[_HandshakeMachine] = None
        tun = self

        class SH(StreamHandler):
            def on_data(self, s, data):
                if tun.hs is not None:
                    tun.hs.feed(data)
                else:
                    tun._emit(data)

            def on_eof(self, s):
                self.on_closed(s)

            def on_closed(self, s):
                if tun.hs is not None:
                    hs, tun.hs = tun.hs, None
                    hs.done(False, b"")
                else:
                    tun._emit_closed()

        stream.set_handler(SH())
        # client-opened streams are writable immediately (optimistic SYN)
        self.hs = _HandshakeMachine(ref, stream.write,
                                    _socks5_connect_req(host, port),
                                    self._done)

    def _done(self, ok: bool, leftover: bytes) -> None:
        self.hs = None
        cb, self.cb = self.cb, None
        if not ok:
            self.stream.close()
            if cb:
                cb(None)
            return
        if leftover:
            self._emit(leftover)
        if cb:
            cb(self)

    def write(self, data: bytes) -> None:
        self.stream.write(data)

    def close(self) -> None:
        self.stream.close()


class _SocksFront(Handler):
    """Local SOCKS5 server (no auth) in front of open_tunnel."""

    ST_GREET, ST_REQ, ST_TUNNEL = range(3)

    def __init__(self, agent: WebSocksProxyAgent, conn: Connection):
        self.agent = agent
        self.conn = conn
        self.buf = bytearray()
        self.state = self.ST_GREET
        self.tunnel: Optional[_Tunnel] = None
        conn.set_handler(self)

    def on_data(self, conn, data):
        self.buf += data
        if self.state == self.ST_GREET and len(self.buf) >= 2:
            n = self.buf[1]
            if self.buf[0] != 5 or len(self.buf) < 2 + n:
                if self.buf[0] != 5:
                    conn.close()
                return
            methods = self.buf[2:2 + n]
            del self.buf[:2 + n]
            if 0 not in methods:
                conn.write(b"\x05\xff")
                conn.close()
                return
            conn.write(b"\x05\x00")
            self.state = self.ST_REQ
        if self.state == self.ST_REQ and len(self.buf) >= 4:
            ver, cmd, _rsv, atyp = self.buf[:4]
            if atyp == 1:
                need = 10
            elif atyp == 4:
                need = 22
            elif atyp == 3:
                if len(self.buf) < 5:
                    return
                need = 7 + self.buf[4]
            else:
                conn.close()
                return
            if len(self.buf) < need:
                return
            if cmd != 1:
                conn.write(b"\x05\x07\x00\x01" + b"\x00" * 6)
                conn.close()
                return
            if atyp == 3:
                host = bytes(self.buf[5:5 + self.buf[4]]).decode("latin-1")
                port = struct.unpack(">H", self.buf[need - 2:need])[0]
            else:
                alen = 4 if atyp == 1 else 16
                host = socket.inet_ntop(
                    socket.AF_INET if alen == 4 else socket.AF_INET6,
                    bytes(self.buf[4:4 + alen]))
                port = struct.unpack(">H", self.buf[need - 2:need])[0]
            del self.buf[:need]
            self.state = self.ST_TUNNEL
            conn.pause_reading()
            self.agent.open_tunnel(host, port, self._up)
        elif self.state == self.ST_TUNNEL and self.tunnel is not None:
            self.tunnel.write(bytes(self.buf))
            self.buf.clear()

    def _up(self, tunnel: Optional[_Tunnel]) -> None:
        if tunnel is None:
            if not self.conn.closed:
                self.conn.write(b"\x05\x05\x00\x01" + b"\x00" * 6)
                self.conn.close_graceful()
            return
        if self.conn.closed:
            tunnel.close()
            return
        self.tunnel = tunnel
        self.conn.write(b"\x05\x00\x00\x01" + b"\x00" * 6)
        early = bytes(self.buf)
        self.buf.clear()
        if early:
            tunnel.write(early)
        # both sides plain fds -> native pump
        pfd = tunnel.pump_fd()
        if pfd is not None:
            loop = self.agent.loop
            self.conn.write(tunnel.take_pending())

            def go(ffd: int) -> None:
                from ..net import vtl
                if not vtl.pump_sets_nodelay():  # pre-r6 .so only
                    vtl.set_nodelay(ffd)
                    vtl.set_nodelay(pfd)
                loop.pump(ffd, pfd, 65536, None)

            detach_when_drained(self.conn, go)
            return
        # stream tunnel: python bridge
        front = self.conn
        tunnel.set_sink(front.write, front.close)
        front.resume_reading()

    def on_eof(self, conn):
        if self.tunnel is not None:
            self.tunnel.close()
        conn.close()

    def on_closed(self, conn, err):
        if self.tunnel is not None:
            self.tunnel.close()


class _ConnectFront(Handler):
    """HTTP CONNECT front (the agent's http-connect gateway)."""

    def __init__(self, agent: WebSocksProxyAgent, conn: Connection):
        self.agent = agent
        self.conn = conn
        self.parser = HeadParser()
        self.tunnel: Optional[_Tunnel] = None
        self.established = False
        conn.set_handler(self)

    def on_data(self, conn, data):
        if self.established and self.tunnel is not None:
            self.tunnel.write(data)
            return
        self.parser.feed(data)
        if self.parser.error:
            conn.close()
            return
        if not self.parser.done:
            return
        if self.parser.method != "CONNECT":
            conn.write(b"HTTP/1.1 405 Method Not Allowed\r\n"
                       b"content-length: 0\r\n\r\n")
            conn.close_graceful()
            return
        hostport = self.parser.uri
        host, _, p = hostport.rpartition(":")
        try:
            port = int(p)
        except ValueError:
            conn.close()
            return
        host = host.strip("[]")
        conn.pause_reading()
        self.early = bytes(self.parser.buf)[self.parser.head_len:]
        self.agent.open_tunnel(host, port, self._up)

    def _up(self, tunnel: Optional[_Tunnel]) -> None:
        if tunnel is None:
            if not self.conn.closed:
                self.conn.write(b"HTTP/1.1 502 Bad Gateway\r\n"
                                b"content-length: 0\r\n\r\n")
                self.conn.close_graceful()
            return
        if self.conn.closed:
            tunnel.close()
            return
        self.tunnel = tunnel
        self.established = True
        self.conn.write(b"HTTP/1.1 200 Connection Established\r\n\r\n")
        if self.early:
            tunnel.write(self.early)
        pfd = tunnel.pump_fd()
        if pfd is not None:
            loop = self.agent.loop
            self.conn.write(tunnel.take_pending())

            def go(ffd: int) -> None:
                from ..net import vtl
                if not vtl.pump_sets_nodelay():  # pre-r6 .so only
                    vtl.set_nodelay(ffd)
                    vtl.set_nodelay(pfd)
                loop.pump(ffd, pfd, 65536, None)

            detach_when_drained(self.conn, go)
            return
        front = self.conn
        tunnel.set_sink(front.write, front.close)
        front.resume_reading()

    def on_eof(self, conn):
        if self.tunnel is not None:
            self.tunnel.close()
        conn.close()

    def on_closed(self, conn, err):
        if self.tunnel is not None:
            self.tunnel.close()
