"""Shadowsocks server protocol (aes-256-cfb) for the WebSocks server.

Parity: vproxyx/websocks/ss/SSProtocolHandler.java:196 — the reference's
websocks server can speak plain shadowsocks so stock ss clients use it
as an exit. Wire format (shadowsocks AEAD-less stream ciphers):

  client -> server:  IV(16) || AES-256-CFB( atyp(1) addr port(2) data... )
  server -> client:  IV(16) || AES-256-CFB( data... )

atyp/addr as in SOCKS5 (1=IPv4(4B), 3=domain(len||bytes), 4=IPv6(16B)).
Key = EVP_BytesToKey(MD5, password) like the original ss tools, so any
stock client with method aes-256-cfb interoperates.

The cipher is a stream: each direction keeps ONE incremental CFB
context for the connection's lifetime.
"""
from __future__ import annotations

import hashlib
import os
import struct
from typing import Callable, Optional

from ..net import vtl
from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..utils.log import Logger

_log = Logger("ss")


def evp_bytes_to_key(password: str, key_len: int = 32) -> bytes:
    """OpenSSL EVP_BytesToKey with MD5, no salt — the shadowsocks KDF."""
    out = b""
    prev = b""
    pw = password.encode()
    while len(out) < key_len:
        prev = hashlib.md5(prev + pw).digest()
        out += prev
    return out[:key_len]


class CfbStream:
    """Incremental AES-256-CFB en/decryptor (one per direction)."""

    def __init__(self, key: bytes, iv: bytes, encrypt: bool):
        from cryptography.hazmat.primitives.ciphers import (Cipher,
                                                            algorithms,
                                                            modes)
        c = Cipher(algorithms.AES(key), modes.CFB(iv))
        self._ctx = c.encryptor() if encrypt else c.decryptor()

    def update(self, data: bytes) -> bytes:
        return self._ctx.update(data)


class _SSSession(Handler):
    """One client connection: IV -> address -> connect -> relay."""

    def __init__(self, server: "SSServer", loop, conn: Connection):
        self.server = server
        self.loop = loop
        self.conn = conn
        self.buf = bytearray()
        self.dec: Optional[CfbStream] = None
        self.enc: Optional[CfbStream] = None
        self.back: Optional[Connection] = None
        self.addr_done = False  # address parsed, back connect in flight
        self.back_up = False
        self.early = bytearray()  # decrypted payload before back is up
        self.dead = False
        conn.set_handler(self)

    # ------------------------------------------------------ front events

    def on_data(self, c: Connection, data: bytes) -> None:
        if self.dec is None:
            self.buf.extend(data)
            if len(self.buf) < 16:
                return
            iv, rest = bytes(self.buf[:16]), bytes(self.buf[16:])
            self.buf = bytearray()
            self.dec = CfbStream(self.server.key, iv, encrypt=False)
            data = rest
            if not data:
                return
        plain = self.dec.update(data)
        if not self.addr_done:
            self.buf.extend(plain)
            self._try_addr()
        elif not self.back_up:
            self.early.extend(plain)
        else:
            self.back.write(plain)
            if self.back.out:  # backpressure: pause the faster side
                self.conn.pause_reading()

    def on_drained(self, c: Connection) -> None:
        # client out-buffer flushed: resume the backend
        if self.back_up and not self.dead:
            self.back.resume_reading()

    def on_eof(self, c: Connection) -> None:
        self._close()

    def on_closed(self, c: Connection, err: int) -> None:
        self._close()

    def _close(self) -> None:
        if self.dead:
            return
        self.dead = True
        self.conn.close()
        if self.back is not None:
            self.back.close()
        self.server.sessions -= 1

    # --------------------------------------------------- address + relay

    def _try_addr(self) -> None:
        b = self.buf
        if len(b) < 1:
            return
        atyp = b[0]
        if atyp == 1:
            need = 1 + 4 + 2
            if len(b) < need:
                return
            host = ".".join(str(x) for x in b[1:5])
        elif atyp == 4:
            need = 1 + 16 + 2
            if len(b) < need:
                return
            import socket as s
            host = s.inet_ntop(s.AF_INET6, bytes(b[1:17]))
        elif atyp == 3:
            if len(b) < 2:
                return
            dl = b[1]
            need = 2 + dl + 2
            if len(b) < need:
                return
            host = bytes(b[2:2 + dl]).decode("ascii", "replace")
        else:
            _log.alert(f"ss: bad atyp {atyp}")
            self._close()
            return
        (port,) = struct.unpack(">H", b[need - 2:need])
        self.early.extend(b[need:])
        self.buf = bytearray()
        self.addr_done = True
        self.server.resolve(self.loop, host, lambda ip:
                            self._connect(ip, port))

    def _connect(self, ip: Optional[str], port: int) -> None:
        if self.dead:
            return
        if ip is None:
            self._close()
            return
        try:
            back = Connection.connect(self.loop, ip, port)
        except OSError:
            self._close()
            return
        self.back = back
        sess = self

        class Back(Handler):
            def on_connected(self, bc: Connection) -> None:
                sess.back_up = True
                # server->client stream starts with our IV
                iv = os.urandom(16)
                sess.enc = CfbStream(sess.server.key, iv, encrypt=True)
                sess.conn.write(iv)
                if sess.early:
                    bc.write(bytes(sess.early))
                    sess.early = bytearray()

            def on_data(self, bc: Connection, data: bytes) -> None:
                if sess.enc is not None and not sess.dead:
                    sess.conn.write(sess.enc.update(data))
                    if sess.conn.out:  # backpressure on a slow client
                        bc.pause_reading()

            def on_drained(self, bc: Connection) -> None:
                if not sess.dead:
                    sess.conn.resume_reading()

            def on_eof(self, bc: Connection) -> None:
                sess._close()

            def on_closed(self, bc: Connection, err: int) -> None:
                sess._close()

        back.set_handler(Back())


def _default_resolve(loop, host: str, cb: Callable[[Optional[str]], None]):
    from .server import _default_resolve as d
    d(loop, host, cb)


class SSServer:
    """Plain shadowsocks exit speaking aes-256-cfb."""

    def __init__(self, alias: str, loop: SelectorEventLoop, bind_ip: str,
                 bind_port: int, password: str, resolve=None):
        self.alias = alias
        self.loop = loop
        self.key = evp_bytes_to_key(password)
        self.resolve = resolve or _default_resolve
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.sessions = 0
        self.accepted = 0
        self.sock: Optional[ServerSock] = None

    def start(self) -> None:
        self.sock = self.loop.call_sync(lambda: ServerSock(
            self.loop, self.bind_ip, self.bind_port, self._on_accept))
        if self.bind_port == 0:
            self.bind_port = self.sock.port

    def stop(self) -> None:
        if self.sock is not None:
            self.loop.run_on_loop(self.sock.close)
            self.sock = None

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        self.accepted += 1
        self.sessions += 1
        try:
            conn = Connection(self.loop, fd, (ip, port))
        except OSError:
            self.sessions -= 1
            vtl.close(fd)
            return
        _SSSession(self, self.loop, conn)
