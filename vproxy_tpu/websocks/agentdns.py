"""AgentDNSServer — the agent's caching DNS front with fake-IP answers.

Parity: vproxyx/websocks/AgentDNSServer.java:396. The agent runs a
small UDP DNS server the host OS points at. For an A query whose domain
the proxy rules claim (DomainChecker.needs_proxy), it leases a fake IP
from the DomainBinder and answers with it — the OS then connects to the
fake IP, landing on the DirectRelayServer, which recovers the domain
and tunnels through the websocks server. Everything else resolves
upstream (system resolver in a worker thread, like the agent's direct
path) and is cached with a TTL.

AAAA queries for proxied domains answer empty-NOERROR so dual-stack
clients fall back to the fake v4 address.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from ..dns import packet as P
from ..net.eventloop import SelectorEventLoop
from ..net.udp import UdpSock
from ..utils.log import Logger

_log = Logger("agent-dns")

CACHE_TTL = 60.0
FAKE_TTL = 10  # answer TTL for fake-IP leases (seconds, kept short)


class AgentDNSServer:
    def __init__(self, alias: str, loop: SelectorEventLoop, bind_ip: str,
                 bind_port: int, checker, binder, resolve=None):
        """checker: DomainChecker (agent.checker); binder: DomainBinder
        shared with the DirectRelayServer; resolve(name) -> list[str]
        override for tests (runs on a worker thread)."""
        self.alias = alias
        self.loop = loop
        self.checker = checker
        self.binder = binder
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self._resolve = resolve or self._system_resolve
        self.sock: Optional[UdpSock] = None
        self.queries = 0
        self.fake_answers = 0
        self.upstream_answers = 0
        self._cache: dict = {}  # (name, qtype) -> (ips, expiry)
        # in-flight dedup: one resolver thread per name; concurrent
        # queries (OS resolvers retry aggressively) join the waiters
        self._inflight: dict = {}  # (name, qtype) -> [(req, ip, port)]

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.sock = UdpSock(self.loop, self.bind_ip, self.bind_port,
                            self._on_packet)
        if self.bind_port == 0:
            self.bind_port = self.sock.local[1]

    def stop(self) -> None:
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    # ------------------------------------------------------------ serving

    def _on_packet(self, data: bytes, ip: str, port: int) -> None:
        try:
            req = P.parse(data)
        except P.DNSFormatError:
            return
        if req.is_resp or not req.questions:
            return
        self.queries += 1
        q = req.questions[0]
        domain = q.qname.rstrip(".")
        if q.qtype not in (P.A, P.AAAA):
            self._respond(req, ip, port, [], rcode=4)  # NOTIMP
            return
        if self.checker.needs_proxy(domain, 0):
            self.fake_answers += 1
            answers = []
            if q.qtype == P.A:
                fake = self.binder.bind(domain)
                answers.append(P.Record(
                    name=q.qname, rtype=P.A, ttl=FAKE_TTL,
                    rdata=socket.inet_aton(fake)))
            # AAAA for a proxied domain: empty NOERROR -> v4 fallback
            self._respond(req, ip, port, answers)
            return
        key = (domain, q.qtype)
        ent = self._cache.get(key)
        if ent is not None and ent[1] > time.monotonic():
            self._answer_ips(req, ip, port, q, ent[0])
            return
        waiters = self._inflight.get(key)  # loop-confined state
        if waiters is not None:
            waiters.append((req, ip, port))
            return
        self._inflight[key] = [(req, ip, port)]

        def work() -> None:
            try:
                ips = self._resolve(domain, q.qtype)
            except OSError:
                ips = []

            def deliver() -> None:
                if ips:
                    if len(self._cache) > 4096:  # hard bound
                        now = time.monotonic()
                        for k in [k for k, v in self._cache.items()
                                  if v[1] < now]:
                            del self._cache[k]
                        # lookup storm of fresh entries: evict oldest
                        overflow = len(self._cache) - 4096
                        if overflow > 0:
                            for k in sorted(self._cache,
                                            key=lambda k: self._cache[k][1]
                                            )[:overflow]:
                                del self._cache[k]
                    self._cache[key] = (ips, time.monotonic() + CACHE_TTL)
                for w_req, w_ip, w_port in self._inflight.pop(key, []):
                    self._answer_ips(w_req, w_ip, w_port,
                                     w_req.questions[0], ips)

            if not self.loop.run_on_loop(deliver):
                pass  # loop gone: drop

        threading.Thread(target=work, daemon=True,
                         name="agent-dns-resolve").start()

    @staticmethod
    def _system_resolve(domain: str, qtype: int) -> list:
        fam = socket.AF_INET if qtype == P.A else socket.AF_INET6
        infos = socket.getaddrinfo(domain, None, fam,
                                   socket.SOCK_STREAM)
        return sorted({i[4][0] for i in infos})

    def _answer_ips(self, req, ip: str, port: int, q, ips: list) -> None:
        answers = []
        for a in ips:
            try:
                raw = socket.inet_pton(
                    socket.AF_INET if q.qtype == P.A else socket.AF_INET6, a)
            except OSError:
                continue
            answers.append(P.Record(name=q.qname, rtype=q.qtype,
                                    ttl=int(CACHE_TTL), rdata=raw))
        if answers:
            self.upstream_answers += 1
        # empty -> NOERROR/no-data, never NXDOMAIN: getaddrinfo cannot
        # distinguish them, and a spurious NXDOMAIN on (say) AAAA would
        # negative-cache the NAME and kill the sibling A lookup (RFC 2308)
        self._respond(req, ip, port, answers)

    def _respond(self, req, ip: str, port: int, answers: list,
                 rcode: int = 0) -> None:
        resp = P.Packet(id=req.id, is_resp=True, aa=False, rd=req.rd,
                        ra=True, rcode=rcode,
                        questions=list(req.questions), answers=answers)
        if self.sock is not None:
            self.sock.send(resp.encode(), ip, port)
