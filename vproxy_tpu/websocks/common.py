"""WebSocks wire protocol helpers (handshake, auth, frames).

The protocol (reference doc/websocks.md:1-160): WebSocket (RFC 6455)
upgrade carrying HTTP Basic auth with a minute-salted password hash,
then a fixed 10-byte "maximum payload length" binary-frame header from
each side, then plain SOCKS5 (RFC 1928) inside what the gateway
believes is one giant WebSocket frame. PONG (0x8a 0x00) keeps pooled
connections alive.

Server-side behavior parity: websocks/WebSocksProtocolHandler.java:540;
client side: WebSocksProxyAgentConnectorProvider.java:826.
"""
from __future__ import annotations

import base64
import hashlib
import os
import time
from typing import Optional

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# FIN + binary opcode, no mask, 64-bit extended payload = 2^63-1
# (doc/websocks.md "WebSocket Maximum Payload Length Frame": signed
# bytes {130, 127, 127, -1, -1, -1, -1, -1, -1, -1})
MAX_PAYLOAD_FRAME = bytes([130, 127, 127] + [255] * 7)

# FIN + PONG opcode, no mask, zero payload (doc/websocks.md "PONG")
PONG_FRAME = bytes([0x8A, 0x00])


def accept_key(client_key: str) -> str:
    """RFC 6455 §1.3 Sec-WebSocket-Accept."""
    d = hashlib.sha1((client_key + WS_GUID).encode()).digest()
    return base64.b64encode(d).decode()


def _minute_now_ms() -> int:
    return int(time.time() * 1000) // 60_000 * 60_000


def password_hash(password: str, minute_ms: int) -> str:
    """base64(sha256(base64(sha256(pass)) + str(minute))) per the spec."""
    inner = base64.b64encode(hashlib.sha256(password.encode()).digest())
    outer = hashlib.sha256(inner + str(minute_ms).encode()).digest()
    return base64.b64encode(outer).decode()


def auth_header(user: str, password: str,
                minute_ms: Optional[int] = None) -> str:
    m = _minute_now_ms() if minute_ms is None else minute_ms
    tok = base64.b64encode(
        f"{user}:{password_hash(password, m)}".encode()).decode()
    return f"Basic {tok}"


def validate_auth(header: Optional[str], users: dict) -> Optional[str]:
    """-> authenticated username, or None. Accepts the +-1 minute skew
    windows the spec requires of servers."""
    if not header or not header.startswith("Basic "):
        return None
    try:
        dec = base64.b64decode(header[6:]).decode()
        user, _, got = dec.partition(":")
    except Exception:
        return None
    pwd = users.get(user)
    if pwd is None or not got:
        return None
    now = _minute_now_ms()
    for m in (now - 60_000, now, now + 60_000):
        if password_hash(pwd, m) == got:
            return user
    return None


def upgrade_request(host: str, user: str, password: str,
                    client_key: Optional[str] = None) -> bytes:
    if client_key is None:
        # RFC 6455 4.1: a randomly selected 16-byte nonce per connection
        # (a constant key would fingerprint the tunnel)
        client_key = base64.b64encode(os.urandom(16)).decode()
    return (f"GET / HTTP/1.1\r\n"
            f"Upgrade: websocket\r\n"
            f"Connection: Upgrade\r\n"
            f"Host: {host}\r\n"
            f"Sec-WebSocket-Key: {client_key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n"
            f"Sec-WebSocket-Protocol: socks5\r\n"
            f"Authorization: {auth_header(user, password)}\r\n"
            f"\r\n").encode()


def upgrade_response(client_key: str) -> bytes:
    return (f"HTTP/1.1 101 Switching Protocols\r\n"
            f"Upgrade: websocket\r\n"
            f"Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept_key(client_key)}\r\n"
            f"Sec-WebSocket-Protocol: socks5\r\n"
            f"\r\n").encode()
