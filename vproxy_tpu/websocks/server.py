"""WebSocksProxyServer — SOCKS5 tunneled inside a WebSocket upgrade.

Parity: vproxyx/WebSocksProxyServer.java:347 + the protocol handler
websocks/WebSocksProtocolHandler.java:540 (behavior per doc/websocks.md):

* HTTP request that is a valid WebSocket upgrade with protocol
  "socks5" and a valid minute-salted Basic auth -> 101 + 10-byte
  max-payload frame exchange -> SOCKS5 handshake -> connect target ->
  relay. Plain-TCP fronts hand both fds to the native splice pump;
  KCP-streamed fronts relay through the stream mux.
* Any other HTTP request -> fake web page (WebRootPageProvider.java:216
  analog: an in-memory default page or a file root) or a redirect —
  the server looks like an ordinary website to probes.
* Unsolicited PONG frames are absorbed at any point before the
  max-payload frame.

Transports: TCP listener and/or a KCP-streamed UDP listener (the agent
side's "UDP-over-KCP" option) — the SAME protocol state machine drives
both via a small duplex adapter.
"""
from __future__ import annotations

import os
import struct
from typing import Callable, Optional

from ..net import vtl
from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..net.kcp import KcpConn
from ..net.splice import detach_when_drained, splice_connect
from ..net.streamed import Stream, StreamedSession, StreamHandler
from ..net.udp import UdpServer
from ..processors.http1 import HeadParser
from ..utils.log import Logger
from . import common

_log = Logger("websocks-server")

KCP_CONV = 0x77736B73  # "wsks"

DEFAULT_PAGE = (b"<!DOCTYPE html><html><head><title>Welcome</title></head>"
                b"<body><h1>Welcome to nginx!</h1><p>If you see this page, "
                b"the web server is successfully installed.</p></body></html>")


class PageProvider:
    """Serves the fake site (WebRootPageProvider analog). root: optional
    directory of static files; falls back to the built-in page for /."""

    def __init__(self, root: Optional[str] = None):
        self.root = root

    def get(self, path: str) -> Optional[tuple[bytes, str]]:
        if self.root is not None:
            root = os.path.abspath(self.root)
            p = os.path.normpath(os.path.join(root, path.lstrip("/")))
            if os.path.commonpath([root, p]) != root:
                return None
            if os.path.isdir(p):
                p = os.path.join(p, "index.html")
            if os.path.isfile(p):
                ctype = "text/html" if p.endswith((".html", ".htm")) \
                    else "application/octet-stream"
                with open(p, "rb") as f:
                    return f.read(), ctype
            return None
        if path in ("/", "/index.html"):
            return DEFAULT_PAGE, "text/html"
        return None


class _Duplex:
    """Uniform face over a TCP Connection or a KCP Stream for the
    handshake machine: write/close + data/closed callbacks. raw_fd is
    set only for plain TCP (enables the native pump handover)."""

    def __init__(self, write, close, conn: Optional[Connection] = None):
        self.write = write
        self.close = close
        self.conn = conn  # plain-TCP front, pump-capable


# SOCKS5 bits (RFC 1928; constants shared with components/socks5)
_VER = 5
_CMD_CONNECT = 1
_ATYP_V4, _ATYP_DOMAIN, _ATYP_V6 = 1, 3, 4


class _Session:
    """One front connection's protocol state machine."""

    ST_HTTP, ST_FRAME10, ST_GREET, ST_REQ, ST_TUNNEL, ST_DONE = range(6)

    def __init__(self, server: "WebSocksProxyServer", loop, dup: _Duplex):
        self.server = server
        self.loop = loop
        self.dup = dup
        self.buf = bytearray()
        self.state = self.ST_HTTP
        self.parser = HeadParser()
        self.back: Optional[Connection] = None

    # ------------------------------------------------------------- input

    def on_data(self, data: bytes) -> None:
        self.buf += data
        try:
            self._advance()
        except Exception:
            _log.error("websocks session error", exc=True)
            self.close()

    def _advance(self) -> None:
        while True:
            if self.state == self.ST_HTTP:
                if not self._http():
                    return
            elif self.state == self.ST_FRAME10:
                if not self._frame10():
                    return
            elif self.state == self.ST_GREET:
                if not self._greet():
                    return
            elif self.state == self.ST_REQ:
                if not self._request():
                    return
            elif self.state == self.ST_TUNNEL:
                # bytes that raced the backend connect: queue to backend
                if self.buf and self.back is not None:
                    self.back.write(bytes(self.buf))
                    self.buf.clear()
                return
            else:
                return

    def _http(self) -> bool:
        self.parser.feed(bytes(self.buf))
        self.buf.clear()
        if self.parser.error:
            self._page_status(400, b"bad request")
            return False
        if not self.parser.done:
            return False
        rest = bytes(self.parser.buf)[self.parser.head_len:]
        h = dict(self.parser.headers)  # keys already lowercased
        if (h.get("upgrade", "").lower() == "websocket"
                and "socks5" in h.get("sec-websocket-protocol", "")):
            user = common.validate_auth(h.get("authorization"),
                                        self.server.users)
            if user is None:
                self._page_status(401, b"unauthorized",
                                  [("WWW-Authenticate", "Basic")])
                return False
            self.user = user
            key = h.get("sec-websocket-key", "")
            self.dup.write(common.upgrade_response(key))
            self.state = self.ST_FRAME10
            self.buf += rest  # combined packets are allowed
            return True
        self._serve_page()
        return False

    def _frame10(self) -> bool:
        # absorb unsolicited PONGs, then expect the 10-byte frame
        while len(self.buf) >= 2 and self.buf[0] == 0x8A:
            if self.buf[1] != 0x00:
                self.close()
                return False
            del self.buf[:2]
        if len(self.buf) < 10:
            return False
        if bytes(self.buf[:2]) != common.MAX_PAYLOAD_FRAME[:2]:
            self.close()
            return False
        del self.buf[:10]
        self.dup.write(common.MAX_PAYLOAD_FRAME)
        self.state = self.ST_GREET
        return True

    def _greet(self) -> bool:
        if len(self.buf) < 2:
            return False
        ver, n = self.buf[0], self.buf[1]
        if ver != _VER or len(self.buf) < 2 + n:
            if ver != _VER:
                self.close()
            return False
        methods = self.buf[2: 2 + n]
        del self.buf[: 2 + n]
        if 0 not in methods:
            self.dup.write(b"\x05\xff")
            self.close()
            return False
        self.dup.write(b"\x05\x00")
        self.state = self.ST_REQ
        return True

    def _request(self) -> bool:
        if len(self.buf) < 4:
            return False
        ver, cmd, _rsv, atyp = self.buf[:4]
        if ver != _VER:
            self.close()
            return False
        if atyp == _ATYP_V4:
            need = 4 + 4 + 2
        elif atyp == _ATYP_V6:
            need = 4 + 16 + 2
        elif atyp == _ATYP_DOMAIN:
            if len(self.buf) < 5:
                return False
            need = 4 + 1 + self.buf[4] + 2
        else:
            self.dup.write(b"\x05\x08\x00\x01" + b"\x00" * 6)
            self.close()
            return False
        if len(self.buf) < need:
            return False
        if cmd != _CMD_CONNECT:
            self.dup.write(b"\x05\x07\x00\x01" + b"\x00" * 6)
            self.close()
            return False
        if atyp == _ATYP_DOMAIN:
            dlen = self.buf[4]
            host = bytes(self.buf[5:5 + dlen]).decode("latin-1")
            port = struct.unpack(">H", self.buf[5 + dlen:7 + dlen])[0]
        else:
            alen = 4 if atyp == _ATYP_V4 else 16
            import socket as _s
            host = _s.inet_ntop(_s.AF_INET if alen == 4 else _s.AF_INET6,
                                bytes(self.buf[4:4 + alen]))
            port = struct.unpack(">H", self.buf[4 + alen:6 + alen])[0]
        del self.buf[:need]
        self.state = self.ST_TUNNEL
        self._connect(host, port, bytes(self.buf))
        self.buf.clear()
        return False

    # ----------------------------------------------------------- connect

    def _connect(self, host: str, port: int, early: bytes) -> None:
        from ..utils.ip import is_ip_literal
        resolve = self.server.resolve
        if is_ip_literal(host):
            self._connect_ip(host, port, early)
        else:
            def done(ip: Optional[str]) -> None:
                if ip is None:
                    self.dup.write(b"\x05\x04\x00\x01" + b"\x00" * 6)
                    self.close()
                else:
                    self._connect_ip(ip, port, early)
            resolve(self.loop, host, done)

    def _connect_ip(self, ip: str, port: int, early: bytes) -> None:
        ok_reply = b"\x05\x00\x00\x01" + b"\x00" * 6
        if self.dup.conn is not None:
            # plain-TCP front: reply, drain, then native pump handover
            conn = self.dup.conn
            conn.pause_reading()
            conn.write(ok_reply)
            self.server.sessions += 1
            self.server.tunneled += 1

            def done(a2b, b2a, err):
                self.server.sessions -= 1

            detach_when_drained(conn, lambda fd: splice_connect(
                self.loop, fd, ip, port, early, done))
            self.state = self.ST_DONE
            return
        # streamed front: python bridge
        try:
            back = Connection.connect(self.loop, ip, port)
        except OSError:
            self.dup.write(b"\x05\x05\x00\x01" + b"\x00" * 6)
            self.close()
            return
        self.back = back
        sess = self
        self.server.sessions += 1
        self.server.tunneled += 1

        class Back(Handler):
            def on_connected(self, c: Connection) -> None:
                sess.dup.write(ok_reply)
                if early:
                    c.write(early)

            def on_data(self, c: Connection, data: bytes) -> None:
                sess.dup.write(data)

            def on_eof(self, c: Connection) -> None:
                sess.dup.close()

            def on_closed(self, c: Connection, err: int) -> None:
                sess.server.sessions -= 1
                sess.back = None
                sess.dup.close()

        back.set_handler(Back())

    # -------------------------------------------------------------- page

    def _serve_page(self) -> None:
        if self.server.redirect is not None:
            self.dup.write((f"HTTP/1.1 302 Found\r\nLocation: "
                            f"{self.server.redirect}\r\ncontent-length: 0"
                            f"\r\nconnection: close\r\n\r\n").encode())
            self.close()
            return
        got = self.server.pages.get(self.parser.uri or "/")
        if got is None:
            self._page_status(404, b"404 not found")
            return
        body, ctype = got
        self.dup.write((f"HTTP/1.1 200 OK\r\ncontent-type: {ctype}\r\n"
                        f"content-length: {len(body)}\r\n"
                        f"connection: close\r\n\r\n").encode() + body)
        self.close()

    def _page_status(self, code: int, body: bytes, extra=()) -> None:
        lines = "".join(f"{k}: {v}\r\n" for k, v in extra)
        self.dup.write((f"HTTP/1.1 {code} X\r\n{lines}"
                        f"content-length: {len(body)}\r\n"
                        f"connection: close\r\n\r\n").encode() + body)
        self.close()

    def close(self) -> None:
        self.state = self.ST_DONE
        if self.back is not None:
            self.back.close()
            self.back = None
        self.dup.close()


def _default_resolve(loop, host: str, cb: Callable[[Optional[str]], None]) -> None:
    """Off-loop getaddrinfo, continuation on the loop (Socks5 pattern)."""
    import socket
    import threading

    def work() -> None:
        try:
            infos = socket.getaddrinfo(host, None, type=socket.SOCK_STREAM)
            ip = infos[0][4][0]
        except OSError:
            ip = None
        loop.run_on_loop(lambda: cb(ip))

    threading.Thread(target=work, daemon=True).start()


class WebSocksProxyServer:
    """users: {username: password}. TCP listener always; kcp=True adds a
    KCP-streamed UDP listener on the same port number."""

    def __init__(self, alias: str, loop: SelectorEventLoop, bind_ip: str,
                 bind_port: int, users: dict, page_root: Optional[str] = None,
                 redirect: Optional[str] = None, kcp: bool = False,
                 resolve=None):
        self.alias = alias
        self.loop = loop
        self.users = dict(users)
        self.pages = PageProvider(page_root)
        self.redirect = redirect
        self.resolve = resolve or _default_resolve
        self.sessions = 0
        self.tunneled = 0  # cumulative established tunnels
        self.accepted = 0
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.want_kcp = kcp
        self.tcp: Optional[ServerSock] = None
        self.udp: Optional[UdpServer] = None
        self.started = False

    def start(self) -> None:
        if self.started:
            return
        self.tcp = self.loop.call_sync(lambda: ServerSock(
            self.loop, self.bind_ip, self.bind_port, self._on_accept))
        if self.bind_port == 0:
            self.bind_port = self.tcp.port
        if self.want_kcp:
            self.udp = self.loop.call_sync(lambda: UdpServer(
                self.loop, self.bind_ip, self.bind_port, self._on_kcp))
        self.started = True

    def stop(self) -> None:
        if not self.started:
            return
        self.started = False
        if self.tcp is not None:
            self.loop.run_on_loop(self.tcp.close)
            self.tcp = None
        if self.udp is not None:
            self.udp.close()
            self.udp = None

    # --------------------------------------------------------- TCP front

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        self.accepted += 1
        conn = Connection(self.loop, fd, (ip, port))
        dup = _Duplex(conn.write, conn.close, conn=conn)
        sess = _Session(self, self.loop, dup)

        class Front(Handler):
            def on_data(self, c: Connection, data: bytes) -> None:
                sess.on_data(data)

            def on_eof(self, c: Connection) -> None:
                sess.close()

            def on_closed(self, c: Connection, err: int) -> None:
                sess.close()

        conn.set_handler(Front())

    # --------------------------------------------------------- KCP front

    def _on_kcp(self, vconn) -> None:
        self.accepted += 1
        loop = self.loop
        kcp = KcpConn(loop, KCP_CONV, vconn.write)

        def on_stream(stream: Stream) -> None:
            dup = _Duplex(stream.write, stream.close)
            sess = _Session(self, loop, dup)

            class SH(StreamHandler):
                def on_data(self, s, data):
                    sess.on_data(data)

                def on_eof(self, s):
                    sess.close()

                def on_closed(self, s):
                    sess.close()

            stream.set_handler(SH())

        mux = StreamedSession(loop, kcp, is_client=False,
                              on_accept=on_stream)

        class VH:
            def on_data(self, c, data):
                kcp.feed(data)

            def on_closed(self, c, err):
                mux.close()

        vconn.set_handler(VH())
