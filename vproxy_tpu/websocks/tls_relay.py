"""WebSocks TLS front, SNI-based relay, and the direct-relay machinery.

Parity targets (reference):
* TLS/wss listener + SNI dispatch — WebSocksProtocolHandler.java:540 and
  WebSocksUtils/ssl setup: the server listens with a real certificate;
  a ClientHello whose SNI is NOT one of the server's own domains is not
  terminated at all but relayed as raw TCP to that host:443, so probes
  see a genuine TLS site (the camouflage story).
* DomainBinder — vproxyx/websocks/relay/DomainBinder.java:148: leases a
  fake IP per proxied domain (with TTL) so the agent's DNS answers give
  the OS a connectable address.
* RelayHttpsServer — relay/RelayHttpsServer.java:289: accepts on the
  fake IPs, recovers the domain from the accepted socket's LOCAL
  address (the client connected to the fake IP), and tunnels to
  domain:443 through the websocks server without touching the TLS
  bytes.

TPU-era notes: the fake-IP pool lives in 127.64.0.0/10 — on Linux the
whole 127/8 is locally bindable/connectable, so tests and single-host
agents need no interface configuration (the reference uses TUN/TAP or
requires route setup for its 100.64/10 pool).
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Callable, Optional

from ..net import vtl
from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..net.tls import TlsSocket
from ..utils.log import Logger

_log = Logger("websocks-tls")


# ------------------------------------------------------------ SNI sniff


def parse_client_hello_sni(buf: bytes):
    """-> ("need", None) while incomplete, ("bad", None) if not a TLS
    ClientHello, ("ok", sni_or_None) once the ClientHello is complete.

    Accumulates handshake bytes across TLS records (a ClientHello may
    span records). Only the server_name extension (RFC 6066) is read.
    """
    hs = bytearray()
    off = 0
    while True:
        if len(buf) - off < 5:
            break
        ctype, ver, rlen = buf[off], buf[off + 1:off + 3], \
            struct.unpack(">H", buf[off + 3:off + 5])[0]
        if ctype != 0x16 or ver[0] != 3:
            return ("bad", None) if not hs and off == 0 else ("need", None)
        if len(buf) - off - 5 < rlen:
            break
        hs += buf[off + 5: off + 5 + rlen]
        off += 5 + rlen
        if len(hs) >= 4:
            mlen = int.from_bytes(hs[1:4], "big")
            if len(hs) - 4 >= mlen:
                break
    if len(hs) < 4:
        return ("need", None)
    if hs[0] != 0x01:  # not ClientHello
        return ("bad", None)
    mlen = int.from_bytes(hs[1:4], "big")
    if len(hs) - 4 < mlen:
        return ("need", None)
    try:
        return ("ok", _sni_from_client_hello(bytes(hs[4: 4 + mlen])))
    except (IndexError, struct.error):
        return ("bad", None)


def _sni_from_client_hello(b: bytes) -> Optional[str]:
    p = 2 + 32  # client_version + random
    sid = b[p]
    p += 1 + sid
    (cs_len,) = struct.unpack(">H", b[p:p + 2])
    p += 2 + cs_len
    cm = b[p]
    p += 1 + cm
    if p + 2 > len(b):
        return None  # no extensions
    (ext_len,) = struct.unpack(">H", b[p:p + 2])
    p += 2
    end = min(p + ext_len, len(b))
    while p + 4 <= end:
        etype, elen = struct.unpack(">HH", b[p:p + 4])
        p += 4
        if etype == 0:  # server_name
            q = p + 2  # skip server_name_list length
            if q + 3 <= p + elen:
                ntype = b[q]
                (nlen,) = struct.unpack(">H", b[q + 1:q + 3])
                if ntype == 0:
                    return b[q + 3: q + 3 + nlen].decode("ascii", "replace")
        p += elen
    return None


# ------------------------------------------------------ TLS front server


class WebSocksTlsFrontend:
    """TLS listener in front of a WebSocksProxyServer.

    SNI in `self_domains` (or absent) -> terminate TLS with the holder's
    certificate and run the normal WebSocks session over the plaintext.
    Any other SNI -> raw TCP relay to (sni, relay_port): the listener is
    indistinguishable from a TLS reverse proxy for that site.
    """

    def __init__(self, server, holder, bind_ip: str, bind_port: int,
                 self_domains: Optional[list] = None, relay_port: int = 443):
        self.server = server
        self.loop: SelectorEventLoop = server.loop
        self.holder = holder
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.self_domains = list(self_domains or [])
        self.relay_port = relay_port
        self.relayed = 0
        self.terminated = 0
        self.sock: Optional[ServerSock] = None

    def start(self) -> None:
        self.sock = self.loop.call_sync(lambda: ServerSock(
            self.loop, self.bind_ip, self.bind_port, self._on_accept))
        if self.bind_port == 0:
            self.bind_port = self.sock.port

    def stop(self) -> None:
        if self.sock is not None:
            self.loop.run_on_loop(self.sock.close)
            self.sock = None

    def _is_self(self, sni: Optional[str]) -> bool:
        if sni is None:
            return True
        if sni in self.self_domains:
            return True
        return any(ck.matches(sni) for ck in self.holder.cert_keys)

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        front = self
        conn = Connection(self.loop, fd, (ip, port))
        buf = bytearray()

        class Sniff(Handler):
            def on_data(self, c: Connection, data: bytes) -> None:
                buf.extend(data)
                state, sni = parse_client_hello_sni(bytes(buf))
                if state == "need" and len(buf) < 32768:
                    return
                if state == "bad" or state == "need":
                    c.close()
                    return
                c.pause_reading()
                if front._is_self(sni):
                    front.terminated += 1
                    front._terminate(c, bytes(buf))
                else:
                    front.relayed += 1
                    front._relay(c, sni, bytes(buf))

            def on_eof(self, c: Connection) -> None:
                c.close()

        conn.set_handler(Sniff())

    def _terminate(self, conn: Connection, sniffed: bytes) -> None:
        """Own-domain path: TLS handshake with our cert, then the normal
        WebSocks session machine over the decrypted stream."""
        from .server import _Duplex, _Session

        tls = TlsSocket(conn, self.holder.front_context)
        # conn=None: the session must NOT detach the raw fd for a native
        # pump handover — the raw stream is ciphertext and the TLS state
        # lives here in Python; tunneled bytes relay through tls.write
        dup = _Duplex(tls.write, tls.close, conn=None)
        sess = _Session(self.server, self.loop, dup)

        class Plain(Handler):
            def on_data(self, t, data: bytes) -> None:
                sess.on_data(data)

            def on_eof(self, t) -> None:
                sess.close()

            def on_closed(self, t, err: int) -> None:
                sess.close()

        tls.set_handler(Plain())
        conn.resume_reading()
        tls.feed_raw(sniffed)

    def _relay(self, conn: Connection, sni: str, sniffed: bytes) -> None:
        """Foreign-SNI path: raw TCP relay to (sni, relay_port); the TLS
        session passes through untouched (we never hold its keys).
        After the sniffed head drains to the backend both fds hand over
        to the native splice pump."""
        loop = self.loop
        front_dead = []

        def connect(ipaddr: Optional[str]) -> None:
            if ipaddr is None or conn.closed:
                conn.close()
                return
            try:
                back = Connection.connect(loop, ipaddr, self.relay_port)
            except OSError:
                conn.close()
                return

            class Back(Handler):
                def on_connected(self, b: Connection) -> None:
                    b.pause_reading()
                    if front_dead:
                        b.close()
                        return
                    b.write(sniffed)
                    if not b.out:
                        self.on_drained(b)

                def on_drained(self, b: Connection) -> None:
                    if b.detached or b.closed:
                        return
                    if front_dead or conn.closed or conn.detached:
                        b.close()
                        return
                    bfd = b.detach()
                    ffd = conn.detach()
                    if not vtl.pump_sets_nodelay():  # pre-r6 .so
                        vtl.set_nodelay(ffd)
                        vtl.set_nodelay(bfd)
                    loop.pump(ffd, bfd, 65536, None)

                def on_closed(self, b: Connection, err: int) -> None:
                    if not conn.detached:
                        conn.close()

                def on_eof(self, b: Connection) -> None:
                    b.close()

            back.set_handler(Back())

            class FrontWait(Handler):
                def on_eof(self, c: Connection) -> None:
                    front_dead.append(1)
                    c.close()

                def on_closed(self, c: Connection, err: int) -> None:
                    front_dead.append(1)

            conn.set_handler(FrontWait())

        self.server.resolve(loop, sni, connect)


# -------------------------------------------------------- domain binder


class DomainBinder:
    """domain <-> fake-IP leases with TTL (DomainBinder.java:148).

    Pool: 127.64.0.0/10 (~4M addresses). A lease is refreshed on every
    bind/lookup; expired leases are reclaimed lazily on allocation."""

    BASE = (127 << 24) | (64 << 16)
    SIZE = 1 << 22

    def __init__(self, ttl_s: float = 300.0):
        self.ttl = ttl_s
        self._by_domain: dict = {}  # domain -> [ip_int, expiry]
        self._by_ip: dict = {}      # ip_int -> domain
        self._next = 1

    @staticmethod
    def _fmt(ip_int: int) -> str:
        return socket.inet_ntoa(struct.pack(">I", ip_int))

    def bind(self, domain: str) -> str:
        """Lease (or refresh) the fake IP for a domain."""
        now = time.monotonic()
        ent = self._by_domain.get(domain)
        if ent is not None:
            ent[1] = now + self.ttl
            return self._fmt(ent[0])
        for _ in range(self.SIZE):
            cand = self.BASE + self._next
            self._next = self._next % (self.SIZE - 2) + 1
            old = self._by_ip.get(cand)
            if old is None:
                break
            oent = self._by_domain.get(old)
            if oent is None or oent[1] < now:  # expired: reclaim
                self._by_domain.pop(old, None)
                break
        else:
            raise OSError("fake-IP pool exhausted")
        self._by_ip[cand] = domain
        self._by_domain[domain] = [cand, now + self.ttl]
        return self._fmt(cand)

    def lookup_ip(self, ip: str) -> Optional[str]:
        """fake IP -> domain (refreshes the lease), None if unknown or
        expired."""
        try:
            (ip_int,) = struct.unpack(">I", socket.inet_aton(ip))
        except OSError:
            return None
        domain = self._by_ip.get(ip_int)
        if domain is None:
            return None
        ent = self._by_domain.get(domain)
        now = time.monotonic()
        if ent is None or ent[1] < now:
            self._by_ip.pop(ip_int, None)
            self._by_domain.pop(domain, None)
            return None
        ent[1] = now + self.ttl
        return domain


# --------------------------------------------------- direct relay server


class DirectRelayServer:
    """Accepts connections addressed to DomainBinder fake IPs and
    tunnels them to (domain, target_port) through the agent
    (RelayHttpsServer.java:289). The domain comes from the accepted
    socket's LOCAL address — the client connected to the fake IP the
    agent's DNS handed out; the TLS (or any) bytes pass through opaque.

    Binds 0.0.0.0 so every 127.64/10 address is accepted on one socket.
    """

    def __init__(self, agent, binder: DomainBinder, bind_port: int = 443,
                 target_port: Optional[int] = None, bind_ip: str = "0.0.0.0"):
        self.agent = agent
        self.binder = binder
        self.loop: SelectorEventLoop = agent.loop
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        # None = same port the client aimed at (our bind port)
        self.target_port = target_port
        self.relayed = 0
        self.sock: Optional[ServerSock] = None

    def start(self) -> None:
        self.sock = self.loop.call_sync(lambda: ServerSock(
            self.loop, self.bind_ip, self.bind_port, self._on_accept))
        if self.bind_port == 0:
            self.bind_port = self.sock.port

    def stop(self) -> None:
        if self.sock is not None:
            self.loop.run_on_loop(self.sock.close)
            self.sock = None

    @staticmethod
    def _local_ip(fd: int) -> Optional[str]:
        try:
            s = socket.socket(fileno=os.dup(fd))
        except OSError:
            return None
        try:
            return s.getsockname()[0]
        finally:
            s.close()

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        local = self._local_ip(fd)
        domain = None if local is None else self.binder.lookup_ip(local)
        if domain is None:
            _log.alert(f"direct-relay: no binding for {local}")
            vtl.close(fd)
            return
        conn = Connection(self.loop, fd, (ip, port))
        conn.pause_reading()
        early = bytearray()

        class FrontWait(Handler):
            def on_data(self, c: Connection, data: bytes) -> None:
                early.extend(data)

            def on_eof(self, c: Connection) -> None:
                c.close()

        conn.set_handler(FrontWait())
        self.relayed += 1
        target = self.bind_port if self.target_port is None \
            else self.target_port

        def up(tunnel) -> None:
            if tunnel is None:
                conn.close()
                return
            if conn.closed:
                tunnel.close()
                return
            if early:
                tunnel.write(bytes(early))

            class Front(Handler):
                def on_data(self, c: Connection, data: bytes) -> None:
                    tunnel.write(data)

                def on_eof(self, c: Connection) -> None:
                    tunnel.close()
                    c.close()

                def on_closed(self, c: Connection, err: int) -> None:
                    tunnel.close()

            conn.set_handler(Front())
            tunnel.set_sink(conn.write, lambda: conn.close())
            conn.resume_reading()

        self.agent.open_tunnel(domain, target, up)
