"""Step-synchronized multi-host submit loop — the SPMD serving clock.

SPMD dispatch has a contract single-host serving never sees: EVERY
host must participate in EVERY dispatch with EQUAL padded shapes, or
the collective deadlocks (a host that skips a step leaves the others
blocked in the reduction forever). This module turns the free-running
micro-batch queue (rules/service.py) into a fleet-wide STEP CLOCK:

* each host drains its local classify queue into a FIXED-shape padded
  batch every VPROXY_TPU_CLUSTER_STEP_MS (batch cap
  VPROXY_TPU_CLUSTER_BATCH, padded with empty Hints) — a host with no
  traffic contributes an all-padding batch, so idle hosts never stall
  busy ones and per-host load may be arbitrarily unequal;
* before dispatching step N of epoch E, the host broadcasts an arrive
  datagram over the membership socket and waits until every UP,
  stepping peer has arrived at step >= N (the cluster-layer barrier).
  The epoch IS the rule generation (cluster/replicate.py), so hosts
  only ever step together against identical tables;
* the barrier AND the device dispatch share one deadline
  (VPROXY_TPU_CLUSTER_STEP_TIMEOUT_MS). Blowing it — a dead peer, a
  wedged collective (failpoint `cluster.step.stall`), or a jax backend
  without cross-process collectives — DEGRADES this host to the PR-3
  inline host-index path (rules/index.py, oracle-parity winners at ~us
  cost): queued and future queries are answered locally, nothing
  fails, the same failover edge as device->oracle. A degraded host
  advertises stepping=false in its heartbeats so surviving peers stop
  waiting for it.
* a degraded host RE-JOINS on the next generation heartbeat: a new
  generation is a fleet-wide epoch switch (every host resets to step 0
  of epoch G), which is exactly the barrier-reset a rejoin needs.

The dispatch itself is `matcher.dispatch_snap` — on a multi-host TPU
mesh that is the jax-fp-sharded SPMD collective (parallel/mesh.py); on
a single-host mesh it is the local device dispatch, with the step
barrier still keeping the fleet in lockstep.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..utils import events, failpoint, trace
from ..utils.log import Logger
from .membership import Membership

_log = Logger("cluster-step")

STEP_MS = int(os.environ.get("VPROXY_TPU_CLUSTER_STEP_MS", "20"))
STEP_TIMEOUT_MS = int(os.environ.get(
    "VPROXY_TPU_CLUSTER_STEP_TIMEOUT_MS", "1000"))
BATCH = int(os.environ.get("VPROXY_TPU_CLUSTER_BATCH", "16"))


class StepLoop:
    """Per-host step-synchronized classify front. submit(hint, cb) from
    any thread; cb(rule_idx, payload) fires after the step that carried
    the query (payload = the matcher generation's attached object, the
    rules/service.py convention)."""

    def __init__(self, matcher, membership: Optional[Membership] = None,
                 step_ms: int = 0, batch_cap: int = 0, timeout_ms: int = 0,
                 on_degrade: Optional[Callable[[], None]] = None,
                 maglev=None):
        self.matcher = matcher
        self.membership = membership
        # optional Maglev plane: when a MaglevMatcher rides along, the
        # step dispatch moves onto the FUSED one-launch entry
        # (rules/engine.fused_dispatch via maglev.FusedPair) — a step
        # answers verdicts AND backend picks from one compiled program,
        # and submit_pick() queries get their pick at zero extra
        # launches. Without it, the pre-r12 hint-only dispatch serves.
        self.maglev = maglev
        self._pair = None
        if maglev is not None:
            from ..rules.maglev import FusedPair
            self._pair = FusedPair(matcher, maglev)
        self.step_ms = step_ms or STEP_MS
        self.batch_cap = batch_cap or BATCH
        self.timeout_ms = timeout_ms or STEP_TIMEOUT_MS
        self.on_degrade = on_degrade
        self.epoch = 0
        self.degraded = False
        self.steps_total = 0
        self.barrier_stalls = 0
        self._step = 0
        self._q: deque = deque()
        self._qlock = threading.Lock()
        self._arrive_cv = threading.Condition()
        # peer id -> (epoch, step) last seen in an arrive datagram
        self._peer_steps: dict[int, tuple[int, int]] = {}
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # persistent dispatch worker: a stuck collective must not stall
        # the step loop thread itself (it has host-index work to do).
        # Requests carry a token; a rejoin bumps it and abandons any
        # stuck worker — its late result is discarded, never delivered
        # into the new epoch.
        self._disp_cv = threading.Condition()
        self._disp_req: Optional[tuple] = None   # (token, hints)
        self._disp_res: Optional[tuple] = None   # (token, "ok"/"err", ...)
        self._disp_thread: Optional[threading.Thread] = None
        self._disp_busy = False
        self._disp_token = 0
        if membership is not None:
            membership.set_step_handler(self._on_step_msg)

    # ------------------------------------------------------------- control

    def start(self, warm: bool = True) -> None:
        if self._thread is not None:
            return
        if warm:
            # compile the fixed-shape dispatch BEFORE the clock starts:
            # a first-step jit compile would blow the barrier deadline
            # and degrade a perfectly healthy host at boot. Bounded —
            # a backend that cannot dispatch at all (no cross-process
            # collectives) surfaces on step 1 as the designed stall.
            self._timed_dispatch(
                [self._PAD_ITEM()] * self.batch_cap,
                time.monotonic() + max(10.0, 3 * self.timeout_ms / 1000.0))
        self._thread = threading.Thread(target=self._run,
                                        name="cluster-step", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        with self._disp_cv:
            self._disp_cv.notify_all()
        with self._arrive_cv:
            self._arrive_cv.notify_all()

    def rejoin(self, epoch: int) -> None:
        """Fleet-wide epoch switch (a new rule generation): every host
        resets to step 0 of the new epoch; a degraded host re-joins."""
        was = self.degraded
        with self._arrive_cv:
            if epoch <= self.epoch:
                return
            self.epoch = epoch
            self._step = 0
            self.degraded = False
            self._arrive_cv.notify_all()
        with self._disp_cv:
            # abandon a worker still stuck in the old epoch's collective
            # (its tokened result will be discarded when it surfaces)
            self._disp_token += 1
            self._disp_busy = False
            self._disp_req = None
            self._disp_res = None
            self._disp_thread = None
        if was:
            events.record("cluster_rejoin",
                          f"re-joined step dispatch at generation {epoch}",
                          generation=epoch)
            _log.info(f"re-joined step dispatch at generation {epoch}")

    @staticmethod
    def _PAD_ITEM():
        from ..rules.ir import Hint
        return (Hint(), b"\x00\x00\x00\x00", None, None, False, 0)

    def submit(self, hint, cb: Callable[[int, object], None]) -> None:
        if self._stopped:
            raise OSError("StepLoop is stopped")
        # the trace context rides the queue item: a sampled query's
        # trace shows barrier vs collective vs host-index time on the
        # node that served it; without a bound context the step plane
        # makes its own 1-in-N decision
        tid = trace.current_id() or trace.maybe_sample()
        with self._qlock:
            self._q.append((hint, b"\x00\x00\x00\x00", None, cb, False,
                            tid))

    def submit_pick(self, hint, ip: bytes, port: Optional[int],
                    cb: Callable[[int, int, object], None]) -> None:
        """Fused classify+pick through the step clock: cb(verdict,
        pick, (hint_payload, maglev_payload)) after the step that
        carried the query — the pick costs ZERO extra launches (it is
        one more gather inside the step's fused program). Requires the
        loop's maglev plane; port=None = source affinity."""
        if self.maglev is None:
            raise ValueError("StepLoop has no maglev plane configured")
        if self._stopped:
            raise OSError("StepLoop is stopped")
        tid = trace.current_id() or trace.maybe_sample()
        with self._qlock:
            self._q.append((hint, ip, port, cb, True, tid))

    def _fused_live(self) -> bool:
        """True only when the NEXT step would actually dispatch fused:
        a maglev plane is configured AND the current publishes carry
        the packed tables + maglev column (VPROXY_TPU_FUSED=0, a
        non-"jax" backend, or a pre-fused publish all fall back to the
        two-dispatch chain — status must say so, not report the
        config)."""
        if self._pair is None:
            return False
        hsnap = self.matcher.snapshot()
        if len(hsnap) <= 5 or hsnap[5] is None:
            return False
        msnap = self.maglev.snapshot()
        return msnap[0] is not None and msnap[1] is not None

    def status(self) -> dict:
        return {"epoch": self.epoch, "step": self._step,
                "fused": self._fused_live(),
                "degraded": self.degraded, "steps_total": self.steps_total,
                "barrier_stalls": self.barrier_stalls,
                "queued": len(self._q), "batch_cap": self.batch_cap,
                "step_ms": self.step_ms, "timeout_ms": self.timeout_ms,
                # client steering rides the membership maglev table
                # (steer_addrs): epoch switches never move affinities,
                # only UP-set changes do — surfaced here so the step
                # view shows what a resize will cost
                "steer": (None if self.membership is None
                          else self.membership.steer_status())}

    def steer_peer(self, key: bytes):
        """Maglev-consistent UP-peer pick for a client steering key —
        the submit plane's replacement for rotation when external
        clients choose which fleet node to submit through (the DNS
        steerer is the server-side form of the same table)."""
        if self.membership is None:
            return None
        return self.membership.steer_peer(key)

    # ------------------------------------------------------------- barrier

    def _on_step_msg(self, msg: dict, peer_id: int) -> None:
        try:
            e, s = int(msg["e"]), int(msg["s"])
        except (KeyError, ValueError, TypeError):
            return
        with self._arrive_cv:
            cur = self._peer_steps.get(peer_id)
            if cur is None or (e, s) > cur:
                self._peer_steps[peer_id] = (e, s)
            self._arrive_cv.notify_all()

    def _barrier_peers(self) -> list[int]:
        """Peers this step must wait for: UP and stepping (a degraded or
        dead host must not wedge the survivors forever — membership
        flips its flags within the heartbeat hysteresis)."""
        if self.membership is None:
            return []
        return [p.node_id for p in self.membership.live_peers()
                if p.node_id != self.membership.self_id and p.stepping]

    def _barrier(self, deadline: float) -> bool:
        if self.membership is None:
            return True
        self.membership.send_step({"e": self.epoch, "s": self._step})
        with self._arrive_cv:
            while True:
                want = self._barrier_peers()
                done = all(
                    self._peer_steps.get(pid, (-1, -1)) >=
                    (self.epoch, self._step)
                    for pid in want)
                if done:
                    return True
                left = deadline - time.monotonic()
                if left <= 0 or self._stopped:
                    return False
                self._arrive_cv.wait(min(left, 0.05))
                # re-broadcast while waiting: a single lost arrive
                # datagram must cost one wait tick, not degrade the
                # fleet (UDP gives no delivery promise)
                self.membership.send_step({"e": self.epoch,
                                           "s": self._step})

    # ------------------------------------------------------------ dispatch

    def _device_dispatch(self, items: list):
        """items: padded (hint, ip, port, cb, want_pick) rows. With a
        maglev plane the step rides the FusedPair's one-launch
        (verdict, pick) program; without it, the hint-only dispatch."""
        if failpoint.hit("cluster.step.stall"):
            # a wedged collective: the step deadline must fire and
            # degrade this host, never hang the fleet
            time.sleep(self.timeout_ms * 3 / 1000.0)
        if self._pair is not None:
            snap = self._pair.snapshot()
            out = np.asarray(self._pair.dispatch_snap(
                snap, [(h, ip, po) for h, ip, po, _, _, _ in items]))
            return (out[: len(items)], self._pair.snap_payload(snap))
        snap = self.matcher.snapshot()
        hints = [h for h, _, _, _, _, _ in items]
        return (np.asarray(self.matcher.dispatch_snap(snap, hints)),
                self.matcher.snap_payload(snap))

    def _dispatch_worker(self) -> None:
        while True:
            with self._disp_cv:
                while self._disp_req is None:
                    if self._stopped:
                        return
                    self._disp_cv.wait(1.0)
                token, hints = self._disp_req
                self._disp_req = None
            try:
                res: tuple = (token, "ok") + self._device_dispatch(hints)
            except MemoryError:
                raise
            except Exception as e:
                res = (token, "err", e)
            with self._disp_cv:
                if token != self._disp_token:
                    return  # abandoned by a rejoin: discard and retire
                self._disp_res = res
                self._disp_busy = False
                self._disp_cv.notify_all()

    _EPOCH_ABORT = object()  # rejoin invalidated this dispatch mid-flight

    def _timed_dispatch(self, hints: list, deadline: float):
        """Run the device dispatch on the worker with the step deadline;
        None on timeout/error (the stall edge), _EPOCH_ABORT when a
        rejoin invalidated the token mid-flight — the step was
        interrupted by an epoch switch, NOT stalled, and must not
        degrade the host."""
        with self._disp_cv:
            # a worker still finishing a PREVIOUS dispatch gets the
            # deadline to wrap up; its stale result is discarded below
            while self._disp_busy:
                left = deadline - time.monotonic()
                if left <= 0 or self._stopped:
                    return None
                self._disp_cv.wait(min(left, 0.05))
            self._disp_busy = True
            self._disp_res = None  # drop any stale completion
            self._disp_token += 1
            token = self._disp_token
            self._disp_req = (token, hints)
            if self._disp_thread is None or not self._disp_thread.is_alive():
                self._disp_thread = threading.Thread(
                    target=self._dispatch_worker, name="cluster-step-disp",
                    daemon=True)
                self._disp_thread.start()
            self._disp_cv.notify_all()
            while self._disp_res is None:
                if self._disp_token != token:
                    return self._EPOCH_ABORT
                left = deadline - time.monotonic()
                if left <= 0 or self._stopped:
                    return None
                self._disp_cv.wait(min(left, 0.05))
            res, self._disp_res = self._disp_res, None
        if res[1] != "ok":
            _log.alert(f"step dispatch failed: {res[2]!r}")
            return None
        return res[2], res[3]

    # ----------------------------------------------------------- main loop

    def _run(self) -> None:
        next_step = time.monotonic()
        while not self._stopped:
            now = time.monotonic()
            if now < next_step:
                time.sleep(min(next_step - now, 0.01))
                continue
            next_step = now + self.step_ms / 1000.0
            batch: list = []
            with self._qlock:
                while self._q and len(batch) < self.batch_cap:
                    batch.append(self._q.popleft())
            self.steps_total += 1
            if self.degraded:
                self._serve_host(batch)
                continue
            deadline = time.monotonic() + self.timeout_ms / 1000.0
            out = None
            # sampled queries in this step: step-phase spans attach to
            # the first one (barrier/collective are step-shared phases)
            tids = [it[5] for it in batch if it[5]]
            t_bar = time.monotonic() if tids else 0.0
            barrier_ok = self._barrier(deadline)
            if tids:
                trace.record_span(
                    tids[0], "cluster", "barrier", int(t_bar * 1e9),
                    int((time.monotonic() - t_bar) * 1e9),
                    epoch=self.epoch, step=self._step, ok=barrier_ok)
            if barrier_ok:
                padded = list(batch) + \
                    [self._PAD_ITEM()] * (self.batch_cap - len(batch))
                t_col = time.monotonic() if tids else 0.0
                out = self._timed_dispatch(padded, deadline)
                if tids and out is not None \
                        and out is not self._EPOCH_ABORT:
                    trace.record_span(
                        tids[0], "cluster", "collective",
                        int(t_col * 1e9),
                        int((time.monotonic() - t_col) * 1e9),
                        batch=len(batch), fused=self._pair is not None)
            if out is self._EPOCH_ABORT:
                # a rejoin landed mid-step (new generation): not a
                # stall — answer this batch locally and step on in the
                # new epoch
                self._serve_host(batch)
                continue
            if out is None:
                self._stall(batch)
                continue
            idxs, payload = out
            self._deliver(batch, idxs, payload)
            self._step += 1

    def _stall(self, batch: list) -> None:
        """Barrier timeout / dead collective: degrade to the inline
        host-index path (the device->oracle failover edge, one level
        up). Queued queries are served immediately — nothing fails."""
        self.barrier_stalls += 1
        self.degraded = True
        now = time.monotonic_ns()
        for it in batch:
            if it[5]:  # the degrade edge lands on EVERY sampled trace
                trace.record_span(it[5], "cluster", "barrier_stall", now,
                                  0, epoch=self.epoch, step=self._step,
                                  timeout_ms=self.timeout_ms)
        events.record("cluster_degrade",
                      f"step barrier stalled past {self.timeout_ms}ms at "
                      f"epoch {self.epoch} step {self._step}; degraded to "
                      "host-index serving",
                      epoch=self.epoch, step=self._step,
                      timeout_ms=self.timeout_ms)
        _log.alert(f"step barrier stalled ({self.timeout_ms}ms); serving "
                   "from the host index until the next generation")
        if self.on_degrade is not None:
            try:
                self.on_degrade()
            except Exception:
                _log.error("on_degrade callback failed", exc=True)
        self._serve_host(batch)

    def _serve_host(self, batch: list) -> None:
        """Degraded / epoch-abort serving: the inline host planes —
        O(probes) hint index plus the O(1) host maglev table for pick
        queries (same winners as the fused program, rules/index.py +
        the shared FNV contract). Nothing fails."""
        if not batch:
            return
        m = self.matcher
        snap = m.snapshot()
        hp = m.snap_payload(snap)
        msnap = None if self.maglev is None else self.maglev.snapshot()
        for hint, ip, port, cb, want, tid in batch:
            v, pick = -1, -1
            t0 = time.monotonic_ns() if tid else 0
            try:  # a broken row delivers -1, never strands its caller
                v = int(m.index_snap(snap, hint))
                if want:
                    pick = int(self.maglev.pick_snap(msnap, ip, port))
            except MemoryError:
                raise
            except Exception:
                _log.error("step host classify failed; delivering "
                           "no-match", exc=True)
            if tid:
                trace.record_span(tid, "cluster", "host_index", t0,
                                  time.monotonic_ns() - t0,
                                  degraded=self.degraded)
            try:
                if want:
                    cb(v, pick, (hp, self.maglev.snap_payload(msnap)))
                else:
                    cb(v, hp)
            except MemoryError:
                raise
            except Exception:
                _log.error("step classify callback failed", exc=True)

    def _deliver(self, batch: list, idxs, payload) -> None:
        # with the maglev plane, payload is the FusedPair's
        # (hint_payload, maglev_payload) and a row is (verdict, pick);
        # plain submits keep the hint-only cb(idx, hint_payload) shape
        paired = self._pair is not None
        hp = payload[0] if paired else payload
        for (_, _, _, cb, want, _), idx in zip(batch, idxs):
            row = np.atleast_1d(np.asarray(idx))
            try:
                if want:
                    pick = int(row[1]) if row.size > 1 else -1
                    cb(int(row[0]), pick, payload)
                else:
                    cb(int(row[0]), hp)
            except MemoryError:
                raise
            except Exception:
                _log.error("step classify callback failed", exc=True)
