"""Rule-generation replication — leader-shipped command logs with a
checksum-gated atomic generation swap.

The control-plane half of the cluster plane (the data-plane half is
cluster/submit.py): every host must serve the SAME rule tables, or two
connections to the same service classify differently depending on
which host accepted them. The mechanism mirrors how LB fleets
replicate forwarding state (Maglev, PAPERS.md) mapped onto this repo's
config-as-command-log persistence (control/persist.py):

* the LEADER (lowest live node id, cluster/membership.py) owns the
  rule state. Every successful mutating command against a replicated
  resource type bumps the rule GENERATION and lands in a bounded
  journal of `(generation, command-line)` entries
  (Command.execute -> Application.cluster.on_command).
* FOLLOWERS poll the leader over TCP (VPROXY_TPU_CLUSTER_POLL_MS):
  `sync(my_generation)` answers with either `noop` (up to date),
  `incr` (the journal suffix the follower is missing) or `snap` (the
  full command-log snapshot, persist.current_config serialization,
  when the follower is too far behind / fresh / diverged).
* every frame carries the leader's generation AND its cluster checksum
  (crc32 over the canonical config + every engine table's rule
  checksum — rules/engine.py HintMatcher/CidrMatcher.checksum(), the
  same generation-snapshot the classify dispatch reads). The follower
  applies the commands OFF-LOOP (this thread, never an event loop);
  the engine tables they touch rebuild as STANDBY tables on the
  engine's background installer (rules/engine.py TableInstaller) and
  land via atomic pointer swaps, so a fleet-wide rule push never
  stalls the step loop or an in-flight dispatch. The follower then
  recomputes its own checksum (after an installer barrier), and only
  then atomically publishes the new generation. Mismatch => the generation is REJECTED: the follower
  stays at its old generation (vproxy_cluster_generation_lag > 0, a
  `generation_reject` recorder event) and forces a full snapshot on
  the next poll. No two hosts ever REPORT the same generation with
  divergent rules.
* frames are length-prefixed with a payload CRC, so a torn transfer
  (connection cut mid-frame — failpoint `cluster.replicate.torn`)
  can never be installed: it fails the frame parse before any command
  is applied.

Leader change (the old leader left the live set): followers force a
full snapshot sync against the new leader — its journal numbering is
not comparable with the old leader's.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Optional

from ..utils import events, failpoint
from ..utils.log import Logger
from .membership import Membership

_log = Logger("cluster-repl")

POLL_MS = int(os.environ.get("VPROXY_TPU_CLUSTER_POLL_MS", "500"))
JOURNAL_CAP = int(os.environ.get("VPROXY_TPU_CLUSTER_JOURNAL", "256"))
_MAGIC = b"VPRC"

# resource types whose mutations replicate to followers: exactly the
# graph persist.current_config serializes. Control-plane-local
# resources (controllers, faults, cluster-node itself) stay per-host.
REPLICATED_TYPES = frozenset({
    "event-loop-group", "event-loop", "upstream", "server-group",
    "server", "security-group", "security-group-rule", "cert-key",
    "tcp-lb", "socks5-server", "dns-server", "switch", "vpc", "route",
    "ip", "user", "tap", "docker-network-plugin-controller",
    "policy",
})


def cluster_checksum(app) -> int:
    """Replica-identity checksum: crc32 of the canonical command-log
    config folded with every upstream engine-table checksum (the same
    published generation the classify dispatch snapshots). Two hosts
    with equal checksums serve bit-identical verdicts."""
    from ..control.persist import current_config
    c = zlib.crc32(current_config(app).encode())
    for alias in sorted(app.upstreams):
        c = zlib.crc32(
            struct.pack(">I", app.upstreams[alias]._matcher.checksum()), c)
    return c


# ------------------------------------------------------------- framing

def _send_frame(sock: socket.socket, obj: dict, torn: bool = False) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    head = _MAGIC + struct.pack(">II", len(payload), zlib.crc32(payload))
    if torn:
        # failpoint cluster.replicate.torn: cut the transfer mid-frame —
        # the receiver must reject it at the framing layer
        sock.sendall((head + payload)[: len(head) + len(payload) // 2])
        return
    sock.sendall(head + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        d = sock.recv(n - len(buf))
        if not d:
            raise OSError(f"connection closed mid-frame "
                          f"({len(buf)}/{n} bytes)")
        buf += d
    return buf


def _recv_frame(sock: socket.socket) -> dict:
    head = _recv_exact(sock, 12)
    if head[:4] != _MAGIC:
        raise OSError("bad replication frame magic")
    length, crc = struct.unpack(">II", head[4:])
    if length > 64 << 20:
        raise OSError(f"replication frame too large ({length})")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise OSError("replication frame crc mismatch (torn transfer)")
    return json.loads(payload)


class Replicator:
    """One per node; leader and follower roles flip with membership."""

    def __init__(self, app, membership: Membership,
                 bind_ip: str, repl_port: int, poll_ms: int = 0):
        self.app = app
        self.membership = membership
        self.poll_ms = poll_ms or POLL_MS
        self.generation = 0
        self.leader_gen_seen = 0
        self.journal: list[tuple[int, str]] = []
        self._lock = threading.Lock()
        # held across (handler mutates app) + (generation bump) on the
        # leader — Command.execute takes it — AND across the
        # (generation, checksum) pairing in _sync_response: a follower
        # sync must never read the OLD generation with a checksum of
        # already-mutated state (that mismatch would force a
        # destructive snapshot teardown on an up-to-date follower)
        self.mutation_lock = threading.Lock()
        self._applying = False      # replicated replay must not re-journal
        self._force_snapshot = False
        self._last_leader: Optional[int] = None
        # True once this node's state provably reached / came from the
        # fleet (a successful leader sync, or journaling with UP
        # peers): fleet-confirmed state REFUSES backward installs from
        # stale restarted leaders (apply_frame), closing the
        # rolling-upgrade rollback race
        self._fleet_confirmed = False
        self._stopped = False
        self._on_generation: list = []  # cb(generation) after install
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bounded retry: a restarting node re-binds the port its dead
        # incarnation held moments ago (rejoin is a first-class flow)
        deadline = time.monotonic() + 3.0
        while True:
            try:
                self._srv.bind((bind_ip, repl_port))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        self._srv.listen(16)
        self.bind_port = self._srv.getsockname()[1]
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- control

    def start(self) -> None:
        if self._threads:
            return
        for target, name in ((self._accept_loop, "cluster-repl-srv"),
                             (self._follow_loop, "cluster-repl-sync")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._stopped = True
        try:
            # shutdown BEFORE close: a thread blocked in accept() holds
            # a kernel reference that would keep the port bound (and a
            # restarted node from re-binding it) until accept returned
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass

    def on_generation(self, cb) -> None:
        """cb(generation) after a generation installs (leader bump or
        follower checksum-verified swap) — the step loop's re-join edge."""
        self._on_generation.append(cb)

    def generation_lag(self) -> int:
        """How many generations this node is behind the fleet (0 on the
        leader and on converged followers)."""
        seen = max(self.leader_gen_seen,
                   self.membership.max_generation_seen())
        return max(0, seen - self.generation)

    def checksum(self) -> int:
        return cluster_checksum(self.app)

    def status(self) -> dict:
        return {"generation": self.generation,
                "generation_lag": self.generation_lag(),
                "leader": self.membership.leader_id(),
                "is_leader": self.membership.is_leader(),
                "checksum": self.checksum(),
                "journal_len": len(self.journal),
                "replication_port": self.bind_port}

    # ------------------------------------------------------------- leader

    def on_command(self, line: str) -> None:
        """A successful mutating command against a replicated type ran
        on this node (Command.execute hook). The leader journals it as
        the next generation; a replay-applied command (follower) is
        ignored — it is already part of a journaled generation."""
        if self._applying or not self.membership.is_leader():
            return
        with self._lock:
            self.generation += 1
            gen = self.generation
            self.journal.append((gen, line))
            if len(self.journal) > JOURNAL_CAP:
                del self.journal[: len(self.journal) - JOURNAL_CAP]
        if any(p.up for p in self.membership.peer_list()
               if p.node_id != self.membership.self_id):
            # journaling toward a live fleet: this state is (being)
            # replicated — it must never roll back to an empty restart
            self._fleet_confirmed = True
        events.record("generation_bump",
                      f"rule generation {gen}: {line[:120]}",
                      generation=gen)
        for cb in list(self._on_generation):
            cb(gen)

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True,
                             name="cluster-repl-conn").start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10.0)
            req = _recv_frame(conn)
            if req.get("t") != "sync":
                return
            follower_gen = int(req.get("gen", 0))
            if self._fleet_ahead() is not None:
                # serving our (stale) state while we are still catching
                # up from the fleet would replicate the rollback a
                # rolling leader restart exists to avoid: tell the
                # follower to hold its last-known-good and poll again
                resp = {"t": "behind", "gen": self.generation}
            else:
                resp = self._sync_response(follower_gen)
            _send_frame(conn, resp,
                        torn=failpoint.hit("cluster.replicate.torn",
                                           f"gen={resp['gen']}"))
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _sync_response(self, follower_gen: int) -> dict:
        from ..control.persist import current_config
        # the checksum must describe the SAME generation the frame
        # advertises: mutation_lock excludes the (handler mutates app,
        # generation bumps) window, so the pairing is atomic — a stale
        # pairing would make followers reject perfectly good frames
        with self.mutation_lock:
            with self._lock:
                gen = self.generation
                journal = list(self.journal)
            cksum = cluster_checksum(self.app)
        if follower_gen == gen:
            return {"t": "noop", "gen": gen, "cksum": cksum}
        missing = [(g, ln) for g, ln in journal if g > follower_gen]
        if follower_gen > 0 and missing and missing[0][0] == follower_gen + 1:
            return {"t": "incr", "gen": gen, "cksum": cksum,
                    "cmds": [ln for _, ln in missing]}
        return {"t": "snap", "gen": gen, "cksum": cksum,
                "config": current_config(self.app)}

    # ----------------------------------------------------------- follower

    def _follow_loop(self) -> None:
        while not self._stopped:
            time.sleep(self.poll_ms / 1000.0)
            if self._stopped:
                return
            try:
                self.sync_once()
            except Exception:
                _log.error("replication sync failed", exc=True)

    def _fleet_ahead(self):
        """(peer_id, generation) of the highest-generation UP peer
        advertising a generation beyond ours, else None. Heartbeats
        carry each node's generation, which is what makes a freshly
        RESTARTED lowest-id node — leader by id, stale by state —
        visible as behind the fleet it nominally leads (the
        rolling-upgrade storm scenario's leader roll)."""
        m = self.membership
        best = None
        for p in m.peer_list():
            if p.node_id == m.self_id or not p.up:
                continue
            if p.generation > self.generation and (
                    best is None or p.generation > best[1]):
                best = (p.node_id, p.generation)
        return best

    def sync_once(self) -> bool:
        """One follower poll against the current leader; True when a
        frame was applied cleanly (incl. noop). Callable directly by
        tests/chaos for deterministic convergence."""
        m = self.membership
        lid = m.leader_id()
        if lid == m.self_id:
            ahead = self._fleet_ahead()
            if ahead is None:
                return True  # leading with fleet-current state
            # leader by id, stale by state (a rolling restart brought
            # the lowest id back empty): catch up FROM the fleet before
            # acting as its source of truth — last-known-good stays
            # serving everywhere while this node pulls a snapshot
            lid = ahead[0]
            self._force_snapshot = True
        if self._last_leader is not None and self._last_leader != lid:
            # new leader: its journal numbering is not ours to trust —
            # neither is the lag baseline we accumulated from the old one
            self._force_snapshot = True
            self.leader_gen_seen = 0
        self._last_leader = lid
        leader = m.peers.get(lid)
        if leader is None:
            return False
        try:
            conn = socket.create_connection((leader.ip, leader.repl_port),
                                            timeout=5.0)
        except OSError:
            return False
        try:
            conn.settimeout(10.0)
            gen = 0 if self._force_snapshot else self.generation
            _send_frame(conn, {"t": "sync", "gen": gen})
            frame = _recv_frame(conn)
        except (OSError, ValueError) as e:
            # torn / failed transfer: reject at the framing layer — no
            # partial apply is possible, the generation stays put
            events.record("generation_reject",
                          f"replication transfer from node {lid} "
                          f"rejected: {e}", leader=lid,
                          generation=self.generation)
            self._force_snapshot = True
            return False
        finally:
            try:
                conn.close()
            except OSError:
                pass
        return self.apply_frame(frame, leader_id=lid)

    def apply_frame(self, frame: dict, leader_id: int = -1) -> bool:
        """Apply one sync frame off-loop; atomic generation swap gated
        on the checksum. Public for tests (replication-parity edges)."""
        from ..control.command import Command
        t0 = time.monotonic()
        kind = frame.get("t")
        gen = int(frame.get("gen", 0))
        if kind == "behind":
            # the leader itself is catching up from the fleet (fresh
            # restart into the lowest id): keep serving last-known-good
            # and poll again — its stale generation must not touch the
            # lag baseline either
            return False
        if gen < self.generation and self._fleet_confirmed:
            # a leader offering to move us BACKWARD while our own state
            # is fleet-confirmed is a stale restart whose heartbeats
            # haven't told it yet (the rolling-upgrade race): hold
            # last-known-good — the catch-up path will pull OUR state
            # into it. The legitimate backward install (this node
            # journaled ALONE in its boot window, the real fleet then
            # appeared) stays allowed: that state was never confirmed.
            self._reject(gen, f"backward generation from leader "
                              f"(local {self.generation} is "
                              "fleet-confirmed; stale leader must "
                              "catch up first)")
            return False
        want = frame.get("cksum")
        # assignment, not max(): a legitimate backward move (the real
        # leader appearing after this node journaled alone in the boot
        # window) must not leave the lag gauge pinned nonzero forever
        self.leader_gen_seen = gen
        if kind == "noop":
            if want is not None and want != self.checksum():
                # same generation, different tables: divergence — force
                # a full snapshot to heal
                self._reject(gen, "checksum diverged at equal generation")
                return False
            return True
        if kind == "incr":
            lines = list(frame.get("cmds", []))
        elif kind == "snap":
            if self.journal and not self._fleet_confirmed:
                # locally-journaled generations that never reached the
                # fleet are about to be replaced by its snapshot. The
                # mutation gate (control/command.py) refuses writes on
                # a leader-by-id that can SEE it is behind, but
                # membership needs heartbeats before a restarted node
                # can see the fleet at all — a write accepted in that
                # blind window lands here, and discarded state must be
                # LOUD, never silent
                lost = [ln for _, ln in self.journal]
                _log.error(
                    f"fleet snapshot discards {len(lost)} unconfirmed "
                    "local generation(s): "
                    + "; ".join(ln[:60] for ln in lost[:4]))
                events.record(
                    "generation_discard",
                    f"{len(lost)} unconfirmed local generation(s) "
                    "replaced by fleet snapshot", discarded=len(lost))
            self._teardown()
            lines = [ln for ln in frame.get("config", "").splitlines()
                     if ln.strip() and not ln.startswith("#")]
        else:
            return False
        self._applying = True
        try:
            for ln in lines:
                try:
                    Command.execute(self.app, ln)
                except Exception as e:
                    self._reject(gen, f"replay failed at {ln[:80]!r}: {e}")
                    return False
        finally:
            self._applying = False
        # the replayed mutations install engine tables through the
        # background TableInstaller (standby compile + atomic swap —
        # the serving path never waits on them). Handlers wait for
        # their own install, but a wait=False mutation path must still
        # never pair a new generation with an old table checksum:
        # barrier on the installer before checksumming. A timed-out
        # barrier is a REJECT with its own reason — comparing against
        # half-installed tables would masquerade as rule divergence.
        from ..rules.engine import flush_installs
        barrier_s = float(os.environ.get(
            "VPROXY_TPU_INSTALL_BARRIER_S", "300"))
        if not flush_installs(timeout=barrier_s):
            self._reject(gen, "engine install barrier timed out "
                              "(standby table compiles still running)")
            return False
        got = self.checksum()
        if want is not None and got != want:
            self._reject(gen, f"table checksum mismatch "
                              f"(leader {want:#x}, local {got:#x})")
            return False
        # checksum verified: atomically publish the new generation
        self.generation = gen
        self._fleet_confirmed = True  # this state came FROM the fleet
        self._force_snapshot = False
        swap_ms = (time.monotonic() - t0) * 1e3
        events.record("generation_install",
                      f"generation {gen} installed ({kind}, "
                      f"{len(lines)} cmds, {swap_ms:.1f}ms)",
                      generation=gen, frame=kind,
                      swap_ms=round(swap_ms, 2))
        for cb in list(self._on_generation):
            cb(gen)
        return True

    def _reject(self, gen: int, why: str) -> None:
        self._force_snapshot = True
        events.record("generation_reject",
                      f"generation {gen} rejected: {why}",
                      generation=gen, local_generation=self.generation)
        _log.alert(f"cluster generation {gen} rejected: {why}; "
                   f"staying at {self.generation}, full snapshot next poll")

    def _teardown(self) -> None:
        """Snapshot apply starts from an empty resource graph: remove
        everything persist.current_config serializes, frontends first
        (reverse dependency order), through the normal handlers so every
        resource's own stop/close runs."""
        from ..control.command import Command
        app = self.app
        self._applying = True
        try:
            def rm(rtype: str, aliases) -> None:
                for a in list(aliases):
                    try:
                        Command.execute(app, f"force-remove {rtype} {a}")
                    except Exception:
                        _log.error(f"teardown {rtype} {a} failed", exc=True)
            rm("tcp-lb", app.tcp_lbs)
            rm("socks5-server", app.socks5_servers)
            rm("dns-server", app.dns_servers)
            rm("switch", app.switches)
            rm("upstream", app.upstreams)
            rm("server-group", app.server_groups)
            rm("security-group", app.security_groups)
            rm("cert-key", app.cert_keys)
            rm("docker-network-plugin-controller", app.docker_controllers)
            from ..control.app import (DEFAULT_ACCEPTOR_ELG,
                                       DEFAULT_CONTROL_ELG,
                                       DEFAULT_WORKER_ELG)
            rm("event-loop-group",
               [a for a in app.elgs
                if a not in (DEFAULT_ACCEPTOR_ELG, DEFAULT_WORKER_ELG,
                             DEFAULT_CONTROL_ELG)])
        finally:
            self._applying = False
