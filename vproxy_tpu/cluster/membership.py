"""Cluster membership — UDP heartbeats among peers with hysteresis edges.

The fleet-coordination analog of Maglev's LB-fleet membership (NSDI'16,
PAPERS.md): every vproxy-tpu host heartbeats every other host over UDP
and keeps an up/down view with the SAME edge-hysteresis idiom as the
backend health checker (components/servergroup._HealthChecker._result:
N consecutive good periods flip UP, N consecutive missed periods flip
DOWN), so a single dropped datagram never flaps the fleet view.

Topology comes from `VPROXY_TPU_CLUSTER_PEERS` — a comma-separated list
of `host:port[/replport]` entries, one per node, in node-id order (the
replication TCP port defaults to the heartbeat port + 1). This node's
id is `jax.process_index()` when `jax.distributed` is up (the cluster
id IS the SPMD host index) and `VPROXY_TPU_CLUSTER_SELF` otherwise.

The heartbeat datagram carries (node id, rule generation, stepping
flag, boot incarnation): generation is how a degraded host learns the
fleet moved to a new table generation (its re-join edge,
cluster/submit.py), stepping is how the step barrier knows which peers
participate in SPMD dispatch.

The same socket carries the step-barrier arrive messages
(cluster/submit.py) — one port per node in the peers spec, demuxed on
the "t" field. Heartbeat RX is a failpoint site (`cluster.peer.drop`,
ctx "from=<id> <addr>"): dropping a peer's heartbeats drives the DOWN
edge deterministically in tests without killing anything.

Membership feeds DNS-as-LB across the fleet: `dns_addrs()` returns the
UP peers' addresses for the cluster service name
(`<VPROXY_TPU_CLUSTER_SERVICE>.vproxy.local`, dns/server.py) — and
never returns an empty set: this node itself is always a member, so
the last peer is never evicted from the answers (an empty A answer
would take the whole service down harder than any dead peer could).
"""
from __future__ import annotations

import json
import os
import select
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import failpoint
from ..utils.log import Logger

_log = Logger("cluster-member")

HB_MS = int(os.environ.get("VPROXY_TPU_CLUSTER_HB_MS", "200"))
UP_N = int(os.environ.get("VPROXY_TPU_CLUSTER_UP", "2"))
DOWN_N = int(os.environ.get("VPROXY_TPU_CLUSTER_DOWN", "3"))


def cluster_service_name() -> str:
    """Sub-domain left of `.vproxy.local` that answers the healthy peer
    set (DNS-as-LB across the fleet)."""
    return os.environ.get("VPROXY_TPU_CLUSTER_SERVICE", "cluster")


@dataclass
class Peer:
    node_id: int
    ip: str
    port: int          # heartbeat/barrier UDP port
    repl_port: int     # rule-replication TCP port
    up: bool = False
    generation: int = 0    # last generation advertised in a heartbeat
    stepping: bool = False  # participating in step-synchronized dispatch
    incarnation: float = 0.0  # peer's boot stamp (restart detection)
    last_rx: float = 0.0
    # last analytics top-K summary gossiped in this peer's heartbeats
    # ({dim: [[key, count], ...]}, utils/sketch.gossip_summary) — the
    # fleet-merge input for GET /analytics on any node
    hh: Optional[dict] = field(default=None, repr=False)
    # last policing enforcement summary gossiped the same way
    # ({"seq", "t": [[dim, key, rate_mtok, burst_mtok, act], ...]},
    # policing/engine.gossip_summary) — a crowd seen by one node sheds
    # fleet-wide within one heartbeat period
    police: Optional[dict] = field(default=None, repr=False)
    _up_cnt: int = 0
    _down_cnt: int = 0
    _rx_since_tick: int = field(default=0, repr=False)

    @property
    def addr(self) -> tuple:
        return (self.ip, self.port)

    def describe(self) -> dict:
        return {"id": self.node_id, "address": f"{self.ip}:{self.port}",
                "replication": f"{self.ip}:{self.repl_port}",
                "up": self.up, "generation": self.generation,
                "stepping": self.stepping}


def parse_peers(spec: str) -> list[Peer]:
    """`host:port[/replport],...` in node-id order."""
    peers = []
    for i, part in enumerate(filter(None, (p.strip()
                                           for p in spec.split(",")))):
        body, _, repl = part.partition("/")
        host, _, port = body.rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]
        if not host or not port:
            raise ValueError(f"bad cluster peer {part!r} "
                             "(want host:port[/replport])")
        p = int(port)
        peers.append(Peer(node_id=i, ip=host, port=p,
                          repl_port=int(repl) if repl else p + 1))
    return peers


def self_node_id() -> int:
    """jax dist process id when the distributed job is up, else
    VPROXY_TPU_CLUSTER_SELF (default 0)."""
    try:
        import jax
        if jax.process_count() > 1:
            return jax.process_index()
    except Exception:
        pass
    return int(os.environ.get(
        "VPROXY_TPU_CLUSTER_SELF",
        os.environ.get("VPROXY_TPU_DIST_PROCID", "0") or "0"))


class Membership:
    """UDP heartbeat loop + peer table. One daemon thread owns the
    socket (send + recv + hysteresis tick); the peer table is read
    under a lock by the DNS/metrics/command surfaces."""

    def __init__(self, self_id: int, peers: list[Peer],
                 hb_ms: int = 0, up: int = 0, down: int = 0,
                 meta: Optional[Callable[[], dict]] = None):
        if not any(p.node_id == self_id for p in peers):
            raise ValueError(f"self id {self_id} not in peers "
                             f"{[p.node_id for p in peers]}")
        self.self_id = self_id
        self.hb_ms = hb_ms or HB_MS
        self.up_n = up or UP_N
        self.down_n = down or DOWN_N
        self._meta = meta
        self._lock = threading.Lock()
        self.peers: dict[int, Peer] = {p.node_id: p for p in peers}
        me = self.peers[self_id]
        me.up = True  # this node is always a member of its own view
        me.stepping = True
        self.incarnation = time.time()
        me.incarnation = self.incarnation
        self._listeners: list[Callable[[Peer, bool], None]] = []
        self._step_handler: Optional[Callable[[dict, int], None]] = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((me.ip, me.port))
        if me.port == 0:
            me.port = self._sock.getsockname()[1]
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # maglev steering table over the UP peer set (rules/maglev.py):
        # (up-id-key, live peers, table, names, last remap fraction).
        # Rebuilt on peer edges — on the membership thread, never a
        # serving one (the DNS steerer only reads the published tuple).
        # The build lock keeps a reader that races a peer edge from
        # publishing a table built against the pre-edge live set over
        # the membership thread's fresh one
        self._maglev: Optional[tuple] = None
        self._maglev_lock = threading.Lock()

    # ------------------------------------------------------------- control

    def start(self) -> None:
        if self._thread is not None:
            return
        try:
            self._maglev_table()  # pre-build: first steer never pays it
        except Exception:
            _log.error("maglev steering prebuild failed", exc=True)
        self._thread = threading.Thread(target=self._run,
                                        name="cluster-membership",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stopped = True
        try:
            self._sock.close()
        except OSError:
            pass

    def on_peer_change(self, cb: Callable[[Peer, bool], None]) -> None:
        self._listeners.append(cb)

    def set_step_handler(self, cb: Optional[Callable[[dict, int], None]]) -> None:
        """cb(msg, peer_id) for `t=step` datagrams (cluster/submit.py)."""
        self._step_handler = cb

    def add_peer(self, node_id: int, ip: str, port: int,
                 repl_port: int = 0) -> Peer:
        with self._lock:
            if node_id in self.peers:
                raise ValueError(f"cluster-node {node_id} already exists")
            p = Peer(node_id=node_id, ip=ip, port=port,
                     repl_port=repl_port or port + 1)
            self.peers[node_id] = p
        return p

    def remove_peer(self, node_id: int) -> None:
        if node_id == self.self_id:
            raise ValueError("cannot remove this node from its own view")
        with self._lock:
            if node_id not in self.peers:
                raise KeyError(node_id)
            p = self.peers.pop(node_id)
        if p.up:
            self._notify(p, False)

    # -------------------------------------------------------------- views

    def live_peers(self) -> list[Peer]:
        with self._lock:
            return [p for p in self.peers.values() if p.up]

    def peer_list(self) -> list[Peer]:
        with self._lock:
            return sorted(self.peers.values(), key=lambda p: p.node_id)

    def leader_id(self) -> int:
        """Lowest live node id (this node always counts as live)."""
        return min(p.node_id for p in self.live_peers())

    def is_leader(self) -> bool:
        return self.leader_id() == self.self_id

    def peers_up(self) -> int:
        return len(self.live_peers())

    def dns_addrs(self) -> list[bytes]:
        """UP peer addresses for the cluster service name. Never empty:
        this node is always in its own view, so the last peer is never
        evicted from the DNS answers."""
        from ..utils.ip import parse_ip
        out = []
        for p in self.live_peers():
            try:
                out.append(parse_ip(p.ip))
            except (OSError, ValueError):
                continue
        if not out:
            out.append(parse_ip(self.peers[self.self_id].ip))
        return out

    def max_generation_seen(self) -> int:
        with self._lock:
            return max((p.generation for p in self.peers.values()),
                       default=0)

    def peer_analytics(self) -> dict:
        """{node_id: gossiped top-K summary} for every UP peer that has
        sent one (this node excluded — its live sketches are merged
        directly, utils/sketch.fleet_table)."""
        with self._lock:
            return {p.node_id: p.hh for p in self.peers.values()
                    if p.up and p.node_id != self.self_id
                    and p.hh is not None}

    def peer_policing(self) -> dict:
        """{node_id: gossiped enforcement summary} for every UP peer —
        the merge input for policing/engine.ingest_peer_tables (local
        entries always win there; dead peers' tables age out by TTL)."""
        with self._lock:
            return {p.node_id: p.police for p in self.peers.values()
                    if p.up and p.node_id != self.self_id
                    and p.police is not None}

    # ------------------------------------------------- maglev steering

    def _maglev_table(self) -> tuple:
        """The steering table over the CURRENT up set — rebuilt only
        when the up-id set changed (one atomic tuple publish; readers
        on serving threads never pay the build). Peer identity is
        id:ip:port, so a peer keeps its permutation — and its clients —
        across everyone else's churn."""
        live = sorted(self.live_peers(), key=lambda p: p.node_id)
        key = tuple(p.node_id for p in live)
        cur = self._maglev
        if cur is not None and cur[0] == key:
            return cur
        with self._maglev_lock:
            # re-derive INSIDE the lock: a concurrent builder may have
            # published while this one waited, and the up set may have
            # moved again — building from the pre-lock snapshot could
            # publish a dead peer over the fresh table
            live = sorted(self.live_peers(), key=lambda p: p.node_id)
            key = tuple(p.node_id for p in live)
            cur = self._maglev
            if cur is not None and cur[0] == key:
                return cur
            return self._maglev_build(live, key, cur)

    def _maglev_build(self, live, key, cur) -> tuple:
        from ..rules import maglev as MG
        m = int(os.environ.get("VPROXY_TPU_CLUSTER_MAGLEV_M", "0")) \
            or MG.DEFAULT_M
        names = [f"{p.node_id}:{p.ip}:{p.port}" for p in live]
        tab = MG.build_table([(n, 1) for n in names], m)
        remap = MG.remap_fraction(
            cur[2] if cur else None, tab,
            cur[3] if cur else None, names) if cur else 0.0
        built = (key, live, tab, names, remap)
        self._maglev = built
        from ..utils import events
        if cur is not None:
            events.record(
                "cluster_steer_rebuild",
                f"peer steering table rebuilt over {len(live)} UP peers: "
                f"{remap:.1%} of client affinities moved",
                peers=len(live), remap=round(remap, 4))
        return built

    def steer_addrs(self, client_ip: bytes) -> list[bytes]:
        """UP peer addresses with the Maglev-picked owner FIRST (DNS
        clients use the first A record; the rest ride along as
        fallback). One FNV over the client address + one slot load —
        and a peer join/death moves only ~1/N of client affinities,
        where the old id-ordered answer pinned every client to the
        lowest id and a resize reshuffled arbitrarily. Never empty
        (this node is always in its own up set)."""
        from ..rules import maglev as MG
        from ..utils.ip import parse_ip
        _key, live, tab, _names, _remap = self._maglev_table()
        addrs = []
        for p in live:
            try:
                addrs.append(parse_ip(p.ip))
            except (OSError, ValueError):
                addrs.append(None)  # hold index alignment with the table
        i = MG.pick(tab, client_ip)  # source affinity: address only
        out = []
        if 0 <= i < len(addrs) and addrs[i] is not None:
            out.append(addrs[i])
        out.extend(a for j, a in enumerate(addrs)
                   if a is not None and j != i)
        if not out:
            out.append(parse_ip(self.peers[self.self_id].ip))
        return out

    def steer_peer(self, key: bytes) -> Optional[Peer]:
        """Maglev-consistent UP peer for an arbitrary steering key (the
        cluster plane's generic client-steering primitive)."""
        from ..rules import maglev as MG
        _k, live, tab, _n, _r = self._maglev_table()
        i = MG.pick(tab, key)
        return live[i] if 0 <= i < len(live) else None

    def steer_status(self) -> dict:
        """GET /cluster: the steering table's shape + last-resize churn."""
        cur = self._maglev
        if cur is None:
            return {"built": False}
        return {"built": True, "m": int(len(cur[2])), "peers": len(cur[1]),
                "last_remap": round(cur[4], 4)}

    # ---------------------------------------------------------- main loop

    def send_step(self, payload: dict) -> None:
        """Broadcast a step-barrier datagram to every OTHER peer (the
        barrier in cluster/submit.py rides the membership socket)."""
        payload = dict(payload)
        payload["t"] = "step"
        payload["id"] = self.self_id
        data = json.dumps(payload, separators=(",", ":")).encode()
        with self._lock:
            others = [p.addr for p in self.peers.values()
                      if p.node_id != self.self_id]
        for addr in others:
            try:
                self._sock.sendto(data, addr)
            except OSError:
                pass

    def _heartbeat_payload(self) -> bytes:
        hb = {"t": "hb", "id": self.self_id, "inc": self.incarnation,
              "gen": 0, "stepping": True}
        if self._meta is not None:
            try:
                hb.update(self._meta())
            except Exception:
                pass
        me = self.peers[self.self_id]
        me.generation = int(hb.get("gen", 0))
        me.stepping = bool(hb.get("stepping", True))
        return json.dumps(hb, separators=(",", ":")).encode()

    def _run(self) -> None:
        next_tick = time.monotonic()
        while not self._stopped:
            now = time.monotonic()
            if now >= next_tick:
                self._send_heartbeats()
                self._tick()
                next_tick = now + self.hb_ms / 1000.0
            timeout = max(0.0, next_tick - time.monotonic())
            try:
                r, _, _ = select.select([self._sock], [], [], timeout)
            except (OSError, ValueError):
                return  # socket closed
            if not r:
                continue
            try:
                data, addr = self._sock.recvfrom(65536)
            except OSError:
                continue
            self._on_datagram(data, addr)

    def _send_heartbeats(self) -> None:
        data = self._heartbeat_payload()
        with self._lock:
            others = [p.addr for p in self.peers.values()
                      if p.node_id != self.self_id]
        for addr in others:
            try:
                self._sock.sendto(data, addr)
            except OSError:
                pass

    def poke(self) -> None:
        """Send an immediate out-of-cycle heartbeat: stepping-flag and
        generation transitions (attach/degrade/rejoin) must reach peers
        NOW, not a heartbeat period later — the step barrier reads
        those flags to build its wait set (cluster/submit.py), and a
        stale flag either wedges peers on a host that stopped stepping
        or hides one that just started."""
        self._send_heartbeats()

    def _on_datagram(self, data: bytes, addr: tuple) -> None:
        try:
            msg = json.loads(data)
            peer_id = int(msg["id"])
        except (ValueError, KeyError, TypeError):
            return
        if msg.get("t") == "step":
            h = self._step_handler
            if h is not None:
                h(msg, peer_id)
            return
        if msg.get("t") != "hb":
            return
        if failpoint.hit("cluster.peer.drop", f"from={peer_id} {addr[0]}"):
            return
        with self._lock:
            p = self.peers.get(peer_id)
            if p is None:
                return
            inc = float(msg.get("inc", 0.0))
            if p.incarnation and inc > p.incarnation and p.up:
                # the peer restarted between two of our ticks: treat the
                # new incarnation as a fresh node (hysteresis restarts)
                p.up = False
                p._up_cnt = p._down_cnt = 0
                restarted: Optional[Peer] = p
            else:
                restarted = None
            p.incarnation = inc
            p.generation = int(msg.get("gen", 0))
            p.stepping = bool(msg.get("stepping", False))
            hh = msg.get("hh")
            if isinstance(hh, dict):  # analytics top-K rides heartbeats
                p.hh = hh
            pol = msg.get("police")
            if isinstance(pol, dict):  # enforcement tables ride them too
                p.police = pol
            p.last_rx = time.monotonic()
            p._rx_since_tick += 1
        if restarted is not None:
            self._notify(restarted, False)

    def _tick(self) -> None:
        """Per-period hysteresis, the ServerGroup health-check idiom:
        heartbeats seen this period count as one success, silence as
        one failure; edges at up_n/down_n consecutive periods."""
        edges: list[tuple[Peer, bool]] = []
        with self._lock:
            for p in self.peers.values():
                if p.node_id == self.self_id:
                    continue
                if p._rx_since_tick > 0:
                    p._rx_since_tick = 0
                    p._up_cnt += 1
                    p._down_cnt = 0
                    if not p.up and p._up_cnt >= self.up_n:
                        p.up = True
                        edges.append((p, True))
                else:
                    p._down_cnt += 1
                    p._up_cnt = 0
                    if p.up and p._down_cnt >= self.down_n:
                        p.up = False
                        p.stepping = False
                        edges.append((p, False))
        for p, up in edges:
            self._notify(p, up)

    def _notify(self, peer: Peer, up: bool) -> None:
        from ..utils import events
        events.record("peer_up" if up else "peer_down",
                      f"cluster node {peer.node_id} ({peer.ip}:{peer.port}) "
                      + ("UP" if up else "DOWN"),
                      node=peer.node_id, generation=peer.generation)
        _log.info(f"cluster node {peer.node_id} "
                  + ("UP" if up else "DOWN"))
        try:
            # rebuild the steering table FIRST, before the listeners (a
            # replicator callback can block on I/O): a DNS query racing
            # that window would otherwise see the stale up-set key and
            # pay the full 65537-slot build on its serving loop
            self._maglev_table()
        except Exception:
            _log.error("maglev steering rebuild failed", exc=True)
        for cb in list(self._listeners):
            try:
                cb(peer, up)
            except Exception:
                _log.error("peer-change listener failed", exc=True)
