"""Cluster plane — membership, rule replication, step-synchronized SPMD
serving (ROADMAP north star: one fleet serving as one proxy).

Three layers, one node object:

* membership.py — UDP heartbeats + hysteresis up/down edges; feeds
  DNS-as-LB (the cluster service name answers only healthy peers) and
  elects the leader (lowest live node id).
* replicate.py — the leader ships generation-tagged command-log
  snapshots/increments over TCP; followers install a generation only
  after the engine-table checksum matches the leader's.
* submit.py — the step clock: per-host classify queues drain into
  fixed-shape padded batches on a fleet-wide barrier; barrier timeout
  degrades a host to the inline host-index path, re-joining on the
  next rule generation.

Boot: `ClusterNode.boot_from_env(app)` (main.py) when
VPROXY_TPU_CLUSTER_PEERS is set. Operate: `add/remove/list
cluster-node` (control/command.py), `GET /cluster` (HTTP controller +
inspection server), `vproxy_cluster_*` metrics (utils/metrics.py).
"""
from __future__ import annotations

import os
import threading
from typing import Optional

from ..utils.log import Logger
from .membership import (Membership, Peer, cluster_service_name,
                         parse_peers, self_node_id)
from .replicate import Replicator, cluster_checksum
from .submit import StepLoop

_log = Logger("cluster")

__all__ = ["ClusterNode", "Membership", "Replicator", "StepLoop", "Peer",
           "cluster_checksum", "cluster_service_name", "dns_peer_addrs",
           "parse_peers", "self_node_id"]


class ClusterNode:
    """One per process; ties membership + replication + the step loop
    and feeds the metrics/DNS/command surfaces."""

    _instance: Optional["ClusterNode"] = None
    _ilock = threading.Lock()

    def __init__(self, app, self_id: int, peers: list[Peer],
                 hb_ms: int = 0, poll_ms: int = 0):
        self.app = app
        self.self_id = self_id
        self.membership = Membership(self_id, peers, hb_ms=hb_ms,
                                     meta=self._hb_meta)
        me = self.membership.peers[self_id]
        self.replicator = Replicator(app, self.membership, me.ip,
                                     me.repl_port, poll_ms=poll_ms)
        me.repl_port = self.replicator.bind_port
        self.submit: Optional[StepLoop] = None
        self.replicator.on_generation(self._on_generation)
        with ClusterNode._ilock:
            ClusterNode._instance = self

    # ------------------------------------------------------------- wiring

    def _hb_meta(self) -> dict:
        meta = {"gen": self.replicator.generation,
                "stepping": self.submit is not None
                and not self.submit.degraded}
        # gossip this node's analytics top-K (utils/sketch): any node's
        # GET /analytics can then render the fleet-merged top table.
        # ALWAYS present (possibly {}): an empty summary must OVERWRITE
        # the peer's stored view, or a node whose burst aged out of its
        # windows would haunt the fleet table forever
        from ..utils import sketch
        meta["hh"] = sketch.gossip_summary() if sketch.enabled() else {}
        # gossip the local-origin policing enforcement table the same
        # way (policing/engine): a crowd detected by one node is shed by
        # every node within a heartbeat period. Same ALWAYS-present
        # rule: {} overwrites, so a policy removal propagates too.
        from ..policing import engine as policing
        meta["police"] = (policing.gossip_summary()
                          if policing.enabled() else {})
        # ingest is piggybacked on the heartbeat TX tick (no extra
        # thread): merge every UP peer's last-gossiped table into the
        # local engine (local entries win; peer entries age out by TTL)
        policing.ingest_peer_tables(self.membership.peer_policing())
        return meta

    def fleet_analytics(self) -> dict:
        """The fleet-merged top table: this node's live sketches +
        every UP peer's gossiped summary."""
        from ..utils import sketch
        return sketch.fleet_table(self.membership.peer_analytics())

    def fleet_policing(self) -> dict:
        """Per-node policed-action attribution for GET /analytics:
        this node's live counts + nothing gossiped yet beyond tables —
        peers report their own counts on their own /analytics; here we
        expose which peers are enforcing (table seq) next to ours."""
        from ..policing import engine as policing
        mine = policing.default().policed_by_node()
        peers = {str(nid): {"seq": (summ or {}).get("seq", 0)}
                 for nid, summ in self.membership.peer_policing().items()}
        return {"self": mine, "peers": peers}

    def _on_generation(self, gen: int) -> None:
        # new rule generation == new step epoch: every host resets its
        # barrier to step 0 of epoch `gen`; a degraded host re-joins
        if self.submit is not None:
            self.submit.rejoin(gen)
            self.membership.poke()  # epoch/stepping flip reaches peers now

    def attach_submit(self, matcher, **kw) -> StepLoop:
        """Attach (and start) the step-synchronized submit loop over
        `matcher` (typically an Upstream's HintMatcher on the multi-host
        mesh)."""
        if self.submit is not None:
            self.submit.stop()
        kw.setdefault("on_degrade", self.membership.poke)
        self.submit = StepLoop(matcher, self.membership, **kw)
        self.submit.start()
        # stepping=true must reach peers before their next barrier, not
        # a heartbeat period later (the flag gates their wait sets)
        self.membership.poke()
        return self.submit

    def on_command(self, line: str) -> None:
        """Command.execute hook: a successful replicated-type mutation
        ran on this node."""
        self.replicator.on_command(line)

    # ------------------------------------------------------------ surface

    def stat(self, key: str) -> float:
        if key == "peers_up":
            return float(self.membership.peers_up())
        if key == "generation":
            return float(self.replicator.generation)
        if key == "generation_lag":
            return float(self.replicator.generation_lag())
        if key == "steps_total":
            return 0.0 if self.submit is None \
                else float(self.submit.steps_total)
        if key == "barrier_stalls_total":
            return 0.0 if self.submit is None \
                else float(self.submit.barrier_stalls)
        return 0.0

    def status(self) -> dict:
        """The `GET /cluster` / `list-detail cluster-node` view."""
        d = {"enabled": True, "self": self.self_id,
             "leader": self.membership.leader_id(),
             "is_leader": self.membership.is_leader(),
             "service": f"{cluster_service_name()}.vproxy.local",
             "steering": self.membership.steer_status(),
             "peers": [p.describe() for p in self.membership.peer_list()]}
        d.update(self.replicator.status())
        d["step"] = None if self.submit is None else self.submit.status()
        return d

    def close(self) -> None:
        if self.submit is not None:
            self.submit.stop()
        self.replicator.close()
        self.membership.close()
        with ClusterNode._ilock:
            if ClusterNode._instance is self:
                ClusterNode._instance = None

    # --------------------------------------------------------------- boot

    @classmethod
    def boot_from_env(cls, app) -> Optional["ClusterNode"]:
        """VPROXY_TPU_CLUSTER_PEERS=host:port[/replport],... — node id =
        list position; this node's id from jax.distributed /
        VPROXY_TPU_CLUSTER_SELF. Returns None when unset (single-host
        deployments never pay for the cluster plane)."""
        spec = os.environ.get("VPROXY_TPU_CLUSTER_PEERS", "")
        if not spec.strip():
            return None
        peers = parse_peers(spec)
        self_id = self_node_id()
        node = cls(app, self_id, peers)
        node.membership.start()
        node.replicator.start()
        _log.info(f"cluster node {self_id}/{len(peers)} up "
                  f"(hb {node.membership.hb_ms}ms, repl port "
                  f"{node.replicator.bind_port})")
        return node


def dns_peer_addrs(client_ip: Optional[bytes] = None) -> Optional[list]:
    """Healthy peer addresses for the cluster service name, or None when
    no cluster is booted (dns/server.py falls through). With a client
    address the answer is Maglev-STEERED: the picked peer rides first
    (clients use the first A record), so a peer join/death mid-traffic
    moves only ~1/N of client affinities instead of reshuffling the
    whole fleet (membership.steer_addrs; docs/cluster.md)."""
    node = ClusterNode._instance
    if node is None:
        return None
    if client_ip is not None:
        return node.membership.steer_addrs(client_ip)
    return node.membership.dns_addrs()
