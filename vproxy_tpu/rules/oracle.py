"""Pure-Python reference matchers — correctness oracle + host fallback.

These replicate, bit-for-bit, the reference's linear-scan semantics:
Hint.matchLevel (Hint.java:92-160), Upstream.searchForGroup
(Upstream.java:187-198), SecurityGroup.allow (SecurityGroup.java:30-45),
RouteTable.lookup (RouteTable.java:44-59 — already on the IR class).

They double as the `matcher=host` provider behind the same seam as the
JAX/TPU matcher (`matcher=jax`), mirroring the reference's -Dvfd SPI.
"""
from __future__ import annotations

from typing import Optional, Sequence

from .ir import AclRule, Hint, HintRule, Proto

HOST_SHIFT = 10
HOST_EXACT = 3
HOST_SUFFIX = 2
HOST_WILDCARD = 1
URI_MAX = 1023
URI_WILDCARD = 1


def match_level(hint: Hint, rule: HintRule) -> int:
    """Hint.matchLevel against one rule's annotations."""
    if rule.is_empty():
        return 0
    if hint.port != 0 and rule.port != 0 and hint.port != rule.port:
        return 0

    host_level = 0
    if rule.host is not None and hint.host is not None:
        if hint.host == rule.host:
            host_level = HOST_EXACT
        elif hint.host.endswith("." + rule.host):
            host_level = HOST_SUFFIX
        elif rule.host == "*":
            host_level = HOST_WILDCARD

    uri_level = 0
    if rule.uri is not None and hint.uri is not None:
        if hint.uri == rule.uri:
            uri_level = len(hint.uri) + URI_WILDCARD
        elif hint.uri.startswith(rule.uri):
            uri_level = len(rule.uri) + URI_WILDCARD
        elif rule.uri == "*":
            uri_level = URI_WILDCARD
        uri_level = min(uri_level, URI_MAX)

    return (host_level << HOST_SHIFT) + uri_level


def search(rules: Sequence[HintRule], hint: Hint) -> int:
    """Upstream.searchForGroup: strictly-greater max, earliest wins.
    Returns the matching rule index, or -1 when nothing matches."""
    best_level = 0
    best = -1
    for i, r in enumerate(rules):
        lv = match_level(hint, r)
        if lv > best_level:
            best_level = lv
            best = i
    return best


def acl_allow(rules: Sequence[AclRule], default_allow: bool,
              proto: Proto, addr: bytes, port: int) -> bool:
    """SecurityGroup.allow: first matching rule in order wins."""
    sub = [r for r in rules if r.protocol == proto]
    if not sub:
        return default_allow
    for r in sub:
        if r.match(addr, port):
            return r.allow
    return default_allow


def acl_first_match(rules: Sequence[AclRule], proto: Proto,
                    addr: bytes, port: int) -> int:
    """Index (within the proto-filtered order) of the first matching rule,
    or -1. Helper for table-compiler parity tests."""
    for i, r in enumerate(r for r in rules if r.protocol == proto):
        if r.match(addr, port):
            return i
    return -1
