"""Maglev consistent-hash backend selection (Eisenbud et al., NSDI'16).

The table compiler behind every plane that picks a destination:

* **build_table()** — the permutation-fill algorithm: each backend gets
  a (offset, skip) permutation of the M (prime) slots from two FNV-1a
  hashes of its identity, and backends claim slots in a weighted turn
  order (the WRR subtract-sum sequence over the weights, so slot
  ownership tracks weight share to within ~1/M·N). The result is an
  int32 slot→backend lookup table with the Maglev disruption bound:
  adding/removing one backend moves ≈ its weight share of slots (plus a
  small permutation-churn tail), never an arbitrary reshuffle.
* **flow_hash()/pick()** — the ONE hash contract shared by all three
  planes (this module, the C lanes/flow cache in native/vtl.cpp, and
  the cluster steerer): FNV-1a 64 over the raw address bytes, plus the
  port as two big-endian bytes when per-connection spread is wanted
  (`port=None` = source affinity: one backend per client address).
  tests/test_maglev.py proves python == C == device picks bit-exactly.
* **MaglevMatcher** — the JAX-engine plane: the table rides the same
  double-buffered generation machinery as the hint/cidr matchers
  (rules/engine.py TableInstaller — standby build + one atomic publish,
  installs never stall serving) and `dispatch_snap` answers a batch of
  addresses with a jitted device gather, so a classify dispatch can
  return backend picks alongside match verdicts from one snapshot pair.

Metrics (utils/metrics): vproxy_maglev_table_builds_total,
vproxy_maglev_build_ms (histogram), vproxy_maglev_remap_fraction (the
last build's fraction of slots that changed owner — the churn a resize
actually caused).

Knobs: VPROXY_TPU_MAGLEV_M (65537 — engine/cluster tables),
VPROXY_TPU_MAGLEV_GROUP_M (4099 — per-ServerGroup tables, rebuilt on
membership edges and so sized for build cost over precision; both must
be prime or the permutations do not cover the table).
"""
from __future__ import annotations

import math
import os
import time
from typing import Optional, Sequence

import numpy as np

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1

DEFAULT_M = int(os.environ.get("VPROXY_TPU_MAGLEV_M", "65537"))
GROUP_M = int(os.environ.get("VPROXY_TPU_MAGLEV_GROUP_M", "4099"))

_TURN_CAP = 4096  # weighted turn-order bound (weights renormalized past it)


def fnv64(data: bytes) -> int:
    """FNV-1a 64 — the shared hash of every maglev plane (the C side in
    native/vtl.cpp implements the same loop; parity is tested)."""
    h = FNV64_OFFSET
    for b in data:
        h = ((h ^ b) * FNV64_PRIME) & _MASK64
    return h


def flow_hash(ip: bytes, port: Optional[int] = None) -> int:
    """The flow key hash: raw address bytes (4 for v4, 16 for v6, as
    utils/ip.parse_ip produces and as they sit in a sockaddr), plus the
    port as two big-endian bytes when per-connection spread is wanted.
    port=None is SOURCE AFFINITY: every connection from one client
    address lands on one backend."""
    if port is None:
        return fnv64(ip)
    return fnv64(ip + bytes((port >> 8 & 0xFF, port & 0xFF)))


def flow_slots(m: int, ips: Sequence[bytes],
               ports: Optional[Sequence[int]] = None) -> np.ndarray:
    """Host-side Maglev table slots for a batch — THE one copy of the
    slot-hash contract every pick plane (device gather, fused program,
    host pick) derives from; a per-element None port is source
    affinity. -> int64 [len(ips)]."""
    return np.fromiter(
        (flow_hash(ip, None if ports is None else ports[i]) % m
         for i, ip in enumerate(ips)), np.int64, len(ips))


def _turns(weights: Sequence[int]) -> list[int]:
    """Weighted turn order for the fill loop: the reference's
    subtract-sum WRR sequence (components/lanes._wrr_seq semantics),
    gcd-reduced and capped — each backend takes turns claiming slots in
    proportion to its weight, which is what makes slot ownership track
    weight share."""
    if not weights:
        return []
    if len(set(weights)) == 1:
        return list(range(len(weights)))
    g = 0
    for w in weights:
        g = math.gcd(g, w)
    if g > 1:
        weights = [w // g for w in weights]
    total = sum(weights)
    if total > _TURN_CAP:
        weights = [max(1, (w * _TURN_CAP) // total) for w in weights]
        total = sum(weights)
    if total > _TURN_CAP:
        return list(range(len(weights)))
    cur = list(weights)
    seq: list[int] = []
    while True:
        idx = max(range(len(cur)), key=lambda i: (cur[i], -i))
        seq.append(idx)
        cur[idx] -= total
        if all(w == 0 for w in cur):
            return seq
        for i in range(len(cur)):
            cur[i] += weights[i]


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in range(2, int(n ** 0.5) + 1):
        if n % p == 0:
            return False
    return True


def build_table(entries: Sequence[tuple[str, int]],
                m: Optional[int] = None) -> np.ndarray:
    """Compile the slot→backend lookup table.

    entries: (identity, weight) per backend, weight > 0; identity is
    whatever names the backend stably across rebuilds (ip:port for
    servers, node ids for cluster peers) — a backend keeps its
    permutation, and therefore most of its slots, across resizes.
    Returns int32[m]; every slot owned (m prime, skip ∈ [1, m-1], so
    each permutation covers the whole table). An empty entry list
    returns an all -1 table.
    """
    if m is None:
        m = DEFAULT_M
    if m < 3 or not _is_prime(m):
        raise ValueError(f"maglev table size {m} must be a prime >= 3")
    t0 = time.monotonic()
    n = len(entries)
    # plain-list fill: numpy scalar loads/stores are ~30x a list's in
    # this loop, and group-size builds run under the group lock on a
    # health edge — the list fill keeps that window ~100µs, not ~5ms
    tab = [-1] * m
    if n:
        cur, skips = [], []
        for name, _w in entries:
            b = name.encode() if isinstance(name, str) else bytes(name)
            cur.append(fnv64(b"o:" + b) % m)
            skips.append(fnv64(b"s:" + b) % (m - 1) + 1)
        turns = _turns([max(1, int(w)) for _, w in entries])
        filled = 0
        while filled < m:
            for i in turns:
                # next unclaimed slot in backend i's permutation —
                # walked incrementally (slot += skip mod m): slots
                # behind cur[i] were claimed when this permutation
                # passed them, so the next free one is always ahead
                sl = cur[i]
                sk = skips[i]
                while tab[sl] >= 0:
                    sl += sk
                    if sl >= m:
                        sl -= m
                tab[sl] = i
                sl += sk
                cur[i] = sl - m if sl >= m else sl
                filled += 1
                if filled >= m:
                    break
    table = np.asarray(tab, np.int32)
    _builds_total().incr()
    _build_ms().observe((time.monotonic() - t0) * 1e3)
    return table


def remap_fraction(old: Optional[np.ndarray], new: np.ndarray,
                   old_names: Optional[Sequence[str]] = None,
                   new_names: Optional[Sequence[str]] = None) -> float:
    """Fraction of slots whose OWNER changed between two builds — the
    churn a resize actually caused. With name lists the comparison is
    by identity (indexes shift when a backend leaves); without, by raw
    index (valid only for same-membership rebuilds). Records the
    vproxy_maglev_remap_fraction gauge."""
    if old is None or len(old) != len(new):
        f = 1.0
    else:
        if old_names is not None and new_names is not None:
            o = np.array([old_names[i] if 0 <= i < len(old_names) else ""
                          for i in old], dtype=object)
            nw = np.array([new_names[i] if 0 <= i < len(new_names) else ""
                           for i in new], dtype=object)
            f = float(np.mean(o != nw))
        else:
            f = float(np.mean(old != new))
    _remap_gauge().set(f)
    return f


def pick(table: np.ndarray, ip: bytes, port: Optional[int] = None) -> int:
    """O(1) host-side pick: slot = flow_hash % M. -1 = empty table."""
    return int(table[flow_hash(ip, port) % len(table)])


# ------------------------------------------------------------ metrics

def _builds_total():
    from ..utils.metrics import GlobalInspection
    return GlobalInspection.get().get_counter(
        "vproxy_maglev_table_builds_total")


def _build_ms():
    # pre-registered (reservoir config included) in
    # GlobalInspection.__init__ — this resolves to that instance
    from ..utils.metrics import GlobalInspection
    return GlobalInspection.get().get_histogram("vproxy_maglev_build_ms")


def _remap_gauge():
    from ..utils.metrics import GlobalInspection
    return GlobalInspection.get().get_gauge("vproxy_maglev_remap_fraction")


# ------------------------------------------------- JAX engine plane

_take_jit = None


def _device_take(dev_table, slots: np.ndarray):
    """Jitted device gather: the maglev pick column a batched dispatch
    returns alongside its match verdicts."""
    global _take_jit
    import jax
    import jax.numpy as jnp
    if _take_jit is None:
        _take_jit = jax.jit(lambda t, s: jnp.take(t, s, mode="clip"))
    return _take_jit(dev_table, slots)


class MaglevMatcher:
    """Device-backed per-generation Maglev table, published through the
    SAME double-buffer machinery as the hint/cidr matchers: set_backends
    enqueues on the process-wide TableInstaller (standby build + device
    upload off the mutation path, then ONE atomic pub-tuple swap), so a
    table rebuild never stalls a serving dispatch."""

    _kind = "maglev"

    def __init__(self, entries: Sequence[tuple[str, int]] = (),
                 m: Optional[int] = None, payload=None):
        self.m = m or DEFAULT_M
        self._entries: list = list(entries)
        self._payload = payload
        self.generation = 0
        self.last_remap = 0.0  # fraction of slots the last install moved
        # (np table, device table, entries, payload) — one atomic tuple
        # so a reader never pairs one generation's table with another's
        # entry list
        self._pub: tuple = (None, None, [], payload)
        self._recompile()
        from . import engine as E
        with E._gen_lock:
            E._MATCHERS.add(self)

    # ---------------------------------------------------------- install

    def set_backends(self, entries: Sequence[tuple[str, int]],
                     payload=None, wait: bool = True) -> None:
        """Install a new backend generation via the background
        TableInstaller (see HintMatcher.set_rules — same standby-swap
        contract: dispatchers never wait, wait=True gives the caller
        read-your-writes)."""
        from .engine import TableInstaller
        t = TableInstaller.get().submit(self, (list(entries), payload))
        if wait:
            t.ev.wait()
            if t.exc is not None:
                raise t.exc

    def _install(self, args: tuple) -> None:
        entries, payload = args
        old = (self._entries, self._payload)
        self._entries = list(entries)
        self._payload = payload
        try:
            self._recompile()
        except BaseException:
            self._entries, self._payload = old
            raise

    def _recompile(self) -> None:
        from . import engine as E
        tab = build_table(self._entries, self.m)
        prev = self._pub[0]
        if prev is None or not self._pub[2]:
            # first build, or empty->populated: an all -1 table owned
            # no flows, so "100% of slots changed owner" would misread
            # a bring-up as total churn
            self.last_remap = 0.0
        else:
            prev_names = [name for name, _ in self._pub[2]] or None
            names = [name for name, _ in self._entries] or None
            self.last_remap = remap_fraction(prev, tab, prev_names, names)
        import jax
        dev = jax.device_put(tab)
        E._sync_standby({"table": dev})
        time.sleep(0)  # preemption point between compile and publish
        self._pub = (tab, dev, list(self._entries), self._payload)
        self.generation += 1
        with E._gen_lock:
            E._GENERATION[0] += 1

    def published_table_bytes(self) -> int:
        dev = self._pub[1]
        return int(getattr(dev, "nbytes", 0)) if dev is not None else 0

    # ------------------------------------------------------------ reads

    def snapshot(self) -> tuple:
        return self._pub

    @staticmethod
    def snap_payload(snap: tuple):
        return snap[3]

    def size(self) -> int:
        return len(self._pub[2])

    def checksum(self) -> int:
        import zlib
        return zlib.crc32(
            "\n".join(f"{n}:{w}" for n, w in self._pub[2]).encode())

    def pick_one(self, ip: bytes, port: Optional[int] = None) -> int:
        return self.pick_snap(self._pub, ip, port)

    def pick_snap(self, snap: tuple, ip: bytes,
                  port: Optional[int] = None) -> int:
        tab = snap[0]
        if tab is None or not snap[2]:
            return -1
        return pick(tab, ip, port)

    def dispatch_snap(self, snap: tuple, ips: Sequence[bytes],
                      ports: Optional[Sequence[int]] = None):
        """Batched device picks against one snapshotted generation
        (async device array; np.asarray() to block). Slots are hashed
        host-side — the same python-int FNV path the encoders use — and
        the gather runs jitted on the device holding the table."""
        tab, dev = snap[0], snap[1]
        if tab is None or not snap[2] or not len(ips):
            return np.full(len(ips), -1, np.int32)
        slots = flow_slots(len(tab), ips, ports)
        from . import engine as E
        E.note_launch()
        return _device_take(dev, slots)

    def match(self, ips: Sequence[bytes],
              ports: Optional[Sequence[int]] = None) -> np.ndarray:
        return np.asarray(self.dispatch_snap(self._pub, ips, ports))


def classify_and_pick(hint_matcher, maglev: MaglevMatcher, hints,
                      ips: Sequence[bytes],
                      ports: Optional[Sequence[int]] = None):
    """ONE batched dispatch answering BOTH questions: match verdicts
    from the hint matcher and backend picks from the maglev table
    against one atomic snapshot pair. On a "jax" matcher with packed
    tables published (the default) this is the FUSED one-launch
    program (rules/engine.fused_dispatch — PERF_NOTES round 12); other
    backends keep the pre-r12 overlapped two-dispatch submit. ->
    (verdicts int32[B], picks int32[B], hint_payload, maglev_payload)."""
    from . import engine as E
    hsnap = hint_matcher.snapshot()
    msnap = maglev.snapshot()
    out = E.fused_dispatch(hint_matcher, hsnap, maglev, msnap, hints,
                           ips, ports)
    if out is not None:
        arr = np.asarray(out)[: len(hints)]
        return (np.ascontiguousarray(arr[:, 0]),
                np.ascontiguousarray(arr[:, 1]),
                hint_matcher.snap_payload(hsnap),
                maglev.snap_payload(msnap))
    if getattr(hint_matcher, "backend", None) == "host":
        v = np.array([hint_matcher.oracle_snap(hsnap, h) for h in hints],
                     np.int32)
    else:
        v = hint_matcher.dispatch_snap(hsnap, hints)  # async device call
    p = maglev.dispatch_snap(msnap, ips, ports)       # overlaps the first
    return (np.asarray(v), np.asarray(p),
            hint_matcher.snap_payload(hsnap), maglev.snap_payload(msnap))


class FusedPair:
    """A (HintMatcher, MaglevMatcher) pair presented through the
    matcher interface the dispatch consumers speak (ClassifyService,
    cluster StepLoop): snapshot() is the atomic snapshot PAIR,
    dispatch_snap() is the fused one-launch (verdict, pick) batch, and
    index_snap() is the host fast lane (O(probes) hint index + O(1)
    maglev table read) for inline lone queries and degraded serving.
    Payloads ride as (hint_payload, maglev_payload)."""

    def __init__(self, hint_matcher, maglev: MaglevMatcher):
        self.hm = hint_matcher
        self.mm = maglev

    @property
    def backend(self) -> str:
        return self.hm.backend

    def size(self) -> int:
        return self.hm.size()

    @property
    def generation(self) -> int:
        return self.hm.generation + self.mm.generation

    def snapshot(self) -> tuple:
        return (self.hm.snapshot(), self.mm.snapshot())

    @staticmethod
    def snap_payload(snap: tuple):
        hsnap, msnap = snap
        return (hsnap[3], msnap[3])

    def index_snap(self, snap: tuple, payload: tuple) -> tuple:
        """(verdict, pick) from the host planes — the same winners as
        the fused program (index parity is tested at the matcher
        level; pick parity is the shared FNV contract)."""
        hsnap, msnap = snap
        hint, ip, port = payload
        return (self.hm.index_snap(hsnap, hint),
                self.mm.pick_snap(msnap, ip, port))

    def dispatch_snap(self, snap: tuple, payloads, pad_to=None,
                      sync: bool = True):
        """One fused launch for a batch of (hint, ip, port) payloads;
        async [cap, 2] device array. Falls back to the overlapped
        two-dispatch chain (host-side stack) when the fused path is
        unavailable for this snapshot."""
        from . import engine as E
        hsnap, msnap = snap
        hints = [p[0] for p in payloads]
        ips = [p[1] for p in payloads]
        ports = [p[2] for p in payloads]
        if all(p is None for p in ports):
            ports = None
        out = E.fused_dispatch(self.hm, hsnap, self.mm, msnap, hints,
                               ips, ports, pad_to=pad_to)
        if out is not None:
            return out
        v = self.hm.dispatch_snap(hsnap, hints, pad_to=pad_to,
                                  sync=sync)
        p = self.mm.dispatch_snap(msnap, ips, ports)
        return _LazyPairRows(v, p, len(hints))


class _LazyPairRows:
    """FusedPair's unfused-fallback result: both dispatches are already
    submitted (overlapped, async); the d2h sync happens when the
    CONSUMER np.asarray()s — preserving the service dispatcher's
    double-buffering (submit batch k+1 before pulling k) exactly like
    the fused path's async device array does."""

    def __init__(self, v, p, n: int):
        self._v, self._p, self._n = v, p, n

    def __array__(self, dtype=None, copy=None):
        n = self._n
        out = np.stack([np.asarray(self._v)[:n].astype(np.int32),
                        np.asarray(self._p)[:n].astype(np.int32)],
                       axis=1)
        return out if dtype is None else out.astype(dtype)
