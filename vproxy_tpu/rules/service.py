"""ClassifyService — the cross-connection micro-batching dispatch queue.

THE north-star mechanism (BASELINE.json): data-plane code (TcpLB hint
classify, SecurityGroup ACL gates, DNS qname lookup, switch routing)
never dispatches the device per connection; it enqueues a query with a
callback and the service coalesces everything that arrives while the
previous device batch is in flight into ONE dispatch ("natural
batching": the dispatch latency itself is the batch window, so the queue
adapts from batch=1 at idle to hundreds under load with no timer).

This replaces the reference's per-connection linear scans
(Upstream.searchForGroup Upstream.java:187-198, SecurityGroup.allow
SecurityGroup.java:30-45, RouteTable.lookup RouteTable.java:44) with a
shared per-process batching front to the compiled device tables.

Dispatch-path policy (mode = VPROXY_TPU_CLASSIFY, default "auto"):

* "auto"   — a flushed batch goes to the device when it has >= 2 queries
             (micro-batch) or the table is big (> SMALL_TABLE rules, the
             same threshold match_one uses); lone queries against small
             tables take the ~1us host oracle instead of a ~1ms device
             round trip.
* "device" — every flushed batch goes to the device (used by tests and
             benchmarks to force the TPU path end-to-end).
* "host"   — pure oracle (latency floor; also the correctness baseline).

Inline fast lane (VPROXY_TPU_INLINE_LONE, default on): in "auto" mode
a LONE query with nothing pending for its matcher is answered INLINE
on the submitting thread from the snapshot's O(probes) host index
(rules/index.py — exact, ~2-10us, winner bit-for-bit vs the oracle):
no dispatcher-thread hop, no device RTT. This is THE accept path —
accepts consult the host index directly on the accept loop, which is
what makes the BASELINE p99 < 50us accept-path contract meetable even
when the device sits behind a slow transport. Micro-batches (n >= 2)
always ride the device — batching is the whole point, and the device
stays the bulk path.

With the fast lane disabled (VPROXY_TPU_INLINE_LONE=0) the pre-round-6
latency-budget policy applies (VPROXY_TPU_CLASSIFY_BUDGET_US, default
5000; 0 = off): lone big-table queries ride the device while its EWMA
stays within budget and reroute to the host index once it blows it.
Either way the device EWMA is kept live by OFF-PATH probes: every
PROBE_EVERY-th inline-served lone query (rate-limited to one per
VPROXY_TPU_PROBE_MIN_S seconds) hands the persistent probe worker a
synthetic device dispatch, so real accept-path queries never eat the
probe cost (the round-4 policy rode probes on real queries, putting
device RTT spikes straight into the reported p99). The probe worker is
deliberately a bad GIL citizen's opposite: it yields between the
phases of its dispatch and the service shrinks the interpreter's GIL
slice (VPROXY_TPU_GIL_SLICE_MS, default 1ms vs CPython's 5ms) so a
probe mid-dispatch can only delay an inline answer by ~one slice —
this is what kills the ~3ms accept-path p999 spikes the round-5 bench
saw whenever a probe held the GIL for a full default interval.

Every delivered query also records submit->delivery latency into a
fixed reservoir; stats.latency_percentiles() surfaces p50/p99 (the
BASELINE "p99 classify latency" contract, measured at the service
boundary).

Failure containment: if a device dispatch raises (TPU tunnel drop — a
demonstrated mode in this environment), the service logs one alarm,
serves that batch and everything after it from the host oracle, and
re-probes the device every RETRY_S seconds. Accepts never die with a
classify backtrace.

Batch shapes are padded to power-of-two buckets (min VPROXY_TPU_PAD_LO,
default 4) so the jitted matchers compile a handful of programs, not
one per batch size. Padding is ARRAY-level (engine dispatch_snap
pad_to): only the real queries pay the host-side encode; pad rows are
invalid-probe fills that can never match.

The dispatcher is DOUBLE-BUFFERED (round 8) for cheap-dispatch
backends: a device batch is submitted asynchronously, and the
dispatcher goes straight back to draining the queue — the next
batch's encode overlaps the previous batch's device compute, and the
previous result is pulled (one host round trip per batch) just before
delivery. A straggler that missed batch k no longer waits out k's
full round trip before k+1 even starts; that wait was THE
service_device_p99 driver (BENCH_r06). Mesh-SHARDED backends instead
submit synchronously (see _device_submit): their per-dispatch cost is
fixed and high, so parking the dispatcher through the round trip —
the "natural batching" window above — beats the overlap (A/B'd).

Callbacks are delivered on the submitting event loop via run_on_loop()
(loop-confinement discipline, SURVEY §5 race-detection row); submissions
without a loop get the callback on the dispatcher thread.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ..utils import sketch, trace
from ..utils.log import Logger
from .engine import SMALL_TABLE, pad_batch
from .ir import Hint

_log = Logger("classify")

RETRY_S = float(os.environ.get("VPROXY_TPU_DEVICE_RETRY_S", "5"))
PAD_LO = int(os.environ.get("VPROXY_TPU_PAD_LO", "4"))
BUDGET_US = float(os.environ.get("VPROXY_TPU_CLASSIFY_BUDGET_US", "5000"))
INLINE_LONE = os.environ.get("VPROXY_TPU_INLINE_LONE", "1") != "0"
PROBE_EVERY = 32     # re-probe the non-preferred lone-query path
PROBE_MIN_S = float(os.environ.get("VPROXY_TPU_PROBE_MIN_S", "0.25"))
GIL_SLICE_MS = float(os.environ.get("VPROXY_TPU_GIL_SLICE_MS", "1"))
LAT_RESERVOIR = 4096  # submit->delivery latency samples kept

_gil_slice_applied = False


def _apply_gil_slice() -> None:
    """Shrink the interpreter's thread-switch interval (once, process-
    wide, never loosening an even smaller configured value): a GIL-
    holding device probe can then only delay an inline accept-path
    answer by ~one slice instead of CPython's default 5ms — the source
    of the round-5 multi-ms p999 spikes."""
    global _gil_slice_applied
    if _gil_slice_applied or GIL_SLICE_MS <= 0:
        return
    _gil_slice_applied = True
    import sys
    want = GIL_SLICE_MS / 1000.0
    if want < sys.getswitchinterval():
        sys.setswitchinterval(want)


class _Req:
    __slots__ = ("payload", "cb", "loop", "t0", "tid")

    def __init__(self, payload, cb, loop):
        self.payload = payload
        self.cb = cb
        self.loop = loop
        self.t0 = time.monotonic()
        # the submitter's trace context rides the request so the
        # dispatcher thread can attach its spans (queue wait, dispatch,
        # d2h sync) to the sampled request that triggered them
        self.tid = trace.current_id()


class _Inflight:
    """One async-submitted device batch awaiting its sync + delivery
    (the dispatcher's double buffer slot)."""

    __slots__ = ("kind", "matcher", "reqs", "snap", "arr", "t0",
                 "lone_big")

    def __init__(self, kind, matcher, reqs, snap, arr, t0, lone_big):
        self.kind = kind
        self.matcher = matcher
        self.reqs = reqs
        self.snap = snap
        self.arr = arr
        self.t0 = t0
        self.lone_big = lone_big


class ClassifyStats:
    """Counters surfaced via utils/metrics GlobalInspection."""

    def __init__(self):
        self.queries = 0          # total submitted
        self.dispatches = 0       # device batches dispatched
        self.device_queries = 0   # queries answered by the device
        self.oracle_queries = 0   # queries answered by the host oracle
        self.failovers = 0        # device errors that degraded a batch
        self.max_batch = 0
        self.budget_reroutes = 0  # lone queries sent to oracle by budget
        self.inline_fast = 0      # lone queries served by the fast lane
        # counter read-modify-writes go through `lock` (writers are the
        # dispatcher thread AND every inline-answering submit thread)
        self.lock = threading.Lock()
        # submit->delivery latency rides the process-global histogram
        # (utils/metrics): log2 buckets on /metrics as
        # vproxy_classify_latency_us_{bucket,sum,count}. That series
        # survives ClassifyService.reset() — it is per-process, like the
        # /metrics surface it feeds. A second, UNregistered histogram
        # keeps this instance's own exact reservoir window, so the
        # p99-contract percentiles of a fresh service (bench runs one
        # per contract) are not polluted by a previous instance's
        # samples still sitting in a shared ring.
        from ..utils.metrics import GlobalInspection, Histogram
        self.lat_hist = GlobalInspection.get().get_histogram(
            "vproxy_classify_latency_us", reservoir=LAT_RESERVOIR)
        self._lat_local = Histogram("classify_latency_local_us",
                                    reservoir=LAT_RESERVOIR)

    def bump(self, name: str, n: int = 1) -> None:
        with self.lock:
            setattr(self, name, getattr(self, name) + n)

    def record_latency(self, seconds: float) -> None:
        us = seconds * 1e6
        self.lat_hist.observe(us)
        self._lat_local.observe(us)

    def latency_percentiles(self) -> Optional[dict]:
        """p50/p99/p999 submit->delivery latency in us (exact over this
        instance's reservoir window)."""
        pct = self._lat_local.percentiles((50.0, 99.0, 99.9))
        if pct is None:
            return None
        return {"n": pct["n"], "p50_us": pct["p50"],
                "p99_us": pct["p99"], "p999_us": pct["p999"]}

    def snapshot(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "queries", "dispatches", "device_queries", "oracle_queries",
            "failovers", "max_batch", "budget_reroutes", "inline_fast")}
        lat = self.latency_percentiles()
        if lat is not None:
            d["latency_p50_us"] = round(lat["p50_us"], 1)
            d["latency_p99_us"] = round(lat["p99_us"], 1)
        return d


class ClassifyService:
    _instance: Optional["ClassifyService"] = None
    _instance_lock = threading.Lock()

    @classmethod
    def get(cls) -> "ClassifyService":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Test hook: drop the singleton (a new one lazily respawns)."""
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.close()

    def __init__(self, mode: Optional[str] = None):
        self.mode = mode or os.environ.get("VPROXY_TPU_CLASSIFY", "auto")
        self.retry_s = RETRY_S
        self.budget_us = BUDGET_US
        self.inline_lone = INLINE_LONE
        _apply_gil_slice()
        # lone-query EWMA latency (us) per path, None until first sample
        self._ewma = {"device": None, "oracle": None}
        self._elock = threading.Lock()
        self._lone_seen = 0
        self._probe_last = 0.0  # monotonic ts of the last spawned probe
        # persistent probe worker: the inline accept path only hands it
        # a request + notify (~1us); spawning a Thread per probe costs
        # ~200us and was visible in the accept-path p99
        self._probe_req: Optional[tuple] = None
        self._probe_cv = threading.Condition()
        self._probe_thread: Optional[threading.Thread] = None
        self.stats = ClassifyStats()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # key -> (kind, matcher, list[_Req]); key identifies the matcher
        self._pending: dict[int, tuple[str, object, list[_Req]]] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._device_down_until = 0.0

    # ------------------------------------------------------------- submit

    def submit_hint(self, matcher, hint: Hint,
                    cb: Callable[[int, object], None], loop=None) -> None:
        """Queue one hint classify; cb(rule_idx, payload) with idx=-1 for
        no match and payload = the matcher generation's attached object
        (Upstream registers its GroupHandle list there so idx is always
        interpreted against the generation that produced it)."""
        self._submit("hint", matcher, hint, cb, loop)

    def submit_cidr(self, matcher, addr: bytes, port: Optional[int],
                    cb: Callable[[int, object], None], loop=None) -> None:
        """Queue one route/ACL lookup; cb(first-match idx, payload), -1
        for none. port=None skips ACL port-range gating entirely."""
        self._submit("cidr", matcher, (addr, port), cb, loop)

    def submit_classify_pick(self, pair, hint: Hint, ip: bytes,
                             port: Optional[int],
                             cb: Callable[[int, int, object], None],
                             loop=None) -> None:
        """Queue one fused classify+pick against a maglev.FusedPair:
        cb(verdict_idx, pick_idx, (hint_payload, maglev_payload)).
        Micro-batches ride the fused ONE-launch program
        (rules/engine.fused_dispatch); lone queries take the inline
        host lane (hint index + O(1) maglev read), same fast-lane
        policy as plain hint submits. port=None = source affinity
        (the shared Maglev hash contract)."""
        self._submit("cpick", pair, (hint, ip, port), cb, loop)

    def _submit(self, kind: str, matcher, payload, cb, loop) -> None:
        inline = False
        with self._cv:
            if self._closed:
                raise OSError("ClassifyService is closed")
            self.stats.queries += 1
            key = id(matcher)
            ent = self._pending.get(key)
            if ent is None and self._inline_host(matcher):
                inline = True  # answered below, outside the lock
            elif ent is None:
                self._pending[key] = (kind, matcher, [_Req(payload, cb, loop)])
            else:
                ent[2].append(_Req(payload, cb, loop))
            if not inline:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="classify-dispatch",
                        daemon=True)
                    self._thread.start()
                self._cv.notify()
        if inline:
            self._answer_inline(kind, matcher, payload, cb, loop)

    def _inline_host(self, matcher) -> bool:
        """Lone query, nothing pending for this matcher: answer it
        synchronously on the submitting thread from the host index. With
        the fast lane on (default) this is the first-class path for
        EVERY lone query in auto mode — the O(probes) index gives the
        same winner as the oracle at ~us cost, so there is nothing a
        device round trip could add but latency. With the lane off, the
        pre-round-6 gates apply: small table (the oracle crossover),
        device marked down, or the budget policy preferring the host.
        Called under the lock; must stay O(1)."""
        if self.mode != "auto":
            return False
        if getattr(matcher, "backend", "host") == "host":
            return True
        if time.monotonic() < self._device_down_until:
            return True
        if matcher.size() <= SMALL_TABLE:
            return True
        if self.inline_lone:
            self.stats.inline_fast += 1
            return True
        if self.budget_us <= 0:
            return False
        dev = self._ewma["device"]
        if dev is None or dev <= self.budget_us:
            return False          # ride the device (measures the EWMA)
        self.stats.budget_reroutes += 1
        return True

    def _answer_inline(self, kind: str, matcher, payload, cb, loop) -> None:
        """Serve one lone query from the snapshot's host index, inline.
        Every PROBE_EVERY-th rerouted query also hands the off-path
        probe worker a request so the device EWMA tracks current
        conditions without any real query eating the probe cost.
        Delivery keeps the loop-confinement contract: run_on_loop runs
        the callback immediately when the submitter IS the loop thread
        (the accept path — fully synchronous), else queues it there."""
        t0 = time.monotonic()
        tid = trace.current_id()
        snap = matcher.snapshot()
        # a host-backend matcher has no device to probe (and its
        # dispatch_snap is the O(rules) oracle — exactly the GIL-holding
        # scan the probe worker must never run)
        big = (matcher.size() > SMALL_TABLE
               and getattr(matcher, "backend", "host") != "host")
        try:
            if kind in ("hint", "cpick"):
                # cpick: the FusedPair host lane -> (verdict, pick)
                i = matcher.index_snap(snap, payload)
            else:
                i = matcher.index_snap(snap, payload[0], payload[1])
        except MemoryError:
            raise
        except Exception:
            _log.error("inline classify failed; delivering no-match",
                       exc=True)
            i = (-1, -1) if kind == "cpick" else -1
        dt = time.monotonic() - t0
        if tid:
            trace.record_span(tid, "engine", "classify_inline",
                              int(t0 * 1e9), int(dt * 1e9), kind=kind)
        st = self.stats
        with st.lock:
            st.oracle_queries += 1
            st.max_batch = max(st.max_batch, 1)
        st.record_latency(dt)
        if big:
            self._note_lone_latency("oracle", dt)
            with self._elock:
                self._lone_seen += 1
                now = time.monotonic()
                probe = (self._lone_seen % PROBE_EVERY == 0
                         and now - self._probe_last >= PROBE_MIN_S)
                if probe:
                    self._probe_last = now
            if probe and self.device_ok():
                self._spawn_probe(kind, matcher, payload)
        pl = matcher.snap_payload(snap)
        if kind == "cpick":
            v, p = int(i[0]), int(i[1])

            def run(cb=cb, v=v, p=p, pl=pl) -> None:
                try:
                    cb(v, p, pl)
                except MemoryError:
                    raise
                except Exception:
                    _log.error("classify callback failed", exc=True)
        else:
            i = int(i)

            def run(cb=cb, i=i, pl=pl) -> None:
                try:
                    cb(i, pl)
                except MemoryError:
                    raise
                except Exception:
                    _log.error("classify callback failed", exc=True)

        if loop is None or not loop.run_on_loop(run):
            run()

    def _spawn_probe(self, kind: str, matcher, payload) -> None:
        """Hand (kind, matcher, payload) to the persistent probe worker;
        at most one probe in flight (a slow tunnel must not queue up),
        and the accept path pays only a notify."""
        with self._probe_cv:
            if self._probe_req is not None:
                return
            self._probe_req = (kind, matcher, payload)
            if self._probe_thread is None:
                self._probe_thread = threading.Thread(
                    target=self._probe_run, name="classify-probe",
                    daemon=True)
                self._probe_thread.start()
            self._probe_cv.notify()

    def _probe_run(self) -> None:
        while True:
            with self._probe_cv:
                while self._probe_req is None:
                    if self._closed:
                        return
                    self._probe_cv.wait(1.0)
                kind, matcher, payload = self._probe_req
            try:
                # chunked, deliberately-yielding dispatch: the probe is
                # background work sharing the GIL with the inline accept
                # path, so it gives the scheduler an explicit preemption
                # point before each GIL-heavy phase (encode, dispatch)
                time.sleep(0)
                snap = matcher.snapshot()
                time.sleep(0)
                t0 = time.monotonic()
                # pad exactly like _device_batch: the probe must time the
                # SAME compiled program real dispatches run, not trigger
                # a fresh batch-1 trace whose compile time poisons the
                # EWMA for hundreds of queries
                np.asarray(self._probe_dispatch(kind, matcher, snap,
                                                payload))
                self._note_lone_latency("device", time.monotonic() - t0)
            except MemoryError:
                raise
            except Exception as e:
                self.stats.bump("failovers")
                self._device_down_until = time.monotonic() + self.retry_s
                _log.alert(f"device probe failed ({e!r}); device marked "
                           f"down for {self.retry_s:.0f}s")
                from ..utils import events
                events.record("classify_failover",
                              f"device probe failed: {e!r}",
                              retry_s=self.retry_s)
            finally:
                with self._probe_cv:
                    self._probe_req = None

    def _probe_dispatch(self, kind: str, matcher, snap, payload):
        return self._device_batch(kind, matcher, snap,
                                  [_Req(payload, None, None)])

    # ---------------------------------------------------------- dispatcher

    def _run(self) -> None:
        # double-buffered: at most ONE device batch in flight while the
        # next one encodes/submits; the in-flight result syncs just
        # before its delivery (one host round trip per batch)
        inflight: Optional[_Inflight] = None
        while True:
            with self._cv:
                while not self._pending and not self._closed \
                        and inflight is None:
                    self._cv.wait()
                batches = list(self._pending.values())
                self._pending.clear()
                closed = self._closed
            if not batches:
                if inflight is not None:
                    self._finish_guarded(inflight)
                    inflight = None
                    continue
                if closed:
                    return
                continue
            for kind, matcher, reqs in batches:
                for part in self._split_uniform(kind, reqs):
                    nxt = None
                    try:
                        nxt = self._begin_uniform(kind, matcher, part)
                    except MemoryError:
                        raise  # OOM contract: log-then-die (utils/oom)
                    except Exception:
                        # the dispatcher thread must survive ANY
                        # per-batch error (incl. oracle/delivery bugs)
                        # — a dead thread would strand every future
                        # classify silently. Callbacks get -1 ("no
                        # match") so callers proceed.
                        _log.error("classify dispatch failed; delivering "
                                   "no-match to batch", exc=True)
                        try:
                            self._deliver(part, [-1] * len(part),
                                          kind=kind)
                        except MemoryError:
                            raise
                        except Exception:
                            _log.error("classify delivery failed",
                                       exc=True)
                    if inflight is not None:
                        # deliver the PREVIOUS batch now that the next
                        # one is already on the device
                        self._finish_guarded(inflight)
                        inflight = None
                    inflight = nxt

    def _use_device(self, matcher, n: int) -> bool:
        if self.mode == "host" or getattr(matcher, "backend", "host") == "host":
            return False
        if time.monotonic() < self._device_down_until:
            return False
        if self.mode == "device":
            return True
        # auto: micro-batches always ride the device; lone queries only
        # when the table is past the oracle's crossover size
        if n >= 2:
            return True
        if matcher.size() <= SMALL_TABLE:
            return False
        return self._lone_path_is_device()

    def _lone_path_is_device(self) -> bool:
        """Budget policy for a lone query that reached the dispatcher
        (the inline gate already served budget-rerouted ones): ride the
        device while it is unmeasured or within budget."""
        if self.budget_us <= 0:
            return True
        dev = self._ewma["device"]
        return dev is None or dev <= self.budget_us

    def _note_lone_latency(self, path: str, seconds: float) -> None:
        # writers: inline submit threads, the probe worker, and the
        # dispatcher — the EWMA read-modify-write needs the lock
        us = seconds * 1e6
        with self._elock:
            cur = self._ewma[path]
            self._ewma[path] = us if cur is None else 0.8 * cur + 0.2 * us

    @staticmethod
    def _split_uniform(kind: str, reqs: list[_Req]) -> list[list[_Req]]:
        if kind == "cidr":
            # port=None means "ignore port ranges" and must NOT share a
            # device batch with port-carrying queries (it would be coerced
            # to port 0 and gated against the ACL ranges)
            with_p = [r for r in reqs if r.payload[1] is not None]
            without = [r for r in reqs if r.payload[1] is None]
            if with_p and without:
                return [with_p, without]
        return [reqs]

    def _begin_uniform(self, kind: str, matcher,
                       reqs: list[_Req]) -> Optional["_Inflight"]:
        """Submit one uniform batch: device batches go out ASYNC and
        return an _Inflight for _finish_inflight to sync+deliver; host
        batches deliver here and return None."""
        n = len(reqs)
        with self.stats.lock:  # inline submit threads write stats too
            self.stats.max_batch = max(self.stats.max_batch, n)
        snap = matcher.snapshot()  # ONE generation for device/oracle/payload
        lone_big = n == 1 and matcher.size() > SMALL_TABLE
        if sketch.ON:
            # device-plane attribution: which upstream's classify load
            # is filling the batches (routes dim, `upstream:<alias>`
            # keys, weight = batch occupancy)
            own = getattr(matcher, "owner_alias", None)
            if own:
                sketch.update("routes", f"upstream:{own}", n,
                              plane="engine")
        # sampled requests in the batch: batch-shared phases (dispatch,
        # d2h sync, host_index) attach to the FIRST one — one span, not
        # one per request; per-request queue wait is recorded for every
        # sampled request on BOTH serving branches
        tids = [r.tid for r in reqs if r.tid]
        if tids:
            t_q = time.monotonic()
            for r in reqs:
                if r.tid:
                    trace.record_span(
                        r.tid, "engine", "queue_wait",
                        int(r.t0 * 1e9), int((t_q - r.t0) * 1e9),
                        kind=kind)
        if self._use_device(matcher, n):
            try:
                t0 = time.monotonic()
                with trace.bind(tids[0] if tids else 0):
                    # the bind makes engine-level launch markers
                    # (rules/engine.note_launch: fused vs unfused)
                    # attach to the sampled request's trace
                    arr = self._device_submit(kind, matcher, snap, reqs)
                if tids:
                    trace.record_span(
                        tids[0], "engine", "dispatch", int(t0 * 1e9),
                        int((time.monotonic() - t0) * 1e9), kind=kind,
                        batch=n)
                return _Inflight(kind, matcher, reqs, snap, arr, t0,
                                 lone_big)
            except MemoryError:
                raise
            except Exception as e:
                self._device_failed(e, n)
        t0 = time.monotonic()
        idxs = self._oracle_batch(kind, matcher, snap, reqs)
        if tids:
            trace.record_span(tids[0], "engine", "host_index",
                              int(t0 * 1e9),
                              int((time.monotonic() - t0) * 1e9),
                              kind=kind, batch=n)
        if lone_big:
            self._note_lone_latency("oracle", time.monotonic() - t0)
        self.stats.bump("oracle_queries", n)
        self._deliver(reqs, idxs, matcher.snap_payload(snap), kind=kind)
        return None

    def _finish_guarded(self, inf: "_Inflight") -> None:
        """_finish_inflight behind the dispatcher's survival guard: the
        thread must outlive ANY per-batch error (incl. oracle/delivery
        bugs) — a dead dispatcher would strand every future classify
        silently. Callbacks get -1 ("no match") so callers proceed."""
        try:
            self._finish_inflight(inf)
        except MemoryError:
            raise  # OOM contract: log-then-die, not limp (utils/oom)
        except Exception:
            _log.error("classify finish failed; delivering no-match "
                       "to batch", exc=True)
            try:
                self._deliver(inf.reqs, [-1] * len(inf.reqs),
                              kind=inf.kind)
            except MemoryError:
                raise
            except Exception:
                _log.error("classify delivery failed", exc=True)

    def _finish_inflight(self, inf: "_Inflight") -> None:
        """Pull one in-flight device batch (the single host round trip)
        and deliver; a device error here degrades THIS batch to the
        oracle and marks the device down, same as a submit failure."""
        n = len(inf.reqs)
        idxs = None
        tids = [r.tid for r in inf.reqs if r.tid]
        try:
            t_sync = time.monotonic()
            idxs = np.asarray(inf.arr)[:n]
            if tids:
                trace.record_span(
                    tids[0], "engine", "d2h_sync", int(t_sync * 1e9),
                    int((time.monotonic() - t_sync) * 1e9),
                    kind=inf.kind, batch=n)
            if inf.lone_big:
                self._note_lone_latency("device", time.monotonic() - inf.t0)
            with self.stats.lock:
                self.stats.dispatches += 1
                self.stats.device_queries += n
        except MemoryError:
            raise
        except Exception as e:
            self._device_failed(e, n)
        if idxs is None:
            t0 = time.monotonic()
            idxs = self._oracle_batch(inf.kind, inf.matcher, inf.snap,
                                      inf.reqs)
            if inf.lone_big:
                self._note_lone_latency("oracle", time.monotonic() - t0)
            self.stats.bump("oracle_queries", n)
        try:
            self._deliver(inf.reqs, idxs,
                          inf.matcher.snap_payload(inf.snap),
                          kind=inf.kind)
        except MemoryError:
            raise
        except Exception:
            _log.error("classify delivery failed", exc=True)

    def _device_failed(self, e: Exception, n: int) -> None:
        self.stats.bump("failovers")
        self._device_down_until = time.monotonic() + self.retry_s
        _log.alert(f"device classify failed ({e!r}); serving from "
                   f"host oracle, retry in {self.retry_s:.0f}s")
        from ..utils import events
        events.record("classify_failover",
                      f"device classify failed: {e!r}",
                      batch=n, retry_s=self.retry_s)

    def _device_submit(self, kind: str, matcher, snap, reqs: list[_Req]):
        """Encode + submit (NO sync): returns the async device result.
        Only the real queries are encoded — the engine pads the arrays
        to the batch bucket with can-never-match fill rows."""
        from ..utils import failpoint
        if failpoint.hit("device.dispatch.error", kind):
            # injected device fault: exercises the host-oracle failover
            # (and the down-until/re-probe machinery) deterministically
            raise RuntimeError("failpoint device.dispatch.error")
        n = len(reqs)
        cap = pad_batch(n, lo=PAD_LO)
        # dispatch-cost policy (A/B'd, BENCH_r08): cheap single-device
        # dispatches PIPELINE (async submit — straggler overlap is the
        # r06->r08 service p99 win, 2.3ms -> 1.5ms), while mesh-sharded
        # dispatches PARK the dispatcher (sync): their fixed
        # per-dispatch cost is high enough that the natural-batching
        # window matters more than overlap (sharded closed-loop p50
        # 3.4ms sync vs 5.9ms async — async halves the batch size)
        sync = getattr(matcher, "backend", "host") in (
            "jax-sharded", "jax-fp-sharded")
        if kind in ("hint", "cpick"):
            # cpick is the FusedPair's matcher interface: the same
            # dispatch_snap call, ONE launch answering verdicts AND picks
            return matcher.dispatch_snap(snap, [r.payload for r in reqs],
                                         pad_to=cap, sync=sync)
        addrs = [r.payload[0] for r in reqs]
        ports = [r.payload[1] for r in reqs]
        if ports[0] is None:  # uniform batches only (see _split_uniform)
            ports = None
        return matcher.dispatch_snap(snap, addrs, ports, pad_to=cap,
                                     sync=sync)

    def _device_batch(self, kind: str, matcher, snap, reqs: list[_Req]):
        """Synchronous submit+pull (the probe worker's path)."""
        return np.asarray(
            self._device_submit(kind, matcher, snap, reqs))[: len(reqs)]

    def _oracle_batch(self, kind: str, matcher, snap,
                      reqs: list[_Req]) -> list[int]:
        """Host-served batch (device down / host path): rides the
        snapshot's O(probes) index — same winner as the linear oracle
        (rules/index.py parity tests), O(table) cheaper per query."""
        if kind in ("hint", "cpick"):
            return [matcher.index_snap(snap, r.payload) for r in reqs]
        return [matcher.index_snap(snap, r.payload[0], r.payload[1])
                for r in reqs]

    def _deliver(self, reqs: list[_Req], idxs, payload=None,
                 kind: str = "hint") -> None:
        """cb(idx, payload) — or cb(verdict, pick, payload) for cpick
        batches, where a row is the fused program's (verdict, pick)
        pair (a scalar row is an error fill: both -1). payload is the
        matcher-owner's object versioned with the table generation that
        served the batch (None when the owner didn't register one).
        Callbacks run on the submitting loop; if that loop is gone,
        inline on this thread so cleanup (closing an accepted fd)
        still happens."""
        now = time.monotonic()
        for r, idx in zip(reqs, idxs):
            self.stats.record_latency(now - r.t0)
            if kind == "cpick":
                v, p = (int(idx[0]), int(idx[1])) if np.ndim(idx) \
                    else (int(idx), int(idx))

                def run(cb=r.cb, v=v, p=p) -> None:
                    try:
                        cb(v, p, payload)
                    except MemoryError:
                        raise
                    except Exception:
                        _log.error("classify callback failed", exc=True)
            else:
                i = int(idx)

                def run(cb=r.cb, i=i) -> None:
                    try:
                        cb(i, payload)
                    except MemoryError:
                        raise
                    except Exception:
                        _log.error("classify callback failed", exc=True)

            if r.loop is None or not r.loop.run_on_loop(run):
                run()

    # ------------------------------------------------------------- control

    def device_ok(self) -> bool:
        return time.monotonic() >= self._device_down_until

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        with self._probe_cv:  # wake the probe worker so it exits
            self._probe_cv.notify()
