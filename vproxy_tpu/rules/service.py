"""ClassifyService — the cross-connection micro-batching dispatch queue.

THE north-star mechanism (BASELINE.json): data-plane code (TcpLB hint
classify, SecurityGroup ACL gates, DNS qname lookup, switch routing)
never dispatches the device per connection; it enqueues a query with a
callback and the service coalesces everything that arrives while the
previous device batch is in flight into ONE dispatch ("natural
batching": the dispatch latency itself is the batch window, so the queue
adapts from batch=1 at idle to hundreds under load with no timer).

This replaces the reference's per-connection linear scans
(Upstream.searchForGroup Upstream.java:187-198, SecurityGroup.allow
SecurityGroup.java:30-45, RouteTable.lookup RouteTable.java:44) with a
shared per-process batching front to the compiled device tables.

Dispatch-path policy (mode = VPROXY_TPU_CLASSIFY, default "auto"):

* "auto"   — a flushed batch goes to the device when it has >= 2 queries
             (micro-batch) or the table is big (> SMALL_TABLE rules, the
             same threshold match_one uses); lone queries against small
             tables take the ~1us host oracle instead of a ~1ms device
             round trip.
* "device" — every flushed batch goes to the device (used by tests and
             benchmarks to force the TPU path end-to-end).
* "host"   — pure oracle (latency floor; also the correctness baseline).

Latency budget (VPROXY_TPU_CLASSIFY_BUDGET_US, default 5000; 0 = off):
in "auto" mode a LONE query against a big table normally rides the
device and eats a full device round trip on the accept path. With a
budget set, the service tracks per-path EWMA latencies for lone queries
(device dispatch vs host-oracle scan) and routes lone queries to the
oracle when the device round trip exceeds the budget and the oracle is
faster; the device is re-probed every PROBE_EVERY-th lone query so the
EWMA tracks tunnel/device conditions. Micro-batches (n >= 2) always
ride the device — batching is the whole point.

Every delivered query also records submit->delivery latency into a
fixed reservoir; stats.latency_percentiles() surfaces p50/p99 (the
BASELINE "p99 classify latency" contract, measured at the service
boundary).

Failure containment: if a device dispatch raises (TPU tunnel drop — a
demonstrated mode in this environment), the service logs one alarm,
serves that batch and everything after it from the host oracle, and
re-probes the device every RETRY_S seconds. Accepts never die with a
classify backtrace.

Batch shapes are padded to power-of-two buckets (min 16) so the jitted
matchers compile a handful of programs, not one per batch size.

Callbacks are delivered on the submitting event loop via run_on_loop()
(loop-confinement discipline, SURVEY §5 race-detection row); submissions
without a loop get the callback on the dispatcher thread.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ..utils.log import Logger
from .engine import SMALL_TABLE, pad_batch
from .ir import Hint

_log = Logger("classify")

RETRY_S = float(os.environ.get("VPROXY_TPU_DEVICE_RETRY_S", "5"))
BUDGET_US = float(os.environ.get("VPROXY_TPU_CLASSIFY_BUDGET_US", "5000"))
PROBE_EVERY = 32     # re-probe the non-preferred lone-query path
LAT_RESERVOIR = 4096  # submit->delivery latency samples kept


class _Req:
    __slots__ = ("payload", "cb", "loop", "t0")

    def __init__(self, payload, cb, loop):
        self.payload = payload
        self.cb = cb
        self.loop = loop
        self.t0 = time.monotonic()


class ClassifyStats:
    """Counters surfaced via utils/metrics GlobalInspection."""

    def __init__(self):
        self.queries = 0          # total submitted
        self.dispatches = 0       # device batches dispatched
        self.device_queries = 0   # queries answered by the device
        self.oracle_queries = 0   # queries answered by the host oracle
        self.failovers = 0        # device errors that degraded a batch
        self.max_batch = 0
        self.budget_reroutes = 0  # lone queries sent to oracle by budget
        # submit->delivery latency reservoir (dispatcher-thread writes)
        self._lat = np.zeros(LAT_RESERVOIR, np.float64)
        self._lat_n = 0

    def record_latency(self, seconds: float) -> None:
        self._lat[self._lat_n % LAT_RESERVOIR] = seconds
        self._lat_n += 1

    def latency_percentiles(self) -> Optional[dict]:
        """p50/p99 submit->delivery latency in us over the reservoir."""
        n = min(self._lat_n, LAT_RESERVOIR)
        if n == 0:
            return None
        w = self._lat[:n] * 1e6
        return {"n": self._lat_n,
                "p50_us": float(np.percentile(w, 50)),
                "p99_us": float(np.percentile(w, 99))}

    def snapshot(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "queries", "dispatches", "device_queries", "oracle_queries",
            "failovers", "max_batch", "budget_reroutes")}
        lat = self.latency_percentiles()
        if lat is not None:
            d["latency_p50_us"] = round(lat["p50_us"], 1)
            d["latency_p99_us"] = round(lat["p99_us"], 1)
        return d


class ClassifyService:
    _instance: Optional["ClassifyService"] = None
    _instance_lock = threading.Lock()

    @classmethod
    def get(cls) -> "ClassifyService":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Test hook: drop the singleton (a new one lazily respawns)."""
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.close()

    def __init__(self, mode: Optional[str] = None):
        self.mode = mode or os.environ.get("VPROXY_TPU_CLASSIFY", "auto")
        self.retry_s = RETRY_S
        self.budget_us = BUDGET_US
        # lone-query EWMA latency (us) per path, None until first sample
        self._ewma = {"device": None, "oracle": None}
        self._lone_seen = 0
        self.stats = ClassifyStats()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # key -> (kind, matcher, list[_Req]); key identifies the matcher
        self._pending: dict[int, tuple[str, object, list[_Req]]] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._device_down_until = 0.0

    # ------------------------------------------------------------- submit

    def submit_hint(self, matcher, hint: Hint,
                    cb: Callable[[int, object], None], loop=None) -> None:
        """Queue one hint classify; cb(rule_idx, payload) with idx=-1 for
        no match and payload = the matcher generation's attached object
        (Upstream registers its GroupHandle list there so idx is always
        interpreted against the generation that produced it)."""
        self._submit("hint", matcher, hint, cb, loop)

    def submit_cidr(self, matcher, addr: bytes, port: Optional[int],
                    cb: Callable[[int, object], None], loop=None) -> None:
        """Queue one route/ACL lookup; cb(first-match idx, payload), -1
        for none. port=None skips ACL port-range gating entirely."""
        self._submit("cidr", matcher, (addr, port), cb, loop)

    def _submit(self, kind: str, matcher, payload, cb, loop) -> None:
        with self._cv:
            if self._closed:
                raise OSError("ClassifyService is closed")
            self.stats.queries += 1
            key = id(matcher)
            ent = self._pending.get(key)
            if ent is None:
                self._pending[key] = (kind, matcher, [_Req(payload, cb, loop)])
            else:
                ent[2].append(_Req(payload, cb, loop))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="classify-dispatch", daemon=True)
                self._thread.start()
            self._cv.notify()

    # ---------------------------------------------------------- dispatcher

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                batches = list(self._pending.values())
                self._pending.clear()
            for kind, matcher, reqs in batches:
                try:
                    self._dispatch(kind, matcher, reqs)
                except MemoryError:
                    raise  # OOM contract: log-then-die, not limp (utils/oom)
                except Exception:
                    # the dispatcher thread must survive ANY per-batch
                    # error (incl. oracle/delivery bugs) — a dead thread
                    # would strand every future classify silently.
                    # Callbacks get -1 ("no match") so callers proceed.
                    _log.error("classify dispatch failed; delivering "
                               "no-match to batch", exc=True)
                    try:
                        self._deliver(reqs, [-1] * len(reqs))
                    except MemoryError:
                        raise
                    except Exception:
                        _log.error("classify delivery failed", exc=True)

    def _use_device(self, matcher, n: int) -> bool:
        if self.mode == "host" or getattr(matcher, "backend", "host") == "host":
            return False
        if time.monotonic() < self._device_down_until:
            return False
        if self.mode == "device":
            return True
        # auto: micro-batches always ride the device; lone queries only
        # when the table is past the oracle's crossover size
        if n >= 2:
            return True
        if matcher.size() <= SMALL_TABLE:
            return False
        return self._lone_path_is_device()

    def _lone_path_is_device(self) -> bool:
        """Budget policy for a lone query against a big table: prefer the
        device, but when its measured round trip blows the latency budget
        and the host oracle is faster, reroute. Either path is re-probed
        periodically so the EWMAs track current conditions."""
        if self.budget_us <= 0:
            return True
        self._lone_seen += 1
        dev, orc = self._ewma["device"], self._ewma["oracle"]
        if dev is None:
            return True           # first lone query: measure the device
        if dev <= self.budget_us:
            return True           # device round trip within budget
        # over budget: prefer the faster path, but flip to the other one
        # every PROBE_EVERY-th query so a stale EWMA can't pin the choice
        prefer_dev = orc is not None and dev <= orc
        if self._lone_seen % PROBE_EVERY == 0:
            return not prefer_dev
        if not prefer_dev:
            self.stats.budget_reroutes += 1
        return prefer_dev

    def _note_lone_latency(self, path: str, seconds: float) -> None:
        us = seconds * 1e6
        cur = self._ewma[path]
        self._ewma[path] = us if cur is None else 0.8 * cur + 0.2 * us

    def _dispatch(self, kind: str, matcher, reqs: list[_Req]) -> None:
        if kind == "cidr":
            # port=None means "ignore port ranges" and must NOT share a
            # device batch with port-carrying queries (it would be coerced
            # to port 0 and gated against the ACL ranges)
            with_p = [r for r in reqs if r.payload[1] is not None]
            without = [r for r in reqs if r.payload[1] is None]
            if with_p and without:
                self._dispatch_uniform(kind, matcher, with_p)
                self._dispatch_uniform(kind, matcher, without)
                return
        self._dispatch_uniform(kind, matcher, reqs)

    def _dispatch_uniform(self, kind: str, matcher, reqs: list[_Req]) -> None:
        n = len(reqs)
        self.stats.max_batch = max(self.stats.max_batch, n)
        snap = matcher.snapshot()  # ONE generation for device/oracle/payload
        lone_big = n == 1 and matcher.size() > SMALL_TABLE
        idxs = None
        if self._use_device(matcher, n):
            try:
                t0 = time.monotonic()
                idxs = self._device_batch(kind, matcher, snap, reqs)
                if lone_big:
                    self._note_lone_latency("device", time.monotonic() - t0)
                self.stats.dispatches += 1
                self.stats.device_queries += n
            except MemoryError:
                raise
            except Exception as e:
                self.stats.failovers += 1
                self._device_down_until = time.monotonic() + self.retry_s
                _log.alert(f"device classify failed ({e!r}); serving from "
                           f"host oracle, retry in {self.retry_s:.0f}s")
        if idxs is None:
            t0 = time.monotonic()
            idxs = self._oracle_batch(kind, matcher, snap, reqs)
            if lone_big:
                self._note_lone_latency("oracle", time.monotonic() - t0)
            self.stats.oracle_queries += n
        self._deliver(reqs, idxs, matcher.snap_payload(snap))

    def _device_batch(self, kind: str, matcher, snap, reqs: list[_Req]):
        n = len(reqs)
        cap = pad_batch(n)
        if kind == "hint":
            hints = [r.payload for r in reqs]
            hints += [Hint()] * (cap - n)
            return np.asarray(matcher.dispatch_snap(snap, hints))[:n]
        addrs = [r.payload[0] for r in reqs]
        ports = [r.payload[1] for r in reqs]
        addrs += [b"\x00\x00\x00\x00"] * (cap - n)
        if ports[0] is not None:  # uniform batches only (see _dispatch)
            ports = ports + [0] * (cap - n)
        else:
            ports = None
        return np.asarray(matcher.dispatch_snap(snap, addrs, ports))[:n]

    def _oracle_batch(self, kind: str, matcher, snap,
                      reqs: list[_Req]) -> list[int]:
        if kind == "hint":
            return [matcher.oracle_snap(snap, r.payload) for r in reqs]
        return [matcher.oracle_snap(snap, r.payload[0], r.payload[1])
                for r in reqs]

    def _deliver(self, reqs: list[_Req], idxs, payload=None) -> None:
        """cb(idx) or cb(idx, payload) — payload is the matcher-owner's
        object versioned with the table generation that served the batch
        (None when the owner didn't register one). Callbacks run on the
        submitting loop; if that loop is gone, inline on this thread so
        cleanup (closing an accepted fd) still happens."""
        now = time.monotonic()
        for r, idx in zip(reqs, idxs):
            self.stats.record_latency(now - r.t0)
            i = int(idx)

            def run(cb=r.cb, i=i) -> None:
                try:
                    cb(i, payload)
                except MemoryError:
                    raise
                except Exception:
                    _log.error("classify callback failed", exc=True)

            if r.loop is None or not r.loop.run_on_loop(run):
                run()

    # ------------------------------------------------------------- control

    def device_ok(self) -> bool:
        return time.monotonic() >= self._device_down_until

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
