"""Unified rule IR for the classify() engine.

This is the host-side intermediate representation that all three
rule-matching sites compile down from (see SURVEY.md §7 L2):

* Upstream Host/SNI/URI hint rules (reference Hint.java:92-160 scoring,
  Upstream.searchForGroup Upstream.java:187-198 linear scan)
* DNS qname -> server-group (DNSServer.java:136 — same Hint machinery)
* RouteTable LPM (RouteTable.java:44-59 ordered first-contains scan)
* SecurityGroup ACL (SecurityGroup.java:30-45 ordered first-match)

The IR is deliberately tiny: rule lists plus payload indices. The
compilers in vproxy_tpu/ops turn these into fixed-shape padded device
tables; vproxy_tpu/rules/oracle.py is the pure-Python reference
implementation used as correctness oracle and host fallback matcher.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..utils.ip import Network, parse_ip, is_ipv6_literal


def format_host(s: Optional[str]) -> Optional[str]:
    """Hint.formatHost: values WITHOUT a port (or v6 literals) pass through
    unchanged; only when a :port is stripped does the leading "www." get
    stripped and empty collapse to None (Hint.java:57-71)."""
    if s is None:
        return None
    colon = s.find(":")
    if is_ipv6_literal(s) or colon == -1:
        return s
    s = s[:colon]
    if s.startswith("www."):
        s = s[len("www."):]
    return s or None


def format_uri(s: Optional[str]) -> Optional[str]:
    """Hint.formatUri: strip ?query, keep '/', strip one trailing '/'."""
    if s is None:
        return None
    q = s.find("?")
    if q != -1:
        s = s[:q]
    if s == "/":
        return s
    if s.endswith("/"):
        s = s[:-1]
    return s


@dataclass(frozen=True)
class Hint:
    """A classification query: (host, port, uri), any may be absent."""

    host: Optional[str] = None
    port: int = 0
    uri: Optional[str] = None

    @staticmethod
    def of_host(host: str) -> "Hint":
        return Hint(host=format_host(host))

    @staticmethod
    def of_host_port(host: str, port: int) -> "Hint":
        return Hint(host=format_host(host), port=port)

    @staticmethod
    def of_host_uri(host: str, uri: str) -> "Hint":
        return Hint(host=format_host(host), uri=format_uri(uri))

    @staticmethod
    def of_host_port_uri(host: str, port: int, uri: str) -> "Hint":
        return Hint(host=format_host(host), port=port, uri=format_uri(uri))

    @staticmethod
    def of_uri(uri: str) -> "Hint":
        return Hint(uri=format_uri(uri))


@dataclass(frozen=True)
class HintRule:
    """One Upstream group's annotations (vproxy/hint-host|port|uri)."""

    host: Optional[str] = None  # exact domain, or "*" wildcard
    port: int = 0
    uri: Optional[str] = None  # uri prefix, or "*" wildcard

    def is_empty(self) -> bool:
        return self.host is None and self.port == 0 and self.uri is None


class Proto(Enum):
    TCP = "tcp"
    UDP = "udp"


@dataclass(frozen=True)
class AclRule:
    """SecurityGroupRule: CIDR + protocol + inclusive port range."""

    alias: str
    network: Network
    protocol: Proto
    min_port: int
    max_port: int
    allow: bool

    def match(self, addr: bytes, port: int) -> bool:
        return self.network.contains_ip(addr) and self.min_port <= port <= self.max_port


@dataclass(frozen=True)
class RouteRule:
    """RouteTable.RouteRule: network -> vni or gateway ip."""

    alias: str
    rule: Network
    to_vni: int = 0
    via_ip: Optional[bytes] = None


@dataclass
class RouteTable:
    """Ordered route list; insertion keeps more-specific-first among
    overlapping rules, exactly as RouteTable.addRule (RouteTable.java:110-154).
    Lookup is first-contains in list order."""

    rules_v4: list[RouteRule] = field(default_factory=list)
    rules_v6: list[RouteRule] = field(default_factory=list)

    def add(self, r: RouteRule) -> None:
        for rr in self.rules_v4 + self.rules_v6:
            if rr.alias == r.alias:
                raise ValueError(f"route {r.alias} already exists")
            if rr.rule == r.rule:
                raise ValueError(f"route {rr.alias} has the same network rule")
        rules = self.rules_v4 if len(r.rule.ip) == 4 else self.rules_v6
        self._insert(r, rules)

    @staticmethod
    def _insert(r: RouteRule, rules: list[RouteRule]) -> None:
        similar = -1
        for i, ri in enumerate(rules):
            if ri.rule.contains_net(r.rule) or r.rule.contains_net(ri.rule):
                similar = i
                break
        if similar == -1:
            rules.append(r)
            return
        insert_index = 0
        i = similar
        while i < len(rules):
            curr = rules[i]
            nxt = rules[i + 1] if i + 1 < len(rules) else None
            if curr.rule.contains_net(r.rule):
                insert_index = i
                break
            if r.rule.contains_net(curr.rule):
                if nxt is None:
                    insert_index = i + 1
                    break
                if r.rule.contains_net(nxt.rule):
                    i += 1
                    continue
                if nxt.rule.contains_net(r.rule):
                    insert_index = i + 1
                    break
            insert_index = i + 1
            break
        rules.insert(insert_index, r)

    def remove(self, alias: str) -> None:
        for rules in (self.rules_v4, self.rules_v6):
            for i, ri in enumerate(rules):
                if ri.alias == alias:
                    del rules[i]
                    return
        raise KeyError(alias)

    def lookup(self, addr: bytes) -> Optional[RouteRule]:
        rules = self.rules_v4 if len(addr) == 4 else self.rules_v6
        for r in rules:
            if r.rule.contains_ip(addr):
                return r
        return None

    @property
    def rules(self) -> list[RouteRule]:
        return self.rules_v4 + self.rules_v6
