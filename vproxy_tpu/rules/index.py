"""Indexed host-side matchers — the lone-query latency floor.

The linear oracle (rules/oracle.py) replicates the reference's scan
loops exactly but costs O(rules) per query (~9ms at 20k hint rules) —
fine as a correctness baseline, unusable as the accept-path fallback the
latency-budget policy routes lone queries to (BASELINE's p99 < 50us
classify contract). These indexes answer a single query in O(probes)
(~2-10us independent of table size) with EXACTLY the oracle's
semantics, using the same probe/bucket/pruning structure the device
tables compile to (ops/fphash.py, ops/hashmatch.py):

* HintIndex — host buckets (exact + dot-suffix probes), uri buckets
  (rule-length prefix probes), wildcard lists; members pruned with the
  identical exactness-preserving signatures (_prune_list). Candidates
  are then scored with oracle.match_level itself, so any covered rule
  scores bit-for-bit like the reference scan (Upstream.searchForGroup,
  Upstream.java:187-198); the coverage argument is the same one the
  device kernels rely on (ops/hashmatch.py bucket-pruning note).
* CidrIndex — per-(family, mask) masked-key dicts over the same
  pattern expansion as the device tables (_expand_patterns mirrors
  Network.maskMatch, Network.java:183-278); route mode keeps the
  bucket's min rule index (ordered-scan winner), ACL mode keeps the
  port-range member list pruned by containment (_prune_acl_members).

ClassifyService consults these for lone queries when the device round
trip blows the latency budget; batches still ride the device.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..ops.hashmatch import _expand_patterns, _prune_list
from . import oracle
from .ir import AclRule, Hint, HintRule


class HintIndex:
    """O(probes) exact Hint matcher (same winner as oracle.search)."""

    def __init__(self, rules: Sequence[HintRule]):
        self.rules = list(rules)
        self.host_buckets: dict[str, list[int]] = {}
        self.uri_buckets: dict[str, list[int]] = {}
        wh: list[int] = []
        wu: list[int] = []
        lset = set()
        from ..ops.cuckoo import coop_yield
        for i, r in enumerate(self.rules):
            if not (i & 31):
                coop_yield()  # cooperative: builds run on the engine's
                #               background installer (cuckoo.coop_yield)
            if r.is_empty():
                continue
            if r.host is not None:
                self.host_buckets.setdefault(r.host, []).append(i)
                if r.host == "*":
                    wh.append(i)
            if r.uri is not None:
                self.uri_buckets.setdefault(r.uri, []).append(i)
                lset.add(len(r.uri))
                if r.uri == "*":
                    wu.append(i)
        # identical pruning signatures as the device table compilers —
        # the exactness argument is ops/hashmatch.py:166-181 verbatim
        for bi, k in enumerate(self.host_buckets):
            if not (bi & 63):
                coop_yield()
            self.host_buckets[k] = _prune_list(
                self.rules, self.host_buckets[k], lambda r: (r.uri, r.port))
        for bi, k in enumerate(self.uri_buckets):
            if not (bi & 63):
                coop_yield()
            self.uri_buckets[k] = _prune_list(
                self.rules, self.uri_buckets[k], lambda r: r.port)
        self.wh = _prune_list(self.rules, wh, lambda r: (r.uri, r.port))
        self.wu = _prune_list(self.rules, wu, lambda r: r.port)
        self.lset = sorted(lset)

    def lookup(self, hint: Hint) -> int:
        """-> matching rule index or -1; winner == oracle.search()."""
        rules = self.rules
        best_lv = 0
        best = -1

        def consider(idxs):
            nonlocal best_lv, best
            for i in idxs:
                lv = oracle.match_level(hint, rules[i])
                if lv > best_lv or (lv == best_lv and best >= 0 and i < best):
                    best_lv, best = lv, i

        hb = self.host_buckets
        if hint.host is not None:
            h = hint.host
            m = hb.get(h)
            if m is not None:
                consider(m)
            # dot-suffix probes: every rule host that q ends with ".host"
            pos = h.find(".")
            while pos >= 0:
                m = hb.get(h[pos + 1:])
                if m is not None:
                    consider(m)
                pos = h.find(".", pos + 1)
            consider(self.wh)
        if hint.uri is not None:
            u = hint.uri
            ub = self.uri_buckets
            for l in self.lset:
                if l > len(u):
                    break
                m = ub.get(u[:l])
                if m is not None:
                    consider(m)
            consider(self.wu)
        return best if best_lv > 0 else -1


class CidrIndex:
    """O(groups) exact first-match CIDR lookup (routes / ACL)."""

    def __init__(self, networks: Sequence, acl: Optional[Sequence[AclRule]] = None):
        # (fam, mask_int) -> {masked_key_int: min idx | [(idx, lo, hi)]}
        self.groups: dict[tuple, dict] = {}
        self.acl = list(acl) if acl is not None else None
        buckets: dict[tuple, dict[int, list[int]]] = {}
        from ..ops.cuckoo import coop_yield
        for i, net in enumerate(networks):
            if not (i & 31):
                coop_yield()  # cooperative: see HintIndex.__init__
            for key, mask, fam in _expand_patterns(net):
                g = buckets.setdefault(
                    (fam, int.from_bytes(mask, "big")), {})
                g.setdefault(int.from_bytes(key, "big"), []).append(i)
        from ..ops.fphash import _prune_acl_members
        for gk, keys in buckets.items():
            out: dict = {}
            for key, items in keys.items():
                if self.acl is None:
                    out[key] = min(items)
                else:
                    out[key] = [
                        (j, self.acl[j].min_port, self.acl[j].max_port)
                        for j in _prune_acl_members(items, self.acl)]
            self.groups[gk] = out

    def lookup(self, addr: bytes, port: Optional[int] = None) -> int:
        """-> first matching rule index in insert order, or -1. Matches
        CidrMatcher.oracle_snap (Network.contains_ip + port gate)."""
        from ..ops.tables import V4, V6
        if len(addr) == 4:
            a, fam = int.from_bytes(b"\x00" * 12 + addr, "big"), V4
        else:
            a, fam = int.from_bytes(addr, "big"), V6
        best = -1
        for (gfam, mask), keys in self.groups.items():
            if gfam != fam:
                continue
            hit = keys.get(a & mask)
            if hit is None:
                continue
            if self.acl is None:
                if best < 0 or hit < best:
                    best = hit
            else:
                for j, lo, hi in hit:
                    if port is None or lo <= port <= hi:
                        if best < 0 or j < best:
                            best = j
                        break
        return best
