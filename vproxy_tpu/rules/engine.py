"""ClassifyEngine — the runtime seam between resources and the matchers.

This is the TPU analog of the reference's per-connection match loops:
components (Upstream, SecurityGroup, switch Table, DNSServer) register
their rules here; data-plane code calls the batched query API. Mirrors
the reference's provider SPI (-Dvfd, FDProvider.java:12-45) as
`backend="jax" | "jax-dense" | "host"`:

* "host"      — the pure-Python oracle (correctness fallback + latency
                floor for tiny tables).
* "jax"       — DEFAULT: cuckoo-hash classify kernels (ops/hashmatch):
                O(1) probes per query, byte-verified (exact regardless
                of hash behavior), gather-bound.
* "jax-fp"    — packed fingerprint kernels (ops/fphash): ~25x fewer
                gathered rows per query than "jax" (the measured cost
                driver, PERF_NOTES.md). Exact for every key in the
                table; a query key NOT in the table can false-positive
                with probability 2^-64 per probe. The throughput path —
                bench.py's 100k-rule TPU numbers ride this backend.
* "jax-dense" — the dense matmul kernels (ops/matchers): O(rules) MXU
                work per query; kept as the brute-force cross-check and
                for rule-axis mesh sharding experiments.
* "jax-sharded" — the cuckoo-hash kernels SPMD over a (batch, rules)
                device mesh (parallel/mesh): each device holds a
                contiguous rule slice compiled into its own table and
                the winner rides pmax/pmin ICI collectives. Rule
                updates reuse caps (same shapes, no retrace); an update
                that outgrows the caps (ops.hashmatch.CapsExceeded)
                transparently rebuilds tables — the jitted fn simply
                retraces on the new shapes.
* "jax-fp-sharded" — the packed fingerprint kernels over the same mesh
                machinery: per-shard fp tables under one unified caps
                dict, same pmax/pmin winner reduction. The multi-chip
                form of the throughput path.

Rule updates never retrace: tables are fixed-capacity (padded), and an
update recompiles numpy arrays and re-uploads same-shape buffers (the
double-buffer swap — README "Modifiable when running"). Capacity (or a
cuckoo bucket tier) grows when exceeded, which recompiles the jitted
matcher once for the new shapes.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..ops import hashmatch as H
from ..ops import tables as T
from ..ops.bitmatch import unpack_bits
from ..ops.matchers import cidr_match_jit, hint_match_jit, table_arrays
from . import oracle
from .ir import AclRule, Hint, HintRule, Proto


def default_backend() -> str:
    return os.environ.get("VPROXY_TPU_MATCHER", "jax")


_MESH = None


def default_mesh():
    """Process-wide (batch, rules) mesh for jax-sharded matchers; batch
    axis size from VPROXY_TPU_MESH_BATCH (default 1 = rules-only)."""
    global _MESH
    if _MESH is None:
        from ..parallel import mesh as M
        _MESH = M.make_mesh(
            batch=int(os.environ.get("VPROXY_TPU_MESH_BATCH", "1")))
    return _MESH


def pad_batch(n: int, mult: int = 1, lo: int = 16) -> int:
    """Batch-shape bucket: pow2 growth from `lo`, rounded up to a
    multiple of `mult` (the mesh batch-axis size, so the axis always
    divides the padded batch). ClassifyService uses the same buckets
    (mult=1) so the jitted matchers see few trace shapes."""
    c = lo
    while c < n:
        c <<= 1
    return -(-c // mult) * mult


# Below this rule count, single (unbatched) queries run on the host oracle:
# a python scan over a handful of rules is ~1us while a device dispatch is
# ~1ms — the device path wins only for big tables or batched queries. The
# device table is still compiled and kept in sync (used by match() batches).
SMALL_TABLE = int(os.environ.get("VPROXY_TPU_SMALL_TABLE", "128"))


def _to_device(arrs: dict) -> dict:
    import jax
    import jax.numpy as jnp
    out = {}
    for k, v in arrs.items():
        if v.dtype == np.float32 and v.ndim == 2:  # matmul weights -> bf16
            out[k] = jax.device_put(jnp.asarray(v, dtype=jnp.bfloat16))
        else:
            out[k] = jax.device_put(v)
    return out


class HintMatcher:
    """Device-backed (or host-fallback) Upstream/DNS hint matcher."""

    def __init__(self, rules: Sequence[HintRule] = (), backend: Optional[str] = None,
                 payload=None, mesh=None):
        self.backend = backend or default_backend()
        self._rules: list[HintRule] = list(rules)
        self._dev: Optional[dict] = None
        self._tab = None  # hash-path table meta
        self._caps: Optional[dict] = None
        self._mesh = mesh  # jax-sharded only (lazily defaulted)
        self._fn = None    # jax-sharded jitted matcher (shape-agnostic)
        # (tab, dev, rules, payload, index) published as ONE tuple so
        # concurrent readers (the ClassifyService dispatcher) never see a
        # torn table/rule/payload version across a set_rules() swap;
        # `payload` is an opaque owner-supplied object versioned WITH the
        # rules (e.g. Upstream's GroupHandle list) so a matched index is
        # always interpreted against the same generation it was matched
        # in; `index` is the O(probes) host-side HintIndex the latency
        # budget policy answers lone queries from (rules/index.py)
        self._pub: tuple = (None, None, [], payload, None)
        self._payload = payload
        self._cksum = None  # (pub-tuple, crc32) cache — see checksum()
        self._recompile()

    @property
    def rules(self) -> list[HintRule]:
        return list(self._rules)

    def set_rules(self, rules: Sequence[HintRule], payload=None) -> None:
        self._rules = list(rules)
        self._payload = payload
        self._recompile()

    def _recompile(self) -> None:
        if self.backend == "jax":
            self._tab = H.compile_hint_hash(self._rules, caps=self._caps)
            self._caps = self._tab.caps
            self._dev = _to_device(self._tab.arrays)
        elif self.backend == "jax-fp":
            from ..ops import fphash as F
            try:
                self._tab = F.compile_hint_fp(self._rules, caps=self._caps)
            except H.CapsExceeded:
                # update outgrew the reused shapes: fresh build (the
                # jitted matcher retraces on the new shapes)
                self._tab = F.compile_hint_fp(self._rules)
            self._caps = self._tab.caps
            self._dev = _to_device(self._tab.arrays)
        elif self.backend in ("jax-sharded", "jax-fp-sharded"):
            from ..parallel import mesh as M
            if self._mesh is None:
                self._mesh = default_mesh()
            shards = self._mesh.shape["rules"]
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                compile_sharded = F.compile_hint_fp_sharded
            else:
                compile_sharded = H.compile_hint_hash_sharded
            try:
                self._tab = compile_sharded(self._rules, shards,
                                            caps=self._caps)
            except H.CapsExceeded:
                # update outgrew the reused shapes: transparent rebuild
                # (the jitted fn retraces on the new shapes)
                self._tab = compile_sharded(self._rules, shards)
            self._caps = self._tab.shards[0].caps
            self._dev = M.shard_hash_table(self._tab, self._mesh)
            # _fn is NOT reset: it closes over key ndims + kernel only,
            # and jit re-specializes on shape changes by itself — the
            # caps-reuse no-retrace contract depends on keeping it
        elif self.backend == "jax-dense":
            cap = self._dev["active"].shape[0] if self._dev is not None else None
            if cap is not None and len(self._rules) > cap:
                cap = None  # outgrew capacity: let the compiler pick a bucket
            tab = T.compile_hint_rules(self._rules, cap=cap)
            self._dev = _to_device(table_arrays(tab))
        idx = None
        # small tables answer lone queries with the linear oracle (the
        # same crossover match_one uses), so the index build — a second
        # O(rules) bucket construction on the update path — only pays
        # for itself past SMALL_TABLE. Built for EVERY backend: the
        # inline accept path serves host-backend matchers too, and a
        # big table must never put an O(rules) scan on an event loop
        if len(self._rules) > SMALL_TABLE:
            from .index import HintIndex
            idx = HintIndex(self._rules)
        self._pub = (self._tab, self._dev, list(self._rules), self._payload,
                     idx)

    def encode(self, hints: Sequence[Hint]) -> dict:
        """Pre-encode a query batch for submit() (hash backend only).
        Bound to the current table version — re-encode after set_rules."""
        assert self.backend == "jax"
        return H.encode_hint_queries(hints, self._tab)

    def submit(self, q: dict):
        """Dispatch an encoded batch; returns the device array (async)."""
        idx, _ = H.hint_hash_jit(self._dev, q)
        return idx

    def match(self, hints: Sequence[Hint]) -> np.ndarray:
        """-> int32 [B] matched rule index, -1 for none."""
        snap = self._pub
        if self.backend == "host" and snap[2] and hints:
            return np.array([oracle.search(snap[2], h) for h in hints],
                            np.int32)
        return np.asarray(self.dispatch_snap(snap, hints))

    def match_one(self, hint: Hint) -> int:
        if self.backend != "host" and len(self._rules) <= SMALL_TABLE:
            return oracle.search(self._rules, hint)
        return int(self.match([hint])[0])

    # ---- ClassifyService API (rules/service.py) ----

    def size(self) -> int:
        return len(self._pub[2])

    def checksum(self) -> int:
        """u32 checksum of the PUBLISHED rule generation (crc32 over the
        canonical rule reprs): two hosts whose tables compiled from the
        same rule list hash identically regardless of caps-growth
        history. The cluster replication gate (cluster/replicate.py)
        compares this across hosts before installing a generation.
        Computed once per generation (cached at publish): replication
        polls read it every few hundred ms and must not pay an O(rules)
        string build each time."""
        pub = self._pub
        cached = self._cksum
        if cached is not None and cached[0] is pub:
            return cached[1]
        import zlib
        v = zlib.crc32("\n".join(map(repr, pub[2])).encode())
        self._cksum = (pub, v)
        return v

    def snapshot(self) -> tuple:
        """One consistent (table, device, rules, payload) generation."""
        return self._pub

    @staticmethod
    def snap_payload(snap: tuple):
        return snap[3]

    def oracle_snap(self, snap: tuple, hint: Hint) -> int:
        return oracle.search(snap[2], hint)

    def index_snap(self, snap: tuple, hint: Hint) -> int:
        """O(probes) host lookup against the snapshot's HintIndex (same
        winner as oracle_snap); falls back to the linear oracle when the
        snapshot has no index (host backend)."""
        idx = snap[4] if len(snap) > 4 else None
        if idx is None:
            return oracle.search(snap[2], hint)
        return idx.lookup(hint)

    def oracle_one(self, hint: Hint) -> int:
        return self.oracle_snap(self._pub, hint)

    def dispatch_snap(self, snap: tuple, hints: Sequence[Hint]):
        """Encode + submit one batch against the snapshotted table
        generation (async device result; np.asarray() it to block)."""
        tab, dev, rules = snap[0], snap[1], snap[2]
        if not rules or not hints:
            return np.full(len(hints), -1, np.int32)
        if self.backend == "jax":
            q = H.encode_hint_queries(hints, tab)
            idx, _ = H.hint_hash_jit(dev, q)
            return idx
        if self.backend == "jax-fp":
            from ..ops import fphash as F
            q = F.encode_hint_queries_fp(hints, tab)
            # resolve the member-mode env knob HERE, per dispatch: jit
            # keys on the static mode arg, so passing None would bake
            # the first dispatch's VPROXY_TPU_FP_MEMBER into the cache
            # and silently ignore later changes (stale lowering)
            idx, _ = F.hint_fp_jit(dev, q, mode=F.default_member_mode())
            return idx
        if self.backend in ("jax-sharded", "jax-fp-sharded"):
            from ..parallel import mesh as M
            from ..parallel.mesh import query_shards
            n = len(hints)
            cap = pad_batch(n, query_shards(self._mesh))
            padded = list(hints) + [Hint()] * (cap - n)
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                q = F.encode_hint_queries_fp_sharded(padded, tab)
                kernel = F.hint_fp_match
            else:
                q = H.encode_hint_queries_sharded(padded, tab)
                kernel = None
            qd = M.shard_hint_queries_sharded(q, self._mesh)
            if self._fn is None:
                self._fn = M.make_sharded_hint_fn(
                    self._mesh, {k: v.ndim for k, v in tab.arrays.items()},
                    {k: v.ndim for k, v in q.items()}, kernel=kernel)
            out = self._fn(dev, qd, np.int32(tab.shard_size))
            # to_local: this process's slice on a multi-process mesh,
            # plain np.asarray single-process
            return M.to_local(out)[:n]
        q = T.encode_hints(hints)
        idx, _ = hint_match_jit(
            dev, q["host"], q["has_host"], unpack_bits(q["uri"]),
            q["has_uri"], q["port"])
        return idx


class CidrMatcher:
    """Device-backed ordered first-match CIDR matcher (routes / ACL)."""

    def __init__(self, networks: Sequence = (), backend: Optional[str] = None,
                 acl: Optional[Sequence[AclRule]] = None, payload=None,
                 mesh=None):
        self.backend = backend or default_backend()
        self._nets = list(networks)
        self._acl = list(acl) if acl is not None else None
        self._dev: Optional[dict] = None
        self._caps: Optional[dict] = None
        self._tab = None   # jax-sharded stacked table meta
        self._mesh = mesh  # jax-sharded only (lazily defaulted)
        self._fns: dict = {}  # jax-sharded jitted fns keyed by with_port
        # (dev, nets, acl, payload, tab, index) — one atomic generation
        # (see HintMatcher._pub for the why)
        self._pub: tuple = (None, [], None, payload, None, None)
        self._payload = payload
        self._cksum = None  # (pub-tuple, crc32) cache — see checksum()
        self._recompile()

    def set_networks(self, networks: Sequence, acl: Optional[Sequence[AclRule]] = None,
                     payload=None) -> None:
        self._nets = list(networks)
        self._acl = list(acl) if acl is not None else None
        self._payload = payload
        self._recompile()

    def _recompile(self) -> None:
        if self.backend == "jax":
            tab = H.compile_cidr_hash(self._nets, acl=self._acl, caps=self._caps)
            self._caps = tab.caps
            self._dev = _to_device(tab.arrays)
        elif self.backend == "jax-fp":
            from ..ops import fphash as F
            try:
                tab = F.compile_cidr_fp(self._nets, acl=self._acl,
                                        caps=self._caps)
            except H.CapsExceeded:
                tab = F.compile_cidr_fp(self._nets, acl=self._acl)
            self._caps = tab.caps
            self._dev = _to_device(tab.arrays)
        elif self.backend in ("jax-sharded", "jax-fp-sharded"):
            from ..parallel import mesh as M
            if self._mesh is None:
                self._mesh = default_mesh()
            shards = self._mesh.shape["rules"]
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                compile_sharded = F.compile_cidr_fp_sharded
            else:
                compile_sharded = H.compile_cidr_hash_sharded
            try:
                self._tab = compile_sharded(
                    self._nets, shards, acl=self._acl, caps=self._caps)
            except H.CapsExceeded:
                # update outgrew the reused shapes: transparent rebuild
                self._tab = compile_sharded(self._nets, shards,
                                            acl=self._acl)
            self._caps = self._tab.shards[0].caps
            self._dev = M.shard_hash_table(self._tab, self._mesh)
            # _fns kept: see HintMatcher._recompile
        elif self.backend == "jax-dense":
            cap = self._dev["allow"].shape[0] if self._dev is not None else None
            if cap is not None and len(self._nets) > cap:
                cap = None
            tab = T.compile_cidr_rules(self._nets, cap=cap, acl=self._acl)
            self._dev = _to_device(table_arrays(tab))
        idx = None
        if len(self._nets) > SMALL_TABLE:  # every backend: see HintMatcher
            from .index import CidrIndex
            idx = CidrIndex(self._nets, acl=self._acl)
        self._pub = (self._dev, list(self._nets),
                     None if self._acl is None else list(self._acl),
                     self._payload, self._tab, idx)

    def match(self, addrs: Sequence[bytes],
              ports: Optional[Sequence[int]] = None) -> np.ndarray:
        """-> int32 [B] first matching rule index (order = insert order), -1
        for none."""
        snap = self._pub
        if self.backend == "host" and snap[1] and addrs:
            return np.array(
                [self.oracle_snap(snap, a, None if ports is None else ports[i])
                 for i, a in enumerate(addrs)], np.int32)
        return np.asarray(self.dispatch_snap(snap, addrs, ports))

    def _scan_one(self, addr: bytes, port: Optional[int]) -> int:
        return self.oracle_snap(self._pub, addr, port)

    def oracle_one(self, addr: bytes, port: Optional[int] = None) -> int:
        return self.oracle_snap(self._pub, addr, port)

    def match_one(self, addr: bytes, port: Optional[int] = None) -> int:
        if self.backend != "host" and len(self._nets) <= SMALL_TABLE:
            return self._scan_one(addr, port)
        return int(self.match([addr], None if port is None else [port])[0])

    # ---- ClassifyService API (rules/service.py) ----

    def size(self) -> int:
        return len(self._pub[1])

    def checksum(self) -> int:
        """u32 checksum of the published networks+ACL generation (see
        HintMatcher.checksum — the cluster replication gate; cached per
        published generation)."""
        snap = self._pub
        cached = self._cksum
        if cached is not None and cached[0] is snap:
            return cached[1]
        import zlib
        text = "\n".join(map(repr, snap[1]))
        if snap[2] is not None:
            text += "\n" + "\n".join(map(repr, snap[2]))
        v = zlib.crc32(text.encode())
        self._cksum = (snap, v)
        return v

    def snapshot(self) -> tuple:
        """One consistent (device, nets, acl, payload) generation."""
        return self._pub

    @staticmethod
    def snap_payload(snap: tuple):
        return snap[3]

    def oracle_snap(self, snap: tuple, addr: bytes,
                    port: Optional[int] = None) -> int:
        nets, acl = snap[1], snap[2]
        for j, net in enumerate(nets):
            if net.contains_ip(addr) and (
                    port is None or acl is None or
                    (acl[j].min_port <= port <= acl[j].max_port)):
                return j
        return -1

    def index_snap(self, snap: tuple, addr: bytes,
                   port: Optional[int] = None) -> int:
        """O(groups) host lookup against the snapshot's CidrIndex (same
        winner as oracle_snap); linear fallback without one."""
        idx = snap[5] if len(snap) > 5 else None
        if idx is None:
            return self.oracle_snap(snap, addr, port)
        # route tables ignore ports entirely (oracle_snap's acl gate)
        return idx.lookup(addr, None if snap[2] is None else port)

    def dispatch_snap(self, snap: tuple, addrs: Sequence[bytes],
                      ports: Optional[Sequence[int]]):
        """Encode + submit one batch against the snapshotted table
        generation (async device result; np.asarray() it to block)."""
        dev, nets, acl = snap[0], snap[1], snap[2]
        if not nets or not addrs:
            return np.full(len(addrs), -1, np.int32)
        a16, fam = T.encode_ips(addrs)
        # route tables (acl=None) have zeroed port-range columns: the port
        # gate must be skipped entirely or every port>0 query misses
        p = None if (ports is None or acl is None) \
            else np.asarray(ports, np.int32)
        if self.backend == "jax":
            return H.cidr_hash_jit(dev, a16, fam, p)
        if self.backend == "jax-fp":
            from ..ops import fphash as F
            return F.cidr_fp_jit(dev, a16, fam, p)
        if self.backend in ("jax-sharded", "jax-fp-sharded"):
            return self._dispatch_sharded(snap, a16, fam, p)
        return cidr_match_jit(dev, a16, fam, p)

    def _dispatch_sharded(self, snap: tuple, a16: np.ndarray,
                          fam: np.ndarray, p: Optional[np.ndarray]):
        from ..parallel import mesh as M
        dev, tab = snap[0], snap[4]
        from ..parallel.mesh import query_shards
        n = a16.shape[0]
        cap = pad_batch(n, query_shards(self._mesh))
        if cap != n:
            a16 = np.concatenate(
                [a16, np.zeros((cap - n,) + a16.shape[1:], a16.dtype)])
            fam = np.concatenate([fam, np.zeros(cap - n, fam.dtype)])
            if p is not None:
                p = np.concatenate([p, np.zeros(cap - n, p.dtype)])
        a16d, famd, pd = M.shard_addr_queries(a16, fam, self._mesh, p)
        with_port = p is not None
        fn = self._fns.get(with_port)
        if fn is None:
            kernel = None
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                kernel = F.cidr_fp_match
            fn = self._fns[with_port] = M.make_sharded_cidr_fn(
                self._mesh, {k: v.ndim for k, v in tab.arrays.items()},
                with_port, kernel=kernel)
        size = np.int32(tab.shard_size)
        out = fn(dev, a16d, famd, pd, size) if with_port \
            else fn(dev, a16d, famd, size)
        return M.to_local(out)[:n]
