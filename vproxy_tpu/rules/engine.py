"""ClassifyEngine — the runtime seam between resources and the matchers.

This is the TPU analog of the reference's per-connection match loops:
components (Upstream, SecurityGroup, switch Table, DNSServer) register
their rules here; data-plane code calls the batched query API. Mirrors
the reference's provider SPI (-Dvfd, FDProvider.java:12-45) as
`backend="jax" | "jax-dense" | "host"`:

* "host"      — the pure-Python oracle (correctness fallback + latency
                floor for tiny tables).
* "jax"       — DEFAULT: cuckoo-hash classify kernels (ops/hashmatch):
                O(1) probes per query, gather-bound. The 10M matches/s
                path.
* "jax-dense" — the dense matmul kernels (ops/matchers): O(rules) MXU
                work per query; kept as the brute-force cross-check and
                for rule-axis mesh sharding experiments.

Rule updates never retrace: tables are fixed-capacity (padded), and an
update recompiles numpy arrays and re-uploads same-shape buffers (the
double-buffer swap — README "Modifiable when running"). Capacity (or a
cuckoo bucket tier) grows when exceeded, which recompiles the jitted
matcher once for the new shapes.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..ops import hashmatch as H
from ..ops import tables as T
from ..ops.bitmatch import unpack_bits
from ..ops.matchers import cidr_match_jit, hint_match_jit, table_arrays
from . import oracle
from .ir import AclRule, Hint, HintRule, Proto


def default_backend() -> str:
    return os.environ.get("VPROXY_TPU_MATCHER", "jax")


# Below this rule count, single (unbatched) queries run on the host oracle:
# a python scan over a handful of rules is ~1us while a device dispatch is
# ~1ms — the device path wins only for big tables or batched queries. The
# device table is still compiled and kept in sync (used by match() batches).
SMALL_TABLE = int(os.environ.get("VPROXY_TPU_SMALL_TABLE", "128"))


def _to_device(arrs: dict) -> dict:
    import jax
    import jax.numpy as jnp
    out = {}
    for k, v in arrs.items():
        if v.dtype == np.float32 and v.ndim == 2:  # matmul weights -> bf16
            out[k] = jax.device_put(jnp.asarray(v, dtype=jnp.bfloat16))
        else:
            out[k] = jax.device_put(v)
    return out


class HintMatcher:
    """Device-backed (or host-fallback) Upstream/DNS hint matcher."""

    def __init__(self, rules: Sequence[HintRule] = (), backend: Optional[str] = None):
        self.backend = backend or default_backend()
        self._rules: list[HintRule] = list(rules)
        self._dev: Optional[dict] = None
        self._tab = None  # hash-path table meta
        self._caps: Optional[dict] = None
        self._recompile()

    @property
    def rules(self) -> list[HintRule]:
        return list(self._rules)

    def set_rules(self, rules: Sequence[HintRule]) -> None:
        self._rules = list(rules)
        self._recompile()

    def _recompile(self) -> None:
        if self.backend == "jax":
            self._tab = H.compile_hint_hash(self._rules, caps=self._caps)
            self._caps = self._tab.caps
            self._dev = _to_device(self._tab.arrays)
        elif self.backend == "jax-dense":
            cap = self._dev["active"].shape[0] if self._dev is not None else None
            if cap is not None and len(self._rules) > cap:
                cap = None  # outgrew capacity: let the compiler pick a bucket
            tab = T.compile_hint_rules(self._rules, cap=cap)
            self._dev = _to_device(table_arrays(tab))

    def encode(self, hints: Sequence[Hint]) -> dict:
        """Pre-encode a query batch for submit() (hash backend only).
        Bound to the current table version — re-encode after set_rules."""
        assert self.backend == "jax"
        return H.encode_hint_queries(hints, self._tab)

    def submit(self, q: dict):
        """Dispatch an encoded batch; returns the device array (async)."""
        idx, _ = H.hint_hash_jit(self._dev, q)
        return idx

    def match(self, hints: Sequence[Hint]) -> np.ndarray:
        """-> int32 [B] matched rule index, -1 for none."""
        if not self._rules or not hints:
            return np.full(len(hints), -1, np.int32)
        if self.backend == "host":
            return np.array([oracle.search(self._rules, h) for h in hints],
                            np.int32)
        if self.backend == "jax":
            return np.asarray(self.submit(self.encode(hints)))
        q = T.encode_hints(hints)
        idx, _ = hint_match_jit(
            self._dev, q["host"], q["has_host"], unpack_bits(q["uri"]),
            q["has_uri"], q["port"])
        return np.asarray(idx)

    def match_one(self, hint: Hint) -> int:
        if self.backend != "host" and len(self._rules) <= SMALL_TABLE:
            return oracle.search(self._rules, hint)
        return int(self.match([hint])[0])


class CidrMatcher:
    """Device-backed ordered first-match CIDR matcher (routes / ACL)."""

    def __init__(self, networks: Sequence = (), backend: Optional[str] = None,
                 acl: Optional[Sequence[AclRule]] = None):
        self.backend = backend or default_backend()
        self._nets = list(networks)
        self._acl = list(acl) if acl is not None else None
        self._dev: Optional[dict] = None
        self._caps: Optional[dict] = None
        self._recompile()

    def set_networks(self, networks: Sequence, acl: Optional[Sequence[AclRule]] = None) -> None:
        self._nets = list(networks)
        self._acl = list(acl) if acl is not None else None
        self._recompile()

    def _recompile(self) -> None:
        if self.backend == "jax":
            tab = H.compile_cidr_hash(self._nets, acl=self._acl, caps=self._caps)
            self._caps = tab.caps
            self._dev = _to_device(tab.arrays)
        elif self.backend == "jax-dense":
            cap = self._dev["allow"].shape[0] if self._dev is not None else None
            if cap is not None and len(self._nets) > cap:
                cap = None
            tab = T.compile_cidr_rules(self._nets, cap=cap, acl=self._acl)
            self._dev = _to_device(table_arrays(tab))

    def submit(self, a16: np.ndarray, fam: np.ndarray,
               ports: Optional[np.ndarray]):
        """Dispatch an encoded batch; returns the device array (async)."""
        p = None if (ports is None or self._acl is None) else ports
        return H.cidr_hash_jit(self._dev, a16, fam, p)

    def match(self, addrs: Sequence[bytes],
              ports: Optional[Sequence[int]] = None) -> np.ndarray:
        """-> int32 [B] first matching rule index (order = insert order), -1
        for none."""
        if not self._nets or not addrs:
            return np.full(len(addrs), -1, np.int32)
        if self.backend == "host":
            return np.array(
                [self._scan_one(a, None if ports is None else ports[i])
                 for i, a in enumerate(addrs)], np.int32)
        a16, fam = T.encode_ips(addrs)
        if self.backend == "jax":
            p = None if ports is None else np.asarray(ports, np.int32)
            return np.asarray(self.submit(a16, fam, p))
        # route tables (acl=None) have zeroed port-range columns: the port
        # gate must be skipped entirely or every port>0 query misses
        p = None if (ports is None or self._acl is None) else np.asarray(ports, np.int32)
        idx = cidr_match_jit(self._dev, a16, fam, p)
        return np.asarray(idx)

    def _scan_one(self, addr: bytes, port: Optional[int]) -> int:
        for j, net in enumerate(self._nets):
            if net.contains_ip(addr) and (
                    port is None or self._acl is None or
                    (self._acl[j].min_port <= port <= self._acl[j].max_port)):
                return j
        return -1

    def match_one(self, addr: bytes, port: Optional[int] = None) -> int:
        if self.backend != "host" and len(self._nets) <= SMALL_TABLE:
            return self._scan_one(addr, port)
        return int(self.match([addr], None if port is None else [port])[0])
