"""ClassifyEngine — the runtime seam between resources and the matchers.

This is the TPU analog of the reference's per-connection match loops:
components (Upstream, SecurityGroup, switch Table, DNSServer) register
their rules here; data-plane code calls the batched query API. Mirrors
the reference's provider SPI (-Dvfd, FDProvider.java:12-45) as
`backend="jax" | "jax-dense" | "host"`:

* "host"      — the pure-Python oracle (correctness fallback + latency
                floor for tiny tables).
* "jax"       — DEFAULT: cuckoo-hash classify kernels (ops/hashmatch):
                O(1) probes per query, byte-verified (exact regardless
                of hash behavior), gather-bound.
* "jax-fp"    — packed fingerprint kernels (ops/fphash): ~25x fewer
                gathered rows per query than "jax" (the measured cost
                driver, PERF_NOTES.md). Exact for every key in the
                table; a query key NOT in the table can false-positive
                with probability 2^-64 per probe. The throughput path —
                bench.py's 100k-rule TPU numbers ride this backend.
* "jax-dense" — the dense matmul kernels (ops/matchers): O(rules) MXU
                work per query; kept as the brute-force cross-check and
                for rule-axis mesh sharding experiments.
* "jax-sharded" — the cuckoo-hash kernels SPMD over a (batch, rules)
                device mesh (parallel/mesh): each device holds a
                contiguous rule slice compiled into its own table and
                the winner rides pmax/pmin ICI collectives. Rule
                updates reuse caps (same shapes, no retrace); an update
                that outgrows the caps (ops.hashmatch.CapsExceeded)
                transparently rebuilds tables — the jitted fn simply
                retraces on the new shapes.
* "jax-fp-sharded" — the packed fingerprint kernels over the same mesh
                machinery: per-shard fp tables under one unified caps
                dict, same pmax/pmin winner reduction. The multi-chip
                form of the throughput path.

Rule updates never retrace: tables are fixed-capacity (padded), and an
update recompiles numpy arrays and re-uploads same-shape buffers.
Capacity (or a cuckoo bucket tier) grows when exceeded, which
recompiles the jitted matcher once for the new shapes.

Generation installs are DOUBLE-BUFFERED (the Pope MLSys'23 weight-swap
idiom applied to rule tables): set_rules()/set_networks() hand the new
rule list to a process-wide background installer (TableInstaller) that
compiles and device_puts a STANDBY table while dispatchers keep
serving the published generation, then publishes by one atomic tuple
swap. Dispatchers never wait on compilation — a 1M-rule compile, a
slow device upload, or an armed `engine.swap.stall` failpoint delays
only the install, never a query. Every publish bumps the matcher's
`generation`, records `vproxy_engine_swap_ms`, and refreshes the
`vproxy_engine_table_bytes{matcher}` accounting.
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Optional, Sequence

import numpy as np

from ..ops import hashmatch as H
from ..ops import tables as T
from ..ops.bitmatch import unpack_bits
from ..ops.matchers import cidr_match_jit, hint_match_jit, table_arrays
from ..utils.log import Logger
from . import oracle
from .ir import AclRule, Hint, HintRule, Proto

_log = Logger("engine")


def mesh_serving() -> bool:
    """True when matchers without an explicit backend should serve SPMD
    over the device mesh. VPROXY_TPU_MESH_SERVE: "1"/"on" forces it,
    "0"/"off" disables, "auto" (default) shards whenever the mesh spans
    more than one REAL accelerator device. Virtual host-platform CPU
    devices (XLA_FLAGS=--xla_force_host_platform_device_count=N) are
    opt-in ("1"): they share one socket, so SPMD there buys rule-table
    capacity per device but ~3x dispatch latency (measured r08) — the
    right default for tests/bench scale runs, the wrong one for every
    small-table matcher in the process."""
    mode = os.environ.get("VPROXY_TPU_MESH_SERVE", "auto")
    if mode in ("0", "off", "no"):
        return False
    import jax
    try:
        devs = jax.devices()
    except Exception:
        return False
    if len(devs) <= 1:
        return False
    if mode in ("1", "on", "yes"):
        return True
    return devs[0].platform != "cpu"


def default_backend() -> str:
    """VPROXY_TPU_MATCHER when set; otherwise the mesh-sharded backend
    (VPROXY_TPU_MESH_BACKEND, default the byte-verified "jax-sharded")
    when mesh_serving() says the device mesh should carry the tables,
    else the single-device "jax" path."""
    env = os.environ.get("VPROXY_TPU_MATCHER")
    if env:
        return env
    if mesh_serving():
        return os.environ.get("VPROXY_TPU_MESH_BACKEND", "jax-sharded")
    return "jax"


_MESH: Optional[tuple] = None  # ((devices...), batch) -> Mesh


def default_mesh():
    """Process-wide (batch, rules) mesh for jax-sharded matchers; batch
    axis size from VPROXY_TPU_MESH_BATCH (default 1 = rules-only).

    Keyed on the CURRENT device set + batch knob, not cached forever: a
    device-count change after first use (a test-forced mesh, a late
    jax.distributed bring-up) must produce a fresh mesh, not silently
    serve the stale one."""
    global _MESH
    import jax
    from ..parallel import mesh as M
    batch = int(os.environ.get("VPROXY_TPU_MESH_BATCH", "1"))
    key = (tuple(jax.devices()), batch)
    if _MESH is None or _MESH[0] != key:
        _MESH = (key, M.make_mesh(batch=batch))
    return _MESH[1]


def pad_batch(n: int, mult: int = 1, lo: int = 16) -> int:
    """Batch-shape bucket: pow2 growth from `lo`, rounded up to a
    multiple of `mult` (the mesh batch-axis size, so the axis always
    divides the padded batch). ClassifyService uses the same buckets
    (mult=1) so the jitted matchers see few trace shapes."""
    c = lo
    while c < n:
        c <<= 1
    return -(-c // mult) * mult


# Below this rule count, single (unbatched) queries run on the host oracle:
# a python scan over a handful of rules is ~1us while a device dispatch is
# ~1ms — the device path wins only for big tables or batched queries. The
# device table is still compiled and kept in sync (used by match() batches).
SMALL_TABLE = int(os.environ.get("VPROXY_TPU_SMALL_TABLE", "128"))


def _to_device(arrs: dict) -> dict:
    import jax
    import jax.numpy as jnp
    out = {}
    for k, v in arrs.items():
        if v.dtype == np.float32 and v.ndim == 2:  # matmul weights -> bf16
            out[k] = jax.device_put(jnp.asarray(v, dtype=jnp.bfloat16))
        else:
            out[k] = jax.device_put(v)
    return out


def _sync_standby(dev) -> None:
    """Materialize a standby table's device buffers BEFORE the publish
    swap: device_put is async, and an unsynced publish makes the first
    post-swap dispatch eat the whole table transfer (measured ~30ms
    spikes at 20k rules — the install thread must pay that wait, never
    a serving thread). Best-effort: a backend whose block_until_ready
    lies (axon tunnel) just keeps the old behavior."""
    if not dev:
        return
    import jax
    try:
        jax.block_until_ready(list(dev.values()))
    except Exception:
        pass


def _install_phase(tid: int, span: str, t0_ns: int, **fields) -> None:
    """One standby-install phase span (compile / upload / swap) on the
    installer's trace (utils/trace) — tid 0 (constructor compiles, or
    tracing off) records nothing."""
    if tid:
        from ..utils import trace
        trace.record_span(tid, "install", span, t0_ns,
                          time.monotonic_ns() - t0_ns, **fields)


# batch padding at the ARRAY level: a pad row must read as "no probes,
# no match" to the kernel. The cuckoo query arrays mark invalid probes
# with -1 (slot/len); everything else (fp fingerprints, byte windows,
# flags) zero-fills — exactly what encoding an empty Hint() produces,
# without paying the encode for it.
_PAD_CUCKOO = {"hp_len": -1, "hp_slot1": -1, "hp_slot2": -1,
               "up_len": -1, "up_slot1": -1, "up_slot2": -1}


def _pad_hint_q(q: dict, cap: int, fills: dict) -> dict:
    out = {}
    for k, v in q.items():
        n = v.shape[0]
        if n >= cap:
            out[k] = v
            continue
        pad = np.full((cap - n,) + v.shape[1:], fills.get(k, 0), v.dtype)
        out[k] = np.concatenate([v, pad])
    return out


# --------------------------------------------- generation-install plumbing
#
# Process-wide accounting of published table generations, surfaced on
# /metrics (utils/metrics) and in `list-detail upstream`:
#   vproxy_engine_generation      — total generation publishes
#   vproxy_engine_swap_ms         — install latency histogram (compile +
#                                   upload + publish, background thread)
#   vproxy_engine_table_bytes{matcher="hint"|"cidr"} — device bytes of
#                                   every live matcher's published table

_gen_lock = threading.Lock()
_GENERATION = [0]
_MATCHERS: "weakref.WeakSet" = weakref.WeakSet()
_LAST_SERVE = [0.0]  # monotonic ts of the last serving-path read
_LAUNCHES = [0]      # device launches on the dispatch path (per batch)
_FUSED_DISP = [0]    # of which: fused one-launch dispatches


def note_serving() -> None:
    """Serving-path breadcrumb (one float store): dispatch_snap /
    index_snap and the classify submit path mark activity so the
    installer only PACES standby compiles when there is serving
    latency to protect — a batch config apply on an idle process
    builds at full speed."""
    _LAST_SERVE[0] = time.monotonic()


def serving_recent(window_s: float = 5.0) -> bool:
    return time.monotonic() - _LAST_SERVE[0] < window_s


def generation_total() -> int:
    return _GENERATION[0]


def note_launch(n: int = 1, kind: str = "", fused: bool = False) -> None:
    """Count one device launch on the dispatch path (a lock-free int
    store race can only lose a count, never corrupt — same contract as
    the C-side counters). This is what makes the fused path's
    one-launch-per-batch claim SCRAPE-verifiable
    (vproxy_engine_dispatch_launches_total) instead of bench-asserted:
    every jitted submit site increments it, so fused batches move the
    counter by exactly 1 and the unfused chain by one per chained op.

    Tracing (utils/trace): when the calling thread carries a sampled
    request's trace context, every launch site also drops a `launch`
    marker span — fused vs unfused distinguishable per launch, so a
    trace shows exactly how many programs a batch really cost. One
    branch when no context is bound."""
    _LAUNCHES[0] += n
    from ..utils import trace
    tid = trace.current_id()
    if tid:
        trace.record_span(tid, "engine", "launch", time.monotonic_ns(),
                          0, kind=kind, fused=fused)


def dispatch_launches_total() -> int:
    return _LAUNCHES[0]


def fused_dispatches_total() -> int:
    return _FUSED_DISP[0]


def table_bytes_total(kind: str) -> int:
    """Sum of published device-table bytes across live matchers of one
    kind ("hint" | "cidr"). The WeakSet snapshot rides _gen_lock —
    matcher constructors add concurrently, and CPython raises on a set
    mutated mid-iteration (a scrape must never lose to a config
    apply)."""
    with _gen_lock:
        matchers = list(_MATCHERS)
    total = 0
    for m in matchers:
        if m._kind == kind:
            total += m.published_table_bytes()
    return total


def _swap_hist():
    # pre-registered (reservoir config included) in
    # GlobalInspection.__init__ — this resolves to that instance
    from ..utils.metrics import GlobalInspection
    return GlobalInspection.get().get_histogram("vproxy_engine_swap_ms")


class _InstallTicket:
    """One caller's claim on a pending install; `exc` carries the
    compile failure back to a waiting set_rules()."""

    __slots__ = ("ev", "exc")

    def __init__(self):
        self.ev = threading.Event()
        self.exc: Optional[BaseException] = None


class TableInstaller:
    """The double-buffer worker: compiles + uploads STANDBY tables off
    the mutation path, one install at a time, then lets the matcher
    publish with an atomic tuple swap.

    * set_rules()/set_networks() enqueue (args, payload) and by default
      WAIT for the publish (read-your-writes for config handlers and
      the cluster replication checksum gate); wait=False callers get a
      ticket they can ignore.
    * dispatchers never wait: they read the published snapshot, which
      only ever changes by one atomic assignment AFTER the standby
      table is fully built and uploaded.
    * back-to-back installs for one matcher COALESCE: only the newest
      pending rule list compiles; earlier waiters are released by the
      newer publish (their write was superseded — same last-writer-wins
      outcome as racing synchronous compiles, at one compile's cost).
    * the compile yields the GIL between phases (sleep(0)) so a
      million-rule build starves inline accept-path answers by at most
      one interpreter slice, not whole seconds.
    * failpoint `engine.swap.stall` sleeps VPROXY_TPU_SWAP_STALL_S
      inside the worker — the provable "slow install stalls nothing"
      edge.
    """

    _instance: Optional["TableInstaller"] = None
    _ilock = threading.Lock()

    @classmethod
    def get(cls) -> "TableInstaller":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = TableInstaller()
            return cls._instance

    def __init__(self):
        self._cv = threading.Condition()
        # id(matcher) -> (matcher, args, [tickets]); order preserved
        self._jobs: dict[int, tuple] = {}
        self._order: list[int] = []
        self._inflight = 0
        self._thread: Optional[threading.Thread] = None

    def submit(self, matcher, args: tuple) -> _InstallTicket:
        t = _InstallTicket()
        with self._cv:
            key = id(matcher)
            job = self._jobs.get(key)
            if job is None:
                self._jobs[key] = (matcher, args, [t])
                self._order.append(key)
            else:  # coalesce: newest rules win, all waiters ride along
                self._jobs[key] = (matcher, args, job[2] + [t])
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="engine-install", daemon=True)
                self._thread.start()
            self._cv.notify()
        return t

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every pending install published (True) or the
        timeout passed (False). The cluster replication gate calls this
        before checksumming so a wait=False mutation can never pair an
        old table checksum with a new generation."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._jobs or self._inflight:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cv.wait(0.05 if left is None else min(left, 0.05))
        return True

    def _run(self) -> None:
        from ..ops.cuckoo import set_build_pacing
        from ..utils import failpoint
        try:
            # background-priority: the standby compile must lose every
            # scheduling fight with a serving thread. GIL handoff is
            # interval-driven either way (service shrinks it to ~1ms),
            # but the compile's GIL-released phases (numpy, XLA
            # compile, device transfers) otherwise steal the serving
            # path's cores — measured 5x p99 inflation on a shared
            # socket without this.
            os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 15)
        except (AttributeError, OSError, PermissionError):
            pass  # non-linux / restricted: yields below still apply
        while True:
            with self._cv:
                while not self._order:
                    self._cv.wait(1.0)
                key = self._order.pop(0)
                matcher, args, tickets = self._jobs.pop(key)
                self._inflight += 1
            exc: Optional[BaseException] = None
            try:
                if failpoint.hit("engine.swap.stall"):
                    # a deliberately slow compile: dispatch must keep
                    # answering the old generation for this whole sleep
                    time.sleep(float(os.environ.get(
                        "VPROXY_TPU_SWAP_STALL_S", "0.5")))
                # standby-compile pacing: each cooperative yield in the
                # build hot loops sleeps ~r x the work since the last
                # one, capping this thread's CPU/GIL duty at 1/(1+r). A
                # full-speed compile costs serving threads ~half the
                # GIL (measured ~2.5x dispatch p99); pacing trades
                # install latency (background, invisible by design)
                # for flat serving latency. Re-read per job:
                # VPROXY_TPU_INSTALL_PACE=0 disables (tests, batch
                # loads with no concurrent serving). Applied ONLY
                # when the serving path was active in the last few
                # seconds (note_serving) — an idle batch apply
                # builds at full speed.
                set_build_pacing(float(os.environ.get(
                    "VPROXY_TPU_INSTALL_PACE", "6"))
                    if serving_recent() else 0.0)
                t0 = time.monotonic()
                time.sleep(0)  # explicit preemption point pre-compile
                # installs are rare: when tracing is on, EVERY install
                # gets its own trace — _recompile's phase spans
                # (compile / upload / swap) attach through the bound
                # context, so an install-under-load trace shows the
                # standby build bracketing unstalled dispatches
                from ..utils import trace
                itid = trace.new_trace_id() if trace.enabled() else 0
                with trace.bind(itid):
                    matcher._install(args)
                if itid:
                    trace.record_span(
                        itid, "install", "install", int(t0 * 1e9),
                        int((time.monotonic() - t0) * 1e9),
                        matcher=getattr(matcher, "_kind", "?"))
                _swap_hist().observe((time.monotonic() - t0) * 1e3)
            except MemoryError as e:
                # OOM keeps the log-then-die contract (utils/oom), but
                # the waiters must still see a FAILED install — a
                # survivor embedding without the oom handler would
                # otherwise ack a mutation that never landed
                exc = e
                raise
            except BaseException as e:  # noqa: BLE001 — ticketed
                exc = e
                _log.error("standby table install failed; serving "
                           "generation unchanged", exc=True)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()
                for t in tickets:
                    t.exc = exc
                    t.ev.set()


def flush_installs(timeout: Optional[float] = None) -> bool:
    """Convenience: wait for all pending generation installs (no-op
    when the installer never started)."""
    inst = TableInstaller._instance
    return True if inst is None else inst.flush(timeout)


# --------------------------------------------- fused classify+pick entry
#
# ops/fused.py packs the compiled hash tables into int8/int32 layouts
# (one meta row + one byte row per rule, one slot row per cuckoo slot)
# and compiles the whole dispatch chain — probe, gather, verdict
# resolve, Maglev pick, optionally the cidr/LPM walk — into ONE jitted
# program. The packed arrays are built INSIDE the matcher's standby
# compile below, so they publish through the same TableInstaller
# atomic-swap as every other table: a fused reader can never pair one
# generation's probe salts with another's packed records.

def fused_enabled() -> bool:
    """VPROXY_TPU_FUSED (default on): build packed tables on "jax"
    matchers and serve classify+pick from the fused one-launch entry.
    Off restores the overlapped two-dispatch chain (the A/B lever)."""
    return os.environ.get("VPROXY_TPU_FUSED", "1") != "0"


_FUSED_FN: dict = {}


def _fused_fn():
    """The compiled fused entry for the CURRENT knob state. Keyed on
    fused.layout_key() — packed-layout version + the VPROXY_TPU_*
    kernel knobs — NOT cached forever: a knob change mid-process must
    select a fresh compiled program, never serve the stale one (the
    PR-6 stale-mesh bug family; regression-tested)."""
    from ..ops import fused as F
    key = F.layout_key()
    fn = _FUSED_FN.get(key)
    if fn is None:
        fn = F.fused_jit
        if F.kernel_mode() in ("pallas", "auto"):
            from ..ops import fused_pallas as FP
            ok, why = FP.pallas_supported()
            # "auto" requires a COMPILED probe pass: interpret mode is
            # the bit-verify lane (~100x slower per batch), so it only
            # serves under an explicit kernel=pallas — exporting
            # VPROXY_TPU_PALLAS_INTERPRET=1 to verify must never flip
            # production serving onto the interpreter
            if ok and (F.kernel_mode() == "pallas"
                       or not FP.interpret_forced()):
                fn = FP.fused_classify_pick_pallas
            elif F.kernel_mode() == "pallas":
                _log.warn(f"VPROXY_TPU_FUSED_KERNEL=pallas but the "
                          f"capability probe refused ({why}); serving "
                          f"the fused jit tier")
        _FUSED_FN[key] = fn
    return fn


def fused_kernel_name() -> str:
    """Which tier the fused entry serves with right now ("jit" or
    "pallas") — surfaced in the HTTP engine object. Reported from
    CACHED state only: a stat read (list-detail / HTTP detail on the
    control thread) must never run the Pallas capability probe, whose
    first pass compiles and dispatches a kernel — the control-path
    stall class PR-10 moved the steering rebuild to avoid. Before the
    first fused dispatch resolves the tier, the answer is the jit
    default."""
    from ..ops import fused as F
    from ..ops import fused_pallas as FP
    fn = _FUSED_FN.get(F.layout_key())
    if fn is not None:
        return "pallas" if fn is FP.fused_classify_pick_pallas else "jit"
    if F.kernel_mode() in ("pallas", "auto"):
        probe = FP.probe_cached()
        if probe is not None and probe[0] and \
                (F.kernel_mode() == "pallas"
                 or not FP.interpret_forced()):
            return "pallas"
    return "jit"


def _fused_stat(fd: Optional[dict]) -> dict:
    """Fused-dispatch state for the operator surfaces (list-detail
    upstream / HTTP engine object) — ONE shape for both matcher kinds:
    packed-table availability, device bytes, serving kernel tier."""
    if fd is None:
        return {"available": False}
    return {"available": True, "kernel": fused_kernel_name(),
            "packed_bytes": int(sum(getattr(v, "nbytes", 0)
                                    for v in fd.values()))}


def fused_dispatch(hm, hsnap: tuple, mm, msnap: tuple, hints,
                   ips: Sequence[bytes],
                   ports: Optional[Sequence[int]] = None,
                   pad_to: Optional[int] = None):
    """ONE launch answering (verdict, pick) for a batch: encoded hint
    queries + host-side Maglev slots into the fused program against
    one (hint, maglev) snapshot pair. Returns the async int32 [B, 2]
    device array, or None when the fused path is unavailable for
    these snapshots (non-"jax" backend, VPROXY_TPU_FUSED=0, or a
    pre-fused publish) — callers fall back to the two-dispatch chain."""
    if not hints or len(hints) != len(ips):
        return None
    fd = hsnap[5] if len(hsnap) > 5 else None
    if fd is None or not hsnap[2]:
        return None
    mtab, mdev = msnap[0], msnap[1]
    if mtab is None or mdev is None:
        return None
    note_serving()
    q = _fused_hint_q(hsnap[0], hints, pad_to)
    slots = _fused_slots(mtab, ips, ports, q["hostb"].shape[0])
    fn = _fused_fn()
    note_launch(kind="cpick", fused=True)
    _FUSED_DISP[0] += 1
    return fn(fd, q, mdev, slots)


def fused_dispatch_all(hm, hsnap: tuple, cm, csnap: tuple, mm,
                       msnap: tuple, hints, addrs: Sequence[bytes],
                       ips: Sequence[bytes],
                       ports: Optional[Sequence[int]] = None,
                       pad_to: Optional[int] = None):
    """The full fused sweep: hint verdict + cidr/LPM route + Maglev
    pick, one launch, int32 [B, 3] (verdict, pick, route). Route
    queries carry no ACL port gate (route-table semantics, ports=None
    in CidrMatcher.dispatch_snap). Always the jit tier — the Pallas
    kernel covers the (verdict, pick) serving contract; the 3-column
    form is the bench/step-loop shape. None when either packed table
    is missing (fallback: the op chain)."""
    if not hints or len(hints) != len(addrs) or len(hints) != len(ips):
        return None
    fd = hsnap[5] if len(hsnap) > 5 else None
    cfd = csnap[6] if len(csnap) > 6 else None
    if fd is None or cfd is None or not hsnap[2] or not csnap[1]:
        return None
    mtab, mdev = msnap[0], msnap[1]
    if mtab is None or mdev is None:
        return None
    note_serving()
    q = _fused_hint_q(hsnap[0], hints, pad_to)
    cap = q["hostb"].shape[0]
    slots = _fused_slots(mtab, ips, ports, cap)
    a16, fam = T.encode_ips(addrs)
    if cap > a16.shape[0]:
        k = cap - a16.shape[0]
        a16 = np.concatenate([a16, np.zeros((k,) + a16.shape[1:],
                                            a16.dtype)])
        fam = np.concatenate([fam, np.full(k, -1, fam.dtype)])
    from ..ops import fused as F
    note_launch(kind="all", fused=True)
    _FUSED_DISP[0] += 1
    return F.fused_jit(fd, q, mdev, slots, cfd, a16, fam, None)


def _fused_hint_q(tab, hints, pad_to: Optional[int]) -> dict:
    q = H.encode_hint_queries(hints, tab, pad_to=pad_to or 0)
    if pad_to and q["hostb"].shape[0] < pad_to:
        q = _pad_hint_q(q, pad_to, _PAD_CUCKOO)
    return q


def _fused_slots(mtab, ips, ports, cap: int) -> np.ndarray:
    """Host-side Maglev slots (maglev.flow_slots — THE one copy of the
    slot-hash contract, so fused picks are bit-identical to every
    other pick plane); pad rows ride slot 0 and are sliced off by the
    caller."""
    from .maglev import flow_slots
    slots = flow_slots(len(mtab), ips, ports)
    if cap > len(slots):
        slots = np.concatenate([slots, np.zeros(cap - len(slots),
                                                np.int64)])
    return slots


class HintMatcher:
    """Device-backed (or host-fallback) Upstream/DNS hint matcher."""

    _kind = "hint"

    def __init__(self, rules: Sequence[HintRule] = (), backend: Optional[str] = None,
                 payload=None, mesh=None):
        self.backend = backend or default_backend()
        self._rules: list[HintRule] = list(rules)
        self._dev: Optional[dict] = None
        self._tab = None  # hash-path table meta
        self._caps: Optional[dict] = None
        self._mesh = mesh  # jax-sharded only (lazily defaulted)
        self._fn = None    # jax-sharded jitted matcher (shape-agnostic)
        self.generation = 0  # bumps on every publish (atomic swap)
        # (tab, dev, rules, payload, index) published as ONE tuple so
        # concurrent readers (the ClassifyService dispatcher) never see a
        # torn table/rule/payload version across a set_rules() swap;
        # `payload` is an opaque owner-supplied object versioned WITH the
        # rules (e.g. Upstream's GroupHandle list) so a matched index is
        # always interpreted against the same generation it was matched
        # in; `index` is the O(probes) host-side HintIndex the latency
        # budget policy answers lone queries from (rules/index.py)
        self._pub: tuple = (None, None, [], payload, None)
        self._payload = payload
        self._cksum = None  # (pub-tuple, crc32) cache — see checksum()
        self._recompile()
        with _gen_lock:
            _MATCHERS.add(self)

    @property
    def rules(self) -> list[HintRule]:
        return list(self._pub[2])  # the PUBLISHED generation

    def set_rules(self, rules: Sequence[HintRule], payload=None,
                  wait: bool = True) -> None:
        """Install a new rule generation via the background
        TableInstaller (standby compile + atomic publish). wait=True
        (default) blocks THIS caller until the publish — dispatchers
        never block either way; wait=False returns immediately (the
        caller reads the old generation until the swap lands)."""
        t = TableInstaller.get().submit(self, (list(rules), payload))
        if wait:
            t.ev.wait()
            if t.exc is not None:
                raise t.exc

    def _install(self, args: tuple) -> None:
        """TableInstaller worker entry: compile + publish one standby
        generation (never called concurrently — one installer thread).
        Transactional: a failed compile restores the serving rule list
        so every read surface still describes the published table."""
        rules, payload = args
        old = (self._rules, self._payload, self._tab, self._dev,
               self._caps)
        self._rules = list(rules)
        self._payload = payload
        try:
            self._recompile()
        except BaseException:
            # restore EVERYTHING a reader or the next recompile touches
            # — a half-updated (_tab, _dev) pair would hash queries
            # with one generation's salts against the other's table
            (self._rules, self._payload, self._tab, self._dev,
             self._caps) = old
            raise

    def published_table_bytes(self) -> int:
        """Device bytes of the published generation's table arrays."""
        dev = self._pub[1]
        if not dev:
            return 0
        return int(sum(getattr(v, "nbytes", 0) for v in dev.values()))

    def _recompile(self) -> None:
        from ..utils import trace
        itid = trace.current_id()  # nonzero only under a traced install
        t_ph = time.monotonic_ns() if itid else 0
        if self.backend == "jax":
            self._tab = H.compile_hint_hash(self._rules, caps=self._caps)
            self._caps = self._tab.caps
            self._dev = _to_device(self._tab.arrays)
        elif self.backend == "jax-fp":
            from ..ops import fphash as F
            try:
                self._tab = F.compile_hint_fp(self._rules, caps=self._caps)
            except H.CapsExceeded:
                # update outgrew the reused shapes: fresh build (the
                # jitted matcher retraces on the new shapes)
                self._tab = F.compile_hint_fp(self._rules)
            self._caps = self._tab.caps
            self._dev = _to_device(self._tab.arrays)
        elif self.backend in ("jax-sharded", "jax-fp-sharded"):
            from ..parallel import mesh as M
            if self._mesh is None:
                self._mesh = default_mesh()
            shards = self._mesh.shape["rules"]
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                compile_sharded = F.compile_hint_fp_sharded
            else:
                compile_sharded = H.compile_hint_hash_sharded
            try:
                self._tab = compile_sharded(self._rules, shards,
                                            caps=self._caps)
            except H.CapsExceeded:
                # update outgrew the reused shapes: transparent rebuild
                # (the jitted fn retraces on the new shapes)
                self._tab = compile_sharded(self._rules, shards)
            self._caps = self._tab.shards[0].caps
            self._dev = M.shard_hash_table(self._tab, self._mesh)
            # memory-lean: the stacked host copy is dead weight once the
            # device holds the shards (a 1M-rule standby would otherwise
            # hold table bytes THREE times mid-install); ndims survive
            # for the jitted-fn spec build
            M.release_host(self._tab)
            # _fn is NOT reset: it closes over key ndims + kernel only,
            # and jit re-specializes on shape changes by itself — the
            # caps-reuse no-retrace contract depends on keeping it
        elif self.backend == "jax-dense":
            cap = self._dev["active"].shape[0] if self._dev is not None else None
            if cap is not None and len(self._rules) > cap:
                cap = None  # outgrew capacity: let the compiler pick a bucket
            tab = T.compile_hint_rules(self._rules, cap=cap)
            self._dev = _to_device(table_arrays(tab))
        idx = None
        # small tables answer lone queries with the linear oracle (the
        # same crossover match_one uses), so the index build — a second
        # O(rules) bucket construction on the update path — only pays
        # for itself past SMALL_TABLE. Built for EVERY backend: the
        # inline accept path serves host-backend matchers too, and a
        # big table must never put an O(rules) scan on an event loop
        if len(self._rules) > SMALL_TABLE:
            from .index import HintIndex
            idx = HintIndex(self._rules)
        # packed fused-dispatch tables (ops/fused.py): built in THIS
        # standby compile and published in the SAME atomic tuple swap —
        # the fused reader's generation consistency is the pub tuple's
        fused_dev = None
        if self.backend == "jax" and fused_enabled():
            from ..ops import fused as F
            fused_dev = _to_device(F.pack_hint_table(self._tab.arrays))
        _install_phase(itid, "compile", t_ph, matcher="hint",
                       rules=len(self._rules))
        t_ph = time.monotonic_ns() if itid else 0
        _sync_standby(self._dev)
        _sync_standby(fused_dev)
        _install_phase(itid, "upload", t_ph, matcher="hint")
        time.sleep(0)  # preemption point between compile and publish
        t_ph = time.monotonic_ns() if itid else 0
        self._pub = (self._tab, self._dev, list(self._rules), self._payload,
                     idx, fused_dev)
        self.generation += 1
        with _gen_lock:
            _GENERATION[0] += 1
        _install_phase(itid, "swap", t_ph, matcher="hint",
                       generation=self.generation)

    def encode(self, hints: Sequence[Hint]) -> dict:
        """Pre-encode a query batch for submit() (hash backend only).
        Bound to the current table version — re-encode after set_rules."""
        assert self.backend == "jax"
        return H.encode_hint_queries(hints, self._tab)

    def submit(self, q: dict):
        """Dispatch an encoded batch; returns the device array (async)."""
        note_launch(kind="hint")
        idx, _ = H.hint_hash_jit(self._dev, q)
        return idx

    def fused_stat(self) -> dict:
        """See engine._fused_stat — packed hint-table state."""
        pub = self._pub
        return _fused_stat(pub[5] if len(pub) > 5 else None)

    def match(self, hints: Sequence[Hint]) -> np.ndarray:
        """-> int32 [B] matched rule index, -1 for none."""
        snap = self._pub
        if self.backend == "host" and snap[2] and hints:
            return np.array([oracle.search(snap[2], h) for h in hints],
                            np.int32)
        return np.asarray(self.dispatch_snap(snap, hints))

    def match_one(self, hint: Hint) -> int:
        # PUBLISHED rules, never self._rules: a standby install mutates
        # the latter seconds before the atomic publish, and a serving
        # read must not route by a generation no surface reports yet
        pub = self._pub
        if self.backend != "host" and len(pub[2]) <= SMALL_TABLE:
            return oracle.search(pub[2], hint)
        return int(self.match([hint])[0])

    # ---- ClassifyService API (rules/service.py) ----

    def size(self) -> int:
        return len(self._pub[2])

    def checksum(self) -> int:
        """u32 checksum of the PUBLISHED rule generation (crc32 over the
        canonical rule reprs): two hosts whose tables compiled from the
        same rule list hash identically regardless of caps-growth
        history. The cluster replication gate (cluster/replicate.py)
        compares this across hosts before installing a generation.
        Computed once per generation (cached at publish): replication
        polls read it every few hundred ms and must not pay an O(rules)
        string build each time."""
        pub = self._pub
        cached = self._cksum
        if cached is not None and cached[0] is pub:
            return cached[1]
        import zlib
        v = zlib.crc32("\n".join(map(repr, pub[2])).encode())
        self._cksum = (pub, v)
        return v

    def snapshot(self) -> tuple:
        """One consistent (table, device, rules, payload) generation."""
        return self._pub

    @staticmethod
    def snap_payload(snap: tuple):
        return snap[3]

    def oracle_snap(self, snap: tuple, hint: Hint) -> int:
        return oracle.search(snap[2], hint)

    def index_snap(self, snap: tuple, hint: Hint) -> int:
        """O(probes) host lookup against the snapshot's HintIndex (same
        winner as oracle_snap); falls back to the linear oracle when the
        snapshot has no index (host backend)."""
        note_serving()
        idx = snap[4] if len(snap) > 4 else None
        if idx is None:
            return oracle.search(snap[2], hint)
        return idx.lookup(hint)

    def oracle_one(self, hint: Hint) -> int:
        return self.oracle_snap(self._pub, hint)

    def dispatch_snap(self, snap: tuple, hints: Sequence[Hint],
                      pad_to: Optional[int] = None, sync: bool = True):
        """Encode + submit one batch against the snapshotted table
        generation (async device result; np.asarray() it to block).

        pad_to: target batch shape (a pad_batch bucket). The hash
        backends encode ONLY the real hints and zero/invalid-fill the
        probe arrays to the bucket — the dispatch path never pays the
        rolling-hash passes for padding rows (they cost the same numpy
        work as real queries).

        sync=False (the service's double-buffered dispatcher): the
        sharded backends return the RAW padded device output instead of
        to_local()[:n] — to_local materializes (np.asarray) on a
        single process, which would silently turn the "async" submit
        into a full round-trip wait. The caller np.asarray()s and
        slices at finish time. Multi-process meshes still to_local here
        (shard dedup needs it)."""
        note_serving()
        tab, dev, rules = snap[0], snap[1], snap[2]
        if not rules or not hints:
            return np.full(len(hints), -1, np.int32)
        note_launch(kind="hint")  # every branch below is one dispatch
        if self.backend == "jax":
            # ONE copy of the encode+pad idiom, shared with the fused
            # entry: small batches encode straight into the padded
            # bucket (the per-hint python path); big ones encode the
            # real rows then array-pad with invalid probes
            idx, _ = H.hint_hash_jit(dev,
                                     _fused_hint_q(tab, hints, pad_to))
            return idx
        if self.backend == "jax-fp":
            from ..ops import fphash as F
            q = F.encode_hint_queries_fp(hints, tab)
            if pad_to and pad_to > len(hints):
                q = _pad_hint_q(q, pad_to, {})
            # resolve the member-mode env knob HERE, per dispatch: jit
            # keys on the static mode arg, so passing None would bake
            # the first dispatch's VPROXY_TPU_FP_MEMBER into the cache
            # and silently ignore later changes (stale lowering)
            idx, _ = F.hint_fp_jit(dev, q, mode=F.default_member_mode())
            return idx
        if self.backend in ("jax-sharded", "jax-fp-sharded"):
            from ..parallel import mesh as M
            from ..parallel.mesh import query_shards
            n = len(hints)
            cap = pad_batch(max(n, pad_to or 0), query_shards(self._mesh))
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                padded = list(hints) + [Hint()] * (cap - n)
                q = F.encode_hint_queries_fp_sharded(padded, tab)
                kernel = F.hint_fp_match
            else:
                # single-pass multi-salt encode: one rolling-hash pass
                # serves every shard (the old path re-encoded per shard
                # — 8x the host cost of the whole dispatch)
                q = H.encode_hint_queries_sharded(hints, tab, pad_to=cap)
                kernel = None
            qd = M.shard_hint_queries_sharded(q, self._mesh)
            if self._fn is None:
                self._fn = M.make_sharded_hint_fn(
                    self._mesh, {k: v.ndim for k, v in tab.arrays.items()},
                    {k: v.ndim for k, v in q.items()}, kernel=kernel)
            out = self._fn(dev, qd, np.int32(tab.shard_size))
            if not sync:
                import jax
                if jax.process_count() <= 1:
                    return out  # async: caller syncs + slices
            # to_local: this process's slice on a multi-process mesh,
            # plain np.asarray single-process
            return M.to_local(out)[:n]
        if pad_to and pad_to > len(hints):
            hints = list(hints) + [Hint()] * (pad_to - len(hints))
        q = T.encode_hints(hints)
        idx, _ = hint_match_jit(
            dev, q["host"], q["has_host"], unpack_bits(q["uri"]),
            q["has_uri"], q["port"])
        return idx


class CidrMatcher:
    """Device-backed ordered first-match CIDR matcher (routes / ACL)."""

    _kind = "cidr"

    def __init__(self, networks: Sequence = (), backend: Optional[str] = None,
                 acl: Optional[Sequence[AclRule]] = None, payload=None,
                 mesh=None):
        self.backend = backend or default_backend()
        self._nets = list(networks)
        self._acl = list(acl) if acl is not None else None
        self._dev: Optional[dict] = None
        self._caps: Optional[dict] = None
        self._tab = None   # jax-sharded stacked table meta
        self._mesh = mesh  # jax-sharded only (lazily defaulted)
        self._fns: dict = {}  # jax-sharded jitted fns keyed by with_port
        self.generation = 0  # bumps on every publish (atomic swap)
        # (dev, nets, acl, payload, tab, index) — one atomic generation
        # (see HintMatcher._pub for the why)
        self._pub: tuple = (None, [], None, payload, None, None)
        self._payload = payload
        self._cksum = None  # (pub-tuple, crc32) cache — see checksum()
        self._recompile()
        with _gen_lock:
            _MATCHERS.add(self)

    def set_networks(self, networks: Sequence, acl: Optional[Sequence[AclRule]] = None,
                     payload=None, wait: bool = True) -> None:
        """Install a new generation via the background TableInstaller
        (see HintMatcher.set_rules — same standby-swap contract)."""
        t = TableInstaller.get().submit(
            self, (list(networks),
                   list(acl) if acl is not None else None, payload))
        if wait:
            t.ev.wait()
            if t.exc is not None:
                raise t.exc

    def _install(self, args: tuple) -> None:
        """See HintMatcher._install — transactional standby compile."""
        networks, acl, payload = args
        old = (self._nets, self._acl, self._payload, self._tab,
               self._dev, self._caps)
        self._nets = list(networks)
        self._acl = list(acl) if acl is not None else None
        self._payload = payload
        try:
            self._recompile()
        except BaseException:
            (self._nets, self._acl, self._payload, self._tab,
             self._dev, self._caps) = old
            raise

    def published_table_bytes(self) -> int:
        dev = self._pub[0]
        if not dev:
            return 0
        return int(sum(getattr(v, "nbytes", 0) for v in dev.values()))

    def _recompile(self) -> None:
        from ..utils import trace
        itid = trace.current_id()  # nonzero only under a traced install
        t_ph = time.monotonic_ns() if itid else 0
        hash_arrays = None  # "jax" backend: source for the packed build
        if self.backend == "jax":
            tab = H.compile_cidr_hash(self._nets, acl=self._acl, caps=self._caps)
            self._caps = tab.caps
            self._dev = _to_device(tab.arrays)
            hash_arrays = tab.arrays
        elif self.backend == "jax-fp":
            from ..ops import fphash as F
            try:
                tab = F.compile_cidr_fp(self._nets, acl=self._acl,
                                        caps=self._caps)
            except H.CapsExceeded:
                tab = F.compile_cidr_fp(self._nets, acl=self._acl)
            self._caps = tab.caps
            self._dev = _to_device(tab.arrays)
        elif self.backend in ("jax-sharded", "jax-fp-sharded"):
            from ..parallel import mesh as M
            if self._mesh is None:
                self._mesh = default_mesh()
            shards = self._mesh.shape["rules"]
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                compile_sharded = F.compile_cidr_fp_sharded
            else:
                compile_sharded = H.compile_cidr_hash_sharded
            try:
                self._tab = compile_sharded(
                    self._nets, shards, acl=self._acl, caps=self._caps)
            except H.CapsExceeded:
                # update outgrew the reused shapes: transparent rebuild
                self._tab = compile_sharded(self._nets, shards,
                                            acl=self._acl)
            self._caps = self._tab.shards[0].caps
            self._dev = M.shard_hash_table(self._tab, self._mesh)
            M.release_host(self._tab)  # memory-lean: see HintMatcher
            # _fns kept: see HintMatcher._recompile
        elif self.backend == "jax-dense":
            cap = self._dev["allow"].shape[0] if self._dev is not None else None
            if cap is not None and len(self._nets) > cap:
                cap = None
            tab = T.compile_cidr_rules(self._nets, cap=cap, acl=self._acl)
            self._dev = _to_device(table_arrays(tab))
        idx = None
        if len(self._nets) > SMALL_TABLE:  # every backend: see HintMatcher
            from .index import CidrIndex
            idx = CidrIndex(self._nets, acl=self._acl)
        # packed fused-dispatch tables: same standby-build + atomic
        # pub-swap contract as HintMatcher._recompile
        fused_dev = None
        if hash_arrays is not None and fused_enabled():
            from ..ops import fused as F
            fused_dev = _to_device(F.pack_cidr_table(hash_arrays))
        _install_phase(itid, "compile", t_ph, matcher="cidr",
                       rules=len(self._nets))
        t_ph = time.monotonic_ns() if itid else 0
        _sync_standby(self._dev)
        _sync_standby(fused_dev)
        _install_phase(itid, "upload", t_ph, matcher="cidr")
        time.sleep(0)  # preemption point between compile and publish
        t_ph = time.monotonic_ns() if itid else 0
        self._pub = (self._dev, list(self._nets),
                     None if self._acl is None else list(self._acl),
                     self._payload, self._tab, idx, fused_dev)
        self.generation += 1
        with _gen_lock:
            _GENERATION[0] += 1
        _install_phase(itid, "swap", t_ph, matcher="cidr",
                       generation=self.generation)

    def fused_stat(self) -> dict:
        """See engine._fused_stat — packed cidr-table state."""
        pub = self._pub
        return _fused_stat(pub[6] if len(pub) > 6 else None)

    def match(self, addrs: Sequence[bytes],
              ports: Optional[Sequence[int]] = None) -> np.ndarray:
        """-> int32 [B] first matching rule index (order = insert order), -1
        for none."""
        snap = self._pub
        if self.backend == "host" and snap[1] and addrs:
            return np.array(
                [self.oracle_snap(snap, a, None if ports is None else ports[i])
                 for i, a in enumerate(addrs)], np.int32)
        return np.asarray(self.dispatch_snap(snap, addrs, ports))

    def _scan_one(self, addr: bytes, port: Optional[int]) -> int:
        return self.oracle_snap(self._pub, addr, port)

    def oracle_one(self, addr: bytes, port: Optional[int] = None) -> int:
        return self.oracle_snap(self._pub, addr, port)

    def match_one(self, addr: bytes, port: Optional[int] = None) -> int:
        # published-generation gate: see HintMatcher.match_one
        if self.backend != "host" and len(self._pub[1]) <= SMALL_TABLE:
            return self._scan_one(addr, port)
        return int(self.match([addr], None if port is None else [port])[0])

    # ---- ClassifyService API (rules/service.py) ----

    def size(self) -> int:
        return len(self._pub[1])

    def checksum(self) -> int:
        """u32 checksum of the published networks+ACL generation (see
        HintMatcher.checksum — the cluster replication gate; cached per
        published generation)."""
        snap = self._pub
        cached = self._cksum
        if cached is not None and cached[0] is snap:
            return cached[1]
        import zlib
        text = "\n".join(map(repr, snap[1]))
        if snap[2] is not None:
            text += "\n" + "\n".join(map(repr, snap[2]))
        v = zlib.crc32(text.encode())
        self._cksum = (snap, v)
        return v

    def snapshot(self) -> tuple:
        """One consistent (device, nets, acl, payload) generation."""
        return self._pub

    @staticmethod
    def snap_payload(snap: tuple):
        return snap[3]

    def oracle_snap(self, snap: tuple, addr: bytes,
                    port: Optional[int] = None) -> int:
        nets, acl = snap[1], snap[2]
        for j, net in enumerate(nets):
            if net.contains_ip(addr) and (
                    port is None or acl is None or
                    (acl[j].min_port <= port <= acl[j].max_port)):
                return j
        return -1

    def index_snap(self, snap: tuple, addr: bytes,
                   port: Optional[int] = None) -> int:
        """O(groups) host lookup against the snapshot's CidrIndex (same
        winner as oracle_snap); linear fallback without one."""
        note_serving()
        idx = snap[5] if len(snap) > 5 else None
        if idx is None:
            return self.oracle_snap(snap, addr, port)
        # route tables ignore ports entirely (oracle_snap's acl gate)
        return idx.lookup(addr, None if snap[2] is None else port)

    def dispatch_snap(self, snap: tuple, addrs: Sequence[bytes],
                      ports: Optional[Sequence[int]],
                      pad_to: Optional[int] = None, sync: bool = True):
        """Encode + submit one batch against the snapshotted table
        generation (async device result; np.asarray() it to block).
        pad_to: pad the encoded arrays to this batch bucket (family -1
        marks pad rows — matches no group, walks no trie). sync: see
        HintMatcher.dispatch_snap."""
        note_serving()
        dev, nets, acl = snap[0], snap[1], snap[2]
        if not nets or not addrs:
            return np.full(len(addrs), -1, np.int32)
        note_launch(kind="cidr")  # every branch below is one dispatch
        a16, fam = T.encode_ips(addrs)
        # route tables (acl=None) have zeroed port-range columns: the port
        # gate must be skipped entirely or every port>0 query misses
        p = None if (ports is None or acl is None) \
            else np.asarray(ports, np.int32)
        if pad_to and pad_to > a16.shape[0]:
            k = pad_to - a16.shape[0]
            a16 = np.concatenate([a16, np.zeros((k,) + a16.shape[1:],
                                                a16.dtype)])
            fam = np.concatenate([fam, np.full(k, -1, fam.dtype)])
            if p is not None:
                p = np.concatenate([p, np.zeros(k, p.dtype)])
        if self.backend == "jax":
            return H.cidr_hash_jit(dev, a16, fam, p)
        if self.backend == "jax-fp":
            from ..ops import fphash as F
            return F.cidr_fp_jit(dev, a16, fam, p)
        if self.backend in ("jax-sharded", "jax-fp-sharded"):
            return self._dispatch_sharded(snap, a16, fam, p, sync=sync)
        return cidr_match_jit(dev, a16, fam, p)

    def _dispatch_sharded(self, snap: tuple, a16: np.ndarray,
                          fam: np.ndarray, p: Optional[np.ndarray],
                          sync: bool = True):
        from ..parallel import mesh as M
        dev, tab = snap[0], snap[4]
        from ..parallel.mesh import query_shards
        n = a16.shape[0]
        cap = pad_batch(n, query_shards(self._mesh))
        if cap != n:
            a16 = np.concatenate(
                [a16, np.zeros((cap - n,) + a16.shape[1:], a16.dtype)])
            fam = np.concatenate([fam, np.zeros(cap - n, fam.dtype)])
            if p is not None:
                p = np.concatenate([p, np.zeros(cap - n, p.dtype)])
        a16d, famd, pd = M.shard_addr_queries(a16, fam, self._mesh, p)
        with_port = p is not None
        fn = self._fns.get(with_port)
        if fn is None:
            kernel = None
            if self.backend == "jax-fp-sharded":
                from ..ops import fphash as F
                kernel = F.cidr_fp_match
            fn = self._fns[with_port] = M.make_sharded_cidr_fn(
                self._mesh, {k: v.ndim for k, v in tab.arrays.items()},
                with_port, kernel=kernel)
        size = np.int32(tab.shard_size)
        out = fn(dev, a16d, famd, pd, size) if with_port \
            else fn(dev, a16d, famd, size)
        if not sync:
            import jax
            if jax.process_count() <= 1:
                return out  # async: caller syncs + slices
        return M.to_local(out)[:n]
