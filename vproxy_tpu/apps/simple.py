"""Simple mode — one-liner load balancer.

Parity: reference `vproxyx/Simple.java:257` (`-Deploy=Simple bind 80
backend h1:80,h2:80 ssl cert key protocol ...`): builds the full
resource graph (upstream, server-group, tcp-lb, controllers) from one
command line. `gen` prints the equivalent config script and exits —
same flag as the reference.

Usage:
  python -m vproxy_tpu simple bind <port> backend <ip:port,...>
      [protocol tcp|http|h2|...] [ssl <cert.pem> <key.pem>] [gen]
"""
from __future__ import annotations

import sys
from typing import List, Optional

from ..control.app import Application
from ..control.command import CmdError, Command


def build_script(bind: int, backends: List[str], protocol: str,
                 ssl: Optional[tuple]) -> List[str]:
    lines = [
        "add upstream ups0",
        "add server-group sg0 timeout 2000 period 5000 up 2 down 3",
        "add server-group sg0 to upstream ups0 weight 10",
    ]
    for i, b in enumerate(backends):
        lines.append(f"add server svr{i} to server-group sg0 "
                     f"address {b} weight 10")
    lb = f"add tcp-lb lb0 address 0.0.0.0:{bind} upstream ups0"
    if protocol != "tcp":
        lb += f" protocol {protocol}"
    if ssl is not None:
        lines.append(f"add cert-key ck0 cert {ssl[0]} key {ssl[1]}")
        lb += " cert-key ck0"
    lines.append(lb)
    return lines


def parse_args(argv: List[str]):
    bind = None
    backends: List[str] = []
    protocol = "tcp"
    ssl = None
    gen = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "bind":
            bind = int(argv[i + 1])
            i += 2
        elif a == "backend":
            backends = [b.strip() for b in argv[i + 1].split(",") if b.strip()]
            i += 2
        elif a == "protocol":
            protocol = argv[i + 1]
            i += 2
        elif a == "ssl":
            ssl = (argv[i + 1], argv[i + 2])
            i += 3
        elif a == "gen":
            gen = True
            i += 1
        else:
            raise ValueError(f"unknown simple-mode argument {a!r}")
    if bind is None or not backends:
        raise ValueError("simple mode needs `bind <port>` and "
                         "`backend <ip:port,...>`")
    return bind, backends, protocol, ssl, gen


def run(argv: List[str]) -> int:
    try:
        bind, backends, protocol, ssl, gen = parse_args(argv)
    except (ValueError, IndexError) as e:
        print(f"simple: {e}", file=sys.stderr)
        return 1
    script = build_script(bind, backends, protocol, ssl)
    if gen:
        print("\n".join(script))
        return 0
    app = Application.create()
    try:
        for line in script:
            Command.execute(app, line)
    except CmdError as e:
        print(f"simple: {e}", file=sys.stderr)
        app.close()
        return 1
    print(f"simple-mode lb on 0.0.0.0:{bind} -> {','.join(backends)} "
          f"protocol {protocol}")
    import threading
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    app.close()
    return 0
