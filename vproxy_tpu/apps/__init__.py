"""Deployable apps (reference vproxyx/*): Simple one-liner LB,
HelloWorld smoke test, Daemon supervisor, KcpTun tunnel."""
