"""Deployable WebSocks apps (reference -Deploy=WebSocksProxyServer /
-Deploy=WebSocksProxyAgent, vproxyx/WebSocksProxyServer.java:347 /
WebSocksProxyAgent.java:398).

Usage:
  python -m vproxy_tpu websocks server <port> user1:pass1[,user2:pass2...]
         [kcp] [root=<dir>] [redirect=<url>]
  python -m vproxy_tpu websocks agent <socks-port> <server-host:port>
         <user:pass> [kcp] [rule=<domain-or-:port-or-/re/-or-*>]...
         [connect=<port>] [pac=<port>]
"""
from __future__ import annotations

import signal
import threading

from ..components.elgroup import EventLoopGroup


def run(argv: list[str]) -> int:
    if not argv or argv[0] not in ("server", "agent"):
        print(__doc__)
        return 1
    mode = argv.pop(0)
    import os
    elg = EventLoopGroup("websocks", os.cpu_count() or 1)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())

    if mode == "server":
        from ..websocks.server import WebSocksProxyServer
        port = int(argv.pop(0))
        users = dict(u.split(":", 1) for u in argv.pop(0).split(","))
        kw = {}
        for a in argv:
            if a == "kcp":
                kw["kcp"] = True
            elif a.startswith("root="):
                kw["page_root"] = a[5:]
            elif a.startswith("redirect="):
                kw["redirect"] = a[9:]
        srv = WebSocksProxyServer("websocks", elg.next(), "0.0.0.0", port,
                                  users, **kw)
        srv.start()
        print(f"websocks server on :{srv.bind_port} "
              f"({'tcp+kcp' if kw.get('kcp') else 'tcp'})")
        stop.wait()
        srv.stop()
    else:
        from ..websocks.agent import WebSocksProxyAgent, WebSocksServerRef
        socks_port = int(argv.pop(0))
        host, _, p = argv.pop(0).rpartition(":")
        user, _, password = argv.pop(0).partition(":")
        kcp = "kcp" in argv
        rules = [a[5:] for a in argv if a.startswith("rule=")] or ["*"]
        connect = next((int(a[8:]) for a in argv
                        if a.startswith("connect=")), None)
        pac = next((int(a[4:]) for a in argv if a.startswith("pac=")), None)
        agent = WebSocksProxyAgent(
            elg, [WebSocksServerRef(host, int(p), user, password, kcp=kcp)],
            proxy_rules=rules, socks_port=socks_port,
            http_connect_port=connect, pac_port=pac)
        print(f"websocks agent: socks5 on 127.0.0.1:{agent.socks_port}"
              + (f", http-connect {agent.http_connect_port}"
                 if agent.http_connect_port else "")
              + (f", pac {agent.pac_port}" if agent.pac_port else ""))
        stop.wait()
        agent.close()
    elg.close()
    return 0
