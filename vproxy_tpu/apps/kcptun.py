"""KcpTun — TCP-over-KCP tunnel client/server.

Parity: reference `vproxyx/KcpTun.java:199` (`doc/vproxy-kcp-tunnel.md`):
the client listens on TCP and multiplexes every accepted connection as
a stream over one KCP/UDP session to the server; the server terminates
streams by connecting to a fixed TCP target. Transport = net/streamed
over net/kcp over net/udp.

Usage:
  python -m vproxy_tpu kcptun server <udp-port> <target-ip:port>
  python -m vproxy_tpu kcptun client <tcp-port> <server-ip:port>
"""
from __future__ import annotations

import sys
from typing import List, Optional

from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..net.kcp import KcpConn
from ..net.streamed import Stream, StreamedSession, StreamHandler
from ..net.udp import UdpServer, UdpSock

CONV = 0x76707478  # arbitrary fixed conv both sides agree on ("vptx")


class _TcpSide(Handler):
    """TCP half of a bridge: forwards to the stream."""

    def __init__(self, stream: Stream):
        self.stream = stream

    def on_data(self, conn, data):
        self.stream.write(data)

    def on_eof(self, conn):
        self.stream.close_graceful()

    def on_closed(self, conn, err):
        self.stream.close()


class _StreamSide(StreamHandler):
    """Stream half of a bridge: forwards to the TCP connection."""

    def __init__(self):
        self.conn: Optional[Connection] = None
        self._early: list[bytes] = []

    def attach(self, conn: Connection) -> None:
        self.conn = conn
        for d in self._early:
            conn.write(d)
        self._early.clear()

    def on_data(self, s, data):
        if self.conn is None:
            self._early.append(data)
        else:
            self.conn.write(data)

    def on_eof(self, s):
        if self.conn is not None:
            self.conn.close_graceful()

    def on_closed(self, s):
        if self.conn is not None:
            self.conn.close()


def run_server(loop: SelectorEventLoop, udp_port: int, target_ip: str,
               target_port: int) -> UdpServer:
    def on_udp_accept(vconn):
        kcp = KcpConn(loop, CONV, vconn.write)

        def on_stream(stream: Stream) -> None:
            sh = _StreamSide()
            stream.set_handler(sh)
            try:
                conn = Connection.connect(loop, target_ip, target_port)
            except OSError:
                stream.close()
                return
            conn.set_handler(_TcpSide(stream))
            sh.attach(conn)

        sess = StreamedSession(loop, kcp, is_client=False,
                               on_accept=on_stream)

        class VH:
            def on_data(self, c, data):
                kcp.feed(data)

            def on_closed(self, c, err):
                sess.close()
        vconn.set_handler(VH())

    return UdpServer(loop, "0.0.0.0", udp_port, on_udp_accept)


class TunClient:
    def __init__(self, loop: SelectorEventLoop, tcp_port: int,
                 server_ip: str, server_port: int, bind_ip: str = "0.0.0.0"):
        self.loop = loop
        self.server = (server_ip, server_port)
        self.sess: Optional[StreamedSession] = None
        self.sock: Optional[UdpSock] = None
        self.closed = False
        self._redial = None
        self._dial()

        self.tcp = loop.call_sync(lambda: ServerSock(
            loop, bind_ip, tcp_port, self._on_accept))
        self.port = self.tcp.port

    def _dial(self) -> None:
        if self.closed:
            return
        self._redial = None
        self.sock = UdpSock(self.loop)
        kcp = KcpConn(self.loop, CONV,
                      lambda d: self.sock.send(d, *self.server))
        self.sock.on_packet = lambda d, ip, p: kcp.feed(d)
        self.sess = StreamedSession(
            self.loop, kcp, is_client=True,
            on_broken=self._on_broken)

    def _on_broken(self) -> None:
        sock, self.sock = self.sock, None
        if sock is not None:
            sock.close()
        if not self.closed:
            self._redial = self.loop.delay(1000, self._dial)  # auto re-dial

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        conn = Connection(self.loop, fd, (ip, port))
        if self.sess is None or self.sess.broken:
            conn.close()
            return
        sh = _StreamSide()
        stream = self.sess.open_stream(sh)
        conn.set_handler(_TcpSide(stream))
        sh.attach(conn)

    def close(self) -> None:
        self.closed = True
        if self._redial is not None:
            self.loop.run_on_loop(self._redial.cancel)
            self._redial = None
        self.tcp.close()
        if self.sess is not None:
            self.sess.close()
        if self.sock is not None:
            self.sock.close()


def run(argv: List[str]) -> int:
    if len(argv) < 3 or argv[0] not in ("server", "client"):
        print(__doc__, file=sys.stderr)
        return 1
    mode = argv[0]
    port = int(argv[1])
    host, sep, p = argv[2].rpartition(":")
    if not sep or not host or not p.isdigit():
        print(__doc__, file=sys.stderr)
        return 1
    peer = (host, int(p))
    loop = SelectorEventLoop("kcptun")
    loop.loop_thread()
    if mode == "server":
        run_server(loop, port, peer[0], peer[1])
        print(f"kcptun server: udp {port} -> tcp {peer[0]}:{peer[1]}")
    else:
        TunClient(loop, port, peer[0], peer[1])
        print(f"kcptun client: tcp {port} -> kcp {peer[0]}:{peer[1]}")
    import threading
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    loop.close()
    return 0
