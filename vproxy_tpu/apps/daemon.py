"""Daemon — supervisor for crash-restart and zero-downtime reload.

Parity: reference `vproxyx/Daemon.java:15-70`: forks a child running
the real app, watches its health, restarts it if it dies; SIGUSR2
launches a NEW child first (binds overlap via SO_REUSEPORT /
noStartupBindCheck), then stops the old one once the new one is up —
zero-downtime config reload.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

CHECK_INTERVAL_S = 1.0
RESTART_DELAY_S = 1.0
RELOAD_GRACE_S = 5.0


class Daemon:
    def __init__(self, child_args: List[str]):
        self.child_args = child_args
        self.child: Optional[subprocess.Popen] = None
        self.stopping = False
        self.reload_requested = False
        self._lock = threading.Lock()

    def _spawn(self) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "vproxy_tpu",
               "noStdIOController"] + self.child_args
        return subprocess.Popen(cmd)

    def request_reload(self, *_a) -> None:
        self.reload_requested = True

    def request_stop(self, *_a) -> None:
        self.stopping = True

    def _do_reload(self) -> None:
        """new child first, old child second (reuseport overlap)."""
        old = self.child
        new = self._spawn()
        t0 = time.time()
        while time.time() - t0 < RELOAD_GRACE_S:
            if new.poll() is not None:  # new child died: keep the old
                print("daemon: reload failed, new child exited "
                      f"{new.returncode}; keeping old", file=sys.stderr)
                return
            time.sleep(0.2)
        self.child = new
        if old is not None and old.poll() is None:
            old.send_signal(signal.SIGTERM)
            try:
                old.wait(timeout=10)
            except subprocess.TimeoutExpired:
                old.kill()
        print("daemon: reloaded", file=sys.stderr)

    def run(self) -> int:
        signal.signal(signal.SIGTERM, self.request_stop)
        signal.signal(signal.SIGINT, self.request_stop)
        if hasattr(signal, "SIGUSR2"):
            signal.signal(signal.SIGUSR2, self.request_reload)
        self.child = self._spawn()
        print(f"daemon: child pid {self.child.pid}", file=sys.stderr)
        while not self.stopping:
            time.sleep(CHECK_INTERVAL_S)
            if self.reload_requested:
                self.reload_requested = False
                self._do_reload()
                continue
            if self.child.poll() is not None:
                print(f"daemon: child exited {self.child.returncode}, "
                      "restarting", file=sys.stderr)
                time.sleep(RESTART_DELAY_S)
                if not self.stopping:
                    self.child = self._spawn()
        if self.child is not None and self.child.poll() is None:
            self.child.send_signal(signal.SIGTERM)
            try:
                self.child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.child.kill()
        return 0


def run(argv: List[str]) -> int:
    return Daemon(argv).run()
