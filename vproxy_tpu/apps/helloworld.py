"""HelloWorld — smoke-test echo servers.

Parity: reference `vproxyx/HelloWorld.java:206`: starts a TCP echo
server and a UDP echo server on the given (or random) port, prints
what it receives, echoes back with a greeting — a "does the runtime
work on this machine" check.
"""
from __future__ import annotations

import sys
from typing import List

from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..net.udp import UdpServer

GREETING = b"hello from vproxy-tpu\n"


class _Echo(Handler):
    def on_data(self, conn, data):
        conn.write(GREETING + data)

    def on_eof(self, conn):
        conn.close_graceful()

    def on_closed(self, conn, err):
        pass


def start(loop: SelectorEventLoop, port: int):
    """Returns (tcp_server, udp_server, actual_port)."""
    def mk():
        def on_accept(fd, ip, p):
            c = Connection(loop, fd, (ip, p))
            c.set_handler(_Echo())
        return ServerSock(loop, "0.0.0.0", port, on_accept)
    tcp = loop.call_sync(mk)
    actual = tcp.port

    class UH:
        def on_data(self, conn, data):
            conn.write(GREETING + data)

        def on_closed(self, conn, err):
            pass

    udp = UdpServer(loop, "0.0.0.0", actual,
                    lambda c: c.set_handler(UH()))
    return tcp, udp, actual


def run(argv: List[str]) -> int:
    port = int(argv[0]) if argv else 0
    loop = SelectorEventLoop("helloworld")
    loop.loop_thread()
    try:
        _tcp, _udp, actual = start(loop, port)
    except OSError as e:
        print(f"helloworld: bind failed: {e}", file=sys.stderr)
        loop.close()
        return 1
    print(f"helloworld: echo on tcp/udp 0.0.0.0:{actual}")
    import threading
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    loop.close()
    return 0
