"""Blocking and file virtual FDs.

Parity:
* selector/wrap/blocking/BlockingDatagramFD.java:364 — a blocking
  send/recv facade over a loop-registered datagram socket, for code that
  runs OFF the event loop (blocking resolvers, scripts) but must share
  the loop's socket. `BlockingUdp` queues inbound datagrams from the
  loop thread and hands them out under a timeout.
* selector/wrap/file/FileFD.java:22 — a file exposed through the
  socket-FD surface so protocol code can stream file contents with the
  same handler API as network connections. `FileConn` quacks like a
  read-only Connection: on_data chunks delivered on the loop with
  pause/resume backpressure, on_eof at the end.
"""
from __future__ import annotations

import os
import queue
from typing import Optional

from .connection import Handler
from .eventloop import SelectorEventLoop
from .udp import UdpSock


class BlockingUdp:
    """Blocking datagram facade over a loop-owned UdpSock."""

    def __init__(self, loop: SelectorEventLoop, ip: str = "",
                 port: int = 0, queue_cap: int = 1024):
        self._q: queue.Queue = queue.Queue(queue_cap)
        self.sock = UdpSock(loop, ip, port, self._on_packet)
        self.local = self.sock.local
        self.closed = False

    _CLOSED = object()  # sentinel: wakes receivers blocked in recv()

    def _on_packet(self, data: bytes, ip: str, port: int) -> None:
        try:
            self._q.put_nowait((data, ip, port))
        except queue.Full:
            # UDP: drop under overload, like the kernel would — but
            # COUNTED (vproxy_udp_drop_total): a storm that overruns a
            # blocking consumer must be visible on /metrics, not silent
            from ..utils.metrics import udp_drop_incr
            udp_drop_incr()

    def send(self, data: bytes, ip: str, port: int) -> None:
        if self.closed:
            raise OSError("closed")
        self.sock.send(data, ip, port)

    def recv(self, timeout: Optional[float] = None):
        """-> (data, ip, port); raises TimeoutError. May be called from
        any thread EXCEPT the owning loop (it would deadlock the loop)."""
        if self.closed:
            raise OSError("closed")
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError("recv timed out")
        if item is self._CLOSED:
            self._q.put_nowait(item)  # wake any other blocked receiver
            raise OSError("closed")
        return item

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.sock.close()
            try:
                self._q.put_nowait(self._CLOSED)
            except queue.Full:
                pass  # a full queue means receivers aren't blocked


class FileConn:
    """Read-only Connection-like over a regular file: chunks stream to
    handler.on_data on the loop, then on_eof. pause/resume give the
    same backpressure surface as a socket Connection."""

    CHUNK = 65536

    def __init__(self, loop: SelectorEventLoop, path: str):
        self.loop = loop
        self.path = path
        self._fd = os.open(path, os.O_RDONLY)
        self.length = os.fstat(self._fd).st_size
        self.remote = ("file", 0)
        self.handler: Handler = Handler()
        self.closed = False
        self.detached = False
        self.bytes_in = 0
        self.bytes_out = 0
        self.out = b""
        self._paused = True
        self._pumping = False

    def set_handler(self, h: Handler) -> None:
        self.handler = h
        self.resume_reading()

    def pause_reading(self) -> None:
        self._paused = True

    def resume_reading(self) -> None:
        self._paused = False
        self._arm()

    def _arm(self) -> None:
        if not self._pumping and not self.closed:
            self._pumping = True
            self.loop.run_on_loop(self._pump)

    def _pump(self) -> None:
        self._pumping = False
        if self.closed or self._paused:
            return
        try:
            chunk = os.read(self._fd, self.CHUNK)
        except OSError:
            self.close(1)
            return
        if not chunk:
            self.handler.on_eof(self)
            return
        self.bytes_in += len(chunk)
        self.handler.on_data(self, chunk)
        self._arm()  # next chunk on the next loop pass (fair scheduling)

    def write(self, data: bytes) -> None:
        raise OSError("FileConn is read-only")

    def close(self, err: int = 0) -> None:
        if self.closed:
            return
        self.closed = True
        os.close(self._fd)
        self.handler.on_closed(self, err)

    close_graceful = close
