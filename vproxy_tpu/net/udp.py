"""UDP datagram sockets + a UDP server that emulates accept().

Parity: reference `selector/wrap/udp` — `ServerDatagramFD.java:350`
(`VirtualDatagramFD:186`), `UDPFDs`: one bound datagram socket serves
many remotes; each new remote address materializes a virtual
connection-like object delivered through an accept callback, with its
own receive queue, idle expiry and sendto-backed writes.

Everything runs on one SelectorEventLoop thread; the API mirrors
net/connection.py's handler style so protocol code written against
Connection ports over unchanged.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, Optional, Tuple

from . import vtl
from .eventloop import SelectorEventLoop

# reference: Config.udpTimeout = 5 min (vproxybase/Config.java:24)
DEFAULT_IDLE_MS = 5 * 60 * 1000


class UdpSock:
    """Plain nonblocking datagram socket registered on a loop.

    on_packet(data, ip, port) fires on the loop thread for every
    datagram. Unbound client sockets pass port=0.
    """

    def __init__(self, loop: SelectorEventLoop, ip: str = "", port: int = 0,
                 on_packet: Optional[Callable[[bytes, str, int], None]] = None,
                 v6: bool = False, reuseport: bool = False):
        self.loop = loop
        self.on_packet = on_packet
        self.closed = False

        def mk() -> None:
            if ip:
                self.fd = vtl.udp_bind(ip, port, reuseport)
            else:
                self.fd = vtl.udp_socket(v6)
            self.local = vtl.sock_name(self.fd)
            loop.add(self.fd, vtl.EV_READ, self._on_readable)
        loop.call_sync(mk)

    def _on_readable(self, fd: int, ev: int) -> None:
        while not self.closed:
            r = vtl.recvfrom(fd)
            if r is None:
                return
            data, ip, port = r
            if self.on_packet is not None:
                self.on_packet(data, ip, port)

    def send(self, data: bytes, ip: str, port: int) -> None:
        if not self.closed:
            vtl.sendto(self.fd, data, ip, port)  # drop on EAGAIN (UDP)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True

        def rm() -> None:
            if self.loop.registered(self.fd):
                self.loop.remove(self.fd)
            vtl.close(self.fd)
        self.loop.run_on_loop(rm)


class UdpVirtualConn:
    """One remote peer of a UdpServer, shaped like a Connection.

    handler needs on_data(conn, data) and on_closed(conn, err); writes
    are sendto() on the shared server socket.
    """

    # datagrams buffered while no handler is attached; beyond this they
    # are dropped (UDP semantics), bounding memory against floods
    PENDING_MAX = 256

    def __init__(self, server: "UdpServer", ip: str, port: int):
        self.server = server
        self.remote = (ip, port)
        self.handler = None
        self.closed = False
        self._pending: deque = deque()
        self._touch()

    def _touch(self) -> None:
        self.last_active = self.server.loop.now

    def set_handler(self, h) -> None:
        self.handler = h
        while self._pending and not self.closed:
            h.on_data(self, self._pending.popleft())

    def _deliver(self, data: bytes) -> None:
        self._touch()
        if self.handler is None:
            if len(self._pending) < self.PENDING_MAX:
                self._pending.append(data)
        else:
            self.handler.on_data(self, data)

    def write(self, data: bytes) -> None:
        if not self.closed:
            self._touch()
            self.server.sock.send(data, self.remote[0], self.remote[1])

    def close(self, err: int = 0) -> None:
        if self.closed:
            return
        self.closed = True
        self.server._conns.pop(self.remote, None)
        if self.handler is not None:
            self.handler.on_closed(self, err)


class UdpServer:
    """accept()-emulating UDP server (reference ServerDatagramFD).

    New remote (ip, port) -> on_accept(UdpVirtualConn); datagrams for a
    known remote go to that conn's handler. Idle conns expire after
    idle_ms (sweep every idle_ms/4).
    """

    def __init__(self, loop: SelectorEventLoop, ip: str, port: int,
                 on_accept: Callable[[UdpVirtualConn], None],
                 idle_ms: int = DEFAULT_IDLE_MS, reuseport: bool = False):
        self.loop = loop
        self.on_accept = on_accept
        self.idle_ms = idle_ms
        self._conns: Dict[Tuple[str, int], UdpVirtualConn] = {}
        self.closed = False
        self.sock = UdpSock(loop, ip, port, self._on_packet,
                            reuseport=reuseport)
        self.local = self.sock.local
        sweep = max(250, idle_ms // 4)
        self._sweeper = None

        def arm() -> None:
            if not self.closed:  # close() may have raced the deferred arm
                self._sweeper = loop.period(sweep, self._expire)
        loop.run_on_loop(arm)

    def _on_packet(self, data: bytes, ip: str, port: int) -> None:
        key = (ip, port)
        conn = self._conns.get(key)
        if conn is None:
            conn = UdpVirtualConn(self, ip, port)
            self._conns[key] = conn
            self.on_accept(conn)
        conn._deliver(data)

    def _expire(self) -> None:
        dead = [c for c in self._conns.values()
                if self.loop.now - c.last_active > self.idle_ms / 1000.0]
        for c in dead:
            c.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self._sweeper is not None:
            self.loop.run_on_loop(self._sweeper.cancel)
        for c in list(self._conns.values()):
            c.close()
        self.sock.close()
