"""Pure-Python FD provider — the fallback backend behind the provider
seam.

Parity: the reference selects its FD backend with `-Dvfd=provided|jdk|
posix|windows` (vfd/FDProvider.java:17-36); `jdk` is the pure-JDK
fallback that works without the native library. This module is that
fallback for this framework: the same surface as the native layer
(net/vtl.py over native/vtl.cpp) built on `socket`/`select.epoll`,
selected with VPROXY_TPU_FD_PROVIDER=py or automatically when libvtl.so
cannot be built/loaded. Semantics mirror the native engine exactly —
including the bidirectional splice pump's ring/EOF/FIN-propagation
behavior and the poll loop's pump-done notification contract — so every
layer above (event loop, connections, TcpLB splice mode) runs unchanged,
only slower (bytes cross the interpreter).
"""
from __future__ import annotations

import errno
import os
import select
import socket
import struct
from typing import Optional

EV_READ = 1
EV_WRITE = 2
EV_ERROR = 4
EV_PUMP_DONE = 8

AGAIN = -errno.EAGAIN

# fd -> socket object for sockets created here (keeps them alive; lets
# accept/sendto/recvfrom/getsockname use the object API on the raw fd)
_socks: dict[int, socket.socket] = {}

# [bytes_spliced, write_calls, short_writes, tls_handshakes] — parity
# with the native provider's vtl_pump_counters (vtl.pump_counters());
# the py provider has no TLS pump so [3] stays 0
PUMP_COUNTERS = [0, 0, 0, 0]

_BLOCKING_IO = (BlockingIOError,)


def _reg(s: socket.socket) -> int:
    s.setblocking(False)
    fd = s.fileno()
    _socks[fd] = s
    return fd


def defer_accept_secs() -> int:
    """VPROXY_TPU_DEFER_ACCEPT (seconds, 0 = off; read per listen so
    benches/tests can toggle it at runtime): listeners only surface
    connections to accept() once the first bytes arrive, so empty
    accepts never wake the loop. Leave off for server-first protocols —
    their clients wait for a banner and would stall out the defer
    window before sending anything. The ONE parser for both providers
    (vtl.py re-exports it)."""
    try:
        return int(os.environ.get("VPROXY_TPU_DEFER_ACCEPT", "0") or "0")
    except ValueError:
        return 0


def tcp_listen(ip: str, port: int, backlog: int = 512,
               reuseport: bool = False, v6: bool = False) -> int:
    s = socket.socket(socket.AF_INET6 if v6 else socket.AF_INET,
                      socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuseport:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        secs = defer_accept_secs()
        if secs > 0 and hasattr(socket, "TCP_DEFER_ACCEPT"):
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_DEFER_ACCEPT, secs)
        s.bind((ip, port))
        s.listen(backlog)
    except OSError:
        s.close()
        raise
    return _reg(s)


def accept(lfd: int):
    s = _socks.get(lfd)
    if s is None:
        raise OSError(errno.EBADF, "not a provider socket")
    try:
        c, addr = s.accept()
    except _BLOCKING_IO:
        return None
    fd = _reg(c)
    if c.family == socket.AF_UNIX:
        return fd, "", 0
    return fd, addr[0], addr[1]


def tcp_connect(ip: str, port: int) -> int:
    s = socket.socket(socket.AF_INET6 if ":" in ip else socket.AF_INET,
                      socket.SOCK_STREAM)
    s.setblocking(False)
    try:
        s.connect((ip, port))
    except BlockingIOError:
        pass
    except OSError:
        s.close()
        raise
    return _reg(s)


def finish_connect(fd: int) -> int:
    s = _socks.get(fd)
    if s is None:
        return -errno.EBADF
    return -s.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)


def unix_listen(path: str, backlog: int = 512) -> int:
    if os.path.exists(path):
        st = os.stat(path)
        import stat as stat_m
        if not stat_m.S_ISSOCK(st.st_mode):
            raise OSError(errno.EADDRINUSE, "path exists and is not a socket")
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        probe.setblocking(False)
        try:
            probe.connect(path)
        except (ConnectionRefusedError, FileNotFoundError):
            os.unlink(path)  # dead leftover
        except OSError:
            pass
        finally:
            probe.close()
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.bind(path)
        s.listen(backlog)
    except OSError:
        s.close()
        raise
    return _reg(s)


def unix_connect(path: str) -> int:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.setblocking(False)
    try:
        s.connect(path)
    except BlockingIOError:
        pass
    except OSError:
        s.close()
        raise
    return _reg(s)


def udp_bind(ip: str, port: int, reuseport: bool = False) -> int:
    s = socket.socket(socket.AF_INET6 if ":" in ip else socket.AF_INET,
                      socket.SOCK_DGRAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuseport:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((ip, port))
    except OSError:
        s.close()
        raise
    return _reg(s)


def udp_socket(v6: bool = False) -> int:
    return _reg(socket.socket(
        socket.AF_INET6 if v6 else socket.AF_INET, socket.SOCK_DGRAM))


def recvfrom(fd: int, n: int = 65536):
    s = _socks.get(fd)
    if s is None:
        raise OSError(errno.EBADF, "not a provider socket")
    try:
        data, addr = s.recvfrom(n)
    except _BLOCKING_IO:
        return None
    return data, addr[0], addr[1]


def sendto(fd: int, data: bytes, ip: str, port: int) -> int:
    s = _socks.get(fd)
    if s is None:
        raise OSError(errno.EBADF, "not a provider socket")
    try:
        return s.sendto(data, (ip, port))
    except _BLOCKING_IO:
        return AGAIN


def read(fd: int, n: int = 65536):
    try:
        return os.read(fd, n)
    except _BLOCKING_IO:
        return None


def write(fd: int, data: bytes) -> int:
    try:
        return os.write(fd, data)
    except _BLOCKING_IO:
        return AGAIN


def close(fd: int) -> None:
    s = _socks.pop(fd, None)
    if s is not None:
        s.close()
        return
    try:
        os.close(fd)
    except OSError:
        pass


def shutdown_wr(fd: int) -> None:
    s = _socks.get(fd)
    try:
        if s is not None:
            s.shutdown(socket.SHUT_WR)
        else:
            socket.socket(fileno=os.dup(fd)).shutdown(socket.SHUT_WR)
    except OSError:
        pass


def set_rcvbuf(fd: int, nbytes: int) -> None:
    import os as _os
    import socket as _s
    try:
        _s.socket(fileno=_os.dup(fd)).setsockopt(
            _s.SOL_SOCKET, _s.SO_RCVBUF, nbytes)
    except OSError:
        pass


def set_nodelay(fd: int, on: bool = True) -> None:
    s = _socks.get(fd)
    try:
        if s is not None:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1 if on else 0)
    except OSError:
        pass


def sock_name(fd: int, peer: bool = False):
    s = _socks.get(fd)
    if s is None:
        raise OSError(errno.EBADF, "not a provider socket")
    addr = s.getpeername() if peer else s.getsockname()
    if s.family == socket.AF_UNIX:
        return addr if isinstance(addr, str) else "", 0
    return addr[0], addr[1]


def check(r: int) -> int:
    if isinstance(r, int) and r < 0:
        raise OSError(-r, os.strerror(-r))
    return r


# ----------------------------------------------------------------- pump


class _Pump:
    """Mirror of the native Pump: two rings, EOF/FIN propagation,
    byte counters, dead/err state (native/vtl.cpp pump engine)."""

    __slots__ = ("id", "fd_a", "fd_b", "cap", "a2b", "b2a", "a_eof",
                 "b_eof", "a_wr_shut", "b_wr_shut", "dead", "err",
                 "bytes_a2b", "bytes_b2a")

    def __init__(self, pid: int, fd_a: int, fd_b: int, cap: int):
        self.id = pid
        self.fd_a, self.fd_b = fd_a, fd_b
        self.cap = cap
        self.a2b = bytearray()
        self.b2a = bytearray()
        self.a_eof = self.b_eof = False
        self.a_wr_shut = self.b_wr_shut = False
        self.dead = False
        self.err = 0
        self.bytes_a2b = self.bytes_b2a = 0


class _PyLoop:
    """Mirror of the native Loop: epoll + wake eventfd + handler
    registry + pump engine + deferred pump-done notifications."""

    def __init__(self):
        self.ep = select.epoll()
        self.wakefd = os.eventfd(0, os.EFD_NONBLOCK | os.EFD_CLOEXEC) \
            if hasattr(os, "eventfd") else None
        if self.wakefd is None:
            self._wr, self.wakefd = None, None
            r, w = os.pipe2(os.O_NONBLOCK | os.O_CLOEXEC)
            self.wakefd, self._wr = r, w
        else:
            self._wr = None
        # fd -> [kind, tag, interest, pump]; kind: 0 py, 1 wake, 2/3 pump
        self.handlers: dict[int, list] = {}
        self.pumps: dict[int, _Pump] = {}
        self.done_pumps: list[int] = []
        self.next_pump_id = 1
        self.handlers[self.wakefd] = [1, 0, EV_READ, None]
        self.ep.register(self.wakefd, select.EPOLLIN)

    # --- registry ---

    @staticmethod
    def _to_ep(ev: int) -> int:
        e = 0
        if ev & EV_READ:
            e |= select.EPOLLIN
        if ev & EV_WRITE:
            e |= select.EPOLLOUT
        return e

    def add(self, fd: int, events: int, tag: int) -> int:
        if fd in self.handlers:
            return -errno.EEXIST
        try:
            self.ep.register(fd, self._to_ep(events))
        except OSError as e:
            return -(e.errno or errno.EINVAL)
        self.handlers[fd] = [0, tag, events, None]
        return 0

    def mod(self, fd: int, events: int, tag: int) -> int:
        h = self.handlers.get(fd)
        if h is None:
            return -errno.ENOENT
        h[1] = tag
        try:
            self.ep.modify(fd, self._to_ep(events))
        except OSError as e:
            return -(e.errno or errno.EINVAL)
        h[2] = events
        return 0

    def delete(self, fd: int) -> int:
        if fd not in self.handlers:
            return -errno.ENOENT
        try:
            self.ep.unregister(fd)
        except OSError:
            pass
        del self.handlers[fd]
        return 0

    def wakeup(self) -> int:
        try:
            if self._wr is not None:
                os.write(self._wr, b"\x01")
            else:
                os.eventfd_write(self.wakefd, 1)
        except (BlockingIOError, OSError):
            pass
        return 0

    # --- pump engine (mirror of pump_flow/pump_run/pump_kill) ---

    def _pump_kill(self, p: _Pump, err: int) -> None:
        if p.dead:
            return
        p.dead = True
        p.err = err
        for fd in (p.fd_a, p.fd_b):
            if fd in self.handlers:
                try:
                    self.ep.unregister(fd)
                except OSError:
                    pass
                del self.handlers[fd]
            close(fd)
        self.done_pumps.append(p.id)

    def _drain(self, p: _Pump, dst: int, ring: bytearray,
               ctr_attr: str) -> bool:
        """ring -> dst until EAGAIN/empty. False = pump killed."""
        while ring:
            want = min(len(ring), 262144)
            try:
                n = os.write(dst, memoryview(ring)[:262144])
            except _BLOCKING_IO:
                PUMP_COUNTERS[1] += 1
                PUMP_COUNTERS[2] += 1
                return True
            except OSError as e:
                self._pump_kill(p, e.errno or errno.EPIPE)
                return False
            PUMP_COUNTERS[1] += 1
            if n < want:
                PUMP_COUNTERS[2] += 1
            if n <= 0:
                return True
            PUMP_COUNTERS[0] += n
            del ring[:n]
            setattr(p, ctr_attr, getattr(p, ctr_attr) + n)
        return True

    def _flow(self, p: _Pump, src: int, dst: int, ring: bytearray,
              eof_attr: str, shut_attr: str, ctr_attr: str) -> bool:
        # flush pending ring -> dst
        if not self._drain(p, dst, ring, ctr_attr):
            return False
        # refill from src (with immediate write-through)
        while not getattr(p, eof_attr) and len(ring) < p.cap:
            try:
                data = os.read(src, p.cap - len(ring))
            except _BLOCKING_IO:
                break
            except OSError as e:
                self._pump_kill(p, e.errno or errno.EIO)
                return False
            if data == b"":
                setattr(p, eof_attr, True)
                break
            ring += data
            if not self._drain(p, dst, ring, ctr_attr):
                return False
        if getattr(p, eof_attr) and not ring and not getattr(p, shut_attr):
            try:
                s = _socks.get(dst)
                if s is not None:
                    s.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            setattr(p, shut_attr, True)
        return True

    def _pump_run(self, p: _Pump) -> None:
        if p.dead:
            return
        if not self._flow(p, p.fd_a, p.fd_b, p.a2b, "a_eof", "b_wr_shut",
                          "bytes_a2b"):
            return
        if not self._flow(p, p.fd_b, p.fd_a, p.b2a, "b_eof", "a_wr_shut",
                          "bytes_b2a"):
            return
        if p.a_eof and p.b_eof and not p.a2b and not p.b2a:
            self._pump_kill(p, 0)
            return
        self._pump_interest(p)

    def _pump_interest(self, p: _Pump) -> None:
        ha = self.handlers.get(p.fd_a)
        hb = self.handlers.get(p.fd_b)
        if ha is None or hb is None:
            return
        ia = ib = 0
        if not p.a_eof and len(p.a2b) < p.cap:
            ia |= EV_READ
        if p.b2a:
            ia |= EV_WRITE
        if not p.b_eof and len(p.b2a) < p.cap:
            ib |= EV_READ
        if p.a2b:
            ib |= EV_WRITE
        for fd, h, want in ((p.fd_a, ha, ia), (p.fd_b, hb, ib)):
            if h[2] != want:
                try:
                    self.ep.modify(fd, self._to_ep(want))
                    h[2] = want
                except OSError:
                    pass

    def pump_new(self, fd_a: int, fd_b: int, bufsize: int) -> int:
        if fd_a in self.handlers or fd_b in self.handlers:
            return 0
        # parity with the native pump: NODELAY is the pump's job now
        # (tcplb._handover no longer sets it) — best-effort, non-TCP
        # fds (unix pairs) just don't have the option
        for fd in (fd_a, fd_b):
            s = _socks.get(fd)
            if s is not None:
                try:
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                except OSError:
                    pass
        pid = self.next_pump_id
        self.next_pump_id += 1
        p = _Pump(pid, fd_a, fd_b, bufsize)
        try:
            self.ep.register(fd_a, select.EPOLLIN)
            self.ep.register(fd_b, select.EPOLLIN)
        except OSError:
            try:
                self.ep.unregister(fd_a)
            except OSError:
                pass
            return 0
        self.handlers[fd_a] = [2, pid, EV_READ, p]
        self.handlers[fd_b] = [3, pid, EV_READ, p]
        self.pumps[pid] = p
        self._pump_run(p)  # kick: buffered bytes may be ready
        return pid

    # --- poll ---

    def poll(self, tags_buf, evs_buf, cap: int, timeout_ms: int) -> int:
        out = 0

        def flush_done():
            nonlocal out
            while self.done_pumps and out < cap:
                tags_buf[out] = self.done_pumps.pop()
                evs_buf[out] = EV_PUMP_DONE
                out += 1

        flush_done()
        if out:
            return out
        try:
            events = self.ep.poll(-1 if timeout_ms < 0 else timeout_ms / 1000.0,
                                  min(cap, 256))
        except InterruptedError:
            return 0
        except OSError as e:
            return -(e.errno or errno.EIO)
        for fd, e in events:
            h = self.handlers.get(fd)
            if h is None:  # torn down earlier in this batch
                continue
            kind = h[0]
            if kind == 1:  # wake
                try:
                    while os.read(self.wakefd, 8):
                        pass
                except (BlockingIOError, OSError):
                    pass
            elif kind == 0:  # py handler
                ve = 0
                if e & (select.EPOLLIN | select.EPOLLHUP):
                    ve |= EV_READ
                if e & select.EPOLLOUT:
                    ve |= EV_WRITE
                if e & select.EPOLLERR:
                    ve |= EV_ERROR
                if ve and out < cap:
                    tags_buf[out] = h[1]
                    evs_buf[out] = ve
                    out += 1
            else:  # pump side
                p = h[3]
                if e & select.EPOLLERR:
                    err = 0
                    s = _socks.get(fd)
                    if s is not None:
                        err = s.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                    self._pump_kill(p, err or errno.EIO)
                else:
                    self._pump_run(p)
        flush_done()
        return out

    def free(self) -> None:
        for p in self.pumps.values():
            if not p.dead:
                close(p.fd_a)
                close(p.fd_b)
        self.pumps.clear()
        try:
            self.ep.close()
        except OSError:
            pass
        for fd in (self.wakefd, self._wr):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass


class PyLib:
    """Method-for-method stand-in for the ctypes CDLL handle: the event
    loop calls LIB.vtl_* without knowing which provider is behind it."""

    def vtl_new(self):
        return _PyLoop()

    def vtl_free(self, lp) -> None:
        lp.free()

    def vtl_wakeup(self, lp) -> int:
        return lp.wakeup()

    def vtl_add(self, lp, fd, events, tag) -> int:
        return lp.add(fd, events, tag)

    def vtl_mod(self, lp, fd, events, tag) -> int:
        return lp.mod(fd, events, tag)

    def vtl_del(self, lp, fd) -> int:
        return lp.delete(fd)

    def vtl_poll(self, lp, tags_buf, evs_buf, cap, timeout_ms) -> int:
        return lp.poll(tags_buf, evs_buf, cap, timeout_ms)

    def vtl_pump_new(self, lp, fd_a, fd_b, bufsize) -> int:
        return lp.pump_new(fd_a, fd_b, bufsize)

    def vtl_pump_stat(self, lp, pid, out) -> int:
        p = lp.pumps.get(pid)
        if p is None:
            return -errno.ENOENT
        out[0], out[1], out[2] = p.bytes_a2b, p.bytes_b2a, p.err
        return 0

    def vtl_pump_close(self, lp, pid) -> int:
        p = lp.pumps.get(pid)
        if p is None:
            return -errno.ENOENT
        lp._pump_kill(p, 0)
        return 0

    def vtl_pump_free(self, lp, pid) -> int:
        p = lp.pumps.pop(pid, None)
        if p is None:
            return -errno.ENOENT
        if not p.dead:
            lp._pump_kill(p, 0)
            lp.pumps.pop(pid, None)
        return 0


LIB = PyLib()

EXPORTS = ("LIB", "tcp_listen", "accept", "tcp_connect", "finish_connect",
           "unix_listen", "unix_connect", "udp_bind", "udp_socket",
           "recvfrom", "sendto", "read", "write", "close", "shutdown_wr",
           "set_nodelay", "sock_name", "check")
