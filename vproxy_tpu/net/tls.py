"""TLS termination over the Connection layer.

Functional analog of the reference's SSL ring buffers
(util/ringbuffer/SSLWrapRingBuffer.java:23 / SSLUnwrapRingBuffer.java:28
wrapping JDK SSLEngine): here `ssl.MemoryBIO` + `SSLObject` sit between
the raw Connection and the upper protocol handler. SNI is surfaced
during the handshake (the TlsExplorer peek, SSLUnwrapRingBuffer.java:
174-186) both to pick the certificate (holder.choose) and as a classify
Hint for tcp-mode relays.

TlsSocket quacks like Connection (write/close/close_graceful/pause/
resume/set_handler/out/bytes counters) so the L7 engine can drive a
TLS-terminated frontend unchanged.
"""
from __future__ import annotations

import ssl
import threading
from typing import Optional

from .connection import Connection, Handler

# The SSLContext (and its sni_callback) is shared across connections; the
# callback fires synchronously inside do_handshake(), so the socket being
# handshaken is tracked per-thread (loop threads are single-writer).
_handshaking = threading.local()


def current_handshake_socket() -> Optional["TlsSocket"]:
    return getattr(_handshaking, "tls", None)


def install_sni_chooser(ctx: ssl.SSLContext, choose) -> None:
    """Install the holder's SNI dispatch on a shared front context:
    choose(server_name) -> SSLContext or None (keep the default)."""

    def _cb(sslobj, server_name, _ctx):
        tls = current_handshake_socket()
        if tls is not None:
            tls.sni = server_name
        chosen = choose(server_name)
        if chosen is not None and chosen is not ctx:
            sslobj.context = chosen
        return None

    ctx.sni_callback = _cb


class TlsSocket:
    """TLS endpoint layered on an established Connection. Server side by
    default (`context` is the shared front SSLContext built by the
    cert-key holder, with SNI dispatch via install_sni_chooser); with
    server_side=False it is the CLIENT side (the agent's wss transport)
    and emits its ClientHello immediately."""

    def __init__(self, conn: Connection, context: ssl.SSLContext,
                 server_side: bool = True,
                 server_hostname: Optional[str] = None):
        self.conn = conn
        self.loop = conn.loop
        self.remote = conn.remote
        self.handler: Handler = Handler()
        self.closed = False
        self.detached = False
        self.sni: Optional[str] = None
        self.alpn_selected: Optional[str] = None
        self.bytes_in = 0
        self.bytes_out = 0
        self._hs_done = False
        self._pending_plain = bytearray()  # writes queued during handshake
        self._in = ssl.MemoryBIO()
        self._out = ssl.MemoryBIO()
        self._obj = context.wrap_bio(self._in, self._out,
                                     server_side=server_side,
                                     server_hostname=server_hostname)
        conn.set_handler(_RawTlsHandler(self))
        if not server_side:
            self._step()  # drive the ClientHello into the out-BIO

    # ----------------------------------------------- Connection-like api

    def set_handler(self, h: Handler) -> None:
        self.handler = h

    def write(self, data: bytes) -> None:
        if self.closed:
            return
        if not self._hs_done:
            self._pending_plain += data
            return
        self._write_plain(data)

    def close(self, err: int = 0) -> None:
        if self.closed:
            return
        self.closed = True
        self.conn.close(err)
        self.handler.on_closed(self, err)

    def close_graceful(self) -> None:
        if self.closed:
            return
        try:
            self._obj.unwrap()  # queue close_notify
        except (ssl.SSLError, OSError, ValueError):
            pass
        self._flush_out()
        self.closed = True
        self.conn.close_graceful()

    def pause_reading(self) -> None:
        self.conn.pause_reading()

    def resume_reading(self) -> None:
        self.conn.resume_reading()

    def feed_raw(self, data: bytes) -> None:
        """Inject ciphertext that was consumed from the Connection BEFORE
        this TlsSocket took it over (an SNI sniffer's buffered bytes)."""
        self._in.write(data)
        self._step()

    # -------------------------------------------------------- internals

    def _mirror(self, data: bytes, outbound: bool) -> None:
        """vmirror "ssl" origin: the only place decrypted bytes exist
        (Mirror.java's SSL-plaintext tap)."""
        from ..utils.ip import parse_ip
        from ..utils.mirror import Mirror
        try:
            rip = parse_ip(self.remote[0])
        except (ValueError, TypeError):
            rip = b"\x00\x00\x00\x00"
        rport = self.remote[1] if self.remote else 0
        if outbound:
            Mirror.get().mirror("ssl", data, src_ip=None, dst_ip=rip,
                                dst_port=rport)
        else:
            Mirror.get().mirror("ssl", data, src_ip=rip, dst_ip=None,
                                src_port=rport)

    def _write_plain(self, data: bytes) -> None:
        from ..utils.mirror import Mirror
        if Mirror.get().hot:
            self._mirror(data, outbound=True)
        try:
            view = memoryview(data)
            while view:
                n = self._obj.write(view[:65536])
                view = view[n:]
        except (ssl.SSLError, OSError):
            self.close(1)
            return
        self.bytes_out += len(data)
        self._flush_out()

    def _flush_out(self) -> None:
        if self._out.pending and not self.conn.closed:
            self.conn.write(self._out.read())

    def _step(self) -> None:
        """Drive handshake + reads after raw bytes land in the in-BIO."""
        if self.closed:
            return
        if not self._hs_done:
            _handshaking.tls = self
            try:
                self._obj.do_handshake()
            except ssl.SSLWantReadError:
                self._flush_out()
                return
            except (ssl.SSLError, OSError):
                self._flush_out()
                self.close(1)
                return
            finally:
                _handshaking.tls = None
            self._hs_done = True
            try:
                self.alpn_selected = self._obj.selected_alpn_protocol()
            except Exception:
                self.alpn_selected = None
            self._flush_out()
            self.handler.on_connected(self)
            if self._pending_plain:
                pending, self._pending_plain = self._pending_plain, bytearray()
                self._write_plain(bytes(pending))
        # decrypt application data
        while not self.closed:
            try:
                plain = self._obj.read(65536)
            except ssl.SSLWantReadError:
                break
            except ssl.SSLZeroReturnError:
                self._flush_out()
                self.handler.on_eof(self)
                return
            except (ssl.SSLError, OSError):
                self.close(1)
                return
            if not plain:
                self._flush_out()
                self.handler.on_eof(self)
                return
            self.bytes_in += len(plain)
            from ..utils.mirror import Mirror
            if Mirror.get().hot:
                self._mirror(plain, outbound=False)
            self.handler.on_data(self, plain)
        self._flush_out()

    @property
    def out(self):
        """Unflushed (ciphertext) output — the backpressure signal the L7
        engine watches, same meaning as Connection.out."""
        return self.conn.out


class _RawTlsHandler(Handler):
    def __init__(self, tls: TlsSocket):
        self.tls = tls

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.tls._in.write(data)
        self.tls._step()

    def on_eof(self, conn: Connection) -> None:
        self.tls._in.write_eof()
        self.tls._step()
        if not self.tls.closed:
            self.tls.handler.on_eof(self.tls)

    def on_closed(self, conn: Connection, err: int) -> None:
        if not self.tls.closed:
            self.tls.closed = True
            self.tls.handler.on_closed(self.tls, err)

    def on_drained(self, conn: Connection) -> None:
        self.tls.handler.on_drained(self.tls)


def client_context(verify: bool = True) -> ssl.SSLContext:
    ctx = ssl.create_default_context()
    if not verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx
