"""Native-pump splice helper: hand a frontend fd + a fresh backend
connection to the C++ splice engine (net/native/vtl.cpp) after flushing
any buffered head bytes.

This is the generic form of TcpLB._splice (components/tcplb.py) for
callers outside the LB resource (WebSocks server/agent, KcpTun): once
handed over, bytes never enter Python again.
"""
from __future__ import annotations

from typing import Callable, Optional

from . import vtl
from .connection import Connection, Handler


def splice_connect(loop, front_fd: int, ip: str, port: int, head: bytes,
                   on_done: Optional[Callable[[int, int, int], None]] = None
                   ) -> None:
    """Connect ip:port and splice front_fd <-> backend natively.

    head: client bytes already read (flushed to the backend first). Any
    protocol reply owed to the client must be written through the front
    Connection (and drained) BEFORE detaching it to get front_fd.
    on_done(bytes_a2b, bytes_b2a, err) fires when the session ends.
    Closes front_fd on any failure.
    """
    try:
        back = Connection.connect(loop, ip, port)
    except OSError as e:
        vtl.close(front_fd)
        if on_done is not None:
            on_done(0, 0, e.errno or -1)
        return

    class Back(Handler):
        def on_connected(self, conn: Connection) -> None:
            conn.pause_reading()  # keep early backend bytes in the kernel
            if head:
                conn.write(head)
            if conn.out:
                return  # wait for drain before handover
            self._handover(conn)

        def on_drained(self, conn: Connection) -> None:
            self._handover(conn)

        def _handover(self, conn: Connection) -> None:
            if conn.detached or conn.closed:
                return
            bfd = conn.detach()
            if not vtl.pump_sets_nodelay():  # pre-r6 .so only
                vtl.set_nodelay(front_fd)
                vtl.set_nodelay(bfd)
            loop.pump(front_fd, bfd, 65536, on_done)

        def on_closed(self, conn: Connection, err: int) -> None:
            vtl.close(front_fd)
            if on_done is not None:
                on_done(0, 0, err or -1)

    back.set_handler(Back())


def detach_when_drained(conn: Connection, cb: Callable[[int], None]) -> None:
    """Run cb(raw_fd) once conn's out buffer has flushed (the written
    protocol reply reached the kernel) and the conn is detached. Replaces
    the conn's handler; reading should already be paused."""
    if not conn.out:
        cb(conn.detach())
        return

    class Flush(Handler):
        def on_drained(self, c: Connection) -> None:
            if not c.detached and not c.closed:
                cb(c.detach())

        def on_closed(self, c: Connection, err: int) -> None:
            pass  # client went away while draining; nothing to splice

    conn.set_handler(Flush())
