"""KCP ARQ reliable transport over UDP.

Parity: reference `selector/wrap/kcp` + `selector/wrap/arqudp`
(`Kcp.java` — a port of the public skywind3000/KCP protocol;
`ArqUDPSocketFD.java:32`): a user-space reliable, ordered byte/segment
transport over UDP with RTO-based and fast retransmission, sliding
windows and window probing. This is a clean-room implementation from
the public KCP wire protocol, not a translation of the reference.

Wire format, little-endian (public KCP spec):

  conv:u32  cmd:u8  frg:u8  wnd:u16  ts:u32  sn:u32  una:u32  len:u32  data

cmd: 81 PUSH (data), 82 ACK, 83 WASK (window probe ask), 84 WINS
(window probe answer). `frg` counts remaining fragments of a message.

`Kcp` is the pure protocol machine (feed input(), poll recv(),
schedule via update()/check()); `KcpConn` binds it to a UdpSock /
UdpVirtualConn on a SelectorEventLoop with the "fast mode" tuning the
reference uses for its tunnels (nodelay, 10ms interval, fast resend=2,
no congestion control).
"""
from __future__ import annotations

import struct
import threading
from collections import deque
import time
from typing import Callable, List, Optional

from .eventloop import SelectorEventLoop

CMD_PUSH = 81
CMD_ACK = 82
CMD_WASK = 83
CMD_WINS = 84

HEAD = struct.Struct("<IBBHIIII")
OVERHEAD = HEAD.size  # 24

RTO_MIN = 100
RTO_DEF = 200
RTO_MAX = 60000
PROBE_INIT = 7000
PROBE_LIMIT = 120000


def _diff(a: int, b: int) -> int:
    """signed distance a-b on the u32 circle."""
    d = (a - b) & 0xFFFFFFFF
    return d - 0x100000000 if d >= 0x80000000 else d


class _Seg:
    __slots__ = ("conv", "cmd", "frg", "wnd", "ts", "sn", "una", "data",
                 "resendts", "rto", "fastack", "xmit")

    def __init__(self, data: bytes = b""):
        self.conv = self.cmd = self.frg = self.wnd = 0
        self.ts = self.sn = self.una = 0
        self.data = data
        self.resendts = self.rto = self.fastack = self.xmit = 0

    def encode(self) -> bytes:
        return HEAD.pack(self.conv, self.cmd, self.frg, self.wnd, self.ts,
                         self.sn, self.una, len(self.data)) + self.data


class Kcp:
    """The ARQ state machine. All times are int milliseconds supplied by
    the caller (monotonic); output(data) emits one UDP datagram."""

    def __init__(self, conv: int, output: Callable[[bytes], None],
                 mtu: int = 1400):
        self.conv = conv
        self.output = output
        self.mtu = mtu
        self.mss = mtu - OVERHEAD
        self.snd_una = 0
        self.snd_nxt = 0
        self.rcv_nxt = 0
        self.snd_wnd = 32
        self.rcv_wnd = 128
        self.rmt_wnd = 32
        self.cwnd = 0
        self.incr = 0
        self.ssthresh = 2
        self.snd_queue: deque = deque()
        self.snd_buf: deque = deque()
        self.rcv_queue: deque = deque()
        self.rcv_buf: List[_Seg] = []  # out-of-order window; stays small
        self.acklist: List[tuple] = []  # (sn, ts)
        self.rx_srtt = 0
        self.rx_rttval = 0
        self.rx_rto = RTO_DEF
        self.rx_minrto = RTO_MIN
        self.current = 0
        self.interval = 100
        self.ts_flush = 100
        self.updated = False
        self.nodelay = 0
        self.fastresend = 0
        self.nocwnd = 0
        self.probe = 0
        self.ts_probe = 0
        self.probe_wait = 0
        self.dead_link = 20
        self.state = 0  # -1 once a segment exceeds dead_link xmits

    # -------------------------------------------------------------- tuning

    def set_nodelay(self, nodelay: int, interval: int, resend: int,
                    nc: int) -> None:
        """Public KCP "fast mode" knob: (1, 10, 2, 1) for tunnels."""
        self.nodelay = nodelay
        self.rx_minrto = 30 if nodelay else RTO_MIN
        self.interval = max(10, min(5000, interval))
        self.fastresend = resend
        self.nocwnd = nc

    def set_wndsize(self, snd: int, rcv: int) -> None:
        self.snd_wnd = snd
        self.rcv_wnd = max(rcv, 128)

    # --------------------------------------------------------------- send

    def send(self, data: bytes) -> None:
        """Queue a message; fragmented into <=mss segments with frg
        counting down to 0 (stream-of-messages semantics)."""
        if not data:
            return
        n = (len(data) + self.mss - 1) // self.mss
        # frg is u8 AND the whole message must fit the peer's reassembly
        # window or recv() can never complete it (public KCP rejects
        # count >= rcv_wnd for the same reason)
        if n > 255 or n >= self.rcv_wnd:
            raise ValueError("message too large: %d fragments" % n)
        for i in range(n):
            seg = _Seg(data[i * self.mss:(i + 1) * self.mss])
            seg.frg = n - i - 1
            self.snd_queue.append(seg)

    # -------------------------------------------------------------- recv

    def recv(self) -> Optional[bytes]:
        """Pop one complete (defragmented) message, or None."""
        if not self.rcv_queue:
            return None
        # whole message present?
        if self.rcv_queue[0].frg + 1 > len(self.rcv_queue):
            return None
        was_full = len(self.rcv_queue) >= self.rcv_wnd
        parts = []
        while self.rcv_queue:
            seg = self.rcv_queue.popleft()
            parts.append(seg.data)
            if seg.frg == 0:
                break
        self._move_rcv_buf()
        if was_full and len(self.rcv_queue) < self.rcv_wnd:
            # window reopened after advertising 0: tell the peer now
            # instead of waiting for its WASK probe (public KCP ASK_TELL)
            self.probe |= 2
        return b"".join(parts)

    def _move_rcv_buf(self) -> None:
        while self.rcv_buf and self.rcv_buf[0].sn == self.rcv_nxt and \
                len(self.rcv_queue) < self.rcv_wnd:
            self.rcv_nxt = (self.rcv_nxt + 1) & 0xFFFFFFFF
            self.rcv_queue.append(self.rcv_buf.pop(0))

    # -------------------------------------------------------------- input

    def input(self, data: bytes) -> None:
        off = 0
        maxack = -1
        una_before = self.snd_una
        while len(data) - off >= OVERHEAD:
            conv, cmd, frg, wnd, ts, sn, una, ln = HEAD.unpack_from(data, off)
            off += OVERHEAD
            if conv != self.conv or len(data) - off < ln:
                return
            payload = data[off:off + ln]
            off += ln
            self.rmt_wnd = wnd
            self._parse_una(una)
            if cmd == CMD_ACK:
                rtt = _diff(self.current, ts)
                if rtt >= 0:
                    self._update_rtt(rtt)
                self._parse_ack(sn)
                if maxack < 0 or _diff(sn, maxack) > 0:
                    maxack = sn
            elif cmd == CMD_PUSH:
                if _diff(sn, (self.rcv_nxt + self.rcv_wnd) & 0xFFFFFFFF) < 0:
                    self.acklist.append((sn, ts))
                    if _diff(sn, self.rcv_nxt) >= 0:
                        seg = _Seg(payload)
                        seg.sn = sn
                        seg.frg = frg
                        self._parse_data(seg)
            elif cmd == CMD_WASK:
                self.probe |= 2  # should send WINS
            elif cmd == CMD_WINS:
                pass
            else:
                return
        # only an in-window maxack may drive fast retransmit; an
        # out-of-range ack sn would inflate fastack on every segment
        if maxack >= 0 and _diff(maxack, self.snd_una) >= 0 and \
                _diff(maxack, self.snd_nxt) < 0:
            for seg in self.snd_buf:
                if _diff(seg.sn, maxack) < 0:
                    seg.fastack += 1
        if _diff(self.snd_una, una_before) > 0:
            self._update_cwnd_on_ack()

    def _update_rtt(self, rtt: int) -> None:
        if self.rx_srtt == 0:
            self.rx_srtt = rtt
            self.rx_rttval = rtt // 2
        else:
            delta = abs(rtt - self.rx_srtt)
            self.rx_rttval = (3 * self.rx_rttval + delta) // 4
            self.rx_srtt = max(1, (7 * self.rx_srtt + rtt) // 8)
        rto = self.rx_srtt + max(self.interval, 4 * self.rx_rttval)
        self.rx_rto = min(max(self.rx_minrto, rto), RTO_MAX)

    def _parse_una(self, una: int) -> None:
        while self.snd_buf and _diff(self.snd_buf[0].sn, una) < 0:
            self.snd_buf.popleft()
        self._shrink_buf()

    def _parse_ack(self, sn: int) -> None:
        if _diff(sn, self.snd_una) < 0 or _diff(sn, self.snd_nxt) >= 0:
            return
        for seg in self.snd_buf:
            if seg.sn == sn:
                self.snd_buf.remove(seg)
                break
            if _diff(sn, seg.sn) < 0:
                break
        self._shrink_buf()

    def _shrink_buf(self) -> None:
        self.snd_una = self.snd_buf[0].sn if self.snd_buf else self.snd_nxt

    def _parse_data(self, newseg: _Seg) -> None:
        # insert into rcv_buf sorted by sn, dropping duplicates
        i = len(self.rcv_buf) - 1
        repeat = False
        while i >= 0:
            d = _diff(newseg.sn, self.rcv_buf[i].sn)
            if d == 0:
                repeat = True
                break
            if d > 0:
                break
            i -= 1
        if not repeat:
            self.rcv_buf.insert(i + 1, newseg)
        self._move_rcv_buf()

    def _update_cwnd_on_ack(self) -> None:
        if self.nocwnd:
            return
        if self.cwnd < self.rmt_wnd:
            mss = self.mss
            if self.cwnd < self.ssthresh:
                self.cwnd += 1
                self.incr += mss
            else:
                self.incr = max(self.incr, mss)
                self.incr += (mss * mss) // self.incr + (mss // 16)
                if (self.cwnd + 1) * mss <= self.incr:
                    self.cwnd = (self.incr + mss - 1) // max(1, mss)
            if self.cwnd > self.rmt_wnd:
                self.cwnd = self.rmt_wnd
                self.incr = self.rmt_wnd * mss

    # -------------------------------------------------------------- flush

    def _wnd_unused(self) -> int:
        return max(0, self.rcv_wnd - len(self.rcv_queue))

    def flush(self) -> None:
        if not self.updated:
            return
        current = self.current
        wnd = self._wnd_unused()
        base = _Seg()
        base.conv = self.conv
        base.wnd = wnd
        base.una = self.rcv_nxt
        out: List[bytes] = []
        size = 0

        def emit(chunk: bytes) -> None:
            nonlocal size
            if size + len(chunk) > self.mtu and out:
                self.output(b"".join(out))
                out.clear()
                size = 0
            out.append(chunk)
            size += len(chunk)

        # pending acks
        for sn, ts in self.acklist:
            base.cmd = CMD_ACK
            base.sn = sn
            base.ts = ts
            emit(base.encode())
        self.acklist.clear()

        # window probing
        if self.rmt_wnd == 0:
            if self.probe_wait == 0:
                self.probe_wait = PROBE_INIT
                self.ts_probe = current + self.probe_wait
            elif _diff(current, self.ts_probe) >= 0:
                self.probe_wait = min(PROBE_LIMIT,
                                      self.probe_wait + self.probe_wait // 2)
                self.ts_probe = current + self.probe_wait
                self.probe |= 1
        else:
            self.ts_probe = 0
            self.probe_wait = 0
        if self.probe & 1:
            base.cmd = CMD_WASK
            base.sn = 0
            base.ts = 0
            emit(base.encode())
        if self.probe & 2:
            base.cmd = CMD_WINS
            base.sn = 0
            base.ts = 0
            emit(base.encode())
        self.probe = 0

        # move from snd_queue into snd_buf within the window
        cwnd = min(self.snd_wnd, self.rmt_wnd)
        if not self.nocwnd:
            cwnd = min(cwnd, max(1, self.cwnd))
        while self.snd_queue and \
                _diff(self.snd_nxt, (self.snd_una + cwnd) & 0xFFFFFFFF) < 0:
            seg = self.snd_queue.popleft()
            seg.conv = self.conv
            seg.cmd = CMD_PUSH
            seg.sn = self.snd_nxt
            self.snd_nxt = (self.snd_nxt + 1) & 0xFFFFFFFF
            self.snd_buf.append(seg)

        resent = self.fastresend if self.fastresend > 0 else 0x7FFFFFFF
        rtomin = 0 if self.nodelay else self.rx_rto >> 3
        lost = change = False
        for seg in self.snd_buf:
            needsend = False
            if seg.xmit == 0:
                needsend = True
                seg.rto = self.rx_rto
                seg.resendts = current + seg.rto + rtomin
            elif _diff(current, seg.resendts) >= 0:
                needsend = True
                if self.nodelay:
                    seg.rto += self.rx_rto // 2
                else:
                    seg.rto += self.rx_rto
                seg.resendts = current + seg.rto
                lost = True
            elif seg.fastack >= resent:
                needsend = True
                seg.fastack = 0
                seg.resendts = current + seg.rto
                change = True
            if needsend:
                seg.xmit += 1
                seg.ts = current
                seg.wnd = wnd
                seg.una = self.rcv_nxt
                emit(seg.encode())
                if seg.xmit >= self.dead_link:
                    self.state = -1
        if out:
            self.output(b"".join(out))

        # congestion window reaction
        if not self.nocwnd:
            if change:
                inflight = _diff(self.snd_nxt, self.snd_una)
                self.ssthresh = max(2, inflight // 2)
                self.cwnd = self.ssthresh + (self.fastresend or 0)
                self.incr = self.cwnd * self.mss
            if lost:
                self.ssthresh = max(2, cwnd // 2)
                self.cwnd = 1
                self.incr = self.mss

    # ---------------------------------------------------------- schedule

    def update(self, current: int) -> None:
        self.current = current
        if not self.updated:
            self.updated = True
            self.ts_flush = current
        slap = _diff(current, self.ts_flush)
        if slap >= 10000 or slap < -10000:
            self.ts_flush = current
            slap = 0
        if slap >= 0:
            self.ts_flush += self.interval
            if _diff(current, self.ts_flush) >= 0:
                self.ts_flush = current + self.interval
            self.flush()

    def check(self, current: int) -> int:
        """ms until the next update() is needed."""
        if not self.updated:
            return 0
        ts_flush = self.ts_flush
        if _diff(current, ts_flush) >= 10000 or _diff(current, ts_flush) <= -10000:
            ts_flush = current
        if _diff(current, ts_flush) >= 0:
            return 0
        tm = _diff(ts_flush, current)
        for seg in self.snd_buf:
            d = _diff(seg.resendts, current)
            if d <= 0:
                return 0
            tm = min(tm, d)
        return min(tm, self.interval)

    @property
    def waitsnd(self) -> int:
        return len(self.snd_buf) + len(self.snd_queue)


class KcpHandler:
    """Callbacks for KcpConn, all on the loop thread."""

    def on_message(self, conn: "KcpConn", data: bytes) -> None: ...

    def on_broken(self, conn: "KcpConn") -> None: ...


class KcpConn:
    """A Kcp machine driven by a SelectorEventLoop timer, transported
    over any object with write(bytes) (UdpVirtualConn) or a (UdpSock,
    ip, port) triple. Fast-mode tuned like the reference's tunnels."""

    def __init__(self, loop: SelectorEventLoop, conv: int,
                 send_raw: Callable[[bytes], None],
                 handler: Optional[KcpHandler] = None):
        self.loop = loop
        self.handler = handler
        self.closed = False
        self.kcp = Kcp(conv, send_raw)
        self.kcp.set_nodelay(1, 10, 2, 1)
        self.kcp.set_wndsize(1024, 1024)
        self._t0 = loop.now
        self._timer = None
        self._flush_pending = False
        loop.run_on_loop(self._schedule)

    def _now_ms(self) -> int:
        return int((time.monotonic() - self._t0) * 1000) & 0xFFFFFFFF

    def _on_loop(self, fn: Callable[[], None]) -> None:
        """Kcp state is loop-thread-confined (same discipline as every
        other component); callers on other threads are marshaled."""
        if threading.current_thread() is self.loop._thread:
            fn()
        else:
            self.loop.run_on_loop(fn)

    def _schedule(self) -> None:
        if self.closed:
            return
        cur = self._now_ms()
        self.kcp.update(cur)
        if self.kcp.state < 0:
            self.close()
            if self.handler is not None:
                self.handler.on_broken(self)
            return
        delay = max(1, self.kcp.check(self._now_ms()))
        self._timer = self.loop.delay(delay, self._schedule)

    def _flush_soon(self) -> None:
        """Coalesce to ONE flush per loop tick. Flushing on every input
        datagram lets duplicate acks fast-retransmit the same segment
        unboundedly (xmit races to dead_link); pacing per tick keeps ack
        latency low without the storm."""
        if self._flush_pending or self.closed:
            return
        self._flush_pending = True

        def run() -> None:
            self._flush_pending = False
            if not self.closed:
                self.kcp.current = self._now_ms()
                self.kcp.flush()
        self.loop.next_tick(run)

    def feed(self, datagram: bytes) -> None:
        """Call with every raw UDP payload for this session."""
        def run() -> None:
            if self.closed:
                return
            self.kcp.input(datagram)
            while True:
                msg = self.kcp.recv()
                if msg is None:
                    break
                if self.handler is not None:
                    self.handler.on_message(self, msg)
            self._flush_soon()
        self._on_loop(run)

    def send(self, data: bytes) -> None:
        def run() -> None:
            if self.closed:
                return
            self.kcp.send(data)
            self._flush_soon()
        self._on_loop(run)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
